// Reed-Solomon codec tests: encode/reconstruct under every loss pattern the
// geometry tolerates, plus rejection past the tolerance. (No reference
// counterpart — blackbird only replicates.)
#include <cstring>
#include <random>
#include <vector>

#include "btest.h"
#include "btpu/ec/rs.h"

using namespace btpu;

namespace {

struct Coded {
  size_t k, m, len;
  std::vector<std::vector<uint8_t>> shards;  // k data then m parity

  Coded(size_t k_, size_t m_, size_t len_, uint64_t seed) : k(k_), m(m_), len(len_) {
    std::mt19937_64 rng(seed);
    shards.assign(k + m, std::vector<uint8_t>(len));
    for (size_t i = 0; i < k; ++i)
      for (auto& b : shards[i]) b = static_cast<uint8_t>(rng());
    std::vector<const uint8_t*> data;
    std::vector<uint8_t*> parity;
    for (size_t i = 0; i < k; ++i) data.push_back(shards[i].data());
    for (size_t j = 0; j < m; ++j) parity.push_back(shards[k + j].data());
    encode_ok = ec::rs_encode(data.data(), k, parity.data(), m, len);
  }
  bool encode_ok{false};

  // Reconstructs with `lost` shard indices removed; returns true when every
  // lost DATA shard came back byte-identical.
  bool recovers(const std::vector<size_t>& lost) {
    std::vector<const uint8_t*> present;
    for (size_t i = 0; i < k + m; ++i) present.push_back(shards[i].data());
    for (size_t i : lost) present[i] = nullptr;
    std::vector<std::vector<uint8_t>> rebuilt(k, std::vector<uint8_t>(len, 0xEE));
    std::vector<uint8_t*> out;
    for (size_t i = 0; i < k; ++i) out.push_back(rebuilt[i].data());
    if (!ec::rs_reconstruct(present.data(), k, m, len, out.data())) return false;
    for (size_t i : lost) {
      if (i >= k) continue;  // parity: not rebuilt by rs_reconstruct
      if (std::memcmp(rebuilt[i].data(), shards[i].data(), len) != 0) return false;
    }
    return true;
  }
};

}  // namespace

BTEST(Ec, EveryDoubleLossPatternRecovers) {
  // k=4, m=2: any 2 of 6 shards may vanish.
  Coded c(4, 2, 4096, 42);
  for (size_t a = 0; a < 6; ++a) {
    for (size_t b = a + 1; b < 6; ++b) {
      BT_EXPECT(c.recovers({a, b}));
    }
  }
  BT_EXPECT(c.recovers({}));   // nothing lost
  BT_EXPECT(c.recovers({3}));  // single data loss
  BT_EXPECT(c.recovers({5}));  // single parity loss (no-op for data)
}

BTEST(Ec, LossBeyondToleranceIsRejected) {
  Coded c(4, 2, 512, 7);
  BT_EXPECT(!c.recovers({0, 1, 2}));  // 3 lost > m=2
  // Degenerate parameters.
  const uint8_t* none[2] = {nullptr, nullptr};
  uint8_t* out[1] = {nullptr};
  BT_EXPECT(!ec::rs_reconstruct(none, 1, 0, 8, out));      // m == 0
  BT_EXPECT(!ec::rs_reconstruct(none, 0, 1, 8, out));      // k == 0
  // Encode rejects out-of-range geometry instead of emitting bad parity.
  Coded big(100, 28, 64, 1);
  BT_EXPECT(big.encode_ok);
  Coded toobig(100, 29, 64, 1);  // k + m = 129 > 128
  BT_EXPECT(!toobig.encode_ok);
}

BTEST(Ec, WideGeometriesAndOddLengths) {
  // k=10, m=4 at a non-power-of-two length; knock out 4 data shards.
  Coded wide(10, 4, 1000, 99);
  BT_EXPECT(wide.recovers({0, 3, 7, 9}));
  BT_EXPECT(wide.recovers({10, 11, 12, 13}));  // all parity lost: data intact
  BT_EXPECT(wide.recovers({0, 11, 5, 13}));    // mixed data+parity loss
  // k=1, m=2 degenerates to replication-by-parity (parity == data).
  Coded mirror(1, 2, 256, 5);
  BT_EXPECT(mirror.recovers({0}));
  BT_EXPECT(mirror.recovers({0, 1}));
  // Parity of a k=1 code is the data itself (Cauchy row is a scalar, and
  // reconstruction must still invert it correctly).
}

BTEST(Ec, EncodeIsDeterministicAndSystematic) {
  Coded a(3, 2, 2048, 1), b(3, 2, 2048, 1);
  for (size_t i = 0; i < 5; ++i) BT_EXPECT(a.shards[i] == b.shards[i]);
  // Systematic: data shards are the original bytes (stored verbatim) — by
  // construction here, but assert parity differs from data (a real code).
  BT_EXPECT(a.shards[3] != a.shards[0]);
}
