// Keystone control-plane tests: object lifecycle, batches, TTL GC, watermark
// eviction, registry watches, heartbeat-driven failure detection, and repair.
// Parity notes: the reference has NO keystone unit tests (its control plane
// is only exercised by the localhost cluster script); this suite covers the
// behaviors documented in SURVEY §2 (KeystoneService row) + §3.5 hermetically.
#include <chrono>
#include <cstring>
#include <thread>

#include "btest.h"
#include "btpu/coord/mem_coordinator.h"
#include "btpu/common/crc32c.h"
#include "btpu/common/wire.h"
#include "btpu/keystone/keystone.h"
#include "btpu/transport/transport.h"

using namespace btpu;
using namespace btpu::keystone;
using namespace std::chrono_literals;

namespace {

bool eventually(const std::function<bool()>& pred, int timeout_ms = 3000) {
  auto deadline = std::chrono::steady_clock::now() + std::chrono::milliseconds(timeout_ms);
  while (std::chrono::steady_clock::now() < deadline) {
    if (pred()) return true;
    std::this_thread::sleep_for(5ms);
  }
  return pred();
}

// A fake worker: local-transport region + registered pool. Owns its memory.
struct FakeWorker {
  std::string id;
  std::vector<uint8_t> memory;
  std::unique_ptr<transport::TransportServer> server;
  MemoryPool pool;

  FakeWorker(const std::string& worker_id, uint64_t size,
             StorageClass cls = StorageClass::RAM_CPU, int32_t slice = 0)
      : id(worker_id), memory(size) {
    server = transport::make_transport_server(TransportKind::LOCAL);
    BT_EXPECT_OK(server->start("", 0));
    auto reg = server->register_region(memory.data(), size, worker_id + "-pool");
    pool.id = worker_id + "-pool";
    pool.node_id = worker_id;
    pool.size = size;
    pool.storage_class = cls;
    pool.remote = reg.value();
    pool.topo = {slice, 0, -1};
  }

  WorkerInfo info() const {
    WorkerInfo w;
    w.worker_id = id;
    w.address = "local:" + id;
    w.topo = pool.topo;
    return w;
  }
};

KeystoneConfig fast_config() {
  KeystoneConfig cfg;
  cfg.gc_interval_sec = 1;
  cfg.health_check_interval_sec = 1;
  cfg.worker_heartbeat_ttl_sec = 1;
  return cfg;
}

uint64_t shard_bytes(const std::vector<CopyPlacement>& copies) {
  uint64_t total = 0;
  for (const auto& c : copies)
    for (const auto& s : c.shards) total += s.length;
  return total;
}

}  // namespace

BTEST(Keystone, PutLifecycleAndLookup) {
  KeystoneService ks(fast_config(), nullptr);
  BT_ASSERT(ks.initialize() == ErrorCode::OK);
  FakeWorker w1("w1", 1 << 20);
  BT_EXPECT_OK(ks.register_worker(w1.info()));
  BT_EXPECT_OK(ks.register_memory_pool(w1.pool));

  const auto v0 = ks.get_view_version();
  WorkerConfig cfg;
  cfg.replication_factor = 1;
  cfg.max_workers_per_copy = 1;
  auto placed = ks.put_start("obj/a", 64 * 1024, cfg);
  BT_ASSERT_OK(placed);
  BT_EXPECT_EQ(shard_bytes(placed.value()), 64 * 1024ull);
  BT_EXPECT(ks.get_view_version() > v0);

  // Double put_start on the same key fails.
  BT_EXPECT(ks.put_start("obj/a", 1024, cfg).error() == ErrorCode::OBJECT_ALREADY_EXISTS);

  BT_EXPECT(ks.object_exists("obj/a").value());
  BT_EXPECT(ks.put_complete("obj/a") == ErrorCode::OK);
  auto got = ks.get_workers("obj/a");
  BT_ASSERT_OK(got);
  BT_EXPECT_EQ(shard_bytes(got.value()), 64 * 1024ull);

  BT_EXPECT(ks.remove_object("obj/a") == ErrorCode::OK);
  BT_EXPECT(!ks.object_exists("obj/a").value());
  BT_EXPECT(ks.get_workers("obj/a").error() == ErrorCode::OBJECT_NOT_FOUND);
  BT_EXPECT(ks.remove_object("obj/a") == ErrorCode::OBJECT_NOT_FOUND);

  // Cancel frees the allocation.
  BT_ASSERT_OK(ks.put_start("obj/b", 512 * 1024, cfg));
  BT_EXPECT(ks.put_cancel("obj/b") == ErrorCode::OK);
  auto stats = ks.get_cluster_stats();
  BT_ASSERT_OK(stats);
  BT_EXPECT_EQ(stats.value().used_capacity, 0ull);
  BT_EXPECT_EQ(stats.value().total_workers, 1ull);
  BT_EXPECT_EQ(stats.value().total_memory_pools, 1ull);
}

BTEST(Keystone, PutCompleteCarriesContentCrc) {
  // Clients that fuse hashing into the transfer only know the whole-object
  // CRC at put_complete time; a nonzero value there must stamp every copy,
  // and 0 must keep whatever put_start carried (older-client path).
  KeystoneService ks(fast_config(), nullptr);
  BT_ASSERT(ks.initialize() == ErrorCode::OK);
  FakeWorker w1("w1", 1 << 20);
  BT_EXPECT_OK(ks.register_worker(w1.info()));
  BT_EXPECT_OK(ks.register_memory_pool(w1.pool));
  WorkerConfig cfg;
  cfg.replication_factor = 1;
  cfg.max_workers_per_copy = 1;

  BT_ASSERT_OK(ks.put_start("crc/fused", 4096, cfg, /*content_crc=*/0));
  BT_EXPECT(ks.put_complete("crc/fused", {}, /*content_crc=*/0xDEADBEEF) == ErrorCode::OK);
  auto got = ks.get_workers("crc/fused");
  BT_ASSERT_OK(got);
  BT_EXPECT_EQ(got.value().front().content_crc, 0xDEADBEEFu);

  // Up-front stamp survives a 0 at complete.
  BT_ASSERT_OK(ks.put_start("crc/upfront", 4096, cfg, /*content_crc=*/0x1234));
  BT_EXPECT(ks.put_complete("crc/upfront") == ErrorCode::OK);
  auto got2 = ks.get_workers("crc/upfront");
  BT_ASSERT_OK(got2);
  BT_EXPECT_EQ(got2.value().front().content_crc, 0x1234u);
}

BTEST(Keystone, GcReclaimsAbandonedPendingPuts) {
  // A client that dies between put_start and put_complete/cancel must not
  // leak its reservation forever (the reference bounded this with backend
  // reservation-token expiry; here allocations live at the control plane).
  auto cfg = fast_config();
  cfg.pending_put_timeout_sec = 1;
  // The fake worker sends no heartbeats; keep the stale-worker reaper from
  // removing its pool while this test waits out the pending timeout.
  cfg.worker_heartbeat_ttl_sec = 3600;
  KeystoneService ks(cfg, nullptr);
  BT_ASSERT(ks.initialize() == ErrorCode::OK);
  BT_ASSERT(ks.start() == ErrorCode::OK);
  FakeWorker w1("w1", 1 << 20);
  BT_EXPECT_OK(ks.register_worker(w1.info()));
  BT_EXPECT_OK(ks.register_memory_pool(w1.pool));

  WorkerConfig wc;
  wc.replication_factor = 1;
  wc.max_workers_per_copy = 1;
  BT_ASSERT_OK(ks.put_start("dead-client/obj", 256 * 1024, wc));
  BT_EXPECT(eventually([&] {
    return !ks.object_exists("dead-client/obj").value() &&
           ks.get_cluster_stats().value().used_capacity == 0;
  }, 5000));

  // The reclaimed space is allocatable again. put_start succeeding proves
  // the ranges were freed; deliberately no put_complete assert — GC could
  // legitimately reclaim this pending put too if the test thread stalls
  // past the (deliberately tiny) timeout.
  BT_ASSERT_OK(ks.put_start("fresh/obj", 900 * 1024, wc));
  (void)ks.put_cancel("fresh/obj");  // GC may have reclaimed the pending put already
  ks.stop();
}

BTEST(Keystone, ListObjectsPrefixOrderLimit) {
  KeystoneService ks(fast_config(), nullptr);
  BT_ASSERT(ks.initialize() == ErrorCode::OK);
  FakeWorker w1("w1", 4 << 20);
  BT_EXPECT_OK(ks.register_worker(w1.info()));
  BT_EXPECT_OK(ks.register_memory_pool(w1.pool));

  WorkerConfig cfg;
  cfg.replication_factor = 1;
  cfg.max_workers_per_copy = 1;
  for (const char* key : {"ckpt/step1/b", "ckpt/step1/a", "ckpt/step2/a", "other/x"}) {
    BT_ASSERT_OK(ks.put_start(key, 4096, cfg));
    BT_EXPECT(ks.put_complete(key) == ErrorCode::OK);
  }
  // A pending (uncommitted) put is invisible to listing.
  BT_ASSERT_OK(ks.put_start("ckpt/step1/pending", 4096, cfg));

  auto all = ks.list_objects("");
  BT_ASSERT_OK(all);
  BT_EXPECT_EQ(all.value().size(), size_t{4});

  auto step1 = ks.list_objects("ckpt/step1/");
  BT_ASSERT_OK(step1);
  BT_ASSERT(step1.value().size() == 2);
  BT_EXPECT_EQ(step1.value()[0].key, "ckpt/step1/a");  // lexicographic
  BT_EXPECT_EQ(step1.value()[1].key, "ckpt/step1/b");
  BT_EXPECT_EQ(step1.value()[0].size, 4096ull);
  BT_EXPECT_EQ(step1.value()[0].complete_copies, 1u);

  auto limited = ks.list_objects("ckpt/", 2);
  BT_ASSERT_OK(limited);
  BT_EXPECT_EQ(limited.value().size(), size_t{2});
  BT_EXPECT_EQ(limited.value()[0].key, "ckpt/step1/a");

  BT_EXPECT(ks.list_objects("nope/").value().empty());

  // Completing the pending put makes it appear.
  BT_EXPECT(ks.put_complete("ckpt/step1/pending") == ErrorCode::OK);
  BT_EXPECT_EQ(ks.list_objects("ckpt/step1/").value().size(), size_t{3});
}

BTEST(Keystone, ValidationAndDefaults) {
  auto cfg = fast_config();
  cfg.default_replicas = 2;
  cfg.max_replicas = 2;
  KeystoneService ks(cfg, nullptr);
  BT_ASSERT(ks.initialize() == ErrorCode::OK);
  FakeWorker w1("w1", 1 << 20), w2("w2", 1 << 20);
  BT_EXPECT_OK(ks.register_worker(w1.info()));
  BT_EXPECT_OK(ks.register_memory_pool(w1.pool));
  BT_EXPECT_OK(ks.register_worker(w2.info()));
  BT_EXPECT_OK(ks.register_memory_pool(w2.pool));

  BT_EXPECT(ks.put_start("", 1024, {}).error() == ErrorCode::INVALID_KEY);
  // 0x01 is the reserved staging-key separator (demotion/repair).
  BT_EXPECT(ks.put_start(std::string("k\x01") + "x", 1024, {}).error() ==
            ErrorCode::INVALID_KEY);
  BT_EXPECT(ks.put_start("k", 0, {}).error() == ErrorCode::INVALID_PARAMETERS);

  // replication_factor 0 -> default_replicas; 99 -> clamped to max_replicas.
  WorkerConfig wc;
  wc.replication_factor = 0;
  wc.max_workers_per_copy = 1;
  auto placed = ks.put_start("k0", 1024, wc);
  BT_ASSERT_OK(placed);
  BT_EXPECT_EQ(placed.value().size(), 2u);
  wc.replication_factor = 99;
  auto placed2 = ks.put_start("k1", 1024, wc);
  BT_ASSERT_OK(placed2);
  BT_EXPECT_EQ(placed2.value().size(), 2u);
}

BTEST(Keystone, BatchOperations) {
  KeystoneService ks(fast_config(), nullptr);
  BT_ASSERT(ks.initialize() == ErrorCode::OK);
  FakeWorker w1("w1", 1 << 20);
  BT_EXPECT_OK(ks.register_worker(w1.info()));
  BT_EXPECT_OK(ks.register_memory_pool(w1.pool));

  WorkerConfig cfg;
  cfg.replication_factor = 1;
  cfg.max_workers_per_copy = 1;
  std::vector<BatchPutStartItem> items = {
      {"b/0", 1024, cfg}, {"b/1", 2048, cfg}, {"", 100, cfg} /* invalid */};
  auto started = ks.batch_put_start(items);
  BT_ASSERT(started.size() == 3);
  BT_EXPECT(started[0].ok());
  BT_EXPECT(started[1].ok());
  BT_EXPECT(!started[2].ok());

  auto exists = ks.batch_object_exists({"b/0", "b/1", "b/2"});
  BT_EXPECT(exists[0].value() && exists[1].value() && !exists[2].value());

  auto completes = ks.batch_put_complete({"b/0", "b/1", "b/2"});
  BT_EXPECT(completes[0] == ErrorCode::OK);
  BT_EXPECT(completes[2] == ErrorCode::OBJECT_NOT_FOUND);

  auto fetched = ks.batch_get_workers({"b/0", "b/2"});
  BT_EXPECT(fetched[0].ok());
  BT_EXPECT(fetched[1].error() == ErrorCode::OBJECT_NOT_FOUND);

  auto cancels = ks.batch_put_cancel({"b/1"});
  BT_EXPECT(cancels[0] == ErrorCode::OK);

  auto removed = ks.remove_all_objects();
  BT_ASSERT_OK(removed);
  BT_EXPECT_EQ(removed.value(), 1ull);  // only b/0 remained
}

BTEST(Keystone, TtlGcCollectsExpiredObjects) {
  KeystoneService ks(fast_config(), nullptr);
  BT_ASSERT(ks.initialize() == ErrorCode::OK);
  FakeWorker w1("w1", 1 << 20);
  BT_EXPECT_OK(ks.register_worker(w1.info()));
  BT_EXPECT_OK(ks.register_memory_pool(w1.pool));

  WorkerConfig cfg;
  cfg.replication_factor = 1;
  cfg.max_workers_per_copy = 1;
  cfg.ttl_ms = 40;
  BT_ASSERT_OK(ks.put_start("ephemeral", 4096, cfg));
  BT_EXPECT(ks.put_complete("ephemeral") == ErrorCode::OK);
  cfg.ttl_ms = 0;  // immortal
  BT_ASSERT_OK(ks.put_start("pinned", 4096, cfg));

  std::this_thread::sleep_for(60ms);
  ks.run_gc_once();
  BT_EXPECT(!ks.object_exists("ephemeral").value());
  BT_EXPECT(ks.object_exists("pinned").value());
  BT_EXPECT_EQ(ks.counters().gc_collected.load(), 1ull);
  auto stats = ks.get_cluster_stats();
  BT_EXPECT_EQ(stats.value().used_capacity, 4096ull);
}

BTEST(Keystone, WatermarkEvictionLruHonorsSoftPin) {
  auto cfg = fast_config();
  cfg.high_watermark = 0.5;
  cfg.eviction_ratio = 0.2;  // target 0.4 after eviction: one 20KB eviction
  KeystoneService ks(cfg, nullptr);
  BT_ASSERT(ks.initialize() == ErrorCode::OK);
  FakeWorker w1("w1", 100 * 1024);
  BT_EXPECT_OK(ks.register_worker(w1.info()));
  BT_EXPECT_OK(ks.register_memory_pool(w1.pool));

  WorkerConfig wc;
  wc.replication_factor = 1;
  wc.max_workers_per_copy = 1;
  // Fill to 60%: three 20KB objects. First is soft-pinned.
  wc.enable_soft_pin = true;
  BT_ASSERT_OK(ks.put_start("pinned", 20 * 1024, wc));
  BT_EXPECT_OK(ks.put_complete("pinned"));
  wc.enable_soft_pin = false;
  BT_ASSERT_OK(ks.put_start("old", 20 * 1024, wc));
  BT_EXPECT_OK(ks.put_complete("old"));
  std::this_thread::sleep_for(5ms);
  BT_ASSERT_OK(ks.put_start("newer", 20 * 1024, wc));
  BT_EXPECT_OK(ks.put_complete("newer"));
  std::this_thread::sleep_for(5ms);
  (void)ks.get_workers("old");  // touch: now "newer" is the LRU victim

  ks.run_health_check_once();
  BT_EXPECT(ks.object_exists("pinned").value());   // soft-pin survives
  BT_EXPECT(ks.object_exists("old").value());      // recently touched survives
  BT_EXPECT(!ks.object_exists("newer").value());   // LRU evicted
  BT_EXPECT_EQ(ks.counters().evicted.load(), 1ull);
}

BTEST(Keystone, PartiallyDamagedStripedCopyReleasesLiveRemnants) {
  // A copy striped across a dead and a live worker is dropped whole; the
  // live worker's shard ranges must return to its pool (not leak as used).
  KeystoneService ks(fast_config(), nullptr);
  BT_ASSERT(ks.initialize() == ErrorCode::OK);
  FakeWorker w1("w1", 1 << 20), w2("w2", 1 << 20);
  for (auto* w : {&w1, &w2}) {
    BT_EXPECT_OK(ks.register_worker(w->info()));
    BT_EXPECT_OK(ks.register_memory_pool(w->pool));
  }

  WorkerConfig cfg;
  cfg.replication_factor = 1;
  cfg.max_workers_per_copy = 2;
  cfg.min_shard_size = 1024;
  auto placed = ks.put_start("striped", 64 * 1024, cfg);
  BT_ASSERT_OK(placed);
  BT_ASSERT(placed.value()[0].shards.size() == 2);
  BT_EXPECT_OK(ks.put_complete("striped"));

  const NodeId victim = placed.value()[0].shards[0].worker_id;
  BT_EXPECT(ks.remove_worker(victim) == ErrorCode::OK);

  // Sole copy lost a shard -> object dropped; the LIVE worker's 32 KiB half
  // must be back to free, so its pool can hold a fresh full-pool object.
  BT_EXPECT(!ks.object_exists("striped").value());
  auto stats = ks.get_cluster_stats();
  BT_ASSERT_OK(stats);
  BT_EXPECT_EQ(stats.value().used_capacity, 0ull);
  WorkerConfig full;
  full.replication_factor = 1;
  full.max_workers_per_copy = 1;
  BT_ASSERT_OK(ks.put_start("refill", 1 << 20, full));
}

BTEST(Keystone, TierPressureDemotesDownLadderWithBytesIntact) {
  // Acceptance-ladder item 4 (BASELINE.md): HBM -> DRAM -> disk-class
  // demotion under pressure. Small "HBM" tier over the watermark, roomy SSD
  // tier below it: the LRU object must MOVE (not die) and keep its bytes.
  auto cfg = fast_config();
  cfg.high_watermark = 0.5;
  cfg.eviction_ratio = 0.2;
  KeystoneService ks(cfg, nullptr);
  BT_ASSERT(ks.initialize() == ErrorCode::OK);
  FakeWorker hot("hot", 100 * 1024, StorageClass::HBM_TPU);
  FakeWorker cold("cold", 1 << 20, StorageClass::SSD);
  for (auto* w : {&hot, &cold}) {
    BT_EXPECT_OK(ks.register_worker(w->info()));
    BT_EXPECT_OK(ks.register_memory_pool(w->pool));
  }

  WorkerConfig wc;
  wc.replication_factor = 1;
  wc.max_workers_per_copy = 1;
  wc.preferred_classes = {StorageClass::HBM_TPU};

  auto client = transport::make_transport_client();
  std::vector<uint8_t> payload(20 * 1024);
  for (size_t i = 0; i < payload.size(); ++i) payload[i] = static_cast<uint8_t>(i * 13 + 5);
  for (const char* key : {"a", "b", "c"}) {  // 60% of the hot tier
    auto placed = ks.put_start(key, payload.size(), wc);
    BT_ASSERT_OK(placed);
    BT_EXPECT(placed.value()[0].shards[0].storage_class == StorageClass::HBM_TPU);
    uint64_t off = 0;
    for (const auto& shard : placed.value()[0].shards) {
      const auto& mem = std::get<MemoryLocation>(shard.location);
      BT_ASSERT(client->write(shard.remote, mem.remote_addr, mem.rkey, payload.data() + off,
                              shard.length) == ErrorCode::OK);
      off += shard.length;
    }
    BT_EXPECT_OK(ks.put_complete(key));
    std::this_thread::sleep_for(5ms);
  }
  (void)ks.get_workers("a");  // touch: "b" becomes the LRU victim
  (void)ks.get_workers("c");  // touch

  const auto v0 = ks.get_view_version();
  ks.run_health_check_once();
  BT_EXPECT_EQ(ks.counters().objects_demoted.load(), 1ull);
  BT_EXPECT_EQ(ks.counters().evicted.load(), 0ull);
  BT_EXPECT(ks.get_view_version() > v0);

  // All three objects still exist; "b" now lives on the SSD tier with the
  // same bytes readable over the data plane.
  for (const char* key : {"a", "b", "c"}) BT_EXPECT(ks.object_exists(key).value());
  auto moved = ks.get_workers("b");
  BT_ASSERT_OK(moved);
  std::vector<uint8_t> back(payload.size(), 0);
  uint64_t off = 0;
  for (const auto& shard : moved.value()[0].shards) {
    BT_EXPECT(shard.storage_class == StorageClass::SSD);
    BT_EXPECT_EQ(shard.worker_id, "cold");
    const auto& mem = std::get<MemoryLocation>(shard.location);
    BT_ASSERT(client->read(shard.remote, mem.remote_addr, mem.rkey, back.data() + off,
                           shard.length) == ErrorCode::OK);
    off += shard.length;
  }
  BT_EXPECT(std::memcmp(back.data(), payload.data(), payload.size()) == 0);

  // The hot tier is back under the watermark; a fresh HBM-preferring put
  // lands in HBM again.
  auto placed = ks.put_start("d", 8 * 1024, wc);
  BT_ASSERT_OK(placed);
  BT_EXPECT(placed.value()[0].shards[0].storage_class == StorageClass::HBM_TPU);
}

BTEST(Keystone, DemotionDisabledFallsBackToEviction) {
  auto cfg = fast_config();
  cfg.high_watermark = 0.5;
  cfg.eviction_ratio = 0.2;
  cfg.enable_tier_demotion = false;
  KeystoneService ks(cfg, nullptr);
  BT_ASSERT(ks.initialize() == ErrorCode::OK);
  FakeWorker hot("hot", 100 * 1024, StorageClass::HBM_TPU);
  FakeWorker cold("cold", 1 << 20, StorageClass::SSD);
  for (auto* w : {&hot, &cold}) {
    BT_EXPECT_OK(ks.register_worker(w->info()));
    BT_EXPECT_OK(ks.register_memory_pool(w->pool));
  }
  WorkerConfig wc;
  wc.replication_factor = 1;
  wc.max_workers_per_copy = 1;
  wc.preferred_classes = {StorageClass::HBM_TPU};
  for (const char* key : {"a", "b", "c"}) {
    BT_ASSERT_OK(ks.put_start(key, 20 * 1024, wc));
    BT_EXPECT_OK(ks.put_complete(key));
    std::this_thread::sleep_for(5ms);
  }
  ks.run_health_check_once();
  BT_EXPECT_EQ(ks.counters().objects_demoted.load(), 0ull);
  BT_EXPECT(ks.counters().evicted.load() >= 1ull);
}

BTEST(Keystone, CoordinatorRegistryAndHeartbeatDeath) {
  // Full §3.5 path: worker advertises itself through the coordinator; its
  // heartbeat TTL lapses; keystone's watcher cleans it up.
  auto coordinator = std::make_shared<coord::MemCoordinator>();
  auto cfg = fast_config();
  cfg.enable_repair = false;
  KeystoneService ks(cfg, coordinator);
  BT_ASSERT(ks.initialize() == ErrorCode::OK);
  BT_ASSERT(ks.start() == ErrorCode::OK);

  FakeWorker w1("w1", 1 << 20);
  const auto cluster = cfg.cluster_id;
  BT_EXPECT_OK(coordinator->put(coord::worker_key(cluster, "w1"), encode_worker_info(w1.info())));
  BT_EXPECT_OK(coordinator->put(coord::pool_key(cluster, "w1", w1.pool.id), encode_pool_record(w1.pool)));
  BT_EXPECT_OK(coordinator->put_with_ttl(coord::heartbeat_key(cluster, "w1"), "alive", 100));

  BT_EXPECT(eventually([&] { return ks.workers().size() == 1; }));
  BT_EXPECT(eventually([&] { return ks.memory_pools().size() == 1; }));

  // Heartbeat lapses -> worker and pools purged, view bumped.
  BT_EXPECT(eventually([&] { return ks.workers().empty(); }));
  BT_EXPECT(ks.memory_pools().empty());
  BT_EXPECT_EQ(ks.counters().workers_lost.load(), 1ull);
  // Persistent keys deleted from the coordinator too.
  BT_EXPECT(!coordinator->get(coord::worker_key(cluster, "w1")).ok());
  ks.stop();
}

BTEST(Keystone, HaStandbyMirrorsObjectsAndTakesOverOnLeaderDeath) {
  // Two keystones share one coordinator. The leader serves all mutations and
  // persists object records; the standby rejects mutations with NOT_LEADER
  // while mirroring the records. When the leader resigns, the standby is
  // promoted, reconciles, and serves the same objects.
  auto coordinator = std::make_shared<coord::MemCoordinator>();
  auto cfg = fast_config();
  cfg.enable_ha = true;
  cfg.service_id = "ks-a";
  auto ks_a = std::make_unique<KeystoneService>(cfg, coordinator);
  BT_ASSERT(ks_a->initialize() == ErrorCode::OK);
  cfg.service_id = "ks-b";
  KeystoneService ks_b(cfg, coordinator);
  BT_ASSERT(ks_b.initialize() == ErrorCode::OK);
  BT_EXPECT(ks_a->is_leader());
  BT_EXPECT(!ks_b.is_leader());

  // Worker advertises through the coordinator so BOTH keystones mirror it.
  FakeWorker w1("w1", 1 << 20);
  const auto cluster = cfg.cluster_id;
  BT_EXPECT_OK(coordinator->put(coord::worker_key(cluster, "w1"), encode_worker_info(w1.info())));
  BT_EXPECT_OK(coordinator->put(coord::pool_key(cluster, "w1", w1.pool.id), encode_pool_record(w1.pool)));
  BT_ASSERT(eventually([&] { return !ks_a->memory_pools().empty(); }));
  BT_ASSERT(eventually([&] { return !ks_b.memory_pools().empty(); }));

  WorkerConfig wc;
  wc.replication_factor = 1;
  wc.max_workers_per_copy = 1;

  // Standby refuses the whole mutation surface.
  BT_EXPECT(ks_b.put_start("ha/obj", 4096, wc).error() == ErrorCode::NOT_LEADER);
  BT_EXPECT(ks_b.remove_object("ha/obj") == ErrorCode::NOT_LEADER);

  // Leader accepts: write real bytes so the takeover can be read back.
  auto placed = ks_a->put_start("ha/obj", 4096, wc);
  BT_ASSERT_OK(placed);
  auto client = transport::make_transport_client();
  std::vector<uint8_t> payload(4096);
  for (size_t i = 0; i < payload.size(); ++i) payload[i] = static_cast<uint8_t>(i * 11 + 3);
  {
    uint64_t off = 0;
    for (const auto& shard : placed.value()[0].shards) {
      const auto& mem = std::get<MemoryLocation>(shard.location);
      BT_ASSERT(client->write(shard.remote, mem.remote_addr, mem.rkey, payload.data() + off,
                              shard.length) == ErrorCode::OK);
      off += shard.length;
    }
  }
  BT_EXPECT(ks_a->put_complete("ha/obj") == ErrorCode::OK);

  // Standby mirrors the persisted record (watch-driven).
  BT_EXPECT(eventually([&] { return ks_b.object_exists("ha/obj").value(); }));

  // Leader dies; standby is promoted and still serves the object.
  ks_a->stop();
  ks_a.reset();
  BT_ASSERT(eventually([&] { return ks_b.is_leader(); }));
  auto got = ks_b.get_workers("ha/obj");
  BT_ASSERT_OK(got);
  std::vector<uint8_t> back(4096, 0);
  uint64_t off = 0;
  for (const auto& shard : got.value()[0].shards) {
    const auto& mem = std::get<MemoryLocation>(shard.location);
    BT_ASSERT(client->read(shard.remote, mem.remote_addr, mem.rkey, back.data() + off,
                           shard.length) == ErrorCode::OK);
    off += shard.length;
  }
  BT_EXPECT(std::memcmp(back.data(), payload.data(), payload.size()) == 0);

  // The new leader owns the mutation surface: fresh puts and removes work,
  // and its allocator adopted the mirrored ranges (no double-allocation).
  BT_ASSERT_OK(ks_b.put_start("ha/obj2", 4096, wc));
  BT_EXPECT(ks_b.put_complete("ha/obj2") == ErrorCode::OK);
  BT_EXPECT(ks_b.remove_object("ha/obj") == ErrorCode::OK);
  auto stats = ks_b.get_cluster_stats();
  BT_ASSERT_OK(stats);
  BT_EXPECT_EQ(stats.value().used_capacity, 4096ull);
}

BTEST(Keystone, BootReplayFromCoordinator) {
  auto coordinator = std::make_shared<coord::MemCoordinator>();
  FakeWorker w1("w1", 1 << 20);
  const std::string cluster = "btpu_cluster";
  BT_EXPECT_OK(coordinator->put(coord::worker_key(cluster, "w1"), encode_worker_info(w1.info())));
  BT_EXPECT_OK(coordinator->put(coord::pool_key(cluster, "w1", w1.pool.id), encode_pool_record(w1.pool)));

  KeystoneService ks(fast_config(), coordinator);
  BT_ASSERT(ks.initialize() == ErrorCode::OK);  // replays state
  BT_EXPECT_EQ(ks.workers().size(), 1u);
  BT_EXPECT_EQ(ks.memory_pools().size(), 1u);
}

BTEST(Keystone, DeadWorkerRepairRebuildsReplicas) {
  KeystoneService ks(fast_config(), nullptr);
  BT_ASSERT(ks.initialize() == ErrorCode::OK);
  FakeWorker w1("w1", 1 << 20), w2("w2", 1 << 20), w3("w3", 1 << 20);
  for (auto* w : {&w1, &w2, &w3}) {
    BT_EXPECT_OK(ks.register_worker(w->info()));
    BT_EXPECT_OK(ks.register_memory_pool(w->pool));
  }

  // Two replicas, one shard each -> two distinct workers hold the object.
  WorkerConfig cfg;
  cfg.replication_factor = 2;
  cfg.max_workers_per_copy = 1;
  auto placed = ks.put_start("precious", 32 * 1024, cfg);
  BT_ASSERT_OK(placed);
  BT_ASSERT(placed.value().size() == 2);

  // Write distinct bytes through the data plane to both copies.
  auto client = transport::make_transport_client();
  std::vector<uint8_t> payload(32 * 1024);
  for (size_t i = 0; i < payload.size(); ++i) payload[i] = static_cast<uint8_t>(i * 7 + 3);
  for (const auto& copy : placed.value()) {
    uint64_t off = 0;
    for (const auto& shard : copy.shards) {
      const auto& mem = std::get<MemoryLocation>(shard.location);
      BT_ASSERT(client->write(shard.remote, mem.remote_addr, mem.rkey, payload.data() + off,
                              shard.length) == ErrorCode::OK);
      off += shard.length;
    }
  }
  BT_EXPECT(ks.put_complete("precious") == ErrorCode::OK);

  // Kill the worker holding copy 0.
  const NodeId victim = placed.value()[0].shards[0].worker_id;
  BT_EXPECT(ks.remove_worker(victim) == ErrorCode::OK);
  BT_EXPECT_EQ(ks.counters().objects_repaired.load(), 1ull);

  // Object still has 2 replicas, none on the dead worker, bytes intact —
  // and the repaired copy landed on a DIFFERENT worker than the survivor
  // (anti-affinity), or losing that one worker would lose both replicas.
  auto got = ks.get_workers("precious");
  BT_ASSERT_OK(got);
  BT_EXPECT_EQ(got.value().size(), 2u);
  BT_EXPECT_NE(got.value()[0].shards[0].worker_id, got.value()[1].shards[0].worker_id);
  for (const auto& copy : got.value()) {
    uint64_t off = 0;
    std::vector<uint8_t> back(32 * 1024, 0);
    for (const auto& shard : copy.shards) {
      BT_EXPECT_NE(shard.worker_id, victim);
      const auto& mem = std::get<MemoryLocation>(shard.location);
      BT_ASSERT(client->read(shard.remote, mem.remote_addr, mem.rkey, back.data() + off,
                             shard.length) == ErrorCode::OK);
      off += shard.length;
    }
    BT_EXPECT(std::memcmp(back.data(), payload.data(), payload.size()) == 0);
  }
}

BTEST(Keystone, InlineObjectsLiveInKeystoneAndSurviveRestart) {
  // Inline tier: the bytes live in the object map (no pools involved at
  // all), the durable record carries them, and a restarted keystone serves
  // them back — with the budget counter restored.
  auto coordinator = std::make_shared<coord::MemCoordinator>();
  auto cfg = fast_config();
  std::string bytes = "inline tier payload: small, hot, and RTT-bound";
  const uint32_t crc = crc32c(bytes.data(), bytes.size());
  WorkerConfig wc;
  wc.replication_factor = 1;  // inline serves default-placement puts only
  {
    KeystoneService ks(cfg, coordinator);
    BT_ASSERT(ks.initialize() == ErrorCode::OK);
    BT_EXPECT(ks.put_inline("inl/x", wc, crc, bytes) == ErrorCode::OK);
    BT_EXPECT_EQ(ks.counters().inline_puts.load(), 1u);
    BT_EXPECT_EQ(ks.inline_bytes_resident(), bytes.size());
    // Duplicate key: refused, budget unchanged.
    BT_EXPECT(ks.put_inline("inl/x", wc, crc, bytes) == ErrorCode::OBJECT_ALREADY_EXISTS);
    BT_EXPECT_EQ(ks.inline_bytes_resident(), bytes.size());
    // Oversized: refused with the fallback code.
    BT_EXPECT(ks.put_inline("inl/big", wc, 0, std::string(cfg.inline_max_bytes + 1, 'x')) ==
              ErrorCode::NOT_IMPLEMENTED);
    ks.stop();
  }
  {
    KeystoneService ks2(cfg, coordinator);
    BT_ASSERT(ks2.initialize() == ErrorCode::OK);
    BT_EXPECT(ks2.object_exists("inl/x").value());
    BT_EXPECT_EQ(ks2.inline_bytes_resident(), bytes.size());
    auto got = ks2.get_workers("inl/x");
    BT_ASSERT_OK(got);
    BT_ASSERT(got.value().size() == 1);
    BT_EXPECT(got.value()[0].shards.empty());
    BT_EXPECT(got.value()[0].inline_data == bytes);
    BT_EXPECT_EQ(got.value()[0].content_crc, crc);
    // Remove returns the budget.
    BT_EXPECT(ks2.remove_object("inl/x") == ErrorCode::OK);
    BT_EXPECT_EQ(ks2.inline_bytes_resident(), 0u);
    ks2.stop();
  }
}

BTEST(Keystone, InlineObjectsExpireLikeAnyOther) {
  KeystoneService ks(fast_config(), nullptr);
  BT_ASSERT(ks.initialize() == ErrorCode::OK);
  WorkerConfig wc;
  wc.replication_factor = 1;
  wc.ttl_ms = 1;
  BT_EXPECT(ks.put_inline("inl/ttl", wc, 0, "ephemeral") == ErrorCode::OK);
  std::this_thread::sleep_for(std::chrono::milliseconds(10));
  ks.run_gc_once();
  BT_EXPECT(!ks.object_exists("inl/ttl").value());
  BT_EXPECT_EQ(ks.inline_bytes_resident(), 0u);
}

BTEST(Keystone, InlineBudgetGateFallsBackWhenSpent) {
  auto cfg = fast_config();
  cfg.inline_total_bytes = 1024;
  KeystoneService ks(cfg, nullptr);
  BT_ASSERT(ks.initialize() == ErrorCode::OK);
  WorkerConfig wc;
  wc.replication_factor = 1;
  BT_EXPECT(ks.put_inline("inl/1", wc, 0, std::string(600, 'a')) == ErrorCode::OK);
  BT_EXPECT(ks.put_inline("inl/2", wc, 0, std::string(600, 'b')) ==
            ErrorCode::NOT_IMPLEMENTED);
  BT_EXPECT(ks.remove_object("inl/1") == ErrorCode::OK);
  BT_EXPECT(ks.put_inline("inl/2", wc, 0, std::string(600, 'b')) == ErrorCode::OK);
}

BTEST(Keystone, RestartRecoversPersistedObjects) {
  // The reference forgets every object when keystone restarts (object map is
  // RAM-only, SURVEY §5). With persist_objects, a new keystone replays the
  // object map from the coordinator AND re-adopts allocator ranges so new
  // allocations cannot collide with surviving placements.
  auto coordinator = std::make_shared<coord::MemCoordinator>();
  auto cfg = fast_config();
  FakeWorker w1("w1", 1 << 20), w2("w2", 1 << 20);
  const auto cluster = cfg.cluster_id;
  auto advertise = [&](FakeWorker& w) {
    BT_EXPECT_OK(coordinator->put(coord::worker_key(cluster, w.id), encode_worker_info(w.info())));
    BT_EXPECT_OK(coordinator->put(coord::pool_key(cluster, w.id, w.pool.id), encode_pool_record(w.pool)));
    BT_EXPECT_OK(coordinator->put_with_ttl(coord::heartbeat_key(cluster, w.id), "alive", 60000));
  };

  std::vector<CopyPlacement> original;
  std::vector<uint8_t> payload(64 * 1024);
  for (size_t i = 0; i < payload.size(); ++i) payload[i] = static_cast<uint8_t>(i * 3 + 1);
  {
    KeystoneService ks(cfg, coordinator);
    BT_ASSERT(ks.initialize() == ErrorCode::OK);
    advertise(w1);
    advertise(w2);
    BT_EXPECT(eventually([&] { return ks.memory_pools().size() == 2; }));

    WorkerConfig wc;
    wc.replication_factor = 2;
    wc.max_workers_per_copy = 1;
    auto placed = ks.put_start("durable/obj", payload.size(), wc);
    BT_ASSERT_OK(placed);
    original = placed.value();
    auto client = transport::make_transport_client();
    for (const auto& copy : original) {
      uint64_t off = 0;
      for (const auto& shard : copy.shards) {
        const auto& mem = std::get<MemoryLocation>(shard.location);
        BT_EXPECT_OK(client->write(shard.remote, mem.remote_addr, mem.rkey, payload.data() + off,
                      shard.length));
        off += shard.length;
      }
    }
    BT_EXPECT(ks.put_complete("durable/obj") == ErrorCode::OK);
    // PENDING objects are not persisted: only COMPLETE ones survive restart.
    BT_ASSERT_OK(ks.put_start("pending/obj", 4096, wc));
    ks.stop();
  }  // keystone "crashes"

  {
    KeystoneService ks2(cfg, coordinator);
    BT_ASSERT(ks2.initialize() == ErrorCode::OK);  // replays registries + objects
    BT_EXPECT(ks2.object_exists("durable/obj").value());
    BT_EXPECT(!ks2.object_exists("pending/obj").value());

    auto got = ks2.get_workers("durable/obj");
    BT_ASSERT_OK(got);
    BT_EXPECT_EQ(got.value().size(), 2u);

    // Read the bytes back through the recovered placements.
    auto client = transport::make_transport_client();
    std::vector<uint8_t> back(payload.size(), 0);
    uint64_t off = 0;
    for (const auto& shard : got.value()[0].shards) {
      const auto& mem = std::get<MemoryLocation>(shard.location);
      BT_ASSERT(client->read(shard.remote, mem.remote_addr, mem.rkey, back.data() + off,
                             shard.length) == ErrorCode::OK);
      off += shard.length;
    }
    BT_EXPECT(back == payload);

    // The allocator re-adopted the ranges: a fresh allocation must not
    // overlap the recovered object's placements.
    WorkerConfig wc;
    wc.replication_factor = 2;
    wc.max_workers_per_copy = 1;
    auto fresh = ks2.put_start("durable/obj2", 64 * 1024, wc);
    BT_ASSERT_OK(fresh);
    for (const auto& copy : fresh.value()) {
      for (const auto& shard : copy.shards) {
        const auto& mem = std::get<MemoryLocation>(shard.location);
        for (const auto& ocopy : original) {
          for (const auto& oshard : ocopy.shards) {
            const auto& omem = std::get<MemoryLocation>(oshard.location);
            if (shard.pool_id == oshard.pool_id) {
              const bool overlap = mem.remote_addr < omem.remote_addr + omem.size &&
                                   omem.remote_addr < mem.remote_addr + mem.size;
              BT_EXPECT(!overlap);
            }
          }
        }
      }
    }
    // Removing the recovered object clears its durable record.
    BT_EXPECT(ks2.remove_object("durable/obj") == ErrorCode::OK);
    BT_EXPECT(!coordinator->get(coord::object_record_key(cluster, "durable/obj")).ok());
  }
}

namespace {
// Fails the Nth object-record put (1-based), passing all others: repair and
// demotion splice memory BEFORE their durable write, so a failed write there
// must be healed later by the health loop's re-persist.
class FlakyCoordinator : public coord::MemCoordinator {
 public:
  explicit FlakyCoordinator(std::string cluster)
      : prefix_(coord::objects_prefix(std::move(cluster))) {}
  ErrorCode put(const std::string& key, const std::string& value) override {
    if (key.rfind(prefix_, 0) == 0 && armed_.load()) {
      if (countdown_.fetch_sub(1) == 1) {
        armed_.store(false);
        ++failed_;
        return ErrorCode::COORD_ERROR;
      }
    }
    return coord::MemCoordinator::put(key, value);
  }
  void fail_nth_object_put(int n) {
    countdown_.store(n);
    armed_.store(true);
  }
  int failed() const { return failed_.load(); }

 private:
  const std::string prefix_;
  std::atomic<bool> armed_{false};
  std::atomic<int> countdown_{0};
  std::atomic<int> failed_{0};
};
}  // namespace

BTEST(Keystone, DeferredPersistCatchesUpAfterCoordinatorOutage) {
  // Repair's merge persists AFTER the splice lands in memory; fail closed is
  // unavailable there. A transient coordinator outage at that exact write
  // must not leave the durable record naming the condemned (released) shard
  // placements forever — the health loop re-persists from current memory.
  auto cfg = fast_config();
  auto coordinator = std::make_shared<FlakyCoordinator>(cfg.cluster_id);
  KeystoneService ks(cfg, coordinator);
  BT_ASSERT(ks.initialize() == ErrorCode::OK);
  FakeWorker w1("w1", 1 << 20), w2("w2", 1 << 20), w3("w3", 1 << 20);
  // Advertised through the coordinator so the post-outage restart can
  // re-adopt placements against replayed pools.
  for (auto* w : {&w1, &w2, &w3}) {
    BT_EXPECT_OK(coordinator->put(coord::worker_key(cfg.cluster_id, w->id), encode_worker_info(w->info())));
    BT_EXPECT_OK(coordinator->put(coord::pool_key(cfg.cluster_id, w->id, w->pool.id),
                     encode_pool_record(w->pool)));
    BT_EXPECT_OK(coordinator->put_with_ttl(coord::heartbeat_key(cfg.cluster_id, w->id), "alive", 60000));
  }
  BT_EXPECT(eventually([&] { return ks.memory_pools().size() == 3; }));

  WorkerConfig wc;
  wc.replication_factor = 2;
  wc.max_workers_per_copy = 1;
  auto placed = ks.put_start("durable/repaired", 32 * 1024, wc);
  BT_ASSERT_OK(placed);
  auto client = transport::make_transport_client();
  std::vector<uint8_t> payload(32 * 1024);
  for (size_t i = 0; i < payload.size(); ++i) payload[i] = static_cast<uint8_t>(i * 13 + 5);
  for (const auto& copy : placed.value()) {
    uint64_t off = 0;
    for (const auto& shard : copy.shards) {
      const auto& mem = std::get<MemoryLocation>(shard.location);
      BT_ASSERT(client->write(shard.remote, mem.remote_addr, mem.rkey, payload.data() + off,
                              shard.length) == ErrorCode::OK);
      off += shard.length;
    }
  }
  BT_EXPECT(ks.put_complete("durable/repaired") == ErrorCode::OK);

  // Repair writes the record twice: the pruned state (pass 1, fail-closed)
  // and the merged repaired state (pass 2, splice-first). Fail pass 2's.
  coordinator->fail_nth_object_put(2);
  const NodeId victim = placed.value()[0].shards[0].worker_id;
  BT_EXPECT(ks.remove_worker(victim) == ErrorCode::OK);
  BT_EXPECT_EQ(coordinator->failed(), 1);
  // The repair is NOT claimed while the durable record lags...
  BT_EXPECT_EQ(ks.counters().objects_repaired.load(), 0ull);
  // ...but memory already serves two healthy copies.
  BT_EXPECT_EQ(ks.get_workers("durable/repaired").value().size(), 2u);

  // The health loop re-persists the dirty key from current memory.
  ks.run_health_check_once();

  // Restart proves durability: a fresh keystone replays TWO copies, none on
  // the dead worker, bytes intact through re-adopted placements.
  ks.stop();
  KeystoneService ks2(cfg, coordinator);
  BT_ASSERT(ks2.initialize() == ErrorCode::OK);
  auto got = ks2.get_workers("durable/repaired");
  BT_ASSERT_OK(got);
  BT_EXPECT_EQ(got.value().size(), 2u);
  for (const auto& copy : got.value()) {
    uint64_t off = 0;
    std::vector<uint8_t> back(payload.size(), 0);
    for (const auto& shard : copy.shards) {
      BT_EXPECT_NE(shard.worker_id, victim);
      const auto& mem = std::get<MemoryLocation>(shard.location);
      BT_ASSERT(client->read(shard.remote, mem.remote_addr, mem.rkey, back.data() + off,
                             shard.length) == ErrorCode::OK);
      off += shard.length;
    }
    BT_EXPECT(std::memcmp(back.data(), payload.data(), payload.size()) == 0);
  }
}

BTEST(Keystone, IdleSlotsReclaimedOnSlotTtlAndCancelledByDrain) {
  auto cfg = fast_config();
  cfg.slot_ttl_sec = 1;
  cfg.pending_put_timeout_sec = 3600;  // slots must NOT wait for this one
  KeystoneService ks(cfg, nullptr);
  BT_ASSERT(ks.initialize() == ErrorCode::OK);
  FakeWorker w1("w1", 1 << 20), w2("w2", 1 << 20);
  for (auto* w : {&w1, &w2}) {
    BT_EXPECT_OK(ks.register_worker(w->info()));
    BT_EXPECT_OK(ks.register_memory_pool(w->pool));
  }
  WorkerConfig wc;
  wc.replication_factor = 1;
  wc.max_workers_per_copy = 1;

  // Idle slots expire on the short slot TTL, releasing their capacity.
  auto granted = ks.put_start_pooled(4096, wc, 4, "c1");
  BT_ASSERT_OK(granted);
  BT_EXPECT_EQ(granted.value().size(), 4u);
  const uint64_t used = ks.get_cluster_stats().value().used_capacity;
  BT_EXPECT(used >= 4 * 4096);
  std::this_thread::sleep_for(1200ms);
  ks.run_gc_once();
  BT_EXPECT_EQ(ks.get_cluster_stats().value().used_capacity, 0ull);
  BT_EXPECT(ks.put_commit_slot(granted.value()[0].slot_key, "late", 0, {}) ==
            ErrorCode::OBJECT_NOT_FOUND);

  // A drain cancels idle slots on the drained worker outright — no writer
  // is attached, so nothing pins the worker until the TTL.
  auto g2 = ks.put_start_pooled(4096, wc, 2, "c2");
  BT_ASSERT_OK(g2);
  const NodeId host = g2.value()[0].copies[0].shards[0].worker_id;
  BT_ASSERT_OK(ks.drain_worker(host));
  BT_EXPECT(ks.put_commit_slot(g2.value()[0].slot_key, "drained", 0, {}) ==
            ErrorCode::OBJECT_NOT_FOUND);
}

BTEST(Keystone, WorkerRestartReadoptsPersistentPools) {
  // A dead worker whose pools are FILE-BACKED (mmap/io_uring) keeps its
  // bytes across the process: the keystone spares such objects from the
  // loss path (OFFLINE, metadata intact) and, when the restarted worker
  // re-registers the pool under a NEW base/rkey, re-carves the ranges,
  // rewrites placements, re-validates the CRC stamps, and serves the
  // object again — zero re-replication. Reference analog: its disk bytes
  // persist too (iouring_disk_backend.cpp:419-438) but its keystone
  // forgets the metadata.
  auto cfg = fast_config();
  KeystoneService ks(cfg, nullptr);
  BT_ASSERT(ks.initialize() == ErrorCode::OK);
  auto w1 = std::make_unique<FakeWorker>("w1", 1 << 20, StorageClass::NVME);
  BT_EXPECT_OK(ks.register_worker(w1->info()));
  BT_EXPECT_OK(ks.register_memory_pool(w1->pool));

  WorkerConfig wc;
  wc.replication_factor = 1;
  wc.max_workers_per_copy = 1;
  std::vector<uint8_t> payload(200 * 1024);
  for (size_t i = 0; i < payload.size(); ++i) payload[i] = static_cast<uint8_t>(i * 17 + 3);
  auto placed = ks.put_start("disk/obj", payload.size(), wc,
                             crc32c(payload.data(), payload.size()));
  BT_ASSERT_OK(placed);
  auto client = transport::make_transport_client();
  {
    uint64_t off = 0;
    for (const auto& shard : placed.value()[0].shards) {
      const auto& mem = std::get<MemoryLocation>(shard.location);
      BT_ASSERT(client->write(shard.remote, mem.remote_addr, mem.rkey,
                              payload.data() + off, shard.length) == ErrorCode::OK);
      off += shard.length;
    }
  }
  CopyShardCrcs stamps;
  stamps.copy_index = 0;
  {
    uint64_t off = 0;
    for (const auto& shard : placed.value()[0].shards) {
      stamps.crcs.push_back(crc32c(payload.data() + off, shard.length));
      off += shard.length;
    }
  }
  BT_EXPECT(ks.put_complete("disk/obj", {stamps}) == ErrorCode::OK);

  // "Crash": keep the backing bytes (the file), lose the process (region).
  std::vector<uint8_t> backing = w1->memory;
  BT_EXPECT(ks.remove_worker("w1") == ErrorCode::OK);
  w1.reset();  // old region unregistered — stale placements now unreadable
  BT_EXPECT(ks.object_exists("disk/obj").value());  // spared, not lost
  BT_EXPECT_EQ(ks.counters().objects_lost.load(), 0ull);
  BT_EXPECT_EQ(ks.counters().objects_offline.load(), 1ull);

  // "Restart": same worker id + pool id, same bytes, NEW base + rkey.
  FakeWorker w1b("w1", 1 << 20, StorageClass::NVME);
  std::copy(backing.begin(), backing.end(), w1b.memory.begin());
  BT_EXPECT_OK(ks.register_worker(w1b.info()));
  BT_EXPECT_OK(ks.register_memory_pool(w1b.pool));

  auto got = ks.get_workers("disk/obj");
  BT_ASSERT_OK(got);
  std::vector<uint8_t> back(payload.size(), 0);
  uint64_t off = 0;
  for (const auto& shard : got.value()[0].shards) {
    const auto& mem = std::get<MemoryLocation>(shard.location);
    BT_ASSERT(client->read(shard.remote, mem.remote_addr, mem.rkey, back.data() + off,
                           shard.length) == ErrorCode::OK);
    off += shard.length;
  }
  BT_EXPECT(back == payload);
  BT_EXPECT_EQ(ks.counters().objects_adopted.load(), 1ull);
  BT_EXPECT_EQ(ks.counters().objects_repaired.load(), 0ull);

  // The re-carved ranges are real: a fresh allocation cannot overlap them.
  auto fresh = ks.put_start("disk/obj2", 500 * 1024, wc);
  BT_ASSERT_OK(fresh);
  const auto& nmem = std::get<MemoryLocation>(fresh.value()[0].shards[0].location);
  const auto& omem = std::get<MemoryLocation>(got.value()[0].shards[0].location);
  const bool overlap = nmem.remote_addr < omem.remote_addr + omem.size &&
                       omem.remote_addr < nmem.remote_addr + nmem.size;
  BT_EXPECT(!overlap);
}

BTEST(Keystone, StaleBackingFileFailsReadoptionValidation) {
  // The restarted worker's backing file was wiped/replaced: the CRC
  // revalidation must demote the object to loss — never serve wrong bytes.
  auto cfg = fast_config();
  KeystoneService ks(cfg, nullptr);
  BT_ASSERT(ks.initialize() == ErrorCode::OK);
  auto w1 = std::make_unique<FakeWorker>("w1", 1 << 20, StorageClass::HDD);
  BT_EXPECT_OK(ks.register_worker(w1->info()));
  BT_EXPECT_OK(ks.register_memory_pool(w1->pool));

  WorkerConfig wc;
  wc.replication_factor = 1;
  wc.max_workers_per_copy = 1;
  std::vector<uint8_t> payload(64 * 1024);
  for (size_t i = 0; i < payload.size(); ++i) payload[i] = static_cast<uint8_t>(i * 31 + 7);
  auto placed = ks.put_start("stale/obj", payload.size(), wc,
                             crc32c(payload.data(), payload.size()));
  BT_ASSERT_OK(placed);
  auto client = transport::make_transport_client();
  const auto& shard = placed.value()[0].shards[0];
  const auto& mem = std::get<MemoryLocation>(shard.location);
  BT_ASSERT(client->write(shard.remote, mem.remote_addr, mem.rkey, payload.data(),
                          payload.size()) == ErrorCode::OK);
  CopyShardCrcs stale_stamp;
  stale_stamp.copy_index = 0;
  stale_stamp.crcs.push_back(crc32c(payload.data(), payload.size()));
  BT_EXPECT(ks.put_complete("stale/obj", {stale_stamp}) == ErrorCode::OK);

  BT_EXPECT(ks.remove_worker("w1") == ErrorCode::OK);
  w1.reset();
  BT_EXPECT(ks.object_exists("stale/obj").value());

  // Restart with a ZEROED "backing file": revalidation must fail. The CRC
  // checks run on the health loop (the watch thread must not stream bytes).
  FakeWorker w1b("w1", 1 << 20, StorageClass::HDD);  // memory starts zeroed
  BT_EXPECT_OK(ks.register_worker(w1b.info()));
  BT_EXPECT_OK(ks.register_memory_pool(w1b.pool));
  ks.run_health_check_once();
  BT_EXPECT(!ks.object_exists("stale/obj").value());
  BT_EXPECT_EQ(ks.counters().objects_lost.load(), 1ull);
}

BTEST(Keystone, SingleReplicaLostObjectIsDropped) {
  auto cfg = fast_config();
  KeystoneService ks(cfg, nullptr);
  BT_ASSERT(ks.initialize() == ErrorCode::OK);
  FakeWorker w1("w1", 1 << 20), w2("w2", 1 << 20);
  BT_EXPECT_OK(ks.register_worker(w1.info()));
  BT_EXPECT_OK(ks.register_memory_pool(w1.pool));
  BT_EXPECT_OK(ks.register_worker(w2.info()));
  BT_EXPECT_OK(ks.register_memory_pool(w2.pool));

  WorkerConfig wc;
  wc.replication_factor = 1;
  wc.max_workers_per_copy = 1;
  auto placed = ks.put_start("fragile", 4096, wc);
  BT_ASSERT_OK(placed);
  BT_EXPECT_OK(ks.put_complete("fragile"));
  const NodeId victim = placed.value()[0].shards[0].worker_id;
  BT_EXPECT(ks.remove_worker(victim) == ErrorCode::OK);
  BT_EXPECT(!ks.object_exists("fragile").value());
  BT_EXPECT_EQ(ks.counters().objects_lost.load(), 1ull);
}

BTEST(Keystone, RestartRecoversPreUpgradeRecordLayouts) {
  // Records persisted by OLDER builds — before erasure coding, and before
  // content CRCs — must decode through the legacy fallbacks on restart, not
  // be purged as garbage. Both historical layouts are hand-encoded here
  // exactly as those builds wrote them.
  auto coordinator = std::make_shared<coord::MemCoordinator>();
  auto cfg = fast_config();
  FakeWorker w1("w1", 1 << 20);
  {  // Registry records in the pre-envelope (v1) layout those builds wrote.
    wire::Writer w;
    const auto info = w1.info();
    wire::encode_fields(w, info.worker_id, info.address, info.topo.slice_id,
                        info.topo.host_id, info.topo.chip_id, info.registered_at_ms,
                        info.last_heartbeat_ms);
    auto b = w.take();
    BT_EXPECT_OK(coordinator->put(coord::worker_key(cfg.cluster_id, w1.id), std::string(b.begin(), b.end())));
  }
  {
    wire::Writer w;
    wire::encode_fields(w, w1.pool.id, w1.pool.node_id, w1.pool.base_addr, w1.pool.size,
                        w1.pool.used, w1.pool.storage_class, w1.pool.remote.transport,
                        w1.pool.remote.endpoint, w1.pool.remote.remote_base,
                        w1.pool.remote.rkey_hex, w1.pool.topo.slice_id, w1.pool.topo.host_id,
                        w1.pool.topo.chip_id);
    // v1 pool records could end here (pre-alignment) — exercise exactly that.
    auto b = w.take();
    BT_EXPECT_OK(coordinator->put(coord::pool_key(cfg.cluster_id, w1.id, w1.pool.id),
                     std::string(b.begin(), b.end())));
  }
  BT_EXPECT_OK(coordinator->put_with_ttl(coord::heartbeat_key(cfg.cluster_id, w1.id), "alive", 60000));

  // Shards in the historical layouts were UNPREFIXED (pre-wire-v2): every
  // nested field back-to-back, exactly as those builds wrote them.
  auto encode_shard = [&](wire::Writer& w, uint64_t off, uint64_t len) {
    wire::encode_fields(w, w1.pool.id, w1.id);                            // pool, worker
    wire::encode_fields(w, w1.pool.remote.transport, w1.pool.remote.endpoint,
                        w1.pool.remote.remote_base, w1.pool.remote.rkey_hex);
    wire::encode_fields(w, StorageClass::RAM_CPU, len);
    w.put<uint8_t>(0);  // LocationDetail alternative: MemoryLocation
    wire::encode_fields(w, w1.pool.remote.remote_base + off,
                        std::stoull(w1.pool.remote.rkey_hex, nullptr, 16), len);
  };
  auto encode_config_legacy = [](wire::Writer& w) {
    // Pre-EC WorkerConfig: 10 fields, no ec_data/ec_parity.
    wire::encode_fields(w, uint64_t{1}, uint64_t{1}, false, std::string{},
                        std::vector<StorageClass>{}, uint64_t{0}, true, false,
                        uint64_t{256 * 1024}, int32_t{-1});
  };

  {  // Layout 1: pre-EC (copy = copy_index + shards only).
    wire::Writer w;
    wire::encode_fields(w, uint64_t{4096}, uint64_t{0}, false, uint8_t{1});
    encode_config_legacy(w);
    w.put<uint32_t>(1);          // one copy
    w.put<uint32_t>(0);          // copy_index
    w.put<uint32_t>(1);          // one shard
    encode_shard(w, 0, 4096);
    wire::encode_fields(w, int64_t{1}, int64_t{2});  // wall-clock stamps
    auto bytes = w.take();
    BT_EXPECT_OK(coordinator->put(coord::object_record_key(cfg.cluster_id, "legacy/pre-ec"),
                     std::string(bytes.begin(), bytes.end())));
  }
  {  // Layout 2: EC-era (copy carries ec fields, config carries ec fields,
     //           but neither has content_crc).
    wire::Writer w;
    wire::encode_fields(w, uint64_t{8000}, uint64_t{0}, false, uint8_t{1});
    wire::encode_fields(w, uint64_t{1}, uint64_t{1}, false, std::string{},
                        std::vector<StorageClass>{}, uint64_t{0}, true, false,
                        uint64_t{256 * 1024}, int32_t{-1}, uint64_t{2}, uint64_t{1});
    w.put<uint32_t>(1);          // one copy
    w.put<uint32_t>(0);          // copy_index
    w.put<uint32_t>(3);          // three shards (2 data + 1 parity)
    encode_shard(w, 8192, 4000);
    encode_shard(w, 16384, 4000);
    encode_shard(w, 24576, 4000);
    wire::encode_fields(w, uint32_t{2}, uint32_t{1}, uint64_t{8000});  // ec geometry
    wire::encode_fields(w, int64_t{3}, int64_t{4});
    auto bytes = w.take();
    BT_EXPECT_OK(coordinator->put(coord::object_record_key(cfg.cluster_id, "legacy/ec-era"),
                     std::string(bytes.begin(), bytes.end())));
  }
  {  // Layout 3: last pre-envelope generation — content_crc present, but no
     //           struct length prefixes and no record envelope.
    wire::Writer w;
    wire::encode_fields(w, uint64_t{2048}, uint64_t{0}, false, uint8_t{1});
    wire::encode_fields(w, uint64_t{1}, uint64_t{1}, false, std::string{},
                        std::vector<StorageClass>{}, uint64_t{0}, true, false,
                        uint64_t{256 * 1024}, int32_t{-1}, uint64_t{0}, uint64_t{0});
    w.put<uint32_t>(1);          // one copy
    w.put<uint32_t>(0);          // copy_index
    w.put<uint32_t>(1);          // one shard
    encode_shard(w, 32768, 2048);
    wire::encode_fields(w, uint32_t{0}, uint32_t{0}, uint64_t{0});  // ec geometry (none)
    wire::encode_fields(w, uint32_t{0xABCD1234});                   // content_crc
    wire::encode_fields(w, int64_t{5}, int64_t{6});
    auto bytes = w.take();
    BT_EXPECT_OK(coordinator->put(coord::object_record_key(cfg.cluster_id, "legacy/crc-era"),
                     std::string(bytes.begin(), bytes.end())));
  }

  KeystoneService ks(cfg, coordinator);
  BT_ASSERT(ks.initialize() == ErrorCode::OK);
  BT_EXPECT(ks.object_exists("legacy/pre-ec").value());
  BT_EXPECT(ks.object_exists("legacy/ec-era").value());

  auto pre = ks.get_workers("legacy/pre-ec");
  BT_ASSERT_OK(pre);
  BT_EXPECT_EQ(pre.value()[0].shards.size(), 1u);
  BT_EXPECT_EQ(pre.value()[0].ec_data_shards, 0u);
  BT_EXPECT_EQ(pre.value()[0].content_crc, 0u);  // unknown: reads skip verify

  auto ec = ks.get_workers("legacy/ec-era");
  BT_ASSERT_OK(ec);
  BT_EXPECT_EQ(ec.value()[0].ec_data_shards, 2u);
  BT_EXPECT_EQ(ec.value()[0].ec_parity_shards, 1u);
  BT_EXPECT_EQ(ec.value()[0].ec_object_size, 8000u);
  BT_EXPECT_EQ(ec.value()[0].content_crc, 0u);

  BT_EXPECT(ks.object_exists("legacy/crc-era").value());
  auto crc = ks.get_workers("legacy/crc-era");
  BT_ASSERT_OK(crc);
  BT_EXPECT_EQ(crc.value()[0].content_crc, 0xABCD1234u);
  BT_EXPECT(crc.value()[0].shard_crcs.empty());  // pre-shard-CRC record

  // Adoption really registered the ranges: fresh allocations avoid them.
  WorkerConfig wc;
  wc.replication_factor = 1;
  wc.max_workers_per_copy = 1;
  auto fresh = ks.put_start("legacy/new", 4096, wc);
  BT_ASSERT_OK(fresh);
  const auto& mem = std::get<MemoryLocation>(fresh.value()[0].shards[0].location);
  const uint64_t lo = mem.remote_addr - w1.pool.remote.remote_base;
  const uint64_t hi = lo + 4096;
  // The actual invariant: no overlap with ANY adopted legacy range.
  const std::pair<uint64_t, uint64_t> adopted[] = {
      {0, 4096}, {8192, 12192}, {16384, 20384}, {24576, 28576}, {32768, 34816}};
  for (const auto& [a, b] : adopted) {
    BT_EXPECT(hi <= a || lo >= b);
  }
}

BTEST(Keystone, FutureFormatRecordsAreKeptNotDeleted) {
  // A record enveloped with a bumped format byte (written by a build newer
  // than this one, seen during a rollback window) is unusable here — but it
  // is object metadata, not garbage: boot must keep it in the coordinator
  // for the newer keystone to serve, and must not serve the object itself.
  auto coordinator = std::make_shared<coord::MemCoordinator>();
  auto cfg = fast_config();
  FakeWorker w1("w1", 1 << 20);
  BT_EXPECT_OK(coordinator->put(coord::worker_key(cfg.cluster_id, w1.id), encode_worker_info(w1.info())));
  BT_EXPECT_OK(coordinator->put(coord::pool_key(cfg.cluster_id, w1.id, w1.pool.id),
                   encode_pool_record(w1.pool)));
  BT_EXPECT_OK(coordinator->put_with_ttl(coord::heartbeat_key(cfg.cluster_id, w1.id), "alive", 60000));

  const auto key = coord::object_record_key(cfg.cluster_id, "future/obj");
  {
    wire::Writer w;
    w.put(~0ull);          // record magic
    w.put<uint8_t>(3);     // bumped format: incompatible future layout
    wire::encode_fields(w, std::string("opaque future payload"));
    auto b = w.take();
    BT_EXPECT_OK(coordinator->put(key, std::string(b.begin(), b.end())));
  }
  {  // Plain garbage (no envelope, undecodable) IS deleted at boot.
    wire::Writer w;
    wire::encode_fields(w, std::string("#!"));
    auto b = w.take();
    BT_EXPECT_OK(coordinator->put(coord::object_record_key(cfg.cluster_id, "garbage/obj"),
                     std::string(b.begin(), b.end())));
  }

  KeystoneService ks(cfg, coordinator);
  BT_ASSERT(ks.initialize() == ErrorCode::OK);
  BT_EXPECT(!ks.object_exists("future/obj").value());
  auto kept = coordinator->get(key);
  BT_EXPECT(kept.ok());  // record survived boot
  auto purged = coordinator->get(coord::object_record_key(cfg.cluster_id, "garbage/obj"));
  BT_EXPECT(!purged.ok());  // garbage did not
}

BTEST(Keystone, FencedPersistStepsDownStaleLeader) {
  // The split-brain window fencing exists for: a leader whose election
  // lease lapsed during a stall (SIGSTOP/GC pause) and whose keepalive
  // thread has NOT yet noticed (refresh interval here is effectively
  // never). Lease expiry erases its candidacy with no callback, so it
  // still believes it leads — its next durable mutation must come back
  // FENCED, fail the client call, and force the stepdown.
  auto coordinator = std::make_shared<coord::MemCoordinator>();
  auto cfg = fast_config();
  cfg.enable_ha = true;
  cfg.service_registration_ttl_sec = 1;      // candidacy lease: 1s
  cfg.service_refresh_interval_sec = 3600;   // keepalive: effectively never
  // This test deliberately idles for seconds; the 1s fast_config heartbeat
  // TTL would let the health loop reap w1 (and repair-delete fence/obj)
  // mid-test. Worker liveness is not what is under test here.
  cfg.worker_heartbeat_ttl_sec = 3600;
  KeystoneService ks(cfg, coordinator);
  BT_ASSERT(ks.initialize() == ErrorCode::OK);
  BT_ASSERT(ks.start() == ErrorCode::OK);
  BT_EXPECT(eventually([&] { return ks.is_leader(); }));

  FakeWorker w1("w1", 1 << 20);
  BT_EXPECT_OK(ks.register_worker(w1.info()));
  BT_EXPECT_OK(ks.register_memory_pool(w1.pool));

  WorkerConfig wc;
  wc.replication_factor = 1;
  wc.max_workers_per_copy = 1;
  BT_ASSERT_OK(ks.put_start("fence/obj", 4096, wc));

  // The lease lapses (no keepalives) and an imposter wins the election
  // with a strictly newer epoch. ks gets NO signal of any of this.
  const std::string election = "btpu-keystone-leader/" + cfg.cluster_id;
  BT_EXPECT(eventually([&] {
    return coordinator->current_leader(election).ok() == false;
  }, 3000));
  std::atomic<bool> imposter_leader{false};
  BT_ASSERT(coordinator->campaign(election, "imposter", 60000,
                                  [&](bool l, uint64_t) { imposter_leader = l; }) ==
            ErrorCode::OK);
  BT_EXPECT(eventually([&] { return imposter_leader.load(); }));
  BT_EXPECT(ks.is_leader());  // still believes — exactly the danger window

  // The commit point is where fencing bites: the durable record is refused,
  // the client call fails, and the stale leader steps down.
  BT_EXPECT(ks.put_complete("fence/obj") == ErrorCode::FENCED);
  BT_EXPECT(!ks.is_leader());
  BT_EXPECT(ks.put_start("fence/late", 1024, wc).error() == ErrorCode::NOT_LEADER);
  // Nothing leaked into durable state from the deposed leader.
  auto rec = coordinator->get(coord::object_record_key(cfg.cluster_id, "fence/obj"));
  BT_EXPECT(!rec.ok());
  ks.stop();
}
