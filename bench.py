#!/usr/bin/env python3
"""Headline benchmark. Prints ONE JSON line:
    {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}

Metric: sustained get throughput for 1 MiB objects striped over a 4-worker
embedded cluster (keystone placement + one-sided transfers on the worker
data plane) — the reference's benchmark_client measured the same put/get
loop (clients/benchmark_client.cpp) but never published numbers; its
worker config advertises a 25 Gbps (3.125 GB/s) link as max_bw_gbps
(configs/worker.yaml:24-25), which is the baseline denominator here.

Secondary numbers (put GB/s, 64 KiB p99 vs the <50 us north star) go to
stderr so the stdout contract stays one line.
"""

import json
import subprocess
import sys
from pathlib import Path

REPO_ROOT = Path(__file__).resolve().parent
BASELINE_GBPS = 3.125  # 25 Gbps reference link (configs/worker.yaml:24)


def ensure_built() -> Path:
    sys.path.insert(0, str(REPO_ROOT))
    from blackbird_tpu import native

    native.build_native()
    return REPO_ROOT / "build" / "bb-bench"


def run_bench(binary: Path, size: int, iterations: int):
    result = subprocess.run(
        [
            str(binary), "--embedded", "4", "--size", str(size),
            "--iterations", str(iterations), "--max-workers", "4", "--json",
        ],
        capture_output=True, text=True, timeout=600, cwd=REPO_ROOT,
    )
    if result.returncode != 0:
        raise RuntimeError(f"bb-bench failed: {result.stderr[-500:]}")
    rows = [json.loads(line) for line in result.stdout.splitlines() if line.strip()]
    return {row["op"]: row for row in rows}


def bench_hbm_tier() -> None:
    """Acceptance ladder item 2 (BASELINE.md): batched 1 MiB put/get against
    the HBM_TPU tier. On a TPU VM the JAX provider puts objects in real
    device HBM; elsewhere this exercises the same path on the CPU device.
    Secondary metric -> stderr (stdout stays the one-line contract)."""
    import time

    try:
        import jax

        from blackbird_tpu import EmbeddedCluster, StorageClass
        from blackbird_tpu.hbm import JaxHbmProvider

        platform = jax.devices()[0].platform
        provider = JaxHbmProvider(chunk_bytes=1 << 20).register()
        try:
            with EmbeddedCluster(workers=1, pool_bytes=256 << 20,
                                 storage_class=StorageClass.HBM_TPU) as cluster:
                client = cluster.client()
                payload = b"\xa5" * (1 << 20)
                # Tunneled dev TPUs read back at ~0.1 GB/s, so keep the
                # iteration count low; real TPU-VM HBM sustains GB/s.
                iters = 8
                for i in range(iters):  # batched puts
                    client.put(f"bench/hbm{i}", payload, max_workers=1)
                provider.synchronize()  # don't bill in-flight H2D to the get loop
                t0 = time.perf_counter()
                for i in range(iters):
                    client.get(f"bench/hbm{i}")
                get_s = time.perf_counter() - t0
                t0 = time.perf_counter()
                for i in range(iters):
                    client.put(f"bench/hbm_w{i}", payload, max_workers=1)
                provider.synchronize()  # device_put is async; time real completion
                put_s = time.perf_counter() - t0
                gb = iters * len(payload) / 1e9
                print(
                    f"hbm tier ({platform}): put 1MiB {gb / put_s:.2f} GB/s | "
                    f"get 1MiB {gb / get_s:.2f} GB/s",
                    file=sys.stderr,
                )
        finally:
            JaxHbmProvider.unregister()
    except Exception as exc:  # secondary metric: never break the contract
        print(f"hbm tier bench skipped: {exc}", file=sys.stderr)


def main() -> int:
    binary = ensure_built()
    main_rows = run_bench(binary, size=1 << 20, iterations=150)
    small_rows = run_bench(binary, size=64 << 10, iterations=300)

    get_gbps = main_rows["get"]["gbps"]
    print(
        f"put 1MiB: {main_rows['put']['gbps']:.2f} GB/s (p99 {main_rows['put']['p99_us']:.0f}us) | "
        f"get 64KiB p99: {small_rows['get']['p99_us']:.1f}us (north star <50us) | "
        f"put 64KiB p99: {small_rows['put']['p99_us']:.1f}us",
        file=sys.stderr,
    )
    bench_hbm_tier()
    print(json.dumps({
        "metric": "get_gbps_1mib_striped4",
        "value": round(get_gbps, 3),
        "unit": "GB/s",
        "vs_baseline": round(get_gbps / BASELINE_GBPS, 3),
    }))
    return 0


if __name__ == "__main__":
    sys.exit(main())
