#!/usr/bin/env bash
# One-command ThreadSanitizer leg: builds the native tree (plus bb-soak)
# under -fsanitize=thread into a separate build/tsan object tree and runs
# the FULL native suite — all 25 suites, not just the concurrency-heavy
# ones (PR 3 widened this from "Cache Transport").
# Narrow when iterating: TSAN_FILTERS="Cache Transport" scripts/tsan.sh
set -euo pipefail
cd "$(dirname "$0")/.."
exec make tsan ${TSAN_FILTERS:+TSAN_FILTERS="${TSAN_FILTERS}"}
