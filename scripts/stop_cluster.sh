#!/usr/bin/env bash
# Stops any bb-* processes from start_cluster.sh.
set -uo pipefail
pkill -f 'bb-worker --config' 2>/dev/null
pkill -f 'bb-keystone --config' 2>/dev/null
pkill -f 'bb-coord' 2>/dev/null
echo "stopped"
