#include "btest.h"

// TSan one-sided-RMA suppression + clockwait interceptor shim, shared with
// the sanitized executables.
#include "../exe/tsan_clockwait_shim.h"
#include "../exe/tsan_rma_suppression.h"

int main(int argc, char** argv) { return btest::run_all(argc, argv); }
