#include "btpu/alloc/range_allocator.h"

#include <algorithm>
#include <numeric>
#include <unordered_map>
#include <unordered_set>

#include "btpu/common/log.h"
#include "btpu/ec/rs.h"

namespace btpu::alloc {

namespace {
uint64_t ceil_div(uint64_t a, uint64_t b) { return (a + b - 1) / b; }

// Pool ids grouped by owning worker, preserving rank order of first
// appearance — the shared substrate for worker-level anti-affinity (replica
// spread) and within-copy worker striping.
struct NodeGroups {
  std::vector<NodeId> order;
  std::unordered_map<NodeId, std::vector<MemoryPoolId>> pools;
};

NodeGroups group_by_node(const PoolMap& pools, const std::vector<MemoryPoolId>& ids) {
  NodeGroups g;
  for (const auto& id : ids) {
    const NodeId& node = pools.at(id).node_id;
    auto [it, inserted] = g.pools.try_emplace(node);
    if (inserted) g.order.push_back(node);
    it->second.push_back(id);
  }
  return g;
}

// Round-robin passes over workers (rank order preserved within each pass):
// any prefix of the result covers as many distinct workers as possible.
std::vector<MemoryPoolId> interleave_nodes(const NodeGroups& g) {
  std::vector<MemoryPoolId> out;
  size_t total = 0;
  for (const auto& [node, ids] : g.pools) total += ids.size();
  out.reserve(total);
  for (size_t pass = 0; out.size() < total; ++pass) {
    for (const auto& node : g.order) {
      const auto& ids = g.pools.at(node);
      if (pass < ids.size()) out.push_back(ids[pass]);
    }
  }
  return out;
}
}  // namespace

ErrorCode RangeAllocator::ensure_pool_allocator(const MemoryPool& pool) {
  {
    // Double-checked: the common case (allocator already exists) must not
    // take the exclusive pools lock — that would re-serialize EVERY
    // allocation behind a single writer mutex and undo the keystone's
    // control-plane sharding.
    SharedLock lock(pools_mutex_);
    const auto& allocators = pool_allocators_;
    if (allocators.contains(pool.id)) return ErrorCode::OK;
  }
  WriterLock lock(pools_mutex_);
  if (pool_allocators_.contains(pool.id)) return ErrorCode::OK;
  try {
    // poolsan_track: the keystone-side allocator is the one authority on
    // placement carve/free, so it owns the pool's sanitizer shadow
    // (generations, red zones, quarantine — btpu/common/poolsan.h).
    pool_allocators_[pool.id] = std::make_unique<PoolAllocator>(pool, /*poolsan_track=*/true);
    LOG_DEBUG << "created allocator for pool " << pool.id << " (" << pool.size << " bytes, "
              << storage_class_name(pool.storage_class) << ")";
    return ErrorCode::OK;
  } catch (const std::invalid_argument& e) {
    LOG_ERROR << "bad pool " << pool.id << ": " << e.what();
    return ErrorCode::INVALID_PARAMETERS;
  } catch (const std::exception& e) {
    LOG_ERROR << "pool " << pool.id << ": " << e.what();
    return ErrorCode::INTERNAL_ERROR;
  }
}

ErrorCode RangeAllocator::ensure_pool_allocators(const PoolMap& pools) {
  {
    SharedLock lock(pools_mutex_);
    const auto& allocators = pool_allocators_;
    bool missing = false;
    for (const auto& [id, pool] : pools) {
      if (!allocators.contains(id)) {
        missing = true;
        break;
      }
    }
    if (!missing) return ErrorCode::OK;
  }
  for (const auto& [id, pool] : pools) {
    BTPU_RETURN_IF_ERROR(ensure_pool_allocator(pool));
  }
  return ErrorCode::OK;
}

uint64_t RangeAllocator::avail_of(const MemoryPoolId& id, const MemoryPool& pool) const {
  SharedLock lock(pools_mutex_);
  auto it = pool_allocators_.find(id);
  return it != pool_allocators_.end() ? it->second->total_free() : pool.available();
}

// Candidate selection: filter by node + class preference, rank by (slice
// affinity, available space), then search the largest worker count w such
// that w pools can each hold ceil(total/w) bytes.
std::vector<MemoryPoolId> RangeAllocator::select_candidate_pools(
    const AllocationRequest& request, const PoolMap& pools) const {
  const bool has_class_pref = !request.preferred_classes.empty();
  auto class_preferred = [&](StorageClass c) {
    if (!has_class_pref) return true;
    return std::find(request.preferred_classes.begin(), request.preferred_classes.end(), c) !=
           request.preferred_classes.end();
  };

  const bool is_ec = request.ec_parity_shards > 0 && request.ec_data_shards > 0;
  std::vector<MemoryPoolId> preferred, fallback;
  for (const auto& [id, pool] : pools) {
    // Coded shards have a wire-only client path: device-tier pools must not
    // consume selection slots (allocate_ec would drop them afterward and
    // overload the rest past what the capacity check vetted). Same for
    // explicit wire_only staging requests (EC repair/drain moves).
    if ((is_ec || request.wire_only) &&
        (pool.remote.transport == TransportKind::HBM ||
         pool.remote.transport == TransportKind::ICI))
      continue;
    if (!request.preferred_node.empty() && pool.node_id != request.preferred_node) continue;
    if (std::find(request.excluded_nodes.begin(), request.excluded_nodes.end(),
                  pool.node_id) != request.excluded_nodes.end())
      continue;
    if (!class_preferred(pool.storage_class)) {
      if (!request.restrict_to_preferred) fallback.push_back(id);
      continue;
    }
    preferred.push_back(id);
  }

  // One availability snapshot for ranking AND the w-search below, taken
  // under a single shared pools lock: the old per-candidate avail_of calls
  // paid 2+ shared-mutex acquisitions per pool per allocation, which adds
  // up at control-plane rates. The snapshot is equally racy either way —
  // commit detects a stale choice when the pool allocator refuses the carve
  // and the whole request rolls back.
  std::unordered_map<MemoryPoolId, uint64_t> avail;
  {
    SharedLock lock(pools_mutex_);
    const auto& allocators = pool_allocators_;
    auto snapshot = [&](const std::vector<MemoryPoolId>& v) {
      for (const auto& id : v) {
        auto it = allocators.find(id);
        avail.emplace(id, it != allocators.end() ? it->second->total_free()
                                                 : pools.at(id).available());
      }
    };
    avail.reserve(preferred.size() + fallback.size());
    snapshot(preferred);
    snapshot(fallback);
  }

  auto rank = [&](std::vector<MemoryPoolId>& v) {
    // The snapshot is taken BEFORE sorting: concurrent allocations mutate
    // per-pool free space, and a comparator whose keys change mid-sort
    // violates strict weak ordering — UB that can corrupt the vector.
    std::sort(v.begin(), v.end(), [&](const MemoryPoolId& a, const MemoryPoolId& b) {
      if (request.preferred_slice >= 0) {
        if (request.preferred_host >= 0) {
          // Host-local pools outrank merely same-slice ones: the mesh-aware
          // shard lane wants the writer's own host first, ICI-reachable
          // same-slice hosts as the first spillover, DCN last.
          auto host_local = [&](const MemoryPoolId& id) {
            const auto& t = pools.at(id).topo;
            return t.slice_id == request.preferred_slice && t.host_id == request.preferred_host;
          };
          const bool ha = host_local(a);
          const bool hb = host_local(b);
          if (ha != hb) return ha;
        }
        const bool sa = pools.at(a).topo.slice_id == request.preferred_slice;
        const bool sb = pools.at(b).topo.slice_id == request.preferred_slice;
        if (sa != sb) return sa;  // same-slice (ICI-reachable) pools first
      }
      const uint64_t fa = avail.at(a);
      const uint64_t fb = avail.at(b);
      if (fa != fb) return fa > fb;
      return a < b;  // deterministic tie-break
    });
  };
  rank(preferred);
  rank(fallback);

  // Replicated requests narrow to `want` pools below; if those all sit on
  // one worker (several pools per worker process), copies could never reach
  // disjoint failure domains. Re-order so the selection covers as many
  // distinct workers as the cluster has.
  if (request.replication_factor > 1) {
    preferred = interleave_nodes(group_by_node(pools, preferred));
    fallback = interleave_nodes(group_by_node(pools, fallback));
  }

  // EC copies need (k+m) * ceil(size/k) bytes over k+m slots; replication
  // needs size * r over (stripe width * r) slots.
  const uint64_t total_bytes =
      is_ec ? ceil_div(request.data_size, request.ec_data_shards) *
                  (request.ec_data_shards + request.ec_parity_shards)
            : request.data_size * request.replication_factor;
  const size_t want = is_ec ? request.ec_data_shards + request.ec_parity_shards
                            : request.max_workers_per_copy * request.replication_factor;
  const size_t max_w = std::min(want, preferred.size() + fallback.size());

  for (size_t w = max_w; w >= 1; --w) {
    // EC shards are indivisible units: with w pools, round-robin puts
    // ceil(n_shards/w) whole shards on the fullest pool, which is more
    // than the even-split ceil(total/w) estimate.
    const uint64_t per_pool =
        is_ec ? ceil_div(request.ec_data_shards + request.ec_parity_shards, w) *
                    ceil_div(request.data_size, request.ec_data_shards)
              : ceil_div(total_bytes, w);
    std::vector<MemoryPoolId> selected;
    selected.reserve(w);
    for (const auto& id : preferred) {
      if (selected.size() == w) break;
      if (avail.at(id) >= per_pool) selected.push_back(id);
    }
    for (const auto& id : fallback) {
      if (selected.size() == w) break;
      if (avail.at(id) >= per_pool) selected.push_back(id);
    }
    if (selected.size() == w) return selected;
    if (w == 1) break;
  }
  return {};
}

Result<AllocationResult> RangeAllocator::allocate(const AllocationRequest& request,
                                                  const PoolMap& pools) {
  if (request.data_size == 0) return ErrorCode::INVALID_PARAMETERS;
  if (request.replication_factor == 0) return ErrorCode::INVALID_PARAMETERS;
  if (request.ec_parity_shards > 0 &&
      (request.ec_data_shards == 0 ||
       request.ec_data_shards + request.ec_parity_shards > ec::kMaxTotalShards))
    return ErrorCode::INVALID_PARAMETERS;

  BTPU_RETURN_IF_ERROR(ensure_pool_allocators(pools));

  auto candidates = select_candidate_pools(request, pools);
  if (candidates.empty()) {
    LOG_WARN << "no eligible pools for object " << request.object_key << " ("
             << request.data_size << "B x" << request.replication_factor << ")";
    return ErrorCode::INSUFFICIENT_SPACE;
  }

  if (request.ec_parity_shards > 0) return allocate_ec(request, candidates, pools);

  if (!request.enable_striping || request.prefer_contiguous) {
    // Contiguous = striping degenerated to one worker per copy.
    AllocationRequest contiguous = request;
    contiguous.max_workers_per_copy = 1;
    auto narrowed = select_candidate_pools(contiguous, pools);
    if (narrowed.empty()) return ErrorCode::INSUFFICIENT_SPACE;
    return allocate_with_striping(contiguous, narrowed, pools);
  }
  return allocate_with_striping(request, candidates, pools);
}

// One erasure-coded copy: exactly k+m equal shards of ceil(size/k) bytes.
// Shards round-robin over DISTINCT WORKERS first (the tolerance contract is
// "any m WORKER losses" — two shards behind one failure domain would
// silently halve it), and only wrap onto reused workers when the cluster is
// smaller than k+m. Device-tier pools (DeviceLocation placements) are not
// eligible: the coded data path is wire-only.
Result<AllocationResult> RangeAllocator::allocate_ec(
    const AllocationRequest& request, const std::vector<MemoryPoolId>& candidates,
    const PoolMap& pools) {
  const size_t k = request.ec_data_shards;
  const size_t m = request.ec_parity_shards;
  if (k == 0 || k + m > ec::kMaxTotalShards) return ErrorCode::INVALID_PARAMETERS;
  const uint64_t shard_len = ceil_div(request.data_size, k);

  // Order candidates so the first n entries cover distinct workers (rank
  // order preserved within each pass), excluding device-tier pools.
  std::vector<MemoryPoolId> ordered;
  {
    std::unordered_set<NodeId> seen;
    std::vector<MemoryPoolId> rest;
    for (const auto& id : candidates) {
      const MemoryPool& pool = pools.at(id);
      if (pool.remote.transport == TransportKind::HBM ||
          pool.remote.transport == TransportKind::ICI)
        continue;  // DeviceLocation shards have no coded client path
      if (seen.insert(pool.node_id).second) {
        ordered.push_back(id);
      } else {
        rest.push_back(id);
      }
    }
    ordered.insert(ordered.end(), rest.begin(), rest.end());
  }
  if (ordered.empty()) return ErrorCode::INSUFFICIENT_SPACE;

  AllocationResult result{};
  std::vector<std::pair<MemoryPoolId, Range>> all_ranges;
  CopyPlacement copy;
  copy.copy_index = 0;
  copy.ec_data_shards = static_cast<uint32_t>(k);
  copy.ec_parity_shards = static_cast<uint32_t>(m);
  copy.ec_object_size = request.data_size;
  copy.shards.reserve(k + m);

  for (size_t i = 0; i < k + m; ++i) {
    const MemoryPoolId& pool_id = ordered[i % ordered.size()];
    std::optional<Range> range;
    {
      SharedLock lock(pools_mutex_);
      auto it = pool_allocators_.find(pool_id);
      if (it == pool_allocators_.end()) {
        rollback_allocation(all_ranges);
        return ErrorCode::MEMORY_POOL_NOT_FOUND;
      }
      range = it->second->allocate(shard_len);
    }
    if (!range) {
      rollback_allocation(all_ranges);
      return ErrorCode::INSUFFICIENT_SPACE;
    }
    all_ranges.emplace_back(pool_id, *range);
    auto shard = create_shard_placement(pool_id, *range, pools);
    if (!shard.ok()) {
      rollback_allocation(all_ranges);
      return shard.error();
    }
    copy.shards.push_back(std::move(shard).value());
  }
  if (auto ec = commit_allocation(request.object_key, all_ranges); ec != ErrorCode::OK) {
    rollback_allocation(all_ranges);
    return ec;
  }
  result.copies.push_back(std::move(copy));
  result.pools_used = std::min(ordered.size(), k + m);
  result.total_shards_created = k + m;
  result.stats.avg_shard_size = shard_len;
  return result;
}

Result<AllocationResult> RangeAllocator::allocate_with_striping(
    const AllocationRequest& request, const std::vector<MemoryPoolId>& candidates,
    const PoolMap& pools) {
  const uint64_t per_copy = request.data_size;
  size_t workers_per_copy = std::min(request.max_workers_per_copy, candidates.size());

  // With replication, trade stripe width for replica spread so copies land on
  // disjoint pools when the pool count allows (reference :291-300).
  if (request.replication_factor > 1 && candidates.size() > workers_per_copy) {
    const size_t ideal = candidates.size() / request.replication_factor;
    if (ideal >= 1) workers_per_copy = std::min(workers_per_copy, ideal);
  }
  // Respect min_shard_size up front: never stripe so wide that shards would
  // fall below the floor (the reference detects this mid-carve and aborts the
  // whole request, :318-324 — we clamp instead and only fail when even one
  // worker per copy cannot fit).
  if (workers_per_copy > 1 && per_copy / workers_per_copy < request.min_shard_size) {
    workers_per_copy = std::max<size_t>(1, per_copy / std::max<uint64_t>(request.min_shard_size, 1));
    workers_per_copy = std::min(workers_per_copy, candidates.size());
  }

  // Replica copies must not share a FAILURE DOMAIN (worker) when the cluster
  // is big enough: a multi-controller device plane runs several pools per
  // worker process, and pool-disjoint-but-worker-colocated copies would let
  // one process death take every copy (reference replication_factor contract,
  // keystone_service.cpp allocate path). Partition candidates by worker,
  // round-robin whole workers across copies; if the partitioned layout cannot
  // fit (uneven free space), fall back to the pool-interleaved layout —
  // co-location beats failing the put.
  std::vector<std::vector<MemoryPoolId>> per_copy_pools;
  if (request.replication_factor > 1) {
    const NodeGroups g = group_by_node(pools, candidates);
    if (g.order.size() >= request.replication_factor) {
      per_copy_pools.resize(request.replication_factor);
      for (size_t c = 0; c < request.replication_factor; ++c) {
        // Whole workers round-robin across copies, then each copy's pool
        // list is itself worker-interleaved so its stripe (the first
        // `width` entries below) spans the copy's workers, not just the
        // first one's pools.
        NodeGroups sub;
        for (size_t ni = c; ni < g.order.size(); ni += request.replication_factor) {
          sub.order.push_back(g.order[ni]);
          sub.pools.emplace(g.order[ni], g.pools.at(g.order[ni]));
        }
        per_copy_pools[c] = interleave_nodes(sub);
      }
    }
  }

  auto try_layout = [&](bool disjoint) -> Result<AllocationResult> {
    AllocationResult result{};
    result.copies.reserve(request.replication_factor);
    std::vector<std::pair<MemoryPoolId, Range>> all_ranges;

    for (size_t copy_idx = 0; copy_idx < request.replication_factor; ++copy_idx) {
      const std::vector<MemoryPoolId>& copy_pools =
          disjoint ? per_copy_pools[copy_idx] : candidates;
      const size_t width = std::min(workers_per_copy, copy_pools.size());
      const uint64_t base_shard = per_copy / width;
      const uint64_t remainder = per_copy % width;

      CopyPlacement copy;
      copy.copy_index = static_cast<uint32_t>(copy_idx);
      copy.shards.reserve(width);

      for (size_t widx = 0; widx < width; ++widx) {
        const size_t pool_idx = disjoint
                                    ? widx
                                    : (copy_idx * workers_per_copy + widx) % copy_pools.size();
        const MemoryPoolId& pool_id = copy_pools[pool_idx];
        const uint64_t shard_size = base_shard + (widx < remainder ? 1 : 0);

        std::optional<Range> range;
        {
          SharedLock lock(pools_mutex_);
          auto it = pool_allocators_.find(pool_id);
          if (it == pool_allocators_.end()) {
            rollback_allocation(all_ranges);
            return ErrorCode::MEMORY_POOL_NOT_FOUND;
          }
          range = it->second->allocate(shard_size);
        }
        if (!range) {
          rollback_allocation(all_ranges);
          return ErrorCode::INSUFFICIENT_SPACE;
        }
        all_ranges.emplace_back(pool_id, *range);

        auto shard = create_shard_placement(pool_id, *range, pools);
        if (!shard.ok()) {
          rollback_allocation(all_ranges);
          return shard.error();
        }
        copy.shards.push_back(std::move(shard).value());
      }
      result.total_shards_created += copy.shards.size();
      result.copies.push_back(std::move(copy));
    }

    if (auto ec = commit_allocation(request.object_key, all_ranges); ec != ErrorCode::OK) {
      rollback_allocation(all_ranges);
      return ec;
    }
    return result;
  };

  Result<AllocationResult> attempt = ErrorCode::INSUFFICIENT_SPACE;
  if (!per_copy_pools.empty()) {
    attempt = try_layout(/*disjoint=*/true);
    if (!attempt.ok() && attempt.error() != ErrorCode::INSUFFICIENT_SPACE) return attempt;
  }
  if (!attempt.ok()) attempt = try_layout(/*disjoint=*/false);
  if (!attempt.ok()) return attempt;
  AllocationResult result = std::move(attempt).value();

  result.pools_used = candidates.size();
  result.stats.avg_shard_size =
      result.total_shards_created ? request.data_size * request.replication_factor /
                                        result.total_shards_created
                                  : 0;
  if (!request.preferred_classes.empty()) {
    for (const auto& copy : result.copies) {
      for (const auto& shard : copy.shards) {
        if (std::find(request.preferred_classes.begin(), request.preferred_classes.end(),
                      shard.storage_class) == request.preferred_classes.end()) {
          result.stats.required_spillover = true;
        }
      }
    }
  }
  {
    SharedLock lock(pools_mutex_);
    double frag = 0.0;
    size_t counted = 0;
    for (const auto& id : candidates) {
      auto it = pool_allocators_.find(id);
      if (it != pool_allocators_.end()) {
        frag += it->second->fragmentation_ratio();
        ++counted;
      }
    }
    result.stats.fragmentation_score =
        counted ? static_cast<uint64_t>(100.0 * frag / static_cast<double>(counted)) : 0;
  }
  return result;
}

Result<ShardPlacement> RangeAllocator::create_shard_placement(const MemoryPoolId& pool_id,
                                                              const Range& range,
                                                              const PoolMap& pools) const {
  auto pool_it = pools.find(pool_id);
  if (pool_it == pools.end()) return ErrorCode::MEMORY_POOL_NOT_FOUND;
  const MemoryPool& pool = pool_it->second;

  SharedLock lock(pools_mutex_);
  auto alloc_it = pool_allocators_.find(pool_id);
  if (alloc_it == pool_allocators_.end()) return ErrorCode::MEMORY_POOL_NOT_FOUND;

  ShardPlacement shard;
  shard.pool_id = pool_id;
  shard.worker_id = pool.node_id;
  shard.remote = pool.remote;
  shard.storage_class = pool.storage_class;
  shard.length = range.length;
  if (pool.storage_class == StorageClass::HBM_TPU &&
      (pool.remote.transport == TransportKind::HBM ||
       pool.remote.transport == TransportKind::ICI)) {
    // On-device tier: clients address {device, region, offset} instead of a
    // flat remote pointer.
    shard.location = DeviceLocation{
        .device_id = pool.remote.endpoint,
        .region_id = pool.base_addr,
        .offset = range.offset,
        .size = range.length,
    };
  } else {
    shard.location = alloc_it->second->to_memory_location(range);
  }
  return shard;
}

ErrorCode RangeAllocator::commit_allocation(
    const ObjectKey& key, const std::vector<std::pair<MemoryPoolId, Range>>& ranges) {
  AllocShard& s = alloc_shard_for(key);
  WriterLock lock(s.mutex);
  if (s.map.contains(key)) {
    LOG_WARN << "object " << key << " already has an allocation";
    return ErrorCode::OBJECT_ALREADY_EXISTS;
  }
  ObjectAllocation alloc;
  alloc.ranges = ranges;
  alloc.total_size = std::accumulate(
      ranges.begin(), ranges.end(), uint64_t{0},
      [](uint64_t sum, const auto& pr) { return sum + pr.second.length; });
  s.map[key] = std::move(alloc);
  return ErrorCode::OK;
}

void RangeAllocator::rollback_allocation(
    const std::vector<std::pair<MemoryPoolId, Range>>& ranges) {
  SharedLock lock(pools_mutex_);
  for (const auto& [pool_id, range] : ranges) {
    auto it = pool_allocators_.find(pool_id);
    if (it != pool_allocators_.end()) it->second->free(range, "rollback");
  }
  if (!ranges.empty()) {
    LOG_DEBUG << "rolled back " << ranges.size() << " ranges";
  }
}

ErrorCode RangeAllocator::adopt_allocation(
    const ObjectKey& key, const std::vector<std::pair<MemoryPoolId, Range>>& ranges,
    const PoolMap& pools) {
  for (const auto& [id, pool] : pools) {
    BTPU_RETURN_IF_ERROR(ensure_pool_allocator(pool));
  }
  std::vector<std::pair<MemoryPoolId, Range>> carved;
  {
    SharedLock lock(pools_mutex_);
    for (const auto& [pool_id, range] : ranges) {
      auto it = pool_allocators_.find(pool_id);
      if (it == pool_allocators_.end() || !it->second->allocate_at(range)) {
        for (const auto& [cid, crange] : carved) {
          auto cit = pool_allocators_.find(cid);
          if (cit != pool_allocators_.end()) cit->second->free(crange);
        }
        return it == pool_allocators_.end() ? ErrorCode::MEMORY_POOL_NOT_FOUND
                                            : ErrorCode::ALLOCATION_FAILED;
      }
      carved.emplace_back(pool_id, range);
    }
  }
  if (auto ec = commit_allocation(key, ranges); ec != ErrorCode::OK) {
    rollback_allocation(carved);
    return ec;
  }
  return ErrorCode::OK;
}

// Two-key ops (rename/merge) transfer OWNERSHIP between shards rather than
// nesting two shard locks: the entry is extracted under the source shard,
// re-inserted under the destination, and put back if the destination check
// fails. The transient not-in-either-map window is safe because every
// caller OWNS both keys for the duration (slot commits own their slot key
// and the not-yet-published final key; movers own their '\x01'-staging
// keys) — nothing else can legitimately address them mid-op.
ErrorCode RangeAllocator::rename_object(const ObjectKey& from, const ObjectKey& to) {
  ObjectAllocation moved;
  {
    AllocShard& s = alloc_shard_for(from);
    WriterLock lock(s.mutex);
    auto it = s.map.find(from);
    if (it == s.map.end()) return ErrorCode::OBJECT_NOT_FOUND;
    moved = std::move(it->second);
    s.map.erase(it);
  }
  {
    AllocShard& s = alloc_shard_for(to);
    WriterLock lock(s.mutex);
    if (!s.map.contains(to)) {
      s.map[to] = std::move(moved);
      return ErrorCode::OK;
    }
  }
  AllocShard& s = alloc_shard_for(from);
  WriterLock lock(s.mutex);
  s.map[from] = std::move(moved);
  return ErrorCode::OBJECT_ALREADY_EXISTS;
}

ErrorCode RangeAllocator::merge_objects(const ObjectKey& from, const ObjectKey& to) {
  ObjectAllocation src;
  {
    AllocShard& s = alloc_shard_for(from);
    WriterLock lock(s.mutex);
    auto it = s.map.find(from);
    if (it == s.map.end()) return ErrorCode::OBJECT_NOT_FOUND;
    src = std::move(it->second);
    s.map.erase(it);
  }
  {
    AllocShard& s = alloc_shard_for(to);
    WriterLock lock(s.mutex);
    auto dst = s.map.find(to);
    if (dst != s.map.end()) {
      dst->second.ranges.insert(dst->second.ranges.end(),
                                std::make_move_iterator(src.ranges.begin()),
                                std::make_move_iterator(src.ranges.end()));
      dst->second.total_size += src.total_size;
      return ErrorCode::OK;
    }
  }
  AllocShard& s = alloc_shard_for(from);
  WriterLock lock(s.mutex);
  s.map[from] = std::move(src);
  return ErrorCode::OBJECT_NOT_FOUND;
}

ErrorCode RangeAllocator::release_range(const ObjectKey& key, const MemoryPoolId& pool_id,
                                        const Range& range) {
  // Lock order: pools before the allocation shard, matching free()/get_stats.
  SharedLock pools_lock(pools_mutex_);
  AllocShard& s = alloc_shard_for(key);
  WriterLock lock(s.mutex);
  auto it = s.map.find(key);
  if (it == s.map.end()) return ErrorCode::OBJECT_NOT_FOUND;
  auto& ranges = it->second.ranges;
  auto rit = std::find_if(ranges.begin(), ranges.end(),
                          [&](const std::pair<MemoryPoolId, Range>& pr) {
                            return pr.first == pool_id && pr.second.offset == range.offset &&
                                   pr.second.length == range.length;
                          });
  if (rit == ranges.end()) return ErrorCode::OBJECT_NOT_FOUND;
  auto pa = pool_allocators_.find(pool_id);
  if (pa != pool_allocators_.end()) pa->second->free(range, key);
  it->second.total_size -= std::min(it->second.total_size, range.length);
  ranges.erase(rit);
  return ErrorCode::OK;
}

void RangeAllocator::remove_pool_ranges(const ObjectKey& key, const MemoryPoolId& pool_id) {
  AllocShard& s = alloc_shard_for(key);
  WriterLock lock(s.mutex);
  auto it = s.map.find(key);
  if (it == s.map.end()) return;
  auto& ranges = it->second.ranges;
  uint64_t dropped = 0;
  ranges.erase(std::remove_if(ranges.begin(), ranges.end(),
                              [&](const std::pair<MemoryPoolId, Range>& pr) {
                                if (pr.first != pool_id) return false;
                                dropped += pr.second.length;
                                return true;
                              }),
               ranges.end());
  it->second.total_size -= std::min(it->second.total_size, dropped);
}

ErrorCode RangeAllocator::free(const ObjectKey& object_key) {
  // Lock order: pools before the allocation shard, matching get_stats
  // (verified by TSan: the reverse order forms a cycle with the stats path).
  SharedLock pools_lock(pools_mutex_);
  AllocShard& s = alloc_shard_for(object_key);
  WriterLock lock(s.mutex);
  auto it = s.map.find(object_key);
  if (it == s.map.end()) {
    LOG_DEBUG << "free of unknown object " << object_key;
    return ErrorCode::OBJECT_NOT_FOUND;
  }
  for (const auto& [pool_id, range] : it->second.ranges) {
    auto pa = pool_allocators_.find(pool_id);
    if (pa != pool_allocators_.end()) pa->second->free(range, object_key);
  }
#if defined(BTPU_POOLSAN)
  // PLANTED MUTANT — double-free class (the allocator bug poolsan's shadow
  // exists to convict): release the object's first range a SECOND time, the
  // way a racing remove/GC pair or a rollback-after-commit once could. The
  // shadow sees the extent already quarantined, CONVICTS with a replayable
  // report, and REFUSES the free — the free map (and whoever owns the bytes
  // by then) stays intact. Pinned by Poolsan.MutantDoubleFree.
  if (poolsan::mutant() == poolsan::Mutant::kDoubleFree && !it->second.ranges.empty()) {
    const auto& [mpool, mrange] = it->second.ranges.front();
    auto pa = pool_allocators_.find(mpool);
    if (pa != pool_allocators_.end()) pa->second->free(mrange, object_key);
  }
#endif
  LOG_DEBUG << "freed object " << object_key << " (" << it->second.total_size << " bytes, "
            << it->second.ranges.size() << " ranges)";
  s.map.erase(it);
  return ErrorCode::OK;
}

AllocatorStats RangeAllocator::get_stats(std::optional<StorageClass> storage_class) const {
  SharedLock pools_lock(pools_mutex_);

  AllocatorStats stats{};
  for (const auto& [id, pa] : pool_allocators_) {
    if (storage_class && pa->storage_class() != *storage_class) continue;
    const uint64_t free_bytes = pa->total_free();
    stats.total_free_bytes += free_bytes;
    stats.bytes_per_class[pa->storage_class()] += free_bytes;
  }
  // Allocation shards are folded one shared lock at a time (ascending):
  // the result is per-shard-consistent, which is all a stats snapshot over
  // a concurrently mutating allocator ever was.
  for (size_t si = 0; si < kAllocShards; ++si) {
    const AllocShard& s = alloc_shards_[si];
    SharedLock alloc_lock(s.mutex);
    for (const auto& [key, alloc] : s.map) {
      stats.total_allocated_bytes += alloc.total_size;
      stats.total_shards += alloc.ranges.size();
      ++stats.total_objects;
      for (const auto& [pool_id, range] : alloc.ranges) {
        auto pa = pool_allocators_.find(pool_id);
        if (pa != pool_allocators_.end())
          stats.allocated_per_class[pa->second->storage_class()] += range.length;
      }
    }
  }
  // Free-weighted mean fragmentation across pools (reference :215-254).
  if (stats.total_free_bytes > 0) {
    double weighted = 0.0;
    for (const auto& [id, pa] : pool_allocators_) {
      if (storage_class && pa->storage_class() != *storage_class) continue;
      const uint64_t pool_free = pa->total_free();
      if (pool_free > 0) {
        weighted += (static_cast<double>(pool_free) /
                     static_cast<double>(stats.total_free_bytes)) *
                    pa->fragmentation_ratio();
      }
    }
    stats.fragmentation_ratio = weighted;
  }
  return stats;
}

uint64_t RangeAllocator::get_free_space(StorageClass storage_class) const {
  SharedLock lock(pools_mutex_);
  uint64_t total = 0;
  for (const auto& [id, pa] : pool_allocators_) {
    if (pa->storage_class() == storage_class) total += pa->total_free();
  }
  return total;
}

uint64_t RangeAllocator::pool_used_bytes(const MemoryPoolId& pool_id) const {
  SharedLock lock(pools_mutex_);
  auto it = pool_allocators_.find(pool_id);
  if (it == pool_allocators_.end()) return 0;  // lazily unmaterialized: empty
  // Red zones and quarantined extents count as used — those bytes really
  // are unavailable to placement.
  return it->second->pool_size() - it->second->total_free();
}

// Feasibility probe mirroring select_candidate_pools' class/node filter.
// (The reference only credits requests preferring RAM_CPU — documented quirk
// at range_allocator.cpp:269-283 — which we deliberately fix.)
bool RangeAllocator::can_allocate(const AllocationRequest& request, const PoolMap& pools) const {
  if (request.data_size == 0 || request.replication_factor == 0) return false;
  const uint64_t needed = request.data_size * request.replication_factor;
  uint64_t available = 0;
  for (const auto& [id, pool] : pools) {
    if (!request.preferred_node.empty() && pool.node_id != request.preferred_node) continue;
    if (!request.preferred_classes.empty() &&
        std::find(request.preferred_classes.begin(), request.preferred_classes.end(),
                  pool.storage_class) == request.preferred_classes.end())
      continue;
    available += avail_of(id, pool);
  }
  return available >= needed;
}

void RangeAllocator::forget_pool(const MemoryPoolId& pool_id) {
  WriterLock lock(pools_mutex_);
  pool_allocators_.erase(pool_id);
}

ErrorCode RangeAllocator::readopt_pool_ranges(const MemoryPool& pool,
                                              const std::vector<Range>& ranges) {
  BTPU_RETURN_IF_ERROR(ensure_pool_allocator(pool));
  SharedLock lock(pools_mutex_);
  auto it = pool_allocators_.find(pool.id);
  if (it == pool_allocators_.end()) return ErrorCode::MEMORY_POOL_NOT_FOUND;
  std::vector<Range> carved;
  for (const Range& range : ranges) {
    if (!it->second->allocate_at(range)) {
      for (const Range& c : carved) it->second->free(c);
      return ErrorCode::ALLOCATION_FAILED;
    }
    carved.push_back(range);
  }
  return ErrorCode::OK;
}

std::unique_ptr<IAllocator> AllocatorFactory::create(Strategy strategy) {
  switch (strategy) {
    case Strategy::RANGE_BASED:
      return create_range_based();
    default:
      LOG_ERROR << "unsupported allocator strategy";
      return nullptr;
  }
}

std::unique_ptr<IAllocator> AllocatorFactory::create_range_based() {
  return std::make_unique<RangeAllocator>();
}

}  // namespace btpu::alloc
