#include "backend_base.h"

#include "btpu/common/log.h"

namespace btpu::storage {

ErrorCode OffsetBackendBase::init_allocator() {
  if (config_.capacity == 0) return ErrorCode::INVALID_CONFIGURATION;
  // The pool allocator needs a valid descriptor; offsets are all we use here,
  // so feed it a synthetic local descriptor.
  MemoryPool pool;
  pool.id = config_.pool_id;
  pool.node_id = config_.node_id;
  pool.size = config_.capacity;
  pool.storage_class = config_.storage_class;
  pool.remote = {TransportKind::LOCAL, "backend:" + config_.pool_id, 0, "", "", "", 0};
  try {
    allocator_ = std::make_unique<alloc::PoolAllocator>(pool);
  } catch (const std::exception& e) {
    LOG_ERROR << "backend " << config_.pool_id << ": " << e.what();
    return ErrorCode::INVALID_CONFIGURATION;
  }
  return ErrorCode::OK;
}

void OffsetBackendBase::sweep_expired_locked() {
  const auto now = std::chrono::steady_clock::now();
  for (auto it = reservations_.begin(); it != reservations_.end();) {
    if (it->second.expires_at <= now) {
      LOG_DEBUG << "backend " << config_.pool_id << ": reservation " << it->first
                << " expired, reclaiming " << it->second.size << " bytes";
      allocator_->free({it->second.offset, it->second.size});
      it = reservations_.erase(it);
    } else {
      ++it;
    }
  }
}

Result<ReservationToken> OffsetBackendBase::reserve_shard(uint64_t size) {
  if (!allocator_) return ErrorCode::INVALID_STATE;
  if (size == 0) return ErrorCode::INVALID_PARAMETERS;
  MutexLock lock(lifecycle_mutex_);
  sweep_expired_locked();
  auto range = allocator_->allocate(size);
  if (!range) return ErrorCode::INSUFFICIENT_SPACE;
  ReservationToken token;
  token.id = next_token_++;
  token.offset = range->offset;
  token.size = range->length;
  token.expires_at = std::chrono::steady_clock::now() +
                     std::chrono::milliseconds(config_.reservation_ttl_ms);
  reservations_[token.id] = token;
  ++total_reserves_;
  return token;
}

ErrorCode OffsetBackendBase::commit_shard(const ReservationToken& token) {
  MutexLock lock(lifecycle_mutex_);
  auto it = reservations_.find(token.id);
  if (it == reservations_.end()) return ErrorCode::INVALID_PARAMETERS;
  if (it->second.expired()) {
    // Expired-but-not-yet-swept: the space is still reserved, so reclaim it
    // and refuse the commit (reference semantics: expired tokens are invalid).
    allocator_->free({it->second.offset, it->second.size});
    reservations_.erase(it);
    return ErrorCode::OPERATION_TIMEOUT;
  }
  committed_[it->second.offset] = it->second.size;
  reservations_.erase(it);
  ++total_commits_;
  return ErrorCode::OK;
}

ErrorCode OffsetBackendBase::abort_shard(const ReservationToken& token) {
  MutexLock lock(lifecycle_mutex_);
  auto it = reservations_.find(token.id);
  if (it == reservations_.end()) return ErrorCode::INVALID_PARAMETERS;
  allocator_->free({it->second.offset, it->second.size});
  reservations_.erase(it);
  ++total_aborts_;
  return ErrorCode::OK;
}

ErrorCode OffsetBackendBase::free_shard(uint64_t offset, uint64_t size) {
  MutexLock lock(lifecycle_mutex_);
  auto it = committed_.find(offset);
  if (it == committed_.end() || it->second != size) return ErrorCode::INVALID_PARAMETERS;
  committed_.erase(it);
  allocator_->free({offset, size});
  ++total_frees_;
  return ErrorCode::OK;
}

uint64_t OffsetBackendBase::used() const {
  MutexLock lock(lifecycle_mutex_);
  uint64_t total = 0;
  for (const auto& [off, size] : committed_) total += size;
  for (const auto& [id, token] : reservations_) total += token.size;
  return total;
}

StorageStats OffsetBackendBase::stats() const {
  MutexLock lock(lifecycle_mutex_);
  StorageStats s;
  s.capacity = config_.capacity;
  for (const auto& [off, size] : committed_) s.used += size;
  for (const auto& [id, token] : reservations_) s.reserved += token.size;
  s.shard_count = committed_.size();
  s.total_reserves = total_reserves_;
  s.total_commits = total_commits_;
  s.total_aborts = total_aborts_;
  s.total_frees = total_frees_;
  s.fragmentation = allocator_ ? allocator_->fragmentation_ratio() : 0.0;
  return s;
}

}  // namespace btpu::storage
