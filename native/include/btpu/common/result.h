// Result<T>: value-or-ErrorCode, the return convention across the framework.
//
// Parity target: reference include/blackbird/common/types.h:31-49 exposes
// Result<T> = std::variant<T, ErrorCode> with free is_ok/get_value/get_error.
// We keep those free functions for API parity but implement Result as a real
// class with ergonomics (ok(), value(), error(), value_or, map) — and we keep
// the variant layout so wire serialization of batch results matches the
// one-of-two encoding the reference uses (types.h:392-ish batch responses).
#pragma once

#include <utility>
#include <variant>

#include "btpu/common/error.h"

namespace btpu {

template <typename T>
class BTPU_NODISCARD Result {
 public:
  // Default state is an error so a forgotten assignment is never a fake success
  // (needed by wire decode, which value-initializes before filling in).
  Result() : v_(ErrorCode::INTERNAL_ERROR) {}
  Result(T value) : v_(std::move(value)) {}                      // NOLINT(implicit)
  Result(ErrorCode code) : v_(code) {}                           // NOLINT(implicit)

  bool ok() const noexcept { return std::holds_alternative<T>(v_); }
  explicit operator bool() const noexcept { return ok(); }

  T& value() & { return std::get<T>(v_); }
  const T& value() const& { return std::get<T>(v_); }
  T&& value() && { return std::get<T>(std::move(v_)); }

  ErrorCode error() const noexcept {
    return ok() ? ErrorCode::OK : std::get<ErrorCode>(v_);
  }

  T value_or(T fallback) const& { return ok() ? value() : std::move(fallback); }

  template <typename F>
  auto map(F&& f) const -> Result<decltype(f(std::declval<const T&>()))> {
    if (!ok()) return error();
    return f(value());
  }

  const std::variant<T, ErrorCode>& raw() const noexcept { return v_; }

 private:
  std::variant<T, ErrorCode> v_;
};

// Free-function surface matching the reference (types.h:37-49).
template <typename T>
bool is_ok(const Result<T>& r) { return r.ok(); }
template <typename T>
T get_value(const Result<T>& r) { return r.value(); }
template <typename T>
ErrorCode get_error(const Result<T>& r) { return r.error(); }

#define BTPU_RETURN_IF_ERROR(expr)                       \
  do {                                                   \
    ::btpu::ErrorCode _btpu_ec = (expr);                 \
    if (_btpu_ec != ::btpu::ErrorCode::OK) return _btpu_ec; \
  } while (0)

#define BTPU_CONCAT_INNER(a, b) a##b
#define BTPU_CONCAT(a, b) BTPU_CONCAT_INNER(a, b)
#define BTPU_ASSIGN_OR_RETURN(lhs, expr)                                     \
  auto BTPU_CONCAT(_btpu_res_, __LINE__) = (expr);                           \
  if (!BTPU_CONCAT(_btpu_res_, __LINE__).ok())                               \
    return BTPU_CONCAT(_btpu_res_, __LINE__).error();                        \
  lhs = std::move(BTPU_CONCAT(_btpu_res_, __LINE__)).value()

}  // namespace btpu
