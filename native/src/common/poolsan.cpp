// btpu::poolsan implementation — see poolsan.h for the model and
// docs/CORRECTNESS.md §12 for the report-reading runbook.
#include "btpu/common/poolsan.h"

#include <atomic>
#include <cstring>
#include <deque>
#include <map>
#include <unordered_map>

#include "btpu/common/env.h"
#include "btpu/common/flight_recorder.h"
#include "btpu/common/log.h"
#include "btpu/common/thread_annotations.h"
#include "btpu/common/trace.h"

#if defined(BTPU_POOLSAN) && defined(__SANITIZE_ADDRESS__) && \
    __has_include(<sanitizer/asan_interface.h>)
#include <sanitizer/asan_interface.h>
#define BTPU_POOLSAN_ASAN 1
#endif

namespace btpu::poolsan {

namespace {

// Dead-byte patterns (gcc-only trees; asan trees poison instead).
constexpr uint8_t kRedzonePattern = 0xBD;
constexpr uint8_t kQuarantinePattern = 0xDE;

// Monotonic conviction counters + live gauges. ordering: relaxed throughout
// — independent monotonic stats/gauges, folded on read with no cross-field
// invariant (same policy as the robustness counters).
std::atomic<uint64_t> g_convictions{0};
std::atomic<uint64_t> g_stale_generation{0};
std::atomic<uint64_t> g_redzone_smash{0};
std::atomic<uint64_t> g_double_free{0};
std::atomic<uint64_t> g_quarantine_bytes{0};
std::atomic<uint64_t> g_quarantined_extents{0};
std::atomic<uint64_t> g_pools_tracked{0};
std::atomic<int> g_disarm_depth{0};

void count_fault(Fault f) {
  // ordering: relaxed — monotonic stat counters (this whole function).
  g_convictions.fetch_add(1, std::memory_order_relaxed);
  switch (f) {
    case Fault::kStaleGeneration:
    case Fault::kQuarantinedAccess:
      g_stale_generation.fetch_add(1, std::memory_order_relaxed);
      break;
    case Fault::kRedzoneSmash:
    case Fault::kQuarantineSmash:
      // ordering: relaxed — monotonic stat counters (whole switch).
      g_redzone_smash.fetch_add(1, std::memory_order_relaxed);
      break;
    case Fault::kDoubleFree:
      g_double_free.fetch_add(1, std::memory_order_relaxed);
      break;
    default:
      break;
  }
}

// One replayable report per conviction: everything needed to reproduce the
// access (pool, fault class, extent window, both generations, state) in one
// log line, plus a flight-recorder event stitched to the requesting op.
void convict(Fault f, const std::string& pool, Access access, uint64_t offset, uint64_t len,
             uint64_t placement_gen, uint64_t extent_gen, const char* state,
             std::string_view who, uint64_t trace_id) {
  count_fault(f);
  LOG_ERROR << "poolsan: CONVICTED " << fault_name(f) << " pool=" << pool << " "
            << (access == Access::kWrite ? "write" : "read") << " [" << offset << ","
            << offset + len << ") placement_gen=" << placement_gen
            << " extent_gen=" << extent_gen << " state=" << state
            << (who.empty() ? "" : " who=") << who << " trace_id=" << trace_id
            << " (replay: same op against the same shadow state; see "
               "docs/CORRECTNESS.md section 12)";
  flight::record_at(trace::now_ns(), flight::Ev::kPoolsanConviction,
                    static_cast<uint64_t>(f), offset, trace_id);
}

void poison_bytes(uint8_t* p, uint64_t n, uint8_t pattern) {
  if (p == nullptr || n == 0) return;
#if defined(BTPU_POOLSAN_ASAN)
  (void)pattern;
  ASAN_POISON_MEMORY_REGION(p, n);
#else
  std::memset(p, pattern, n);
#endif
}

void unpoison_bytes(uint8_t* p, uint64_t n) {
  if (p == nullptr || n == 0) return;
#if defined(BTPU_POOLSAN_ASAN)
  ASAN_UNPOISON_MEMORY_REGION(p, n);
#endif
}

// Canary verification (gcc trees only — under asan the poisoned bytes trap
// the offender at the faulting instruction, which is strictly better).
bool canary_intact(const uint8_t* p, uint64_t n, uint8_t pattern) {
#if defined(BTPU_POOLSAN_ASAN)
  (void)p;
  (void)n;
  (void)pattern;
  return true;
#else
  if (p == nullptr) return true;
  for (uint64_t i = 0; i < n; ++i)
    if (p[i] != pattern) return false;
  return true;
#endif
}

}  // namespace

const char* fault_name(Fault f) noexcept {
  switch (f) {
    case Fault::kStaleGeneration: return "stale_generation";
    case Fault::kQuarantinedAccess: return "quarantined_access";
    case Fault::kRedzoneAccess: return "redzone_access";
    case Fault::kOverrun: return "extent_overrun";
    case Fault::kRedzoneSmash: return "redzone_smash";
    case Fault::kQuarantineSmash: return "quarantine_smash";
    case Fault::kDoubleFree: return "double_free";
  }
  return "unknown";
}

bool compiled_in() noexcept {
#if defined(BTPU_POOLSAN)
  return true;
#else
  return false;
#endif
}

bool armed() noexcept {
#if defined(BTPU_POOLSAN)
  // ordering: relaxed — the disarm depth is a test-harness toggle flipped
  // between serial tests, not a synchronization point.
  if (g_disarm_depth.load(std::memory_order_relaxed) > 0) return false;
  return env_bool("BTPU_POOLSAN", true);
#else
  return false;
#endif
}

Counters counters() noexcept {
  Counters c;
  // ordering: relaxed — independent monotonic counters/gauges, folded on read.
  c.convictions = g_convictions.load(std::memory_order_relaxed);
  c.stale_generation = g_stale_generation.load(std::memory_order_relaxed);
  c.redzone_smash = g_redzone_smash.load(std::memory_order_relaxed);
  c.double_free = g_double_free.load(std::memory_order_relaxed);
  c.quarantine_bytes = g_quarantine_bytes.load(std::memory_order_relaxed);
  c.quarantined_extents = g_quarantined_extents.load(std::memory_order_relaxed);
  c.pools_tracked = g_pools_tracked.load(std::memory_order_relaxed);
  return c;
}

void reset_counters_for_test() noexcept {
  // ordering: relaxed — test-harness reset between serial tests.
  g_convictions.store(0, std::memory_order_relaxed);
  g_stale_generation.store(0, std::memory_order_relaxed);
  g_redzone_smash.store(0, std::memory_order_relaxed);
  g_double_free.store(0, std::memory_order_relaxed);
}

Mutant mutant() noexcept {
#if defined(BTPU_POOLSAN)
  const char* m = env_str("BTPU_POOLSAN_MUTANT");
  if (m == nullptr || *m == '\0') return Mutant::kNone;
  if (std::strcmp(m, "overrun") == 0) return Mutant::kOverrun;
  if (std::strcmp(m, "stale_read") == 0) return Mutant::kStaleRead;
  if (std::strcmp(m, "double_free") == 0) return Mutant::kDoubleFree;
  return Mutant::kNone;
#else
  return Mutant::kNone;
#endif
}

ScopedDisarm::ScopedDisarm() {
  // ordering: relaxed — see armed().
  g_disarm_depth.fetch_add(1, std::memory_order_relaxed);
}
ScopedDisarm::~ScopedDisarm() {
  // ordering: relaxed — see armed().
  g_disarm_depth.fetch_sub(1, std::memory_order_relaxed);
}

// ---- shadow state ----------------------------------------------------------

struct Shadow::Impl {
  mutable Mutex mutex;
  struct Extent {
    uint64_t len{0};
    uint64_t rz{0};
    uint64_t gen{0};
    bool quarantined{false};
    // Byte-level effect deferred by an open AccessPin (poolsan.h): the
    // quarantine fill / red-zone arm has NOT been written yet, so the
    // matching canary check must be skipped until the flush applies it.
    bool fill_pending{false};
    bool rz_pending{false};
  };
  // offset -> extent; the authoritative map every resolve consults.
  std::map<uint64_t, Extent> extents BTPU_GUARDED_BY(mutex);
  // Open AccessPins on this pool; while nonzero, on_free/on_alloc defer
  // their poison/pattern writes (state flips stay immediate). The dirty
  // flag makes the last unpin's flush O(extents) only when needed.
  uint64_t pins BTPU_GUARDED_BY(mutex){0};
  bool deferred_dirty BTPU_GUARDED_BY(mutex){false};
  std::deque<uint64_t> quarantine BTPU_GUARDED_BY(mutex);  // FIFO of offsets
  uint64_t q_usable BTPU_GUARDED_BY(mutex){0};
  uint64_t gen_counter BTPU_GUARDED_BY(mutex){0};
  // Host binding: set only by the process that owns the region's memory
  // (bind_host). Guarded by the same mutex so canary writes can never race
  // an unbind's unpoison-and-detach.
  uint8_t* host BTPU_GUARDED_BY(mutex){nullptr};
  uint64_t host_len BTPU_GUARDED_BY(mutex){0};
  uint64_t q_budget{0};
  uint64_t rz_default{0};

  // Finds the extent containing `offset` (usable bytes OR red zone).
  // Returns extents.end() when offset falls in untracked space.
  std::map<uint64_t, Extent>::iterator containing(uint64_t offset) BTPU_REQUIRES(mutex) {
    auto it = extents.upper_bound(offset);
    if (it == extents.begin()) return extents.end();
    --it;
    const uint64_t span = it->second.len + it->second.rz;
    if (offset >= it->first + span) return extents.end();
    return it;
  }

  // Applies every deferred byte-level effect once the last pin drops. An
  // extent that was freed AND released (or the whole pool unbound) while
  // pinned simply lost its pending flag with the state that carried it —
  // the flush only writes what the CURRENT state still calls for.
  void flush_deferred() BTPU_REQUIRES(mutex) {
    if (!deferred_dirty) return;
    deferred_dirty = false;
    for (auto& [off, e] : extents) {
      if (host != nullptr) {
        if (e.fill_pending && e.quarantined)
          poison_bytes(host + off, e.len, kQuarantinePattern);
        if (e.rz_pending && !e.quarantined && e.rz)
          poison_bytes(host + off + e.len, e.rz, kRedzonePattern);
      }
      e.fill_pending = false;
      e.rz_pending = false;
    }
  }

  // Pops quarantine FIFO entries until `q_usable <= budget`, verifying
  // quarantine canaries on the way out. Appends released full spans.
  void pop_quarantine_to(uint64_t budget, const std::string& pool,
                         std::vector<ReleasedSpan>& out) BTPU_REQUIRES(mutex) {
    while (q_usable > budget && !quarantine.empty()) {
      const uint64_t off = quarantine.front();
      quarantine.pop_front();
      auto it = extents.find(off);
      if (it == extents.end() || !it->second.quarantined) continue;  // defensive
      const Extent e = it->second;
      if (host != nullptr) {
        // A fill deferred by a pin was never written — nothing to verify.
        if (!e.fill_pending && !canary_intact(host + off, e.len, kQuarantinePattern)) {
          convict(Fault::kQuarantineSmash, pool, Access::kWrite, off, e.len, 0, e.gen,
                  "quarantined", /*who=*/{}, /*trace_id=*/0);
        }
        unpoison_bytes(host + off, e.len + e.rz);
      }
      q_usable -= e.len;
      // ordering: relaxed — live gauges.
      g_quarantine_bytes.fetch_sub(e.len, std::memory_order_relaxed);
      g_quarantined_extents.fetch_sub(1, std::memory_order_relaxed);
      out.push_back({off, e.len + e.rz});
      extents.erase(it);
    }
  }
};

// ---- registry --------------------------------------------------------------

namespace {

struct Registry {
  SharedMutex mutex;
  std::unordered_map<std::string, std::weak_ptr<Shadow>> by_name BTPU_GUARDED_BY(mutex);
  std::unordered_map<uintptr_t, std::weak_ptr<Shadow>> by_base BTPU_GUARDED_BY(mutex);
  // Host bindings declared before the shadow exists (worker registers its
  // regions before the keystone materializes the pool's allocator).
  struct Binding {
    uintptr_t base{0};
    uint64_t len{0};
  };
  std::unordered_map<std::string, Binding> bindings BTPU_GUARDED_BY(mutex);
  // alias -> pool id (SHM segment names; see alias_pool).
  std::unordered_map<std::string, std::string> aliases BTPU_GUARDED_BY(mutex);

  static Registry& instance() {
    static Registry r;
    return r;
  }
};

// The serve-path shadow lookup: host base address first (worker side), then
// the region tag as a pool id or alias. Shared by check_access and the
// AccessPin surface so both resolve the SAME shadow for a given region.
ShadowPtr lookup_shadow(const void* base, const char* tag) {
  ShadowPtr shadow;
  auto& reg = Registry::instance();
  SharedLock lock(reg.mutex);
  auto it = reg.by_base.find(reinterpret_cast<uintptr_t>(base));
  if (it != reg.by_base.end()) shadow = it->second.lock();
  if (!shadow && tag != nullptr) {
    auto nit = reg.by_name.find(tag);
    if (nit == reg.by_name.end()) {
      auto ait = reg.aliases.find(tag);
      if (ait != reg.aliases.end()) nit = reg.by_name.find(ait->second);
    }
    if (nit != reg.by_name.end()) shadow = nit->second.lock();
  }
  return shadow;
}

// Attaches a host binding to a live shadow (registry lock held by caller;
// takes the shadow's leaf mutex). Rejects size mismatches — a colliding
// pool id must degrade to untracked-by-base, never mis-poison.
void attach_host_locked(const ShadowPtr& shadow, uint8_t* base, uint64_t len) {
  MutexLock lock(shadow->impl_->mutex);
  if (len != shadow->size()) {
    LOG_WARN << "poolsan: host binding for pool " << shadow->pool_id() << " is " << len
             << " bytes but the shadow tracks " << shadow->size() << " — not binding";
    return;
  }
  shadow->impl_->host = base;
  shadow->impl_->host_len = len;
}

}  // namespace

Shadow::Shadow(std::string pool_id, uint64_t size)
    : impl_(std::make_unique<Impl>()), pool_id_(std::move(pool_id)), size_(size) {
  impl_->q_budget = env_u64("BTPU_POOLSAN_QUARANTINE_BYTES", 1ull << 20);
  impl_->rz_default = env_u64("BTPU_POOLSAN_REDZONE", 64);
  // ordering: relaxed — live gauge.
  g_pools_tracked.fetch_add(1, std::memory_order_relaxed);
}

Shadow::~Shadow() {
  // Unpoison everything this shadow ever poisoned: the region's memory can
  // outlive the shadow (keystone restart, forget_pool), and leftover asan
  // poison on recycled heap would convict innocent future allocations.
  uintptr_t bound = 0;
  {
    MutexLock lock(impl_->mutex);
    if (impl_->host != nullptr) {
      bound = reinterpret_cast<uintptr_t>(impl_->host);
      for (const auto& [off, e] : impl_->extents) {
        if (e.quarantined) unpoison_bytes(impl_->host + off, e.len + e.rz);
        else if (e.rz) unpoison_bytes(impl_->host + off + e.len, e.rz);
      }
      impl_->host = nullptr;
    }
    // ordering: relaxed — live gauges.
    g_quarantine_bytes.fetch_sub(impl_->q_usable, std::memory_order_relaxed);
    g_quarantined_extents.fetch_sub(impl_->quarantine.size(), std::memory_order_relaxed);
  }
  auto& reg = Registry::instance();
  WriterLock lock(reg.mutex);
  if (bound != 0) {
    auto it = reg.by_base.find(bound);
    if (it != reg.by_base.end() && it->second.expired()) reg.by_base.erase(it);
  }
  // ordering: relaxed — live gauge.
  g_pools_tracked.fetch_sub(1, std::memory_order_relaxed);
}

uint64_t Shadow::redzone_bytes() const noexcept { return impl_->rz_default; }

uint64_t Shadow::on_alloc(uint64_t offset, uint64_t len, uint64_t rz_len) {
  MutexLock lock(impl_->mutex);
  const uint64_t gen = ++impl_->gen_counter;
  Impl::Extent& e = impl_->extents[offset] = Impl::Extent{len, rz_len, gen, false};
  if (impl_->host != nullptr) {
    // Fresh extent: its bytes may have been poisoned as part of an earlier
    // quarantined span — make them writable again, then arm the red zone.
    // Arming is a byte-level effect, so an open pin defers it: this carve
    // may reuse space a pinned copy is still streaming out of.
    unpoison_bytes(impl_->host + offset, len);
    if (rz_len) {
      if (impl_->pins > 0) {
        e.rz_pending = true;
        impl_->deferred_dirty = true;
      } else {
        poison_bytes(impl_->host + offset + len, rz_len, kRedzonePattern);
      }
    }
  }
  return gen;
}

void Shadow::on_adopt(uint64_t offset, uint64_t len) {
  MutexLock lock(impl_->mutex);
  // Replayed placements predate this shadow: generation 0 = wildcard (any
  // placement stamp validates), no red zone assumed.
  impl_->extents[offset] = Impl::Extent{len, 0, 0, false};
}

FreeOutcome Shadow::on_free(uint64_t offset, uint64_t len, std::string_view who) {
  FreeOutcome out;
  MutexLock lock(impl_->mutex);
  auto it = impl_->extents.find(offset);
  if (it == impl_->extents.end()) {
    // Untracked start: a pre-arm carve frees verbatim, but a range that
    // OVERLAPS tracked space is a wild free — refusing it is what keeps
    // the neighbor extent's bytes (and the free map) intact.
    auto over = impl_->containing(offset);
    if (over == impl_->extents.end()) {
      auto next = impl_->extents.lower_bound(offset);
      if (next != impl_->extents.end() && next->first < offset + len)
        over = next;
    }
    if (over != impl_->extents.end()) {
      convict(Fault::kDoubleFree, pool_id_, Access::kWrite, offset, len, 0,
              over->second.gen, over->second.quarantined ? "quarantined" : "allocated",
              who, 0);
      out.refused = true;
    }
    return out;  // untracked: caller frees verbatim
  }
  Impl::Extent& e = it->second;
  if (e.quarantined) {
    convict(Fault::kDoubleFree, pool_id_, Access::kWrite, offset, len, 0, e.gen,
            "quarantined", who, 0);
    out.refused = true;
    return out;
  }
  if (len != e.len) {
    convict(Fault::kDoubleFree, pool_id_, Access::kWrite, offset, len, 0, e.gen,
            "allocated (length mismatch)", who, 0);
    out.refused = true;
    return out;
  }
  // A red zone whose arming a pin deferred was never written: no canary to
  // verify (and none to smash).
  if (impl_->host != nullptr && e.rz && !e.rz_pending &&
      !canary_intact(impl_->host + offset + e.len, e.rz, kRedzonePattern)) {
    convict(Fault::kRedzoneSmash, pool_id_, Access::kWrite, offset, e.len, 0, e.gen,
            "allocated", who, 0);
    out.smashed = true;  // reported; the free itself still proceeds
  }
  // The state flip is IMMEDIATE even under a pin — the very next resolve
  // convicts this extent — but the poison/pattern fill waits for the last
  // pin to drop: a copy the pool already vouched for may still be reading
  // these bytes (the sanctioned RMA race; poolsan.h "access pins").
  e.quarantined = true;
  if (impl_->host != nullptr) {
    if (impl_->pins > 0) {
      e.fill_pending = true;
      impl_->deferred_dirty = true;
    } else {
      poison_bytes(impl_->host + offset, e.len, kQuarantinePattern);
    }
  }
  impl_->quarantine.push_back(offset);
  impl_->q_usable += e.len;
  // ordering: relaxed — live gauges.
  g_quarantine_bytes.fetch_add(e.len, std::memory_order_relaxed);
  g_quarantined_extents.fetch_add(1, std::memory_order_relaxed);
  out.quarantined = true;
  // Budget re-read per free (ctor value as fallback): frees are control-
  // plane rate, and a live dial lets tests/operators shrink the hold
  // without rebuilding pools.
  impl_->pop_quarantine_to(env_u64("BTPU_POOLSAN_QUARANTINE_BYTES", impl_->q_budget),
                           pool_id_, out.release);
  return out;
}

std::vector<ReleasedSpan> Shadow::drain_all() {
  std::vector<ReleasedSpan> out;
  MutexLock lock(impl_->mutex);
  impl_->pop_quarantine_to(0, pool_id_, out);
  return out;
}

uint64_t Shadow::gen_at(uint64_t offset) const noexcept {
  MutexLock lock(impl_->mutex);
  auto it = impl_->extents.find(offset);
  return it != impl_->extents.end() && !it->second.quarantined ? it->second.gen : 0;
}

uint64_t Shadow::quarantined_usable_bytes() const noexcept {
  MutexLock lock(impl_->mutex);
  return impl_->q_usable;
}

uint64_t Shadow::quarantined_span_bytes() const noexcept {
  MutexLock lock(impl_->mutex);
  uint64_t total = 0;
  for (const uint64_t off : impl_->quarantine) {
    auto it = impl_->extents.find(off);
    if (it != impl_->extents.end() && it->second.quarantined)
      total += it->second.len + it->second.rz;
  }
  return total;
}

// ---- registry surface ------------------------------------------------------

ShadowPtr create_shadow(const std::string& pool_id, uint64_t size) {
  if (!armed() || size == 0) return nullptr;
  auto shadow = std::make_shared<Shadow>(pool_id, size);
  auto& reg = Registry::instance();
  WriterLock lock(reg.mutex);
  reg.by_name[pool_id] = shadow;
  auto bit = reg.bindings.find(pool_id);
  if (bit != reg.bindings.end()) {
    attach_host_locked(shadow, reinterpret_cast<uint8_t*>(bit->second.base),
                       bit->second.len);
    reg.by_base[bit->second.base] = shadow;
  }
  return shadow;
}

void bind_host(const std::string& pool_id, void* base, uint64_t len) {
  if (!armed() || base == nullptr || len == 0) return;
  auto& reg = Registry::instance();
  WriterLock lock(reg.mutex);
  // A re-bind (worker re-initialized the pool without an intervening
  // unbind) must retire the PREVIOUS base's index entry: a later heap
  // placement at that address would otherwise resolve a foreign shadow.
  if (auto prev = reg.bindings.find(pool_id);
      prev != reg.bindings.end() && prev->second.base != reinterpret_cast<uintptr_t>(base))
    reg.by_base.erase(prev->second.base);
  reg.bindings[pool_id] = {reinterpret_cast<uintptr_t>(base), len};
  auto it = reg.by_name.find(pool_id);
  if (it != reg.by_name.end()) {
    if (ShadowPtr shadow = it->second.lock()) {
      attach_host_locked(shadow, static_cast<uint8_t*>(base), len);
      reg.by_base[reinterpret_cast<uintptr_t>(base)] = shadow;
    }
  }
}

void unbind_host(const std::string& pool_id) {
  auto& reg = Registry::instance();
  WriterLock lock(reg.mutex);
  auto bit = reg.bindings.find(pool_id);
  if (bit == reg.bindings.end()) return;
  const uintptr_t base = bit->second.base;
  reg.bindings.erase(bit);
  auto nit = reg.by_name.find(pool_id);
  if (nit != reg.by_name.end()) {
    if (ShadowPtr shadow = nit->second.lock()) {
      MutexLock lock2(shadow->impl_->mutex);
      if (shadow->impl_->host != nullptr) {
        // The region's memory is about to be freed by its owner: unpoison
        // everything so recycled heap starts clean, then detach — no byte
        // of it may be touched through this shadow again.
        for (const auto& [off, e] : shadow->impl_->extents) {
          if (e.quarantined) unpoison_bytes(shadow->impl_->host + off, e.len + e.rz);
          else if (e.rz) unpoison_bytes(shadow->impl_->host + off + e.len, e.rz);
        }
        shadow->impl_->host = nullptr;
        shadow->impl_->host_len = 0;
      }
    }
  }
  reg.by_base.erase(base);
}

void alias_pool(const std::string& alias, const std::string& pool_id) {
  if (!armed() || alias.empty() || alias == pool_id) return;
  auto& reg = Registry::instance();
  WriterLock lock(reg.mutex);
  reg.aliases[alias] = pool_id;
}

ErrorCode check_access(const void* base, const char* tag, uint64_t region_len,
                       uint64_t offset, uint64_t len, uint64_t gen, Access access,
                       uint64_t trace_id) noexcept {
  ShadowPtr shadow = lookup_shadow(base, tag);
  if (!shadow) return ErrorCode::OK;  // untracked region: bounds proof only
  // A shadow whose geometry disagrees with the caller's region is a pool-id
  // collision (two clusters in one process) — degrade to untracked rather
  // than convict against the wrong extent map.
  if (shadow->size() != region_len) return ErrorCode::OK;
  MutexLock lock(shadow->impl_->mutex);
  auto it = shadow->impl_->containing(offset);
  if (it == shadow->impl_->extents.end()) {
    // Untracked space. A placement CARRYING a generation believed an extent
    // lived here — it was freed and drained: stale by definition.
    if (gen != 0) {
      convict(Fault::kStaleGeneration, shadow->pool_id(), access, offset, len, gen, 0,
              "free", /*who=*/{}, trace_id);
      return ErrorCode::STALE_EXTENT;
    }
    // Unstamped access starting in free space but RUNNING INTO a tracked
    // extent is the neighbor-corruption shape from the other side (the red
    // zone only guards the left neighbor, and may have been dropped under
    // pressure) — convict it like on_free convicts the wild free.
    auto next = shadow->impl_->extents.lower_bound(offset);
    if (next != shadow->impl_->extents.end() && len > next->first - offset) {
      convict(Fault::kOverrun, shadow->pool_id(), access, offset, len, gen,
              next->second.gen, next->second.quarantined ? "quarantined" : "allocated",
              /*who=*/{}, trace_id);
      return ErrorCode::MEMORY_ACCESS_ERROR;
    }
    return ErrorCode::OK;
  }
  const auto& e = it->second;
  const uint64_t ext_off = it->first;
  if (offset >= ext_off + e.len) {
    // Inside the extent's red zone.
    convict(Fault::kRedzoneAccess, shadow->pool_id(), access, offset, len, gen, e.gen,
            e.quarantined ? "quarantined" : "redzone", /*who=*/{}, trace_id);
    return ErrorCode::MEMORY_ACCESS_ERROR;
  }
  if (e.quarantined) {
    convict(Fault::kQuarantinedAccess, shadow->pool_id(), access, offset, len, gen, e.gen,
            "quarantined", /*who=*/{}, trace_id);
    return ErrorCode::STALE_EXTENT;
  }
  if (offset + len > ext_off + e.len) {
    convict(Fault::kOverrun, shadow->pool_id(), access, offset, len, gen, e.gen,
            "allocated", /*who=*/{}, trace_id);
    return ErrorCode::MEMORY_ACCESS_ERROR;
  }
  if (gen != 0 && e.gen != 0 && gen != e.gen) {
    convict(Fault::kStaleGeneration, shadow->pool_id(), access, offset, len, gen, e.gen,
            "allocated", /*who=*/{}, trace_id);
    return ErrorCode::STALE_EXTENT;
  }
  return ErrorCode::OK;
}

namespace internal {

ShadowPtr pin_shadow(const void* base, const char* tag, uint64_t region_len) noexcept {
  if (!armed()) return nullptr;
  ShadowPtr shadow = lookup_shadow(base, tag);
  // Same degrade rule as check_access: a geometry mismatch is a pool-id
  // collision — pinning the wrong shadow would defer a stranger's poison.
  if (!shadow || shadow->size() != region_len) return nullptr;
  MutexLock lock(shadow->impl_->mutex);
  ++shadow->impl_->pins;
  return shadow;
}

void unpin_shadow(const ShadowPtr& shadow) noexcept {
  if (!shadow) return;
  MutexLock lock(shadow->impl_->mutex);
  if (--shadow->impl_->pins == 0) shadow->impl_->flush_deferred();
}

}  // namespace internal

uint64_t scrub_canaries() {
#if defined(BTPU_POOLSAN_ASAN)
  return 0;  // asan traps at the faulting instruction; nothing to sweep
#else
  std::vector<ShadowPtr> shadows;
  {
    auto& reg = Registry::instance();
    SharedLock lock(reg.mutex);
    shadows.reserve(reg.by_name.size());
    for (const auto& [name, weak] : reg.by_name)
      if (ShadowPtr s = weak.lock()) shadows.push_back(std::move(s));
  }
  uint64_t smashes = 0;
  for (const auto& shadow : shadows) {
    MutexLock lock(shadow->impl_->mutex);
    if (shadow->impl_->host == nullptr) continue;
    for (auto& [off, e] : shadow->impl_->extents) {
      // Pending = deferred by an open pin, never written: nothing to verify.
      if (e.quarantined) {
        if (!e.fill_pending &&
            !canary_intact(shadow->impl_->host + off, e.len, kQuarantinePattern)) {
          convict(Fault::kQuarantineSmash, shadow->pool_id(), Access::kWrite, off, e.len,
                  0, e.gen, "quarantined", "scrub", 0);
          ++smashes;
          // Re-arm so one smash is one report per scrub epoch, not per pass.
          poison_bytes(shadow->impl_->host + off, e.len, kQuarantinePattern);
        }
      } else if (e.rz && !e.rz_pending &&
                 !canary_intact(shadow->impl_->host + off + e.len, e.rz, kRedzonePattern)) {
        convict(Fault::kRedzoneSmash, shadow->pool_id(), Access::kWrite, off, e.len, 0,
                e.gen, "allocated", "scrub", 0);
        ++smashes;
        poison_bytes(shadow->impl_->host + off + e.len, e.rz, kRedzonePattern);
      }
    }
  }
  return smashes;
#endif
}

}  // namespace btpu::poolsan
