// Failure handling: dead-worker cleanup, replica re-replication, and
// erasure-coded reconstruction.
#include "btpu/keystone/keystone.h"

#include "keystone_internal.h"

#include <algorithm>
#include <optional>
#include <unordered_set>

#include "btpu/common/log.h"
#include "btpu/common/trace.h"
#include "btpu/common/crc32c.h"
#include "btpu/common/wire.h"
#include "btpu/ec/rs.h"
#include "btpu/storage/hbm_provider.h"

namespace btpu::keystone {

using coord::WatchEvent;

using namespace detail;

// ---- failure handling -----------------------------------------------------

void KeystoneService::cleanup_stale_workers() {
  const int64_t now = now_wall_ms();
  const int64_t ttl = config_.worker_heartbeat_ttl_sec * 1000;
  std::vector<NodeId> stale;
  {
    SharedLock lock(registry_mutex_);
    for (const auto& [id, info] : workers_) {
      if (info.is_stale(now, ttl)) stale.push_back(id);
    }
  }
  for (const auto& id : stale) {
    LOG_WARN << "worker " << id << " is stale, cleaning up";
    cleanup_dead_worker(id);
  }
}

void KeystoneService::cleanup_dead_worker(const NodeId& worker_id) {
  std::vector<MemoryPoolId> dead_pools;
  {
    WriterLock lock(registry_mutex_);
    // A worker that dies mid-drain (or after a failed drain) must not leave
    // its id in draining_ forever — a replacement re-registering under the
    // same id would be silently unallocatable.
    draining_.erase(worker_id);
    if (!workers_.erase(worker_id)) return;  // already handled
    for (auto it = pools_.begin(); it != pools_.end();) {
      if (it->second.node_id == worker_id) {
        dead_pools.push_back(it->first);
        // Persistent tiers (mmap/io_uring backing files) keep their bytes
        // across the process: remember the pool's last advertisement so a
        // restarted worker's re-registration can re-adopt instead of
        // re-replicating (readopt_offline_pool).
        if (storage_class_is_persistent(it->second.storage_class)) {
          offline_pools_[it->first] = it->second;
        }
        it = pools_.erase(it);
      } else {
        ++it;
      }
    }
  }
  for (const auto& pool_id : dead_pools) adapter_.forget_pool(pool_id);
  ++counters_.workers_lost;

  // Registry-local cleanup runs on every keystone (each one watches the
  // heartbeat prefix); coordinator-state deletion and repair are the
  // leader's job — a standby mutating either would race the leader.
  if (coordinator_ && is_leader_.load()) {
    warn_if_error(coord_del_record(coord::worker_key(config_.cluster_id, worker_id)), "dead-worker record delete", ErrorCode::COORD_KEY_NOT_FOUND);
    for (const auto& pool_id : dead_pools)
      warn_if_error(coord_del_record(coord::pool_key(config_.cluster_id, worker_id, pool_id)), "dead-worker record delete", ErrorCode::COORD_KEY_NOT_FOUND);
    warn_if_error(coord_del_record(coord::heartbeat_key(config_.cluster_id, worker_id)), "dead-worker record delete", ErrorCode::COORD_KEY_NOT_FOUND);
  }
  bump_view();
  LOG_WARN << "worker " << worker_id << " removed (" << dead_pools.size() << " pools)";

  if (config_.enable_repair && is_leader_.load()) {
    const size_t repaired = repair_objects_for_dead_worker(worker_id);
    if (repaired) {
      LOG_INFO << "repaired " << repaired << " objects after losing " << worker_id;
    }
  }
}

// Rebuilds every object that had placements on `worker_id` from a surviving
// replica over the data plane. The reference has no equivalent — placements
// dangle after worker death (SURVEY §3.5) — but TPU-VM preemption makes
// repair mandatory (SURVEY §7 hard parts).
size_t KeystoneService::repair_objects_for_dead_worker(const NodeId& worker_id) {
  // Full registry view for range release (draining workers' ranges must
  // still map back correctly); ALLOCATION targets exclude draining workers.
  alloc::PoolMap live_pools;
  {
    SharedLock lock(registry_mutex_);
    live_pools = pools_;
  }
  const alloc::PoolMap target_pools = allocatable_pools_snapshot();

  // Pass 1 — metadata only, under the lock: prune dead placements so clients
  // stop dialing the dead worker immediately, drop objects that lost every
  // copy, and queue the rest for re-replication. No data moves here, so the
  // lock hold is bounded by map size, not object bytes.
  struct PendingRepair {
    ObjectKey key;
    uint64_t size{0};
    uint64_t epoch{0};
    size_t needed{0};
    WorkerConfig config;
    std::vector<CopyPlacement> surviving;
  };
  struct PendingEcRepair {
    ObjectKey key;
    uint64_t epoch{0};
    CopyPlacement copy;  // snapshot, dead shards still listed at their indices
    std::vector<size_t> dead_idx;
    WorkerConfig config;
  };
  std::vector<PendingEcRepair> ec_pending;
  // Live-worker snapshot for EC recoverability counting (a coded object may
  // already carry shards lost to EARLIER deaths; tolerance is cumulative).
  std::unordered_set<NodeId> live_workers;
  {
    SharedLock lock(registry_mutex_);
    for (const auto& [id, w] : workers_) {
      if (id != worker_id) live_workers.insert(id);
    }
  }

  std::vector<PendingRepair> pending;
  // Cache invalidations for keys this pass mutated (version bump) or lost
  // (0): collected under the lock, fanned out after it — the watch lane
  // must not ride inside the object-map critical section when the
  // coordinator is remote.
  std::vector<std::pair<ObjectKey, uint64_t>> cache_invals;
  // Any durable write that fails mid-pass defers the rest of this worker's
  // repair to the health loop (repair_retry_): the death event fires once,
  // so without the retry a transient coordinator outage would strand
  // objects with dead placements forever.
  bool deferred = false;
  // Shards in ascending order, one exclusive lock at a time: each shard's
  // prune is atomic for its keys, and clients on other shards keep moving
  // while this one is swept. The pass was never atomic across the whole map
  // (pass 2 re-checks epochs per key), so per-shard locking changes nothing
  // the retry machinery doesn't already absorb.
  for (size_t msi = 0; msi < shard_count_ && !deferred; ++msi) {
    ObjectShard& s = shards_[msi];
    WriterLock lock(s.mutex);
    for (auto it = s.map.begin(); it != s.map.end();) {
      if (!is_leader_.load()) {  // deposed mid-pass: stop issuing doomed RPCs
        deferred = true;
        break;
      }
      ObjectInfo& info = it->second;
      auto damaged = [&](const CopyPlacement& copy) {
        return std::any_of(copy.shards.begin(), copy.shards.end(),
                           [&](const ShardPlacement& s) { return s.worker_id == worker_id; });
      };

      // Pooled put slots touching the dead worker are simply cancelled: no
      // writer is attached, so there is nothing to repair, spare, or count
      // as lost — the owning client's commit misses and falls back.
      if (info.slot && std::any_of(info.copies.begin(), info.copies.end(), damaged)) {
        const ObjectKey key = it->first;
        for (const auto& copy : info.copies) {
          for (const auto& shard : copy.shards) {
            if (shard.worker_id == worker_id)
              adapter_.allocator().remove_pool_ranges(key, shard.pool_id);
          }
        }
        slot_objects_.fetch_sub(1);
        warn_if_error(free_object_locked(s, key, info), "lost-object range free");
        it = s.map.erase(it);
        ++counters_.put_cancels;
        bump_view();
        continue;
      }

      // Erasure-coded objects have ONE copy whose shard ORDER is the code
      // geometry — the copy is never dropped whole. Dead shards stay listed
      // (clients fail reading them and reconstruct from any k survivors:
      // degraded-but-readable); only past the parity tolerance is the
      // object gone. Dead-worker range bookkeeping is released either way.
      if (!info.copies.empty() && info.copies.front().ec_data_shards > 0) {
        CopyPlacement& copy = info.copies.front();
        if (!damaged(copy)) {
          ++it;
          continue;
        }
        const ObjectKey key = it->first;
        size_t dead = 0;
        for (const auto& shard : copy.shards) {
          if (!live_workers.contains(shard.worker_id)) ++dead;
        }
        auto drop_dead_worker_bookkeeping = [&] {
          for (const auto& shard : copy.shards) {
            if (shard.worker_id == worker_id)
              adapter_.allocator().remove_pool_ranges(key, shard.pool_id);
          }
        };
        if (dead > copy.ec_parity_shards) {
          // Same persistent-tier exception as the replicated loss branch.
          bool adoptable = true;
          {
            SharedLock rlock(registry_mutex_);
            for (const auto& shard : copy.shards) {
              if (live_workers.contains(shard.worker_id)) continue;
              if (!offline_pools_.contains(shard.pool_id)) {
                adoptable = false;
                break;
              }
            }
          }
          if (adoptable) {
            ++counters_.objects_offline;
            LOG_WARN << "coded object " << key << " OFFLINE past tolerance with worker "
                     << worker_id << ": bytes persist on file-backed pools — kept for "
                        "re-adoption at restart";
            ++it;
            continue;
          }
          LOG_WARN << "coded object " << key << " lost " << dead << " shards (tolerance "
                   << copy.ec_parity_shards << ") with worker " << worker_id;
          // Fence-first: a deposed leader must not free the survivors'
          // ranges; the promoted leader owns the loss accounting.
          if (unpersist_object(key) != ErrorCode::OK) {
            deferred = true;
            ++it;
            continue;
          }
          drop_dead_worker_bookkeeping();
          warn_if_error(adapter_.free_object(key), "unplaceable-object free");
          it = s.map.erase(it);
          ++counters_.objects_lost;
          bump_view();
          cache_invals.emplace_back(key, 0);
          continue;
        }
        // Persist the bumped epoch BEFORE touching allocator state: a
        // rejected durable write (deposed leader / coordinator outage)
        // leaves the object exactly as the durable record describes it.
        const uint64_t prev_epoch = info.epoch;
        info.epoch = next_epoch_.fetch_add(1);
        if (persist_object(key, info) != ErrorCode::OK) {
          info.epoch = prev_epoch;
          deferred = true;
          ++it;
          continue;
        }
        drop_dead_worker_bookkeeping();
        bump_view();
        cache_invals.emplace_back(key, info.epoch);
        if (info.state == ObjectState::kComplete) {
          // Queue reconstruction of EVERY dead shard (including ones from
          // earlier deaths): without healing, losses accumulate until the
          // tolerance is exceeded and a recoverable object dies.
          std::vector<size_t> dead_idx;
          for (size_t si = 0; si < copy.shards.size(); ++si) {
            if (!live_workers.contains(copy.shards[si].worker_id)) dead_idx.push_back(si);
          }
          ec_pending.push_back({key, info.epoch, copy, std::move(dead_idx), info.config});
        }
        ++it;
        continue;
      }
      std::vector<CopyPlacement> surviving;
      bool any_damaged = false;
      for (const auto& copy : info.copies) {
        if (damaged(copy)) {
          any_damaged = true;
        } else {
          surviving.push_back(copy);
        }
      }
      if (!any_damaged) {
        ++it;
        continue;
      }
      const ObjectKey key = it->first;
      if (surviving.empty()) {
        // Persistent-tier exception: a copy whose every dead shard sits on
        // an OFFLINE PERSISTENT pool (mmap/io_uring backing file — the
        // bytes outlive the process) is kept intact, placements and
        // durable record untouched, and re-validated + refreshed when the
        // restarted worker re-registers the pool (readopt_offline_pool).
        // The reference's disk bytes also survive restarts
        // (iouring_disk_backend.cpp:419-438) but its keystone forgets the
        // metadata; here neither side forgets.
        bool adoptable = false;
        {
          SharedLock rlock(registry_mutex_);
          for (const auto& copy : info.copies) {
            bool ok = !copy.shards.empty();
            for (const auto& shard : copy.shards) {
              if (live_workers.contains(shard.worker_id)) continue;
              if (!offline_pools_.contains(shard.pool_id)) {
                ok = false;
                break;
              }
            }
            if (ok) {
              adoptable = true;
              break;
            }
          }
        }
        if (adoptable) {
          ++counters_.objects_offline;
          LOG_WARN << "object " << key << " OFFLINE with worker " << worker_id
                   << ": bytes persist on its file-backed pools — kept for "
                      "re-adoption at restart, not re-replicated";
          ++it;
          continue;
        }
        LOG_WARN << "object " << key << " lost all replicas with worker " << worker_id;
        // Fence-first, as in the coded branch above.
        if (unpersist_object(key) != ErrorCode::OK) {
          deferred = true;
          ++it;
          continue;
        }
        // Dead-worker shards lose only their bookkeeping (a later free of
        // ranges on a re-registered pool would corrupt the fresh free-map).
        for (const auto& copy : info.copies) {
          for (const auto& shard : copy.shards) {
            if (shard.worker_id == worker_id)
              adapter_.allocator().remove_pool_ranges(key, shard.pool_id);
          }
        }
        warn_if_error(adapter_.free_object(key), "repair rollback free");
        it = s.map.erase(it);
        ++counters_.objects_lost;
        bump_view();
        cache_invals.emplace_back(key, 0);
        continue;
      }
      // Make the pruned state durable BEFORE releasing any ranges: if the
      // durable write is rejected (deposed leader / coordinator outage),
      // this node must not hand ranges the durable record — and therefore
      // the promoted leader — still maps back to the pools.
      ObjectInfo updated = info;
      updated.copies = surviving;
      for (size_t i = 0; i < updated.copies.size(); ++i) updated.copies[i].copy_index = i;
      updated.epoch = next_epoch_.fetch_add(1);
      if (persist_object(key, updated) != ErrorCode::OK) {
        deferred = true;
        ++it;
        continue;
      }
      // Every damaged copy is dropped whole, so release all its ranges now:
      // dead-worker shards lose only their bookkeeping (see above), while
      // live-worker shards of a partially-damaged striped copy hand their
      // bytes back to the pool — otherwise worker churn slowly fills the
      // surviving pools with orphaned, unreadable ranges.
      for (const auto& copy : info.copies) {
        if (!damaged(copy)) continue;
        for (const auto& shard : copy.shards) {
          if (shard.worker_id == worker_id) {
            adapter_.allocator().remove_pool_ranges(key, shard.pool_id);
          } else if (auto pr = shard_to_range(shard, live_pools)) {
            warn_if_error(adapter_.allocator().release_range(key, pr->first, pr->second), "repaired shard range release");
          }
        }
      }
      info = std::move(updated);
      const size_t needed = info.config.replication_factor > surviving.size()
                                ? info.config.replication_factor - surviving.size()
                                : 0;
      bump_view();
      cache_invals.emplace_back(key, info.epoch);
      if (needed > 0 && info.state == ObjectState::kComplete) {
        pending.push_back(
            {key, info.size, info.epoch, needed, info.config, std::move(surviving)});
      }
      ++it;
    }
  }
  for (const auto& [key, version] : cache_invals) publish_cache_invalidation(key, version);

  // Pass 2 — no metadata lock while bytes move: stage the top-up copies
  // under a temporary allocator key, stream from a survivor, then merge the
  // staging allocation into the object atomically iff its epoch is unchanged.
  size_t repaired = 0;
  for (auto& p : pending) {
    if (!is_leader_.load()) {  // deposed mid-repair: stop streaming
      deferred = true;
      break;
    }
    const ObjectKey staging_key = p.key + "\x01" "repair";
    alloc::AllocationRequest req =
        alloc::KeystoneAllocatorAdapter::to_allocation_request(staging_key, p.size, p.config);
    req.replication_factor = p.needed;
    // Anti-affinity: a repaired copy must not land behind a failure domain
    // that already holds a survivor; relax only if the cluster is too small.
    for (const auto& copy : p.surviving) {
      for (const auto& shard : copy.shards) {
        if (std::find(req.excluded_nodes.begin(), req.excluded_nodes.end(),
                      shard.worker_id) == req.excluded_nodes.end())
          req.excluded_nodes.push_back(shard.worker_id);
      }
    }
    auto attempt = adapter_.allocator().allocate(req, target_pools);
    if (!attempt.ok()) {
      req.excluded_nodes.clear();
      attempt = adapter_.allocator().allocate(req, target_pools);
    }
    if (!attempt.ok()) {
      // No room to re-replicate: the object stays degraded on its survivors
      // (pass 1 already pruned the dead placements) — never deleted.
      LOG_WARN << "repair of " << p.key << " degraded to " << p.surviving.size()
               << " copies: " << to_string(attempt.error());
      continue;
    }
    std::vector<CopyPlacement> staged = std::move(attempt).value().copies;

    const CopyPlacement* streamed_src = nullptr;
    bool used_unchecked = false;
    for (const auto& src : p.surviving) {
      // live_pools: the full registry snapshot from the top of the pass —
      // the fabric lane needs fabric_addr for BOTH ends' pools.
      used_unchecked = false;
      if (copy_object_bytes(*data_client_, src, staged, p.size, &live_pools,
                            &counters_.fabric_moves, &used_unchecked) == ErrorCode::OK) {
        streamed_src = &src;
        break;
      }
    }
    if (!streamed_src) {
      warn_if_error(adapter_.free_object(staging_key), "repair staging free");
      deferred = true;  // survivors still serve reads; health loop retries
      continue;
    }

    ObjectShard& s = shard_for(p.key);
    WriterLock lock(s.mutex);
    auto it = s.map.find(p.key);
    if (it == s.map.end() || it->second.epoch != p.epoch) {
      lock.unlock();
      warn_if_error(adapter_.free_object(staging_key), "repair staging free");
      continue;  // object changed while the bytes moved; its new state wins
    }
    if (adapter_.allocator().merge_objects(staging_key, p.key) != ErrorCode::OK) {
      lock.unlock();
      LOG_ERROR << "repair merge failed for " << p.key;
      warn_if_error(adapter_.free_object(staging_key), "repair staging free");
      deferred = true;
      continue;
    }
    for (auto& copy : staged) {
      copy.copy_index = it->second.copies.size();
      copy.content_crc = it->second.copies.empty()
                             ? 0
                             : it->second.copies.front().content_crc;
      carry_shard_crcs(*streamed_src, copy);
      it->second.copies.push_back(std::move(copy));
    }
    it->second.epoch = next_epoch_.fetch_add(1);
    const uint64_t spliced_epoch = it->second.epoch;
    // Fabric- and chip-to-chip-moved bytes bypassed the staged lane's
    // streaming CRC gate but carry the source's stamps: have the scrub
    // verify them ahead of its ring walk (and heal from a sibling if the
    // source was rotten).
    if (used_unchecked) queue_scrub_target(p.key);
    if (auto ec = persist_object(p.key, it->second); ec != ErrorCode::OK) {
      // The merge already landed locally (memory + allocator are consistent)
      // but the durable record is stale. A coordinator outage heals at this
      // key's next successful persist; a fence means this node is deposed
      // and the promoted leader's reconcile-on-promotion owns the truth.
      // Either way the repair cannot be claimed. The splice is irreversible
      // in memory, so queue the key for the health loop's re-persist — a
      // healthy object is never revisited by repair, so nothing else would
      // ever write the record again.
      LOG_ERROR << "repair of " << p.key << " not durably recorded: " << to_string(ec);
      mark_persist_dirty(p.key);
      bump_view();
      lock.unlock();
      publish_cache_invalidation(p.key, spliced_epoch);
      deferred = true;
      continue;
    }
    ++counters_.objects_repaired;
    ++repaired;
    bump_view();
    lock.unlock();
    publish_cache_invalidation(p.key, spliced_epoch);
  }

  // Pass 2b — erasure-coded objects: reconstruct every dead shard from any
  // k survivors (segmented, bounded memory) onto fresh placements and
  // splice them in at their geometry positions. Without this, coded
  // objects never heal — losses accumulate across deaths until tolerance
  // is exceeded and a recoverable object dies.
  for (auto& r : ec_pending) {
    if (!is_leader_.load()) {  // deposed mid-repair: stop streaming
      deferred = true;
      break;
    }
    if (repair_ec_object(r.key, r.epoch, r.copy, r.dead_idx, target_pools)) {
      ++counters_.objects_repaired;
      ++repaired;
    }
  }
  {
    MutexLock lock(repair_retry_mutex_);
    if (deferred) {
      repair_retry_.insert(worker_id);
    } else {
      repair_retry_.erase(worker_id);
    }
  }
  return repaired;
}

// Rebuilds the dead shards of one coded copy. Returns true when the object
// was fully healed (every dead shard reconstructed and spliced).
//
// When the copy carries per-shard CRC stamps, every shard read during
// reconstruction is screened against its stamp. A live-but-rotten shard
// must never serve as a reconstruction basis (the rebuild would be garbage,
// restamped as valid — turning recoverable rot into permanent loss);
// instead it is promoted to a repair target itself, so repair heals silent
// corruption in the same pass that heals worker death.
bool KeystoneService::repair_ec_object(const ObjectKey& key, uint64_t epoch,
                                       const CopyPlacement& copy,
                                       const std::vector<size_t>& dead_idx,
                                       const alloc::PoolMap& target_pools) {
  if (dead_idx.empty()) return false;
  const size_t k = copy.ec_data_shards;
  const size_t m = copy.ec_parity_shards;
  const size_t n = copy.shards.size();
  if (k == 0 || n != k + m) return false;
  const uint64_t L = copy.shards.front().length;
  const bool stamped = copy.shard_crcs.size() == n;

  // Repair targets: the caller's dead shards, plus any live shard a CRC
  // screen condemns below (each retry may extend this list).
  std::vector<size_t> targets = dead_idx;
  const std::vector<size_t> original_dead = dead_idx;

  struct Staged {
    std::string staging_key;
    CopyPlacement placement;
  };
  std::vector<Staged> staged;
  auto free_all_staged = [&] {
    for (auto& st : staged) warn_if_error(adapter_.free_object(st.staging_key), "repair staging free");
    staged.clear();
  };
  std::vector<uint32_t> rebuilt_crcs;

  // Each attempt either completes the segmented reconstruction with a clean
  // basis, or condemns at least one more shard (bounded by tolerance m).
  for (;;) {
    std::vector<bool> dead(n, false);
    for (size_t d : targets) dead[d] = true;

    // 1. Fresh placements, one plain wire shard per target index;
    // anti-affine with every worker the copy still touches (and earlier
    // replacements).
    std::vector<NodeId> excluded;
    for (size_t i = 0; i < n; ++i) {
      if (!dead[i]) excluded.push_back(copy.shards[i].worker_id);
    }
    staged.assign(targets.size(), {});
    bool staged_ok = true;
    for (size_t j = 0; j < targets.size() && staged_ok; ++j) {
      const size_t d = targets[j];
      WorkerConfig cfg = {};
      cfg.replication_factor = 1;
      cfg.max_workers_per_copy = 1;
      staged[j].staging_key = key + "\x01" "ecrepair" + std::to_string(d);
      alloc::AllocationRequest req = alloc::KeystoneAllocatorAdapter::to_allocation_request(
          staged[j].staging_key, L, cfg);
      // Stay in a wire tier (a device shard would be unreadable to the coded
      // client path, even on the relaxed retry); same class as the lost
      // shard when possible.
      req.wire_only = true;
      req.preferred_classes = {copy.shards[d].storage_class};
      req.excluded_nodes = excluded;
      auto attempt = adapter_.allocator().allocate(req, target_pools);
      if (!attempt.ok()) {
        req.excluded_nodes.clear();
        attempt = adapter_.allocator().allocate(req, target_pools);
      }
      // The coded geometry needs exactly ONE shard at this position.
      if (!attempt.ok() || attempt.value().copies[0].shards.size() != 1 ||
          std::holds_alternative<DeviceLocation>(
              attempt.value().copies[0].shards[0].location)) {
        if (attempt.ok()) warn_if_error(adapter_.free_object(staged[j].staging_key), "repair staging free");
        staged.resize(j);
        staged_ok = false;
        LOG_WARN << "ec repair of " << key << " stays degraded: no placement for shard "
                 << d;
        break;
      }
      staged[j].placement = std::move(attempt).value().copies[0];
      excluded.push_back(staged[j].placement.shards[0].worker_id);
    }
    if (!staged_ok) {
      free_all_staged();
      return false;
    }

    // 2. Segmented reconstruction: read each segment from k survivors,
    // rebuild missing data rows, re-encode missing parity rows, write out.
    constexpr uint64_t kSeg = 8ull << 20;
    std::vector<size_t> basis;  // the k survivors we read (data first)
    for (size_t i = 0; i < n && basis.size() < k; ++i) {
      if (!dead[i]) basis.push_back(i);
    }
    if (basis.size() < k) {
      free_all_staged();
      return false;  // beyond tolerance (pass 1 should have caught this)
    }
    bool parity_dead = false;
    for (size_t d : targets) parity_dead |= d >= k;

    std::vector<std::vector<uint8_t>> seg_bufs(n);  // read/rebuilt segments
    const uint64_t seg_cap = std::min<uint64_t>(L, kSeg);
    for (size_t i : basis) seg_bufs[i].resize(seg_cap);
    for (size_t d : targets) seg_bufs[d].resize(seg_cap);
    // Parity re-encode needs every data row; data rows outside the basis and
    // not dead can stay empty unless parity is being rebuilt.
    if (parity_dead) {
      for (size_t i = 0; i < k; ++i) seg_bufs[i].resize(seg_cap);
    }
    std::vector<std::vector<uint8_t>> parity_rows;
    if (parity_dead) parity_rows.assign(m, std::vector<uint8_t>(seg_cap));
    rebuilt_crcs.assign(targets.size(), 0);
    // Incremental CRC per shard we read, for the basis screen.
    std::vector<uint32_t> read_crcs(n, 0);
    std::vector<bool> was_read(n, false);

    bool io_failed = false;
    for (uint64_t off = 0; off < L && !io_failed; off += kSeg) {
      const uint64_t seg = std::min(kSeg, L - off);
      std::vector<const uint8_t*> present(n, nullptr);
      for (size_t i : basis) {
        if (transport::shard_io(*data_client_, copy.shards[i], off, seg_bufs[i].data(), seg,
                                /*is_write=*/false) != ErrorCode::OK) {
          LOG_WARN << "ec repair of " << key << " stays degraded: survivor " << i
                   << " unreadable";
          io_failed = true;
          break;
        }
        read_crcs[i] = crc32c(seg_bufs[i].data(), seg, read_crcs[i]);
        was_read[i] = true;
        present[i] = seg_bufs[i].data();
      }
      if (io_failed) break;
      // Data rows needed for parity re-encode but outside the basis (only
      // possible when they are alive: read them too).
      if (parity_dead) {
        for (size_t i = 0; i < k; ++i) {
          if (present[i] || dead[i]) continue;
          if (transport::shard_io(*data_client_, copy.shards[i], off, seg_bufs[i].data(),
                                  seg,
                                  /*is_write=*/false) != ErrorCode::OK) {
            io_failed = true;
            break;
          }
          read_crcs[i] = crc32c(seg_bufs[i].data(), seg, read_crcs[i]);
          was_read[i] = true;
          present[i] = seg_bufs[i].data();
        }
        if (io_failed) break;
      }
      std::vector<uint8_t*> out(k, nullptr);
      for (size_t d : targets) {
        if (d < k) out[d] = seg_bufs[d].data();
      }
      if (!ec::rs_reconstruct(present.data(), k, m, seg, out.data())) {
        io_failed = true;
        break;
      }
      if (parity_dead) {
        std::vector<const uint8_t*> data_rows(k);
        for (size_t i = 0; i < k; ++i) data_rows[i] = seg_bufs[i].data();
        std::vector<uint8_t*> parity_ptrs(m);
        for (size_t j = 0; j < m; ++j) parity_ptrs[j] = parity_rows[j].data();
        if (!ec::rs_encode(data_rows.data(), k, parity_ptrs.data(), m, seg)) {
          io_failed = true;
          break;
        }
      }
      for (size_t j = 0; j < targets.size(); ++j) {
        const size_t d = targets[j];
        const uint8_t* src = d < k ? seg_bufs[d].data() : parity_rows[d - k].data();
        if (transport::shard_io(*data_client_, staged[j].placement.shards[0], off,
                                const_cast<uint8_t*>(src), seg,
                                /*is_write=*/true) != ErrorCode::OK) {
          io_failed = true;
          break;
        }
        // Restamp as we write: segments stream in order, so the incremental
        // CRC over them IS the rebuilt shard's CRC32C.
        rebuilt_crcs[j] = crc32c(src, seg, rebuilt_crcs[j]);
      }
    }
    if (io_failed) {
      free_all_staged();
      return false;
    }

    // 3. The basis screen: a source shard whose bytes fail its stamp fed
    // garbage into the reconstruction — condemn it, drop this attempt's
    // staging, and retry with the rotten shard as a repair target too.
    if (stamped) {
      std::vector<size_t> condemned;
      for (size_t i = 0; i < n; ++i) {
        if (was_read[i] && read_crcs[i] != copy.shard_crcs[i]) condemned.push_back(i);
      }
      if (!condemned.empty()) {
        for (size_t c : condemned) {
          LOG_WARN << "ec repair of " << key << ": live shard " << c
                   << " failed its CRC stamp (pool " << copy.shards[c].pool_id
                   << ", worker " << copy.shards[c].worker_id
                   << ") — promoting to repair target";
          targets.push_back(c);
        }
        free_all_staged();
        if (targets.size() > m) {
          LOG_WARN << "ec repair of " << key << " stays degraded: " << targets.size()
                   << " dead+rotten shards exceed tolerance m=" << m;
          return false;
        }
        continue;  // retry with a clean basis
      }
    }
    break;  // reconstruction complete with a verified basis
  }

  // 4. Splice under the lock iff the object didn't change underneath us.
  ObjectShard& s = shard_for(key);
  WriterLock lock(s.mutex);
  auto it = s.map.find(key);
  if (it == s.map.end() || it->second.epoch != epoch ||
      it->second.copies.empty() || it->second.copies.front().shards.size() != n) {
    lock.unlock();
    free_all_staged();
    return false;
  }
  for (const auto& st : staged) {
    if (adapter_.allocator().merge_objects(st.staging_key, key) != ErrorCode::OK) {
      lock.unlock();
      LOG_ERROR << "ec repair merge failed for " << key;
      // Staged keys not yet merged are freed; merged ranges now belong to
      // the object and are released when it is removed.
      free_all_staged();
      return false;
    }
  }
  for (size_t j = 0; j < targets.size(); ++j) {
    const size_t d = targets[j];
    // Dead shards' range bookkeeping was already dropped in pass 1 — but a
    // shard promoted here (live, rotten) still holds its range: release it,
    // or the pool leaks the space forever.
    if (std::find(original_dead.begin(), original_dead.end(), d) == original_dead.end()) {
      if (auto pr = shard_to_range(it->second.copies.front().shards[d], memory_pools())) {
        warn_if_error(adapter_.allocator().release_range(key, pr->first, pr->second), "splice range release");
      }
    }
    // Entries are replaced in place, preserving the geometry order.
    it->second.copies.front().shards[d] = staged[j].placement.shards[0];
    if (it->second.copies.front().shard_crcs.size() == n)
      it->second.copies.front().shard_crcs[d] = rebuilt_crcs[j];
  }
  it->second.epoch = next_epoch_.fetch_add(1);
  const uint64_t spliced_epoch = it->second.epoch;
  if (auto ec = persist_object(key, it->second); ec != ErrorCode::OK) {
    // Same discipline as the replicated merge path: the splice already landed
    // locally (memory + allocator are consistent) but the durable record is
    // stale — a promoted leader would still map the condemned shard
    // locations. The repair cannot be claimed (scrub_healed stays honest),
    // and because the now-healthy object will never be revisited by repair,
    // the key is queued for the health loop's re-persist.
    LOG_ERROR << "ec repair of " << key << " not durably recorded: " << to_string(ec);
    mark_persist_dirty(key);
    bump_view();
    lock.unlock();
    publish_cache_invalidation(key, spliced_epoch);
    return false;
  }
  bump_view();
  lock.unlock();
  publish_cache_invalidation(key, spliced_epoch);
  LOG_INFO << "ec repair rebuilt " << targets.size() << " shard(s) of " << key;
  return true;
}

}  // namespace btpu::keystone
