// TSan default suppressions/options for sanitized btpu executables.
//
// Rationale (see native/src/transport/local_transport.cpp): the LOCAL
// transport emulates one-sided RMA with a same-address-space memcpy, so a
// reader racing a remote write is the modeled hardware behavior — always
// discarded downstream through an epoch re-check or CRC gate. The pvm
// lane (pvm_access) is the SAME model over process_vm_readv/writev — for
// same-process targets it degrades to that same direct memcpy, so a
// one-sided put racing a concurrent scrub/read of the same pool bytes is
// again the modeled nondeterminism, CRC-gated downstream (surfaced by
// bb-soak --fanin, whose TCP wire mode keeps writers on the pvm lane
// while scrub reads the same pools). The hook must live in the
// EXECUTABLE: TSan reads it during .preinit, before shared-library
// symbols are guaranteed registered.
#pragma once

#if defined(__SANITIZE_THREAD__)
extern "C" const char* __tsan_default_suppressions() {
  return
      // Audit trail (CORRECTNESS §2/§10): each suppression names its
      // schedule-exploration evidence so the list cannot silently accrete.
      //   local_access — modeled one-sided-RMA tear; the DISCARD gates that
      //     make it benign (epoch re-check, CRC) are the same epoch
      //     machinery the sched mutant demote_skip_epoch_check proves the
      //     hunter can convict when bypassed. TODO(sched): a DFS fixture
      //     modeling reader-vs-one-sided-write over local_access with the
      //     epoch re-check as the invariant would retire this entry's
      //     hand-argument entirely.
      //   pvm_access — same model, pvm lane degraded to the same-process
      //     memcpy (surfaced by bb-soak --fanin). Covered by the same TODO:
      //     the kernel the DFS mode should eventually cover is the
      //     local/pvm one-sided copy vs scrub-read pair.
      "race:btpu::transport::local_access\n"
      "race:btpu::transport::pvm_access\n";
}

// detect_deadlocks=0: TSan's DYNAMIC lock-order detector is unsound under
// stack-address reuse — libstdc++'s std::mutex/shared_mutex destructors
// never call pthread_*_destroy, so mutexes of DEAD stack objects stay in
// the global lock graph and successive tests' fixtures at recycled
// addresses chain into phantom "cycles" spanning unrelated single-threaded
// tests (observed: a 4-edge cycle across four different BTEST bodies, all
// main-thread). Lock ORDER is machine-checked statically instead — the
// clang -Wthread-safety sweep enforces the documented ACQUIRED_BEFORE/
// AFTER hierarchy (docs/CORRECTNESS.md §1) — while TSan keeps doing what
// only it can do: data-race detection, which this hook leaves fully on.
extern "C" const char* __tsan_default_options() {
  return "detect_deadlocks=0";
}
#endif
