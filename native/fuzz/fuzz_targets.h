// Fuzz entry points for the four hostile-input decode surfaces:
//
//   rpc_frame      RPC payload decode (all message shapes) + the v4
//                  deadline and v5 trace trailer strips (server order),
//                  incl. an encode/decode round-trip invariant
//   control_error  0xEE pre-dispatch rejection frames
//   tcp_header     raw TCP DataRequestHeader / StagedFrame (data_wire.h)
//   record         WAL/persist records: worker info, pool record, object
//                  record (envelope dispatch + all legacy layouts)
//   wal_record     coordinator WAL v2 scanner (wal_format.h): chain-CRC
//                  classification (clean / torn tail / corrupt / legacy /
//                  future) + an append/scan round-trip invariant
//
// Header-only on purpose: the SAME functions compile into (a) the libFuzzer
// harness (scripts/fuzz.sh under clang), (b) the gcc corpus-replay binary
// (build/fuzz/btpu_fuzz_replay), and (c) the default-suite regression test
// (native/tests/test_wire_fuzz_corpus.cpp) — so a crasher found by any of
// them regresses against the exact decoder production runs.
//
// Contract for every target: NEVER crash, NEVER read out of bounds, and
// uphold the stated invariants (asserted via fuzz_expect, which aborts so
// both libFuzzer and asan report it as a finding). Return value is 0
// (libFuzzer convention); "input rejected" is a normal outcome, not a
// failure.
#pragma once

#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <string>
#include <vector>

#include "btpu/common/wire.h"
#include "btpu/coord/wal_format.h"
#include "btpu/keystone/keystone.h"
#include "btpu/rpc/rpc.h"
#include "btpu/transport/data_wire.h"

namespace btpu_fuzz {

inline void fuzz_expect(bool ok, const char* what) {
  if (!ok) {
    std::fprintf(stderr, "FUZZ INVARIANT VIOLATED: %s\n", what);
    std::abort();
  }
}

// ---- rpc_frame -------------------------------------------------------------
// First byte selects the message shape (covering every field pattern the
// protocol uses: strings, vectors, nested structs, Result<T>, raw bytes,
// parallel vectors); the rest is the payload. Both the lax (frame-bounded)
// and strict decodes run, plus the deadline-trailer strip. A payload that
// decodes must re-encode and re-decode cleanly (round-trip invariant).
template <typename Msg>
inline void rpc_roundtrip(const std::vector<uint8_t>& payload) {
  Msg m{};
  if (!btpu::wire::from_bytes_lax(payload, m)) return;
  const auto bytes = btpu::wire::to_bytes(m);
  Msg again{};
  fuzz_expect(btpu::wire::from_bytes_lax(bytes, again),
              "rpc re-encode of a decoded message must decode");
  Msg strict{};
  (void)btpu::wire::from_bytes(payload, strict);  // strict verdict may differ; must not crash
}

inline int run_rpc_frame(const uint8_t* data, size_t size) {
  using namespace btpu;
  if (size == 0) return 0;
  const uint8_t sel = data[0];
  std::vector<uint8_t> payload(data + 1, data + size);
  // The server strips the trailers before decoding — mirror its order
  // exactly: deadline (outermost, v4) first, then trace (v5).
  uint32_t budget_ms = 0;
  (void)rpc::strip_deadline_trailer(payload, budget_ms);
  uint64_t trace_id = 0, parent_span = 0;
  if (rpc::strip_trace_trailer(payload, trace_id, parent_span)) {
    fuzz_expect(trace_id != 0,
                "a stripped trace trailer must never carry the untraced id 0");
  }
  switch (sel % 14) {
    case 0: rpc_roundtrip<GetWorkersResponse>(payload); break;
    case 1: rpc_roundtrip<PutStartRequest>(payload); break;
    case 2: rpc_roundtrip<PutStartResponse>(payload); break;
    case 3: rpc_roundtrip<PutCompleteRequest>(payload); break;
    case 4: rpc_roundtrip<BatchGetWorkersResponse>(payload); break;
    case 5: rpc_roundtrip<BatchPutStartRequest>(payload); break;
    case 6: rpc_roundtrip<BatchPutCompleteRequest>(payload); break;
    case 7: rpc_roundtrip<ListObjectsResponse>(payload); break;
    case 8: rpc_roundtrip<PutCommitSlotRequest>(payload); break;
    case 9: rpc_roundtrip<PutStartPooledResponse>(payload); break;
    case 10: rpc_roundtrip<PutInlineRequest>(payload); break;
    case 11: rpc_roundtrip<GetClusterStatsResponse>(payload); break;
    case 12: rpc_roundtrip<PingResponse>(payload); break;
    case 13: rpc_roundtrip<ObjectExistsResponse>(payload); break;
  }
  return 0;
}

// ---- control_error ---------------------------------------------------------
inline int run_control_error(const uint8_t* data, size_t size) {
  using namespace btpu;
  std::vector<uint8_t> payload(data, data + size);
  ErrorCode code{};
  uint32_t hint_ms = 0;
  if (rpc::decode_control_error(payload, code, hint_ms)) {
    fuzz_expect(hint_ms <= rpc::kMaxBackoffHintMs,
                "control-error backoff hint must be clamped");
    fuzz_expect(code == ErrorCode::RETRY_LATER || code == ErrorCode::DEADLINE_EXCEEDED ||
                    code == ErrorCode::RESOURCE_EXHAUSTED,
                "control-error code must be a pre-dispatch rejection code");
  }
  return 0;
}

// ---- tcp_header ------------------------------------------------------------
inline int run_tcp_header(const uint8_t* data, size_t size) {
  using namespace btpu::transport::datawire;
  DataRequestHeader hdr{};
  if (decode_request_header(data, size, hdr)) {
    fuzz_expect(valid_op(hdr.op), "decoded header must carry a known op");
    if (hdr.op == kOpHello) {
      fuzz_expect(hdr.len >= 1 && hdr.len <= kMaxHelloNameBytes,
                  "hello name length must be within its ceiling");
    } else {
      fuzz_expect(hdr.len <= kMaxDataOpBytes, "data op length must be capped");
    }
  }
  StagedFrame frame{};
  if (decode_staged_frame(data, size, frame)) {
    fuzz_expect(frame.h.op == kOpReadStaged || frame.h.op == kOpWriteStaged,
                "staged frame must carry a staged op");
    fuzz_expect(frame.h.len <= kMaxDataOpBytes, "staged chunk length must be capped");
  }
  return 0;
}

// ---- record ----------------------------------------------------------------
// First byte selects the decoder; the rest is the durable record bytes.
inline int run_record(const uint8_t* data, size_t size) {
  using namespace btpu;
  if (size == 0) return 0;
  const uint8_t sel = data[0];
  const std::string bytes(reinterpret_cast<const char*>(data + 1), size - 1);
  switch (sel % 3) {
    case 0: {
      keystone::WorkerInfo info;
      (void)keystone::decode_worker_info(bytes, info);
      break;
    }
    case 1: {
      MemoryPool pool;
      (void)keystone::decode_pool_record(bytes, pool);
      break;
    }
    case 2:
      (void)keystone::probe_object_record(bytes);
      break;
  }
  return 0;
}

// ---- wal_record ------------------------------------------------------------
// Input = a whole WAL file image. The scanner is what coordinator crash
// recovery trusts to separate "truncate and heal" from "refuse to serve",
// so its classification invariants are pinned here:
//   * every intact record lies inside the input and inside valid_end;
//   * kClean accounts for every byte; torn/corrupt valid_end never exceeds
//     the damage point;
//   * re-appending the scanned records through the SAME framing (fresh
//     header + chained CRCs) must scan back kClean with identical payloads
//     (append/replay round-trip).
inline int run_wal_record(const uint8_t* data, size_t size) {
  using namespace btpu::coord;
  const wal::ScanResult scanned = wal::scan(data, size);
  fuzz_expect(scanned.valid_end <= size, "wal scan valid_end must stay in bounds");
  size_t prev_end = sizeof(wal::FileHeader);
  for (const auto& [off, len] : scanned.records) {
    fuzz_expect(off >= sizeof(wal::FileHeader) + sizeof(wal::RecordHeader) &&
                    off + len <= size && off + len <= scanned.valid_end,
                "wal scan record must lie inside the intact prefix");
    fuzz_expect(off == prev_end + sizeof(wal::RecordHeader),
                "wal scan records must tile the file densely");
    prev_end = off + len;
  }
  switch (scanned.status) {
    case wal::ScanStatus::kClean:
      fuzz_expect(size == 0 || scanned.valid_end == size,
                  "a clean scan must account for every byte");
      break;
    case wal::ScanStatus::kTornTail:
    case wal::ScanStatus::kCorrupt:
      fuzz_expect(scanned.valid_end < size, "damage verdicts require surplus bytes");
      break;
    case wal::ScanStatus::kLegacy: {
      // Legacy files replay through the pre-chain rules: same bounds
      // invariants, no chain to verify.
      const wal::ScanResult legacy = wal::scan_legacy(data, size);
      fuzz_expect(legacy.valid_end <= size, "legacy scan valid_end must stay in bounds");
      for (const auto& [off, len] : legacy.records)
        fuzz_expect(off + len <= size, "legacy record must stay in bounds");
      break;
    }
    case wal::ScanStatus::kFuture:
      break;
  }
  // Round trip: rebuild a fresh journal from the recovered payloads; it
  // must scan clean with the records byte-identical.
  if (!scanned.records.empty()) {
    std::vector<uint8_t> rebuilt;
    uint32_t chain = wal::kChainSeed;
    wal::append_file_header(rebuilt);
    for (const auto& [off, len] : scanned.records)
      wal::append_record(rebuilt, chain, data + off, len);
    const wal::ScanResult again = wal::scan(rebuilt.data(), rebuilt.size());
    fuzz_expect(again.status == wal::ScanStatus::kClean,
                "re-appended journal must scan clean");
    fuzz_expect(again.records.size() == scanned.records.size(),
                "re-appended journal must keep every record");
    for (size_t i = 0; i < again.records.size(); ++i) {
      const auto& [aoff, alen] = again.records[i];
      const auto& [soff, slen] = scanned.records[i];
      fuzz_expect(alen == slen &&
                      std::memcmp(rebuilt.data() + aoff, data + soff, slen) == 0,
                  "re-appended record must be byte-identical");
    }
  }
  return 0;
}

// ---- registry --------------------------------------------------------------
using FuzzFn = int (*)(const uint8_t*, size_t);
struct FuzzTarget {
  const char* name;
  FuzzFn fn;
};
inline constexpr FuzzTarget kFuzzTargets[] = {
    {"rpc_frame", run_rpc_frame},
    {"control_error", run_control_error},
    {"tcp_header", run_tcp_header},
    {"record", run_record},
    {"wal_record", run_wal_record},
};

}  // namespace btpu_fuzz
