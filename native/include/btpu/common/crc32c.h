// CRC32C (Castagnoli) — the end-to-end object integrity checksum.
//
// Clients stamp objects at put_start and verify on get; a mismatch is
// treated as copy/shard loss (replica failover, or parity reconstruction
// for erasure-coded objects), making bit-rot self-healing where redundancy
// exists. No reference counterpart — blackbird trusts the transport.
// Hardware CRC32 instruction (SSE4.2) when available, sliced table fallback.
#pragma once

#include <cstddef>
#include <cstdint>

namespace btpu {

// CRC32C of [data, data+len); `seed` chains incremental computation
// (pass the previous return value). 0 is the conventional initial seed.
uint32_t crc32c(const void* data, size_t len, uint32_t seed = 0);

}  // namespace btpu
