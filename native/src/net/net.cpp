#include "btpu/net/net.h"

#include <arpa/inet.h>
#include <fcntl.h>
#include <netdb.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <poll.h>
#include <sys/socket.h>
#include <sys/uio.h>
#include <unistd.h>

#include <cerrno>
#include <chrono>
#include <cstdlib>
#include <cstring>

#include "btpu/common/env.h"
#include "btpu/common/log.h"
#include "btpu/common/wire.h"

namespace btpu::net {

Socket& Socket::operator=(Socket&& o) noexcept {
  if (this != &o) {
    close();
    fd_ = o.fd_;
    o.fd_ = -1;
  }
  return *this;
}

Socket::~Socket() { close(); }

void Socket::close() {
  if (fd_ >= 0) {
    ::close(fd_);
    fd_ = -1;
  }
}

void Socket::shutdown() {
  if (fd_ >= 0) ::shutdown(fd_, SHUT_RDWR);
}

std::optional<HostPort> parse_host_port(const std::string& endpoint) {
  auto colon = endpoint.rfind(':');
  if (colon == std::string::npos || colon == 0 || colon + 1 >= endpoint.size()) return std::nullopt;
  HostPort hp;
  hp.host = endpoint.substr(0, colon);
  try {
    int port = std::stoi(endpoint.substr(colon + 1));
    if (port < 0 || port > 65535) return std::nullopt;
    hp.port = static_cast<uint16_t>(port);
  } catch (...) {
    return std::nullopt;
  }
  return hp;
}

Result<Socket> tcp_listen(const std::string& host, uint16_t port, uint16_t* bound_port) {
  Socket s(::socket(AF_INET, SOCK_STREAM | SOCK_CLOEXEC, 0));
  if (!s.valid()) return ErrorCode::NETWORK_ERROR;
  int one = 1;
  ::setsockopt(s.fd(), SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));

  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_port = htons(port);
  if (host.empty() || host == "0.0.0.0") {
    addr.sin_addr.s_addr = INADDR_ANY;
  } else if (::inet_pton(AF_INET, host.c_str(), &addr.sin_addr) != 1) {
    return ErrorCode::INVALID_ADDRESS;
  }
  if (::bind(s.fd(), reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) != 0) {
    LOG_ERROR << "bind " << host << ":" << port << " failed: " << std::strerror(errno);
    return ErrorCode::NETWORK_ERROR;
  }
  if (::listen(s.fd(), 128) != 0) return ErrorCode::NETWORK_ERROR;
  if (bound_port) {
    sockaddr_in actual{};
    socklen_t len = sizeof(actual);
    if (::getsockname(s.fd(), reinterpret_cast<sockaddr*>(&actual), &len) == 0)
      *bound_port = ntohs(actual.sin_port);
  }
  return s;
}

Result<Socket> tcp_connect(const std::string& host, uint16_t port, int timeout_ms,
                           bool bulk_buffers) {
  addrinfo hints{};
  hints.ai_family = AF_INET;
  hints.ai_socktype = SOCK_STREAM;
  addrinfo* res = nullptr;
  const std::string port_str = std::to_string(port);
  if (::getaddrinfo(host.c_str(), port_str.c_str(), &hints, &res) != 0 || !res)
    return ErrorCode::INVALID_ADDRESS;

  Socket s(::socket(AF_INET, SOCK_STREAM | SOCK_CLOEXEC, 0));
  if (!s.valid()) {
    ::freeaddrinfo(res);
    return ErrorCode::NETWORK_ERROR;
  }
  if (bulk_buffers) set_bulk_buffers(s.fd());  // pre-connect: affects window scaling
  // Non-blocking connect + poll so timeout_ms is honored: the kernel's
  // default SYN-retry timeout (~2 min) would otherwise stall data-path
  // threads on unreachable workers (preemption/failover latency).
  const int flags = ::fcntl(s.fd(), F_GETFL, 0);
  ::fcntl(s.fd(), F_SETFL, flags | O_NONBLOCK);
  int rc = ::connect(s.fd(), res->ai_addr, res->ai_addrlen);
  const int connect_errno = errno;  // freeaddrinfo may clobber errno
  ::freeaddrinfo(res);
  if (rc != 0 && connect_errno != EINPROGRESS) {
    LOG_DEBUG << "connect " << host << ":" << port
              << " failed: " << std::strerror(connect_errno);
    return ErrorCode::CONNECTION_FAILED;
  }
  if (rc != 0) {
    pollfd pfd{s.fd(), POLLOUT, 0};
    const auto deadline = std::chrono::steady_clock::now() +
                          std::chrono::milliseconds(timeout_ms > 0 ? timeout_ms : 0);
    int ready;
    for (;;) {
      int wait_ms = -1;
      if (timeout_ms > 0) {
        wait_ms = static_cast<int>(std::chrono::duration_cast<std::chrono::milliseconds>(
                                       deadline - std::chrono::steady_clock::now())
                                       .count());
        if (wait_ms < 0) wait_ms = 0;
      }
      ready = ::poll(&pfd, 1, wait_ms);
      if (ready >= 0 || errno != EINTR) break;  // EINTR: retry with remaining budget
    }
    int soerr = 0;
    socklen_t slen = sizeof(soerr);
    if (ready <= 0 ||
        ::getsockopt(s.fd(), SOL_SOCKET, SO_ERROR, &soerr, &slen) != 0 || soerr != 0) {
      LOG_DEBUG << "connect " << host << ":" << port
                << (ready <= 0 ? " timed out" : " failed: ") << (soerr ? std::strerror(soerr) : "");
      return ErrorCode::CONNECTION_FAILED;
    }
  }
  ::fcntl(s.fd(), F_SETFL, flags);  // back to blocking for the data path
  set_nodelay(s.fd());
  return s;
}

Result<Socket> tcp_accept(const Socket& listener, int timeout_ms) {
  if (timeout_ms >= 0) {
    pollfd pfd{listener.fd(), POLLIN, 0};
    int rc = ::poll(&pfd, 1, timeout_ms);
    if (rc == 0) return ErrorCode::OPERATION_TIMEOUT;
    if (rc < 0) return ErrorCode::NETWORK_ERROR;
  }
  int fd = ::accept4(listener.fd(), nullptr, nullptr, SOCK_CLOEXEC);
  if (fd < 0) return ErrorCode::CONNECTION_FAILED;
  set_nodelay(fd);
  return Socket(fd);
}

ErrorCode read_exact(int fd, void* buf, size_t n) {
  auto* p = static_cast<uint8_t*>(buf);
  while (n > 0) {
    ssize_t rc = ::read(fd, p, n);
    if (rc == 0) return ErrorCode::CLIENT_DISCONNECTED;
    if (rc < 0) {
      if (errno == EINTR) continue;
      return ErrorCode::NETWORK_ERROR;
    }
    p += rc;
    n -= static_cast<size_t>(rc);
  }
  return ErrorCode::OK;
}

// Socket sends go through send/sendmsg with MSG_NOSIGNAL, never raw
// write/writev: a peer that disconnects with a response still pending
// answers the next send with RST, and a raw write would raise SIGPIPE and
// KILL the serving process — a vanished client must read as
// NETWORK_ERROR on that one connection, not as worker death. (Found by
// the uring-engine fan-in work: the event loop's ring sends get -EPIPE
// for free, and the thread server's serve loop died where the engine
// survived.) These helpers also serve FILE fds (the coordinator WAL
// appends through write_all), where send() answers ENOTSOCK — fall back
// to plain write/writev there; a regular file cannot SIGPIPE.
ErrorCode file_write_all(int fd, const void* buf, size_t n) {
  const auto* p = static_cast<const uint8_t*>(buf);
  while (n > 0) {
    const ssize_t rc = ::write(fd, p, n);
    if (rc < 0) {
      if (errno == EINTR) continue;
      return ErrorCode::NETWORK_ERROR;
    }
    p += rc;
    n -= static_cast<size_t>(rc);
  }
  return ErrorCode::OK;
}

ErrorCode write_all(int fd, const void* buf, size_t n) {
  const auto* p = static_cast<const uint8_t*>(buf);
  bool is_file = false;  // sticky per call: don't re-pay the doomed send()
  while (n > 0) {
    ssize_t rc = is_file ? ::write(fd, p, n) : ::send(fd, p, n, MSG_NOSIGNAL);
    if (rc < 0 && !is_file && errno == ENOTSOCK) {
      is_file = true;
      continue;
    }
    if (rc < 0) {
      if (errno == EINTR) continue;
      return ErrorCode::NETWORK_ERROR;
    }
    p += rc;
    n -= static_cast<size_t>(rc);
  }
  return ErrorCode::OK;
}

ErrorCode write_iov2(int fd, const void* h, size_t hn, const void* p, size_t pn) {
  iovec iov[2] = {{const_cast<void*>(h), hn}, {const_cast<void*>(p), pn}};
  size_t idx = 0;
  bool is_file = false;  // sticky per call, as in write_all
  while (idx < 2) {
    ssize_t rc;
    if (is_file) {
      rc = ::writev(fd, &iov[idx], static_cast<int>(2 - idx));
    } else {
      msghdr msg{};
      msg.msg_iov = &iov[idx];
      msg.msg_iovlen = 2 - idx;
      rc = ::sendmsg(fd, &msg, MSG_NOSIGNAL);
      if (rc < 0 && errno == ENOTSOCK) {
        is_file = true;
        continue;
      }
    }
    if (rc < 0) {
      if (errno == EINTR) continue;
      return ErrorCode::NETWORK_ERROR;
    }
    auto remaining = static_cast<size_t>(rc);
    while (idx < 2 && remaining >= iov[idx].iov_len) {
      remaining -= iov[idx].iov_len;
      ++idx;
    }
    if (idx < 2 && remaining > 0) {
      iov[idx].iov_base = static_cast<uint8_t*>(iov[idx].iov_base) + remaining;
      iov[idx].iov_len -= remaining;
    }
  }
  return ErrorCode::OK;
}

void set_nodelay(int fd) {
  int one = 1;
  ::setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
}

void set_bulk_buffers(int fd, int bytes) {
  // Deep buffers for bulk data-path sockets only; control-plane sockets keep
  // kernel autotuning (explicit buffer sizes disable it and pin kernel
  // memory per socket, which a coordinator with many workers multiplies).
  // Pinned buffers cap the window below what autotune reaches on high-BDP
  // links (net.ipv4.tcp_{r,w}mem max > our pin), but measure ~1.7x faster
  // for 1 MiB gets on same-host paths, which is where the shm/tcp data
  // plane actually runs. BTPU_SOCK_BUFS=auto leaves both directions to
  // autotuning for WAN-ish deployments; =N pins both to N bytes.
  static const char* mode = env_str("BTPU_SOCK_BUFS");
  if (mode && std::strcmp(mode, "auto") == 0) return;
  if (mode) {
    int custom = std::atoi(mode);
    if (custom > 0) bytes = custom;
  }
  ::setsockopt(fd, SOL_SOCKET, SO_SNDBUF, &bytes, sizeof(bytes));
  ::setsockopt(fd, SOL_SOCKET, SO_RCVBUF, &bytes, sizeof(bytes));
}

void set_keepalive(int fd) {
  int one = 1;
  ::setsockopt(fd, SOL_SOCKET, SO_KEEPALIVE, &one, sizeof(one));
}

ErrorCode send_frame(int fd, uint8_t opcode, const void* payload, size_t n) {
  if (n > kMaxFrameBytes) return ErrorCode::BUFFER_OVERFLOW;
  uint8_t header[5];
  const uint32_t len = static_cast<uint32_t>(n);
  std::memcpy(header, &len, 4);
  header[4] = opcode;
  return write_iov2(fd, header, sizeof(header), payload, n);
}

ErrorCode recv_frame(int fd, uint8_t& opcode, std::vector<uint8_t>& payload) {
  uint8_t header[5];
  BTPU_RETURN_IF_ERROR(read_exact(fd, header, sizeof(header)));
  // Checked parse of the frame header; the length is a hostile-controlled
  // allocation size, so it must clear kMaxFrameBytes BEFORE resize().
  wire::WireReader r(header, sizeof(header));
  uint32_t len = 0;
  if (!r.u32(len) || !r.u8(opcode)) return ErrorCode::NETWORK_ERROR;  // unreachable: 5 bytes
  if (len > kMaxFrameBytes) return ErrorCode::BUFFER_OVERFLOW;
  payload.resize(len);
  if (len > 0) BTPU_RETURN_IF_ERROR(read_exact(fd, payload.data(), len));
  return ErrorCode::OK;
}

}  // namespace btpu::net
