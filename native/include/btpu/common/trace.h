// First-class span timing.
//
// Role parity: the reference has no structured tracing — demo clients
// hand-roll high_resolution_clock spans (clients/ucx_client.cpp:116-148).
// Since the scoreboard metric is p50/p99 latency (BASELINE.md), the
// framework aggregates spans always-on (~20ns/op) and can emit JSONL events
// when BTPU_TRACE=<path> is set. Aggregates surface in /metrics as
// btpu_span_{p50,p99}_us{span="..."} gauges.
//
// Usage:  { TRACE_SPAN("client.put.transfer"); ...hot path... }
#pragma once

#include <chrono>
#include <cstdint>
#include <string>
#include <string_view>
#include <vector>

namespace btpu::trace {

struct SpanStats {
  std::string name;
  uint64_t count{0};
  double total_us{0};
  double p50_us{0};
  double p99_us{0};
  double max_us{0};
};

// Records one duration sample for `name`.
void record(std::string_view name, double duration_us);

// Aggregated percentiles per span name (reservoir of recent samples).
std::vector<SpanStats> summary();
void reset();

// RAII span.
class Span {
 public:
  explicit Span(std::string_view name)
      : name_(name), start_(std::chrono::steady_clock::now()) {}
  ~Span() {
    const auto end = std::chrono::steady_clock::now();
    record(name_, std::chrono::duration<double, std::micro>(end - start_).count());
  }
  Span(const Span&) = delete;
  Span& operator=(const Span&) = delete;

 private:
  std::string_view name_;
  std::chrono::steady_clock::time_point start_;
};

}  // namespace btpu::trace

#define BTPU_TRACE_CONCAT_INNER(a, b) a##b
#define BTPU_TRACE_CONCAT(a, b) BTPU_TRACE_CONCAT_INNER(a, b)
#define TRACE_SPAN(name) ::btpu::trace::Span BTPU_TRACE_CONCAT(_btpu_span_, __LINE__)(name)
