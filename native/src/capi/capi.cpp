#include "btpu/capi.h"

#include <cstdio>
#include <cstring>

#include <algorithm>

#include "btpu/client/embedded.h"
#include "btpu/common/crc32c.h"
#include "btpu/common/flight_recorder.h"
#include "btpu/common/histogram.h"
#include "btpu/common/log.h"
#include "btpu/common/poolsan.h"
#include "btpu/common/trace.h"
#include "btpu/transport/transport.h"

using namespace btpu;

struct btpu_cluster {
  std::unique_ptr<client::EmbeddedCluster> impl;
};

struct btpu_worker {
  std::unique_ptr<worker::WorkerService> impl;
};

struct btpu_client {
  std::unique_ptr<client::ObjectClient> impl;
};

struct btpu_async_batch {
  std::shared_ptr<client::AsyncBatch> impl;
};

extern "C" {

btpu_cluster* btpu_cluster_create(uint32_t n_workers, uint64_t pool_bytes,
                                  uint32_t storage_class, uint32_t transport) {
  return btpu_cluster_create_ex(n_workers, pool_bytes, storage_class, transport, nullptr,
                                -1);
}

btpu_cluster* btpu_cluster_create_ex(uint32_t n_workers, uint64_t pool_bytes,
                                     uint32_t storage_class, uint32_t transport,
                                     const char* data_dir, int64_t group_commit_us) {
  auto options = client::EmbeddedClusterOptions::simple(
      n_workers, pool_bytes, static_cast<StorageClass>(storage_class));
  const auto kind = static_cast<TransportKind>(transport);
  for (auto& w : options.workers) {
    w.transport = kind;
    if (kind == TransportKind::TCP) w.listen_host = "127.0.0.1";
  }
  if (data_dir && data_dir[0]) {
    options.durability.dir = data_dir;
    options.durability.group_commit_us = group_commit_us;
  }
  auto cluster = std::make_unique<client::EmbeddedCluster>(std::move(options));
  if (cluster->start() != ErrorCode::OK) return nullptr;
  auto* handle = new btpu_cluster;
  handle->impl = std::move(cluster);
  return handle;
}

btpu_cluster* btpu_cluster_create_tiered(uint32_t n_workers, uint64_t device_bytes,
                                         uint64_t host_bytes) {
  client::EmbeddedClusterOptions options;
  options.keystone.gc_interval_sec = 1;
  options.keystone.health_check_interval_sec = 1;
  for (uint32_t i = 0; i < n_workers; ++i) {
    worker::WorkerServiceConfig w;
    w.worker_id = "worker-" + std::to_string(i);
    w.cluster_id = options.keystone.cluster_id;
    w.transport = TransportKind::LOCAL;
    w.heartbeat_interval_ms = 100;
    w.heartbeat_ttl_ms = 500;
    w.topo = {0, static_cast<int32_t>(i), -1};
    if (device_bytes > 0) {
      worker::PoolConfig hbm;
      hbm.id = "hbm-" + std::to_string(i);
      hbm.storage_class = StorageClass::HBM_TPU;
      hbm.capacity = device_bytes;
      hbm.device_id = "tpu:" + std::to_string(i);
      w.pools.push_back(hbm);
    }
    worker::PoolConfig host;
    host.id = "dram-" + std::to_string(i);
    host.storage_class = StorageClass::RAM_CPU;
    host.capacity = host_bytes;
    w.pools.push_back(host);
    options.workers.push_back(std::move(w));
  }
  auto cluster = std::make_unique<client::EmbeddedCluster>(std::move(options));
  if (cluster->start() != ErrorCode::OK) return nullptr;
  auto* handle = new btpu_cluster;
  handle->impl = std::move(cluster);
  return handle;
}

void btpu_cluster_destroy(btpu_cluster* cluster) { delete cluster; }

int32_t btpu_cluster_kill_worker(btpu_cluster* cluster, uint32_t index) {
  if (!cluster) return static_cast<int32_t>(ErrorCode::INVALID_PARAMETERS);
  cluster->impl->kill_worker(index);
  return 0;
}

uint32_t btpu_cluster_worker_count(btpu_cluster* cluster) {
  return cluster ? static_cast<uint32_t>(cluster->impl->worker_count()) : 0;
}

void btpu_cluster_counters(btpu_cluster* cluster, uint64_t out[6]) {
  if (!cluster || !out) return;
  const auto& c = cluster->impl->keystone().counters();
  out[0] = c.objects_repaired.load();
  out[1] = c.objects_lost.load();
  out[2] = c.evicted.load();
  out[3] = c.gc_collected.load();
  out[4] = c.workers_lost.load();
  out[5] = c.objects_demoted.load();
}

btpu_worker* btpu_worker_create(const char* config_yaml_path, const char* coord_endpoints) {
  if (!config_yaml_path) return nullptr;
  auto service = worker::WorkerService::create_from_yaml(
      config_yaml_path, coord_endpoints ? coord_endpoints : "");
  if (!service.ok()) return nullptr;
  auto* handle = new btpu_worker;
  handle->impl = std::move(service).value();
  return handle;
}

uint32_t btpu_worker_pool_count(btpu_worker* worker) {
  return worker ? static_cast<uint32_t>(worker->impl->pools().size()) : 0;
}

const char* btpu_worker_id(btpu_worker* worker) {
  return worker ? worker->impl->config().worker_id.c_str() : "";
}

void btpu_worker_destroy(btpu_worker* worker) {
  if (!worker) return;
  worker->impl->stop();
  delete worker;
}

btpu_client* btpu_client_create_embedded(btpu_cluster* cluster) {
  if (!cluster) return nullptr;
  auto* handle = new btpu_client;
  handle->impl = cluster->impl->make_client();
  return handle;
}

btpu_client* btpu_client_create_remote(const char* keystone_endpoint) {
  if (!keystone_endpoint) return nullptr;
  client::ClientOptions options;
  options.set_keystone_endpoints(keystone_endpoint);
  if (options.keystone_address.empty()) return nullptr;
  auto client = std::make_unique<client::ObjectClient>(options);
  if (client->connect() != ErrorCode::OK) return nullptr;
  auto* handle = new btpu_client;
  handle->impl = std::move(client);
  return handle;
}

void btpu_client_destroy(btpu_client* client) { delete client; }

void btpu_client_set_verify(btpu_client* client, int32_t verify) {
  if (client && client->impl) client->impl->set_verify_reads(verify != 0);
}

int32_t btpu_put(btpu_client* client, const char* key, const void* data, uint64_t size,
                 uint32_t replicas, uint32_t max_workers, uint32_t preferred_class) {
  return btpu_put_ex2(client, key, data, size, replicas, max_workers, preferred_class,
                      /*ttl_ms=*/-1, /*soft_pin=*/0, /*preferred_slice=*/-1);
}

// Kept at its original 9-arg signature: exported C symbols never change
// shape in place (a stale caller would pass garbage for the new arg).
// New knobs land in a NEW entry point below.
int32_t btpu_put_ex(btpu_client* client, const char* key, const void* data, uint64_t size,
                    uint32_t replicas, uint32_t max_workers, uint32_t preferred_class,
                    int64_t ttl_ms, int32_t soft_pin) {
  return btpu_put_ex2(client, key, data, size, replicas, max_workers, preferred_class,
                      ttl_ms, soft_pin, /*preferred_slice=*/-1);
}

int32_t btpu_put_ex2(btpu_client* client, const char* key, const void* data, uint64_t size,
                     uint32_t replicas, uint32_t max_workers, uint32_t preferred_class,
                     int64_t ttl_ms, int32_t soft_pin, int32_t preferred_slice) {
  return btpu_put_ex3(client, key, data, size, replicas, max_workers, preferred_class,
                      ttl_ms, soft_pin, preferred_slice, /*preferred_host=*/-1);
}

int32_t btpu_put_ex3(btpu_client* client, const char* key, const void* data, uint64_t size,
                     uint32_t replicas, uint32_t max_workers, uint32_t preferred_class,
                     int64_t ttl_ms, int32_t soft_pin, int32_t preferred_slice,
                     int32_t preferred_host) {
  if (!client || !key || !data) return static_cast<int32_t>(ErrorCode::INVALID_PARAMETERS);
  WorkerConfig cfg;
  cfg.replication_factor = replicas == 0 ? 1 : replicas;
  cfg.max_workers_per_copy = max_workers == 0 ? 1 : max_workers;
  if (preferred_class != 0)
    cfg.preferred_classes = {static_cast<StorageClass>(preferred_class)};
  if (ttl_ms >= 0) cfg.ttl_ms = static_cast<uint64_t>(ttl_ms);
  cfg.enable_soft_pin = soft_pin != 0;
  cfg.preferred_slice = preferred_slice;  // -1 = no slice affinity
  cfg.preferred_host = preferred_host;    // -1 = no host affinity
  return static_cast<int32_t>(client->impl->put(key, data, size, cfg));
}

int32_t btpu_put_ec(btpu_client* client, const char* key, const void* data, uint64_t size,
                    uint32_t ec_data, uint32_t ec_parity, uint32_t preferred_class,
                    int64_t ttl_ms, int32_t soft_pin) {
  return btpu_put_ec2(client, key, data, size, ec_data, ec_parity, preferred_class,
                      ttl_ms, soft_pin, /*preferred_slice=*/-1);
}

int32_t btpu_put_ec2(btpu_client* client, const char* key, const void* data, uint64_t size,
                     uint32_t ec_data, uint32_t ec_parity, uint32_t preferred_class,
                     int64_t ttl_ms, int32_t soft_pin, int32_t preferred_slice) {
  if (!client || !key || !data) return static_cast<int32_t>(ErrorCode::INVALID_PARAMETERS);
  WorkerConfig cfg;
  cfg.ec_data_shards = ec_data;
  cfg.ec_parity_shards = ec_parity;
  if (preferred_class != 0)
    cfg.preferred_classes = {static_cast<StorageClass>(preferred_class)};
  if (ttl_ms >= 0) cfg.ttl_ms = static_cast<uint64_t>(ttl_ms);
  cfg.enable_soft_pin = soft_pin != 0;
  cfg.preferred_slice = preferred_slice;  // -1 = no slice affinity
  return static_cast<int32_t>(client->impl->put(key, data, size, cfg));
}

int32_t btpu_get(btpu_client* client, const char* key, void* buffer, uint64_t buffer_size,
                 uint64_t* out_size) {
  if (!client || !key || !out_size) return static_cast<int32_t>(ErrorCode::INVALID_PARAMETERS);
  if (!buffer) {
    // Size probe: a coherent cached entry answers without the metadata RTT
    // (the probe+read pattern Python's get() uses stays two cache hits).
    if (auto cached = client->impl->cached_object_size(key)) {
      *out_size = *cached;
      return 0;
    }
    auto placements = client->impl->get_workers(key);
    if (!placements.ok()) return static_cast<int32_t>(placements.error());
    *out_size = placements.value().empty() ? 0 : copy_logical_size(placements.value().front());
    return 0;
  }
  auto got = client->impl->get_into(key, buffer, buffer_size);
  if (!got.ok()) return static_cast<int32_t>(got.error());
  *out_size = got.value();
  return 0;
}

int32_t btpu_put_many(btpu_client* client, uint32_t n, const char* const* keys,
                      const void* const* bufs, const uint64_t* sizes, uint32_t replicas,
                      uint32_t max_workers, uint32_t preferred_class, int32_t* out_codes) {
  if (!client || (n && (!keys || !bufs || !sizes)) || !out_codes)
    return static_cast<int32_t>(ErrorCode::INVALID_PARAMETERS);
  WorkerConfig cfg;
  cfg.replication_factor = replicas == 0 ? 1 : replicas;
  cfg.max_workers_per_copy = max_workers == 0 ? 1 : max_workers;
  if (preferred_class != 0)
    cfg.preferred_classes = {static_cast<StorageClass>(preferred_class)};
  std::vector<client::ObjectClient::PutItem> items(n);
  for (uint32_t i = 0; i < n; ++i) items[i] = {keys[i], bufs[i], sizes[i]};
  const auto results = client->impl->put_many(items, cfg);
  for (uint32_t i = 0; i < n; ++i) out_codes[i] = static_cast<int32_t>(results[i]);
  return 0;
}

int32_t btpu_get_many(btpu_client* client, uint32_t n, const char* const* keys,
                      void* const* bufs, const uint64_t* buf_sizes, uint64_t* out_sizes,
                      int32_t* out_codes) {
  if (!client || (n && (!keys || !bufs || !buf_sizes)) || !out_sizes || !out_codes)
    return static_cast<int32_t>(ErrorCode::INVALID_PARAMETERS);
  std::vector<client::ObjectClient::GetItem> items(n);
  for (uint32_t i = 0; i < n; ++i) items[i] = {keys[i], bufs[i], buf_sizes[i]};
  auto results = client->impl->get_many(items);
  for (uint32_t i = 0; i < n; ++i) {
    if (results[i].ok()) {
      out_sizes[i] = results[i].value();
      out_codes[i] = 0;
    } else {
      out_sizes[i] = 0;
      out_codes[i] = static_cast<int32_t>(results[i].error());
    }
  }
  return 0;
}

int32_t btpu_sizes_many(btpu_client* client, uint32_t n, const char* const* keys,
                        uint64_t* out_sizes, int32_t* out_codes) {
  if (!client || (n && !keys) || !out_sizes || !out_codes)
    return static_cast<int32_t>(ErrorCode::INVALID_PARAMETERS);
  // Coherent cached entries answer their size probe locally (same shortcut
  // as btpu_get's null-buffer probe): a fully hot batch costs zero keystone
  // RTTs, and only the remainder rides the batched metadata round.
  std::vector<ObjectKey> key_vec;
  std::vector<uint32_t> miss_idx;
  key_vec.reserve(n);
  for (uint32_t i = 0; i < n; ++i) {
    if (auto cached = client->impl->cached_object_size(keys[i])) {
      out_sizes[i] = *cached;
      out_codes[i] = 0;
    } else {
      miss_idx.push_back(i);
      key_vec.emplace_back(keys[i]);
    }
  }
  if (key_vec.empty()) return 0;
  const auto placements = client->impl->get_workers_many(key_vec);
  for (uint32_t j = 0; j < miss_idx.size() && j < placements.size(); ++j) {
    const uint32_t i = miss_idx[j];
    if (!placements[j].ok()) {
      out_sizes[i] = 0;
      out_codes[i] = static_cast<int32_t>(placements[j].error());
      continue;
    }
    if (placements[j].value().empty()) {
      // Object known but no complete copy (failed put, eviction in
      // flight): distinguishable from a genuine zero-byte object.
      out_sizes[i] = 0;
      out_codes[i] = static_cast<int32_t>(ErrorCode::NO_COMPLETE_WORKER);
      continue;
    }
    out_sizes[i] = copy_logical_size(placements[j].value().front());
    out_codes[i] = 0;
  }
  return 0;
}

btpu_async_batch* btpu_get_many_async(btpu_client* client, uint32_t n,
                                      const char* const* keys, void* const* bufs,
                                      const uint64_t* buf_sizes) {
  if (!client || (n && (!keys || !bufs || !buf_sizes))) return nullptr;
  std::vector<client::ObjectClient::GetItem> items(n);
  for (uint32_t i = 0; i < n; ++i) items[i] = {keys[i], bufs[i], buf_sizes[i]};
  auto* batch = new btpu_async_batch;
  batch->impl = client->impl->get_many_async(std::move(items));
  return batch;
}

btpu_async_batch* btpu_put_many_async(btpu_client* client, uint32_t n,
                                      const char* const* keys, const void* const* bufs,
                                      const uint64_t* sizes, uint32_t replicas,
                                      uint32_t max_workers, uint32_t preferred_class) {
  if (!client || (n && (!keys || !bufs || !sizes))) return nullptr;
  WorkerConfig cfg;
  cfg.replication_factor = replicas == 0 ? 1 : replicas;
  cfg.max_workers_per_copy = max_workers == 0 ? 1 : max_workers;
  if (preferred_class != 0)
    cfg.preferred_classes = {static_cast<StorageClass>(preferred_class)};
  std::vector<client::ObjectClient::PutItem> items(n);
  for (uint32_t i = 0; i < n; ++i) items[i] = {keys[i], bufs[i], sizes[i]};
  auto* batch = new btpu_async_batch;
  batch->impl = client->impl->put_many_async(std::move(items), cfg);
  return batch;
}

int32_t btpu_async_batch_done(btpu_async_batch* batch) {
  return batch && batch->impl->done() ? 1 : 0;
}

int32_t btpu_async_batch_wait(btpu_async_batch* batch, uint32_t timeout_ms) {
  return batch && batch->impl->wait(timeout_ms) ? 1 : 0;
}

void btpu_async_batch_cancel(btpu_async_batch* batch) {
  if (batch) batch->impl->cancel();
}

int32_t btpu_async_batch_results(btpu_async_batch* batch, int32_t* out_codes,
                                 uint64_t* out_sizes) {
  if (!batch) return static_cast<int32_t>(ErrorCode::INVALID_PARAMETERS);
  if (!batch->impl->done()) return static_cast<int32_t>(ErrorCode::RETRY_LATER);
  const auto& codes = batch->impl->codes();
  const auto& sizes = batch->impl->sizes();
  for (size_t i = 0; i < codes.size(); ++i) {
    if (out_codes) out_codes[i] = static_cast<int32_t>(codes[i]);
    if (out_sizes) out_sizes[i] = sizes[i];
  }
  return static_cast<int32_t>(batch->impl->status());
}

void btpu_async_batch_free(btpu_async_batch* batch) {
  if (!batch) return;
  // Buffer-safety contract (capi.h): the caller may free item buffers the
  // moment this returns, so a still-running batch is cancelled and waited
  // out — never left racing freed memory.
  if (!batch->impl->done()) {
    batch->impl->cancel();
    (void)batch->impl->wait(0);  // 0 = forever; cancel bounds the wait
  }
  delete batch;
}

int32_t btpu_exists(btpu_client* client, const char* key, int32_t* out_exists) {
  if (!client || !key || !out_exists) return static_cast<int32_t>(ErrorCode::INVALID_PARAMETERS);
  auto r = client->impl->object_exists(key);
  if (!r.ok()) return static_cast<int32_t>(r.error());
  *out_exists = r.value() ? 1 : 0;
  return 0;
}

int32_t btpu_remove(btpu_client* client, const char* key) {
  if (!client || !key) return static_cast<int32_t>(ErrorCode::INVALID_PARAMETERS);
  return static_cast<int32_t>(client->impl->remove(key));
}

int32_t btpu_stats(btpu_client* client, uint64_t out[5]) {
  if (!client || !out) return static_cast<int32_t>(ErrorCode::INVALID_PARAMETERS);
  auto stats = client->impl->cluster_stats();
  if (!stats.ok()) return static_cast<int32_t>(stats.error());
  out[0] = stats.value().total_workers;
  out[1] = stats.value().total_memory_pools;
  out[2] = stats.value().total_objects;
  out[3] = stats.value().total_capacity;
  out[4] = stats.value().used_capacity;
  return 0;
}

uint64_t btpu_pvm_op_count(void) { return transport::pvm_op_count(); }
uint64_t btpu_pvm_byte_count(void) { return transport::pvm_byte_count(); }
uint64_t btpu_tcp_staged_op_count(void) { return transport::tcp_staged_op_count(); }
uint64_t btpu_tcp_staged_byte_count(void) { return transport::tcp_staged_byte_count(); }
uint64_t btpu_tcp_stream_op_count(void) { return transport::tcp_stream_op_count(); }
uint64_t btpu_tcp_stream_byte_count(void) { return transport::tcp_stream_byte_count(); }
uint64_t btpu_tcp_pool_direct_op_count(void) { return transport::tcp_pool_direct_op_count(); }
uint64_t btpu_tcp_pool_direct_byte_count(void) {
  return transport::tcp_pool_direct_byte_count();
}
uint64_t btpu_tcp_zerocopy_sent_count(void) { return transport::tcp_zerocopy_sent_count(); }
uint64_t btpu_tcp_zerocopy_copied_count(void) {
  return transport::tcp_zerocopy_copied_count();
}
uint64_t btpu_uring_loop_count(void) { return transport::uring_active_loop_count(); }
uint64_t btpu_wire_pool_threads(void) { return transport::wire_pool_threads_resolved(); }
uint64_t btpu_cached_op_count(void) { return cache::cached_op_count(); }
uint64_t btpu_cached_byte_count(void) { return cache::cached_byte_count(); }

uint64_t btpu_deadline_exceeded_count(void) {
  // ordering: relaxed — stat folds for the C API; point-in-time reads of monotonic counters (this block and the seven below).
  return robust_counters().deadline_exceeded.load(std::memory_order_relaxed);
}
uint64_t btpu_shed_count(void) {
  return robust_counters().shed.load(std::memory_order_relaxed);
}
uint64_t btpu_client_deadline_exceeded_count(void) {
  return robust_counters().client_deadline_exceeded.load(std::memory_order_relaxed);
}
uint64_t btpu_retry_count(void) {
  // ordering: relaxed — stat fold (see btpu_deadline_exceeded_count).
  return robust_counters().retries.load(std::memory_order_relaxed);
}
uint64_t btpu_retry_budget_exhausted_count(void) {
  return robust_counters().retry_budget_exhausted.load(std::memory_order_relaxed);
}
uint64_t btpu_hedge_fired_count(void) {
  return robust_counters().hedges_fired.load(std::memory_order_relaxed);
}
uint64_t btpu_hedge_win_count(void) {
  // ordering: relaxed — stat fold (see btpu_deadline_exceeded_count).
  return robust_counters().hedge_wins.load(std::memory_order_relaxed);
}
uint64_t btpu_breaker_trip_count(void) {
  return robust_counters().breaker_trips.load(std::memory_order_relaxed);
}
uint64_t btpu_breaker_skip_count(void) {
  // ordering: relaxed — stat fold (see btpu_deadline_exceeded_count).
  return robust_counters().breaker_skips.load(std::memory_order_relaxed);
}
uint64_t btpu_persist_retry_backlog(void) {
  return keystone::persist_retry_backlog_process_total();
}
uint64_t btpu_client_inflight_ops(void) {
  // ordering: relaxed — stat fold (see btpu_deadline_exceeded_count).
  return client::client_core_counters().inflight.load(std::memory_order_relaxed);
}
uint64_t btpu_client_peak_inflight_ops(void) {
  // ordering: relaxed — stat fold (see btpu_deadline_exceeded_count).
  return client::client_core_counters().peak_inflight.load(std::memory_order_relaxed);
}
uint64_t btpu_client_cq_depth(void) {
  // ordering: relaxed — stat fold (see btpu_deadline_exceeded_count).
  return client::client_core_counters().queue_depth.load(std::memory_order_relaxed);
}
uint64_t btpu_client_ops_submitted_count(void) {
  // ordering: relaxed — stat fold (see btpu_deadline_exceeded_count).
  return client::client_core_counters().submitted.load(std::memory_order_relaxed);
}
uint64_t btpu_client_ops_completed_count(void) {
  // ordering: relaxed — stat fold (see btpu_deadline_exceeded_count).
  return client::client_core_counters().completed.load(std::memory_order_relaxed);
}
uint64_t btpu_client_ops_cancelled_count(void) {
  // ordering: relaxed — stat fold (see btpu_deadline_exceeded_count).
  return client::client_core_counters().cancelled.load(std::memory_order_relaxed);
}
uint64_t btpu_optimistic_hit_count(void) {
  // ordering: relaxed — stat fold (see btpu_deadline_exceeded_count).
  return client::client_core_counters().optimistic_hits.load(std::memory_order_relaxed);
}
uint64_t btpu_optimistic_revalidate_count(void) {
  // ordering: relaxed — stat fold (see btpu_deadline_exceeded_count).
  return client::client_core_counters().optimistic_revalidates.load(
      std::memory_order_relaxed);
}

/* ---- pool sanitizer ------------------------------------------------------ */

uint64_t btpu_poolsan_armed(void) { return poolsan::armed() ? 1 : 0; }
uint64_t btpu_poolsan_conviction_count(void) { return poolsan::counters().convictions; }
uint64_t btpu_poolsan_stale_extent_count(void) { return poolsan::counters().stale_generation; }
uint64_t btpu_poolsan_redzone_smash_count(void) { return poolsan::counters().redzone_smash; }
uint64_t btpu_poolsan_double_free_count(void) { return poolsan::counters().double_free; }
uint64_t btpu_poolsan_quarantine_bytes(void) { return poolsan::counters().quarantine_bytes; }

/* ---- observability: histograms, trace spans, flight recorder ------------- */

namespace {
// Shared truncating-copy contract of every *_json exporter (NULL buffer
// sizes; out_len always reports the full length).
int32_t copy_json_out(const std::string& json, char* buffer, uint64_t buffer_size,
                      uint64_t* out_len) {
  if (!out_len) return static_cast<int32_t>(ErrorCode::INVALID_PARAMETERS);
  *out_len = json.size();
  if (buffer && buffer_size > 0) {
    const uint64_t n = std::min<uint64_t>(buffer_size, json.size());
    std::memcpy(buffer, json.data(), n);
  }
  return 0;
}
}  // namespace

uint64_t btpu_op_get_count(void) { return hist::op("get").snapshot().count; }
uint64_t btpu_op_get_p50_us(void) {
  const auto s = hist::op("get").snapshot();
  return static_cast<uint64_t>(hist::Histogram::quantile_us(s, 0.50));
}
uint64_t btpu_op_get_p99_us(void) {
  const auto s = hist::op("get").snapshot();
  return static_cast<uint64_t>(hist::Histogram::quantile_us(s, 0.99));
}
uint64_t btpu_flight_event_count(void) { return flight::recorder().recorded(); }
uint64_t btpu_trace_span_count(void) { return trace::span_ring_recorded(); }

void btpu_set_tracing(int32_t on) { trace::set_enabled(on != 0); }

int32_t btpu_histograms_json(char* buffer, uint64_t buffer_size, uint64_t* out_len) {
  return copy_json_out(hist::dump_json(), buffer, buffer_size, out_len);
}

int32_t btpu_trace_spans_json(uint64_t trace_id, char* buffer, uint64_t buffer_size,
                              uint64_t* out_len) {
  return copy_json_out(trace::dump_spans_json(trace_id), buffer, buffer_size, out_len);
}

int32_t btpu_flight_json(char* buffer, uint64_t buffer_size, uint64_t* out_len) {
  return copy_json_out(flight::recorder().dump_json(), buffer, buffer_size, out_len);
}

void btpu_client_cache_configure(btpu_client* client, uint64_t cache_bytes) {
  if (client && client->impl) client->impl->configure_cache(cache_bytes);
}

int32_t btpu_client_cache_stats(btpu_client* client, uint64_t out[9]) {
  if (!client || !client->impl || !out)
    return static_cast<int32_t>(ErrorCode::INVALID_PARAMETERS);
  const auto s = client->impl->cache_stats();
  out[0] = s.hits;
  out[1] = s.misses;
  out[2] = s.fills;
  out[3] = s.invalidations;
  out[4] = s.stale_rejects;
  out[5] = s.lease_expiries;
  out[6] = s.evictions;
  out[7] = s.bytes;
  out[8] = s.entries;
  return 0;
}

int32_t btpu_drain_worker(btpu_client* client, const char* worker_id, uint64_t* out_moved) {
  if (!client || !worker_id) return static_cast<int32_t>(ErrorCode::INVALID_PARAMETERS);
  auto moved = client->impl->drain_worker(worker_id);
  if (!moved.ok()) return static_cast<int32_t>(moved.error());
  if (out_moved) *out_moved = moved.value();
  return 0;
}

namespace {
// JSON string escape. Bytes >= 0x80 are escaped as \u00xx too: keys are
// arbitrary bytes (only "" and '\x01' are rejected at put time), and raw
// non-UTF-8 bytes would make the whole JSON document undecodable on the
// Python side because of one odd key.
std::string json_escape(const std::string& s) {
  std::string out;
  char hex[8];
  for (char c : s) {
    const auto u = static_cast<unsigned char>(c);
    if (c == '"' || c == '\\') {
      out += '\\';
      out += c;
    } else if (u < 0x20 || u >= 0x80) {
      std::snprintf(hex, sizeof(hex), "\\u%04x", u);
      out += hex;
    } else {
      out += c;
    }
  }
  return out;
}
}  // namespace

namespace {
std::string placements_to_json(const std::vector<CopyPlacement>& copies);
}

int32_t btpu_placements_json(btpu_client* client, const char* key, char* buffer,
                             uint64_t buffer_size, uint64_t* out_len) {
  if (!client || !key || !out_len) return static_cast<int32_t>(ErrorCode::INVALID_PARAMETERS);
  auto placements = client->impl->get_workers(key);
  if (!placements.ok()) return static_cast<int32_t>(placements.error());
  const std::string json = placements_to_json(placements.value());
  *out_len = json.size();
  if (buffer && buffer_size > 0) {
    const uint64_t n = std::min<uint64_t>(buffer_size, json.size());
    std::memcpy(buffer, json.data(), n);
  }
  return 0;
}

namespace {
std::string placements_to_json(const std::vector<CopyPlacement>& copies) {
  std::string json = "[";
  const auto& esc = json_escape;
  bool first_copy = true;
  for (const auto& copy : copies) {
    if (!first_copy) json += ",";
    first_copy = false;
    json += "{\"copy_index\":" + std::to_string(copy.copy_index);
    if (copy.content_crc != 0)
      json += ",\"crc\":" + std::to_string(copy.content_crc);
    if (copy.ec_data_shards > 0) {
      json += ",\"ec\":{\"data_shards\":" + std::to_string(copy.ec_data_shards) +
              ",\"parity_shards\":" + std::to_string(copy.ec_parity_shards) +
              ",\"object_size\":" + std::to_string(copy.ec_object_size) + "}";
    }
    json += ",\"shards\":[";
    bool first_shard = true;
    for (const auto& shard : copy.shards) {
      if (!first_shard) json += ",";
      first_shard = false;
      json += "{\"worker\":\"" + esc(shard.worker_id) + "\",\"pool\":\"" +
              esc(shard.pool_id) + "\",\"class\":\"" +
              std::string(storage_class_name(shard.storage_class)) +
              "\",\"transport\":\"" +
              std::string(transport_kind_name(shard.remote.transport)) +
              "\",\"endpoint\":\"" + esc(shard.remote.endpoint) + "\"";
      if (!shard.remote.fabric_addr.empty())
        json += ",\"fabric\":\"" + esc(shard.remote.fabric_addr) + "\"";
      json += ",\"length\":" + std::to_string(shard.length) + ",\"location\":";
      if (const auto* mem = std::get_if<MemoryLocation>(&shard.location)) {
        json += "{\"kind\":\"memory\",\"remote_addr\":" +
                std::to_string(mem->remote_addr) + ",\"rkey\":" +
                std::to_string(mem->rkey) + "}";
      } else if (const auto* dev = std::get_if<DeviceLocation>(&shard.location)) {
        json += "{\"kind\":\"device\",\"device\":\"" + esc(dev->device_id) +
                "\",\"region\":" + std::to_string(dev->region_id) +
                ",\"offset\":" + std::to_string(dev->offset) + "}";
      } else if (const auto* file = std::get_if<FileLocation>(&shard.location)) {
        json += "{\"kind\":\"file\",\"path\":\"" + esc(file->file_path) +
                "\",\"offset\":" + std::to_string(file->file_offset) + "}";
      } else {
        json += "{\"kind\":\"unknown\"}";
      }
      json += "}";
    }
    json += "]}";
  }
  json += "]";
  return json;
}
}  // namespace

// Put lifecycle + fabric commands for runtime-owning clients (fabric.py):
// put_start returns the granted placements as JSON; the caller moves the
// bytes itself (e.g. device fabric) and then completes or cancels.
int32_t btpu_put_start_json(btpu_client* client, const char* key, uint64_t size,
                            uint32_t replicas, uint32_t max_workers,
                            const char* preferred_class, char* buffer,
                            uint64_t buffer_size, uint64_t* out_len) {
  if (!client || !key || !out_len) return static_cast<int32_t>(ErrorCode::INVALID_PARAMETERS);
  WorkerConfig config;
  config.replication_factor = replicas ? replicas : 1;
  config.max_workers_per_copy = max_workers ? max_workers : 1;
  if (preferred_class && *preferred_class) {
    auto cls = storage_class_from_name(preferred_class);
    if (!cls) return static_cast<int32_t>(ErrorCode::INVALID_PARAMETERS);
    config.preferred_classes = {*cls};
  }
  auto placed = client->impl->put_start(key, size, config);
  if (!placed.ok()) return static_cast<int32_t>(placed.error());
  const std::string json = placements_to_json(placed.value());
  *out_len = json.size();
  if (buffer && buffer_size > 0) {
    const uint64_t n = std::min<uint64_t>(buffer_size, json.size());
    std::memcpy(buffer, json.data(), n);
  }
  return 0;
}

int32_t btpu_put_complete(btpu_client* client, const char* key) {
  if (!client || !key) return static_cast<int32_t>(ErrorCode::INVALID_PARAMETERS);
  return static_cast<int32_t>(client->impl->put_complete(key));
}

int32_t btpu_put_cancel(btpu_client* client, const char* key) {
  if (!client || !key) return static_cast<int32_t>(ErrorCode::INVALID_PARAMETERS);
  return static_cast<int32_t>(client->impl->put_cancel(key));
}

namespace {
int32_t make_remote(const char* transport, const char* endpoint, RemoteDescriptor& out) {
  if (!transport || !endpoint) return static_cast<int32_t>(ErrorCode::INVALID_PARAMETERS);
  auto kind = transport_kind_from_name(transport);
  if (!kind) return static_cast<int32_t>(ErrorCode::INVALID_PARAMETERS);
  out.transport = *kind;
  out.endpoint = endpoint;
  return 0;
}
}  // namespace

// Commands the worker serving (transport, endpoint) to OFFER
// [remote_addr, remote_addr+len) on its device fabric under transfer_id;
// the caller pulls it with its own JAX runtime.
int32_t btpu_fabric_offer(btpu_client* client, const char* transport, const char* endpoint,
                          uint64_t remote_addr, uint64_t rkey, uint64_t len,
                          uint64_t transfer_id) {
  if (!client) return static_cast<int32_t>(ErrorCode::INVALID_PARAMETERS);
  RemoteDescriptor remote;
  if (auto rc = make_remote(transport, endpoint, remote)) return rc;
  return static_cast<int32_t>(
      client->impl->fabric_offer(remote, remote_addr, rkey, len, transfer_id));
}

// Commands the worker to PULL transfer_id from src_fabric into its region
// at [remote_addr, remote_addr+len) — the fabric put leg.
int32_t btpu_fabric_pull(btpu_client* client, const char* transport, const char* endpoint,
                         uint64_t remote_addr, uint64_t rkey, uint64_t len,
                         uint64_t transfer_id, const char* src_fabric) {
  if (!client || !src_fabric) return static_cast<int32_t>(ErrorCode::INVALID_PARAMETERS);
  RemoteDescriptor remote;
  if (auto rc = make_remote(transport, endpoint, remote)) return rc;
  return static_cast<int32_t>(
      client->impl->fabric_pull(remote, remote_addr, rkey, len, transfer_id, src_fabric));
}

int32_t btpu_list_json(btpu_client* client, const char* prefix, uint64_t limit, char* buffer,
                       uint64_t buffer_size, uint64_t* out_len) {
  if (!client || !prefix || !out_len) return static_cast<int32_t>(ErrorCode::INVALID_PARAMETERS);
  auto listed = client->impl->list_objects(prefix, limit);
  if (!listed.ok()) return static_cast<int32_t>(listed.error());

  const auto& esc = json_escape;
  std::string json = "[";
  bool first = true;
  for (const auto& obj : listed.value()) {
    if (!first) json += ",";
    first = false;
    json += "{\"key\":\"" + esc(obj.key) + "\",\"size\":" + std::to_string(obj.size) +
            ",\"copies\":" + std::to_string(obj.complete_copies) +
            ",\"soft_pin\":" + (obj.soft_pin ? "true" : "false") + "}";
  }
  json += "]";

  *out_len = json.size();
  if (buffer && buffer_size > 0) {
    const uint64_t n = std::min<uint64_t>(buffer_size, json.size());
    std::memcpy(buffer, json.data(), n);
  }
  return 0;
}

int32_t btpu_pools_json(btpu_client* client, char* buffer, uint64_t buffer_size,
                        uint64_t* out_len) {
  if (!client || !out_len) return static_cast<int32_t>(ErrorCode::INVALID_PARAMETERS);
  auto pools = client->impl->list_pools();
  if (!pools.ok()) return static_cast<int32_t>(pools.error());

  const auto& esc = json_escape;
  std::string json = "[";
  bool first = true;
  for (const auto& p : pools.value()) {
    if (!first) json += ",";
    first = false;
    json += "{\"pool\":\"" + esc(p.id) + "\",\"worker\":\"" + esc(p.node_id) +
            "\",\"class\":\"" + std::string(storage_class_name(p.storage_class)) +
            "\",\"transport\":\"" + std::string(transport_kind_name(p.remote.transport)) +
            "\",\"slice\":" + std::to_string(p.topo.slice_id) +
            ",\"host\":" + std::to_string(p.topo.host_id) +
            ",\"chip\":" + std::to_string(p.topo.chip_id) +
            ",\"capacity\":" + std::to_string(p.size) +
            ",\"used\":" + std::to_string(p.used);
    if (!p.fabric_addr.empty()) json += ",\"fabric\":\"" + esc(p.fabric_addr) + "\"";
    json += "}";
  }
  json += "]";
  return copy_json_out(json, buffer, buffer_size, out_len);
}

uint32_t btpu_crc32c(const void* data, uint64_t size, uint32_t seed) {
  if (!data || size == 0) return seed;
  return crc32c(data, size, seed);
}

const char* btpu_error_name(int32_t code) {
  return to_string(static_cast<ErrorCode>(code)).data();
}

}  // extern "C"
