#include "btpu/client/client.h"

#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <map>
#include <random>

#include "btpu/common/crc32c.h"
#include "btpu/common/env.h"
#include "btpu/common/flight_recorder.h"
#include "btpu/common/histogram.h"
#include "btpu/common/wire.h"
#include "btpu/common/log.h"
#include "btpu/common/poolsan.h"
#include "btpu/common/trace.h"
#include "btpu/coord/remote_coordinator.h"
#include "btpu/ec/rs.h"
#include "btpu/rpc/rpc.h"
#include "btpu/storage/hbm_provider.h"

namespace btpu::client {

void ClientOptions::set_keystone_endpoints(const std::string& list) {
  keystone_address.clear();
  keystone_fallbacks.clear();
  size_t pos = 0;
  while (pos <= list.size()) {
    const size_t next = list.find(',', pos);
    const std::string part = list.substr(pos, next - pos);
    if (!part.empty()) {
      if (keystone_address.empty()) {
        keystone_address = part;
      } else {
        keystone_fallbacks.push_back(part);
      }
    }
    if (next == std::string::npos) break;
    pos = next + 1;
  }
}

namespace {
// Namespaces this client session's pooled slot keys on the keystone.
std::string random_slot_tag() {
  std::random_device rd;
  char buf[17];
  std::snprintf(buf, sizeof(buf), "%08x%08x", rd(), rd());
  return buf;
}

// Operator/env overrides for the robustness knobs (tests and deployments
// flip these without a code change).
void apply_robustness_env(ClientOptions& options) {
  options.op_deadline_ms = env_u32("BTPU_OP_DEADLINE_MS", options.op_deadline_ms);
  options.hedge_reads = env_bool("BTPU_HEDGE_READS", options.hedge_reads);
  options.optimistic_reads = env_bool("BTPU_OPTIMISTIC_READS", options.optimistic_reads);
  options.inline_refusal_backoff_ms =
      env_u32("BTPU_INLINE_RETRY_MS", options.inline_refusal_backoff_ms);
}

// Sampled latency probe for the cached-get fast path: a ~2us local memcpy
// cannot absorb the full tracing scope (two clock reads alone are ~3% of
// it — the bench.py trace-overhead guard holds the line at 5%), so
// 1-in-8 hits measure and record with weight 8 into
// btpu_op_duration_us{op="get_cached"} + one flight op_end event. Uniform
// sampling is quantile-unbiased, and the weight keeps _count/_sum rates
// honest; the unmeasured 7/8 pay one tls increment and a branch. Cache
// hits make no wire calls, so there is nothing to trace-propagate here.
inline uint64_t cached_probe_start() {
  thread_local uint32_t tick = 0;
  if ((++tick & 7u) != 0 || !trace::enabled()) return 0;
  return trace::now_ns();
}

inline void cached_probe_finish(uint64_t t0) {
  if (t0 == 0) return;
  const uint64_t dur_us = (trace::now_ns() - t0) / 1000;
  hist::op("get_cached").record_us_weighted(dur_us, 8);
  flight::record_at(t0 + dur_us * 1000, flight::Ev::kOpEnd, dur_us, 0, 0);
}
}  // namespace

ObjectClient::ObjectClient(ClientOptions options)
    : options_(std::move(options)),
      verify_default_(options_.verify_reads),
      data_(transport::make_transport_client()),
      slot_tag_(random_slot_tag()),
      breakers_(options_.breaker) {
  apply_robustness_env(options_);
  {
    MutexLock lock(rpc_mutex_);
    rpc_ = std::make_shared<rpc::KeystoneRpcClient>(options_.keystone_address);
    rpc_->set_retry_policy(options_.retry);
  }
  setup_cache();
}

ObjectClient::ObjectClient(ClientOptions options, keystone::KeystoneService* embedded)
    : options_(std::move(options)),
      verify_default_(options_.verify_reads),
      embedded_(embedded),
      data_(transport::make_transport_client()),
      breakers_(options_.breaker) {
  apply_robustness_env(options_);
  setup_cache();
}

ObjectClient::~ObjectClient() {
  teardown_cache_watch();
  cancel_pooled_slots();
  // Op core first: queued async ops (and lane-hosted hedge primaries)
  // reference client state that must outlive them — the core's destructor
  // runs every queued op to completion and joins its lanes.
  {
    MutexLock lock(op_core_mutex_);
    // ordering: release — fast-path loads must not observe a core that is
    // mid-destruction (new submissions after this point would be a caller
    // bug; the null mirror turns them into a fresh-core build, also a bug,
    // but never a dangling dereference).
    op_core_ptr_.store(nullptr, std::memory_order_release);
    op_core_.reset();
  }
  // Loser hedge attempts still reference this client's transport; wait for
  // them to drain into their discard buffers before tearing anything down.
  MutexLock lock(hedge_mutex_);
  // ordering: acquire — pairs with the losers' acq_rel decrement: observing 0 means every loser's last touch of this client happened-before teardown.
  while (hedge_inflight_.load(std::memory_order_acquire) != 0) hedge_cv_.wait(lock);
}

ErrorCode ObjectClient::connect() {
  if (embedded_) return ErrorCode::OK;
  auto snap = rpc_snapshot();
  auto ec = snap->connect();
  // Initial connect participates in failover too: the configured primary
  // may already be a dead or standby keystone.
  const size_t endpoints = 1 + options_.keystone_fallbacks.size();
  for (size_t i = 0; i + 1 < endpoints && ec != ErrorCode::OK; ++i) {
    rotate_keystone(snap);
    snap = rpc_snapshot();
    ec = snap->connect();
  }
  return ec;
}

void ObjectClient::rotate_keystone(const std::shared_ptr<rpc::KeystoneRpcClient>& failed) {
  // The decision and the swap are ONE critical section: N threads failing
  // on the same dead keystone must produce one rotation, not N (each extra
  // rotation steps the shared index past the live endpoint and burns a
  // caller's only retry). A caller whose failed snapshot is no longer
  // installed simply adopts the sibling's rotation. The dial is deferred:
  // constructing KeystoneRpcClient is cheap, and call_raw connects lazily,
  // so the lock is never held across a (possibly seconds-long) connect.
  std::shared_ptr<rpc::KeystoneRpcClient> fresh;
  std::string address;
  {
    MutexLock lock(rpc_mutex_);
    if (failed && rpc_ != failed) return;  // a sibling already rotated past it
    const size_t endpoints = 1 + options_.keystone_fallbacks.size();
    keystone_index_ = (keystone_index_ + 1) % endpoints;
    address = keystone_index_ == 0 ? options_.keystone_address
                                   : options_.keystone_fallbacks[keystone_index_ - 1];
    fresh = std::make_shared<rpc::KeystoneRpcClient>(address);
    fresh->set_retry_policy(options_.retry);  // survives failover rotation
    rpc_ = fresh;
  }
  LOG_WARN << "keystone failover: switching to " << address;
  (void)fresh->connect();  // best-effort pre-dial; calls reconnect lazily anyway
}

Result<bool> ObjectClient::object_exists(const ObjectKey& key) {
  OpDeadlineScope op_scope(static_cast<int64_t>(options_.op_deadline_ms));
  if (embedded_) return embedded_->object_exists(key);
  return rpc_failover(/*idempotent=*/true, [&](rpc::KeystoneRpcClient& r) { return r.object_exists(key); });
}

Result<std::vector<CopyPlacement>> ObjectClient::get_workers(const ObjectKey& key) {
  OpDeadlineScope op_scope(static_cast<int64_t>(options_.op_deadline_ms));
#if defined(BTPU_POOLSAN)
  // PLANTED MUTANT — stale-descriptor class (the bug generation stamps
  // exist to convict): serve placements from a never-invalidated memo, the
  // way an over-eager placement cache once could across a remove/GC. The
  // first get memoizes; every later get reuses the stale descriptors, and
  // the data plane must answer STALE_EXTENT — never a neighbor object's
  // bytes. Pinned by Poolsan.MutantStaleRead.
  if (poolsan::mutant() == poolsan::Mutant::kStaleRead) {
    static Mutex memo_mutex;
    static std::unordered_map<ObjectKey, std::vector<CopyPlacement>> memo;
    {
      MutexLock lock(memo_mutex);
      auto it = memo.find(key);
      if (it != memo.end()) return it->second;
    }
    auto fresh = embedded_ ? embedded_->get_workers(key)
                           : rpc_failover(/*idempotent=*/true, [&](rpc::KeystoneRpcClient& r) {
                               return r.get_workers(key);
                             });
    if (fresh.ok()) {
      MutexLock lock(memo_mutex);
      memo[key] = fresh.value();
    }
    return fresh;
  }
#endif
  if (embedded_) return embedded_->get_workers(key);
  return rpc_failover(/*idempotent=*/true, [&](rpc::KeystoneRpcClient& r) { return r.get_workers(key); });
}


ErrorCode ObjectClient::fabric_offer(const RemoteDescriptor& remote, uint64_t addr,
                                     uint64_t rkey, uint64_t len, uint64_t transfer_id) {
  return data_->fabric_offer(remote, addr, rkey, len, transfer_id);
}

ErrorCode ObjectClient::fabric_pull(const RemoteDescriptor& remote, uint64_t addr,
                                    uint64_t rkey, uint64_t len, uint64_t transfer_id,
                                    const std::string& src_fabric) {
  return data_->fabric_pull(remote, addr, rkey, len, transfer_id, src_fabric);
}

Result<std::vector<CopyPlacement>> ObjectClient::put_start(const ObjectKey& key,
                                                           uint64_t size,
                                                           const WorkerConfig& config,
                                                           uint32_t content_crc) {
  OpDeadlineScope op_scope(static_cast<int64_t>(options_.op_deadline_ms));
  invalidate_placements(key);  // same re-created-key rule as put()
  if (embedded_) return embedded_->put_start(key, size, config, content_crc);
  return rpc_failover(/*idempotent=*/false, [&](rpc::KeystoneRpcClient& r) {
    return r.put_start(key, size, config, content_crc);
  });
}

ErrorCode ObjectClient::put_complete(const ObjectKey& key,
                                     const std::vector<CopyShardCrcs>& shard_crcs) {
  if (embedded_) return embedded_->put_complete(key, shard_crcs);
  return rpc_failover(/*idempotent=*/false, [&](rpc::KeystoneRpcClient& r) {
    return r.put_complete(key, shard_crcs);
  });
}

ErrorCode ObjectClient::put_cancel(const ObjectKey& key) {
  if (embedded_) return embedded_->put_cancel(key);
  return rpc_failover(/*idempotent=*/false,
                      [&](rpc::KeystoneRpcClient& r) { return r.put_cancel(key); });
}

ErrorCode ObjectClient::remove(const ObjectKey& key) {
  trace::OpScope op_trace("remove");
  OpDeadlineScope op_scope(static_cast<int64_t>(options_.op_deadline_ms));
  invalidate_placements(key);  // a re-created key must not serve stale bytes
  if (embedded_) return embedded_->remove_object(key);
  return rpc_failover(/*idempotent=*/false,
                      [&](rpc::KeystoneRpcClient& r) { return r.remove_object(key); });
}

Result<uint64_t> ObjectClient::remove_all() {
  OpDeadlineScope op_scope(static_cast<int64_t>(options_.op_deadline_ms));
  invalidate_all_placements();  // same re-created-key rule as remove()
  if (embedded_) return embedded_->remove_all_objects();
  return rpc_failover(/*idempotent=*/false,
                      [&](rpc::KeystoneRpcClient& r) { return r.remove_all_objects(); });
}

Result<uint64_t> ObjectClient::drain_worker(const NodeId& worker_id) {
  if (embedded_) return embedded_->drain_worker(worker_id);
  // A long-running mutation: NOT_LEADER rotates, lost replies do not retry.
  return rpc_failover(/*idempotent=*/false,
                      [&](rpc::KeystoneRpcClient& r) { return r.drain_worker(worker_id); });
}

Result<std::vector<ObjectSummary>> ObjectClient::list_objects(const std::string& prefix,
                                                              uint64_t limit) {
  if (embedded_) return embedded_->list_objects(prefix, limit);
  return rpc_failover(/*idempotent=*/true, [&](rpc::KeystoneRpcClient& r) {
    return r.list_objects(prefix, limit);
  });
}

Result<std::vector<MemoryPool>> ObjectClient::list_pools() {
  if (embedded_) return embedded_->list_pools();
  return rpc_failover(/*idempotent=*/true,
                      [&](rpc::KeystoneRpcClient& r) { return r.list_pools(); });
}

Result<ClusterStats> ObjectClient::cluster_stats() {
  if (embedded_) return embedded_->get_cluster_stats();
  return rpc_failover(/*idempotent=*/true,
                      [&](rpc::KeystoneRpcClient& r) { return r.get_cluster_stats(); });
}

Result<ViewVersionId> ObjectClient::ping() {
  if (embedded_) return embedded_->get_view_version();
  return rpc_failover(/*idempotent=*/true, [&](rpc::KeystoneRpcClient& r) { return r.ping(); });
}

// One shard transfer; `buf` already points at the shard's slice of the
// object buffer (running-offset math lives in the copy-level loop).
// Location dispatch lives in transport::shard_io, shared with keystone's
// repair/demotion data movers.
ErrorCode ObjectClient::shard_io(const ShardPlacement& shard, uint8_t* buf, bool is_write) {
  return transport::shard_io(*data_, shard, 0, buf, shard.length, is_write);
}

}  // namespace btpu::client
