#include "btpu/common/types.h"

// Every libbtpu build evaluates the wire-layout static_asserts.
#include "btpu/common/wire_layout_check.h"

namespace btpu {

std::string_view storage_class_name(StorageClass c) noexcept {
  switch (c) {
    case StorageClass::STORAGE_UNSPECIFIED: return "unspecified";
    case StorageClass::RAM_CPU: return "ram_cpu";
    case StorageClass::HBM_TPU: return "hbm_tpu";
    case StorageClass::NVME: return "nvme";
    case StorageClass::SSD: return "ssd";
    case StorageClass::HDD: return "hdd";
    case StorageClass::CXL_MEMORY: return "cxl_memory";
    case StorageClass::CXL_TYPE2_DEVICE: return "cxl_type2";
    case StorageClass::CUSTOM: return "custom";
  }
  return "unknown";
}

int tier_rank(StorageClass c) noexcept {
  switch (c) {
    case StorageClass::HBM_TPU: return 0;
    case StorageClass::RAM_CPU: return 1;
    case StorageClass::CXL_MEMORY: return 2;
    case StorageClass::CXL_TYPE2_DEVICE: return 3;
    case StorageClass::NVME: return 4;
    case StorageClass::SSD: return 5;
    case StorageClass::HDD: return 6;
    case StorageClass::CUSTOM: return 7;
    case StorageClass::STORAGE_UNSPECIFIED: return 8;
  }
  return 8;
}

std::optional<StorageClass> storage_class_from_name(std::string_view name) noexcept {
  if (name == "ram_cpu" || name == "RAM_CPU" || name == "dram") return StorageClass::RAM_CPU;
  if (name == "hbm_tpu" || name == "HBM_TPU" || name == "hbm") return StorageClass::HBM_TPU;
  if (name == "nvme" || name == "NVME") return StorageClass::NVME;
  if (name == "ssd" || name == "SSD") return StorageClass::SSD;
  if (name == "hdd" || name == "HDD") return StorageClass::HDD;
  if (name == "cxl_memory" || name == "CXL_MEMORY") return StorageClass::CXL_MEMORY;
  if (name == "cxl_type2" || name == "CXL_TYPE2_DEVICE") return StorageClass::CXL_TYPE2_DEVICE;
  if (name == "custom" || name == "CUSTOM") return StorageClass::CUSTOM;
  if (name == "unspecified") return StorageClass::STORAGE_UNSPECIFIED;
  return std::nullopt;
}

std::string_view transport_kind_name(TransportKind k) noexcept {
  switch (k) {
    case TransportKind::TRANSPORT_UNSPECIFIED: return "unspecified";
    case TransportKind::LOCAL: return "local";
    case TransportKind::SHM: return "shm";
    case TransportKind::TCP: return "tcp";
    case TransportKind::ICI: return "ici";
    case TransportKind::HBM: return "hbm";
  }
  return "unknown";
}

std::optional<TransportKind> transport_kind_from_name(std::string_view name) noexcept {
  if (name == "local") return TransportKind::LOCAL;
  if (name == "shm") return TransportKind::SHM;
  if (name == "tcp") return TransportKind::TCP;
  if (name == "ici") return TransportKind::ICI;
  if (name == "hbm") return TransportKind::HBM;
  if (name == "unspecified") return TransportKind::TRANSPORT_UNSPECIFIED;
  return std::nullopt;
}

ErrorCode KeystoneConfig::validate() const {
  if (cluster_id.empty()) return ErrorCode::MISSING_REQUIRED_FIELD;
  if (high_watermark <= 0.0 || high_watermark > 1.0) return ErrorCode::VALUE_OUT_OF_RANGE;
  if (eviction_ratio < 0.0 || eviction_ratio > 1.0) return ErrorCode::VALUE_OUT_OF_RANGE;
  if (gc_interval_sec <= 0 || health_check_interval_sec <= 0) return ErrorCode::VALUE_OUT_OF_RANGE;
  if (max_replicas <= 0 || default_replicas <= 0 || default_replicas > max_replicas)
    return ErrorCode::VALUE_OUT_OF_RANGE;
  return ErrorCode::OK;
}

}  // namespace btpu
