// TCP server exposing a MemCoordinator to remote processes (bb-coord).
// Replaces the reference's external etcd dependency for multi-process
// clusters while keeping the Coordinator interface etcd-shaped.
#pragma once

#include <atomic>
#include <memory>
#include <mutex>
#include <thread>
#include <vector>

#include "btpu/coord/mem_coordinator.h"
#include "btpu/net/net.h"

namespace btpu::coord {

class CoordServer {
 public:
  // host:port with port 0 = pick an ephemeral port (see port()).
  CoordServer(std::string host, uint16_t port, DurabilityOptions durability = {});
  ~CoordServer();

  ErrorCode start();
  void stop();
  uint16_t port() const noexcept { return port_; }
  std::string endpoint() const { return host_ + ":" + std::to_string(port_); }
  MemCoordinator& store() { return store_; }

 private:
  void accept_loop();
  void serve_connection(std::shared_ptr<net::Socket> sock);

  std::string host_;
  uint16_t port_;
  net::Socket listener_;
  MemCoordinator store_;
  std::thread accept_thread_;
  std::atomic<bool> running_{false};

  std::mutex conns_mutex_;
  std::vector<std::thread> conn_threads_;
  std::vector<std::shared_ptr<net::Socket>> conns_;  // live sockets, for shutdown
};

}  // namespace btpu::coord
