// bb-worker: data-plane daemon (role of reference examples/worker_example.cpp,
// planned as a production binary in src/executables/CMakeLists.txt).
#include <csignal>
#include <cstdio>
#include <cstring>
#include <thread>

#include "btpu/common/log.h"
#include "btpu/worker/worker.h"

namespace {
volatile std::sig_atomic_t g_stop = 0;
void handle_signal(int) { g_stop = 1; }
}  // namespace

int main(int argc, char** argv) {
  std::string config_path;
  std::string coord_override;
  for (int i = 1; i < argc; ++i) {
    if (!std::strcmp(argv[i], "--config") && i + 1 < argc) config_path = argv[++i];
    else if (!std::strcmp(argv[i], "--coord") && i + 1 < argc) coord_override = argv[++i];
    else if (!std::strcmp(argv[i], "--help")) {
      std::printf("usage: bb-worker --config worker.yaml [--coord host:port]\n");
      return 0;
    }
  }
  if (config_path.empty()) {
    std::fprintf(stderr, "bb-worker: --config is required\n");
    return 1;
  }

  auto service = btpu::worker::WorkerService::create_from_yaml(config_path, coord_override);
  if (!service.ok()) {
    std::fprintf(stderr, "bb-worker: startup failed (%s)\n",
                 std::string(btpu::to_string(service.error())).c_str());
    return 1;
  }
  auto worker_ptr = std::move(service).value();
  auto& worker = *worker_ptr;
  const auto& config = worker.config();
  std::printf("bb-worker %s up with %zu pools\n", config.worker_id.c_str(),
              config.pools.size());
  std::fflush(stdout);

  std::signal(SIGINT, handle_signal);
  std::signal(SIGTERM, handle_signal);
  while (!g_stop) std::this_thread::sleep_for(std::chrono::milliseconds(200));
  worker.stop();
  return 0;
}
