// Corpus-replay regression gate: every checked-in fuzz input (seeds AND
// past crashers, native/fuzz/corpus/<target>/) replays through the exact
// decoders production runs, in the DEFAULT suite — so a crasher found once
// regresses forever, clang or no clang, fuzzer or no fuzzer.
//
// The hostile-input pins below additionally freeze the post-hardening
// verdicts the decoders must reach. Each one FAILS against the pre-hardened
// decoders (unclamped backoff hints, unvalidated object-state bytes,
// trailing-garbage-tolerant v1 pool records, an unvalidated packed TCP
// header) — they are the proof the WireReader migration changed behavior
// where it had to, not just shuffled code.
#include <dirent.h>
#include <sys/stat.h>
#include <unistd.h>

#include <algorithm>
#include <fstream>
#include <string>
#include <vector>

#include "../fuzz/fuzz_corpus.h"
#include "../fuzz/fuzz_targets.h"
#include "btpu/common/env.h"
#include "btest.h"

namespace {

using namespace btpu;

std::string corpus_root() {
  return btest::locate_repo_path("BTPU_FUZZ_CORPUS", "native/fuzz/corpus");
}

BTEST(WireFuzzCorpus, ReplayAllTargets) {
  const std::string root = corpus_root();
  size_t total = 0;
  for (const auto& target : btpu_fuzz::kFuzzTargets) {
    const auto files = btpu_fuzz::list_corpus_dir(root + "/" + std::string(target.name));
    // An empty directory means the corpus went missing — that must FAIL,
    // not silently pass as "replayed zero inputs".
    BT_EXPECT(!files.empty());
    for (const auto& f : files) {
      const auto bytes = btpu_fuzz::read_corpus_file(f);
      target.fn(bytes.data(), bytes.size());  // must not crash / violate invariants
      ++total;
    }
  }
  BT_EXPECT(total >= 40);  // seeds alone exceed this; shrinkage = lost corpus
}

// ---- regression-pinned hostile inputs --------------------------------------

BTEST(WireFuzzCorpus, ControlErrorHintIsClamped) {
  // Pre-hardening, decode_control_error handed the raw u32 to the caller
  // and the rpc client slept on it: one forged frame = a ~49-day stall.
  ErrorCode code{};
  uint32_t hint = 0;
  const auto frame = rpc::encode_control_error(ErrorCode::RETRY_LATER, 0xFFFFFFFFu);
  BT_ASSERT(rpc::decode_control_error(frame, code, hint));
  BT_EXPECT_EQ(hint, rpc::kMaxBackoffHintMs);
  BT_EXPECT(code == ErrorCode::RETRY_LATER);
  // Only the three pre-dispatch rejection codes may ride the frame.
  const auto forged = rpc::encode_control_error(ErrorCode::OK, 10);
  BT_EXPECT(!rpc::decode_control_error(forged, code, hint));
  // Truncation is rejected, appended fields (newer peer) are tolerated.
  std::vector<uint8_t> shortframe(frame.begin(), frame.begin() + 7);
  BT_EXPECT(!rpc::decode_control_error(shortframe, code, hint));
  auto extended = rpc::encode_control_error(ErrorCode::DEADLINE_EXCEEDED, 5);
  extended.push_back(0x99);
  BT_EXPECT(rpc::decode_control_error(extended, code, hint));
}

BTEST(WireFuzzCorpus, ObjectRecordStateByteValidated) {
  // Pre-hardening, a corrupt/hostile durable record with state=7 decoded
  // "successfully" and static_cast poured 7 into ObjectState, where every
  // downstream comparison misread it. Now: garbage, rejected.
  auto record_with_state = [](uint8_t state) {
    wire::Writer w;
    w.put<uint64_t>(~0ull);  // envelope magic
    w.put<uint8_t>(2);       // current format
    WorkerConfig wc;
    wire::encode_fields(w, uint64_t{4096}, uint64_t{0}, false, state, wc,
                        std::vector<CopyPlacement>{}, int64_t{1}, int64_t{2});
    const auto b = w.take();
    return std::string(b.begin(), b.end());
  };
  BT_EXPECT(keystone::probe_object_record(record_with_state(0)));   // kPending
  BT_EXPECT(keystone::probe_object_record(record_with_state(1)));   // kComplete
  BT_EXPECT(!keystone::probe_object_record(record_with_state(7)));  // hostile
  BT_EXPECT(!keystone::probe_object_record(record_with_state(0xFF)));
}

BTEST(WireFuzzCorpus, V1PoolRecordRejectsTrailingGarbage) {
  // A v1 (envelope-less) pool record, hand-framed to the frozen legacy
  // layout: fields + v1 remote (4 fields) + topo + optional alignment.
  wire::Writer w;
  wire::encode_fields(w, std::string("p1"), std::string("n1"), uint64_t{0x1000},
                      uint64_t{1 << 20}, uint64_t{0}, StorageClass::RAM_CPU);
  wire::encode_fields(w, TransportKind::TCP, std::string("h:1"), uint64_t{0x1000},
                      std::string("ab"));                      // v1 remote
  wire::encode_fields(w, int32_t{1}, int32_t{2}, int32_t{3});  // topo
  wire::encode_fields(w, uint64_t{64});                        // alignment (last v1 field)
  auto bytes = w.take();
  MemoryPool pool;
  BT_EXPECT(keystone::decode_pool_record(std::string(bytes.begin(), bytes.end()), pool));
  BT_EXPECT_EQ(pool.alignment, 64ull);
  // v1 is frozen history: bytes past the last field are corruption, not
  // version skew. Pre-hardening this decoded "successfully".
  bytes.push_back(0xEE);
  BT_EXPECT(!keystone::decode_pool_record(std::string(bytes.begin(), bytes.end()), pool));
}

BTEST(WireFuzzCorpus, TcpHeaderRejectsHostileOpAndLength) {
  using namespace transport::datawire;
  auto raw = [](uint8_t op, uint64_t len) {
    DataRequestHeader h{op, 0x1000, 0xBEEF, len, 0, 0, 0, 0};
    std::vector<uint8_t> v(sizeof(h));
    std::memcpy(v.data(), &h, sizeof(h));
    return v;
  };
  constexpr size_t kHdr = sizeof(DataRequestHeader);  // 53 since extent_gen
  DataRequestHeader hdr{};
  // Pre-hardening the server read the packed struct straight off the
  // socket: any op byte was dispatched, and a forged len drove a
  // multi-exabyte drain loop / scratch resize. All rejected at parse now.
  BT_EXPECT(decode_request_header(raw(kOpRead, 1 << 20).data(), kHdr, hdr));
  BT_EXPECT(!decode_request_header(raw(0x42, 16).data(), kHdr, hdr));          // unknown op
  BT_EXPECT(!decode_request_header(raw(0, 16).data(), kHdr, hdr));             // op 0
  BT_EXPECT(!decode_request_header(raw(kOpWrite, ~0ull >> 1).data(), kHdr, hdr));  // 2^63 len
  BT_EXPECT(!decode_request_header(raw(kOpHello, 0).data(), kHdr, hdr));       // empty name
  BT_EXPECT(!decode_request_header(raw(kOpHello, 4096).data(), kHdr, hdr));    // name > 255
  BT_EXPECT(!decode_request_header(raw(kOpRead, 16).data(), kHdr - 1, hdr));   // truncated
  // A legacy 29-byte (pre-trace) or 45-byte (pre-poolsan) header is
  // TRUNCATED under the ship-together contract — rejected, never
  // mis-decoded into garbage ids/generations.
  BT_EXPECT(!decode_request_header(raw(kOpRead, 16).data(), 29, hdr));
  BT_EXPECT(!decode_request_header(raw(kOpRead, 16).data(), 45, hdr));
  // Staged frames: wrong inner op rejected, truncation rejected.
  StagedFrame f{{kOpWriteStaged, 0x1000, 0xBEEF, 4096, 0, 0, 0, 0}, 0x100};
  std::vector<uint8_t> fv(sizeof(f));
  std::memcpy(fv.data(), &f, sizeof(f));
  StagedFrame out{};
  BT_EXPECT(decode_staged_frame(fv.data(), fv.size(), out));
  BT_EXPECT_EQ(out.shm_off, uint64_t{0x100});
  BT_EXPECT(!decode_staged_frame(fv.data(), fv.size() - 1, out));
  fv[0] = kOpRead;  // not a staged op
  BT_EXPECT(!decode_staged_frame(fv.data(), fv.size(), out));
}

BTEST(WireFuzzCorpus, WalScanClassifiesTornVsCorrupt) {
  // The crash-recovery trust boundary: a torn TAIL heals by truncation, a
  // chain break MID-log must refuse recovery (silently truncating there
  // would discard acked records). Pinned against the exact scanner
  // journal_load runs (wal_format.h).
  namespace wal = btpu::coord::wal;
  std::vector<uint8_t> file;
  uint32_t chain = wal::kChainSeed;
  wal::append_file_header(file);
  const std::vector<uint8_t> r1{1, 'a', 'b', 'c'};
  const std::vector<uint8_t> r2{2, 'd', 'e'};
  wal::append_record(file, chain, r1.data(), r1.size());
  wal::append_record(file, chain, r2.data(), r2.size());

  auto scan_of = [](std::vector<uint8_t> v) { return wal::scan(v.data(), v.size()); };
  // Clean: every byte accounted for, both records surfaced.
  auto clean = scan_of(file);
  BT_EXPECT(clean.status == wal::ScanStatus::kClean);
  BT_EXPECT_EQ(clean.records.size(), size_t{2});
  BT_EXPECT_EQ(clean.valid_end, file.size());
  // Torn record header: truncate at the last intact record.
  {
    auto v = file;
    v.insert(v.end(), {0x05, 0x00, 0x00});
    auto res = scan_of(v);
    BT_EXPECT(res.status == wal::ScanStatus::kTornTail);
    BT_EXPECT_EQ(res.valid_end, file.size());
    BT_EXPECT_EQ(res.records.size(), size_t{2});
  }
  // Torn payload (complete header, short body): torn tail too.
  {
    auto v = file;
    uint32_t c2 = chain;
    const std::vector<uint8_t> r3{1, 'z', 'z', 'z', 'z'};
    wal::append_record(v, c2, r3.data(), r3.size());
    v.resize(v.size() - 2);
    auto res = scan_of(v);
    BT_EXPECT(res.status == wal::ScanStatus::kTornTail);
    BT_EXPECT_EQ(res.valid_end, file.size());
  }
  // Flipped byte mid-log: a COMPLETE record failing its chain CRC is
  // corruption — valid_end stops before the damage and the verdict is
  // refuse, not truncate.
  {
    auto v = file;
    v[sizeof(wal::FileHeader) + sizeof(wal::RecordHeader) + 1] ^= 0x01;
    auto res = scan_of(v);
    BT_EXPECT(res.status == wal::ScanStatus::kCorrupt);
    BT_EXPECT_EQ(res.valid_end, sizeof(wal::FileHeader));
    BT_EXPECT(res.records.empty());
  }
  // Rotten length field with bytes beyond it: corruption as well (a torn
  // append can only leave a SHORT header, never a complete wrong one).
  {
    auto v = file;
    const uint32_t bad = wal::kMaxRecordBytes + 1;
    std::memcpy(v.data() + sizeof(wal::FileHeader), &bad, sizeof(bad));
    BT_EXPECT(scan_of(v).status == wal::ScanStatus::kCorrupt);
  }
  // Version from the future: refuse outright (kFuture), never truncate.
  {
    auto v = file;
    const uint32_t future = wal::kFileVersion + 1;
    std::memcpy(v.data() + sizeof(uint32_t), &future, sizeof(future));
    BT_EXPECT(scan_of(v).status == wal::ScanStatus::kFuture);
  }
  // No magic: legacy dispatch; the pre-chain scanner still bounds records.
  {
    std::vector<uint8_t> legacy;
    const uint32_t len = static_cast<uint32_t>(r1.size());
    const uint8_t* lp = reinterpret_cast<const uint8_t*>(&len);
    legacy.insert(legacy.end(), lp, lp + sizeof(len));
    legacy.insert(legacy.end(), r1.begin(), r1.end());
    BT_EXPECT(scan_of(legacy).status == wal::ScanStatus::kLegacy);
    auto res = wal::scan_legacy(legacy.data(), legacy.size());
    BT_EXPECT_EQ(res.records.size(), size_t{1});
    BT_EXPECT_EQ(res.valid_end, legacy.size());
  }
}

BTEST(WireFuzzCorpus, DeadlineTrailerStripIsExact) {
  WorkerConfig wc;
  auto payload = wire::to_bytes(PutStartRequest{"k", 4096, wc, 0});
  const size_t bare = payload.size();
  rpc::append_deadline_trailer(payload, 123);
  uint32_t budget = 0;
  BT_ASSERT(rpc::strip_deadline_trailer(payload, budget));
  BT_EXPECT_EQ(budget, 123u);
  BT_EXPECT_EQ(payload.size(), bare);
  // No trailer, wrong magic, short payload: never stripped, never read OOB.
  BT_EXPECT(!rpc::strip_deadline_trailer(payload, budget));
  std::vector<uint8_t> tiny{1, 2, 3};
  BT_EXPECT(!rpc::strip_deadline_trailer(tiny, budget));
}

BTEST(WireFuzzCorpus, TraceTrailerStripIsExactAndOrdered) {
  WorkerConfig wc;
  auto payload = wire::to_bytes(PutStartRequest{"k", 4096, wc, 0});
  const size_t bare = payload.size();
  // v5 client framing: trace INSIDE, deadline OUTERMOST; the server strips
  // in reverse append order. Both round-trip exactly.
  rpc::append_trace_trailer(payload, 0xABCDEF0123456789ull, 0x42ull);
  rpc::append_deadline_trailer(payload, 250);
  uint32_t budget = 0;
  uint64_t trace_id = 0, parent = 0;
  BT_ASSERT(rpc::strip_deadline_trailer(payload, budget));
  BT_EXPECT_EQ(budget, 250u);
  BT_ASSERT(rpc::strip_trace_trailer(payload, trace_id, parent));
  BT_EXPECT_EQ(trace_id, 0xABCDEF0123456789ull);
  BT_EXPECT_EQ(parent, 0x42ull);
  BT_EXPECT_EQ(payload.size(), bare);
  // Truncated mid-trailer: nothing stripped, payload untouched.
  auto truncated = wire::to_bytes(PutStartRequest{"k", 4096, wc, 0});
  rpc::append_trace_trailer(truncated, 0x1111222233334444ull, 0x5555ull);
  truncated.resize(truncated.size() - 6);
  const size_t tsize = truncated.size();
  BT_EXPECT(!rpc::strip_trace_trailer(truncated, trace_id, parent));
  BT_EXPECT_EQ(truncated.size(), tsize);
  // A forged trailer carrying trace id 0 (the reserved untraced value) is
  // refused — 0 must stay unambiguous everywhere downstream.
  auto forged = wire::to_bytes(PutStartRequest{"k", 4096, wc, 0});
  rpc::append_trace_trailer(forged, 1, 1);
  std::memset(forged.data() + forged.size() - 16, 0, 8);
  BT_EXPECT(!rpc::strip_trace_trailer(forged, trace_id, parent));
}

}  // namespace
