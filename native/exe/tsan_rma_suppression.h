// TSan default suppressions for sanitized btpu executables.
//
// Rationale (see native/src/transport/local_transport.cpp): the LOCAL
// transport emulates one-sided RMA with a same-address-space memcpy, so a
// reader racing a remote write is the modeled hardware behavior — always
// discarded downstream through an epoch re-check or CRC gate. The hook
// must live in the EXECUTABLE: TSan reads it during .preinit, before
// shared-library symbols are guaranteed registered.
#pragma once

#if defined(__SANITIZE_THREAD__)
extern "C" const char* __tsan_default_suppressions() {
  return "race:btpu::transport::local_access\n";
}
#endif
