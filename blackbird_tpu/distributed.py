"""Multi-host bridge: derive this host's worker from the JAX runtime.

On a pod every host runs ONE process that owns that host's chips
(jax.distributed); the multi-controller data plane (docs/OPERATIONS.md)
wants exactly one `hbm_tpu` pool per local device in that process. This
module turns the JAX runtime's own view of the host into that worker:

    import blackbird_tpu.distributed as btd
    btd.init()                       # jax.distributed when env says so
    btd.serve(coord_endpoints="coord:9300",
              pool_bytes_per_device=8 << 30,
              keystone_endpoints="ks:9100")   # drain-on-preemption target

`init()` is a thin, idempotent wrapper over jax.distributed.initialize —
on single-process runs (no coordinator env) it is a no-op, so the same
entrypoint works on a laptop, a single TPU VM, and a pod slice.

Role parity: the reference's multi-host story is "run worker_service on
every host with a hand-written config" (examples/worker_example.cpp); here
the config is derived from the runtime so it cannot drift from the devices
the process actually owns.
"""

from __future__ import annotations

import os
import tempfile
from pathlib import Path
from typing import Any


_initialized = False


def init(coordinator_address: str | None = None,
         num_processes: int | None = None,
         process_id: int | None = None) -> None:
    """Joins the multi-host JAX runtime when one is configured.

    Explicit args win; otherwise JAX_COORDINATOR_ADDRESS (jax's own env) or
    COORDINATOR_ADDRESS supplies the address. With no coordinator
    configured anywhere this is a no-op, keeping single-host runs on the
    same code path. Idempotent: a second call (entrypoint re-run, or user
    code that initialized jax.distributed itself) does nothing.
    """
    global _initialized
    import jax

    if coordinator_address is None:
        # jax only reads JAX_COORDINATOR_ADDRESS itself; honor the plain
        # name too since this module's docs advertise it as a trigger.
        coordinator_address = os.environ.get(
            "JAX_COORDINATOR_ADDRESS") or os.environ.get("COORDINATOR_ADDRESS")
        if coordinator_address is None:
            return
    if _initialized:
        return
    # Multi-process collectives on the CPU backend need the Gloo
    # implementation selected explicitly on some jax versions (newer ones
    # pick it automatically; without it, cross-process psum fails with
    # "Multiprocess computations aren't implemented on the CPU backend").
    # Checked via config/env, NOT jax.default_backend(): querying the
    # backend would initialize it before jax.distributed.initialize.
    try:
        platforms = (getattr(jax.config, "jax_platforms", None)
                     or os.environ.get("JAX_PLATFORMS") or "")
        if "cpu" in platforms:
            jax.config.update("jax_cpu_collectives_implementation", "gloo")
    except Exception:  # noqa: BLE001 - option absent on this jax version
        pass
    try:
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes,
            process_id=process_id,
        )
    except RuntimeError as exc:
        # jax 0.9 raises "distributed.initialize should only be called
        # once."; older builds said "already initialized" — both mean the
        # runtime is up, which is what this wrapper promises.
        msg = str(exc).lower()
        if "already" not in msg and "only be called once" not in msg:
            raise
    _initialized = True


def _advertise_host_for(coord_endpoints: str) -> str:
    """The address OTHER hosts can reach this one at.

    Binding 0.0.0.0 would make the transport advertise 127.0.0.1 — every
    pod host would register pools at loopback and cross-host reads/repair
    would dial themselves. The interface that routes to the coordinator is
    the one peers share, so a connected UDP socket (no traffic) to it
    yields the right local address; hostname resolution is the fallback.
    """
    import socket

    first = coord_endpoints.split(",")[0]
    host, _, port = first.rpartition(":")
    try:
        with socket.socket(socket.AF_INET, socket.SOCK_DGRAM) as probe:
            probe.connect((host or first, int(port) if port else 9300))
            return str(probe.getsockname()[0])
    except OSError:
        try:
            return socket.gethostbyname(socket.gethostname())
        except OSError:
            return "127.0.0.1"


def worker_config_for_this_host(
    coord_endpoints: str,
    *,
    pool_bytes_per_device: int,
    dram_pool_bytes: int = 0,
    cluster_id: str = "blackbird",
    listen_host: str | None = None,
    slice_id: int = 0,
    heartbeat_interval_ms: int = 1000,
    heartbeat_ttl_ms: int = 5000,
    workdir: str | None = None,
) -> Path:
    """Writes this process's worker.yaml: one hbm_tpu pool per LOCAL device.

    host_id comes from jax.process_index() and the worker id is derived
    from it, so every pod host gets a distinct, stable identity and the
    allocator's worker-level anti-affinity sees one failure domain per
    process — the property cross-process repair relies on. listen_host
    defaults to the address peers can actually reach (see
    _advertise_host_for), never 0.0.0.0.
    """
    import jax

    from blackbird_tpu.worker import write_worker_yaml

    process_index = int(jax.process_index())
    worker_id = f"{cluster_id}-host{process_index}"
    pools: list[dict[str, Any]] = [
        {"id": f"{worker_id}-hbm-{d}", "storage_class": "hbm_tpu",
         "capacity": pool_bytes_per_device, "device_id": f"tpu:{d}"}
        for d in range(len(jax.local_devices()))
    ]
    if dram_pool_bytes:
        pools.append({"id": f"{worker_id}-dram", "storage_class": "ram_cpu",
                      "capacity": dram_pool_bytes})
    out_dir = Path(workdir) if workdir else Path(tempfile.mkdtemp(prefix="btpu_host_"))
    out_dir.mkdir(parents=True, exist_ok=True)
    path = out_dir / f"{worker_id}.yaml"
    write_worker_yaml(
        path, worker_id=worker_id, cluster_id=cluster_id,
        coord_endpoints=coord_endpoints, pools=pools,
        listen_host=listen_host or _advertise_host_for(coord_endpoints),
        host_id=process_index, slice_id=slice_id,
        heartbeat_interval_ms=heartbeat_interval_ms,
        heartbeat_ttl_ms=heartbeat_ttl_ms)
    return path


def serve(coord_endpoints: str, *, pool_bytes_per_device: int,
          dram_pool_bytes: int = 0, cluster_id: str = "blackbird",
          keystone_endpoints: str | None = None, **config_kwargs: Any) -> int:
    """Derives this host's worker config and runs the worker host until a
    signal arrives; SIGTERM (the preemption notice) drains through
    `keystone_endpoints` first when given. Blocks; returns the exit code."""
    from blackbird_tpu import worker

    config = worker_config_for_this_host(
        coord_endpoints,
        pool_bytes_per_device=pool_bytes_per_device,
        dram_pool_bytes=dram_pool_bytes,
        cluster_id=cluster_id,
        **config_kwargs,
    )
    argv = ["--config", str(config)]
    if keystone_endpoints:
        argv += ["--drain-on-term", keystone_endpoints]
    return worker.main(argv)
