// Lease-coherent client object cache (btpu/cache/object_cache.h): unit tests
// for the segmented-LRU core, plus end-to-end coherence proofs against the
// embedded cluster — invalidation on overwrite/remove/evict/repair, torn-free
// concurrent readers during invalidation, and the lease-expiry fallback with
// the invalidation watch stream severed mid-flight.
#include <atomic>
#include <cstring>
#include <thread>

#include "btest.h"
#include "btpu/cache/object_cache.h"
#include "btpu/client/embedded.h"
#include "btpu/common/crc32c.h"

using namespace btpu;
using cache::ObjectCache;
using cache::ObjectVersion;

namespace {

ObjectCache::Bytes make_bytes(size_t n, uint8_t seed) {
  auto v = std::make_shared<std::vector<uint8_t>>(n);
  for (size_t i = 0; i < n; ++i) (*v)[i] = static_cast<uint8_t>(seed + i * 131);
  return v;
}

std::vector<uint8_t> pattern(size_t n, uint8_t seed) {
  std::vector<uint8_t> v(n);
  for (size_t i = 0; i < n; ++i) v[i] = static_cast<uint8_t>(seed + i * 131);
  return v;
}

ObjectCache::Clock::time_point lease(int ms) {
  return ObjectCache::Clock::now() + std::chrono::milliseconds(ms);
}

client::ClientOptions cached_options(uint64_t cache_bytes) {
  client::ClientOptions opts;
  opts.cache_bytes = cache_bytes;
  return opts;
}

}  // namespace

// ---- unit: segmented LRU core ----------------------------------------------

BTEST(Cache, HitMissAndVersionedFill) {
  ObjectCache cache(1 << 20);
  const ObjectVersion v1{7, 1};
  BT_EXPECT(cache.lookup_validated("k", v1).outcome == ObjectCache::Outcome::kMiss);
  cache.fill("k", v1, 123, make_bytes(1024, 1), lease(60'000));
  auto hit = cache.lookup_validated("k", v1);
  BT_ASSERT(hit.outcome == ObjectCache::Outcome::kHit);
  BT_EXPECT_EQ(hit.bytes->size(), size_t{1024});
  BT_EXPECT_EQ(hit.content_crc, 123u);
  // A moved version rejects the resident entry (stale_reject) and misses.
  auto stale = cache.lookup_validated("k", ObjectVersion{7, 2});
  BT_EXPECT(stale.outcome == ObjectCache::Outcome::kMiss);
  const auto stats = cache.stats();
  BT_EXPECT_EQ(stats.stale_rejects, uint64_t{1});
  BT_EXPECT_EQ(stats.entries, uint64_t{0});  // rejected entry is gone
  // An unstamped version is never cacheable.
  cache.fill("u", ObjectVersion{}, 1, make_bytes(64, 2), lease(60'000));
  BT_EXPECT_EQ(cache.stats().fills, uint64_t{1});
}

BTEST(Cache, FillRefusesOlderEpochOfSameGeneration) {
  ObjectCache cache(1 << 20);
  cache.fill("k", {9, 5}, 1, make_bytes(64, 5), lease(60'000));
  cache.fill("k", {9, 3}, 2, make_bytes(64, 3), lease(60'000));  // stale racer loses
  auto hit = cache.lookup_validated("k", {9, 5});
  BT_ASSERT(hit.outcome == ObjectCache::Outcome::kHit);
  BT_EXPECT_EQ(hit.content_crc, 1u);
}

BTEST(Cache, CapacityEvictionIsSegmented) {
  // One shard (tiny capacity), 4 KiB budget: hot entries promoted to the
  // protected segment must survive a probation scan that evicts cold ones.
  ObjectCache cache(4 << 10);
  const ObjectVersion v{1, 1};
  cache.fill("hot", v, 1, make_bytes(1 << 10, 1), lease(60'000));
  // Second touch promotes "hot" into protected.
  BT_EXPECT(cache.lookup_validated("hot", v).outcome == ObjectCache::Outcome::kHit);
  for (int i = 0; i < 16; ++i)
    cache.fill("scan/" + std::to_string(i), v, 1, make_bytes(1 << 10, uint8_t(i)), lease(60'000));
  const auto stats = cache.stats();
  BT_EXPECT(stats.evictions > 0);
  BT_EXPECT(stats.bytes <= 4 << 10);
  BT_EXPECT(cache.lookup_validated("hot", v).outcome == ObjectCache::Outcome::kHit);
}

BTEST(Cache, OversizedObjectsAreRefused) {
  ObjectCache cache(64 << 10, /*max_object_bytes=*/8 << 10);
  cache.fill("big", {1, 1}, 1, make_bytes(16 << 10, 1), lease(60'000));
  BT_EXPECT_EQ(cache.stats().fills, uint64_t{0});
  BT_EXPECT_EQ(cache.stats().bytes, uint64_t{0});
}

BTEST(Cache, LeaseExpiryDemandsRevalidation) {
  ObjectCache cache(1 << 20);
  cache.fill("k", {3, 4}, 9, make_bytes(256, 1), lease(0));  // born expired
  auto hit = cache.lookup("k");
  BT_ASSERT(hit.outcome == ObjectCache::Outcome::kExpired);
  // Matching revalidation renews; the next lookup serves.
  cache.renew("k", {3, 4}, lease(60'000));
  BT_EXPECT(cache.lookup("k").outcome == ObjectCache::Outcome::kHit);
  // Mismatching revalidation drops the entry.
  cache.renew("k", {3, 9}, lease(60'000));
  BT_EXPECT(cache.lookup("k").outcome == ObjectCache::Outcome::kMiss);
  BT_EXPECT_EQ(cache.stats().stale_rejects, uint64_t{1});
}

// ---- end-to-end: embedded cluster, direct-validated coherence --------------

BTEST(Cache, EmbeddedHitsServeWithoutWorkerOps) {
  client::EmbeddedCluster cluster(client::EmbeddedClusterOptions::simple(2, 32 << 20));
  BT_ASSERT(cluster.start() == ErrorCode::OK);
  auto c = cluster.make_client(cached_options(8 << 20));
  const auto data = pattern(64 << 10, 42);
  BT_ASSERT(c->put("hot", data.data(), data.size()) == ErrorCode::OK);
  std::vector<uint8_t> out(data.size());
  // First read misses and fills; the next ones hit.
  for (int i = 0; i < 5; ++i) {
    auto got = c->get_into("hot", out.data(), out.size());
    BT_ASSERT_OK(got);
    BT_EXPECT_EQ(got.value(), data.size());
    BT_EXPECT(out == data);
  }
  const auto stats = c->cache_stats();
  BT_EXPECT_EQ(stats.fills, uint64_t{1});
  BT_EXPECT(stats.hits >= 4);
  // get() (allocating variant) also serves from the same entry.
  auto whole = c->get("hot");
  BT_ASSERT_OK(whole);
  BT_EXPECT(whole.value() == data);
  cluster.stop();
}

BTEST(Cache, InvalidationOnOverwriteRemoveAndGc) {
  client::EmbeddedClusterOptions opts = client::EmbeddedClusterOptions::simple(2, 32 << 20);
  client::EmbeddedCluster cluster(opts);
  BT_ASSERT(cluster.start() == ErrorCode::OK);
  auto reader = cluster.make_client(cached_options(8 << 20));
  auto writer = cluster.make_client();  // uncached second client

  const auto v1 = pattern(32 << 10, 1), v2 = pattern(32 << 10, 2);
  BT_ASSERT(writer->put("k", v1.data(), v1.size()) == ErrorCode::OK);
  BT_EXPECT(reader->get("k").value() == v1);       // fill
  BT_EXPECT(reader->get("k").value() == v1);       // hit

  // Overwrite (remove + re-put) by ANOTHER client: the very next read must
  // see the new bytes — the version check makes stale structurally
  // impossible, no grace period.
  BT_ASSERT(writer->remove("k") == ErrorCode::OK);
  BT_ASSERT(writer->put("k", v2.data(), v2.size()) == ErrorCode::OK);
  BT_EXPECT(reader->get("k").value() == v2);
  BT_EXPECT(reader->cache_stats().stale_rejects >= 1);

  // Remove: the cached bytes must not resurrect the object.
  BT_ASSERT(writer->remove("k") == ErrorCode::OK);
  BT_EXPECT(!reader->get("k").ok());

  // TTL GC (the eviction-shaped deletion a client never asked for): cached
  // bytes must not outlive the object.
  WorkerConfig wc;
  wc.replication_factor = 1;
  wc.ttl_ms = 1;
  BT_ASSERT(writer->put("ttl", v1.data(), v1.size(), wc) == ErrorCode::OK);
  BT_EXPECT(reader->get("ttl").value() == v1);
  std::this_thread::sleep_for(std::chrono::milliseconds(10));
  cluster.keystone().run_gc_once();
  BT_EXPECT(!reader->get("ttl").ok());
  cluster.stop();
}

BTEST(Cache, InvalidationOnWatermarkEviction) {
  // Keystone watermark eviction (delete-shaped, no client asked for it):
  // cached bytes of an evicted object must not serve once it is gone.
  auto opts = client::EmbeddedClusterOptions::simple(1, 512 << 10);
  opts.keystone.high_watermark = 0.5;
  opts.keystone.eviction_ratio = 0.2;
  client::EmbeddedCluster cluster(opts);
  BT_ASSERT(cluster.start() == ErrorCode::OK);
  auto c = cluster.make_client(cached_options(8 << 20));
  WorkerConfig wc;
  wc.replication_factor = 1;
  wc.max_workers_per_copy = 1;
  std::vector<std::vector<uint8_t>> datas;
  for (int i = 0; i < 5; ++i) {  // 5 x 64 KiB = 62% of the pool, > watermark
    datas.push_back(pattern(64 << 10, static_cast<uint8_t>(i)));
    BT_ASSERT(c->put("ev/" + std::to_string(i), datas[i].data(), datas[i].size(), wc) ==
              ErrorCode::OK);
    BT_EXPECT(c->get("ev/" + std::to_string(i)).value() == datas[i]);  // fill
    std::this_thread::sleep_for(std::chrono::milliseconds(2));  // LRU order
  }
  cluster.keystone().run_health_check_once();
  BT_ASSERT(cluster.keystone().counters().evicted.load() > 0);
  size_t evicted_seen = 0;
  for (int i = 0; i < 5; ++i) {
    auto got = c->get("ev/" + std::to_string(i));
    if (got.ok()) {
      BT_EXPECT(got.value() == datas[i]);  // survivors still verify
    } else {
      // Evicted: the cached bytes must NOT have resurrected the object.
      BT_EXPECT(got.error() == ErrorCode::OBJECT_NOT_FOUND);
      ++evicted_seen;
    }
  }
  BT_EXPECT(evicted_seen > 0);
  cluster.stop();
}

BTEST(Cache, InvalidationAfterRepairRewrite) {
  // Repair rewrites a replica after worker death: the epoch bump must force
  // cached readers to revalidate (and the refreshed read must verify).
  auto opts = client::EmbeddedClusterOptions::simple(3, 32 << 20);
  opts.use_coordinator = false;  // direct feed: kill_worker drives repair
  client::EmbeddedCluster cluster(opts);
  BT_ASSERT(cluster.start() == ErrorCode::OK);
  auto c = cluster.make_client(cached_options(8 << 20));
  const auto data = pattern(64 << 10, 7);
  WorkerConfig wc;
  wc.replication_factor = 2;
  wc.max_workers_per_copy = 1;
  BT_ASSERT(c->put("rep", data.data(), data.size(), wc) == ErrorCode::OK);
  BT_EXPECT(c->get("rep").value() == data);  // fill
  const auto placements = cluster.keystone().get_workers("rep");
  BT_ASSERT_OK(placements);
  BT_ASSERT(!placements.value().empty());
  BT_ASSERT(!placements.value().front().shards.empty());
  const NodeId victim = placements.value().front().shards.front().worker_id;
  size_t victim_idx = 0;
  for (size_t i = 0; i < cluster.worker_count(); ++i) {
    if (cluster.worker_alive(i) && cluster.worker(i).config().worker_id == victim)
      victim_idx = i;
  }
  cluster.kill_worker(victim_idx);  // synchronously triggers repair
  const auto before = c->cache_stats();
  auto after_repair = c->get("rep");
  BT_ASSERT_OK(after_repair);
  BT_EXPECT(after_repair.value() == data);
  // The repair's epoch bump rejected the resident entry: no stale serve.
  BT_EXPECT(c->cache_stats().stale_rejects > before.stale_rejects);
  cluster.stop();
}

BTEST(Cache, ConcurrentReadersDuringInvalidationNeverTear) {
  client::EmbeddedCluster cluster(client::EmbeddedClusterOptions::simple(2, 64 << 20));
  BT_ASSERT(cluster.start() == ErrorCode::OK);
  auto writer = cluster.make_client();
  const size_t n = 32 << 10;
  const auto a = std::vector<uint8_t>(n, 0xAA), b = std::vector<uint8_t>(n, 0xBB);
  BT_ASSERT(writer->put("flip", a.data(), n) == ErrorCode::OK);

  std::atomic<bool> stop{false};
  std::atomic<bool> torn{false};
  std::vector<std::thread> readers;
  for (int t = 0; t < 3; ++t) {
    readers.emplace_back([&, t] {
      auto c = cluster.make_client(cached_options(4 << 20));
      std::vector<uint8_t> out(n);
      (void)t;
      while (!stop.load()) {
        auto got = c->get_into("flip", out.data(), out.size());
        if (!got.ok()) continue;  // overwrite gap (removed, not yet re-put)
        // Every successful read must be ENTIRELY one version: a mixed
        // buffer means an invalidation tore a concurrent cached serve.
        // (A third byte value — e.g. 0x00 from an unwritten extent — once
        // meant a PENDING object's placements were served; the diagnostic
        // names the bytes so the next regression is attributable.)
        const uint8_t first = out[0];
        if (first != 0xAA && first != 0xBB) {
          std::printf("        torn: first byte 0x%02x (size %llu)\n", first,
                      (unsigned long long)got.value());
          torn.store(true);
        }
        for (size_t i = 1; i < n; ++i) {
          if (out[i] != first) {
            std::printf("        torn: out[0]=0x%02x out[%zu]=0x%02x\n", first, i, out[i]);
            torn.store(true);
            break;
          }
        }
      }
    });
  }
  for (int round = 0; round < 40; ++round) {
    const auto& next = (round & 1) ? b : a;
    (void)writer->remove("flip");  // round 0: nothing to remove yet
    BT_ASSERT(writer->put("flip", next.data(), n) == ErrorCode::OK);
  }
  stop.store(true);
  for (auto& th : readers) th.join();
  BT_EXPECT(!torn.load());
  cluster.stop();
}

BTEST(Cache, ClientCapacityEvictionUnderTinyBudget) {
  client::EmbeddedCluster cluster(client::EmbeddedClusterOptions::simple(2, 64 << 20));
  BT_ASSERT(cluster.start() == ErrorCode::OK);
  // 128 KiB cache, 10 x 64 KiB objects: at most two resident at a time.
  auto c = cluster.make_client(cached_options(128 << 10));
  std::vector<std::vector<uint8_t>> datas;
  for (int i = 0; i < 10; ++i) {
    datas.push_back(pattern(64 << 10, static_cast<uint8_t>(i)));
    BT_ASSERT(c->put("obj/" + std::to_string(i), datas[i].data(), datas[i].size()) ==
              ErrorCode::OK);
  }
  for (int pass = 0; pass < 3; ++pass) {
    for (int i = 0; i < 10; ++i) {
      auto got = c->get("obj/" + std::to_string(i));
      BT_ASSERT_OK(got);
      BT_EXPECT(got.value() == datas[i]);
    }
  }
  const auto stats = c->cache_stats();
  BT_EXPECT(stats.evictions > 0);
  BT_EXPECT(stats.bytes <= 128 << 10);
  cluster.stop();
}

// ---- end-to-end: lease + watch coherence (the remote-client path) ----------

BTEST(Cache, LeaseModeWatchInvalidationAndSeveredFallback) {
  // Embedded cluster, but the caching client is FORCED onto the remote
  // coherence path: keystone-granted leases + the coordinator invalidation
  // watch — hermetic coverage of exactly what a remote client runs.
  auto opts = client::EmbeddedClusterOptions::simple(2, 32 << 20);
  opts.keystone.cache_lease_ms = 150;  // short lease: the severed bound below
  client::EmbeddedCluster cluster(opts);
  BT_ASSERT(cluster.start() == ErrorCode::OK);

  client::ClientOptions copts = cached_options(8 << 20);
  copts.cache_force_lease_mode = true;
  copts.cache_coordinator = cluster.coordinator_shared();
  copts.cluster_id = opts.keystone.cluster_id;
  auto reader = cluster.make_client(copts);
  auto writer = cluster.make_client();

  const auto v1 = pattern(32 << 10, 1), v2 = pattern(32 << 10, 2),
             v3 = pattern(32 << 10, 3);
  BT_ASSERT(writer->put("k", v1.data(), v1.size()) == ErrorCode::OK);
  BT_EXPECT(reader->get("k").value() == v1);  // fill under lease
  BT_EXPECT(reader->get("k").value() == v1);  // hit within lease
  BT_EXPECT(reader->cache_stats().hits >= 1);

  // Overwrite with the watch LIVE: the MemCoordinator delivers the remove's
  // invalidation before the writer's call returns, so the next read is
  // fresh even though the reader's lease had not expired.
  BT_ASSERT(writer->remove("k") == ErrorCode::OK);
  BT_ASSERT(writer->put("k", v2.data(), v2.size()) == ErrorCode::OK);
  BT_EXPECT(reader->get("k").value() == v2);
  BT_EXPECT(reader->cache_stats().invalidations >= 1);

  // Sever the watch stream mid-flight: entries degrade to their lease
  // deadline and every hit must revalidate — the next read pays one control
  // RTT, matches the version, and serves the cached bytes.
  reader->sever_cache_watch_for_test();
  const auto before = reader->cache_stats();
  BT_EXPECT(reader->get("k").value() == v2);
  BT_EXPECT(reader->cache_stats().lease_expiries > before.lease_expiries);

  // Overwrite with the stream severed: within the (renewed) lease the
  // reader may serve v2, but past the lease deadline the revalidation MUST
  // observe the new version — the lease-expiry bound, honored.
  BT_ASSERT(writer->remove("k") == ErrorCode::OK);
  BT_ASSERT(writer->put("k", v3.data(), v3.size()) == ErrorCode::OK);
  std::this_thread::sleep_for(std::chrono::milliseconds(200));  // > lease TTL
  BT_EXPECT(reader->get("k").value() == v3);
  BT_EXPECT(reader->cache_stats().stale_rejects >= 1);
  cluster.stop();
}

BTEST(Cache, LeaseOnlyClientHonorsExpiryBoundWithoutAnyWatch) {
  // No coordinator handle at all (the remote-client-without-bb-coord
  // shape): coherence rests entirely on lease expiry + revalidation.
  auto opts = client::EmbeddedClusterOptions::simple(2, 32 << 20);
  opts.keystone.cache_lease_ms = 100;
  client::EmbeddedCluster cluster(opts);
  BT_ASSERT(cluster.start() == ErrorCode::OK);
  client::ClientOptions copts = cached_options(8 << 20);
  copts.cache_force_lease_mode = true;  // and no cache_coordinator
  auto reader = cluster.make_client(copts);
  auto writer = cluster.make_client();

  const auto v1 = pattern(16 << 10, 1), v2 = pattern(16 << 10, 2);
  BT_ASSERT(writer->put("k", v1.data(), v1.size()) == ErrorCode::OK);
  BT_EXPECT(reader->get("k").value() == v1);
  BT_ASSERT(writer->remove("k") == ErrorCode::OK);
  BT_ASSERT(writer->put("k", v2.data(), v2.size()) == ErrorCode::OK);
  std::this_thread::sleep_for(std::chrono::milliseconds(150));  // > lease
  BT_EXPECT(reader->get("k").value() == v2);
  cluster.stop();
}
