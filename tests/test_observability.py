"""Observability surface through the Python bindings: real latency
histograms, trace spans, and the flight recorder (ISSUE 10)."""

from __future__ import annotations

from typing import Any

from blackbird_tpu import Client, EmbeddedCluster


def _series(histograms: list[dict[str, Any]], family: str,
            label_value: str | None = None) -> list[dict[str, Any]]:
    return [
        h for h in histograms
        if h["family"] == family and
        (label_value is None or h["label_value"] == label_value)
    ]


def test_histograms_and_lane_counter_summaries() -> None:
    with EmbeddedCluster(workers=2, pool_bytes=16 << 20) as cluster:
        client = cluster.client()
        payload = b"x" * 65536
        for i in range(8):
            client.put(f"obs/{i}", payload)
            assert client.get(f"obs/{i}") == payload

        hists = Client.histograms()
        gets = _series(hists, "btpu_op_duration_us", "get")
        assert gets and gets[0]["count"] >= 8
        assert gets[0]["p99_us"] >= gets[0]["p50_us"] > 0
        # Buckets are non-cumulative and sum to the count.
        assert sum(b["n"] for b in gets[0]["buckets"]) == gets[0]["count"]
        # Put rode one of the put families (inline/slot/placed by size).
        puts = [h for h in _series(hists, "btpu_op_duration_us")
                if h["label_value"].startswith("put")]
        assert sum(h["count"] for h in puts) >= 8

        lanes = Client.lane_counters()
        assert lanes["hist_get_count"] == gets[0]["count"]
        assert lanes["hist_get_p99_us"] >= lanes["hist_get_p50_us"] > 0
        assert lanes["flight_events"] > 0
        assert lanes["trace_spans"] > 0


def test_trace_spans_stitch_by_trace_id() -> None:
    with EmbeddedCluster(workers=1, pool_bytes=8 << 20) as cluster:
        client = cluster.client()
        client.put("obs/traced", b"y" * 4096)
        assert client.get("obs/traced") == b"y" * 4096

        spans = Client.trace_spans()
        assert spans, "span ring empty after traced ops"
        roots = [s for s in spans if s["name"] == "get"]
        assert roots, f"no root get span in {[s['name'] for s in spans][:10]}"
        trace_id = int(roots[-1]["trace"], 16)
        assert trace_id != 0
        one = Client.trace_spans(trace_id)
        assert one and all(s["trace"] == roots[-1]["trace"] for s in one)
        for s in one:
            assert s["dur_us"] >= 0 and s["start_us"] > 0 and s["pid"] > 0


def test_flight_events_flow_and_tracing_switch() -> None:
    with EmbeddedCluster(workers=1, pool_bytes=8 << 20) as cluster:
        client = cluster.client()
        client.put("obs/flight", b"z" * 1024)
        events = Client.flight_events()
        assert events
        assert any(e["ev"] == "op_end" for e in events)

        # The master switch stops new events; re-enabling resumes.
        Client.set_tracing(False)
        try:
            before = Client.lane_counters()["flight_events"]
            client.put("obs/off", b"q" * 512)
            assert Client.lane_counters()["flight_events"] == before
        finally:
            Client.set_tracing(True)
        client.put("obs/on", b"r" * 512)
        assert any(e["ev"] == "op_end" for e in Client.flight_events())
