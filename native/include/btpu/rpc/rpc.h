// Keystone RPC protocol: opcodes map 1:1 to KeystoneService methods.
//
// Versioning stance: wire structs are NOT cross-version stable (no
// negotiation — matching the reference's struct_pack RPC, which had none
// either). Upgrades are atomic per cluster: restart keystones and clients
// together. Durable records are the exception — they outlive binaries, so
// keystone.cpp keeps legacy decode fallbacks for them.
//
// Parity target: reference include/blackbird/rpc/rpc_service.h:28-274 — 14
// rpc_* handlers over YLT coro_rpc (rpc_service.cpp:360-385). Framing is the
// shared net.h frame: [u32 len][u8 opcode][wire-encoded struct]; responses
// reuse the request opcode.
#pragma once

#include <cstdint>

namespace btpu::rpc {

enum class Method : uint8_t {
  kObjectExists = 1,
  kGetWorkers = 2,
  kPutStart = 3,
  kPutComplete = 4,
  kPutCancel = 5,
  kRemoveObject = 6,
  kRemoveAllObjects = 7,
  kGetClusterStats = 8,
  kGetViewVersion = 9,
  kBatchObjectExists = 10,
  kBatchGetWorkers = 11,
  kBatchPutStart = 12,
  kBatchPutComplete = 13,
  kBatchPutCancel = 14,
  kPing = 15,
  kDrainWorker = 16,
  kListObjects = 17,
};

}  // namespace btpu::rpc
