// HBM provider: the C ABI seam between the native worker and the device
// runtime that actually owns TPU HBM.
//
// On real TPU VMs the provider is implemented by the Python/JAX layer
// (blackbird_tpu/hbm.py registers ctypes callbacks: regions are device
// buffers, read/write are host<->device transfers). Tests and CPU-only dev
// use the built-in emulated provider (host memory). This mirrors the
// north-star's "TPU-HBM allocator behind the same region-descriptor
// contract" (BASELINE.json) without pretending libtpu exposes raw one-sided
// DMA to third parties.
//
// All functions return 0 on success, nonzero on failure.
#pragma once

#include <cstdint>

extern "C" {

typedef struct BtpuHbmProviderV1 {
  void* ctx;
  // Allocates a device region of `size` bytes on `device_id` ("tpu:0").
  int (*alloc_region)(void* ctx, const char* device_id, uint64_t size, uint64_t* out_region_id);
  int (*free_region)(void* ctx, uint64_t region_id);
  // Host -> device and device -> host byte transfers within a region.
  int (*write)(void* ctx, uint64_t region_id, uint64_t offset, const void* src, uint64_t len);
  int (*read)(void* ctx, uint64_t region_id, uint64_t offset, void* dst, uint64_t len);
  // Bytes of free HBM remaining on the device (best effort; 0 = unknown).
  uint64_t (*available)(void* ctx, const char* device_id);
} BtpuHbmProviderV1;

// Installs the process-wide provider (Python calls this through ctypes).
// Passing NULL restores the built-in emulated provider.
void btpu_register_hbm_provider(const BtpuHbmProviderV1* provider);

}  // extern "C"

namespace btpu::storage {
// Returns the active provider (emulated one if none registered).
const BtpuHbmProviderV1& hbm_provider();
// True when the active provider is the built-in host-memory emulation.
bool hbm_provider_is_emulated();
}  // namespace btpu::storage
