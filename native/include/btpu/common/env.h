// Shared environment-knob parsing. Every operator override in the native
// tree reads through these, so empty-string / garbage handling stays
// uniform: unset OR empty falls back, non-numeric parses as 0 (strtoul
// semantics) — a deliberate "explicitly off" escape hatch.
//
// This file is the ONLY place in the native tree allowed to call getenv
// (scripts/btpu_lint.py rule env-via-env-h; native/tests are exempt because
// they set/save/restore variables to exercise the knobs themselves).
#pragma once

#include <cstdint>
#include <cstdlib>
#include <cstring>

namespace btpu {

inline uint64_t env_u64(const char* name, uint64_t fallback) {
  const char* v = std::getenv(name);
  if (!v || !v[0]) return fallback;
  return std::strtoull(v, nullptr, 10);
}

inline uint32_t env_u32(const char* name, uint32_t fallback) {
  const char* v = std::getenv(name);
  if (!v || !v[0]) return fallback;
  return static_cast<uint32_t>(std::strtoul(v, nullptr, 10));
}

// String knob: unset OR empty yields the fallback (which may be nullptr for
// "no override"). The returned pointer aliases the environment — treat it
// as borrowed, same as getenv itself.
inline const char* env_str(const char* name, const char* fallback = nullptr) {
  const char* v = std::getenv(name);
  return (v && v[0]) ? v : fallback;
}

// Boolean knob: unset/empty falls back; "0", "false", "off", "no" are
// false; anything else present is true (so BTPU_FOO=1 and BTPU_FOO=on both
// enable).
inline bool env_bool(const char* name, bool fallback) {
  const char* v = std::getenv(name);
  if (!v || !v[0]) return fallback;
  return !(std::strcmp(v, "0") == 0 || std::strcmp(v, "false") == 0 ||
           std::strcmp(v, "off") == 0 || std::strcmp(v, "no") == 0);
}

}  // namespace btpu
