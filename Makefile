# GNU-make fallback build — mirrors CMakeLists.txt for containers that ship
# only gcc/make (no cmake/ninja). `blackbird_tpu.native.build_native()` uses
# this automatically when cmake is missing; artifacts land in build/ exactly
# where the cmake build puts them, so nothing downstream cares which ran.
#
#   make -j$(nproc)            # libbtpu.so + btpu_tests + bb-* executables
#   make examples              # example binaries (not needed by tests/bench)

CXX      ?= g++
BUILD    ?= build
# Warning hygiene (docs/CORRECTNESS.md): the whole tree is -Werror, and
# -Werror=unused-result is the teeth behind the BTPU_NODISCARD /
# [[nodiscard]]-typed ErrorCode/Result sweep — a dropped error is a compile
# error. Wire/decoder TUs additionally build with -Wconversion (see
# WCONV_SRCS below): silent narrowing in a length/offset computation is
# exactly how bounds checks rot.
WARNFLAGS := -Wall -Wextra -Wno-unused-parameter -Werror -Werror=unused-result
CXXFLAGS ?= -std=c++20 -O2 -g -fPIC $(WARNFLAGS) \
            -Inative/include -pthread
# -lrt: shm_open/shm_unlink live in librt on pre-2.34 glibc
LDFLAGS  ?= -pthread -lrt

LIB_SRCS := $(wildcard native/src/*/*.cpp)
LIB_OBJS := $(patsubst %.cpp,$(BUILD)/obj/%.o,$(LIB_SRCS))
TEST_SRCS := $(wildcard native/tests/*.cpp)
TEST_OBJS := $(patsubst %.cpp,$(BUILD)/obj/%.o,$(TEST_SRCS))
EXE_SRCS := $(wildcard native/exe/*.cpp)
EXES     := $(patsubst native/exe/%.cpp,$(BUILD)/%,$(EXE_SRCS))
EXAMPLE_SRCS := $(wildcard examples/*.cpp)
EXAMPLES := $(patsubst examples/%.cpp,$(BUILD)/example_%,$(EXAMPLE_SRCS))

HDRS := $(shell find native/include native/src native/exe native/fuzz -name '*.h')

.PHONY: all native examples clean tsan asan sched lint check wire-golden \
        capi-golden fuzz fuzz-replay
all: native
native: $(BUILD)/libbtpu.so $(BUILD)/btpu_tests $(EXES)
examples: $(EXAMPLES)

# ---- sanitizer matrix (docs/CORRECTNESS.md) --------------------------------
# Each sanitizer rebuilds into its own object tree (sanitized objects are
# ABI-incompatible with the normal build) and runs the FULL native suite by
# default. bb-soak is built in both trees so the soak harness can run
# sanitized too. main.cpp compiles in exe/tsan_rma_suppression.h — the only
# RACE suppression in the tree (the MODELED one-sided-RMA race of the LOCAL
# transport: a reader racing a remote write is emulated hardware behavior,
# discarded through epoch/CRC gates downstream) — plus
# exe/tsan_clockwait_shim.h, an interceptor shim for gcc-10 libtsan's
# missing pthread_cond_clockwait (see docs/CORRECTNESS.md).
#
#   make tsan                      # ThreadSanitizer, all suites + bb-soak build
#   make asan                      # Address+UB(+Leak) sanitizers, all suites
#   TSAN_FILTERS="Cache Transport" make tsan    # narrow to suites
TSAN_BUILD := $(BUILD)/tsan
TSAN_FILTERS ?=
# Schedule-exploration hooks (btpu/common/sched.h) ride every sanitizer
# tree: the Sched/SchedDfs/SchedMutants suites need them, and for all other
# suites a disarmed hook is one relaxed load per lock op. The NORMAL build
# deliberately does NOT define this — bench.py's cached-get guard proves the
# release hot path carries zero sched cost because the hooks don't exist.
SCHED_FLAGS := -DBTPU_SCHED=1
# Pool sanitizer (btpu/common/poolsan.h): shadow extent state, generation
# checks, red zones, quarantine — armed by default in every sanitizer tree
# (env dial BTPU_POOLSAN=0|1), compiled OUT of the release build so the
# hot-path resolve is a pure bounds proof (bench.py "poolsan overhead"
# guard row proves the release cost).
POOLSAN_FLAGS := -DBTPU_POOLSAN=1
# AddressSanitizer + UndefinedBehaviorSanitizer; LeakSanitizer rides along
# with ASan on Linux. -fno-sanitize-recover turns every UB finding into a
# hard failure instead of a log line.
ASAN_BUILD := $(BUILD)/asan
ASAN_FILTERS ?=

# One protocol for every sanitizer leg: $(call sanitizer_run,name,builddir,
# sanitize-flags,filters). Adding a suite/exe or changing the run loop
# happens HERE, once.
define sanitizer_run
	$(MAKE) BUILD=$(2) \
	  CXXFLAGS="-std=c++20 -O1 -g -fPIC $(WARNFLAGS) \
	            -Inative/include -pthread $(3)" \
	  LDFLAGS="-pthread -lrt $(3)" \
	  $(2)/libbtpu.so $(2)/btpu_tests $(2)/bb-soak
	@set -e; if [ -z "$(strip $(4))" ]; then \
	  echo "== $(1): all suites =="; \
	  $(2)/btpu_tests; \
	else \
	  for f in $(4); do \
	    echo "== $(1): $$f =="; \
	    $(2)/btpu_tests --filter=$$f; \
	  done; \
	fi
endef

comma := ,
ASAN_FLAGS := -fsanitize=address$(comma)undefined -fno-sanitize-recover=all
tsan:
	$(call sanitizer_run,tsan,$(TSAN_BUILD),-fsanitize=thread $(SCHED_FLAGS) $(POOLSAN_FLAGS),$(TSAN_FILTERS))
asan:
	$(call sanitizer_run,asan,$(ASAN_BUILD),$(ASAN_FLAGS) $(SCHED_FLAGS) $(POOLSAN_FLAGS),$(ASAN_FILTERS))

# ---- schedule-exploration campaign (docs/CORRECTNESS.md §10) ---------------
# Builds the asan tree (which carries the sched hooks) and runs the full
# schedule-exploration surface at campaign budget: seeded PCT sweeps over
# the Sched fixtures, the exhaustive DFS model check of the lock-free
# kernels, and the planted-mutant matrix. Knobs:
#   BTPU_SCHED_SEEDS          seeds per fixture          (default here: 200)
#   BTPU_SCHED_MUTANT_BUDGET  seed budget per planted mutant (default: 150)
#   BTPU_SCHED_SEED           pin ONE seed — the replay path
sched:
	$(MAKE) BUILD=$(ASAN_BUILD) \
	  CXXFLAGS="-std=c++20 -O1 -g -fPIC $(WARNFLAGS) \
	            -Inative/include -pthread $(ASAN_FLAGS) $(SCHED_FLAGS) $(POOLSAN_FLAGS)" \
	  LDFLAGS="-pthread -lrt $(ASAN_FLAGS)" \
	  $(ASAN_BUILD)/libbtpu.so $(ASAN_BUILD)/btpu_tests
	env BTPU_SCHED_SEEDS="$${BTPU_SCHED_SEEDS:-200}" $(ASAN_BUILD)/btpu_tests --filter=Sched

# ---- hostile-input fuzz gate (docs/CORRECTNESS.md) -------------------------
# `make fuzz` drives every wire-decode surface with hostile bytes: libFuzzer
# harnesses under clang (exploration), and ALWAYS the deterministic
# corpus-replay + mutation sweep (reproducible everywhere, asan+ubsan
# instrumented). Knobs: BTPU_FUZZ_EXECS (per-target executions for the
# deterministic leg), BTPU_FUZZ_TIME (seconds per libFuzzer target).
fuzz:
	scripts/fuzz.sh

# Internal: the asan+ubsan-instrumented replay binary (also the seed-corpus
# generator: build/asan/btpu_fuzz_replay --gen-seeds native/fuzz/corpus).
fuzz-replay:
	$(MAKE) BUILD=$(ASAN_BUILD) \
	  CXXFLAGS="-std=c++20 -O1 -g -fPIC $(WARNFLAGS) \
	            -Inative/include -pthread $(ASAN_FLAGS) $(SCHED_FLAGS) $(POOLSAN_FLAGS)" \
	  LDFLAGS="-pthread -lrt $(ASAN_FLAGS)" \
	  $(ASAN_BUILD)/btpu_fuzz_replay

# ---- static gates ----------------------------------------------------------
# clang -Wthread-safety sweep over every native source (the machine check
# behind the GUARDED_BY/REQUIRES annotations) + python bytecode lint.
# Degrades to a skip-with-notice when clang is not installed.
lint:
	scripts/lint.sh

# Regenerate the wire-layout golden table (append-only changes ONLY — the
# diff of wire_golden.txt is the wire-compat review).
# Dump to a temp file and move into place only on success: a crashing
# binary must not clobber the checked-in table.
wire-golden: $(BUILD)/btpu_tests
	$(BUILD)/btpu_tests --dump-wire-golden > native/tests/wire_golden.txt.tmp
	mv native/tests/wire_golden.txt.tmp native/tests/wire_golden.txt
	@echo "wrote native/tests/wire_golden.txt"

# Regenerate the FFI golden manifest (native/tests/capi_golden.txt) from the
# headers — the diff is the ABI review, like wire-golden above. Purely
# textual (scripts/capi_check.py parses the headers); no build needed.
# Temp-file dance for the same reason as wire-golden.
capi-golden:
	python3 scripts/capi_check.py --dump-golden > native/tests/capi_golden.txt.tmp
	mv native/tests/capi_golden.txt.tmp native/tests/capi_golden.txt
	@echo "wrote native/tests/capi_golden.txt"

# ---- the one-command correctness gate --------------------------------------
# tier-1 pytest + lint + full native suite + asan + tsan. Every PR runs this.
check:
	scripts/check.sh

# Wire/decoder TUs carry the extra -Wconversion hammer: these parse hostile
# bytes, where a u64->u32 narrowing in a length check is a security bug.
WCONV_SRCS := native/src/net/net.cpp native/src/net/uring_engine.cpp \
              native/src/rpc/rpc_client.cpp \
              native/src/rpc/rpc_server.cpp native/src/common/types.cpp \
              native/src/common/error.cpp native/src/common/deadline.cpp \
              native/src/keystone/keystone_persist.cpp \
              native/src/transport/tcp_transport.cpp \
              native/src/coord/mem_coordinator.cpp
$(patsubst %.cpp,$(BUILD)/obj/%.o,$(WCONV_SRCS)): WARN_EXTRA := -Wconversion

$(BUILD)/obj/%.o: %.cpp $(HDRS)
	@mkdir -p $(dir $@)
	$(CXX) $(CXXFLAGS) $(WARN_EXTRA) -c $< -o $@

$(BUILD)/libbtpu.so: $(LIB_OBJS)
	$(CXX) -shared $^ $(LDFLAGS) -o $@

$(BUILD)/btpu_tests: $(TEST_OBJS) $(BUILD)/libbtpu.so
	$(CXX) $(TEST_OBJS) -L$(BUILD) -lbtpu $(LDFLAGS) -Wl,-rpath,'$$ORIGIN' -o $@

$(BUILD)/%: $(BUILD)/obj/native/exe/%.o $(BUILD)/libbtpu.so
	$(CXX) $< -L$(BUILD) -lbtpu $(LDFLAGS) -Wl,-rpath,'$$ORIGIN' -o $@

$(BUILD)/btpu_fuzz_replay: $(BUILD)/obj/native/fuzz/fuzz_replay_main.o $(BUILD)/libbtpu.so
	$(CXX) $< -L$(BUILD) -lbtpu $(LDFLAGS) -Wl,-rpath,'$$ORIGIN' -o $@

$(BUILD)/example_%: $(BUILD)/obj/examples/%.o $(BUILD)/libbtpu.so
	$(CXX) $< -L$(BUILD) -lbtpu $(LDFLAGS) -Wl,-rpath,'$$ORIGIN' -o $@

clean:
	rm -rf $(BUILD)/obj $(BUILD)/libbtpu.so $(BUILD)/btpu_tests $(EXES) $(EXAMPLES)
