"""TPU pod/slice topology discovery.

Role parity: the north star replaces etcd-registered NIC endpoints with
placement driven by TPU topology (BASELINE.json). On TPU VMs jax exposes the
pod structure; here it is mapped onto the native TopoCoord scheme
{slice_id, host_id, chip_id} used by the allocator's slice-affinity ranking.
"""

from __future__ import annotations

from dataclasses import dataclass


@dataclass(frozen=True)
class TopoCoord:
    slice_id: int
    host_id: int
    chip_id: int


def discover() -> list[TopoCoord]:
    """One TopoCoord per addressable device, in jax.devices() order."""
    import jax

    coords: list[TopoCoord] = []
    for device in jax.devices():
        slice_id = getattr(device, "slice_index", 0) or 0
        host_id = getattr(device, "process_index", 0) or 0
        chip_id = getattr(device, "id", 0)
        coords.append(TopoCoord(slice_id, host_id, chip_id))
    return coords


def local_coord() -> TopoCoord:
    """Coordinate of this host (chip_id = -1 marks host memory)."""
    import jax

    devices = jax.local_devices()
    if not devices:
        return TopoCoord(0, 0, -1)
    first = devices[0]
    return TopoCoord(getattr(first, "slice_index", 0) or 0,
                     getattr(first, "process_index", 0) or 0, -1)


def worker_yaml_fields() -> dict[str, int]:
    """slice_id/host_id fields for a worker config on this host."""
    coord = local_coord()
    return {"slice_id": coord.slice_id, "host_id": coord.host_id}
