#!/usr/bin/env python3
"""Device-mesh object store walkthrough (runs on a CPU mesh or real TPUs).

One HBM pool per chip under the ICI transport: puts stripe across chips,
gets gather back, a killed worker triggers chip-to-chip repair through the
provider's device-to-device copy path, and a sharded JAX array checkpoints
into the same namespace.

    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \\
        python examples/device_mesh.py
"""

import os
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))  # repo root

import jax

if os.environ.get("JAX_PLATFORMS"):
    # Some images force a hardware platform from sitecustomize past the env
    # var; pin the config explicitly so the CPU-mesh invocation works.
    try:
        jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
    except Exception:  # noqa: BLE001
        pass
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from blackbird_tpu import EmbeddedCluster, StorageClass
from blackbird_tpu.checkpoint import load_sharded, save_sharded
from blackbird_tpu.hbm import JaxHbmProvider
from blackbird_tpu.native import TransportKind
from blackbird_tpu.parallel import make_mesh


def main() -> int:
    n = len(jax.devices())
    workers = max(4, n)  # single-chip boxes still get a multi-worker cluster
    print(f"{n} devices ({jax.devices()[0].platform}), {workers} workers")
    provider = JaxHbmProvider().register()
    try:
        with EmbeddedCluster(workers=workers, pool_bytes=16 << 20,
                             storage_class=StorageClass.HBM_TPU,
                             transport=TransportKind.ICI) as cluster:
            client = cluster.client()

            # Striped over the mesh; replicas land on disjoint workers.
            payload = np.random.default_rng(0).bytes(4 << 20)
            client.put("demo/blob", payload, replicas=2, max_workers=workers // 2)
            assert client.get("demo/blob") == payload
            for copy in client.placements("demo/blob"):
                chips = [s["location"]["device"] for s in copy["shards"]]
                print(f"copy {copy['copy_index']} on {chips}")

            # Kill a chip's worker: repair re-replicates device-to-device.
            cluster.kill_worker(0)
            deadline = time.monotonic() + 15
            while (cluster.counters()["objects_repaired"] < 1
                   and time.monotonic() < deadline):
                time.sleep(0.05)
            print(f"repaired={cluster.counters()['objects_repaired']} "
                  f"ici_copies={provider.copy_calls}")
            assert client.get("demo/blob") == payload

            # Sharded checkpoint into the same store (device tier).
            mesh = make_mesh(n)
            arr = jax.device_put(
                np.arange(n * 256, dtype=np.float32).reshape(n, 256),
                NamedSharding(mesh, P("workers", None)))
            save_sharded(client, "demo/ckpt", arr,
                         preferred_class=StorageClass.HBM_TPU)
            back = load_sharded(client, "demo/ckpt")
            np.testing.assert_array_equal(back, np.asarray(arr))
            print("checkpoint round-tripped through the device tier")
    finally:
        JaxHbmProvider.unregister()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
