#include "btpu/common/env.h"
#include "btpu/common/trace.h"

#include "btpu/common/thread_annotations.h"

#include <algorithm>
#include <cstdio>
#include <cstdlib>
#include <map>
#include <mutex>

namespace btpu::trace {

namespace {

constexpr size_t kReservoir = 4096;

struct SpanAccumulator {
  uint64_t count{0};
  double total_us{0};
  double max_us{0};
  std::vector<double> samples;  // ring of recent durations
  size_t next{0};

  void add(double us) {
    ++count;
    total_us += us;
    max_us = std::max(max_us, us);
    if (samples.size() < kReservoir) {
      samples.push_back(us);
    } else {
      samples[next] = us;
      next = (next + 1) % kReservoir;
    }
  }
};

struct Registry {
  Mutex mutex;
  std::map<std::string, SpanAccumulator, std::less<>> spans BTPU_GUARDED_BY(mutex);
  FILE* jsonl BTPU_GUARDED_BY(mutex){nullptr};

  Registry() {
    if (const char* path = env_str("BTPU_TRACE")) {
      jsonl = std::fopen(path, "a");
    }
  }

  static Registry& instance() {
    static Registry* r = new Registry;  // leaked: spans recorded at exit
    return *r;
  }
};

double percentile_of(std::vector<double>& sorted, double p) {
  if (sorted.empty()) return 0.0;
  const size_t idx =
      std::min(sorted.size() - 1, static_cast<size_t>(p * static_cast<double>(sorted.size())));
  return sorted[idx];
}

}  // namespace

void record(std::string_view name, double duration_us) {
  auto& reg = Registry::instance();
  MutexLock lock(reg.mutex);
  auto it = reg.spans.find(name);
  if (it == reg.spans.end()) {
    it = reg.spans.emplace(std::string(name), SpanAccumulator{}).first;
  }
  it->second.add(duration_us);
  if (reg.jsonl) {
    std::fprintf(reg.jsonl, "{\"span\":\"%.*s\",\"us\":%.2f}\n",
                 static_cast<int>(name.size()), name.data(), duration_us);
  }
}

std::vector<SpanStats> summary() {
  auto& reg = Registry::instance();
  MutexLock lock(reg.mutex);
  std::vector<SpanStats> out;
  out.reserve(reg.spans.size());
  for (auto& [name, acc] : reg.spans) {
    SpanStats stats;
    stats.name = name;
    stats.count = acc.count;
    stats.total_us = acc.total_us;
    stats.max_us = acc.max_us;
    auto sorted = acc.samples;
    std::sort(sorted.begin(), sorted.end());
    stats.p50_us = percentile_of(sorted, 0.50);
    stats.p99_us = percentile_of(sorted, 0.99);
    out.push_back(std::move(stats));
  }
  return out;
}

void reset() {
  auto& reg = Registry::instance();
  MutexLock lock(reg.mutex);
  reg.spans.clear();
}

}  // namespace btpu::trace
