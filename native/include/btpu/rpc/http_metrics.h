// Minimal HTTP/1.1 server exposing Prometheus text metrics, /healthz, and
// the observability debug endpoints:
//   /metrics       counters + gauges + REAL histograms (_bucket/_sum/_count)
//   /healthz       liveness probe
//   /debug/flight  flight-recorder dump (JSON lines, oldest first)
//   /debug/trace   span-ring dump (JSON lines); ?trace=<16-hex-id> filters
//                  to one trace — the endpoint bb-trace stitches from
//
// The keystone is OPTIONAL: a worker/coordinator process runs this server
// too (BTPU_OBS_PORT in bb-worker/bb-coord) and serves the process-wide
// sections — histograms, lane/robustness counters, flight, trace — without
// any control-plane state. That is what makes every hop of a distributed
// trace collectable over HTTP.
//
// Parity target: the reference runs a coro_http metrics server but never
// registers the /metrics route (rpc_service.cpp:387-390, README claims
// notwithstanding) — here it is real.
#pragma once

#include <atomic>
#include <functional>
#include <string>
#include <thread>

#include "btpu/net/net.h"

namespace btpu::keystone {
class KeystoneService;
}

namespace btpu::rpc {

class MetricsHttpServer {
 public:
  // service == nullptr: process-wide observability only (worker/coord
  // processes) — the keystone sections are simply omitted from /metrics.
  MetricsHttpServer(keystone::KeystoneService* service, std::string host, uint16_t port);
  MetricsHttpServer(keystone::KeystoneService& service, std::string host, uint16_t port)
      : MetricsHttpServer(&service, std::move(host), port) {}
  ~MetricsHttpServer();

  ErrorCode start();
  void stop();
  uint16_t port() const noexcept { return port_; }

  // Prometheus exposition text (exposed for tests — the /metrics
  // self-check test parses exactly this).
  std::string render_metrics() const;

 private:
  void accept_loop();

  keystone::KeystoneService* service_;
  std::string host_;
  uint16_t port_;
  net::Socket listener_;
  std::atomic<bool> running_{false};
  std::thread accept_thread_;
};

}  // namespace btpu::rpc
