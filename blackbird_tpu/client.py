"""Object client: put/get bytes or numpy arrays against a cluster."""

from __future__ import annotations

import ctypes
from typing import TYPE_CHECKING, Any, TypeAlias, cast

import numpy as np
import numpy.typing as npt

from blackbird_tpu import native
from blackbird_tpu.native import StorageClass, check, lib

if TYPE_CHECKING:
    from blackbird_tpu.cluster import EmbeddedCluster

# Accepted put() payloads; ndarray dtype is irrelevant (raw bytes move).
AnyArray: TypeAlias = "np.ndarray[Any, np.dtype[Any]]"
Buffer: TypeAlias = "bytes | bytearray | memoryview | AnyArray"

# Uninitialized bytes objects the C side fills in place: a fresh bytes of n
# NULs (bytes(n), create_string_buffer) costs a zero-fill pass PLUS the copy
# out — on 1 MiB objects that doubled end-to-end get latency. Writing into a
# just-created, never-exposed bytes object is the standard CPython C-API
# pattern (PyBytes_FromStringAndSize(NULL, n) then fill).
_PyBytes_FromStringAndSize = ctypes.pythonapi.PyBytes_FromStringAndSize
_PyBytes_FromStringAndSize.restype = ctypes.py_object
_PyBytes_FromStringAndSize.argtypes = [ctypes.c_char_p, ctypes.c_ssize_t]


def _uninit_bytes(n: int) -> bytes:
    return cast(bytes, _PyBytes_FromStringAndSize(None, n))


def _bytes_addr(b: bytes) -> ctypes.c_void_p:
    return ctypes.cast(ctypes.c_char_p(b), ctypes.c_void_p)


class AsyncBatch:
    """One in-flight async batch on the client op core.

    Returned by Client.get_many_async / put_many_async. The batch owns its
    item buffers (kept alive until the native side reports completion), so
    the caller only holds this object: poll done(), block on wait(), or call
    result() — which waits, raises on the first failed item (same contract
    as the sync batch calls), and for gets returns the bytes in key order.
    close() cancels a still-running batch and waits it out before freeing
    the native handle (buffer safety); dropping the last reference does the
    same via __del__."""

    def __init__(self, handle: int, keys: list[str],
                 buffers: list[bytes] | None, keep_alive: list[Any]) -> None:
        self._handle: int | None = handle
        self._keys = keys
        self._buffers = buffers  # get batches only; None for puts
        self._keep_alive = keep_alive

    def _live(self) -> int:
        if self._handle is None:
            raise RuntimeError("async batch is closed")
        return self._handle

    def done(self) -> bool:
        return bool(lib.btpu_async_batch_done(self._live()))

    def wait(self, timeout_ms: int = 0) -> bool:
        """Blocks until the batch completes; False on timeout (0 = forever;
        the batch keeps running after a timeout)."""
        return bool(lib.btpu_async_batch_wait(self._live(), timeout_ms))

    def cancel(self) -> None:
        """Best-effort: stages not yet run are skipped; items the op never
        reached raise OPERATION_CANCELLED from result()."""
        lib.btpu_async_batch_cancel(self._live())

    def result(self) -> list[bytes] | None:
        """Waits for completion, raises on the first failed item, and
        returns the fetched bytes in key order (None for put batches)."""
        handle = self._live()
        self.wait()
        n = len(self._keys)
        codes = (ctypes.c_int32 * n)()
        out_sizes = (ctypes.c_uint64 * n)()
        check(lib.btpu_async_batch_results(handle, codes, out_sizes), "async batch")
        for i, key in enumerate(self._keys):
            check(codes[i], f"async {key!r}")
        if self._buffers is None:
            return None
        return [b if out_sizes[i] == len(b) else b[: out_sizes[i]]
                for i, b in enumerate(self._buffers)]

    def close(self) -> None:
        if self._handle is not None:
            # The native free cancels + waits a still-running batch, so the
            # buffers this object keeps alive are safe to release after.
            lib.btpu_async_batch_free(self._handle)
            self._handle = None

    def __del__(self) -> None:
        self.close()


class Client:
    """put/get/exists/remove against an embedded or remote cluster.

    Parity surface: reference BlackbirdClient (blackbird_client.h:47-106) —
    connect/object_exists/put/get/remove — with numpy-friendly helpers.
    """

    def __init__(self, keystone_endpoint: str, *, verify: bool = True,
                 cache_bytes: int | None = None) -> None:
        """keystone_endpoint may be a comma-separated list ("host:a,host:b"):
        the first entry is the primary, the rest HA fallbacks the client
        rotates through on NOT_LEADER or connection failure.

        verify=False skips CRC verification on reads (and with it
        corrupt-replica failover / corrupt-shard reconstruction) — for
        latency-critical paths that rely on background scrub instead.

        cache_bytes arms the lease-coherent client object cache: repeated
        hot gets of unchanged objects are served from local memory with zero
        worker round trips, bounded-stale by the keystone-granted read lease
        and revalidated (one control RTT) at lease expiry. None reads the
        BTPU_CACHE_BYTES env var (unset/0 = off); see docs/OPERATIONS.md
        for sizing and lease tuning."""
        self._cluster_ref: EmbeddedCluster | None = None
        self._handle: int | None = lib.btpu_client_create_remote(
            keystone_endpoint.encode())
        if not self._handle:
            raise RuntimeError(f"cannot reach keystone at {keystone_endpoint}")
        if not verify:
            lib.btpu_client_set_verify(self._handle, 0)
        self._configure_cache(cache_bytes)

    def set_verify(self, verify: bool) -> None:
        """Toggle CRC verification on this client's reads (default on)."""
        lib.btpu_client_set_verify(self._handle, 1 if verify else 0)

    def _configure_cache(self, cache_bytes: int | None) -> None:
        import os

        if cache_bytes is None:
            cache_bytes = int(os.environ.get("BTPU_CACHE_BYTES", "0") or 0)
        if not cache_bytes:
            return
        # native.have(), not hasattr: the manifest says whether this build
        # can cache; asking for a cache it cannot provide must raise, not
        # silently serve uncached (docs/CORRECTNESS.md §11).
        if not native.have("btpu_client_cache_configure"):
            raise RuntimeError(
                "cache_bytes requested but this libbtpu build has no client "
                "object cache (btpu_client_cache_configure missing)")
        lib.btpu_client_cache_configure(self._handle, cache_bytes)

    def cache_stats(self) -> dict[str, int]:
        """Object-cache counters (all zero when the cache is off):
        hits/misses/fills, invalidations (watch/mutation-dropped entries),
        stale_rejects (hits refused because the object version moved),
        lease_expiries (hits that revalidated), evictions (capacity), and
        the resident bytes/entries."""
        out = (ctypes.c_uint64 * 9)()
        if native.have("btpu_client_cache_stats"):
            check(lib.btpu_client_cache_stats(self._handle, out), "cache_stats")
        keys = ("hits", "misses", "fills", "invalidations", "stale_rejects",
                "lease_expiries", "evictions", "bytes", "entries")
        return dict(zip(keys, (int(v) for v in out)))

    @classmethod
    def _embedded(cls, cluster: EmbeddedCluster,
                  cache_bytes: int | None = None) -> Client:
        self = cls.__new__(cls)
        self._cluster_ref = cluster  # keep alive
        self._handle = lib.btpu_client_create_embedded(cluster._handle)
        if not self._handle:
            raise RuntimeError("embedded client creation failed")
        self._configure_cache(cache_bytes)
        return self

    def put(
        self,
        key: str,
        data: Buffer,
        *,
        replicas: int = 1,
        max_workers: int = 4,
        preferred_class: StorageClass | None = None,
        ttl_ms: int | None = None,
        soft_pin: bool = False,
        ec: tuple[int, int] | None = None,
        preferred_slice: int | None = None,
        preferred_host: int | None = None,
    ) -> None:
        """ttl_ms: None = the framework default (30 min), 0 = never
        expires, >0 = the GC collects the object that long after CREATION
        (a fixed deadline, not a sliding window — reads do not extend it).
        soft_pin exempts the object from watermark eviction (demotion
        still applies). ec=(k, m) stores ONE Reed-Solomon coded copy of k
        data + m parity shards instead of replicas: any m worker losses
        tolerated at (k+m)/k storage overhead (e.g. ec=(4, 2) survives two
        losses at 1.5x, where replicas=3 costs 3x). preferred_slice ranks
        pools on that TPU slice first so placements ride ICI and spill to
        other slices (the DCN path) only when the slice is full.
        preferred_host (requires preferred_slice: host ids are per-slice
        coordinates) ranks that host's pools above the rest of the slice,
        so a sharded writer can pin each shard's bytes to the worker on the
        shard's own host — the placement plane's zero-cross-host lane. Host
        affinity is incompatible with ec: coded shards are deliberately
        spread across workers for loss independence."""
        if ttl_ms is not None and ttl_ms < 0:
            raise ValueError(f"ttl_ms must be >= 0, got {ttl_ms}")
        if preferred_host is not None and preferred_slice is None:
            raise ValueError("preferred_host requires preferred_slice "
                             "(host ids are per-slice coordinates)")
        if preferred_host is not None and ec is not None:
            raise ValueError("preferred_host is incompatible with ec "
                             "(coded shards are placed anti-affine)")
        if isinstance(data, np.ndarray):
            data = np.ascontiguousarray(data)
            buf = data.ctypes.data_as(ctypes.c_void_p)
            size = data.nbytes
        else:
            data = bytes(data)  # zero-copy: put never mutates the buffer
            buf = ctypes.cast(ctypes.c_char_p(data), ctypes.c_void_p)
            size = len(data)
        if ec is not None:
            k, m = ec
            if k < 1 or m < 1:
                raise ValueError(f"ec needs k >= 1 and m >= 1, got {ec}")
            check(
                lib.btpu_put_ec2(
                    self._handle,
                    key.encode(),
                    buf,
                    size,
                    k,
                    m,
                    int(preferred_class) if preferred_class else 0,
                    -1 if ttl_ms is None else ttl_ms,
                    1 if soft_pin else 0,
                    -1 if preferred_slice is None else preferred_slice,
                ),
                f"put {key!r}",
            )
            return
        check(
            lib.btpu_put_ex3(
                self._handle,
                key.encode(),
                buf,
                size,
                replicas,
                max_workers,
                int(preferred_class) if preferred_class else 0,
                -1 if ttl_ms is None else ttl_ms,
                1 if soft_pin else 0,
                -1 if preferred_slice is None else preferred_slice,
                -1 if preferred_host is None else preferred_host,
            ),
            f"put {key!r}",
        )

    def get(self, key: str) -> bytes:
        ckey = key.encode()
        size = ctypes.c_uint64()
        check(lib.btpu_get(self._handle, ckey, None, 0, ctypes.byref(size)),
              f"get {key!r}")
        # The C side fills the final bytes object directly: no zero-fill
        # pass, no copy out (see _uninit_bytes).
        buffer = _uninit_bytes(size.value)
        out = ctypes.c_uint64()
        check(
            lib.btpu_get(self._handle, ckey, _bytes_addr(buffer), size.value,
                         ctypes.byref(out)),
            f"get {key!r}",
        )
        return buffer if out.value == size.value else buffer[: out.value]

    def get_array(self, key: str, dtype: npt.DTypeLike = np.uint8,
                  shape: tuple[int, ...] | None = None) -> AnyArray:
        raw = np.frombuffer(self.get(key), dtype=dtype)
        return raw.reshape(shape) if shape is not None else raw

    def get_into(self, key: str, out: AnyArray) -> int:
        """Reads into a preallocated array; returns the object size."""
        assert out.flags["C_CONTIGUOUS"]
        got = ctypes.c_uint64()
        check(
            lib.btpu_get(
                self._handle,
                key.encode(),
                out.ctypes.data_as(ctypes.c_void_p),
                out.nbytes,
                ctypes.byref(got),
            ),
            f"get {key!r}",
        )
        return int(got.value)

    def put_many(
        self,
        items: dict[str, Buffer],
        *,
        replicas: int = 1,
        max_workers: int = 4,
        preferred_class: StorageClass | None = None,
    ) -> None:
        """Stores every item with ONE keystone round trip and one coalesced
        device transfer for all HBM shards (acceptance ladder item 2:
        "batched 1 MB put/get, HBM tier"). Raises on the first failed item."""
        n = len(items)
        keys = (ctypes.c_char_p * n)()
        bufs = (ctypes.c_void_p * n)()
        sizes = (ctypes.c_uint64 * n)()
        codes = (ctypes.c_int32 * n)()
        keep_alive: list[bytes | AnyArray] = []
        for i, (key, data) in enumerate(items.items()):
            if isinstance(data, np.ndarray):
                data = np.ascontiguousarray(data)
                keep_alive.append(data)
                bufs[i] = data.ctypes.data_as(ctypes.c_void_p)
                sizes[i] = data.nbytes
            else:
                # Zero-copy: point straight into the immutable bytes object
                # (the C side never mutates put buffers and gets an explicit
                # length, so neither NUL-termination nor a private copy is
                # needed — copying here cost a full memcpy of every batch).
                data = bytes(data)
                keep_alive.append(data)
                bufs[i] = ctypes.cast(ctypes.c_char_p(data), ctypes.c_void_p)
                sizes[i] = len(data)
            keys[i] = key.encode()
        check(
            lib.btpu_put_many(
                self._handle, n, keys, bufs, sizes, replicas, max_workers,
                int(preferred_class) if preferred_class else 0, codes,
            ),
            "put_many",
        )
        for i, key in enumerate(items):
            check(codes[i], f"put {key!r}")

    def get_many(self, keys: list[str]) -> list[bytes]:
        """Batched get: one keystone size-probe round trip, then one data
        round trip with a coalesced device transfer. Raises on the first
        failed key."""
        n = len(keys)
        sizes = (ctypes.c_uint64 * n)()
        codes = (ctypes.c_int32 * n)()
        ckeys = (ctypes.c_char_p * n)(*[k.encode() for k in keys])
        check(lib.btpu_sizes_many(self._handle, n, ckeys, sizes, codes), "sizes_many")
        for i, key in enumerate(keys):
            check(codes[i], f"get {key!r}")
        # The C side fills the final bytes objects directly (see _uninit_bytes).
        buffers = [_uninit_bytes(sizes[i]) for i in range(n)]
        bufs = (ctypes.c_void_p * n)(*[_bytes_addr(b) for b in buffers])
        out_sizes = (ctypes.c_uint64 * n)()
        check(lib.btpu_get_many(self._handle, n, ckeys, bufs, sizes, out_sizes, codes),
              "get_many")
        for i, key in enumerate(keys):
            check(codes[i], f"get {key!r}")
        return [b if out_sizes[i] == len(b) else b[: out_sizes[i]]
                for i, b in enumerate(buffers)]

    def get_many_async(self, keys: list[str]) -> AsyncBatch:
        """Async batched get: one synchronous keystone size probe to size
        the buffers (served locally for cached/hot keys), then the data
        movement rides the client op core and this call returns immediately
        — one thread can keep thousands of batches in flight. Read the
        bytes with AsyncBatch.result()."""
        n = len(keys)
        sizes = (ctypes.c_uint64 * n)()
        codes = (ctypes.c_int32 * n)()
        ckeys = (ctypes.c_char_p * n)(*[k.encode() for k in keys])
        check(lib.btpu_sizes_many(self._handle, n, ckeys, sizes, codes), "sizes_many")
        for i, key in enumerate(keys):
            check(codes[i], f"get {key!r}")
        buffers = [_uninit_bytes(sizes[i]) for i in range(n)]
        bufs = (ctypes.c_void_p * n)(*[_bytes_addr(b) for b in buffers])
        handle = lib.btpu_get_many_async(self._handle, n, ckeys, bufs, sizes)
        assert handle is not None  # NULL only on invalid args; ours are built here
        return AsyncBatch(handle, keys, buffers, keep_alive=[buffers])

    def put_many_async(
        self,
        items: dict[str, Buffer],
        *,
        replicas: int = 1,
        max_workers: int = 4,
        preferred_class: StorageClass | None = None,
    ) -> AsyncBatch:
        """Async batched put: returns immediately with the batch in flight
        on the client op core. The payloads are kept alive by the returned
        AsyncBatch; call result() (or wait()) to confirm the writes."""
        n = len(items)
        keys = (ctypes.c_char_p * n)()
        bufs = (ctypes.c_void_p * n)()
        sizes = (ctypes.c_uint64 * n)()
        keep_alive: list[bytes | AnyArray] = []
        for i, (key, data) in enumerate(items.items()):
            if isinstance(data, np.ndarray):
                data = np.ascontiguousarray(data)
                keep_alive.append(data)
                bufs[i] = data.ctypes.data_as(ctypes.c_void_p)
                sizes[i] = data.nbytes
            else:
                data = bytes(data)
                keep_alive.append(data)
                bufs[i] = ctypes.cast(ctypes.c_char_p(data), ctypes.c_void_p)
                sizes[i] = len(data)
            keys[i] = key.encode()
        handle = lib.btpu_put_many_async(
            self._handle, n, keys, bufs, sizes, replicas, max_workers,
            int(preferred_class) if preferred_class else 0,
        )
        assert handle is not None  # NULL only on invalid args; ours are built here
        return AsyncBatch(handle, list(items.keys()), None, keep_alive=[keep_alive])

    def list(self, prefix: str = "", limit: int = 0) -> list[dict[str, Any]]:
        """Complete objects whose key starts with `prefix`, lexicographic:
        [{"key", "size", "copies", "soft_pin"}]. limit 0 = unlimited. No
        reference counterpart — its object map was not enumerable."""
        import json

        size = ctypes.c_uint64()
        check(lib.btpu_list_json(self._handle, prefix.encode(), limit, None, 0,
                                 ctypes.byref(size)),
              f"list {prefix!r}")
        while True:
            cap = max(size.value, 2)
            buffer = ctypes.create_string_buffer(cap)
            check(lib.btpu_list_json(self._handle, prefix.encode(), limit, buffer,
                                     cap, ctypes.byref(size)),
                  f"list {prefix!r}")
            if size.value <= cap:  # else grew between calls (concurrent puts)
                return cast("list[dict[str, Any]]",
                            json.loads(buffer.raw[: size.value].decode()))

    def placements(self, key: str) -> list[dict[str, Any]]:
        """Where the object's bytes live: one dict per copy, with shards
        carrying worker/pool/storage-class/transport and the location
        (memory address, device region, or file). Parity: the C++ SDK's
        get_workers (reference BlackbirdClient::get_workers)."""
        import json

        size = ctypes.c_uint64()
        check(lib.btpu_placements_json(self._handle, key.encode(), None, 0,
                                       ctypes.byref(size)),
              f"placements {key!r}")
        while True:
            cap = size.value
            buffer = ctypes.create_string_buffer(cap)
            check(lib.btpu_placements_json(self._handle, key.encode(), buffer,
                                           cap, ctypes.byref(size)),
                  f"placements {key!r}")
            if size.value <= cap:  # else grew between calls (repair/demotion)
                return cast("list[dict[str, Any]]",
                            json.loads(buffer.raw[: size.value].decode()))

    def pools(self) -> list[dict[str, Any]]:
        """Every registered memory pool with its topology coordinates:
        [{"pool", "worker", "class", "transport", "slice", "host", "chip",
        "capacity", "used", "fabric"?}], ordered by pool id. This is the
        placement plane's topology-discovery read: PodPlacement maps each
        (slice, host) coordinate to the worker whose pools live there and
        routes sharded puts host-locally (blackbird_tpu/placement.py)."""
        import json

        size = ctypes.c_uint64()
        check(lib.btpu_pools_json(self._handle, None, 0, ctypes.byref(size)),
              "pools")
        while True:
            cap = max(size.value, 2)
            buffer = ctypes.create_string_buffer(cap)
            check(lib.btpu_pools_json(self._handle, buffer, cap,
                                      ctypes.byref(size)),
                  "pools")
            if size.value <= cap:  # else grew between calls (worker joined)
                return cast("list[dict[str, Any]]",
                            json.loads(buffer.raw[: size.value].decode()))

    def drain_worker(self, worker_id: str) -> int:
        """Gracefully evacuates a LIVE worker (e.g. on a TPU preemption
        notice): every shard it holds is rebuilt on the remaining workers —
        streamed from the still-alive source, so replicas=1 objects survive
        where a crash would lose them — and the worker is retired. Returns
        the number of shards migrated."""
        moved = ctypes.c_uint64()
        check(lib.btpu_drain_worker(self._handle, worker_id.encode(),
                                    ctypes.byref(moved)),
              f"drain {worker_id!r}")
        return int(moved.value)

    def exists(self, key: str) -> bool:
        flag = ctypes.c_int32()
        check(lib.btpu_exists(self._handle, key.encode(), ctypes.byref(flag)),
              f"exists {key!r}")
        return bool(flag.value)

    def remove(self, key: str) -> None:
        check(lib.btpu_remove(self._handle, key.encode()), f"remove {key!r}")

    def stats(self) -> dict[str, int]:
        out = (ctypes.c_uint64 * 5)()
        check(lib.btpu_stats(self._handle, out), "stats")
        return {
            "workers": out[0],
            "pools": out[1],
            "objects": out[2],
            "capacity": out[3],
            "used": out[4],
        }

    @staticmethod
    def lane_counters() -> dict[str, int]:
        """Process-global data-lane scoreboard: which lane moved this
        process's bytes, and how many. pvm = same-host one-sided
        process_vm_readv/writev (1 user-space copy per byte), staged =
        shm-staged TCP (2 copies), stream = socket payload (1 client-side
        copy + the kernel socket path), cached = the client object cache
        (0 wire bytes, 1 user-space copy out of local memory). Every counter
        symbol here is REQUIRED by the blackbird_tpu/_capi.py manifest:
        binding fails at import if one is missing, so a 0 in this dict means
        the count IS zero — the old hasattr guard that silently reported 0
        for a missing (or worse, bound-without-restype, u64-truncating)
        symbol is gone (docs/CORRECTNESS.md §11)."""
        names = {
            "pvm_ops": "btpu_pvm_op_count",
            "pvm_bytes": "btpu_pvm_byte_count",
            "staged_ops": "btpu_tcp_staged_op_count",
            "staged_bytes": "btpu_tcp_staged_byte_count",
            "stream_ops": "btpu_tcp_stream_op_count",
            "stream_bytes": "btpu_tcp_stream_byte_count",
            # Server-side stream lane: reads this process answered straight
            # off registered pool pages (zero worker-side staging copies) —
            # the uring engine's pool-direct sends + the fallback server's
            # gather-write path.
            "pool_direct_ops": "btpu_tcp_pool_direct_op_count",
            "pool_direct_bytes": "btpu_tcp_pool_direct_byte_count",
            # SEND_ZC completions by kernel verdict (uring engine only):
            # sent = transmitted straight from pool pages, copied = the
            # kernel privately copied first (loopback always; sustained
            # copied on a real NIC is a perf regression signal).
            "zerocopy_sent": "btpu_tcp_zerocopy_sent_count",
            "zerocopy_copied": "btpu_tcp_zerocopy_copied_count",
            # Live io_uring event-loop threads serving TCP data planes in
            # this process (0 = thread-per-connection fallback), and the
            # resolved wire worker pool size (BTPU_WIRE_POOL_THREADS).
            "uring_loops": "btpu_uring_loop_count",
            "wire_pool_threads": "btpu_wire_pool_threads",
            "cached_ops": "btpu_cached_op_count",
            "cached_bytes": "btpu_cached_byte_count",
            # Overload-robustness scoreboard (deadlines / sheds / hedges /
            # breakers); process-global like the lanes above.
            "deadline_exceeded": "btpu_deadline_exceeded_count",
            "shed": "btpu_shed_count",
            "client_deadline_exceeded": "btpu_client_deadline_exceeded_count",
            "retries": "btpu_retry_count",
            "retry_budget_exhausted": "btpu_retry_budget_exhausted_count",
            "hedges_fired": "btpu_hedge_fired_count",
            "hedge_wins": "btpu_hedge_win_count",
            "breaker_trips": "btpu_breaker_trip_count",
            "breaker_skips": "btpu_breaker_skip_count",
            # Durability-lag backlog: objects whose durable record write is
            # deferred and retrying (acked vs durable state diverged across
            # every in-process keystone). Alert on sustained nonzero.
            "persist_retry_backlog": "btpu_persist_retry_backlog",
            # Pool sanitizer (btpu/common/poolsan.h): 0 in release builds
            # (compiled out); any nonzero conviction count in a
            # production-shadow run is an alert (docs/OPERATIONS.md).
            "poolsan_armed": "btpu_poolsan_armed",
            "poolsan_convictions": "btpu_poolsan_conviction_count",
            "poolsan_stale_extent": "btpu_poolsan_stale_extent_count",
            "poolsan_redzone_smash": "btpu_poolsan_redzone_smash_count",
            "poolsan_double_free": "btpu_poolsan_double_free_count",
            "poolsan_quarantine_bytes": "btpu_poolsan_quarantine_bytes",
            # Real histogram summaries for the hot get family (full set via
            # Client.histograms()): sample count + bucket-interpolated
            # p50/p99 of btpu_op_duration_us{op="get"}.
            "hist_get_count": "btpu_op_get_count",
            "hist_get_p50_us": "btpu_op_get_p50_us",
            "hist_get_p99_us": "btpu_op_get_p99_us",
            # Client op core (the completion-based async engine behind
            # get_many_async/put_many_async and lane-hosted hedge
            # primaries): inflight/cq_depth are gauges, the rest monotonic.
            "client_inflight_ops": "btpu_client_inflight_ops",
            "client_peak_inflight_ops": "btpu_client_peak_inflight_ops",
            "client_cq_depth": "btpu_client_cq_depth",
            "client_ops_submitted": "btpu_client_ops_submitted_count",
            "client_ops_completed": "btpu_client_ops_completed_count",
            "client_ops_cancelled": "btpu_client_ops_cancelled_count",
            # FaRM-style optimistic reads: placement-cache serves with zero
            # keystone turns, and revalidation retries after a cached
            # attempt failed (BTPU_OPTIMISTIC_READS=1 arms the lane).
            "optimistic_hits": "btpu_optimistic_hit_count",
            "optimistic_revalidates": "btpu_optimistic_revalidate_count",
            # Observability plumbing health: flight-recorder events and
            # trace spans recorded in this process.
            "flight_events": "btpu_flight_event_count",
            "trace_spans": "btpu_trace_span_count",
        }
        counters: dict[str, int] = {}
        for key, fn_name in names.items():
            # Direct call, no hasattr: every name is a required manifest
            # symbol, typed u64 by _load(). An unknown name would raise
            # AttributeError here — loudly, as drift should.
            counters[key] = int(getattr(lib, fn_name)())
        return counters

    @staticmethod
    def _json_export(fn_name: str, *args: Any) -> str:
        """Shared NULL-probe-then-fill pattern of the capi *_json exports.
        Retries when the dump GREW between probe and fill (a live process
        records events continuously) — same loop as placements()/list().
        The *_json exports are OPTIONAL manifest symbols (prebuilt older
        libraries); absent ones report an empty dump, explicitly."""
        if not native.have(fn_name):
            return ""
        fn = getattr(lib, fn_name)
        size = ctypes.c_uint64()
        check(fn(*args, None, 0, ctypes.byref(size)), fn_name)
        while True:
            if size.value == 0:
                return ""
            cap = size.value
            buffer = ctypes.create_string_buffer(cap + 1)
            check(fn(*args, buffer, cap, ctypes.byref(size)), fn_name)
            if size.value <= cap:  # else grew between calls: go again
                return buffer.raw[: size.value].decode()

    @staticmethod
    def histograms() -> list[dict[str, Any]]:
        """Every registered latency histogram in this process (op families,
        keystone RPC methods, data-plane ops, WAL sync, uring send):
        count/sum plus bucket-interpolated p50/p99 and the non-zero
        log2-microsecond buckets. The same data /metrics exports as
        Prometheus _bucket/_sum/_count series."""
        import json
        body = Client._json_export("btpu_histograms_json")
        return cast("list[dict[str, Any]]", json.loads(body)) if body else []

    @staticmethod
    def trace_spans(trace_id: int = 0) -> list[dict[str, Any]]:
        """Completed spans in this process's span ring (optionally filtered
        to one 64-bit trace id). Each record carries name, trace/span/parent
        ids (hex), start_us/dur_us on the host-wide monotonic clock, and
        pid/tid — the exact records bb-trace stitches into Perfetto JSON."""
        import json
        body = Client._json_export("btpu_trace_spans_json",
                                   ctypes.c_uint64(trace_id))
        return [cast("dict[str, Any]", json.loads(line))
                for line in body.splitlines() if line.strip()]

    @staticmethod
    def flight_events() -> list[dict[str, Any]]:
        """The process flight recorder: the last N structured events (op
        start/end, retries, hedges, sheds, cache hits/misses, WAL
        append/sync, uring submit/complete), oldest first."""
        import json
        body = Client._json_export("btpu_flight_json")
        return [cast("dict[str, Any]", json.loads(line))
                for line in body.splitlines() if line.strip()]

    @staticmethod
    def set_tracing(on: bool) -> None:
        """Master tracing switch (trace-id minting + span recording + flight
        events). Default from BTPU_TRACING (on). No-op on prebuilt older
        libraries without the switch (OPTIONAL manifest symbol)."""
        if native.have("btpu_set_tracing"):
            lib.btpu_set_tracing(1 if on else 0)

    def close(self) -> None:
        if self._handle:
            lib.btpu_client_destroy(self._handle)
            self._handle = None

    def __del__(self) -> None:
        try:
            self.close()
        except Exception:
            pass
