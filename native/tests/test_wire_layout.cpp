// Wire golden-table test: freezes the ENCODED layout of every struct that
// crosses the wire (btpu/common/wire.h) against a checked-in table,
// native/tests/wire_golden.txt.
//
// The wire format's compat story is append-only (wire.h header comment):
// fields encode in a fixed order with fixed widths, missing trailing fields
// default, unknown trailing bytes are skipped. A field inserted mid-struct,
// a reordered pair, or a widened scalar silently breaks every peer running
// the old build — and nothing caught it until decode failed in production.
// This test encodes a canonical instance of every wire struct and diffs the
// exact bytes against the golden table, so ANY layout change fails the
// suite. Intentional (append-only!) changes regenerate the table:
//
//     make wire-golden        # wraps: build/btpu_tests --dump-wire-golden
//
// and the diff of wire_golden.txt in review IS the wire-compat review.
#include <cstdio>
#include <cstring>
#include <fstream>
#include <map>
#include <sstream>
#include <string>
#include <vector>

#include <unistd.h>

#include "btpu/common/env.h"
#include "btpu/common/wire.h"
#include "btpu/coord/wal_format.h"
#include "btpu/rpc/rpc.h"
#include "btest.h"

namespace {

using namespace btpu;

// ---- canonical instances --------------------------------------------------
// Deterministic, fully-populated values: every field non-default and
// distinct, nested structs/vectors non-empty, so each field's bytes appear
// in the encoding at a stable offset.

TopoCoord canon_topo() { return {3, 7, 1}; }

RemoteDescriptor canon_remote() {
  RemoteDescriptor d;
  d.transport = TransportKind::TCP;
  d.endpoint = "h:1";
  d.remote_base = 0x1111;
  d.rkey_hex = "ab";
  d.fabric_addr = "fa";
  d.pvm_endpoint = "pv";
  d.data_wire_version = 0x55;
  return d;
}

// extent_gen appended (poolsan generation stamp) — nonzero here so the
// golden row pins the field's encoding, not just its presence.
MemoryLocation canon_memloc() { return {0x2222, 0x3333, 0x44, 0x55}; }
FileLocation canon_fileloc() { return {"/f", 0x55}; }
DeviceLocation canon_devloc() { return {"tpu:0", 9, 0x66, 0x77}; }

ShardPlacement canon_shard() {
  ShardPlacement s;
  s.pool_id = "p1";
  s.worker_id = "w1";
  s.remote = canon_remote();
  s.storage_class = StorageClass::RAM_CPU;
  s.length = 0x88;
  s.location = canon_memloc();
  return s;
}

CopyPlacement canon_copy() {
  CopyPlacement c;
  c.copy_index = 2;
  c.shards = {canon_shard()};
  c.ec_data_shards = 4;
  c.ec_parity_shards = 2;
  c.ec_object_size = 0x99;
  c.content_crc = 0xAA;
  c.shard_crcs = {0xBB, 0xCC};
  c.inline_data = "in";
  c.cache_version = 0xDD;
  c.cache_gen = 0xEE;
  c.cache_lease_ms = 0xFF;
  return c;
}

WorkerConfig canon_config() {
  WorkerConfig c;
  c.replication_factor = 2;
  c.max_workers_per_copy = 3;
  c.enable_soft_pin = true;
  c.preferred_node = "n1";
  c.preferred_classes = {StorageClass::HBM_TPU, StorageClass::NVME};
  c.ttl_ms = 0x111;
  c.enable_locality_awareness = false;
  c.prefer_contiguous = true;
  c.min_shard_size = 0x222;
  c.preferred_slice = 5;
  c.ec_data_shards = 6;
  c.ec_parity_shards = 3;
  c.preferred_host = 7;
  return c;
}

ClusterStats canon_stats() { return {1, 2, 3, 4, 5, 0.5, 6}; }

MemoryPool canon_pool() {
  MemoryPool p;
  p.id = "pool";
  p.node_id = "node";
  p.base_addr = 0x333;
  p.size = 0x444;
  p.used = 0x55;
  p.storage_class = StorageClass::SSD;
  p.remote = canon_remote();
  p.topo = canon_topo();
  p.alignment = 0x66;
  p.fabric_addr = "fb";
  return p;
}

ObjectSummary canon_summary() { return {"k1", 0x777, 2, true}; }
BatchPutStartItem canon_bpsi() { return {"k2", 0x888, canon_config(), 0x99}; }
CopyShardCrcs canon_cscrcs() { return {1, {0xAB, 0xCD}}; }
PutSlot canon_slot() { return {"\x01slot/t/1", {canon_copy()}}; }

std::string hex(const std::vector<uint8_t>& v) {
  static const char* d = "0123456789abcdef";
  std::string out;
  out.reserve(v.size() * 2);
  for (uint8_t b : v) {
    out.push_back(d[b >> 4]);
    out.push_back(d[b & 0xf]);
  }
  return out.empty() ? "-" : out;
}

template <typename T>
std::string enc(const T& v) {
  wire::Writer w;
  wire::encode(w, v);
  return hex(w.buffer());
}

// One row per wire struct: name -> hex of the canonical encoding.
std::vector<std::pair<std::string, std::string>> golden_rows() {
  std::vector<std::pair<std::string, std::string>> rows;
  auto add = [&](const char* name, std::string h) { rows.emplace_back(name, std::move(h)); };

  // Data-model composites (size-prefixed encode_struct bodies).
  add("TopoCoord", enc(canon_topo()));
  add("RemoteDescriptor", enc(canon_remote()));
  add("MemoryLocation", enc(canon_memloc()));
  add("FileLocation", enc(canon_fileloc()));
  add("DeviceLocation", enc(canon_devloc()));
  add("LocationDetail/Memory", enc(LocationDetail{canon_memloc()}));
  add("LocationDetail/File", enc(LocationDetail{canon_fileloc()}));
  add("LocationDetail/Device", enc(LocationDetail{canon_devloc()}));
  add("ShardPlacement", enc(canon_shard()));
  add("CopyPlacement", enc(canon_copy()));
  add("PutSlot", enc(canon_slot()));
  add("WorkerConfig", enc(canon_config()));
  add("ClusterStats", enc(canon_stats()));
  add("MemoryPool", enc(canon_pool()));
  add("ObjectSummary", enc(canon_summary()));
  add("BatchPutStartItem", enc(canon_bpsi()));
  add("CopyShardCrcs", enc(canon_cscrcs()));
  add("Result<bool>/ok", enc(Result<bool>(true)));
  add("Result<bool>/err", enc(Result<bool>(ErrorCode::OBJECT_NOT_FOUND)));

  // RPC messages (frame-bounded, tail-tolerant field lists).
  add("ObjectExistsRequest", enc(ObjectExistsRequest{"k"}));
  add("ObjectExistsResponse", enc(ObjectExistsResponse{true, ErrorCode::OK}));
  add("GetWorkersRequest", enc(GetWorkersRequest{"k"}));
  add("GetWorkersResponse",
      enc(GetWorkersResponse{{canon_copy()}, ErrorCode::OBJECT_NOT_FOUND}));
  add("PutStartRequest", enc(PutStartRequest{"k", 0x123, canon_config(), 0x45}));
  add("PutStartResponse", enc(PutStartResponse{{canon_copy()}, ErrorCode::OK}));
  add("PutCompleteRequest", enc(PutCompleteRequest{"k", {canon_cscrcs()}, 0x67}));
  add("PutCompleteResponse", enc(PutCompleteResponse{ErrorCode::OK}));
  add("PutCancelRequest", enc(PutCancelRequest{"k"}));
  add("PutCancelResponse", enc(PutCancelResponse{ErrorCode::OK}));
  add("RemoveObjectRequest", enc(RemoveObjectRequest{"k"}));
  add("RemoveObjectResponse", enc(RemoveObjectResponse{ErrorCode::OK}));
  add("RemoveAllObjectsRequest", enc(RemoveAllObjectsRequest{}));
  add("RemoveAllObjectsResponse", enc(RemoveAllObjectsResponse{7, ErrorCode::OK}));
  add("DrainWorkerRequest", enc(DrainWorkerRequest{"w"}));
  add("DrainWorkerResponse", enc(DrainWorkerResponse{8, ErrorCode::OK}));
  add("GetClusterStatsRequest", enc(GetClusterStatsRequest{}));
  add("GetClusterStatsResponse", enc(GetClusterStatsResponse{canon_stats(), ErrorCode::OK}));
  add("GetViewVersionRequest", enc(GetViewVersionRequest{}));
  add("GetViewVersionResponse", enc(GetViewVersionResponse{9, ErrorCode::OK}));
  add("ListObjectsRequest", enc(ListObjectsRequest{"pre", 10}));
  add("ListObjectsResponse", enc(ListObjectsResponse{{canon_summary()}, ErrorCode::OK}));
  add("ListPoolsRequest", enc(ListPoolsRequest{}));
  add("ListPoolsResponse", enc(ListPoolsResponse{{canon_pool()}, ErrorCode::OK}));
  add("BatchObjectExistsRequest", enc(BatchObjectExistsRequest{{"a", "b"}}));
  add("BatchObjectExistsResponse",
      enc(BatchObjectExistsResponse{{Result<bool>(true)}, ErrorCode::OK}));
  add("BatchGetWorkersRequest", enc(BatchGetWorkersRequest{{"a"}}));
  add("BatchGetWorkersResponse",
      enc(BatchGetWorkersResponse{
          {Result<std::vector<CopyPlacement>>(std::vector<CopyPlacement>{canon_copy()})},
          ErrorCode::OK}));
  add("BatchPutStartRequest", enc(BatchPutStartRequest{{canon_bpsi()}}));
  add("BatchPutStartResponse",
      enc(BatchPutStartResponse{
          {Result<std::vector<CopyPlacement>>(ErrorCode::INSUFFICIENT_SPACE)},
          ErrorCode::OK}));
  add("BatchPutCompleteRequest",
      enc(BatchPutCompleteRequest{{"a"}, {{canon_cscrcs()}}, {0x12}}));
  add("BatchPutCompleteResponse",
      enc(BatchPutCompleteResponse{{ErrorCode::OK}, ErrorCode::OK}));
  add("BatchPutCancelRequest", enc(BatchPutCancelRequest{{"a"}}));
  add("BatchPutCancelResponse", enc(BatchPutCancelResponse{{ErrorCode::OK}, ErrorCode::OK}));
  add("PutStartPooledRequest", enc(PutStartPooledRequest{0x234, canon_config(), 2, "tag"}));
  add("PutStartPooledResponse",
      enc(PutStartPooledResponse{ErrorCode::OK, {canon_slot()}}));
  add("PutCommitSlotRequest",
      enc(PutCommitSlotRequest{"s", "k", 0x34, {canon_cscrcs()}, 1, 0x345, canon_config(),
                               "tag"}));
  add("PutCommitSlotResponse", enc(PutCommitSlotResponse{ErrorCode::OK, {canon_slot()}}));
  add("PutInlineRequest", enc(PutInlineRequest{"k", canon_config(), 0x56, "data"}));
  add("PutInlineResponse", enc(PutInlineResponse{ErrorCode::OK}));
  add("PingRequest", enc(PingRequest{3}));
  add("PingResponse", enc(PingResponse{11, 3}));

  // RPC tagged trailers (rpc.h): raw appended bytes, not wire-struct
  // encodes — pin the exact framing (magic + fields) a peer strips.
  {
    std::vector<uint8_t> t;
    rpc::append_deadline_trailer(t, 250);
    add("rpc/deadline_trailer", hex(t));
  }
  {
    std::vector<uint8_t> t;
    rpc::append_trace_trailer(t, 0x1122334455667788ull, 0x99AABBCCDDEEFF00ull);
    add("rpc/trace_trailer", hex(t));
  }

  // Coordinator WAL v2 on-disk framing (wal_format.h): a durable format, so
  // it is frozen like the durable record envelopes. The canonical journal is
  // one header + one record ("xyz" payload) — header bytes, chained CRC, and
  // record framing all pinned by this row.
  {
    std::vector<uint8_t> journal;
    uint32_t chain = coord::wal::kChainSeed;
    coord::wal::append_file_header(journal);
    const uint8_t payload[] = {'x', 'y', 'z'};
    coord::wal::append_record(journal, chain, payload, sizeof(payload));
    add("wal/file_header+record", hex(journal));
  }
  return rows;
}

// Locates native/tests/wire_golden.txt from the test binary's location
// (build/ or build/{tsan,asan}/) or the repo-root cwd; BTPU_WIRE_GOLDEN
// overrides.
std::string golden_path() {
  return btest::locate_repo_path("BTPU_WIRE_GOLDEN", "native/tests/wire_golden.txt");
}

}  // namespace

// Regen entry point (main.cpp --dump-wire-golden): prints the current table.
int btpu_dump_wire_golden() {
  std::printf("# Wire layout golden table — encoded bytes of one canonical instance per\n");
  std::printf("# wire struct (native/tests/test_wire_layout.cpp). Regenerate with\n");
  std::printf("# `make wire-golden` ONLY for append-only changes; any other diff here\n");
  std::printf("# is a wire-compat break. Format: <name> <hex|- >\n");
  for (const auto& [name, h] : golden_rows()) std::printf("%s %s\n", name.c_str(), h.c_str());
  return 0;
}

BTEST(Wire, GoldenLayoutTable) {
  const std::string path = golden_path();
  std::ifstream in(path);
  BT_ASSERT(in.good());

  std::map<std::string, std::string> want;
  std::string line;
  while (std::getline(in, line)) {
    if (line.empty() || line[0] == '#') continue;
    const auto sp = line.find(' ');
    BT_ASSERT(sp != std::string::npos);
    want[line.substr(0, sp)] = line.substr(sp + 1);
  }

  const auto rows = golden_rows();
  // Every current struct must match its golden row byte-for-byte.
  for (const auto& [name, h] : rows) {
    auto it = want.find(name);
    if (it == want.end()) {
      btest::report_failure(__FILE__, __LINE__,
                            "wire struct '" + name +
                                "' missing from wire_golden.txt — run `make wire-golden` "
                                "and review the diff as a wire-compat change");
      continue;
    }
    if (it->second != h) {
      btest::report_failure(
          __FILE__, __LINE__,
          "wire layout of '" + name + "' CHANGED\n    golden:  " + it->second +
              "\n    current: " + h +
              "\n  If this is an intentional append-only addition, regenerate with "
              "`make wire-golden`; anything else breaks rolling upgrades and durable "
              "coordinator records.");
    }
  }
  // And no golden row may vanish (a deleted struct breaks old peers too).
  for (const auto& [name, h] : want) {
    bool found = false;
    for (const auto& [n2, h2] : rows) found |= n2 == name;
    if (!found) {
      btest::report_failure(__FILE__, __LINE__,
                            "golden row '" + name +
                                "' no longer produced — wire structs must not disappear; "
                                "run `make wire-golden` only if this removal is deliberate");
    }
  }
}

// The append-only contract itself: a tail-extended frame decodes (newer
// peer), a truncated-at-field-boundary frame defaults the tail (older
// peer). Guards the rule the golden table assumes.
BTEST(Wire, GoldenTailTolerance) {
  CopyPlacement c = canon_copy();
  wire::Writer w;
  wire::encode(w, c);
  // Newer peer: append 4 unknown bytes INSIDE the struct body (the
  // size-prefix covers them) — decode must skip them.
  {
    std::vector<uint8_t> bytes = w.buffer();
    uint32_t body = 0;
    std::memcpy(&body, bytes.data(), 4);
    body += 4;
    std::memcpy(bytes.data(), &body, 4);
    bytes.insert(bytes.end(), {0xde, 0xad, 0xbe, 0xef});
    CopyPlacement out;
    wire::Reader r(bytes);
    BT_EXPECT(wire::decode(r, out));
    BT_EXPECT_EQ(out.cache_lease_ms, c.cache_lease_ms);
  }
  // Older peer: body truncated before the cache stamps — they default to 0.
  {
    std::vector<uint8_t> bytes = w.buffer();
    // Re-encode without the last three fields by shrinking the body to the
    // inline_data boundary: compute it by encoding a copy of the prefix.
    wire::Writer prefix;
    wire::encode_struct(prefix, c.copy_index, c.shards, c.ec_data_shards, c.ec_parity_shards,
                        c.ec_object_size, c.content_crc, c.shard_crcs, c.inline_data);
    CopyPlacement out;
    wire::Reader r(prefix.buffer());
    BT_EXPECT(wire::decode(r, out));
    BT_EXPECT_EQ(out.inline_data, c.inline_data);
    BT_EXPECT_EQ(out.cache_version, 0u);
    BT_EXPECT_EQ(out.cache_gen, 0u);
    BT_EXPECT_EQ(out.cache_lease_ms, 0u);
  }
}
