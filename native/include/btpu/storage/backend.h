// Storage backend interface: tiered worker-side memory/disk pools.
//
// Parity target: reference include/blackbird/worker/storage/storage_backend.h
// (ReservationToken :14-25, StorageStats :30-41, StorageBackend :46-126,
// factory :131-133). Lifecycle preserved: reserve_shard -> commit_shard |
// abort_shard -> free_shard, with reservations expiring after a deadline.
// Changes from the reference:
//   * every backend manages offsets with alloc::PoolAllocator (the reference
//     RamBackend rescans committed shards per reserve, ram_backend.cpp:228-259
//     O(n log n); its MmapDiskBackend already used the allocator);
//   * the factory wires ALL storage classes — the reference returns nullptr
//     for NVME/SSD/HDD (ram_backend.cpp:299-302) even though its worker
//     requests them, which is why disk pools are commented out of its config;
//   * the HBM_TPU tier replaces (broken) RAM_GPU via a provider callback
//     table (hbm_backend.h) so the device side can be JAX on real TPUs and a
//     host emulation in tests;
//   * read_at/write_at give every tier a uniform byte-access path used by
//     non-mapped tiers (io_uring files, HBM device memory).
#pragma once

#include <chrono>
#include <memory>
#include <string>

#include "btpu/common/types.h"

namespace btpu::storage {

struct ReservationToken {
  uint64_t id{0};
  uint64_t offset{0};
  uint64_t size{0};
  std::chrono::steady_clock::time_point expires_at;

  bool expired() const { return std::chrono::steady_clock::now() >= expires_at; }
};

struct StorageStats {
  uint64_t capacity{0};
  uint64_t used{0};       // committed bytes
  uint64_t reserved{0};   // reserved-not-yet-committed bytes
  uint64_t shard_count{0};
  uint64_t total_reserves{0};
  uint64_t total_commits{0};
  uint64_t total_aborts{0};
  uint64_t total_frees{0};
  double fragmentation{0.0};
};

struct BackendConfig {
  std::string pool_id;
  NodeId node_id;
  StorageClass storage_class{StorageClass::RAM_CPU};
  uint64_t capacity{0};
  std::string path;               // disk tiers: backing file / shard directory
  bool use_odirect{false};        // io_uring tier: O_DIRECT for NVME/SSD
  std::string device_id{"tpu:0"}; // HBM tier: provider device
  int64_t reservation_ttl_ms{10 * 60 * 1000};  // reference: 10 min
  uint64_t interleave_granularity{256};  // CXL tier: bytes per interleave region
  int numa_node{-1};                     // CXL tier: bind region to this node (-1 = off)
};

// CXL interleave region an offset falls in (reference computes this per
// shard, cxl_memory_backend.cpp:171).
inline uint64_t cxl_region_id(uint64_t offset, uint64_t interleave_granularity) {
  return interleave_granularity ? offset / interleave_granularity : 0;
}

class StorageBackend {
 public:
  virtual ~StorageBackend() = default;

  virtual ErrorCode initialize() = 0;
  virtual void shutdown() = 0;

  virtual Result<ReservationToken> reserve_shard(uint64_t size) = 0;
  virtual ErrorCode commit_shard(const ReservationToken& token) = 0;
  virtual ErrorCode abort_shard(const ReservationToken& token) = 0;
  virtual ErrorCode free_shard(uint64_t offset, uint64_t size) = 0;

  virtual uint64_t capacity() const = 0;
  virtual uint64_t used() const = 0;
  virtual uint64_t available() const { return capacity() - used(); }
  virtual StorageStats stats() const = 0;
  virtual StorageClass storage_class() const = 0;
  virtual const std::string& pool_id() const = 0;

  // Base address of the registered region; nullptr for tiers without a flat
  // host mapping (io_uring files, HBM device memory) — those serve bytes via
  // read_at/write_at instead.
  virtual void* base_address() const = 0;

  // Stable CPU-addressable alias of the region for tiers whose primary
  // store is NOT host memory (HBM provider v5 host-view mode); nullptr
  // otherwise. Valid for the region's whole life when non-null — the
  // worker advertises it on the same-host one-sided PVM lane. Tiers with a
  // real base_address() don't need this (the base itself is advertised).
  virtual void* host_view_base() const { return nullptr; }

  virtual ErrorCode write_at(uint64_t offset, const void* src, uint64_t len) = 0;
  virtual ErrorCode read_at(uint64_t offset, void* dst, uint64_t len) = 0;

  // Backing-file descriptor for tiers whose region offsets map 1:1 onto a
  // flat file (the io_uring disk backend), or -1. The TCP data plane's
  // uring engine uses it to submit region READS on the same ring as its
  // socket ops — disk bytes flow file -> connection buffer -> socket with
  // no callback thread and no staging segment. `odirect` (when non-null)
  // reports whether the fd is O_DIRECT (the engine then 512-aligns).
  // Ownership: the backend keeps the fd open until shutdown(); the worker
  // stops transports before backend shutdown, so borrowers never outlive
  // it. WRITES stay on write_at — only reads ride the direct lane.
  virtual int direct_io_fd(bool* odirect) const {
    if (odirect) *odirect = false;
    return -1;
  }

  // Disk tiers persist bytes across restarts; memory tiers do not.
  virtual bool persistent() const { return false; }

  // Device-tier backends (HBM) expose their provider region so placements
  // can address {device, region, offset} directly instead of a flat remote
  // pointer; 0 = not device-backed.
  virtual uint64_t device_region_id() const { return 0; }
  virtual const std::string& device_id() const {
    static const std::string kNone;
    return kNone;
  }

  // Cross-process device fabric (hbm_provider v4). fabric_address() == ""
  // means this backend has no fabric and the hooks return NOT_IMPLEMENTED.
  virtual std::string fabric_address() const { return {}; }
  virtual ErrorCode fabric_offer(uint64_t offset, uint64_t len, uint64_t transfer_id) {
    (void)offset;
    (void)len;
    (void)transfer_id;
    return ErrorCode::NOT_IMPLEMENTED;
  }
  virtual ErrorCode fabric_pull(const std::string& remote_addr, uint64_t transfer_id,
                                uint64_t offset, uint64_t len) {
    (void)remote_addr;
    (void)transfer_id;
    (void)offset;
    (void)len;
    return ErrorCode::NOT_IMPLEMENTED;
  }
};

// Builds a backend for any storage class (no nullptr gaps):
//   RAM_CPU        -> RamBackend (malloc or caller-provided region)
//   CXL_*          -> CxlBackend (DAX/file mmap with anonymous fallback)
//   HBM_TPU        -> HbmBackend (provider-backed device memory)
//   NVME/SSD       -> IoUringDiskBackend (O_DIRECT default for NVME)
//   HDD            -> MmapDiskBackend
std::unique_ptr<StorageBackend> create_storage_backend(const BackendConfig& config);

// RAM backend adopting caller-owned memory (e.g. a transport-allocated shm
// segment) instead of mallocing its own.
std::unique_ptr<StorageBackend> create_ram_backend_with_region(const BackendConfig& config,
                                                               void* region);

// CXL backend adopting caller-owned memory: alignment + interleave semantics
// are preserved even when the bytes live in a transport segment.
std::unique_ptr<StorageBackend> create_cxl_backend_with_region(const BackendConfig& config,
                                                               void* region);

}  // namespace btpu::storage
