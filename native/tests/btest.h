// Tiny self-contained unit-test framework (gtest is not available in this
// image and network fetch is disallowed, so we ship our own runner).
// Usage:   BTEST(Suite, Name) { BT_EXPECT_EQ(a, b); ... }
// Runner:  btpu_tests [--filter=substring] [--list]
#pragma once

#include <cstdio>
#include <functional>
#include <sstream>
#include <string>
#include <vector>

namespace btest {

struct TestCase {
  std::string name;
  std::function<void()> fn;
};

inline std::vector<TestCase>& registry() {
  static std::vector<TestCase> r;
  return r;
}

inline int& failure_count() {
  static int n = 0;
  return n;
}

inline bool& current_failed() {
  static bool f = false;
  return f;
}

struct Registrar {
  Registrar(std::string name, std::function<void()> fn) {
    registry().push_back({std::move(name), std::move(fn)});
  }
};

template <typename A, typename B>
std::string fmt_cmp(const char* op, const A& a, const B& b) {
  std::ostringstream ss;
  ss << "expected: " << a << " " << op << " " << b;
  return ss.str();
}

inline void report_failure(const char* file, int line, const std::string& msg) {
  std::fprintf(stderr, "  FAIL %s:%d: %s\n", file, line, msg.c_str());
  current_failed() = true;
}

inline int run_all(int argc, char** argv) {
  std::string filter;
  bool list = false;
  for (int i = 1; i < argc; ++i) {
    std::string a = argv[i];
    if (a.rfind("--filter=", 0) == 0) filter = a.substr(9);
    if (a == "--list") list = true;
  }
  int ran = 0, failed = 0;
  for (auto& tc : registry()) {
    if (!filter.empty() && tc.name.find(filter) == std::string::npos) continue;
    if (list) {
      std::printf("%s\n", tc.name.c_str());
      continue;
    }
    current_failed() = false;
    std::printf("[ RUN  ] %s\n", tc.name.c_str());
    std::fflush(stdout);
    try {
      tc.fn();
    } catch (const std::exception& e) {
      report_failure("<exception>", 0, std::string("uncaught exception: ") + e.what());
    } catch (...) {
      report_failure("<exception>", 0, "uncaught non-std exception");
    }
    ++ran;
    if (current_failed()) {
      ++failed;
      std::printf("[ FAIL ] %s\n", tc.name.c_str());
    } else {
      std::printf("[  OK  ] %s\n", tc.name.c_str());
    }
    std::fflush(stdout);
  }
  if (!list) {
    std::printf("%d tests ran, %d failed\n", ran, failed);
  }
  return failed == 0 ? 0 : 1;
}

}  // namespace btest

#define BTEST(Suite, Name)                                                   \
  static void btest_##Suite##_##Name();                                      \
  static ::btest::Registrar btest_reg_##Suite##_##Name(#Suite "." #Name,     \
                                                       btest_##Suite##_##Name); \
  static void btest_##Suite##_##Name()

#define BT_EXPECT(cond)                                                      \
  do {                                                                       \
    if (!(cond)) ::btest::report_failure(__FILE__, __LINE__, "expected: " #cond); \
  } while (0)

#define BT_EXPECT_EQ(a, b)                                                   \
  do {                                                                       \
    auto _va = (a);                                                          \
    auto _vb = (b);                                                          \
    if (!(_va == _vb))                                                       \
      ::btest::report_failure(__FILE__, __LINE__, ::btest::fmt_cmp("==", _va, _vb)); \
  } while (0)

#define BT_EXPECT_NE(a, b)                                                   \
  do {                                                                       \
    auto _va = (a);                                                          \
    auto _vb = (b);                                                          \
    if (_va == _vb)                                                          \
      ::btest::report_failure(__FILE__, __LINE__, ::btest::fmt_cmp("!=", _va, _vb)); \
  } while (0)

#define BT_ASSERT(cond)                                                      \
  do {                                                                       \
    if (!(cond)) {                                                           \
      ::btest::report_failure(__FILE__, __LINE__, "required: " #cond);       \
      return;                                                                \
    }                                                                        \
  } while (0)

#define BT_ASSERT_OK(result_expr)                                            \
  do {                                                                       \
    if (!(result_expr).ok()) {                                               \
      ::btest::report_failure(__FILE__, __LINE__,                            \
                              std::string("required OK, got error ") +       \
                                  std::string(::btpu::to_string((result_expr).error()))); \
      return;                                                                \
    }                                                                        \
  } while (0)
