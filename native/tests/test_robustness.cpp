// Overload-robustness layer: deadlines + retry budgets (btpu/common/
// deadline.h), admission control (admission.h), circuit breakers
// (circuit_breaker.h), deadline propagation over the keystone RPC wire and
// the TCP data plane, latency fault injection, and hedged replica reads.
#include <unistd.h>

#include <atomic>
#include <cstdlib>
#include <cstring>
#include <thread>

#include "btest.h"
#include "btpu/client/embedded.h"
#include "btpu/common/admission.h"
#include "btpu/common/circuit_breaker.h"
#include "btpu/common/deadline.h"
#include "btpu/common/wire.h"
#include "btpu/net/net.h"
#include "btpu/rpc/rpc.h"
#include "btpu/rpc/rpc_client.h"
#include "btpu/rpc/rpc_server.h"
#include "btpu/transport/transport.h"

using namespace btpu;
using namespace btpu::client;

namespace {

std::vector<uint8_t> pattern(uint64_t size, uint8_t seed = 1) {
  std::vector<uint8_t> data(size);
  for (uint64_t i = 0; i < size; ++i) data[i] = static_cast<uint8_t>(i * 131 + seed);
  return data;
}

uint64_t parse_rkey(const RemoteDescriptor& d) { return std::stoull(d.rkey_hex, nullptr, 16); }

uint64_t ms_since(std::chrono::steady_clock::time_point t0) {
  return static_cast<uint64_t>(std::chrono::duration_cast<std::chrono::milliseconds>(
                                   std::chrono::steady_clock::now() - t0)
                                   .count());
}

// Scoped setenv: the admission/test-delay knobs are read at server
// construction, so tests set them around the fixture and restore after.
struct ScopedEnv {
  ScopedEnv(const char* name, const std::string& value) : name_(name) {
    if (const char* old = std::getenv(name)) saved_ = old;
    ::setenv(name, value.c_str(), 1);
  }
  ~ScopedEnv() {
    if (saved_.empty())
      ::unsetenv(name_);
    else
      ::setenv(name_, saved_.c_str(), 1);
  }
  const char* name_;
  std::string saved_;
};

// The first wire endpoint of copy `i` of `key` (latency/fault targets).
std::string first_endpoint(ObjectClient& client, const ObjectKey& key, size_t copy) {
  auto placements = client.get_workers(key);
  if (!placements.ok() || placements.value().size() <= copy) return "";
  for (const auto& shard : placements.value()[copy].shards) {
    if (!shard.remote.endpoint.empty()) return shard.remote.endpoint;
  }
  return "";
}

}  // namespace

// ---- primitives ------------------------------------------------------------

BTEST(Robust, DeadlineBasics) {
  Deadline none;
  BT_EXPECT(none.is_infinite());
  BT_EXPECT(!none.expired());
  BT_EXPECT_EQ(none.wire_budget_ms(), 0u);
  BT_EXPECT(Deadline::after_ms(0).is_infinite());
  BT_EXPECT(Deadline::after_ms(-5).is_infinite());
  BT_EXPECT(Deadline::from_wire(0).is_infinite());

  Deadline soon = Deadline::after_ms(10'000);
  BT_EXPECT(!soon.expired());
  BT_EXPECT(soon.remaining_ms() > 9'000 && soon.remaining_ms() <= 10'000);
  BT_EXPECT(soon.wire_budget_ms() > 9'000 && soon.wire_budget_ms() <= 10'000);

  Deadline past = Deadline::at(Deadline::Clock::now() - std::chrono::milliseconds(5));
  BT_EXPECT(past.expired());
  BT_EXPECT_EQ(past.remaining_ms(), 0);
  BT_EXPECT_EQ(past.wire_budget_ms(), 1u);  // never 0 on the wire (= "none")

  BT_EXPECT(soon.min(none).time_point() == soon.time_point());
  BT_EXPECT(past.min(soon).time_point() == past.time_point());
}

BTEST(Robust, OpDeadlineScopeNestsAndTightens) {
  BT_EXPECT(current_op_deadline().is_infinite());
  {
    OpDeadlineScope outer(static_cast<int64_t>(50));
    const Deadline d1 = current_op_deadline();
    BT_EXPECT(!d1.is_infinite());
    {
      // A LOOSER nested scope must not extend the caller's budget.
      OpDeadlineScope inner(static_cast<int64_t>(60'000));
      BT_EXPECT(current_op_deadline().time_point() == d1.time_point());
      // A tighter one tightens.
      OpDeadlineScope tighter(static_cast<int64_t>(1));
      BT_EXPECT(current_op_deadline().time_point() < d1.time_point());
    }
    BT_EXPECT(current_op_deadline().time_point() == d1.time_point());
  }
  BT_EXPECT(current_op_deadline().is_infinite());
}

BTEST(Robust, RetryPolicyJitteredExponentialBackoff) {
  RetryPolicy policy{100, 1000, 2.0, 5};
  for (int round = 0; round < 20; ++round) {
    const uint64_t b0 = policy.backoff_ms(0);
    BT_EXPECT(b0 > 100 / 2 && b0 <= 100);  // equal jitter: (raw/2, raw]
    const uint64_t b2 = policy.backoff_ms(2);
    BT_EXPECT(b2 > 400 / 2 && b2 <= 400);
    const uint64_t b9 = policy.backoff_ms(9);
    BT_EXPECT(b9 > 1000 / 2 && b9 <= 1000);  // capped at max_ms
  }
}

BTEST(Robust, RetryBudgetExtinguishesStormsAndRefills) {
  RetryBudget budget(4.0, 1.0);
  // Above half capacity retries are affordable; the bucket drains in
  // O(capacity) and then refuses until successes refill it.
  BT_EXPECT(budget.try_spend());
  BT_EXPECT(budget.try_spend());
  BT_EXPECT(!budget.try_spend());  // at half capacity (2.0): refused
  BT_EXPECT(!budget.try_spend());
  budget.on_success();
  BT_EXPECT(budget.try_spend());
  // Refunds cap at capacity.
  for (int i = 0; i < 100; ++i) budget.on_success();
  BT_EXPECT(budget.tokens() <= 4.0 + 1e-9);
}

BTEST(Robust, LatencyTrackerQuantiles) {
  LatencyTracker tracker;
  BT_EXPECT_EQ(tracker.quantile_us(0.95, 16), 0ull);  // too few samples
  for (uint64_t i = 1; i <= 100; ++i) tracker.record_us(i * 10);
  const uint64_t p50 = tracker.quantile_us(0.50, 16);
  const uint64_t p95 = tracker.quantile_us(0.95, 16);
  BT_EXPECT(p50 >= 400 && p50 <= 600);
  BT_EXPECT(p95 >= 900 && p95 <= 1000);
}

// ---- circuit breaker -------------------------------------------------------

BTEST(Robust, CircuitBreakerTripHalfOpenRecover) {
  CircuitBreaker::Options opts;
  opts.failure_threshold = 3;
  opts.open_ms = 40;
  opts.half_open_probes = 1;
  CircuitBreaker breaker(opts);

  BT_EXPECT(breaker.allow());
  breaker.record_failure();
  breaker.record_failure();
  BT_EXPECT(breaker.state() == CircuitBreaker::State::kClosed);
  breaker.record_failure();  // third consecutive: trip
  BT_EXPECT(breaker.state() == CircuitBreaker::State::kOpen);
  BT_EXPECT(breaker.open_now());
  BT_EXPECT(!breaker.allow());

  // Cooldown (jittered within [open_ms/2, open_ms]) elapses -> HALF_OPEN
  // admits exactly one probe.
  std::this_thread::sleep_for(std::chrono::milliseconds(opts.open_ms + 5));
  BT_EXPECT(!breaker.open_now());
  BT_EXPECT(breaker.allow());  // the probe
  BT_EXPECT(breaker.state() == CircuitBreaker::State::kHalfOpen);
  BT_EXPECT(!breaker.allow());  // probe budget spent
  // Probe fails: straight back to OPEN for another cooldown.
  breaker.record_failure();
  BT_EXPECT(breaker.state() == CircuitBreaker::State::kOpen);

  std::this_thread::sleep_for(std::chrono::milliseconds(opts.open_ms + 5));
  BT_EXPECT(breaker.allow());
  breaker.record_success(100);  // probe succeeds: recovered
  BT_EXPECT(breaker.state() == CircuitBreaker::State::kClosed);
  BT_EXPECT(breaker.allow());
}

BTEST(Robust, CircuitBreakerLatencyTrip) {
  CircuitBreaker::Options opts;
  opts.slow_threshold = 3;
  opts.slow_floor_us = 100;
  opts.slow_factor = 4.0;
  opts.open_ms = 30;
  CircuitBreaker breaker(opts);
  // Build a fast baseline (EWMA mean ~100us; trip line = 400us).
  for (int i = 0; i < 32; ++i) breaker.record_success(100);
  BT_EXPECT(breaker.state() == CircuitBreaker::State::kClosed);
  // A worker answering correctly but far over the line is operationally
  // DOWN for tail purposes: consecutive slow successes trip the breaker.
  // (Slow outliers are excluded from the EWMA, so the trip line cannot
  // chase the slowness it exists to catch.)
  breaker.record_success(5'000);
  breaker.record_success(5'000);
  BT_EXPECT(breaker.state() == CircuitBreaker::State::kClosed);
  breaker.record_success(5'000);
  BT_EXPECT(breaker.state() == CircuitBreaker::State::kOpen);

  // A probe that answers but is STILL over the line must re-open, not
  // close-and-fold: folding the slow probe would converge the EWMA onto the
  // slow latency and permanently defeat the trip via the recovery path.
  std::this_thread::sleep_for(std::chrono::milliseconds(opts.open_ms + 5));
  BT_EXPECT(breaker.allow());  // the probe
  breaker.record_success(5'000);
  BT_EXPECT(breaker.state() == CircuitBreaker::State::kOpen);
  const uint64_t mean_after = breaker.mean_latency_us();
  BT_EXPECT(mean_after < 400);  // slow probe stayed OUT of the baseline
  // A genuinely fast probe recovers.
  std::this_thread::sleep_for(std::chrono::milliseconds(opts.open_ms + 5));
  BT_EXPECT(breaker.allow());
  breaker.record_success(100);
  BT_EXPECT(breaker.state() == CircuitBreaker::State::kClosed);
}

// ---- admission gate --------------------------------------------------------

BTEST(Robust, AdmissionGateLifoShedsOldestWaiter) {
  AdmissionGate::Options opts;
  opts.max_inflight = 1;
  opts.max_queue = 1;
  opts.backoff_hint_ms = 17;
  AdmissionGate gate(opts);

  BT_EXPECT(gate.admit(Deadline::infinite()) == AdmissionGate::Verdict::kAdmitted);

  // Waiter A queues; a later arrival overflows the queue and A — the OLDEST
  // waiter, the one closest to its client-side timeout — is the one shed.
  std::atomic<int> a_verdict{-1};
  std::thread a([&] {
    a_verdict = static_cast<int>(gate.admit(Deadline::infinite()));
    if (a_verdict.load() == static_cast<int>(AdmissionGate::Verdict::kAdmitted))
      gate.release();
  });
  while (gate.queued() == 0) std::this_thread::sleep_for(std::chrono::milliseconds(1));

  std::atomic<int> b_verdict{-1};
  std::thread b([&] {
    b_verdict = static_cast<int>(gate.admit(Deadline::infinite()));
    if (b_verdict.load() == static_cast<int>(AdmissionGate::Verdict::kAdmitted))
      gate.release();
  });
  a.join();  // A was shed synchronously by B's arrival
  BT_EXPECT_EQ(a_verdict.load(), static_cast<int>(AdmissionGate::Verdict::kShed));
  BT_EXPECT_EQ(gate.backoff_hint_ms(), 17u);

  gate.release();  // the original holder leaves; B (newest) is admitted
  b.join();
  BT_EXPECT_EQ(b_verdict.load(), static_cast<int>(AdmissionGate::Verdict::kAdmitted));
  BT_EXPECT_EQ(gate.inflight(), 0u);
  BT_EXPECT_EQ(gate.queued(), 0ull);
}

BTEST(Robust, AdmissionGateHonorsWaiterDeadline) {
  AdmissionGate::Options opts;
  opts.max_inflight = 1;
  opts.max_queue = 4;
  AdmissionGate gate(opts);
  BT_EXPECT(gate.admit(Deadline::infinite()) == AdmissionGate::Verdict::kAdmitted);
  // A queued waiter whose own budget expires is rejected without service.
  const auto t0 = std::chrono::steady_clock::now();
  BT_EXPECT(gate.admit(Deadline::after_ms(30)) == AdmissionGate::Verdict::kDeadline);
  BT_EXPECT(ms_since(t0) >= 25);
  gate.release();
  BT_EXPECT_EQ(gate.queued(), 0ull);  // the dead waiter removed itself
}

BTEST(Robust, AdmissionGateBytesWatermark) {
  AdmissionGate::Options opts;
  opts.max_inflight = 8;
  opts.max_queue = 0;  // never wait: refusals are immediate
  opts.max_inflight_bytes = 1000;
  AdmissionGate gate(opts);
  BT_EXPECT(gate.admit(Deadline::infinite(), 900) == AdmissionGate::Verdict::kAdmitted);
  // Over the bytes watermark with something already in flight: shed.
  BT_EXPECT(gate.admit(Deadline::infinite(), 200) == AdmissionGate::Verdict::kShed);
  gate.release(900);
  // An oversized single request is never deadlocked out: bytes only brake
  // when something else is in flight.
  BT_EXPECT(gate.admit(Deadline::infinite(), 5000) == AdmissionGate::Verdict::kAdmitted);
  gate.release(5000);
}

// ---- keystone RPC deadline propagation + admission -------------------------

namespace {
struct RpcRobustFixture {
  keystone::KeystoneService ks{[] {
                                 KeystoneConfig c;
                                 c.gc_interval_sec = 1;
                                 c.health_check_interval_sec = 1;
                                 return c;
                               }(),
                               nullptr};
  std::unique_ptr<transport::TransportServer> transport_server;
  std::vector<uint8_t> memory;
  std::unique_ptr<rpc::KeystoneRpcServer> server;
  std::unique_ptr<rpc::KeystoneRpcClient> client;

  bool up() {
    if (ks.initialize() != ErrorCode::OK) return false;
    memory.resize(1 << 20);
    transport_server = transport::make_transport_server(TransportKind::LOCAL);
    BT_EXPECT_OK(transport_server->start("", 0));
    auto reg = transport_server->register_region(memory.data(), memory.size(), "p0");
    if (!reg.ok()) return false;
    keystone::WorkerInfo w;
    w.worker_id = "w0";
    w.address = "local:w0";
    BT_EXPECT_OK(ks.register_worker(w));
    MemoryPool pool;
    pool.id = "p0";
    pool.node_id = "w0";
    pool.size = memory.size();
    pool.storage_class = StorageClass::RAM_CPU;
    pool.remote = reg.value();
    BT_EXPECT_OK(ks.register_memory_pool(pool));
    server = std::make_unique<rpc::KeystoneRpcServer>(ks, "127.0.0.1", 0);
    if (server->start() != ErrorCode::OK) return false;
    client = std::make_unique<rpc::KeystoneRpcClient>(server->endpoint());
    return client->connect() == ErrorCode::OK;
  }
};
}  // namespace

BTEST(RpcRobust, ExpiredOnArrivalRejectedBeforeAnyWork) {
  RpcRobustFixture f;
  BT_ASSERT(f.up());
  const uint64_t rejected_before = robust_counters().deadline_exceeded.load();

  // Hand-framed request whose wire budget is 0 = "expired on arrival"
  // (clients never send this; the server must refuse before dispatch).
  auto hp = net::parse_host_port(f.server->endpoint());
  BT_ASSERT(hp.has_value());
  auto sock = net::tcp_connect(hp->host, hp->port);
  BT_ASSERT(sock.ok());
  std::vector<uint8_t> payload = wire::to_bytes(ObjectExistsRequest{"any"});
  rpc::append_deadline_trailer(payload, 0);
  BT_ASSERT(net::send_frame(sock.value().fd(), static_cast<uint8_t>(rpc::Method::kObjectExists),
                            payload.data(), payload.size()) == ErrorCode::OK);
  uint8_t resp_op = 0;
  std::vector<uint8_t> resp;
  BT_ASSERT(net::recv_frame(sock.value().fd(), resp_op, resp) == ErrorCode::OK);
  BT_EXPECT_EQ(resp_op, rpc::kControlErrorOpcode);
  ErrorCode code{};
  uint32_t hint = 0;
  BT_ASSERT(rpc::decode_control_error(resp, code, hint));
  BT_EXPECT(code == ErrorCode::DEADLINE_EXCEEDED);
  BT_EXPECT(robust_counters().deadline_exceeded.load() > rejected_before);

  // The connection survives a rejection: a fresh healthy request on the
  // same socket is answered normally.
  payload = wire::to_bytes(ObjectExistsRequest{"any"});
  BT_ASSERT(net::send_frame(sock.value().fd(), static_cast<uint8_t>(rpc::Method::kObjectExists),
                            payload.data(), payload.size()) == ErrorCode::OK);
  BT_ASSERT(net::recv_frame(sock.value().fd(), resp_op, resp) == ErrorCode::OK);
  BT_EXPECT_EQ(resp_op, static_cast<uint8_t>(rpc::Method::kObjectExists));
}

BTEST(RpcRobust, ClientFailsLocallyWhenBudgetAlreadySpent) {
  RpcRobustFixture f;
  BT_ASSERT(f.up());
  OpDeadlineScope expired(Deadline::at(Deadline::Clock::now() - std::chrono::milliseconds(1)));
  auto result = f.client->object_exists("any");
  BT_ASSERT(!result.ok());
  BT_EXPECT(result.error() == ErrorCode::DEADLINE_EXCEEDED);
}

BTEST(RpcRobust, MidServiceExpiryAnswersDeadlineExceededForReads) {
  // The service delay outlives the caller's budget: the keystone performs
  // the (read-only) dispatch but must answer DEADLINE_EXCEEDED — the answer
  // outlived its asker.
  ScopedEnv delay("BTPU_RPC_TEST_DELAY_MS", "120");
  RpcRobustFixture f;
  BT_ASSERT(f.up());
  {
    OpDeadlineScope scope(static_cast<int64_t>(60));
    auto result = f.client->object_exists("any");
    BT_ASSERT(!result.ok());
    BT_EXPECT(result.error() == ErrorCode::DEADLINE_EXCEEDED);
  }
  // Without a deadline the same slow call completes fine.
  BT_ASSERT_OK(f.client->object_exists("any"));
}

BTEST(RpcRobust, OverloadShedsWithRetryLaterWhileControlPlaneAnswers) {
  // A 1-deep gate with a 1-deep queue and a slow service: a burst must shed
  // with RETRY_LATER (+hint) while control-plane pings keep answering.
  ScopedEnv inflight("BTPU_RPC_MAX_INFLIGHT", "1");
  ScopedEnv queue("BTPU_RPC_MAX_QUEUE", "1");
  ScopedEnv delay("BTPU_RPC_TEST_DELAY_MS", "120");
  RpcRobustFixture f;
  BT_ASSERT(f.up());

  const uint64_t shed_before = robust_counters().shed.load();
  // Retries OFF for the storm clients: the point is to observe the shed.
  RetryPolicy no_retry{1, 1, 1.0, 1};

  constexpr int kStorm = 6;
  std::vector<std::unique_ptr<rpc::KeystoneRpcClient>> clients;
  for (int i = 0; i < kStorm; ++i) {
    clients.push_back(std::make_unique<rpc::KeystoneRpcClient>(f.server->endpoint()));
    clients.back()->set_retry_policy(no_retry);
    BT_ASSERT(clients.back()->connect() == ErrorCode::OK);
  }
  std::atomic<int> shed_seen{0}, ok_seen{0};
  std::vector<std::thread> storm;
  for (int i = 0; i < kStorm; ++i) {
    storm.emplace_back([&, i] {
      auto result = clients[i]->object_exists("storm");
      if (!result.ok() && result.error() == ErrorCode::RETRY_LATER)
        shed_seen.fetch_add(1);
      else if (result.ok())
        ok_seen.fetch_add(1);
    });
  }
  // While the storm saturates the gate, the control plane stays usable:
  // ping bypasses admission entirely.
  std::this_thread::sleep_for(std::chrono::milliseconds(20));
  const auto t0 = std::chrono::steady_clock::now();
  BT_ASSERT_OK(f.client->ping());
  BT_EXPECT(ms_since(t0) < 100);  // did not queue behind the 120ms-each storm
  for (auto& t : storm) t.join();

  BT_EXPECT(shed_seen.load() >= 1);
  BT_EXPECT(ok_seen.load() >= 1);  // inflight + queued still complete
  BT_EXPECT(robust_counters().shed.load() > shed_before);
}

BTEST(RpcRobust, ShedsRetryTransparentlyWithBackoffHint) {
  // Default retry policy: the storm client retries RETRY_LATER sheds after
  // the hinted backoff, so a transient burst is absorbed, not surfaced.
  ScopedEnv inflight("BTPU_RPC_MAX_INFLIGHT", "1");
  ScopedEnv queue("BTPU_RPC_MAX_QUEUE", "0");  // every concurrent call sheds
  ScopedEnv delay("BTPU_RPC_TEST_DELAY_MS", "40");
  RpcRobustFixture f;
  BT_ASSERT(f.up());

  const uint64_t retries_before = robust_counters().retries.load();
  constexpr int kCallers = 3;
  std::vector<std::unique_ptr<rpc::KeystoneRpcClient>> clients;
  for (int i = 0; i < kCallers; ++i) {
    clients.push_back(std::make_unique<rpc::KeystoneRpcClient>(f.server->endpoint()));
    RetryPolicy patient{5, 50, 2.0, 8};
    clients.back()->set_retry_policy(patient);
    BT_ASSERT(clients.back()->connect() == ErrorCode::OK);
  }
  std::atomic<int> ok_seen{0};
  std::vector<std::thread> callers;
  for (int i = 0; i < kCallers; ++i) {
    callers.emplace_back([&, i] {
      if (clients[i]->object_exists("burst").ok()) ok_seen.fetch_add(1);
    });
  }
  for (auto& t : callers) t.join();
  BT_EXPECT_EQ(ok_seen.load(), kCallers);  // everyone eventually served
  BT_EXPECT(robust_counters().retries.load() > retries_before);
}

// ---- latency fault injection ------------------------------------------------

BTEST(Transport, FaultSpecInjectsLatencyFixedJitterAndOverride) {
  // A local loopback region to read through the faulty wrapper.
  auto server = transport::make_transport_server(TransportKind::LOCAL);
  BT_ASSERT(server->start("", 0) == ErrorCode::OK);
  std::vector<uint8_t> region(4096, 0xAB);
  auto reg = server->register_region(region.data(), region.size(), "lat0");
  BT_ASSERT(reg.ok());

  transport::FaultSpec spec;
  spec.latency_ms = 40;
  auto slow = transport::make_faulty_transport_client(transport::make_transport_client(),
                                                      spec);
  std::vector<uint8_t> buf(256);
  auto t0 = std::chrono::steady_clock::now();
  BT_ASSERT(slow->read(reg.value(), reg.value().remote_base, parse_rkey(reg.value()), buf.data(),
                       buf.size()) == ErrorCode::OK);
  BT_EXPECT(ms_since(t0) >= 40);
  BT_EXPECT_EQ(buf[0], 0xAB);

  // Endpoint-narrowed: a different endpoint is unaffected.
  transport::FaultSpec narrow;
  narrow.latency_ms = 200;
  narrow.latency_endpoint = "someone-else:1234";
  auto fast = transport::make_faulty_transport_client(transport::make_transport_client(),
                                                      narrow);
  t0 = std::chrono::steady_clock::now();
  BT_ASSERT(fast->read(reg.value(), reg.value().remote_base, parse_rkey(reg.value()), buf.data(),
                       buf.size()) == ErrorCode::OK);
  BT_EXPECT(ms_since(t0) < 100);

  // Dynamic override: a chaos thread spikes and clears latency mid-run
  // without swapping transports under I/O.
  auto dial = std::make_shared<std::atomic<uint32_t>>(0);
  transport::FaultSpec dynamic;
  dynamic.latency_override_ms = dial;
  auto dialed = transport::make_faulty_transport_client(transport::make_transport_client(),
                                                        dynamic);
  t0 = std::chrono::steady_clock::now();
  BT_ASSERT(dialed->read(reg.value(), reg.value().remote_base, parse_rkey(reg.value()), buf.data(),
                         buf.size()) == ErrorCode::OK);
  BT_EXPECT(ms_since(t0) < 30);  // dial at 0: no injection
  dial->store(50);
  t0 = std::chrono::steady_clock::now();
  BT_ASSERT(dialed->read(reg.value(), reg.value().remote_base, parse_rkey(reg.value()), buf.data(),
                         buf.size()) == ErrorCode::OK);
  BT_EXPECT(ms_since(t0) >= 50);
}

// ---- hedged replica reads + breakers, end to end ---------------------------

BTEST(EndToEnd, HedgedReadFirstWinsUnderSlowReplica) {
  EmbeddedCluster cluster(EmbeddedClusterOptions::simple(2, 8 << 20));
  BT_ASSERT(cluster.start() == ErrorCode::OK);
  ClientOptions copts;
  copts.hedge_reads = true;
  copts.hedge_delay_ms = 10;  // fixed trigger: deterministic for the test
  auto client = cluster.make_client(copts);

  WorkerConfig cfg;
  cfg.replication_factor = 2;
  cfg.max_workers_per_copy = 1;
  auto data = pattern(64 * 1024, 77);
  BT_ASSERT(client->put("hedge/obj", data.data(), data.size(), cfg) == ErrorCode::OK);

  // Copy 0 (the first candidate) goes 300ms slow; the hedge fires at 10ms
  // against copy 1 and must win long before the primary would finish.
  const std::string slow_ep = first_endpoint(*client, "hedge/obj", 0);
  BT_ASSERT(!slow_ep.empty());
  transport::FaultSpec spec;
  spec.latency_ms = 300;
  spec.latency_endpoint = slow_ep;
  client->inject_data_client_for_test(
      transport::make_faulty_transport_client(transport::make_transport_client(), spec));

  const uint64_t fired_before = robust_counters().hedges_fired.load();
  const uint64_t wins_before = robust_counters().hedge_wins.load();
  const size_t samples_before = client->read_latency().samples();

  const auto t0 = std::chrono::steady_clock::now();
  auto back = client->get("hedge/obj");
  const uint64_t took_ms = ms_since(t0);
  BT_ASSERT_OK(back);
  BT_EXPECT(back.value() == data);
  BT_EXPECT(took_ms < 200);  // the 300ms primary did NOT gate the read
  BT_EXPECT(robust_counters().hedges_fired.load() > fired_before);
  BT_EXPECT(robust_counters().hedge_wins.load() > wins_before);
  // First-wins, counted once: exactly one effective-latency sample for one
  // logical read (the loser drains into a discard buffer).
  BT_EXPECT_EQ(client->read_latency().samples(), samples_before + 1);

  // The client must be destructible while a loser attempt is still
  // in flight — the destructor drains hedge threads (tsan covers the rest).
  client.reset();
}

BTEST(EndToEnd, HedgeLoserFailureDoesNotPoisonWinner) {
  // The slow replica is also BROKEN: the hedge wins with good bytes, and
  // the loser's eventual failure must not surface.
  EmbeddedCluster cluster(EmbeddedClusterOptions::simple(2, 8 << 20));
  BT_ASSERT(cluster.start() == ErrorCode::OK);
  ClientOptions copts;
  copts.hedge_reads = true;
  copts.hedge_delay_ms = 5;
  auto client = cluster.make_client(copts);

  WorkerConfig cfg;
  cfg.replication_factor = 2;
  cfg.max_workers_per_copy = 1;
  auto data = pattern(32 * 1024, 91);
  BT_ASSERT(client->put("hedge/poison", data.data(), data.size(), cfg) == ErrorCode::OK);

  const std::string bad_ep = first_endpoint(*client, "hedge/poison", 0);
  BT_ASSERT(!bad_ep.empty());
  transport::FaultSpec spec;
  spec.latency_ms = 100;
  spec.latency_endpoint = bad_ep;
  spec.fail_endpoint = bad_ep;  // slow AND failing
  client->inject_data_client_for_test(
      transport::make_faulty_transport_client(transport::make_transport_client(), spec));

  auto back = client->get("hedge/poison");
  BT_ASSERT_OK(back);
  BT_EXPECT(back.value() == data);
}

BTEST(EndToEnd, BreakerTripsAndRoutesAroundFailingReplicaThenRecovers) {
  EmbeddedCluster cluster(EmbeddedClusterOptions::simple(2, 8 << 20));
  BT_ASSERT(cluster.start() == ErrorCode::OK);
  ClientOptions copts;
  copts.hedge_reads = false;  // isolate the breaker behavior
  copts.breaker.failure_threshold = 2;
  copts.breaker.open_ms = 60;
  auto client = cluster.make_client(copts);

  WorkerConfig cfg;
  cfg.replication_factor = 2;
  cfg.max_workers_per_copy = 1;
  auto data = pattern(16 * 1024, 13);
  BT_ASSERT(client->put("breaker/obj", data.data(), data.size(), cfg) == ErrorCode::OK);

  const std::string bad_ep = first_endpoint(*client, "breaker/obj", 0);
  BT_ASSERT(!bad_ep.empty());
  transport::FaultSpec spec;
  spec.fail_endpoint = bad_ep;
  client->inject_data_client_for_test(
      transport::make_faulty_transport_client(transport::make_transport_client(), spec));

  const uint64_t trips_before = robust_counters().breaker_trips.load();
  const uint64_t skips_before = robust_counters().breaker_skips.load();
  // Each read fails over to the healthy replica; after failure_threshold
  // failures the breaker opens and later reads don't even try the bad one.
  for (int i = 0; i < 4; ++i) {
    auto back = client->get("breaker/obj");
    BT_ASSERT_OK(back);
    BT_EXPECT(back.value() == data);
  }
  auto breaker = client->breakers().peek(bad_ep);
  BT_ASSERT(breaker != nullptr);
  BT_EXPECT(breaker->state() == CircuitBreaker::State::kOpen);
  BT_EXPECT(robust_counters().breaker_trips.load() > trips_before);
  BT_EXPECT(robust_counters().breaker_skips.load() > skips_before);

  // Heal the endpoint; after the cooldown a half-open probe closes the
  // breaker again (reads keep succeeding throughout).
  client->inject_data_client_for_test(transport::make_transport_client());
  std::this_thread::sleep_for(std::chrono::milliseconds(80));
  for (int i = 0; i < 3; ++i) BT_ASSERT_OK(client->get("breaker/obj"));
  BT_EXPECT(breaker->state() == CircuitBreaker::State::kClosed);
}

BTEST(EndToEnd, AllBreakersOpenStillReads) {
  // Degraded beats dead: when EVERY replica's breaker is open the read must
  // still proceed in original order rather than refuse.
  EmbeddedCluster cluster(EmbeddedClusterOptions::simple(2, 8 << 20));
  BT_ASSERT(cluster.start() == ErrorCode::OK);
  ClientOptions copts;
  copts.hedge_reads = false;
  copts.breaker.failure_threshold = 1;
  copts.breaker.open_ms = 60'000;  // stays open for the whole test
  auto client = cluster.make_client(copts);

  WorkerConfig cfg;
  cfg.replication_factor = 2;
  cfg.max_workers_per_copy = 1;
  auto data = pattern(8 * 1024, 44);
  BT_ASSERT(client->put("breaker/all", data.data(), data.size(), cfg) == ErrorCode::OK);

  // Trip copy 0's endpoint, then copy 1's, with one failing read each.
  transport::FaultSpec all_fail;
  all_fail.fail_endpoint = first_endpoint(*client, "breaker/all", 0);
  client->inject_data_client_for_test(
      transport::make_faulty_transport_client(transport::make_transport_client(), all_fail));
  BT_ASSERT_OK(client->get("breaker/all"));  // copy0 fails (trips), copy1 serves
  transport::FaultSpec other_fail;
  other_fail.fail_endpoint = first_endpoint(*client, "breaker/all", 1);
  client->inject_data_client_for_test(
      transport::make_faulty_transport_client(transport::make_transport_client(), other_fail));
  BT_ASSERT_OK(client->get("breaker/all"));  // copy1 fails (trips), copy0 serves

  // Both open now; a healthy transport must still serve the read.
  client->inject_data_client_for_test(transport::make_transport_client());
  auto back = client->get("breaker/all");
  BT_ASSERT_OK(back);
  BT_EXPECT(back.value() == data);
}

BTEST(EndToEnd, OpDeadlineFailsDoomedReplicaCascade) {
  // With every replica's transfer slower than the whole budget, the op must
  // fail DEADLINE_EXCEEDED after the first attempt instead of marching
  // through the remaining replicas (doomed work).
  EmbeddedCluster cluster(EmbeddedClusterOptions::simple(3, 8 << 20));
  BT_ASSERT(cluster.start() == ErrorCode::OK);
  ClientOptions copts;
  copts.hedge_reads = false;
  copts.op_deadline_ms = 40;
  auto client = cluster.make_client(copts);

  WorkerConfig cfg;
  cfg.replication_factor = 3;
  cfg.max_workers_per_copy = 1;
  auto data = pattern(16 * 1024, 3);
  BT_ASSERT(client->put("deadline/cascade", data.data(), data.size(), cfg) == ErrorCode::OK);

  transport::FaultSpec spec;
  spec.latency_ms = 60;          // every transfer outlives the 40ms budget
  spec.fail_nth_read = 1;        // and the first read also fails outright
  client->inject_data_client_for_test(
      transport::make_faulty_transport_client(transport::make_transport_client(), spec));

  const auto t0 = std::chrono::steady_clock::now();
  auto back = client->get("deadline/cascade");
  const uint64_t took_ms = ms_since(t0);
  BT_ASSERT(!back.ok());
  BT_EXPECT(back.error() == ErrorCode::DEADLINE_EXCEEDED);
  // One 60ms attempt, not three: the cascade was cut at the deadline check.
  BT_EXPECT(took_ms < 150);
}

// ---- data-plane (TCP) admission + deadline ---------------------------------

BTEST(TcpRobust, WireVersionMismatchRefusedBeforeAnyByte) {
  // The raw packed data-plane headers have no length prefix: a peer on a
  // DIFFERENT framing dialect would desync the stream. The descriptor
  // advertises the dialect; a positive mismatch is refused locally with
  // REMOTE_ENDPOINT_ERROR (before any byte goes out), while 0 (legacy /
  // WAL-restored metadata) and the matching version are served.
  auto server = transport::make_transport_server(TransportKind::TCP);
  BT_ASSERT(server->start("127.0.0.1", 0) == ErrorCode::OK);
  std::vector<uint8_t> backing(1 << 16, 0x3C);
  auto reg = server->register_region(backing.data(), backing.size(), "verchk");
  BT_ASSERT(reg.ok());
  BT_EXPECT_EQ(reg.value().data_wire_version, transport::kTcpDataWireVersion);

  auto client = transport::make_transport_client();
  std::vector<uint8_t> buf(4096);
  // Matching version: served.
  BT_EXPECT(client->read(reg.value(), reg.value().remote_base, parse_rkey(reg.value()),
                         buf.data(), buf.size()) == ErrorCode::OK);
  BT_EXPECT_EQ(buf[0], 0x3C);
  // Pre-versioned metadata (0): served under the ship-together contract.
  RemoteDescriptor legacy = reg.value();
  legacy.data_wire_version = 0;
  BT_EXPECT(client->read(legacy, legacy.remote_base, parse_rkey(legacy), buf.data(),
                         buf.size()) == ErrorCode::OK);
  // Positive mismatch: refused, single-op and batch lanes both.
  RemoteDescriptor future = reg.value();
  future.data_wire_version = transport::kTcpDataWireVersion + 1;
  BT_EXPECT(client->read(future, future.remote_base, parse_rkey(future), buf.data(),
                         buf.size()) == ErrorCode::REMOTE_ENDPOINT_ERROR);
  transport::WireOp op{};
  op.remote = &future;
  op.addr = future.remote_base;
  op.rkey = parse_rkey(future);
  op.buf = buf.data();
  op.len = buf.size();
  BT_EXPECT(client->read_batch(&op, 1, 0) == ErrorCode::REMOTE_ENDPOINT_ERROR);
  BT_EXPECT(op.status == ErrorCode::REMOTE_ENDPOINT_ERROR);
}

BTEST(TcpRobust, DataGateShedsUnderSaturationAndServesAfter) {
  // A 1-op gate with no queue on the TCP data server: a second concurrent
  // op sheds with RETRY_LATER while the first (slow, virtual-region-backed)
  // is in flight; after the gate clears, ops are served again.
  ScopedEnv ops("BTPU_DATA_MAX_INFLIGHT_OPS", "1");
  ScopedEnv queue("BTPU_DATA_MAX_QUEUE", "0");
  auto server = transport::make_transport_server(TransportKind::TCP);
  BT_ASSERT(server->start("127.0.0.1", 0) == ErrorCode::OK);

  // A virtual region whose reads take 150ms (a wedged/slow backend).
  std::atomic<int> served{0};
  auto reg = server->register_virtual_region(
      1 << 20, "slowvr",
      [&](uint64_t, void* dst, uint64_t len) {
        std::this_thread::sleep_for(std::chrono::milliseconds(150));
        std::memset(dst, 0x5A, len);
        served.fetch_add(1);
        return ErrorCode::OK;
      },
      [&](uint64_t, const void*, uint64_t) { return ErrorCode::OK; });
  BT_ASSERT(reg.ok());

  const uint64_t shed_before = robust_counters().shed.load();
  auto client = transport::make_transport_client();
  std::atomic<int> ok_count{0}, shed_count{0};
  std::vector<std::thread> readers;
  for (int i = 0; i < 3; ++i) {
    readers.emplace_back([&] {
      std::vector<uint8_t> buf(4096);
      const auto ec = client->read(reg.value(), 0, parse_rkey(reg.value()), buf.data(), buf.size());
      if (ec == ErrorCode::OK)
        ok_count.fetch_add(1);
      else if (ec == ErrorCode::RETRY_LATER)
        shed_count.fetch_add(1);
    });
    // Stagger so the first is mid-service when the rest arrive.
    std::this_thread::sleep_for(std::chrono::milliseconds(20));
  }
  for (auto& t : readers) t.join();
  BT_EXPECT(ok_count.load() >= 1);
  BT_EXPECT(shed_count.load() >= 1);
  BT_EXPECT(robust_counters().shed.load() > shed_before);

  // Gate cleared: the next read is served.
  std::vector<uint8_t> buf(4096);
  BT_EXPECT(client->read(reg.value(), 0, parse_rkey(reg.value()), buf.data(), buf.size()) ==
            ErrorCode::OK);
  BT_EXPECT_EQ(buf[0], 0x5A);
}

BTEST(TcpRobust, WireDeadlinePropagatesAndExpiredSubOpsFailLocally) {
  auto server = transport::make_transport_server(TransportKind::TCP);
  BT_ASSERT(server->start("127.0.0.1", 0) == ErrorCode::OK);
  std::vector<uint8_t> region(1 << 20);
  auto reg = server->register_region(region.data(), region.size(), "dlr");
  BT_ASSERT(reg.ok());
  auto client = transport::make_transport_client();

  // A healthy deadline rides the wire and the op completes.
  {
    OpDeadlineScope scope(static_cast<int64_t>(5'000));
    std::vector<uint8_t> buf(64 * 1024, 0x33);
    BT_EXPECT(client->write(reg.value(), reg.value().remote_base, parse_rkey(reg.value()),
                            buf.data(), buf.size()) == ErrorCode::OK);
    BT_EXPECT_EQ(region[0], 0x33);
  }
  // A spent budget fails locally before any bytes move.
  {
    OpDeadlineScope scope(Deadline::at(Deadline::Clock::now() - std::chrono::milliseconds(1)));
    const uint64_t before = robust_counters().client_deadline_exceeded.load();
    std::vector<uint8_t> buf(4096, 0x44);
    BT_EXPECT(client->write(reg.value(), reg.value().remote_base, parse_rkey(reg.value()),
                            buf.data(), buf.size()) == ErrorCode::DEADLINE_EXCEEDED);
    BT_EXPECT(robust_counters().client_deadline_exceeded.load() > before);
  }
}
