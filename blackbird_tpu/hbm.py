"""JAX-backed HBM provider: TPU device buffers as the top storage tier.

The native HbmBackend talks to a C ABI provider table (hbm_provider.h). This
module implements that table with JAX: a region is a list of fixed-size
device-resident uint8 chunks on one TPU chip; read/write are host<->device
transfers. Registering the provider flips every HBM_TPU pool in this process
from the built-in host-memory emulation to real device memory.

Granularity: writes/reads are chunk-based (default 1 MiB). Whole-chunk
writes cost one device_put; partial-chunk writes read-modify-write through
the host, so align shard sizes to the chunk size for peak throughput (the
native allocator's min_shard_size does this for you when set to >= chunk).
"""

from __future__ import annotations

import ctypes
import threading

import numpy as np

from blackbird_tpu.native import lib

_u64 = ctypes.c_uint64

_ALLOC_FN = ctypes.CFUNCTYPE(ctypes.c_int, ctypes.c_void_p, ctypes.c_char_p, _u64,
                             ctypes.POINTER(_u64))
_FREE_FN = ctypes.CFUNCTYPE(ctypes.c_int, ctypes.c_void_p, _u64)
_WRITE_FN = ctypes.CFUNCTYPE(ctypes.c_int, ctypes.c_void_p, _u64, _u64, ctypes.c_void_p, _u64)
_READ_FN = ctypes.CFUNCTYPE(ctypes.c_int, ctypes.c_void_p, _u64, _u64, ctypes.c_void_p, _u64)
_AVAIL_FN = ctypes.CFUNCTYPE(_u64, ctypes.c_void_p, ctypes.c_char_p)


class _ProviderStruct(ctypes.Structure):
    _fields_ = [
        ("ctx", ctypes.c_void_p),
        ("alloc_region", _ALLOC_FN),
        ("free_region", _FREE_FN),
        ("write", _WRITE_FN),
        ("read", _READ_FN),
        ("available", _AVAIL_FN),
    ]


class JaxHbmProvider:
    """Chunked device-buffer regions managed through JAX."""

    def __init__(self, chunk_bytes: int = 1 << 20):
        import jax

        self._jax = jax
        self.chunk_bytes = chunk_bytes
        self._lock = threading.Lock()
        self._regions: dict[int, dict] = {}
        self._next_id = 1
        self._struct = None  # built in register()

    # -- device helpers ----------------------------------------------------

    def _device_for(self, device_id: str):
        devices = self._jax.local_devices()
        if ":" in device_id:
            try:
                ordinal = int(device_id.split(":", 1)[1])
                if 0 <= ordinal < len(devices):
                    return devices[ordinal]
            except ValueError:
                pass
        return devices[0]

    # -- provider callbacks ------------------------------------------------

    def _alloc(self, _ctx, device_id, size, out_id):
        try:
            device = self._device_for(device_id.decode() if device_id else "tpu:0")
            n_chunks = (size + self.chunk_bytes - 1) // self.chunk_bytes
            zero = np.zeros(self.chunk_bytes, dtype=np.uint8)
            chunks = [self._jax.device_put(zero, device) for _ in range(n_chunks)]
            with self._lock:
                region_id = self._next_id
                self._next_id += 1
                self._regions[region_id] = {
                    "chunks": chunks,
                    "size": size,
                    "device": device,
                }
            out_id[0] = region_id
            return 0
        except Exception:  # noqa: BLE001 - must not raise through the C ABI
            return 1

    def _free(self, _ctx, region_id):
        with self._lock:
            return 0 if self._regions.pop(region_id, None) is not None else 1

    def _rw(self, region_id, offset, buf, length, is_write):
        try:
            with self._lock:
                region = self._regions.get(region_id)
            if region is None or offset + length > region["size"]:
                return 1
            jax = self._jax
            cb = self.chunk_bytes
            src = (
                np.ctypeslib.as_array(ctypes.cast(buf, ctypes.POINTER(ctypes.c_uint8)),
                                      shape=(length,))
                if length
                else np.empty(0, np.uint8)
            )
            if not is_write and length:
                # Prefetch every chunk the read spans before the copy loop:
                # device->host transfers overlap instead of serializing, which
                # matters most when the host<->device link is latency-bound.
                first = offset // cb
                last = (offset + length - 1) // cb
                for chunk in region["chunks"][first : last + 1]:
                    if hasattr(chunk, "copy_to_host_async"):
                        chunk.copy_to_host_async()
            pos = 0
            while pos < length:
                chunk_idx = (offset + pos) // cb
                chunk_off = (offset + pos) % cb
                n = min(length - pos, cb - chunk_off)
                if is_write:
                    if chunk_off == 0 and n == cb:
                        new_chunk = np.array(src[pos : pos + n], copy=True)
                    else:
                        host = np.asarray(region["chunks"][chunk_idx])
                        new_chunk = host.copy()
                        new_chunk[chunk_off : chunk_off + n] = src[pos : pos + n]
                    region["chunks"][chunk_idx] = jax.device_put(new_chunk, region["device"])
                else:
                    host = np.asarray(region["chunks"][chunk_idx])
                    src[pos : pos + n] = host[chunk_off : chunk_off + n]
                pos += n
            return 0
        except Exception:  # noqa: BLE001
            return 1

    def _write(self, _ctx, region_id, offset, buf, length):
        return self._rw(region_id, offset, buf, length, is_write=True)

    def _read(self, _ctx, region_id, offset, buf, length):
        return self._rw(region_id, offset, buf, length, is_write=False)

    def _available(self, _ctx, _device_id):
        return 0  # unknown

    # -- registration ------------------------------------------------------

    def register(self) -> "JaxHbmProvider":
        """Installs this provider process-wide for all HBM_TPU backends."""
        self._struct = _ProviderStruct(
            ctx=None,
            alloc_region=_ALLOC_FN(self._alloc),
            free_region=_FREE_FN(self._free),
            write=_WRITE_FN(self._write),
            read=_READ_FN(self._read),
            available=_AVAIL_FN(self._available),
        )
        lib.btpu_register_hbm_provider(ctypes.cast(ctypes.pointer(self._struct),
                                                   ctypes.c_void_p))
        return self

    @staticmethod
    def unregister() -> None:
        """Restores the built-in host-memory emulation."""
        lib.btpu_register_hbm_provider(None)

    def region_count(self) -> int:
        with self._lock:
            return len(self._regions)

    def synchronize(self) -> None:
        """Blocks until all in-flight device transfers have completed.

        jax.device_put is asynchronous, so a write that has returned may
        still be copying host->device; call this before timing-sensitive
        checkpoints (benchmarks, barrier points)."""
        with self._lock:
            chunks = [c for r in self._regions.values() for c in r["chunks"]]
        for chunk in chunks:
            if hasattr(chunk, "block_until_ready"):
                chunk.block_until_ready()
