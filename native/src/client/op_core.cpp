#include "btpu/client/op_core.h"

#include <algorithm>

#include "btpu/common/env.h"
#include "btpu/common/sched.h"

namespace btpu::client {

ClientCoreCounters& client_core_counters() noexcept {
  static ClientCoreCounters counters;
  return counters;
}

namespace {

uint32_t resolve_lanes(uint32_t requested) {
  if (requested > 0) return std::min(requested, 64u);
  const uint64_t env = env_u64("BTPU_CLIENT_LANES", 0);
  if (env > 0) return static_cast<uint32_t>(std::min<uint64_t>(env, 64));
  const uint32_t hw = std::max(1u, std::thread::hardware_concurrency());
  return std::min(4u, hw);
}

// One op entered the in-flight set: gauge up, peak folded in.
void note_submitted() {
  auto& c = client_core_counters();
  // ordering: relaxed — stat fold.
  c.submitted.fetch_add(1, std::memory_order_relaxed);
  // ordering: relaxed — gauge; readers want a recent value, not an edge.
  const uint64_t now = c.inflight.fetch_add(1, std::memory_order_relaxed) + 1;
  // ordering: relaxed — monotonic max fold; losers retry on a newer peak.
  uint64_t peak = c.peak_inflight.load(std::memory_order_relaxed);
  while (now > peak &&
         !c.peak_inflight.compare_exchange_weak(peak, now, std::memory_order_relaxed)) {
  }
}

void note_completed(ErrorCode status) {
  auto& c = client_core_counters();
  // ordering: relaxed — stat fold.
  c.completed.fetch_add(1, std::memory_order_relaxed);
  if (status == ErrorCode::OPERATION_CANCELLED)
    // ordering: relaxed — stat fold.
    c.cancelled.fetch_add(1, std::memory_order_relaxed);
  // ordering: relaxed — gauge decrement.
  c.inflight.fetch_sub(1, std::memory_order_relaxed);
}

}  // namespace

bool OpCore::Handle::done() const {
  if (!op_) return true;
  MutexLock lock(op_->m);
  return op_->done;
}

bool OpCore::Handle::wait(const Deadline& deadline) const {
  if (!op_) return true;
  MutexLock lock(op_->m);
  while (!op_->done) {
    if (deadline.is_infinite()) {
      op_->cv.wait(lock);
    } else {
      if (op_->cv.wait_until(lock, deadline.time_point()) == std::cv_status::timeout &&
          !op_->done)
        return false;
    }
  }
  return true;
}

void OpCore::Handle::cancel() const {
  if (!op_) return;
  // ordering: relaxed — the flag is re-checked under Op::m-adjacent control
  // flow before every stage; a late observation only delays the skip by one
  // stage, never corrupts state.
  op_->cancel.store(true, std::memory_order_relaxed);
}

ErrorCode OpCore::Handle::status() const {
  if (!op_) return ErrorCode::OK;
  MutexLock lock(op_->m);
  return op_->status;
}

OpCore::OpCore(uint32_t lanes) : lanes_(resolve_lanes(lanes)) {}

OpCore::~OpCore() {
  {
    MutexLock lock(m_);
    stopping_ = true;
  }
  cv_.notify_all();
  std::vector<std::thread> threads;
  {
    MutexLock lock(m_);
    threads.swap(threads_);
  }
  for (auto& t : threads) t.join();
  // Sched-armed per-op threads: wait them out the same way the hedge drain
  // does (notify-under-mutex on the other side, see finish()).
  MutexLock lock(spawn_mutex_);
  // ordering: acquire — pairs with the per-op threads' acq_rel decrement:
  // observing 0 means every spawned op's last touch happened-before teardown.
  while (spawned_.load(std::memory_order_acquire) != 0) spawn_cv_.wait(lock);
}

void OpCore::start_lanes_locked() {
  if (started_) return;
  started_ = true;
  threads_.reserve(lanes_);
  for (uint32_t i = 0; i < lanes_; ++i) threads_.emplace_back([this] { lane_main(); });
}

void OpCore::finish(const std::shared_ptr<Op>& op, ErrorCode status) {
  // Counters fold BEFORE completion publishes: a waiter that wakes on done
  // must already see this op counted completed/cancelled and out of the
  // inflight gauge (ClientCore.CancelBeforeStageSkipsIt pins that order).
  note_completed(status);
  {
    // Notify UNDER the mutex: a waiter (or the batch owner) may free the op
    // handle the instant it observes done, the same discipline as the hedge
    // drain (docs/CORRECTNESS.md).
    MutexLock lock(op->m);
    op->status = status;
    op->done = true;
    op->cv.notify_all();
  }
  // Drop the stage closure: it may pin its own submitter (an async batch
  // holds the op's Handle while the closure holds the batch — a refcount
  // cycle), so a completed op keeping it would leak the whole chain. Only
  // the finishing runner ever touches step, and the op outlives this call
  // through the caller's shared_ptr.
  op->step = nullptr;
}

void OpCore::advance(const std::shared_ptr<Op>& op) {
  // ordering: relaxed — see Handle::cancel.
  if (op->cancel.load(std::memory_order_relaxed)) {
    finish(op, ErrorCode::OPERATION_CANCELLED);
    return;
  }
  if (op->deadline.expired()) {
    // ordering: relaxed — monotonic stat counter.
    robust_counters().client_deadline_exceeded.fetch_add(1, std::memory_order_relaxed);
    finish(op, ErrorCode::DEADLINE_EXCEEDED);
    return;
  }
  Step step;
  {
    // Stages run under the op's deadline so every wire call inside carries
    // the caller's budget (the ambient deadline is thread-local).
    OpDeadlineScope scope(op->deadline);
    step = op->step();
  }
  if (step == Step::kDone) {
    finish(op, ErrorCode::OK);
    return;
  }
  // kYield: back of the queue — lanes interleave every in-flight op.
  {
    MutexLock lock(m_);
    queue_.push_back(op);
  }
  // ordering: relaxed — gauge increment.
  client_core_counters().queue_depth.fetch_add(1, std::memory_order_relaxed);
  cv_.notify_one();
}

void OpCore::lane_main() {
  for (;;) {
    std::shared_ptr<Op> op;
    {
      MutexLock lock(m_);
      ++idle_lanes_;
      while (queue_.empty() && !stopping_) cv_.wait(lock);
      --idle_lanes_;
      if (queue_.empty()) return;  // stopping_ and drained
      op = std::move(queue_.front());
      queue_.pop_front();
    }
    // ordering: relaxed — gauge decrement.
    client_core_counters().queue_depth.fetch_sub(1, std::memory_order_relaxed);
    advance(op);
  }
}

OpCore::Handle OpCore::submit(std::function<Step()> step, Deadline deadline) {
  auto op = std::make_shared<Op>();
  op->step = std::move(step);
  op->deadline = deadline;
  note_submitted();
  if (sched::armed()) {
    // Deterministic mode: the schedule explorer owns every interleaving, so
    // each op gets an adopted thread (the exact shape the Sched fixtures
    // pin) instead of a free-running persistent lane.
    // ordering: acq_rel — increment visible before the spawned thread's
    // decrement; the destructor's acquire drain sees every op retired.
    spawned_.fetch_add(1, std::memory_order_acq_rel);
    BTPU_SCHED_DECL_SPAWN();
    std::thread([this, op] {
      BTPU_SCHED_ADOPT_SPAWNED();
      for (;;) {
        // ordering: relaxed — see Handle::cancel.
        if (op->cancel.load(std::memory_order_relaxed)) {
          finish(op, ErrorCode::OPERATION_CANCELLED);
          break;
        }
        if (op->deadline.expired()) {
          // ordering: relaxed — monotonic stat counter.
          robust_counters().client_deadline_exceeded.fetch_add(1,
                                                               std::memory_order_relaxed);
          finish(op, ErrorCode::DEADLINE_EXCEEDED);
          break;
        }
        Step step_result;
        {
          OpDeadlineScope scope(op->deadline);
          step_result = op->step();
        }
        if (step_result == Step::kDone) {
          finish(op, ErrorCode::OK);
          break;
        }
        BTPU_SCHED_YIELD();  // the explorer decides who advances next
      }
      {
        MutexLock lock(spawn_mutex_);
        // ordering: acq_rel — pairs with the destructor's acquire drain load.
        spawned_.fetch_sub(1, std::memory_order_acq_rel);
        spawn_cv_.notify_all();
      }
    }).detach();
    return Handle(std::move(op));
  }
  {
    MutexLock lock(m_);
    start_lanes_locked();
    queue_.push_back(op);
  }
  // ordering: relaxed — gauge increment.
  client_core_counters().queue_depth.fetch_add(1, std::memory_order_relaxed);
  cv_.notify_one();
  return Handle(std::move(op));
}

bool OpCore::try_run_detached(std::function<void()> fn) {
  if (sched::armed()) return false;  // determinism: the caller spawns + adopts
  auto op = std::make_shared<Op>();
  op->step = [work = std::move(fn)]() {
    work();
    return Step::kDone;
  };
  {
    MutexLock lock(m_);
    if (stopping_) return false;
    start_lanes_locked();
    // A hedge primary queued behind a deep backlog — or with every lane
    // busy and none to dequeue it promptly — would rescue no tail latency;
    // the caller's own spawn is the right valve there. (A lane running an
    // op that hedges also lands here: it is itself busy, so when it is the
    // last free-looking lane this check forces the spawn path and no lane
    // ever waits on an op only itself could run.)
    if (idle_lanes_ == 0 || queue_.size() >= lanes_) return false;
    queue_.push_back(op);
  }
  note_submitted();
  // ordering: relaxed — gauge increment.
  client_core_counters().queue_depth.fetch_add(1, std::memory_order_relaxed);
  cv_.notify_one();
  return true;
}

uint64_t OpCore::queue_depth() const {
  MutexLock lock(m_);
  return queue_.size();
}

}  // namespace btpu::client
