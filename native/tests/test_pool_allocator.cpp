// PoolAllocator unit tests.
// Behavior parity with reference tests/allocation/test_pool_allocator.cpp
// (free-range init, exact alloc/free merge-back, split remainder, best-fit vs
// first-fit, neighbor merges, fragmentation math, concurrency stress).
#include <atomic>
#include <random>
#include <thread>

#include "btest.h"
#include "btpu/alloc/pool_allocator.h"

using namespace btpu;
using namespace btpu::alloc;

namespace {
MemoryPool make_pool(const std::string& id = "pool-0", uint64_t size = 1 << 20,
                     StorageClass cls = StorageClass::RAM_CPU) {
  MemoryPool p;
  p.id = id;
  p.node_id = "node-0";
  p.size = size;
  p.storage_class = cls;
  p.remote = {TransportKind::TCP, "127.0.0.1:7000", 0x10000000, "beef", "", "", 0};
  return p;
}
}  // namespace

BTEST(PoolAllocator, StartsWithOneFreeRangeCoveringPool) {
  PoolAllocator pa(make_pool("p", 4096));
  BT_EXPECT_EQ(pa.total_free(), 4096ull);
  BT_EXPECT_EQ(pa.largest_free_block(), 4096ull);
  BT_EXPECT_EQ(pa.free_range_count(), 1u);
  BT_EXPECT_EQ(pa.fragmentation_ratio(), 0.0);
}

BTEST(PoolAllocator, RejectsInvalidPoolDescriptors) {
  auto expect_throw = [](MemoryPool p) {
    bool threw = false;
    try {
      PoolAllocator pa(p);
    } catch (const std::invalid_argument&) {
      threw = true;
    }
    BT_EXPECT(threw);
  };
  auto zero = make_pool();
  zero.size = 0;
  expect_throw(zero);
  auto no_transport = make_pool();
  no_transport.remote.transport = TransportKind::TRANSPORT_UNSPECIFIED;
  expect_throw(no_transport);
  auto no_endpoint = make_pool();
  no_endpoint.remote.endpoint = "";
  expect_throw(no_endpoint);
  auto bad_rkey = make_pool();
  bad_rkey.remote.rkey_hex = "xyzzy";
  expect_throw(bad_rkey);
}

BTEST(PoolAllocator, ExactAllocationConsumesWholeBlock) {
  PoolAllocator pa(make_pool("p", 4096));
  auto r = pa.allocate(4096);
  BT_ASSERT(r.has_value());
  BT_EXPECT_EQ(r->offset, 0ull);
  BT_EXPECT_EQ(r->length, 4096ull);
  BT_EXPECT_EQ(pa.total_free(), 0ull);
  BT_EXPECT(!pa.allocate(1).has_value());
  pa.free(*r);
  BT_EXPECT_EQ(pa.total_free(), 4096ull);
  BT_EXPECT_EQ(pa.free_range_count(), 1u);
}

BTEST(PoolAllocator, SplitLeavesRemainder) {
  PoolAllocator pa(make_pool("p", 4096));
  auto r = pa.allocate(1000);
  BT_ASSERT(r.has_value());
  BT_EXPECT_EQ(pa.total_free(), 3096ull);
  BT_EXPECT_EQ(pa.largest_free_block(), 3096ull);
  BT_EXPECT_EQ(pa.free_range_count(), 1u);
}

BTEST(PoolAllocator, ZeroSizeAllocationFails) {
  PoolAllocator pa(make_pool());
  BT_EXPECT(!pa.allocate(0).has_value());
  BT_EXPECT(!pa.can_allocate(0));
}

BTEST(PoolAllocator, BestFitPicksSmallestSufficientHole) {
  PoolAllocator pb(make_pool("pb", 10000));
  auto r1 = pb.allocate(2000);  // [0,2000)
  auto r2 = pb.allocate(500);   // [2000,2500) - separator
  auto r3 = pb.allocate(3000);  // [2500,5500)
  auto r4 = pb.allocate(500);   // [5500,6000) - separator
  auto r5 = pb.allocate(4000);  // [6000,10000)
  BT_ASSERT(r1 && r2 && r3 && r4 && r5);
  pb.free(*r1);
  pb.free(*r3);
  pb.free(*r5);
  // Holes now: 2000 @0, 3000 @2500, 4000 @6000. Best fit for 2500 -> @2500.
  auto best = pb.allocate(2500, /*prefer_best_fit=*/true);
  BT_ASSERT(best.has_value());
  BT_EXPECT_EQ(best->offset, 2500ull);
}

BTEST(PoolAllocator, FirstFitPicksLowestOffsetHole) {
  PoolAllocator pa(make_pool("p", 10000));
  auto r1 = pa.allocate(3000);  // [0,3000)
  auto r2 = pa.allocate(500);
  auto r3 = pa.allocate(2000);  // [3500,5500)
  BT_ASSERT(r1 && r2 && r3);
  pa.free(*r1);
  pa.free(*r3);
  // Holes: 3000 @0, 2000 @3500, 4500 @5500. First fit for 1500 -> @0.
  auto first = pa.allocate(1500, /*prefer_best_fit=*/false);
  BT_ASSERT(first.has_value());
  BT_EXPECT_EQ(first->offset, 0ull);
}

BTEST(PoolAllocator, FreeMergesWithLeftNeighbor) {
  PoolAllocator pa(make_pool("p", 8192));
  auto a = pa.allocate(1024);
  auto b = pa.allocate(1024);
  BT_ASSERT(a && b);
  pa.free(*a);
  BT_EXPECT_EQ(pa.free_range_count(), 2u);  // hole @0 + tail
  pa.free(*b);                              // merges left into @0 and right into tail
  BT_EXPECT_EQ(pa.free_range_count(), 1u);
  BT_EXPECT_EQ(pa.total_free(), 8192ull);
}

BTEST(PoolAllocator, FreeMergesWithRightNeighbor) {
  PoolAllocator pa(make_pool("p", 8192));
  auto a = pa.allocate(1024);
  auto b = pa.allocate(1024);
  BT_ASSERT(a && b);
  pa.free(*b);  // adjacent to tail -> merge right
  BT_EXPECT_EQ(pa.free_range_count(), 1u);
  BT_EXPECT_EQ(pa.largest_free_block(), 8192ull - 1024ull);
  pa.free(*a);
  BT_EXPECT_EQ(pa.free_range_count(), 1u);
  BT_EXPECT_EQ(pa.total_free(), 8192ull);
}

BTEST(PoolAllocator, FreeMergesBothSides) {
  PoolAllocator pa(make_pool("p", 3 * 1024));
  auto a = pa.allocate(1024);
  auto b = pa.allocate(1024);
  auto c = pa.allocate(1024);
  BT_ASSERT(a && b && c);
  BT_EXPECT_EQ(pa.total_free(), 0ull);
  pa.free(*a);
  pa.free(*c);
  BT_EXPECT_EQ(pa.free_range_count(), 2u);
  pa.free(*b);  // bridges both holes
  BT_EXPECT_EQ(pa.free_range_count(), 1u);
  BT_EXPECT_EQ(pa.largest_free_block(), 3 * 1024ull);
}

BTEST(PoolAllocator, FragmentationMath) {
  PoolAllocator pa(make_pool("p", 10000));
  auto r1 = pa.allocate(2000);  // [0,2000)
  auto r2 = pa.allocate(2000);  // [2000,4000)
  auto r3 = pa.allocate(6000);  // [4000,10000)
  BT_ASSERT(r1 && r2 && r3);
  pa.free(*r1);  // hole 2000
  pa.free(*r3);  // hole 6000
  // total_free = 8000, largest = 6000 -> frag = 1 - 6000/8000 = 0.25
  BT_EXPECT_EQ(pa.total_free(), 8000ull);
  BT_EXPECT_EQ(pa.largest_free_block(), 6000ull);
  BT_EXPECT(std::abs(pa.fragmentation_ratio() - 0.25) < 1e-9);
  BT_EXPECT(pa.can_allocate(6000));
  BT_EXPECT(!pa.can_allocate(6001));  // 8000 free but not contiguous
}

BTEST(PoolAllocator, ToMemoryLocationAddsBaseAndParsesRkey) {
  auto pool = make_pool("p", 1 << 16);
  pool.remote.remote_base = 0xAB000000;
  pool.remote.rkey_hex = "1f2e";
  PoolAllocator pa(pool);
  auto r = pa.allocate(4096);
  BT_ASSERT(r.has_value());
  auto loc = pa.to_memory_location(*r);
  BT_EXPECT_EQ(loc.remote_addr, 0xAB000000ull + r->offset);
  BT_EXPECT_EQ(loc.rkey, 0x1f2eull);
  BT_EXPECT_EQ(loc.size, 4096ull);
}

BTEST(PoolAllocator, ConcurrentAllocateFreeStress) {
  // Parity with the reference's only concurrency test
  // (test_pool_allocator.cpp:184): hammer allocate/free from many threads and
  // verify conservation afterwards.
  PoolAllocator pa(make_pool("p", 8 << 20));
  constexpr int kThreads = 8;
  constexpr int kIters = 500;
  std::atomic<bool> failed{false};

  std::vector<std::thread> threads;
  for (int t = 0; t < kThreads; ++t) {
    threads.emplace_back([&pa, &failed, t] {
      std::mt19937 rng(t);
      std::vector<Range> held;
      for (int i = 0; i < kIters; ++i) {
        if (held.empty() || (rng() % 2 == 0)) {
          uint64_t size = 64 + rng() % 4096;
          auto r = pa.allocate(size);
          if (r) {
            if (r->length != size) failed = true;
            held.push_back(*r);
          }
        } else {
          size_t idx = rng() % held.size();
          pa.free(held[idx]);
          held.erase(held.begin() + idx);
        }
      }
      for (const auto& r : held) pa.free(r);
    });
  }
  for (auto& th : threads) th.join();
  BT_EXPECT(!failed.load());
  BT_EXPECT_EQ(pa.total_free(), uint64_t{8 << 20});
  BT_EXPECT_EQ(pa.free_range_count(), 1u);  // everything merged back
}

BTEST(PoolAllocator, AlignedCarveRoundsOffsetsUp) {
  auto pool = make_pool("p", 1 << 20);
  pool.alignment = 4096;
  PoolAllocator pa(pool);
  // Misalign the free map: carve 100 bytes (sub-unit, packs at 0), then a
  // unit-sized request must skip to the next 4 KiB boundary, not start at 100.
  auto head = pa.allocate(100);
  BT_EXPECT(head.has_value());
  BT_EXPECT_EQ(head->offset, 0ull);
  auto aligned = pa.allocate(8192);
  BT_EXPECT(aligned.has_value());
  BT_EXPECT_EQ(aligned->offset, 4096ull);
  // Sub-unit shards keep packing into the leading gap — alignment never
  // wastes a whole unit on small objects.
  auto gap = pa.allocate(1000);
  BT_EXPECT(gap.has_value());
  BT_EXPECT_EQ(gap->offset, 100ull);
  BT_EXPECT_EQ(pa.total_free(), (1ull << 20) - 100 - 8192 - 1000);
}

BTEST(PoolAllocator, AlignmentPaddingMergesBackOnFree) {
  auto pool = make_pool("p", 64 << 10);
  pool.alignment = 4096;
  PoolAllocator pa(pool);
  auto a = pa.allocate(100);
  auto b = pa.allocate(4096);
  BT_EXPECT(a && b);
  pa.free(*a);
  pa.free(*b);
  BT_EXPECT_EQ(pa.total_free(), uint64_t{64 << 10});
  BT_EXPECT_EQ(pa.free_range_count(), 1u);
}

BTEST(PoolAllocator, CanAllocateAccountsForAlignmentPadding) {
  auto pool = make_pool("p", 8192);
  pool.alignment = 4096;
  PoolAllocator pa(pool);
  auto head = pa.allocate(100);  // free space is now [100,8192) = 8092 bytes
  BT_EXPECT(head.has_value());
  BT_EXPECT(pa.can_allocate(4096));    // fits at offset 4096
  BT_EXPECT(!pa.can_allocate(8000));   // 8092 free, but only 4096 aligned-usable
  BT_EXPECT(!pa.allocate(8000).has_value());
}
