// Shared corpus-file helpers for the fuzz tooling: used by the replay
// driver (fuzz_replay_main.cpp) AND the default-suite corpus test
// (test_wire_fuzz_corpus.cpp), so both always agree on which inputs exist
// (same directory listing rules, same ordering, same read semantics).
#pragma once

#include <dirent.h>

#include <algorithm>
#include <cstdint>
#include <fstream>
#include <iterator>
#include <string>
#include <vector>

namespace btpu_fuzz {

inline std::vector<std::string> list_corpus_dir(const std::string& dir) {
  std::vector<std::string> out;
  DIR* d = ::opendir(dir.c_str());
  if (!d) return out;
  while (dirent* e = ::readdir(d)) {
    const std::string name = e->d_name;
    if (name == "." || name == "..") continue;
    out.push_back(dir + "/" + name);
  }
  ::closedir(d);
  std::sort(out.begin(), out.end());
  return out;
}

inline std::vector<uint8_t> read_corpus_file(const std::string& path) {
  std::ifstream in(path, std::ios::binary);
  return std::vector<uint8_t>((std::istreambuf_iterator<char>(in)),
                              std::istreambuf_iterator<char>());
}

}  // namespace btpu_fuzz
