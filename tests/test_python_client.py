"""Python bindings: embedded cluster + client put/get/remove/failure flows."""

import numpy as np
import pytest

from blackbird_tpu import Client, EmbeddedCluster, StorageClass, TransportKind
from blackbird_tpu.native import BtpuError, ErrorCode


def test_put_get_bytes_roundtrip() -> None:
    with EmbeddedCluster(workers=4, pool_bytes=16 << 20) as cluster:
        client = cluster.client()
        payload = bytes(bytearray(range(256)) * 1024)  # 256 KiB
        client.put("py/obj", payload, max_workers=4)
        assert client.exists("py/obj")
        assert client.get("py/obj") == payload
        client.remove("py/obj")
        assert not client.exists("py/obj")


def test_put_get_numpy_roundtrip() -> None:
    with EmbeddedCluster(workers=2, pool_bytes=16 << 20) as cluster:
        client = cluster.client()
        array = np.arange(65536, dtype=np.float32).reshape(256, 256)
        client.put("py/array", array)
        back = client.get_array("py/array", dtype=np.float32, shape=(256, 256))
        np.testing.assert_array_equal(array, back)

        out = np.empty_like(array)
        n = client.get_into("py/array", out)
        assert n == array.nbytes
        np.testing.assert_array_equal(array, out)


def test_missing_object_raises_object_not_found() -> None:
    with EmbeddedCluster(workers=1, pool_bytes=1 << 20) as cluster:
        client = cluster.client()
        with pytest.raises(BtpuError) as excinfo:
            client.get("nope")
        assert excinfo.value.code == ErrorCode.OBJECT_NOT_FOUND
        with pytest.raises(BtpuError):
            client.put("dup", b"x")
            client.put("dup", b"x")


def test_replication_and_worker_death_repair() -> None:
    with EmbeddedCluster(workers=3, pool_bytes=16 << 20) as cluster:
        client = cluster.client()
        payload = np.random.default_rng(7).bytes(128 * 1024)
        client.put("py/precious", payload, replicas=2, max_workers=1)
        cluster.kill_worker(0)
        # Repair happens synchronously in the death path; data must survive
        # regardless of which worker held which copy.
        counters = cluster.counters()
        assert counters["workers_lost"] == 1
        assert client.get("py/precious") == payload


def test_stats_and_cluster_shapes() -> None:
    with EmbeddedCluster(workers=2, pool_bytes=8 << 20) as cluster:
        client = cluster.client()
        stats = client.stats()
        assert stats["workers"] == 2
        assert stats["pools"] == 2
        # A 4 KiB object rides the keystone's inline tier: it counts as an
        # object but consumes no pool capacity.
        client.put("py/s", b"abcd" * 1024)
        assert client.stats()["objects"] == 1
        assert client.stats()["used"] == 0
        assert client.get("py/s") == b"abcd" * 1024
        # A 64 KiB object takes the placed path and holds real pool ranges.
        client.put("py/big", b"wxyz" * 16384)
        assert client.stats()["objects"] == 2
        assert client.stats()["used"] >= 65536


def test_shm_transport_cluster() -> None:
    with EmbeddedCluster(workers=2, pool_bytes=8 << 20,
                         transport=TransportKind.SHM) as cluster:
        client = cluster.client()
        payload = b"shm-bytes" * 5000
        client.put("py/shm", payload, max_workers=2)
        assert client.get("py/shm") == payload


def test_tiered_cluster_hbm_preference() -> None:
    with EmbeddedCluster(workers=1, pool_bytes=16 << 20,
                         tiered_device_bytes=1 << 20) as cluster:
        client = cluster.client()
        small = b"hot" * 1000
        client.put("py/hot", small, preferred_class=StorageClass.HBM_TPU)
        assert client.get("py/hot") == small
        # Larger than the HBM pool: spills to DRAM but still round-trips.
        big = np.random.default_rng(3).bytes(4 << 20)
        client.put("py/cold", big, preferred_class=StorageClass.HBM_TPU)
        assert client.get("py/cold") == big


def test_tiered_cluster_demotes_under_pressure() -> None:
    """Watermark pressure on the device tier moves objects down to DRAM
    (objects_demoted counter) instead of deleting them; bytes stay intact."""
    import time

    with EmbeddedCluster(workers=1, pool_bytes=64 << 20,
                         tiered_device_bytes=4 << 20) as cluster:
        client = cluster.client()
        rng = np.random.default_rng(7)
        payloads = {}
        for i in range(4):  # ~3.9 MiB of a 4 MiB device tier: > 90% watermark
            key = f"py/demote/{i}"
            payloads[key] = rng.bytes(1000 * 1024)
            # max_workers=1 keeps each object whole on the device tier
            # (striping would spread it over HBM+DRAM and dilute pressure).
            client.put(key, payloads[key], max_workers=1,
                       preferred_class=StorageClass.HBM_TPU)

        deadline = time.time() + 10
        while time.time() < deadline:
            if cluster.counters()["objects_demoted"] >= 1:
                break
            time.sleep(0.2)
        counters = cluster.counters()
        assert counters["objects_demoted"] >= 1
        assert counters["evicted"] == 0  # moved, not deleted
        for key, expected in payloads.items():
            assert client.get(key) == expected


def test_placements_introspection() -> None:
    from blackbird_tpu import EmbeddedCluster

    with EmbeddedCluster(workers=4, pool_bytes=16 << 20) as cluster:
        client = cluster.client()
        client.put("intro/obj", b"z" * (1 << 20), replicas=2, max_workers=2)
        copies = client.placements("intro/obj")
        assert len(copies) == 2
        workers = set()
        for copy in copies:
            assert len(copy["shards"]) == 2  # striped x2 (256 KiB floor)
            for shard in copy["shards"]:
                assert shard["class"] == "ram_cpu"
                assert shard["location"]["kind"] == "memory"
                assert shard["length"] > 0
                workers.add(shard["worker"])
        assert len(workers) == 4  # copies spread over disjoint workers


def test_list_objects_by_prefix() -> None:
    with EmbeddedCluster(workers=2, pool_bytes=16 << 20) as cluster:
        client = cluster.client()
        client.put("ls/a", b"x" * 1024)
        client.put("ls/b", b"y" * 2048, replicas=2)
        client.put("other/c", b"z" * 512)

        everything = client.list()
        assert {o["key"] for o in everything} == {"ls/a", "ls/b", "other/c"}

        ls = client.list("ls/")
        assert [o["key"] for o in ls] == ["ls/a", "ls/b"]  # lexicographic
        assert ls[0]["size"] == 1024
        assert ls[1]["copies"] == 2
        assert ls[0]["soft_pin"] is False

        assert client.list("ls/", limit=1) == [ls[0]]
        assert client.list("nope/") == []


def test_erasure_coded_put_get() -> None:
    with EmbeddedCluster(workers=6, pool_bytes=16 << 20) as cluster:
        client = cluster.client()
        payload = bytes(bytearray(range(256)) * 2048)  # 512 KiB
        client.put("ec/py", payload, ec=(4, 2))
        assert client.get("ec/py") == payload

        copies = client.placements("ec/py")
        assert len(copies) == 1  # one coded copy, not replicas
        assert copies[0]["ec"] == {
            "data_shards": 4, "parity_shards": 2, "object_size": len(payload),
        }
        assert len(copies[0]["shards"]) == 6
        assert len({s["worker"] for s in copies[0]["shards"]}) == 6  # anti-affine

        # Listing and size queries report the LOGICAL size, not k+m shards.
        listed = client.list("ec/")
        assert listed[0]["size"] == len(payload)

        with pytest.raises(ValueError):
            client.put("ec/bad", b"x", ec=(0, 2))


def test_object_ttl_and_soft_pin() -> None:
    import time

    from blackbird_tpu import EmbeddedCluster

    with EmbeddedCluster(workers=1, pool_bytes=8 << 20) as cluster:
        client = cluster.client()
        client.put("ttl/short", b"ephemeral", ttl_ms=300)
        client.put("ttl/forever", b"permanent", ttl_ms=0)
        client.put("ttl/pinned", b"pinned", soft_pin=True)
        assert client.exists("ttl/short")

        deadline = time.monotonic() + 10  # gc interval is 1s in embedded
        while client.exists("ttl/short") and time.monotonic() < deadline:
            time.sleep(0.1)
        assert not client.exists("ttl/short")  # TTL'd object collected
        assert client.get("ttl/forever") == b"permanent"  # ttl_ms=0: never
        assert client.get("ttl/pinned") == b"pinned"


def test_object_cache_hot_reads_and_coherence() -> None:
    """Lease-coherent client object cache: repeated hot gets are served from
    local memory (hits counted, cached lane bytes counted), and an
    overwrite/remove by ANOTHER client is never served stale."""
    with EmbeddedCluster(workers=2, pool_bytes=32 << 20) as cluster:
        reader = cluster.client(cache_bytes=8 << 20)
        writer = cluster.client()
        payload_a = np.random.default_rng(1).bytes(64 * 1024)
        payload_b = np.random.default_rng(2).bytes(64 * 1024)
        writer.put("cache/hot", payload_a)

        lane0 = Client.lane_counters().get("cached_bytes", 0)
        assert reader.get("cache/hot") == payload_a  # miss + fill
        for _ in range(4):
            assert reader.get("cache/hot") == payload_a  # hits
        stats = reader.cache_stats()
        assert stats["fills"] == 1
        assert stats["hits"] >= 4
        assert stats["bytes"] == len(payload_a)
        assert Client.lane_counters().get("cached_bytes", 0) > lane0

        # Cross-client overwrite: the next read must observe the new bytes
        # (version validation makes a stale serve structurally impossible).
        writer.remove("cache/hot")
        writer.put("cache/hot", payload_b)
        assert reader.get("cache/hot") == payload_b
        assert reader.cache_stats()["stale_rejects"] >= 1

        # Remove: cached bytes must not resurrect the object.
        writer.remove("cache/hot")
        with pytest.raises(BtpuError) as excinfo:
            reader.get("cache/hot")
        assert excinfo.value.code == ErrorCode.OBJECT_NOT_FOUND

        # get_many rides the cache too (the checkpoint load_sharded shape).
        items = {f"cache/m{i}": np.random.default_rng(i).bytes(16 * 1024)
                 for i in range(4)}
        for key, val in items.items():
            writer.put(key, val)
        assert reader.get_many(list(items)) == list(items.values())
        before = reader.cache_stats()["hits"]
        assert reader.get_many(list(items)) == list(items.values())
        assert reader.cache_stats()["hits"] >= before + 4


def test_drain_worker_preserves_rf1_objects() -> None:
    """Graceful evacuation vs crash: a replicas=1 object on the drained
    worker survives (streamed off the live source) where kill_worker would
    have lost it."""
    from blackbird_tpu import EmbeddedCluster

    with EmbeddedCluster(workers=3, pool_bytes=16 << 20) as cluster:
        client = cluster.client()
        payload = b"precious" * 100_000
        client.put("drain/obj", payload, replicas=1, max_workers=3)
        moved = client.drain_worker("worker-1")
        assert moved >= 1
        assert client.stats()["workers"] == 2
        assert client.get("drain/obj") == payload
        for copy in client.placements("drain/obj"):
            for shard in copy["shards"]:
                assert shard["worker"] != "worker-1"
