// HA failover walkthrough, fully in-process: an active/standby coordinator
// pair with a mirroring follower, a client holding both endpoints, and a
// simulated primary crash — the standby promotes and the client's next
// operation transparently lands on it.
//
// Role parity: the reference delegates this entire layer to an etcd cluster
// (etcd_service.cpp) and ships no failover demo; here the coordinator HA is
// part of the framework (coord_server.h). Production shape:
//   bb-coord --port 9290 --data-dir /var/btpu/coord        # primary
//   bb-coord --port 9294 --follow primary:9290             # standby
// with every service's coord_endpoints set to "primary:9290,standby:9294".
#include <cstdio>
#include <thread>

#include "btpu/coord/coord_server.h"
#include "btpu/coord/remote_coordinator.h"

using namespace btpu;

int main() {
  // Primary + mirroring standby.
  auto primary = std::make_unique<coord::CoordServer>("127.0.0.1", 0);
  if (primary->start() != ErrorCode::OK) return 1;
  coord::CoordServer standby("127.0.0.1", 0);
  standby.set_follower(true);
  if (standby.start() != ErrorCode::OK) return 1;
  coord::CoordFollower follower(
      standby, {.primary_endpoint = primary->endpoint(), .takeover_grace_ms = 500});
  if (follower.start() != ErrorCode::OK) return 1;
  std::printf("primary %s, standby %s (mirroring)\n", primary->endpoint().c_str(),
              standby.endpoint().c_str());

  // A client that knows both endpoints.
  coord::RemoteCoordinator client(primary->endpoint() + "," + standby.endpoint());
  if (client.connect() != ErrorCode::OK) return 1;
  (void)client.put("/demo/config", "v1");  // demo: failure shows in the reads below
  std::printf("wrote /demo/config=v1 via the primary\n");

  // The standby serves reads but refuses writes while the primary lives.
  coord::RemoteCoordinator standby_client(standby.endpoint());
  if (standby_client.connect() != ErrorCode::OK) return 1;
  auto read = standby_client.get("/demo/config");
  std::printf("standby mirrors the key: %s\n",
              read.ok() ? read.value().c_str() : "MISSING");
  std::printf("standby rejects writes: %s\n",
              std::string(to_string(standby_client.put("/x", "y"))).c_str());

  // Crash the primary; the follower promotes after its grace period.
  std::printf("killing the primary...\n");
  primary.reset();
  for (int i = 0; i < 100 && !follower.promoted(); ++i)
    std::this_thread::sleep_for(std::chrono::milliseconds(50));
  std::printf("standby promoted: %s\n", follower.promoted() ? "yes" : "no");

  // The same client object keeps working — its next call rotates over.
  ErrorCode ec = ErrorCode::CONNECTION_FAILED;
  for (int i = 0; i < 100 && ec != ErrorCode::OK; ++i) {
    ec = client.put("/demo/config", "v2");
    if (ec != ErrorCode::OK) std::this_thread::sleep_for(std::chrono::milliseconds(50));
  }
  auto after = client.get("/demo/config");
  std::printf("post-failover write: %s, read back: %s\n",
              std::string(to_string(ec)).c_str(),
              after.ok() ? after.value().c_str() : "MISSING");
  follower.stop();
  return after.ok() && after.value() == "v2" ? 0 : 1;
}
