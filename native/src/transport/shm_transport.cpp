// SHM transport: same-host zero-copy via POSIX shared memory.
//
// The worker allocates its pool inside a shm segment (alloc_region); clients
// shm_open + mmap the same segment once and then address object bytes
// directly — one memcpy end to end, no sockets, the same data-path shape a
// TPU-VM-local HBM/DRAM tier wants. Remote addresses are segment offsets
// (remote_base = 0), so placements stay valid across worker restarts that
// recreate the segment at a different virtual address.
#include <fcntl.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>

#include <cctype>
#include <cstring>
#include <mutex>
#include <random>
#include <shared_mutex>
#include <unordered_map>

#include "btpu/common/pool_span.h"

#include "btpu/common/crc32c.h"
#include "btpu/common/log.h"
#include "btpu/transport/transport.h"

namespace btpu::transport {

namespace {

struct ShmSegment {
  std::string name;
  uint8_t* base{nullptr};
  uint64_t len{0};
};

class ShmTransportServer : public TransportServer {
 public:
  ~ShmTransportServer() override { stop(); }

  TransportKind kind() const noexcept override { return TransportKind::SHM; }
  ErrorCode start(const std::string&, uint16_t) override { return ErrorCode::OK; }

  void stop() override {
    MutexLock lock(mutex_);
    for (auto& [base, seg] : segments_) {
      ::munmap(seg.base, seg.len);
      ::shm_unlink(seg.name.c_str());
    }
    segments_.clear();
  }

  void* alloc_region(uint64_t len, const std::string& tag) override {
    std::string name = "/btpu_" + std::to_string(::getpid()) + "_" + sanitize(tag);
    int fd = ::shm_open(name.c_str(), O_CREAT | O_EXCL | O_RDWR, 0600);
    if (fd < 0) {
      // Segment left over from a previous crashed run: replace it.
      ::shm_unlink(name.c_str());
      fd = ::shm_open(name.c_str(), O_CREAT | O_EXCL | O_RDWR, 0600);
      if (fd < 0) return nullptr;
    }
    if (::ftruncate(fd, static_cast<off_t>(len)) != 0) {
      ::close(fd);
      ::shm_unlink(name.c_str());
      return nullptr;
    }
    void* base = ::mmap(nullptr, len, PROT_READ | PROT_WRITE, MAP_SHARED, fd, 0);
    ::close(fd);
    if (base == MAP_FAILED) {
      ::shm_unlink(name.c_str());
      return nullptr;
    }
    MutexLock lock(mutex_);
    segments_[base] = {name, static_cast<uint8_t*>(base), len};
    LOG_DEBUG << "shm segment " << name << " (" << len << " bytes)";
    return base;
  }

  Result<RemoteDescriptor> register_region(void* base, uint64_t len,
                                           const std::string& tag) override {
    MutexLock lock(mutex_);
    auto it = segments_.find(base);
    if (it == segments_.end() || it->second.len < len) {
      LOG_ERROR << "shm register_region for memory not allocated via alloc_region";
      return ErrorCode::INVALID_PARAMETERS;
    }
    RemoteDescriptor d;
    d.transport = TransportKind::SHM;
    d.endpoint = it->second.name;
    d.remote_base = 0;  // addresses are segment offsets
    d.rkey_hex = rkey_to_hex(rng_() | 1);
    return d;
  }

  ErrorCode unregister_region(const RemoteDescriptor& desc) override {
    MutexLock lock(mutex_);
    for (auto it = segments_.begin(); it != segments_.end(); ++it) {
      if (it->second.name == desc.endpoint) {
        ::munmap(it->second.base, it->second.len);
        ::shm_unlink(it->second.name.c_str());
        segments_.erase(it);
        return ErrorCode::OK;
      }
    }
    return ErrorCode::MEMORY_POOL_NOT_FOUND;
  }

 private:
  static std::string sanitize(const std::string& tag) {
    std::string out;
    for (char c : tag) out.push_back(std::isalnum(static_cast<unsigned char>(c)) ? c : '_');
    return out;
  }

  Mutex mutex_;
  std::unordered_map<void*, ShmSegment> segments_ BTPU_GUARDED_BY(mutex_);
  std::mt19937_64 rng_ BTPU_GUARDED_BY(mutex_){0x73686d726567ull};
};

// Client-side cache of mapped segments. Reader-writer lock: every same-host
// transfer resolves its segment here, so N client threads share the hit
// path instead of convoying on one mutex per op (mappings change only when
// a worker (re)starts).
class ShmMapCache {
 public:
  static ShmMapCache& instance() {
    static ShmMapCache c;
    return c;
  }

  // Maps (or returns cached) segment; out_len = segment size.
  uint8_t* map(const std::string& name, uint64_t& out_len) {
    {
      SharedLock lock(mutex_);
      auto it = maps_.find(name);
      if (it != maps_.end()) {
        out_len = it->second.len;
        return it->second.base;
      }
    }
    int fd = ::shm_open(name.c_str(), O_RDWR, 0600);
    if (fd < 0) return nullptr;
    struct stat st {};
    if (::fstat(fd, &st) != 0 || st.st_size <= 0) {
      ::close(fd);
      return nullptr;
    }
    void* base =
        ::mmap(nullptr, static_cast<size_t>(st.st_size), PROT_READ | PROT_WRITE, MAP_SHARED, fd, 0);
    ::close(fd);
    if (base == MAP_FAILED) return nullptr;
    WriterLock lock(mutex_);
    auto [it, inserted] = maps_.try_emplace(
        name, ShmSegment{name, static_cast<uint8_t*>(base), static_cast<uint64_t>(st.st_size)});
    if (!inserted) {
      // A racing thread mapped it first: keep the cached mapping, drop ours.
      ::munmap(base, static_cast<size_t>(st.st_size));
    }
    out_len = it->second.len;
    return it->second.base;
  }

  void drop(const std::string& name) {
    WriterLock lock(mutex_);
    auto it = maps_.find(name);
    if (it != maps_.end()) {
      ::munmap(it->second.base, it->second.len);
      maps_.erase(it);
    }
  }

 private:
  SharedMutex mutex_;
  std::unordered_map<std::string, ShmSegment> maps_ BTPU_GUARDED_BY(mutex_);
};

}  // namespace

ErrorCode shm_access(const std::string& name, uint64_t offset, void* buf, uint64_t len,
                     bool is_write, uint32_t* crc_out, uint64_t extent_gen) {
  uint64_t seg_len = 0;
  uint8_t* base = ShmMapCache::instance().map(name, seg_len);
  if (!base) return ErrorCode::CONNECTION_FAILED;
  // The segment name doubles as the poolsan shadow alias (the worker
  // aliases it to the pool id at registration): a client addressing the
  // pool through its own mapping still gets stale/quarantined extents
  // convicted. Addresses here are segment offsets == pool offsets.
  auto span = poolspan::resolve(base, seg_len, offset, len, extent_gen,
                                is_write ? poolspan::Access::kWrite
                                         : poolspan::Access::kRead,
                                name.c_str());
  if (!span.ok()) return span.error();
  uint8_t* target = span.value().data();
  if (is_write) {
    if (crc_out) {
      *crc_out = crc32c_copy(target, buf, len);  // fused: hash while moving
    } else {
      std::memcpy(target, buf, len);
    }
  } else if (crc_out) {
    *crc_out = crc32c_copy(buf, target, len);  // fused: hash while moving
  } else {
    std::memcpy(buf, target, len);
  }
  return ErrorCode::OK;
}

std::unique_ptr<TransportServer> make_shm_transport_server() {
  return std::make_unique<ShmTransportServer>();
}

}  // namespace btpu::transport
