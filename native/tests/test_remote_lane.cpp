// Remote-lane suite: the cross-host-shaped byte path, on a loopback
// cluster with the same-host fast lanes FORCE-DISABLED (BTPU_PVM=0 kills
// the process_vm direct-copy lane, BTPU_STAGED_DATA=0 the shm staging
// lane), so every payload byte rides the TCP stream lane — pool-direct
// gather writes on the serving side, one fused copy+CRC drain on the
// client side. This is the path a genuinely remote client takes; the
// fakes-free proof is the lane scoreboard (stream counters advance, pvm
// and staged stay flat).
//
// `make check` runs this suite under BOTH engines (BTPU_IOURING_NET=0 and
// =1 legs), so every property here is pinned on the io_uring event loop
// AND the thread-per-connection fallback.
#include <atomic>
#include <chrono>
#include <cstring>
#include <thread>
#include <vector>

#include "btest.h"
#include "btpu/client/embedded.h"
#include "btpu/common/crc32c.h"
#include "btpu/transport/transport.h"

using namespace btpu;
using namespace btpu::client;
using namespace btpu::transport;

namespace {

std::vector<uint8_t> pattern(uint64_t size, uint8_t seed = 1) {
  std::vector<uint8_t> data(size);
  for (uint64_t i = 0; i < size; ++i) data[i] = static_cast<uint8_t>(i * 131 + seed);
  return data;
}

struct ScopedEnv {
  ScopedEnv(const char* name, const std::string& value) : name_(name) {
    if (const char* old = std::getenv(name)) saved_ = old;
    ::setenv(name, value.c_str(), 1);
  }
  ~ScopedEnv() {
    if (saved_.empty())
      ::unsetenv(name_);
    else
      ::setenv(name_, saved_.c_str(), 1);
  }
  const char* name_;
  std::string saved_;
};

// The force-disabled fast lanes, applied for one test's scope.
struct RemoteShaped {
  ScopedEnv no_pvm{"BTPU_PVM", "0"};
  ScopedEnv no_staged{"BTPU_STAGED_DATA", "0"};
};

EmbeddedClusterOptions tcp_cluster(size_t n_workers, uint64_t pool_bytes) {
  auto options = EmbeddedClusterOptions::simple(n_workers, pool_bytes);
  for (auto& w : options.workers) {
    w.transport = TransportKind::TCP;
    w.listen_host = "127.0.0.1";
  }
  return options;
}

uint64_t parse_rkey(const RemoteDescriptor& d) { return std::stoull(d.rkey_hex, nullptr, 16); }

}  // namespace

BTEST(RemoteLane, StripedGetByteExactOverStreamLane) {
  RemoteShaped remote;
  EmbeddedCluster cluster(tcp_cluster(4, 8 << 20));
  BT_ASSERT(cluster.start() == ErrorCode::OK);
  auto client = cluster.make_client();

  const uint64_t pvm_before = pvm_op_count();
  const uint64_t staged_before = tcp_staged_op_count();
  const uint64_t stream_before = tcp_stream_op_count();
  const uint64_t stream_bytes_before = tcp_stream_byte_count();

  WorkerConfig cfg;
  cfg.replication_factor = 1;
  cfg.max_workers_per_copy = 4;  // striped across all four workers
  auto data = pattern((1 << 20) + 7, 41);
  BT_ASSERT(client->put("remote/striped", data.data(), data.size(), cfg) == ErrorCode::OK);
  auto back = client->get("remote/striped");
  BT_ASSERT_OK(back);
  BT_EXPECT(back.value() == data);

  // Every byte of the get rode the stream lane: no pvm ops, no staged ops,
  // and at least the object's size in stream bytes.
  BT_EXPECT_EQ(pvm_op_count(), pvm_before);
  BT_EXPECT_EQ(tcp_staged_op_count(), staged_before);
  BT_EXPECT(tcp_stream_op_count() > stream_before);
  BT_EXPECT(tcp_stream_byte_count() - stream_bytes_before >= data.size());
}

BTEST(RemoteLane, UnevenChunkSizesByteExact) {
  // Sizes chosen to straddle every boundary the lane chunks on: single
  // bytes, sub-header sizes, page +/- 1, chunk-size stragglers.
  RemoteShaped remote;
  EmbeddedCluster cluster(tcp_cluster(2, 16 << 20));
  BT_ASSERT(cluster.start() == ErrorCode::OK);
  auto client = cluster.make_client();

  WorkerConfig cfg;
  cfg.replication_factor = 1;
  cfg.max_workers_per_copy = 2;
  const uint64_t sizes[] = {1,         37,          4097,         64 * 1024 + 13,
                            256 * 1024 + 7777,      (1 << 20) + 3};
  int idx = 0;
  for (const uint64_t size : sizes) {
    const std::string key = "remote/uneven-" + std::to_string(idx++);
    auto data = pattern(size, static_cast<uint8_t>(90 + idx));
    BT_ASSERT(client->put(key, data.data(), data.size(), cfg) == ErrorCode::OK);
    auto back = client->get(key);
    BT_ASSERT_OK(back);
    BT_EXPECT(back.value() == data);
  }
}

BTEST(RemoteLane, ErasureCodedGetReconstructsOverStreamLane) {
  RemoteShaped remote;
  EmbeddedCluster cluster(tcp_cluster(6, 8 << 20));
  BT_ASSERT(cluster.start() == ErrorCode::OK);
  auto client = cluster.make_client();

  WorkerConfig cfg;
  cfg.ec_data_shards = 4;
  cfg.ec_parity_shards = 2;
  auto data = pattern(512 * 1024 + 29, 67);
  BT_ASSERT(client->put("remote/ec", data.data(), data.size(), cfg) == ErrorCode::OK);

  const uint64_t stream_before = tcp_stream_op_count();
  auto healthy = client->get("remote/ec");
  BT_ASSERT_OK(healthy);
  BT_EXPECT(healthy.value() == data);
  BT_EXPECT(tcp_stream_op_count() > stream_before);

  // Degraded read: one shard's worker dies, parity reconstructs — still
  // entirely over the stream lane.
  cluster.kill_worker(0);
  auto degraded = client->get("remote/ec");
  BT_ASSERT_OK(degraded);
  BT_EXPECT(degraded.value() == data);
}

BTEST(RemoteLane, CorruptReplicaDetectedThroughFusedCrc) {
  // The stream lane folds the CRC into the client's single drain pass
  // (Crc32cStream) — corrupt replica bytes must still be caught by that
  // fused hash, heal from the healthy copy, and detect (never serve
  // garbage) when every copy is rotten.
  RemoteShaped remote;
  EmbeddedCluster cluster(tcp_cluster(2, 8 << 20));
  BT_ASSERT(cluster.start() == ErrorCode::OK);
  auto client = cluster.make_client();

  WorkerConfig cfg;
  cfg.replication_factor = 2;
  cfg.max_workers_per_copy = 1;
  auto data = pattern(768 * 1024 + 11, 29);
  BT_ASSERT(client->put("remote/crc", data.data(), data.size(), cfg) == ErrorCode::OK);

  auto placements = client->get_workers("remote/crc");
  BT_ASSERT_OK(placements);
  BT_ASSERT(placements.value().size() >= 2);
  auto corrupt = [&](const CopyPlacement& copy) {
    const auto& shard = copy.shards[0];
    const auto& mem = std::get<MemoryLocation>(shard.location);
    std::vector<uint8_t> garbage(4096, 0x5a);
    auto raw = make_transport_client();
    BT_ASSERT(raw->write(shard.remote, mem.remote_addr + 2000, mem.rkey, garbage.data(),
                         garbage.size()) == ErrorCode::OK);
  };
  corrupt(placements.value()[0]);

  auto healed = client->get("remote/crc");
  BT_ASSERT_OK(healed);
  BT_EXPECT(healed.value() == data);

  corrupt(placements.value()[1]);
  auto dead = client->get("remote/crc");
  BT_ASSERT(!dead.ok());
  BT_EXPECT(dead.error() == ErrorCode::CHECKSUM_MISMATCH);
}

BTEST(RemoteLane, MidStreamPeerDeathReturnsCleanErrorNotHang) {
  // A serving peer dying mid-transfer must surface as an ErrorCode on the
  // in-flight op promptly — never a wedged client. The reader thread
  // hammers large stream reads while the server is stopped under it.
  RemoteShaped remote;
  // Region declared before the server: a failed assertion below must tear
  // the server down while the registered bytes are still alive.
  std::vector<uint8_t> region(8 << 20);
  for (size_t i = 0; i < region.size(); ++i) region[i] = static_cast<uint8_t>(i * 7 + 3);
  auto server = make_transport_server(TransportKind::TCP);
  BT_ASSERT(server->start("127.0.0.1", 0) == ErrorCode::OK);
  auto reg = server->register_region(region.data(), region.size(), "death");
  BT_ASSERT_OK(reg);

  auto client = make_transport_client();
  // Warm the connection with one good read.
  std::vector<uint8_t> dst(region.size());
  BT_ASSERT(client->read(reg.value(), reg.value().remote_base, parse_rkey(reg.value()),
                         dst.data(), dst.size()) == ErrorCode::OK);
  BT_EXPECT(std::memcmp(dst.data(), region.data(), region.size()) == 0);

  std::atomic<bool> got_error{false};
  std::atomic<bool> done{false};
  std::thread reader([&] {
    for (int i = 0; i < 100000 && !got_error.load(); ++i) {
      const ErrorCode rc = client->read(reg.value(), reg.value().remote_base,
                                        parse_rkey(reg.value()), dst.data(), dst.size());
      if (rc != ErrorCode::OK) got_error.store(true);
    }
    done.store(true);
  });
  std::this_thread::sleep_for(std::chrono::milliseconds(30));
  server->stop();  // peer death mid-stream
  const auto deadline = std::chrono::steady_clock::now() + std::chrono::seconds(10);
  while (!done.load() && std::chrono::steady_clock::now() < deadline)
    std::this_thread::sleep_for(std::chrono::milliseconds(5));
  BT_EXPECT(done.load());       // returned, did not hang
  BT_EXPECT(got_error.load());  // and returned an ERROR, not fabricated OK
  if (done.load()) reader.join();
}

BTEST(RemoteLane, EngineAndFallbackServeByteIdenticalStreams) {
  // One region, two servers: the io_uring engine (where the kernel allows)
  // and the force-disabled fallback. A client must get byte-identical data
  // AND identical fused CRCs from both — the wire is one protocol.
  RemoteShaped remote;
  std::vector<uint8_t> region(2 << 20);
  for (size_t i = 0; i < region.size(); ++i)
    region[i] = static_cast<uint8_t>((i * 151) >> 2 ^ i);

  auto engine_srv = make_transport_server(TransportKind::TCP);
  BT_ASSERT(engine_srv->start("127.0.0.1", 0) == ErrorCode::OK);
  auto engine_reg = engine_srv->register_region(region.data(), region.size(), "ab-a");
  BT_ASSERT_OK(engine_reg);

  ScopedEnv force_fallback("BTPU_IOURING_NET", "0");
  auto thread_srv = make_transport_server(TransportKind::TCP);
  BT_ASSERT(thread_srv->start("127.0.0.1", 0) == ErrorCode::OK);
  auto thread_reg = thread_srv->register_region(region.data(), region.size(), "ab-b");
  BT_ASSERT_OK(thread_reg);

  auto client = make_transport_client();
  const struct {
    uint64_t off, len;
  } cases[] = {{0, 4096}, {511, 64 * 1024 + 9}, {8192, (1 << 20) + 1}};
  for (const auto& c : cases) {
    std::vector<uint8_t> via_engine(c.len, 0x11), via_thread(c.len, 0x22);
    WireOp a{&engine_reg.value(), engine_reg.value().remote_base + c.off,
             parse_rkey(engine_reg.value()), via_engine.data(), c.len};
    a.want_crc = true;
    WireOp b{&thread_reg.value(), thread_reg.value().remote_base + c.off,
             parse_rkey(thread_reg.value()), via_thread.data(), c.len};
    b.want_crc = true;
    BT_EXPECT(client->read_batch(&a, 1) == ErrorCode::OK);
    BT_EXPECT(client->read_batch(&b, 1) == ErrorCode::OK);
    BT_EXPECT(via_engine == via_thread);
    BT_EXPECT(std::memcmp(via_engine.data(), region.data() + c.off, c.len) == 0);
    BT_EXPECT_EQ(a.crc, b.crc);
    BT_EXPECT_EQ(a.crc, crc32c(region.data() + c.off, c.len));
  }
  thread_srv->stop();
  engine_srv->stop();
}
