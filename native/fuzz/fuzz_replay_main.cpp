// Deterministic fuzz driver for clang-less builds (gcc has no libFuzzer) —
// and the seed-corpus generator for boxes that do have it.
//
//   btpu_fuzz_replay --corpus DIR [--execs N] [--target NAME]
//       Replays every checked-in input under DIR/<target>/ through its
//       decoder, then runs a deterministic mutation sweep (xorshift64 with
//       a seed derived from the input bytes — the SAME inputs every run,
//       so a failure here reproduces everywhere) until >= N total
//       executions per target. Exit 0 = no crash, no invariant violation.
//
//   btpu_fuzz_replay --gen-seeds DIR
//       Writes the seed corpus: valid encodings of canonical messages,
//       truncations of each, and the known-hostile regression inputs.
//       Found crashers get added to the same directories by hand (see
//       docs/CORRECTNESS.md, "add-a-crasher" workflow).
//
// Build: scripts/fuzz.sh (make fuzz). Under clang the libFuzzer harnesses
// (fuzz_main_libfuzzer.cpp) take over the exploration job; this binary
// still runs as the deterministic leg so the two agree on the corpus.
#include <dirent.h>
#include <sys/stat.h>

#include <algorithm>
#include <chrono>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <fstream>
#include <string>
#include <vector>

#include "../fuzz/fuzz_corpus.h"
#include "../fuzz/fuzz_targets.h"

namespace {

using btpu_fuzz::kFuzzTargets;

uint64_t xorshift64(uint64_t& s) {
  s ^= s << 13;
  s ^= s >> 7;
  s ^= s << 17;
  return s;
}

uint64_t fnv1a(const std::vector<uint8_t>& v) {
  uint64_t h = 1469598103934665603ull;
  for (uint8_t b : v) {
    h ^= b;
    h *= 1099511628211ull;
  }
  return h ? h : 1;  // xorshift state must be non-zero
}

// One mutation step: the classic byte/bit/length edits plus "interesting"
// integer splices (the values length checks get wrong).
void mutate(std::vector<uint8_t>& v, uint64_t& s) {
  static const uint64_t kInteresting[] = {0,        1,         0x7f,       0xff,
                                          0x7fff,   0xffff,    0x7fffffff, 0xffffffffull,
                                          1ull << 32, ~0ull >> 1, ~0ull};
  const uint64_t op = xorshift64(s) % 6;
  if (v.empty() && op != 4) {
    v.push_back(static_cast<uint8_t>(xorshift64(s)));
    return;
  }
  switch (op) {
    case 0:  // bit flip
      v[xorshift64(s) % v.size()] ^= static_cast<uint8_t>(1u << (xorshift64(s) % 8));
      break;
    case 1:  // byte set
      v[xorshift64(s) % v.size()] = static_cast<uint8_t>(xorshift64(s));
      break;
    case 2:  // truncate
      v.resize(xorshift64(s) % (v.size() + 1));
      break;
    case 3: {  // interesting integer splice (u8..u64 at a random offset)
      const uint64_t val = kInteresting[xorshift64(s) % (sizeof(kInteresting) / 8)];
      const size_t width = 1u << (xorshift64(s) % 4);  // 1,2,4,8
      if (v.size() >= width) {
        const size_t at = xorshift64(s) % (v.size() - width + 1);
        std::memcpy(v.data() + at, &val, width);
      }
      break;
    }
    case 4:  // extend with random bytes
      for (size_t i = 0, n = 1 + xorshift64(s) % 16; i < n; ++i)
        v.push_back(static_cast<uint8_t>(xorshift64(s)));
      break;
    case 5: {  // duplicate a slice (grows nested vectors/strings)
      const size_t at = xorshift64(s) % v.size();
      const size_t n = std::min<size_t>(1 + xorshift64(s) % 32, v.size() - at);
      v.insert(v.end(), v.begin() + static_cast<ptrdiff_t>(at),
               v.begin() + static_cast<ptrdiff_t>(at + n));
      break;
    }
  }
}

using btpu_fuzz::list_corpus_dir;
using btpu_fuzz::read_corpus_file;

void write_file(const std::string& path, const std::vector<uint8_t>& bytes) {
  std::ofstream out(path, std::ios::binary | std::ios::trunc);
  out.write(reinterpret_cast<const char*>(bytes.data()),
            static_cast<std::streamsize>(bytes.size()));
}

// ---- seed generation -------------------------------------------------------

std::vector<uint8_t> with_sel(uint8_t sel, const std::vector<uint8_t>& payload) {
  std::vector<uint8_t> out;
  out.push_back(sel);
  out.insert(out.end(), payload.begin(), payload.end());
  return out;
}

void gen_seeds(const std::string& root) {
  using namespace btpu;
  auto emit = [&](const char* target, const char* name, const std::vector<uint8_t>& bytes) {
    const std::string dir = root + "/" + target;
    ::mkdir(root.c_str(), 0755);
    ::mkdir(dir.c_str(), 0755);
    write_file(dir + "/" + name + ".bin", bytes);
  };
  auto truncations = [&](const char* target, const char* name,
                         const std::vector<uint8_t>& bytes) {
    emit(target, name, bytes);
    for (size_t cut : {size_t{1}, bytes.size() / 2,
                       bytes.size() > 0 ? bytes.size() - 1 : size_t{0}}) {
      if (cut >= bytes.size()) continue;
      emit(target, (std::string(name) + "_trunc" + std::to_string(cut)).c_str(),
           std::vector<uint8_t>(bytes.begin(), bytes.begin() + static_cast<ptrdiff_t>(cut)));
    }
  };

  // Canonical message payloads (field shapes matter, values do not).
  CopyPlacement copy;
  copy.copy_index = 1;
  ShardPlacement shard;
  shard.pool_id = "p1";
  shard.worker_id = "w1";
  shard.remote = {TransportKind::TCP, "h:1", 0x1000, "ab", "fa", "pv", 1};
  shard.storage_class = StorageClass::RAM_CPU;
  shard.length = 64;
  shard.location = MemoryLocation{0x2000, 0x55, 64};
  copy.shards = {shard};
  copy.content_crc = 0x1234;
  copy.shard_crcs = {0xAB};
  WorkerConfig wc;
  wc.replication_factor = 2;

  // rpc_frame: sel byte picks the message shape in run_rpc_frame.
  truncations("rpc_frame", "get_workers_resp",
              with_sel(0, wire::to_bytes(GetWorkersResponse{{copy}, ErrorCode::OK})));
  truncations("rpc_frame", "put_start_req",
              with_sel(1, wire::to_bytes(PutStartRequest{"k", 4096, wc, 0x77})));
  truncations("rpc_frame", "batch_get_workers_resp",
              with_sel(4, wire::to_bytes(BatchGetWorkersResponse{
                              {Result<std::vector<CopyPlacement>>(std::vector<CopyPlacement>{copy}),
                               Result<std::vector<CopyPlacement>>(ErrorCode::OBJECT_NOT_FOUND)},
                              ErrorCode::OK})));
  truncations("rpc_frame", "batch_put_start_req",
              with_sel(5, wire::to_bytes(BatchPutStartRequest{{{"k1", 128, wc, 1}}})));
  truncations("rpc_frame", "commit_slot_req",
              with_sel(8, wire::to_bytes(PutCommitSlotRequest{"s", "k", 5, {{0, {0xCD}}},
                                                              1, 128, wc, "tag"})));
  truncations("rpc_frame", "put_inline_req",
              with_sel(10, wire::to_bytes(PutInlineRequest{"k", wc, 9, "payload"})));
  {
    // With a v4 deadline trailer appended, as real requests carry it.
    auto p = wire::to_bytes(PutStartRequest{"k", 4096, wc, 0x77});
    rpc::append_deadline_trailer(p, 250);
    truncations("rpc_frame", "put_start_req_deadline", with_sel(1, p));
  }
  {
    // Fully-traced request: trace trailer INSIDE, deadline trailer
    // OUTERMOST — the exact v5 client framing run_rpc_frame strips.
    auto p = wire::to_bytes(GetWorkersRequest{"k"});
    rpc::append_trace_trailer(p, 0xABCDEF0123456789ull, 0x42ull);
    rpc::append_deadline_trailer(p, 250);
    truncations("rpc_frame", "get_workers_req_traced", with_sel(0, p));
  }
  {
    // Hostile: a trace trailer truncated mid-ids (magic intact, span id
    // missing) — must strip nothing and decode as plain payload bytes.
    auto p = wire::to_bytes(PutStartRequest{"k", 4096, wc, 0x77});
    rpc::append_trace_trailer(p, 0x1111222233334444ull, 0x5555ull);
    p.resize(p.size() - 6);
    emit("rpc_frame", "hostile_truncated_trace_trailer", with_sel(1, p));
  }
  {
    // Hostile: a forged trace trailer carrying the reserved untraced id 0
    // — strip_trace_trailer must refuse it (0 stays unambiguous).
    auto p = wire::to_bytes(PutStartRequest{"k", 4096, wc, 0x77});
    rpc::append_trace_trailer(p, 1, 1);
    std::memset(p.data() + p.size() - 16, 0, 8);  // zero the trace id in place
    emit("rpc_frame", "hostile_zero_trace_id", with_sel(1, p));
  }

  // control_error: the three legal codes, plus the clamp-pinning hostile
  // hint and an over-long (appended-field) frame.
  truncations("control_error", "retry_later",
              rpc::encode_control_error(ErrorCode::RETRY_LATER, 25));
  emit("control_error", "deadline",
       rpc::encode_control_error(ErrorCode::DEADLINE_EXCEEDED, 0));
  emit("control_error", "hostile_hint",
       rpc::encode_control_error(ErrorCode::RETRY_LATER, 0xFFFFFFFFu));
  {
    auto v = rpc::encode_control_error(ErrorCode::RESOURCE_EXHAUSTED, 10);
    v.push_back(0x7);  // a newer peer appended a field; must stay decodable
    emit("control_error", "appended_field", v);
  }

  // tcp_header: every op, raw header bytes (+ the staged frame), hostile
  // unknown-op and absurd-length variants.
  using namespace btpu::transport::datawire;
  auto hdr_bytes = [](uint8_t op, uint64_t addr, uint64_t rkey, uint64_t len,
                      uint32_t dl, uint64_t trace_id = 0, uint64_t span_id = 0,
                      uint64_t extent_gen = 0) {
    DataRequestHeader h{op, addr, rkey, len, dl, trace_id, span_id, extent_gen};
    std::vector<uint8_t> v(sizeof(h));
    std::memcpy(v.data(), &h, sizeof(h));
    return v;
  };
  truncations("tcp_header", "read", hdr_bytes(kOpRead, 0x1000, 0xBEEF, 65536, 0));
  emit("tcp_header", "write", hdr_bytes(kOpWrite, 0x2000, 0xBEEF, 1 << 20, 250));
  emit("tcp_header", "hello", hdr_bytes(kOpHello, 0, 0, 24, 0));
  emit("tcp_header", "fabric_pull", hdr_bytes(kOpFabricPull, 0x3000, 0xF00D, 4096, 50));
  emit("tcp_header", "hostile_unknown_op", hdr_bytes(0x42, 0, 0, 16, 0));
  emit("tcp_header", "hostile_len", hdr_bytes(kOpWrite, 0, 0, ~0ull >> 1, 0));
  emit("tcp_header", "hostile_hello_len", hdr_bytes(kOpHello, 0, 0, 4096, 0));
  {
    StagedFrame f{{kOpWriteStaged, 0x1000, 0xBEEF, 256 << 10, 100, 0, 0, 0}, 0x40000};
    std::vector<uint8_t> v(sizeof(f));
    std::memcpy(v.data(), &f, sizeof(f));
    truncations("tcp_header", "staged_write", v);
  }
  // Distributed-trace propagation seeds (observability change): a traced
  // header, the legacy zero = untraced shape at the OLD 29-byte size (must
  // now be rejected as truncated, never mis-decoded), and ids at the u64
  // ceiling.
  emit("tcp_header", "traced_read",
       hdr_bytes(kOpRead, 0x1000, 0xBEEF, 65536, 250, 0x1122334455667788ull,
                 0x99AABBCCDDEEFF00ull));
  {
    auto legacy = hdr_bytes(kOpRead, 0x1000, 0xBEEF, 65536, 0, 0, 0);
    legacy.resize(29);  // the pre-trace header size
    emit("tcp_header", "legacy_29b_truncated", legacy);
  }
  emit("tcp_header", "max_trace_ids",
       hdr_bytes(kOpWrite, 0x2000, 0xBEEF, 4096, 0, ~0ull, ~0ull));
  // Pool-sanitizer generation seeds: a stamped header, the ceiling value,
  // and the pre-poolsan 45-byte size (rejected as truncated under the
  // ship-together contract, like the 29-byte shape above).
  emit("tcp_header", "genstamped_read",
       hdr_bytes(kOpRead, 0x1000, 0xBEEF, 65536, 0, 0, 0, 0x0123456789ABCDEFull));
  emit("tcp_header", "max_extent_gen", hdr_bytes(kOpWrite, 0x2000, 0xBEEF, 4096, 0, 0, 0, ~0ull));
  {
    auto legacy45 = hdr_bytes(kOpRead, 0x1000, 0xBEEF, 65536, 0, 7, 9);
    legacy45.resize(45);  // the pre-poolsan header size
    emit("tcp_header", "legacy_45b_truncated", legacy45);
  }
  {
    StagedFrame f{{kOpReadStaged, 0x1000, 0xBEEF, 64 << 10, 50, 0xD15711B07ull, 0x51A9ull, 3},
                  0x2000};
    std::vector<uint8_t> v(sizeof(f));
    std::memcpy(v.data(), &f, sizeof(f));
    truncations("tcp_header", "traced_staged_read", v);
  }

  // record: worker/pool/object records (sel byte picks the decoder),
  // truncations, plus the regression-pinned hostile records.
  keystone::WorkerInfo wi;
  wi.worker_id = "w1";
  wi.address = "h:1";
  wi.topo = {1, 2, 3};
  wi.registered_at_ms = 111;
  wi.last_heartbeat_ms = 222;
  {
    const std::string b = keystone::encode_worker_info(wi);
    truncations("record", "worker",
                with_sel(0, std::vector<uint8_t>(b.begin(), b.end())));
  }
  MemoryPool pool;
  pool.id = "p1";
  pool.node_id = "n1";
  pool.base_addr = 0x1000;
  pool.size = 1 << 20;
  pool.storage_class = StorageClass::RAM_CPU;
  pool.remote = shard.remote;
  pool.topo = {1, 2, 3};
  {
    const std::string b = keystone::encode_pool_record(pool);
    truncations("record", "pool", with_sel(1, std::vector<uint8_t>(b.begin(), b.end())));
  }
  {
    // Current-era object record, hand-framed exactly as
    // keystone_persist.cpp's encode_object_record writes it:
    // [u64 ~0][u8 2][size][ttl][soft_pin][state][config][copies][ts][ts].
    wire::Writer w;
    w.put<uint64_t>(~0ull);
    w.put<uint8_t>(2);
    wire::encode_fields(w, uint64_t{4096}, uint64_t{0}, false, uint8_t{1}, wc,
                        std::vector<CopyPlacement>{copy}, int64_t{1000}, int64_t{2000});
    truncations("record", "object", with_sel(2, w.take()));
    // Same record with a hostile state byte (7): must be rejected.
    wire::Writer w2;
    w2.put<uint64_t>(~0ull);
    w2.put<uint8_t>(2);
    wire::encode_fields(w2, uint64_t{4096}, uint64_t{0}, false, uint8_t{7}, wc,
                        std::vector<CopyPlacement>{copy}, int64_t{1000}, int64_t{2000});
    emit("record", "hostile_state", with_sel(2, w2.take()));
    // Future-format envelope: must be refused (kept, not garbage).
    wire::Writer w3;
    w3.put<uint64_t>(~0ull);
    w3.put<uint8_t>(9);
    w3.put<uint32_t>(0xDEAD);
    emit("record", "future_format", with_sel(2, w3.take()));
  }
  // wal_record: whole coordinator-WAL file images. Valid chains, the torn
  // shapes recovery must truncate, and the chained-CRC-break / rotten-length
  // shapes it must REFUSE (hard-fail classification is the regression
  // surface here), plus legacy / future-version dispatch.
  {
    namespace wal = btpu::coord::wal;
    auto record_payload = [](uint8_t type, const char* key, const char* value) {
      wire::Writer w;
      w.put<uint8_t>(type);
      wire::encode(w, std::string(key));
      wire::encode(w, std::string(value));
      w.put<int64_t>(0);
      return w.take();
    };
    std::vector<uint8_t> valid;
    uint32_t chain = wal::kChainSeed;
    wal::append_file_header(valid);
    const auto r1 = record_payload(1, "/k/a", "v1");
    const auto r2 = record_payload(1, "/k/b", "v2");
    const auto r3 = record_payload(2, "/k/a", "");
    wal::append_record(valid, chain, r1.data(), r1.size());
    wal::append_record(valid, chain, r2.data(), r2.size());
    wal::append_record(valid, chain, r3.data(), r3.size());
    emit("wal_record", "valid_chain", valid);
    emit("wal_record", "header_only",
         std::vector<uint8_t>(valid.begin(), valid.begin() + sizeof(wal::FileHeader)));
    emit("wal_record", "empty", {});
    {  // torn record header (4 stray bytes after the last record)
      auto v = valid;
      v.insert(v.end(), {0x10, 0x00, 0x00, 0x00});
      emit("wal_record", "torn_header", v);
    }
    {  // torn payload: full header promising more bytes than exist
      auto v = valid;
      uint32_t c2 = chain;
      const auto r4 = record_payload(1, "/k/torn", "vvvv");
      wal::append_record(v, c2, r4.data(), r4.size());
      v.resize(v.size() - 3);
      emit("wal_record", "torn_payload", v);
    }
    {  // torn FILE header (the 8-byte header write itself tore)
      emit("wal_record", "torn_file_header",
           std::vector<uint8_t>(valid.begin(), valid.begin() + 5));
    }
    {  // chained-CRC break mid-log: one flipped payload byte = REFUSE
      auto v = valid;
      v[sizeof(wal::FileHeader) + sizeof(wal::RecordHeader) + 2] ^= 0x40;
      emit("wal_record", "chain_break_midlog", v);
    }
    {  // rotten length field mid-log (complete header, impossible len)
      auto v = valid;
      const uint32_t bad = 0xFFFFFFFFu;
      std::memcpy(v.data() + sizeof(wal::FileHeader), &bad, sizeof(bad));
      emit("wal_record", "rotten_length_midlog", v);
    }
    {  // future journal version: refuse, never truncate
      auto v = valid;
      const uint32_t future = wal::kFileVersion + 1;
      std::memcpy(v.data() + sizeof(uint32_t), &future, sizeof(future));
      emit("wal_record", "future_version", v);
    }
    {  // legacy pre-chain journal ([u32 len][payload], no header, no CRC)
      std::vector<uint8_t> legacy;
      for (const auto* rec : {&r1, &r2, &r3}) {
        const uint32_t len = static_cast<uint32_t>(rec->size());
        const uint8_t* lp = reinterpret_cast<const uint8_t*>(&len);
        legacy.insert(legacy.end(), lp, lp + sizeof(len));
        legacy.insert(legacy.end(), rec->begin(), rec->end());
      }
      emit("wal_record", "legacy_journal", legacy);
      legacy.resize(legacy.size() - 2);  // legacy torn tail
      emit("wal_record", "legacy_torn", legacy);
    }
  }
  std::printf("seed corpus written under %s\n", root.c_str());
}

// ---- decode-cost microbench (bench.py guard row) ---------------------------
// Times the checked decoders on the messages a 1 MiB striped get actually
// parses, so bench.py can show the WireReader bounds checks cost nothing
// against the wire time. Run on a NON-sanitized build (asan skews timing).
void bench_decode() {
  using namespace btpu;
  using namespace btpu::transport::datawire;
  using clock = std::chrono::steady_clock;

  // Data-plane header: what the server parses per sub-op.
  DataRequestHeader h{kOpRead, 0x1000, 0xBEEF, 1 << 20, 250, 0xFEEDull, 0xBEEFull, 7};
  std::vector<uint8_t> raw(sizeof(h));
  std::memcpy(raw.data(), &h, sizeof(h));
  constexpr int kHdrIters = 2'000'000;
  uint64_t sink = 0;
  auto t0 = clock::now();
  for (int i = 0; i < kHdrIters; ++i) {
    DataRequestHeader out{};
    if (decode_request_header(raw.data(), raw.size(), out)) sink += out.len;
  }
  const double hdr_ns =
      std::chrono::duration<double, std::nano>(clock::now() - t0).count() / kHdrIters;

  // Control-plane: the GetWorkersResponse a striped get decodes once (4
  // shards, CRC stamps — the realistic metadata payload).
  CopyPlacement copy;
  copy.copy_index = 0;
  for (int s = 0; s < 4; ++s) {
    ShardPlacement shard;
    shard.pool_id = "pool-" + std::to_string(s);
    shard.worker_id = "worker-" + std::to_string(s);
    shard.remote = {TransportKind::TCP, "10.0.0.1:7070", 0x1000, "abcd", "fa", "pv", 1};
    shard.storage_class = StorageClass::RAM_CPU;
    shard.length = (1 << 20) / 4;
    shard.location = MemoryLocation{0x2000, 0x55, (1 << 20) / 4};
    copy.shards.push_back(shard);
    copy.shard_crcs.push_back(0x1234 + static_cast<uint32_t>(s));
  }
  copy.content_crc = 0x9999;
  const auto payload = wire::to_bytes(GetWorkersResponse{{copy}, ErrorCode::OK});
  constexpr int kRpcIters = 200'000;
  t0 = clock::now();
  for (int i = 0; i < kRpcIters; ++i) {
    GetWorkersResponse out{};
    if (wire::from_bytes_lax(payload, out)) sink += out.copies.size();
  }
  const double rpc_ns =
      std::chrono::duration<double, std::nano>(clock::now() - t0).count() / kRpcIters;

  // JSON on stdout for bench.py; sink printed to stderr so nothing folds.
  std::printf("{\"header_decode_ns\": %.1f, \"rpc_response_decode_ns\": %.1f, "
              "\"rpc_payload_bytes\": %zu}\n",
              hdr_ns, rpc_ns, payload.size());
  std::fprintf(stderr, "sink=%llu\n", static_cast<unsigned long long>(sink));
}

}  // namespace

int main(int argc, char** argv) {
  std::string corpus, gen, only_target;
  uint64_t execs = 250000;
  for (int i = 1; i < argc; ++i) {
    const std::string a = argv[i];
    if (a == "--corpus" && i + 1 < argc) corpus = argv[++i];
    else if (a == "--gen-seeds" && i + 1 < argc) gen = argv[++i];
    else if (a == "--execs" && i + 1 < argc) execs = std::strtoull(argv[++i], nullptr, 10);
    else if (a == "--target" && i + 1 < argc) only_target = argv[++i];
    else if (a == "--bench-decode") { bench_decode(); return 0; }
    else {
      std::fprintf(stderr,
                   "usage: %s --corpus DIR [--execs N] [--target NAME] | --gen-seeds DIR\n",
                   argv[0]);
      return 2;
    }
  }
  if (!gen.empty()) {
    gen_seeds(gen);
    return 0;
  }
  if (corpus.empty()) {
    std::fprintf(stderr, "need --corpus or --gen-seeds\n");
    return 2;
  }
  for (const auto& t : kFuzzTargets) {
    if (!only_target.empty() && only_target != t.name) continue;
    const auto files = list_corpus_dir(corpus + "/" + t.name);
    if (files.empty()) {
      std::fprintf(stderr, "fuzz: no corpus for %s under %s — refusing to claim coverage\n",
                   t.name, corpus.c_str());
      return 1;
    }
    uint64_t ran = 0;
    // Phase 1: pure replay (every checked-in input, incl. past crashers).
    std::vector<std::vector<uint8_t>> inputs;
    for (const auto& f : files) {
      inputs.push_back(read_corpus_file(f));
      t.fn(inputs.back().data(), inputs.back().size());
      ++ran;
    }
    // Phase 2: deterministic mutation sweep until the exec budget is spent.
    uint64_t seed_idx = 0;
    while (ran < execs) {
      const auto& base = inputs[seed_idx % inputs.size()];
      uint64_t s = fnv1a(base) ^ (0x9E3779B97F4A7C15ull * (seed_idx + 1));
      std::vector<uint8_t> v = base;
      const uint64_t steps = 1 + xorshift64(s) % 8;
      for (uint64_t m = 0; m < steps; ++m) {
        mutate(v, s);
        t.fn(v.data(), v.size());
        if (++ran >= execs) break;
      }
      ++seed_idx;
    }
    std::printf("fuzz[%s]: %llu execs over %zu seed inputs, 0 crashes\n", t.name,
                static_cast<unsigned long long>(ran), files.size());
  }
  return 0;
}
