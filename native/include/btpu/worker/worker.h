// Worker service: the data plane. Builds tiered storage pools, registers
// them with the transport, advertises pools + itself through the
// coordination service, and heartbeats. After registration workers never
// touch the data path — clients move bytes with one-sided transfers.
//
// Parity target: reference include/blackbird/worker/worker_service.h:21-154 /
// src/worker/worker_service.cpp (YAML config :25-108, backend construction
// :317-360, transport registration :167-221, advertisement :399-432,
// heartbeat :434-459, key deletion on stop :256-297). Changes:
//   * all tiers advertise, including NVME/SSD (reference's factory gap) and
//     HBM (reference flags RAM_GPU registration broken, :196) — non-mapped
//     tiers ride callback-backed virtual transport regions;
//   * transport is chosen per config (tcp | shm | local), not hard-coded UCX.
#pragma once

#include <atomic>
#include <condition_variable>
#include <memory>
#include <thread>

#include "btpu/common/thread_annotations.h"
#include "btpu/coord/coordinator.h"
#include "btpu/keystone/keystone.h"
#include "btpu/storage/backend.h"
#include "btpu/transport/transport.h"

namespace btpu::worker {

struct PoolConfig {
  std::string id;
  StorageClass storage_class{StorageClass::RAM_CPU};
  uint64_t capacity{0};
  std::string path;       // disk tiers; CXL tiers: DAX device / pmem file
  std::string device_id;  // hbm tier ("tpu:0")
  uint64_t interleave_granularity{256};  // cxl tiers
  int numa_node{-1};                     // cxl tiers (-1 = unbound)
  // Advertised placement alignment; 0 = tier default (HBM: provider chunk
  // size so shards hit whole-chunk device transfers; others: none).
  uint64_t alignment{0};
};

struct WorkerServiceConfig {
  NodeId worker_id;
  std::string cluster_id{kDefaultClusterId};
  std::string coord_endpoints;  // "" = standalone (keystone fed directly)
  TransportKind transport{TransportKind::TCP};
  std::string listen_host{"0.0.0.0"};
  uint16_t listen_port{0};  // 0 = ephemeral, advertised after bind
  TopoCoord topo;
  int64_t heartbeat_ttl_ms{10000};
  int64_t heartbeat_interval_ms{5000};
  std::vector<PoolConfig> pools;

  // Loads the YAML subset schema (configs/worker.yaml). Throws
  // std::runtime_error on parse/validation failure.
  static WorkerServiceConfig from_yaml(const std::string& file_path);
  ErrorCode validate() const;
};

class WorkerService {
 public:
  WorkerService(WorkerServiceConfig config, std::shared_ptr<coord::Coordinator> coordinator);

  // One-call production startup shared by bb-worker and the Python worker
  // host (capi): yaml load (+ optional coordinator override), coordinator
  // connect, initialize, start. Returns a RUNNING worker or the first error.
  static Result<std::unique_ptr<WorkerService>> create_from_yaml(
      const std::string& config_path, const std::string& coord_override = "");
  ~WorkerService();

  ErrorCode initialize();  // backends + transports + regions
  ErrorCode start();       // advertise + heartbeat
  void stop();

  const WorkerServiceConfig& config() const noexcept { return config_; }
  // Advertised pool records (valid after initialize()).
  std::vector<MemoryPool> pools() const;
  keystone::WorkerInfo info() const;
  // Worker-local stats per pool.
  std::vector<std::pair<std::string, storage::StorageStats>> stats() const;
  storage::StorageBackend* backend(const std::string& pool_id);

 private:
  void heartbeat_loop();
  void advertise();

  WorkerServiceConfig config_;
  std::shared_ptr<coord::Coordinator> coordinator_;
  std::unique_ptr<transport::TransportServer> primary_transport_;
  std::unique_ptr<transport::TransportServer> virtual_transport_;  // for non-mapped tiers

  struct PoolRuntime {
    PoolConfig config;
    std::unique_ptr<storage::StorageBackend> backend;
    MemoryPool record;
  };
  std::vector<PoolRuntime> pools_;

  std::atomic<bool> running_{false};
  std::thread heartbeat_thread_;
  // condition_variable_any: waits on the annotated Mutex (BasicLockable),
  // which plain condition_variable cannot.
  CondVarAny stop_cv_;
  Mutex stop_mutex_;
  bool initialized_{false};  // initialize()/start() sequencing, caller thread only
};

}  // namespace btpu::worker
