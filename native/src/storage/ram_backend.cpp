// RAM tier (host DRAM / CXL-style memory): flat allocation, direct mapping.
//
// Parity target: reference src/worker/storage/ram_backend.cpp (malloc pool,
// reserve/commit lifecycle) and cxl_memory_backend.cpp (mmap'd device
// memory with anonymous fallback) — both collapse to one backend here since
// the only difference is where the bytes live; the worker may hand us
// transport-owned memory (shm segment) via set_external_region.
#include <cstdlib>
#include <cstring>

#include "backend_base.h"
#include "btpu/common/log.h"
#include "btpu/common/pool_span.h"

namespace btpu::storage {

class RamBackend : public OffsetBackendBase {
 public:
  explicit RamBackend(BackendConfig config) : OffsetBackendBase(std::move(config)) {}
  ~RamBackend() override { shutdown(); }

  // Adopt caller-owned memory (e.g. a shm segment) instead of mallocing.
  void set_external_region(void* base) { external_base_ = base; }

  ErrorCode initialize() override {
    if (base_) return ErrorCode::INVALID_STATE;
    if (external_base_) {
      base_ = static_cast<uint8_t*>(external_base_);
      owned_ = false;
    } else {
      base_ = static_cast<uint8_t*>(std::malloc(config_.capacity));
      if (!base_) return ErrorCode::OUT_OF_MEMORY;
      owned_ = true;
    }
    return init_allocator();
  }

  void shutdown() override {
    if (base_ && owned_) std::free(base_);
    base_ = nullptr;
  }

  void* base_address() const override { return base_; }

  ErrorCode write_at(uint64_t offset, const void* src, uint64_t len) override {
    if (!base_) return ErrorCode::INVALID_STATE;
    auto span = poolspan::resolve(base_, config_.capacity, offset, len, 0,
                                  poolspan::Access::kWrite, config_.pool_id.c_str());
    if (!span.ok()) return span.error();
    std::memcpy(span.value().data(), src, len);
#if defined(BTPU_POOLSAN)
    // PLANTED MUTANT — 1-byte extent overrun (the neighbor-corruption class
    // red zones exist to catch): smear one byte past the written window,
    // the way an off-by-one length computation once would. On asan trees
    // the poisoned red zone traps this store natively; on gcc trees the
    // smashed canary is CONVICTED at free/scrub with a replayable report.
    // Pinned by Poolsan.MutantOverrun.
    if (poolsan::mutant() == poolsan::Mutant::kOverrun && offset + len < config_.capacity)
      span.value().data()[len] = 0x5A;
#endif
    return ErrorCode::OK;
  }

  ErrorCode read_at(uint64_t offset, void* dst, uint64_t len) override {
    if (!base_) return ErrorCode::INVALID_STATE;
    auto span = poolspan::resolve(base_, config_.capacity, offset, len, 0,
                                  poolspan::Access::kRead, config_.pool_id.c_str());
    if (!span.ok()) return span.error();
    std::memcpy(dst, span.value().data(), len);
    return ErrorCode::OK;
  }

 private:
  uint8_t* base_{nullptr};
  void* external_base_{nullptr};
  bool owned_{false};
};

std::unique_ptr<StorageBackend> make_ram_backend(const BackendConfig& config) {
  return std::make_unique<RamBackend>(config);
}

std::unique_ptr<StorageBackend> create_ram_backend_with_region(const BackendConfig& config,
                                                               void* region) {
  auto backend = std::make_unique<RamBackend>(config);
  backend->set_external_region(region);
  return backend;
}

}  // namespace btpu::storage
