// Server-side admission control: a bounded request gate with adaptive-LIFO
// shedding, shared by the keystone RPC server and the TCP data-plane server.
//
// The failure mode this kills: an overloaded server that keeps accepting
// work builds an unbounded queue, every queued request eventually times out
// client-side, and the server spends its capacity producing answers nobody
// is still waiting for — one slow node browns out the cluster. Instead:
//   * at most `max_inflight` requests are serviced concurrently;
//   * at most `max_queue` more may WAIT, newest-first (LIFO): under a burst
//     the requests most likely to still have a live waiter are served
//     first, and the oldest waiter — the one closest to its client-side
//     deadline — is shed with RETRY_LATER + a backoff hint;
//   * a waiter whose own deadline expires in the queue is rejected with
//     DEADLINE_EXCEEDED before any work is done for it;
//   * bytes watermark: admission can also be charged in payload bytes
//     (data plane), so a few giant transfers cannot monopolize the gate
//     that op-count alone would admit.
// Control-plane traffic bypasses the gate entirely at the call site —
// health checks and leadership probes must work exactly when the gate is
// closed (that is when operators need them).
#pragma once

#include <condition_variable>
#include <cstdint>
#include <deque>

#include "btpu/common/deadline.h"
#include "btpu/common/thread_annotations.h"

namespace btpu {

class AdmissionGate {
 public:
  struct Options {
    uint32_t max_inflight{64};
    uint32_t max_queue{128};
    // Bytes watermark for payload-charged admission; 0 = op count only.
    uint64_t max_inflight_bytes{0};
    // Hint returned with RETRY_LATER sheds (the client jitters around it).
    uint32_t backoff_hint_ms{50};
  };

  enum class Verdict : uint8_t {
    kAdmitted = 0,
    kShed = 1,      // queue over watermark: RETRY_LATER(backoff_hint_ms)
    kDeadline = 2,  // the waiter's own budget expired while queued
  };

  explicit AdmissionGate(Options options) : options_(options) {}

  // Blocks until admitted, shed, or the deadline expires. Every kAdmitted
  // MUST be paired with release(bytes) with the same byte charge.
  Verdict admit(const Deadline& deadline, uint64_t bytes = 0) {
    MutexLock lock(mutex_);
    if (can_enter_locked(bytes)) {
      enter_locked(bytes);
      return Verdict::kAdmitted;
    }
    if (queue_.size() >= options_.max_queue) {
      // Adaptive LIFO: shed the OLDEST waiter (front), not the newcomer —
      // the newcomer's client deadline has the most budget left, so serving
      // it first maximizes work that still has a live waiter. The shed
      // waiter gets RETRY_LATER, which is cheaper for its client than the
      // timeout it was marching toward.
      if (!queue_.empty()) {
        Waiter* oldest = queue_.front();
        queue_.pop_front();
        oldest->verdict = Verdict::kShed;
        oldest->decided = true;
        cv_.notify_all();
      } else {
        return Verdict::kShed;  // max_queue == 0: never wait
      }
    }
    Waiter self;
    self.bytes = bytes;
    queue_.push_back(&self);
    while (!self.decided) {
      if (deadline.is_infinite()) {
        cv_.wait(lock);
      } else if (cv_.wait_until(lock, deadline.time_point()) == std::cv_status::timeout &&
                 !self.decided) {
        remove_locked(&self);
        return Verdict::kDeadline;
      }
    }
    return self.verdict;
  }

  // Non-blocking admission for event-loop servers (the uring data plane):
  // enters and returns true when capacity allows, false otherwise — the
  // caller parks the op in its OWN queue (mirroring the adaptive-LIFO
  // semantics above) and retries after releases. Thread waiters queue
  // first so an event loop sharing a gate with blocking callers cannot
  // starve them. Every true MUST be paired with release(bytes).
  [[nodiscard]] bool try_enter(uint64_t bytes = 0) {
    MutexLock lock(mutex_);
    if (!queue_.empty()) return false;
    if (!can_enter_locked(bytes)) return false;
    enter_locked(bytes);
    return true;
  }

  void release(uint64_t bytes = 0) {
    MutexLock lock(mutex_);
    --inflight_;
    inflight_bytes_ -= bytes;
    wake_locked();
  }

  uint32_t backoff_hint_ms() const noexcept { return options_.backoff_hint_ms; }
  const Options& options() const noexcept { return options_; }

  uint32_t inflight() const {
    MutexLock lock(mutex_);
    return inflight_;
  }
  size_t queued() const {
    MutexLock lock(mutex_);
    return queue_.size();
  }

 private:
  struct Waiter {
    uint64_t bytes{0};
    bool decided{false};
    Verdict verdict{Verdict::kAdmitted};
  };

  bool can_enter_locked(uint64_t bytes) const BTPU_REQUIRES(mutex_) {
    if (inflight_ >= options_.max_inflight) return false;
    // A gate must never deadlock on one oversized request: bytes are only
    // a brake when something else is already in flight.
    if (options_.max_inflight_bytes != 0 && inflight_ > 0 &&
        inflight_bytes_ + bytes > options_.max_inflight_bytes)
      return false;
    return true;
  }
  void enter_locked(uint64_t bytes) BTPU_REQUIRES(mutex_) {
    ++inflight_;
    inflight_bytes_ += bytes;
  }
  void wake_locked() BTPU_REQUIRES(mutex_) {
    // Admit from the BACK (newest) while capacity allows.
    bool woke = false;
    while (!queue_.empty() && can_enter_locked(queue_.back()->bytes)) {
      Waiter* w = queue_.back();
      queue_.pop_back();
      enter_locked(w->bytes);
      w->verdict = Verdict::kAdmitted;
      w->decided = true;
      woke = true;
    }
#if defined(BTPU_SCHED)
    if (woke && sched::mutant_enabled("admission_lost_wakeup")) {
      // PLANTED MUTANT — lost-wakeup class: decide the waiter but skip the
      // notify. An admitted waiter parks forever on cv_; the scheduler's
      // all-blocked watchdog convicts it as a deadlock with the seed
      // printed (SchedMutants matrix).
      return;
    }
#endif
    if (woke) cv_.notify_all();
  }
  void remove_locked(Waiter* w) BTPU_REQUIRES(mutex_) {
    for (auto it = queue_.begin(); it != queue_.end(); ++it) {
      if (*it == w) {
        queue_.erase(it);
        return;
      }
    }
  }

  const Options options_;
  mutable Mutex mutex_;
  uint32_t inflight_ BTPU_GUARDED_BY(mutex_){0};
  uint64_t inflight_bytes_ BTPU_GUARDED_BY(mutex_){0};
  std::deque<Waiter*> queue_ BTPU_GUARDED_BY(mutex_);
  CondVarAny cv_;
};

// RAII admission: verdict() tells the caller whether to serve or reject.
class AdmissionTicket {
 public:
  AdmissionTicket(AdmissionGate& gate, const Deadline& deadline, uint64_t bytes = 0)
      : gate_(gate), bytes_(bytes), verdict_(gate.admit(deadline, bytes)) {}
  ~AdmissionTicket() {
    if (verdict_ == AdmissionGate::Verdict::kAdmitted) gate_.release(bytes_);
  }
  AdmissionTicket(const AdmissionTicket&) = delete;
  AdmissionTicket& operator=(const AdmissionTicket&) = delete;

  AdmissionGate::Verdict verdict() const noexcept { return verdict_; }
  bool admitted() const noexcept {
    return verdict_ == AdmissionGate::Verdict::kAdmitted;
  }

 private:
  AdmissionGate& gate_;
  uint64_t bytes_;
  AdmissionGate::Verdict verdict_;
};

}  // namespace btpu
