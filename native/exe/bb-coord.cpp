// bb-coord: standalone coordination service (the etcd role in the reference
// deployment, scripts/start_cluster.sh launches etcd first — here the
// framework ships its own).
#include <csignal>
#include <cstdio>
#include <cstring>
#include <string>

#include "btpu/common/log.h"
#include "btpu/coord/coord_server.h"

namespace {
volatile std::sig_atomic_t g_stop = 0;
void handle_signal(int) { g_stop = 1; }
}  // namespace

int main(int argc, char** argv) {
  std::string host = "0.0.0.0";
  uint16_t port = 9290;
  btpu::coord::DurabilityOptions durability;
  for (int i = 1; i < argc; ++i) {
    if (!std::strcmp(argv[i], "--host") && i + 1 < argc) host = argv[++i];
    else if (!std::strcmp(argv[i], "--port") && i + 1 < argc) port = static_cast<uint16_t>(std::stoi(argv[++i]));
    else if (!std::strcmp(argv[i], "--data-dir") && i + 1 < argc) durability.dir = argv[++i];
    else if (!std::strcmp(argv[i], "--no-fsync")) durability.fsync = false;
    else if (!std::strcmp(argv[i], "--help")) {
      std::printf("usage: bb-coord [--host H] [--port P] [--data-dir DIR] [--no-fsync]\n"
                  "  --data-dir DIR  persist state (WAL + snapshot); restart recovers\n"
                  "                  keys, leases (re-armed to full TTL), and objects\n"
                  "  --no-fsync      skip per-record fsync (tests/benchmarks)\n");
      return 0;
    }
  }
  btpu::coord::CoordServer server(host, port, durability);
  if (server.start() != btpu::ErrorCode::OK) {
    std::fprintf(stderr, "bb-coord: failed to listen on %s:%u\n", host.c_str(), port);
    return 1;
  }
  std::printf("bb-coord listening on %s\n", server.endpoint().c_str());
  std::fflush(stdout);
  std::signal(SIGINT, handle_signal);
  std::signal(SIGTERM, handle_signal);
  while (!g_stop) {
    std::this_thread::sleep_for(std::chrono::milliseconds(200));
  }
  server.stop();
  return 0;
}
