"""Standalone worker host: the production TPU-VM worker process.

On a TPU VM the process that owns the chip is the one with the JAX runtime
in it, so the native worker must run in THAT process for the HBM tier to
serve real device memory — the pure-C++ `bb-worker` can only offer the
emulated (host-memory) provider. This module is the deployment shape for
device-tier workers:

    python -m blackbird_tpu.worker --config worker.yaml \
        [--coord host:port[,host:port...]] [--no-jax]

It registers a `JaxHbmProvider` (unless --no-jax), then starts the native
WorkerService from the same worker.yaml `bb-worker` reads: pools come up,
transport regions register (HBM pools as callback-backed regions served by
the provider — cross-process clients reach them over the worker's TCP/SHM
data plane; in-process ICI meshes use EmbeddedCluster instead), the worker
advertises itself to the coordinator and heartbeats. Role parity:
reference examples/worker_example.cpp + src/worker/worker_service.cpp,
with the device tier actually functional (the reference's RAM_GPU tier was
broken, worker_service.cpp:196).
"""

from __future__ import annotations

import argparse
import os
import signal
import sys
import threading
from typing import TYPE_CHECKING, Any

from blackbird_tpu.native import lib

if TYPE_CHECKING:
    from pathlib import Path

    from blackbird_tpu.hbm import JaxHbmProvider


def write_worker_yaml(path: str | Path, *, worker_id: str, cluster_id: str,
                      coord_endpoints: str, pools: list[dict[str, Any]],
                      listen_host: str = "0.0.0.0", host_id: int = 0,
                      slice_id: int = 0, heartbeat_interval_ms: int = 1000,
                      heartbeat_ttl_ms: int = 5000) -> None:
    """Writes a worker.yaml — THE single source for the config shape used by
    every programmatic launcher (procluster, the jax.distributed bridge).

    Each pool dict: {"id", "storage_class", "capacity" (int bytes or a
    "8MB"-style string), optional "device_id"}."""

    def q(value: object) -> str:
        # Interpolated strings are single-quoted so ':'/'#' cannot corrupt
        # the document; the native parser strips one layer of quotes but has
        # no escape for an embedded quote, so those are rejected outright.
        s = str(value)
        if "'" in s or '"' in s or "\n" in s:
            raise ValueError(f"unrepresentable YAML scalar: {s!r}")
        return f"'{s}'"

    lines = [
        f"worker_id: {q(worker_id)}",
        f"cluster_id: {q(cluster_id)}",
        f"coord_endpoints: {q(coord_endpoints)}",
        "transport: tcp",
        f"listen_host: {q(listen_host)}",
        f"slice_id: {slice_id:d}",
        f"host_id: {host_id:d}",
        "heartbeat:",
        f"  interval_ms: {heartbeat_interval_ms:d}",
        f"  ttl_ms: {heartbeat_ttl_ms:d}",
        "pools:",
    ]
    for pool in pools:
        lines.append(f"  - id: {q(pool['id'])}")
        lines.append(f"    storage_class: {q(pool['storage_class'])}")
        lines.append(f"    capacity: {q(pool['capacity'])}")
        # `is not None`, not truthiness: device 0 is a real device.
        if pool.get("device_id") is not None:
            lines.append(f"    device_id: {q(pool['device_id'])}")
        if pool.get("path") is not None:  # file-backed tiers (mmap/io_uring)
            lines.append(f"    path: {q(pool['path'])}")
    with open(path, "w") as f:
        f.write("\n".join(lines) + "\n")


def _pin_jax_platform() -> None:
    """Honor JAX_PLATFORMS before the backend initializes: some images
    register a hardware PJRT plugin from sitecustomize that overrides the
    env var, and initializing a sick tunneled device can hang outright."""
    if not os.environ.get("JAX_PLATFORMS"):
        return
    try:
        import jax

        jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
    except Exception:  # noqa: BLE001
        pass


class WorkerHost:
    """A running native worker, optionally fronting JAX device memory."""

    def __init__(self, config_path: str, coord: str | None = None,
                 jax_provider: bool = True) -> None:
        self._provider: JaxHbmProvider | None = None
        if jax_provider:
            _pin_jax_platform()
            from blackbird_tpu.hbm import JaxHbmProvider

            self._provider = JaxHbmProvider().register()
        self._handle: int | None = lib.btpu_worker_create(
            config_path.encode(), coord.encode() if coord else None)
        if not self._handle:
            if self._provider is not None:
                self._provider.unregister()
            raise RuntimeError(f"worker startup failed (config {config_path!r})")

    @property
    def pool_count(self) -> int:
        return lib.btpu_worker_pool_count(self._handle)

    @property
    def worker_id(self) -> str:
        raw = lib.btpu_worker_id(self._handle)
        return raw.decode() if raw is not None else ""

    def close(self) -> None:
        if self._handle:
            lib.btpu_worker_destroy(self._handle)
            self._handle = None
        if self._provider is not None:
            self._provider.unregister()
            self._provider = None

    def __enter__(self) -> WorkerHost:
        return self

    def __exit__(self, *exc: object) -> None:
        self.close()


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(description=(__doc__ or "").splitlines()[0])
    parser.add_argument("--config", required=True, help="worker.yaml path")
    parser.add_argument("--coord", default=None,
                        help="coordinator endpoint list override (host:port,...)")
    parser.add_argument("--no-jax", action="store_true",
                        help="skip the JAX HBM provider (host tiers only)")
    parser.add_argument("--drain-on-term", metavar="KEYSTONE",
                        help="on SIGTERM (the TPU preemption notice), ask the "
                             "keystone at this endpoint list to drain this "
                             "worker — every copy migrates off the live "
                             "process — before shutting down")
    args = parser.parse_args(argv)

    host = WorkerHost(args.config, coord=args.coord, jax_provider=not args.no_jax)
    print(f"worker up with {host.pool_count} pools", flush=True)

    stop = threading.Event()
    got_signal: dict[str, int | None] = {"sig": None}

    def on_signal(signum: int, _frame: object) -> None:
        got_signal["sig"] = signum
        stop.set()

    for sig in (signal.SIGINT, signal.SIGTERM):
        signal.signal(sig, on_signal)
    stop.wait()
    # Drain only on SIGTERM (the preemption notice); Ctrl-C stays a prompt
    # dev shutdown.
    if args.drain_on_term and got_signal["sig"] == signal.SIGTERM:
        # The id comes from the native worker itself (btpu_worker_id) — no
        # second YAML parser to drift from the one that registered it.
        worker_id = host.worker_id
        try:
            from blackbird_tpu.client import Client

            moved = Client(args.drain_on_term).drain_worker(worker_id)
            print(f"drained {worker_id}: {moved} shards migrated", flush=True)
        except Exception as exc:  # noqa: BLE001 - shut down regardless
            print(f"drain failed ({exc}); shutting down anyway", flush=True)
    host.close()
    return 0


if __name__ == "__main__":
    sys.exit(main())
