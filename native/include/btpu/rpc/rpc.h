// Keystone RPC protocol: opcodes map 1:1 to KeystoneService methods.
//
// Versioning stance: the wire protocol IS cross-version stable within the
// v2 opcode epoch. Every composite struct is size-prefixed and every
// message decodes tail-tolerantly (wire.h), so the append-only evolution
// rule — new fields only at the end, types never change — lets older and
// newer peers interoperate in both directions during a rolling upgrade;
// test_rpc.cpp's compatibility tests frame newer- and older-peer messages
// by hand and prove it. kPing carries each side's kProtocolVersion so
// operators can audit a mixed fleet. The v1 epoch (opcodes 1-17, unprefixed
// structs) predates this guarantee; v2 opcodes live at +64 so a cross-epoch
// call fails loudly with an unknown-opcode error instead of a mis-decode.
//
// Parity target: reference include/blackbird/rpc/rpc_service.h:28-274 — 14
// rpc_* handlers over YLT coro_rpc (rpc_service.cpp:360-385; struct_pack had
// no version tolerance — this is our own bar, not the reference's). Framing
// is the shared net.h frame: [u32 len][u8 opcode][wire-encoded struct];
// responses reuse the request opcode.
#pragma once

#include <cstdint>
#include <cstring>
#include <vector>

#include "btpu/common/error.h"
#include "btpu/common/wire.h"

namespace btpu::rpc {

// Wire-protocol version advertised in the kPing handshake. Bump when the
// append-only rule is insufficient to describe a change (should be rare).
// v4: requests may carry a deadline trailer (below) and servers may answer
// any request with a control-error frame (kControlErrorOpcode) — both are
// ignored-by-old-peers constructs, so v3<->v4 still interoperates.
// v5: requests may additionally carry a trace trailer (trace id + parent
// span id, Dapper-style propagation). Appended BEFORE the deadline trailer
// so a v4 server still finds its deadline magic at the payload tail and
// the trace bytes fall into the tail-tolerant decode; v4<->v5
// interoperates in both directions (traced requests to an old server are
// simply served untraced).
inline constexpr uint32_t kProtocolVersion = 5;

// First version whose put_complete APPLIES the appended content_crc field.
// A newer client talking to an older keystone must keep stamping the
// whole-object CRC at put_start (the old path) — deferring it would decode
// cleanly but silently leave every object unstamped, disabling the
// verified-read gate for bytes written during a rolling upgrade.
inline constexpr uint32_t kProtoContentCrcAtComplete = 3;

enum class Method : uint8_t {
  kObjectExists = 65,
  kGetWorkers = 66,
  kPutStart = 67,
  kPutComplete = 68,
  kPutCancel = 69,
  kRemoveObject = 70,
  kRemoveAllObjects = 71,
  kGetClusterStats = 72,
  kGetViewVersion = 73,
  kBatchObjectExists = 74,
  kBatchGetWorkers = 75,
  kBatchPutStart = 76,
  kBatchPutComplete = 77,
  kBatchPutCancel = 78,
  kPing = 79,
  kDrainWorker = 80,
  kListObjects = 81,
  kPutStartPooled = 82,
  kPutCommitSlot = 83,
  kPutInline = 84,
  kListPools = 85,
};

// ---- deadline propagation (protocol v4) ------------------------------------
// The per-request deadline rides as a TRAILER appended after the encoded
// request struct: [u64 magic][u32 remaining_budget_ms]. Request payloads are
// decoded tail-tolerantly (wire.h from_bytes_lax), so a pre-v4 server simply
// ignores the 12 extra bytes; a v4 server strips and honors them. The budget
// is RELATIVE (remaining ms at send time) so clock skew between hosts can
// never expire a request spuriously — the receiving server restarts the
// clock at receipt. budget_ms == 0 on the wire is reserved for "already
// expired" (hand-framed only; clients fail locally instead of sending it).
inline constexpr uint64_t kDeadlineTrailerMagic = 0xB7D0DEAD11A3C4F5ull;
inline constexpr size_t kDeadlineTrailerBytes = 12;

inline void append_deadline_trailer(std::vector<uint8_t>& payload, uint32_t budget_ms) {
  const size_t at = payload.size();
  payload.resize(at + kDeadlineTrailerBytes);
  std::memcpy(payload.data() + at, &kDeadlineTrailerMagic, sizeof(kDeadlineTrailerMagic));
  std::memcpy(payload.data() + at + sizeof(kDeadlineTrailerMagic), &budget_ms,
              sizeof(budget_ms));
}

// Strips a trailing deadline trailer when present. Returns true and the
// budget (which may legitimately be 0 = expired-on-arrival) iff the magic
// matched; payload is truncated to the bare request bytes either way a
// trailer was found. A payload shorter than the trailer simply has no
// trailer — that is version skew (pre-v4 peer), not corruption.
BTPU_NODISCARD inline bool strip_deadline_trailer(std::vector<uint8_t>& payload,
                                                  uint32_t& budget_ms) {
  if (payload.size() < kDeadlineTrailerBytes) return false;
  const size_t at = payload.size() - kDeadlineTrailerBytes;
  wire::WireReader r(payload.data() + at, kDeadlineTrailerBytes);
  uint64_t magic = 0;
  if (!r.u64(magic) || magic != kDeadlineTrailerMagic) return false;
  if (!r.u32(budget_ms)) return false;
  payload.resize(at);
  return true;
}

// ---- trace propagation (protocol v5) ---------------------------------------
// The ambient trace context rides as a second tagged trailer:
// [u64 magic][u64 trace_id][u64 parent_span_id]. Append ORDER is the
// compatibility contract: [request][trace trailer][deadline trailer] — the
// deadline trailer stays OUTERMOST (at the payload tail) so a pre-v5
// server's strip_deadline_trailer still matches, after which the trace
// bytes are trailing garbage its tail-tolerant request decode ignores. A
// v5 server strips deadline first, then trace. trace_id 0 is never sent
// (untraced requests simply omit the trailer), so 0 stays the unambiguous
// "untraced" value everywhere.
inline constexpr uint64_t kTraceTrailerMagic = 0xB7D07A1DC0FFEE15ull;
inline constexpr size_t kTraceTrailerBytes = 24;

inline void append_trace_trailer(std::vector<uint8_t>& payload, uint64_t trace_id,
                                 uint64_t parent_span_id) {
  const size_t at = payload.size();
  payload.resize(at + kTraceTrailerBytes);
  std::memcpy(payload.data() + at, &kTraceTrailerMagic, sizeof(kTraceTrailerMagic));
  std::memcpy(payload.data() + at + 8, &trace_id, sizeof(trace_id));
  std::memcpy(payload.data() + at + 16, &parent_span_id, sizeof(parent_span_id));
}

// Strips a trailing trace trailer when present: true iff the magic matched
// AND the carried trace id is nonzero (a forged zero id would alias the
// "untraced" sentinel downstream — treat it as no trailer). The payload is
// truncated to the bare bytes only when a valid trailer was found.
BTPU_NODISCARD inline bool strip_trace_trailer(std::vector<uint8_t>& payload,
                                               uint64_t& trace_id,
                                               uint64_t& parent_span_id) {
  if (payload.size() < kTraceTrailerBytes) return false;
  const size_t at = payload.size() - kTraceTrailerBytes;
  wire::WireReader r(payload.data() + at, kTraceTrailerBytes);
  uint64_t magic = 0;
  if (!r.u64(magic) || magic != kTraceTrailerMagic) return false;
  uint64_t tid = 0, sid = 0;
  if (!r.u64(tid) || !r.u64(sid)) return false;
  if (tid == 0) return false;  // forged/hand-framed: 0 means untraced
  trace_id = tid;
  parent_span_id = sid;
  payload.resize(at);
  return true;
}

// Human-readable method names: histogram labels
// (btpu_rpc_duration_us{method=...}) and span names share these literals.
inline const char* method_name(uint8_t opcode) noexcept {
  switch (static_cast<Method>(opcode)) {
    case Method::kObjectExists: return "object_exists";
    case Method::kGetWorkers: return "get_workers";
    case Method::kPutStart: return "put_start";
    case Method::kPutComplete: return "put_complete";
    case Method::kPutCancel: return "put_cancel";
    case Method::kRemoveObject: return "remove_object";
    case Method::kRemoveAllObjects: return "remove_all_objects";
    case Method::kGetClusterStats: return "get_cluster_stats";
    case Method::kGetViewVersion: return "get_view_version";
    case Method::kBatchObjectExists: return "batch_object_exists";
    case Method::kBatchGetWorkers: return "batch_get_workers";
    case Method::kBatchPutStart: return "batch_put_start";
    case Method::kBatchPutComplete: return "batch_put_complete";
    case Method::kBatchPutCancel: return "batch_put_cancel";
    case Method::kPing: return "ping";
    case Method::kDrainWorker: return "drain_worker";
    case Method::kListObjects: return "list_objects";
    case Method::kPutStartPooled: return "put_start_pooled";
    case Method::kPutCommitSlot: return "put_commit_slot";
    case Method::kPutInline: return "put_inline";
    case Method::kListPools: return "list_pools";
  }
  return "unknown";
}

// Span names for the server-side dispatch span (must be literals: the span
// ring stores pointers — see trace.h).
inline const char* method_span_name(uint8_t opcode) noexcept {
  switch (static_cast<Method>(opcode)) {
    case Method::kObjectExists: return "keystone.rpc.object_exists";
    case Method::kGetWorkers: return "keystone.rpc.get_workers";
    case Method::kPutStart: return "keystone.rpc.put_start";
    case Method::kPutComplete: return "keystone.rpc.put_complete";
    case Method::kPutCancel: return "keystone.rpc.put_cancel";
    case Method::kRemoveObject: return "keystone.rpc.remove_object";
    case Method::kRemoveAllObjects: return "keystone.rpc.remove_all_objects";
    case Method::kGetClusterStats: return "keystone.rpc.get_cluster_stats";
    case Method::kGetViewVersion: return "keystone.rpc.get_view_version";
    case Method::kBatchObjectExists: return "keystone.rpc.batch_object_exists";
    case Method::kBatchGetWorkers: return "keystone.rpc.batch_get_workers";
    case Method::kBatchPutStart: return "keystone.rpc.batch_put_start";
    case Method::kBatchPutComplete: return "keystone.rpc.batch_put_complete";
    case Method::kBatchPutCancel: return "keystone.rpc.batch_put_cancel";
    case Method::kPing: return "keystone.rpc.ping";
    case Method::kDrainWorker: return "keystone.rpc.drain_worker";
    case Method::kListObjects: return "keystone.rpc.list_objects";
    case Method::kPutStartPooled: return "keystone.rpc.put_start_pooled";
    case Method::kPutCommitSlot: return "keystone.rpc.put_commit_slot";
    case Method::kPutInline: return "keystone.rpc.put_inline";
    case Method::kListPools: return "keystone.rpc.list_pools";
  }
  return "keystone.rpc.unknown";
}

// ---- control-error frames (protocol v4) ------------------------------------
// Overload rejections (RETRY_LATER + backoff hint) and deadline rejections
// (DEADLINE_EXCEEDED) are answered BEFORE the request is dispatched, so they
// cannot ride the per-method response structs. The server instead answers
// with this reserved response opcode and payload [u32 error][u32 hint_ms].
// A v4 client surfaces the error without closing the connection; a pre-v4
// client sees a mismatched response opcode and treats the call as failed —
// which under overload it is.
inline constexpr uint8_t kControlErrorOpcode = 0xEE;

// The backoff hint is advice from an UNTRUSTED peer: clients sleep on it, so
// an unclamped hint is a one-frame denial of service (hint_ms = 2^32-1
// would park a caller for ~49 days). Anything above this ceiling decodes
// clamped; servers never legitimately hint more than a few seconds.
inline constexpr uint32_t kMaxBackoffHintMs = 60'000;

inline std::vector<uint8_t> encode_control_error(ErrorCode code, uint32_t hint_ms) {
  std::vector<uint8_t> out(8);
  const uint32_t raw = static_cast<uint32_t>(code);
  std::memcpy(out.data(), &raw, sizeof(raw));
  std::memcpy(out.data() + 4, &hint_ms, sizeof(hint_ms));
  return out;
}

// Tail-tolerant on purpose (the append-only rule lets a newer server grow
// this frame), but strict about the error code: only the three pre-dispatch
// rejection codes may ride a control-error frame — anything else is a
// corrupt or forged frame and the caller treats the RPC as failed.
BTPU_NODISCARD inline bool decode_control_error(const std::vector<uint8_t>& payload,
                                                ErrorCode& code, uint32_t& hint_ms) {
  wire::WireReader r(payload.data(), payload.size());
  uint32_t raw = 0;
  uint32_t hint = 0;
  if (!r.u32(raw) || !r.u32(hint)) return false;
  code = static_cast<ErrorCode>(raw);
  hint_ms = hint > kMaxBackoffHintMs ? kMaxBackoffHintMs : hint;
  return code == ErrorCode::RETRY_LATER || code == ErrorCode::DEADLINE_EXCEEDED ||
         code == ErrorCode::RESOURCE_EXHAUSTED;
}

}  // namespace btpu::rpc
