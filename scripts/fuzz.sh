#!/usr/bin/env bash
# Hostile-input fuzz gate (make fuzz) over the five wire-decode surfaces
# (rpc_frame, control_error, tcp_header, record, wal_record —
# native/fuzz/fuzz_targets.h):
#
#   1. libFuzzer leg (clang only): one coverage-guided harness per target,
#      -fsanitize=fuzzer,address,undefined, seeded from the checked-in
#      corpus, BTPU_FUZZ_TIME seconds each (default 60). Skipped WITH A
#      NOTICE when clang/libFuzzer is unavailable — never silently.
#   2. Deterministic leg (always): the asan+ubsan corpus-replay binary
#      replays every checked-in input (including past crashers) and runs a
#      reproducible mutation sweep to >= BTPU_FUZZ_EXECS executions per
#      target (default 1,000,000). Same inputs every run, every box.
#
# New crashers: copy the reproducer into native/fuzz/corpus/<target>/ and
# commit it — the replay leg and the default-suite corpus test
# (test_wire_fuzz_corpus.cpp) then pin it forever. See docs/CORRECTNESS.md.
set -uo pipefail
cd "$(dirname "$0")/.."

EXECS="${BTPU_FUZZ_EXECS:-1000000}"
FTIME="${BTPU_FUZZ_TIME:-60}"
JOBS="$(nproc 2> /dev/null || echo 1)"
CORPUS=native/fuzz/corpus
fail=0

for t in rpc_frame control_error tcp_header record; do
  if [ -z "$(ls -A "$CORPUS/$t" 2> /dev/null)" ]; then
    echo "fuzz: FAIL — no checked-in corpus for $t (expected $CORPUS/$t/*)" >&2
    exit 1
  fi
done

# ---- libFuzzer leg (clang boxes) ------------------------------------------
CLANG="${CLANG:-}"
if [ -z "${CLANG}" ]; then
  for cand in clang++ clang++-21 clang++-20 clang++-19 clang++-18 clang++-17 \
              clang++-16 clang++-15 clang++-14; do
    if command -v "$cand" > /dev/null 2>&1; then CLANG="$cand"; break; fi
  done
fi
have_libfuzzer=0
if [ -n "${CLANG}" ]; then
  if echo 'extern "C" int LLVMFuzzerTestOneInput(const unsigned char*, unsigned long){return 0;}' \
     | "${CLANG}" -x c++ -fsanitize=fuzzer - -o /tmp/btpu_fuzz_probe 2> /dev/null; then
    have_libfuzzer=1
    rm -f /tmp/btpu_fuzz_probe
  fi
fi

if [ "$have_libfuzzer" = "1" ]; then
  echo "fuzz: libFuzzer leg (${CLANG}, ${FTIME}s per target)"
  # The record target calls into libbtpu.so (keystone record decoders), so
  # the library itself must be clang-built with asan+ubsan+coverage
  # (fuzzer-no-link): linking the gcc build would leave those decoders
  # uninstrumented — OOB reads invisible, no coverage feedback — and mixing
  # gcc-libasan with clang-compiler-rt in one process aborts at startup.
  # No -Werror here: the gcc sweep owns warning hygiene; a clang-only
  # warning must not take down the fuzz leg.
  mkdir -p build/fuzz
  if ! make -j"$JOBS" BUILD=build/fuzz/clang CXX="${CLANG}" \
       CXXFLAGS="-std=c++20 -O1 -g -fPIC -Inative/include -pthread \
                 -fsanitize=address,undefined,fuzzer-no-link" \
       LDFLAGS="-pthread -lrt -fsanitize=address,undefined,fuzzer-no-link" \
       build/fuzz/clang/libbtpu.so > /dev/null; then
    echo "fuzz: FAIL — could not build the clang-instrumented libbtpu.so" >&2
    exit 1
  fi
  for t in rpc_frame control_error tcp_header record wal_record; do
    bin="build/fuzz/fuzz_$t"
    if ! "${CLANG}" -std=c++20 -O1 -g -Inative/include \
         -fsanitize=fuzzer,address,undefined -DBTPU_FUZZ_TARGET="$t" \
         native/fuzz/fuzz_main_libfuzzer.cpp \
         -Lbuild/fuzz/clang -lbtpu -Wl,-rpath,"\$ORIGIN/clang" -pthread -lrt -o "$bin"; then
      echo "fuzz: FAIL — could not build $bin" >&2
      fail=1
      continue
    fi
    mkdir -p "build/fuzz/corpus_$t"  # findings dir (kept out of the seed set)
    if ! "$bin" -max_total_time="$FTIME" -print_final_stats=1 \
         "build/fuzz/corpus_$t" "$CORPUS/$t"; then
      echo "fuzz: FAIL — $t crashed; add the reproducer to $CORPUS/$t/ and fix" >&2
      fail=1
    fi
  done
else
  echo "fuzz: NOTICE — clang/libFuzzer not available; coverage-guided leg skipped" >&2
  echo "fuzz:          (the deterministic asan sweep below still runs)" >&2
fi

# ---- deterministic leg (every box) ----------------------------------------
echo "fuzz: deterministic corpus-replay + mutation sweep (asan+ubsan, ${EXECS} execs/target)"
if ! make -j"$JOBS" fuzz-replay; then
  echo "fuzz: FAIL — could not build the replay binary" >&2
  exit 1
fi
if ! build/asan/btpu_fuzz_replay --corpus "$CORPUS" --execs "$EXECS"; then
  echo "fuzz: FAIL — deterministic sweep found a crash/invariant violation" >&2
  fail=1
fi

exit "$fail"
