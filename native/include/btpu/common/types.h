// Core data model of the TPU-native distributed object store.
//
// Parity target: reference include/blackbird/common/types.h:50-513. The public
// contracts (put_start/put_complete lifecycle structs, placements carrying
// {endpoint, remote_addr, rkey}, batch request/response pairs) match the
// reference so a Blackbird user finds the same API surface. The internals are
// redesigned TPU-first:
//   * transports are pluggable — a generic RemoteDescriptor replaces the four
//     hard-coded ucx_* fields on MemoryPool (reference types.h:471-475);
//   * StorageClass puts TPU HBM where the reference put (broken) RAM_GPU
//     (reference worker_service.cpp:196 flags RAM_GPU as broken);
//   * every pool carries TopoCoord {slice, host, chip} so placement can be
//     ICI/DCN-aware instead of node-string-only (reference
//     range_allocator.cpp:436-438 only knows node ids).
#pragma once

#include <cstdint>
#include <map>
#include <optional>
#include <string>
#include <unordered_map>
#include <variant>
#include <vector>

#include "btpu/common/result.h"

namespace btpu {

using ObjectKey = std::string;
using MemoryPoolId = std::string;
using NodeId = std::string;
using Version = uint64_t;
using ViewVersionId = int64_t;
using LeaseId = int64_t;

// FNV-1a 64-bit: THE key-hash family for every lock-striped map keyed by
// object key (keystone object shards, allocator allocation shards). One
// definition so the "same family" relationship those maps document is
// enforced, and stable across processes/boots by construction — persisted
// records must re-shard identically, and no seed may leak layout.
inline uint64_t fnv1a64(const std::string& bytes) noexcept {
  uint64_t h = 1469598103934665603ull;
  for (const char c : bytes) {
    h ^= static_cast<uint8_t>(c);
    h *= 1099511628211ull;
  }
  return h;
}

// -------------------------------------------------------------------------
// Constants (reference types.h:69-74)
// -------------------------------------------------------------------------
inline constexpr const char* kDefaultClusterId = "btpu_cluster";
inline constexpr double kDefaultHighWatermark = 0.9;
inline constexpr int64_t kDefaultClientTtlSec = 10;
inline constexpr size_t kDefaultReplicationFactor = 3;
inline constexpr size_t kDefaultMaxWorkersPerCopy = 4;

// -------------------------------------------------------------------------
// Storage tiers (reference types.h:82-93, with RAM_GPU -> HBM_TPU)
// -------------------------------------------------------------------------
enum class StorageClass : uint32_t {
  STORAGE_UNSPECIFIED = 0,
  RAM_CPU = 1,   // host DRAM
  HBM_TPU = 2,   // TPU on-chip HBM — top tier (replaces reference RAM_GPU)
  NVME = 3,
  SSD = 4,
  HDD = 5,
  CXL_MEMORY = 6,
  CXL_TYPE2_DEVICE = 7,
  CUSTOM = 999,
};

std::string_view storage_class_name(StorageClass c) noexcept;

// Tiers whose bytes survive the owning process (file-backed: mmap and
// io_uring disk backends). Memory tiers — DRAM, HBM, CXL without a backing
// path — die with the worker.
inline bool storage_class_is_persistent(StorageClass c) noexcept {
  return c == StorageClass::NVME || c == StorageClass::SSD || c == StorageClass::HDD;
}
std::optional<StorageClass> storage_class_from_name(std::string_view name) noexcept;

// Tier height for the eviction/demotion ladder: lower rank = faster tier.
// HBM_TPU(0) > RAM_CPU(1) > CXL_MEMORY(2) > CXL_TYPE2(3) > NVME(4) > SSD(5)
// > HDD(6); CUSTOM/UNSPECIFIED sort last.
int tier_rank(StorageClass c) noexcept;

// -------------------------------------------------------------------------
// Transports. The reference hard-codes UCX in four places; here every shard
// placement names the transport a client must use to reach its bytes.
// -------------------------------------------------------------------------
enum class TransportKind : uint32_t {
  TRANSPORT_UNSPECIFIED = 0,
  LOCAL = 1,  // same-process memcpy (hermetic tests, embedded cluster)
  SHM = 2,    // same-host shared memory (TPU-VM-local zero copy)
  TCP = 3,    // sockets — dev fallback + DCN inter-slice path
  ICI = 4,    // intra-slice one-sided DMA (device mesh collectives / libtpu)
  HBM = 5,    // on-device HBM regions fronted by the HBM provider
};

std::string_view transport_kind_name(TransportKind k) noexcept;
std::optional<TransportKind> transport_kind_from_name(std::string_view name) noexcept;

// Where a worker sits in the TPU pod: used for ICI-vs-DCN placement decisions.
// slice_id: which TPU slice; host_id: which TPU VM host within the slice;
// chip_id: device ordinal on that host (-1 = host memory, not chip-attached).
struct TopoCoord {
  int32_t slice_id{0};
  int32_t host_id{0};
  int32_t chip_id{-1};

  bool same_host(const TopoCoord& o) const noexcept {
    return slice_id == o.slice_id && host_id == o.host_id;
  }
  bool same_slice(const TopoCoord& o) const noexcept { return slice_id == o.slice_id; }

  bool operator==(const TopoCoord&) const = default;
};

// How a client reaches a worker's registered region: the transport to dial,
// the endpoint to dial it at, and the key that authorizes one-sided access.
// Parity: reference UcxEndpoint (types.h:97-102) + the ucx_* advertisement
// fields on MemoryPool (types.h:471-475), folded into one descriptor.
struct RemoteDescriptor {
  TransportKind transport{TransportKind::TRANSPORT_UNSPECIFIED};
  std::string endpoint;      // "host:port" (tcp), shm name (shm), mesh axis addr (ici)
  uint64_t remote_base{0};   // base remote address of the registered region
  std::string rkey_hex;      // packed region key, hex-encoded
  // Device-fabric endpoint serving this region ("" = none): rides into
  // every ShardPlacement cut from the pool, so a runtime-owning CLIENT can
  // fabric-pull/offer shards directly (jax.experimental.transfer) instead
  // of staging through the worker's host lane. Wire-append-only.
  std::string fabric_addr;
  // Same-host one-sided lane ("" = none): "bootid:pid:starttime:base:len"
  // (hex base/len) naming the serving process and the region's virtual
  // base. A client on the SAME boot reads/writes the bytes itself with
  // process_vm_readv/writev — one kernel copy, zero worker CPU, no socket
  // — the reference's ucp_get_nbx one-sided principle for host-addressable
  // tiers across processes (pvm_transport.cpp). Clients elsewhere (or on a
  // stack where the syscall is denied) fall back to the primary transport
  // above. Wire-append-only.
  std::string pvm_endpoint;
  // Raw-framing dialect of the endpoint's data plane (tcp: the packed
  // DataRequestHeader/StagedFrame layout, which has NO length prefix and so
  // no tail tolerance). Advertised at region registration, checked by the
  // client before the first byte goes out: a mismatched pair fails fast
  // with REMOTE_ENDPOINT_ERROR instead of desyncing the byte stream.
  // 0 = pre-versioned peer (or a transport whose framing is self-describing
  // and never checks). Wire-append-only.
  uint32_t data_wire_version{0};

  bool operator==(const RemoteDescriptor&) const = default;
};

// -------------------------------------------------------------------------
// Shard locations (reference types.h:107-136)
// -------------------------------------------------------------------------
struct MemoryLocation {
  uint64_t remote_addr{0};
  uint64_t rkey{0};  // 64-bit; the reference truncates to u32 (types.h:109)
  uint64_t size{0};
  // Pool-sanitizer generation stamp (btpu/common/poolsan.h), minted when the
  // extent was carved and validated on every resolve in -DBTPU_POOLSAN
  // trees: a descriptor cached across a remove/GC/evict/demote is convicted
  // STALE_EXTENT at the access site instead of served as a neighbor
  // object's bytes. 0 = unstamped (release builds, pre-poolsan records) —
  // bounds + shadow-state checks still apply, generation comparison is
  // skipped. Wire-append-only.
  uint64_t extent_gen{0};
  bool operator==(const MemoryLocation&) const = default;
};

struct FileLocation {
  std::string file_path;
  uint64_t file_offset{0};
  bool operator==(const FileLocation&) const = default;
};

// On-device (TPU HBM) region — generalizes the reference's CxlMemoryLocation
// (types.h:124-130) to any device-attached memory with region ids.
struct DeviceLocation {
  std::string device_id;   // e.g. "tpu:0"
  uint64_t region_id{0};
  uint64_t offset{0};
  uint64_t size{0};
  bool operator==(const DeviceLocation&) const = default;
};

using LocationDetail = std::variant<MemoryLocation, FileLocation, DeviceLocation>;

// -------------------------------------------------------------------------
// Placements (reference types.h:139-157)
// -------------------------------------------------------------------------
struct ShardPlacement {
  MemoryPoolId pool_id;
  NodeId worker_id;
  RemoteDescriptor remote;
  StorageClass storage_class{StorageClass::STORAGE_UNSPECIFIED};
  uint64_t length{0};
  LocationDetail location{MemoryLocation{}};
  bool operator==(const ShardPlacement&) const = default;
};

struct CopyPlacement {
  uint32_t copy_index{0};
  std::vector<ShardPlacement> shards;
  // Erasure geometry; 0,0 = plain replicated/striped copy. When
  // ec_data_shards = k > 0: the first k shards hold the object bytes
  // (k equal shards of ceil(size/k), the last zero-padded), the remaining
  // ec_parity_shards are Reed-Solomon parity (btpu/ec/rs.h), and
  // ec_object_size is the logical size (shard lengths sum past it by the
  // padding + parity).
  uint32_t ec_data_shards{0};
  uint32_t ec_parity_shards{0};
  uint64_t ec_object_size{0};
  // CRC32C of the object bytes, stamped by the writing client at put_start
  // (0 = unknown). Readers verify after assembling the object; a mismatch
  // is treated as copy loss (failover / parity reconstruction).
  uint32_t content_crc{0};
  // Per-shard CRC32C, parallel to `shards` (empty = not stamped — records
  // from pre-shard-CRC builds). The object CRC detects corruption; these
  // localize it to a shard, which is what lets EC repair reconstruct
  // multiple corrupt shards and scrub name the corrupt worker/pool.
  std::vector<uint32_t> shard_crcs;
  // Inline tier: small objects' bytes live HERE, in the keystone's object
  // map, instead of on worker pools (`shards` is then empty). The durable
  // record carries them (restart + HA mirror come for free), get_workers
  // returns them (a first verified read is ONE control RTT, no data-plane
  // hop), and put_inline stores them in one RPC. Wire-append-only: older
  // peers decode this struct fine and see a shardless copy.
  std::string inline_data;
  // Client object-cache coherence stamps (btpu/cache/object_cache.h),
  // filled by the keystone on READ replies only (get_workers /
  // batch_get_workers — never persisted): cache_version is the object's
  // current epoch (bumped on every placement/content mutation), cache_gen
  // the keystone incarnation that minted it (fresh per process/promotion,
  // so re-minted epochs after a restart can never collide with cached
  // ones), and cache_lease_ms how long a client may serve the bytes from
  // its cache before revalidating (KeystoneConfig::cache_lease_ms; 0 = the
  // server grants no caching). Wire-append-only: a pre-cache server leaves
  // all three 0 and clients simply never cache.
  uint64_t cache_version{0};
  uint64_t cache_gen{0};
  uint32_t cache_lease_ms{0};
  size_t shards_size() const noexcept { return shards.size(); }
};

// Logical object bytes held by one copy (EC-aware; replicated copies are
// the sum of their shard lengths; inline copies carry the bytes themselves).
inline uint64_t copy_logical_size(const CopyPlacement& c) {
  if (!c.inline_data.empty()) return c.inline_data.size();
  if (c.ec_data_shards > 0) return c.ec_object_size;
  uint64_t total = 0;
  for (const auto& s : c.shards) total += s.length;
  return total;
}

// -------------------------------------------------------------------------
// Placement policy per object (reference WorkerConfig, types.h:161-189)
// -------------------------------------------------------------------------
struct WorkerConfig {
  size_t replication_factor{kDefaultReplicationFactor};
  size_t max_workers_per_copy{kDefaultMaxWorkersPerCopy};
  bool enable_soft_pin{false};
  std::string preferred_node;
  std::vector<StorageClass> preferred_classes;
  uint64_t ttl_ms{30ull * 60ull * 1000ull};
  bool enable_locality_awareness{true};
  bool prefer_contiguous{false};
  // Striping floor: never split so wide that shards drop below this. The
  // default keeps latency-bound small objects (the <50 us p99 64 KiB north
  // star, BASELINE.md) on a SINGLE shard — one wire round trip — while
  // bandwidth-bound objects >=2x this still stripe. Lower it explicitly for
  // workloads that want tiny wide stripes.
  size_t min_shard_size{256 * 1024};
  // TPU extension: when set, placement prefers pools on this slice and only
  // spills across slices (DCN) when the slice cannot hold the object.
  int32_t preferred_slice{-1};
  // Mesh-aware extension of the slice hint: when set (with preferred_slice),
  // placement prefers pools on this HOST within the slice — the shard-local
  // lane of a pod checkpoint writes each shard to its own host's worker,
  // zero cross-host data-plane bytes when shardings match. Ranked above the
  // slice hint, spills to same-slice then anywhere when the host is full.
  // Without preferred_slice the host id alone is meaningless (host ids are
  // per-slice coordinates) and the hint is ignored.
  int32_t preferred_host{-1};
  // Erasure coding (no reference counterpart — it only replicates): when
  // ec_parity_shards > 0 the object is stored as ONE coded copy of
  // ec_data_shards data + ec_parity_shards parity shards (any
  // ec_parity_shards losses tolerated at (k+m)/k storage overhead);
  // replication_factor is ignored.
  size_t ec_data_shards{0};
  size_t ec_parity_shards{0};
};

struct ClusterStats {
  uint64_t total_workers{0};
  uint64_t total_memory_pools{0};
  uint64_t total_objects{0};
  uint64_t total_capacity{0};
  uint64_t used_capacity{0};
  double avg_utilization{0.0};
  // Bytes resident in the keystone's inline tier (not pool capacity —
  // inline objects live in the object map; see KeystoneConfig).
  uint64_t inline_bytes{0};
};

// -------------------------------------------------------------------------
// Memory pool registry entry (reference types.h:464-493)
// -------------------------------------------------------------------------
struct MemoryPool {
  MemoryPoolId id;
  NodeId node_id;
  uint64_t base_addr{0};
  uint64_t size{0};
  uint64_t used{0};
  StorageClass storage_class{StorageClass::STORAGE_UNSPECIFIED};
  RemoteDescriptor remote;
  TopoCoord topo;
  // Placement offsets in this pool are rounded up to this boundary
  // (0/1 = none). HBM pools advertise the provider chunk size so shards hit
  // the whole-chunk fast path (no read-modify-write on device).
  uint64_t alignment{0};
  // Cross-process device fabric endpoint (hbm_provider v4; "" = none): when
  // BOTH ends of a keystone-driven move advertise one, the bytes ride the
  // device fabric (jax.experimental.transfer — chip fabric on TPU) instead
  // of the staged host lane.
  std::string fabric_addr;

  double utilization() const noexcept {
    return size > 0 ? static_cast<double>(used) / static_cast<double>(size) : 0.0;
  }
  uint64_t available() const noexcept { return size > used ? size - used : 0; }
};

// -------------------------------------------------------------------------
// RPC wire structs, 1:1 with keystone methods (reference types.h:217-407).
// Batch results use the Result<T> one-of encoding.
// -------------------------------------------------------------------------
struct ObjectExistsRequest { ObjectKey key; };
struct ObjectExistsResponse { bool exists{false}; ErrorCode error_code{ErrorCode::OK}; };

struct GetWorkersRequest { ObjectKey key; };
struct GetWorkersResponse { std::vector<CopyPlacement> copies; ErrorCode error_code{ErrorCode::OK}; };

struct PutStartRequest {
  ObjectKey key;
  uint64_t data_size{0};
  WorkerConfig config;
  uint32_t content_crc{0};  // CRC32C of the bytes about to be written
};
struct PutStartResponse { std::vector<CopyPlacement> copies; ErrorCode error_code{ErrorCode::OK}; };

// Per-shard CRC32C stamps for one copy, reported by the writing client at
// put_complete (shard boundaries are chosen by placement, so the client can
// only compute these AFTER put_start). For coded copies the vector covers
// all k+m shards, parity included.
struct CopyShardCrcs {
  uint32_t copy_index{0};
  std::vector<uint32_t> crcs;
};

struct PutCompleteRequest {
  ObjectKey key;
  std::vector<CopyShardCrcs> shard_crcs;  // may be empty (older clients)
  // Whole-object CRC32C, carried here (not put_start) so clients can fuse
  // the hash into the transfer itself and fold shard stamps into it —
  // nothing reads it while the object is still kPending. 0 = keep whatever
  // put_start stamped (older clients hash up front and send it there).
  uint32_t content_crc{0};
};
struct PutCompleteResponse { ErrorCode error_code{ErrorCode::OK}; };

struct PutCancelRequest { ObjectKey key; };
struct PutCancelResponse { ErrorCode error_code{ErrorCode::OK}; };

struct RemoveObjectRequest { ObjectKey key; };
struct RemoveObjectResponse { ErrorCode error_code{ErrorCode::OK}; };

struct RemoveAllObjectsRequest {};
struct RemoveAllObjectsResponse { uint64_t objects_removed{0}; ErrorCode error_code{ErrorCode::OK}; };

struct DrainWorkerRequest { NodeId worker_id; };
struct DrainWorkerResponse { uint64_t copies_migrated{0}; ErrorCode error_code{ErrorCode::OK}; };

struct GetClusterStatsRequest {};
struct GetClusterStatsResponse { ClusterStats stats; ErrorCode error_code{ErrorCode::OK}; };

struct GetViewVersionRequest {};
struct GetViewVersionResponse { ViewVersionId view_version{0}; ErrorCode error_code{ErrorCode::OK}; };

// Listing API (no reference counterpart — the reference object map is
// enumerable only via logs; checkpoint/driver tooling needs prefix listing
// to discover keys, keystone_service.h:84-322 offers none).
struct ObjectSummary {
  ObjectKey key;
  uint64_t size{0};
  uint32_t complete_copies{0};
  bool soft_pin{false};
};
struct ListObjectsRequest { std::string prefix; uint64_t limit{0}; };  // 0 = unlimited
struct ListObjectsResponse {
  std::vector<ObjectSummary> objects;
  ErrorCode error_code{ErrorCode::OK};
};

// Pool-registry listing (no reference counterpart): the placement plane's
// topology discovery. A mesh-aware client lists pools once, learns each
// worker's TopoCoord (slice/host/chip) and capacity, and derives its own
// host-local placement hints from them — no side-channel config file.
struct ListPoolsRequest {};
struct ListPoolsResponse {
  std::vector<MemoryPool> pools;
  ErrorCode error_code{ErrorCode::OK};
};

struct BatchObjectExistsRequest { std::vector<ObjectKey> keys; };
struct BatchObjectExistsResponse {
  std::vector<Result<bool>> results;
  ErrorCode error_code{ErrorCode::OK};
};

struct BatchGetWorkersRequest { std::vector<ObjectKey> keys; };
struct BatchGetWorkersResponse {
  std::vector<Result<std::vector<CopyPlacement>>> results;
  ErrorCode error_code{ErrorCode::OK};
};

struct BatchPutStartItem {
  ObjectKey key;
  uint64_t data_size{0};
  WorkerConfig config;
  uint32_t content_crc{0};
};
struct BatchPutStartRequest { std::vector<BatchPutStartItem> requests; };
struct BatchPutStartResponse {
  std::vector<Result<std::vector<CopyPlacement>>> results;
  ErrorCode error_code{ErrorCode::OK};
};

struct BatchPutCompleteRequest {
  std::vector<ObjectKey> keys;
  // Parallel to `keys`; empty, or one (possibly empty) entry per key.
  std::vector<std::vector<CopyShardCrcs>> shard_crcs;
  // Parallel to `keys`; empty, or one entry per key (0 = keep put_start's
  // stamp). See PutCompleteRequest::content_crc.
  std::vector<uint32_t> content_crcs;
};
struct BatchPutCompleteResponse { std::vector<ErrorCode> results; ErrorCode error_code{ErrorCode::OK}; };

struct BatchPutCancelRequest { std::vector<ObjectKey> keys; };
struct BatchPutCancelResponse { std::vector<ErrorCode> results; ErrorCode error_code{ErrorCode::OK}; };

// Pooled small-put slots (no reference counterpart; the reference pays two
// control RTTs per put, blackbird_client.cpp:87-117). put_start_pooled
// pre-allocates `count` anonymous PENDING objects of one (size, config)
// class under internal "\x01slot/<tag>/<seq>" keys; a later put writes a
// slot's placements and commits it AS the final key in ONE control round
// trip (put_commit_slot), which can piggyback a refill in the same RTT.
// Unused slots are reclaimed like any abandoned pending put, on the
// shorter KeystoneConfig::slot_ttl_sec deadline.
struct PutSlot {
  ObjectKey slot_key;
  std::vector<CopyPlacement> copies;
};
struct PutStartPooledRequest {
  uint64_t data_size{0};
  WorkerConfig config;
  uint32_t count{1};
  std::string client_tag;  // namespaces slot keys per client session
};
// error_code leads (unlike the older responses) so the NOT_IMPLEMENTED
// single-field frame an old server answers unknown opcodes with decodes
// cleanly and the client can fall back to the two-RTT path.
struct PutStartPooledResponse {
  ErrorCode error_code{ErrorCode::OK};
  std::vector<PutSlot> slots;  // may be fewer than requested
};
struct PutCommitSlotRequest {
  ObjectKey slot_key;
  ObjectKey key;  // final user-visible key
  uint32_t content_crc{0};
  std::vector<CopyShardCrcs> shard_crcs;
  // Piggybacked replacement-slot grant: the same RTT that commits this put
  // pre-allocates the next slots of the class (data_size, config, tag are
  // repeated because the commit must not depend on server-side lookups).
  uint32_t refill_count{0};
  uint64_t data_size{0};
  WorkerConfig config;
  std::string client_tag;
};
struct PutCommitSlotResponse {
  ErrorCode error_code{ErrorCode::OK};  // commit outcome (see request note)
  std::vector<PutSlot> slots;           // refills; best-effort, may be empty
};

// Inline-tier put: one control RTT stores a small object's bytes in the
// keystone's object map (see KeystoneConfig::inline_max_bytes). A server
// that refuses (disabled, oversized, budget spent, or a pre-inline build
// answering an unknown opcode) returns NOT_IMPLEMENTED in a single-field
// frame and the client falls back to the placed path — same convention as
// the pooled-slot RPCs.
struct PutInlineRequest {
  ObjectKey key;
  WorkerConfig config;      // ttl / soft-pin policy (placement fields unused)
  uint32_t content_crc{0};  // CRC32C of `data` (0 = unstamped)
  std::string data;
};
struct PutInlineResponse { ErrorCode error_code{ErrorCode::OK}; };

// Ping doubles as the protocol-version handshake: each side sends the
// highest wire-protocol version it speaks (rpc.h kProtocolVersion). A peer
// that predates the handshake leaves the field 0.
struct PingRequest { uint32_t proto_version{0}; };
struct PingResponse { ViewVersionId view_version{0}; uint32_t proto_version{0}; };

// -------------------------------------------------------------------------
// Service configs (reference KeystoneConfig types.h:410-445,
// ClientConfig :448-461; worker config lives in worker/worker_service.h)
// -------------------------------------------------------------------------
struct KeystoneConfig {
  std::string cluster_id{kDefaultClusterId};
  std::string coord_endpoints;            // coordination service endpoints ("" = in-process)
  std::string listen_address{"0.0.0.0:9090"};
  std::string http_metrics_port{"9091"};
  std::string service_id;                 // auto-generated when empty

  bool enable_gc{true};
  bool enable_ha{false};
  double eviction_ratio{0.1};
  double high_watermark{kDefaultHighWatermark};
  int64_t client_ttl_sec{kDefaultClientTtlSec};
  int64_t worker_heartbeat_ttl_sec{30};

  int64_t service_registration_ttl_sec{60};
  int64_t service_refresh_interval_sec{30};
  int64_t gc_interval_sec{30};
  int64_t health_check_interval_sec{10};
  // Reclaim puts stuck in the pending state (client crashed between
  // put_start and put_complete/cancel) after this long; 0 disables. Plays
  // the role of the reference's 10-min backend reservation-token expiry
  // (ram_backend.cpp:69) at the control plane, where the allocation
  // actually lives here.
  int64_t pending_put_timeout_sec{900};
  // Unused pooled put slots (put_start_pooled) are reclaimed after this
  // much idle time — much shorter than pending_put_timeout_sec because a
  // slot holds reserved capacity with no writer attached until a put picks
  // it up; a client that loses its slot transparently falls back to the
  // two-RTT put path. 0 disables slot granting entirely.
  int64_t slot_ttl_sec{60};

  int32_t max_replicas{3};
  int32_t default_replicas{1};

  // Background integrity scrub (leader only): every scrub_interval_sec the
  // health loop verified-reads up to scrub_objects_per_pass objects' shards
  // against their writer-stamped CRC32C, healing corrupt replicated shards
  // byte-identically from a healthy copy and corrupt coded shards through
  // parity reconstruction. 0 disables. This server-side floor is what makes
  // raw (verify=false) client reads an honest latency trade. The reference
  // has no integrity checking at all.
  int64_t scrub_interval_sec{0};
  uint32_t scrub_objects_per_pass{16};

  // Client object-cache lease (btpu/cache): get_workers replies grant
  // readers the right to serve the returned object version from a local
  // cache for this long without revalidation. Invalidations fan out over
  // the coordinator watch lane ("cacheinval" topic) and usually land well
  // inside the lease; the lease is the HARD staleness bound when that lane
  // is down or severed. 0 disables granting (clients fall back to uncached
  // reads). Short by design: a lease only saves a control RTT per hot
  // object per TTL, while a long lease stretches the worst-case staleness
  // window a severed watch stream can produce.
  uint32_t cache_lease_ms{2000};

  // Inline tier: objects up to inline_max_bytes are stored IN the keystone's
  // object map (durable record + HA mirror carry the bytes) instead of on
  // worker pools — put_inline is one control RTT, and get_workers returns
  // the bytes so a first verified read never touches the data plane. The
  // keystone-wide budget caps resident inline bytes; past it (or past
  // inline_max_bytes) clients transparently fall back to the placed path.
  // 0 disables granting (clients fall back).
  uint64_t inline_max_bytes{4096};
  uint64_t inline_total_bytes{256ull << 20};

  // TPU extensions
  bool enable_repair{true};       // re-replicate objects after worker death
  bool tier_aware_eviction{true}; // evict per-tier, not on global average
  // Under tier pressure, move LRU objects down the tier ladder
  // (HBM -> DRAM -> CXL -> NVMe/SSD/HDD) over the data plane instead of
  // deleting them; deletion remains the fallback when no lower tier fits.
  // (The reference only deletes, keystone_service.cpp:530-584.)
  bool enable_tier_demotion{true};
  // Persist object metadata through the coordination service so a keystone
  // restart recovers the object map (the reference forgets all objects on
  // restart, SURVEY §5 checkpoint/resume). No-op without a coordinator.
  bool persist_objects{true};

  // RPC admission control (btpu/common/admission.h): at most
  // rpc_max_inflight non-control requests are serviced concurrently, at
  // most rpc_max_queue more wait (adaptive LIFO — the oldest waiter is shed
  // with RETRY_LATER + rpc_shed_backoff_hint_ms when the queue overflows).
  // Control-plane ops (ping, view version, cluster stats, drain) bypass the
  // gate so operators can observe an overloaded keystone. 0 = auto
  // (BTPU_RPC_MAX_INFLIGHT / BTPU_RPC_MAX_QUEUE env overrides, else
  // 4 x metadata shard count inflight, 4 x that queued).
  uint32_t rpc_max_inflight{0};
  uint32_t rpc_max_queue{0};
  uint32_t rpc_shed_backoff_hint_ms{50};

  // Object-map shard count (lock striping): single-key metadata ops lock
  // exactly one shard, so control-plane throughput scales with cores
  // instead of serializing on one map-wide mutex. 0 = auto: the
  // BTPU_KEYSTONE_SHARDS env var when set, else min(hw_concurrency, 16).
  // Resolved once at service construction and clamped to [1, 256];
  // KeystoneService::metadata_shard_count() reports the value in effect.
  uint32_t metadata_shards{0};

  // Loads a YAML config file (subset grammar, see config.h). Throws
  // std::runtime_error on parse/validation failure like the reference
  // (src/common/types.cpp:76-85).
  static KeystoneConfig from_yaml(const std::string& file_path);
  ErrorCode validate() const;
};

struct ClientConfig {
  std::string node_id;
  std::string keystone_address;
  std::string local_address{"0.0.0.0:0"};
  uint64_t memory_pool_size{1ull << 30};
  std::string storage_path;
};

}  // namespace btpu
