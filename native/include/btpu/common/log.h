// Minimal leveled logging (role parity with the reference's glog usage —
// LOG(INFO/WARNING/ERROR) + VLOG(1/2), e.g. reference range_allocator.cpp:32,60).
// Level via env BTPU_LOG = error|warn|info|debug|trace (default warn).
#pragma once

#include <chrono>
#include <cstdlib>
#include <cstring>
#include <sstream>
#include <string>

#include "btpu/common/env.h"
#include "btpu/common/error.h"

namespace btpu::log {

enum class Level : int { kError = 0, kWarn = 1, kInfo = 2, kDebug = 3, kTrace = 4 };

inline Level global_level() {
  static Level lvl = [] {
    const char* e = ::btpu::env_str("BTPU_LOG");
    if (!e) return Level::kWarn;
    if (!std::strcmp(e, "error")) return Level::kError;
    if (!std::strcmp(e, "warn")) return Level::kWarn;
    if (!std::strcmp(e, "info")) return Level::kInfo;
    if (!std::strcmp(e, "debug")) return Level::kDebug;
    if (!std::strcmp(e, "trace")) return Level::kTrace;
    return Level::kWarn;
  }();
  return lvl;
}

inline bool enabled(Level l) { return static_cast<int>(l) <= static_cast<int>(global_level()); }

void emit(Level l, const char* file, int line, const std::string& msg);

class Line {
 public:
  Line(Level l, const char* file, int line) : level_(l), file_(file), line_(line) {}
  ~Line() { emit(level_, file_, line_, ss_.str()); }
  template <typename T>
  Line& operator<<(const T& v) {
    ss_ << v;
    return *this;
  }

 private:
  Level level_;
  const char* file_;
  int line_;
  std::ostringstream ss_;
};

struct Sink {  // swallows the stream when the level is disabled
  template <typename T>
  Sink& operator<<(const T&) { return *this; }
};

}  // namespace btpu::log

#define BTPU_LOG(lvl)                                        \
  if (!::btpu::log::enabled(::btpu::log::Level::lvl)) {      \
  } else                                                     \
    ::btpu::log::Line(::btpu::log::Level::lvl, __FILE__, __LINE__)

#define LOG_ERROR BTPU_LOG(kError)
#define LOG_WARN BTPU_LOG(kWarn)
#define LOG_INFO BTPU_LOG(kInfo)
#define LOG_DEBUG BTPU_LOG(kDebug)
#define LOG_TRACE BTPU_LOG(kTrace)

namespace btpu {

// Error sink for cleanup / best-effort paths. ErrorCode is a [[nodiscard]]
// type, so every tolerated failure must say so explicitly — and a bare
// (void) cast hides real failures (a leaked range, a stale durable record)
// forever. This logs any outcome other than OK (or the one explicitly
// tolerated code, e.g. NOT_FOUND on an idempotent delete) and keeps the
// tolerance greppable. Hot paths never call this with a failure in steady
// state, so the log cost is zero there.
inline void warn_if_error(ErrorCode ec, const char* what,
                          ErrorCode tolerated = ErrorCode::OK) {
  if (ec != ErrorCode::OK && ec != tolerated) {
    LOG_WARN << what << " failed: " << to_string(ec) << " (tolerated; best-effort path)";
  }
}

}  // namespace btpu
