#!/usr/bin/env bash
# CI entrypoint: the one command a CI job runs.
#
# Two differences from a developer's `make check`:
#   - BTPU_REQUIRE_CLANG=1 / BTPU_REQUIRE_MYPY=1 / BTPU_REQUIRE_RUFF=1:
#     CI images are expected to ship clang, mypy, and ruff, so the
#     tool-absent SKIPs a laptop tolerates (TSA sweep, strict type check,
#     pyflakes-class sweep, capi libclang refinement) become hard failures
#     here — the lint gates cannot silently degrade in CI.
#   - a bounded `make fuzz` leg (BTPU_FUZZ_EXECS/BTPU_FUZZ_TIME below):
#     enough executions to catch a decoder regression on every push; the
#     long exploratory runs stay manual/nightly (`make fuzz` with defaults).
#
# Exit code is the OR of both legs; each leg's scoreboard prints regardless.
set -uo pipefail
cd "$(dirname "$0")/.."

overall=0

echo "==================================================================="
echo "== ci: make check (BTPU_REQUIRE_CLANG=1, BTPU_REQUIRE_MYPY=1, BTPU_REQUIRE_RUFF=1)"
echo "==================================================================="
if ! BTPU_REQUIRE_CLANG=1 BTPU_REQUIRE_MYPY=1 BTPU_REQUIRE_RUFF=1 make check; then
  overall=1
fi

echo "==================================================================="
echo "== ci: make fuzz (smoke: bounded execs/time)"
echo "==================================================================="
if ! BTPU_FUZZ_EXECS="${BTPU_FUZZ_EXECS:-200000}" \
     BTPU_FUZZ_TIME="${BTPU_FUZZ_TIME:-30}" make fuzz; then
  overall=1
fi

if [ "$overall" -ne 0 ]; then
  echo "ci: FAIL (see legs above)" >&2
fi
exit "$overall"
