// io_uring submission-queue event loop for the TCP data plane.
//
// One loop per worker core multiplexes thousands of connections: accepts,
// request-header reads, pool-direct sends (writev straight off registered
// pool pages — zero worker-side staging copies), and disk reads submitted
// on the SAME ring as the network ops (the backend's io_uring file lane,
// see iouring_disk_backend.cpp), replacing the thread-per-connection serve
// loop. The wire protocol is byte-identical to the fallback server
// (data_wire.h packed headers, frozen by wire_layout_check.h + the golden
// table) — a client cannot tell which engine answered, and the staged shm
// lane keeps working unchanged on top of it.
//
// Availability is a RUNTIME question (sandboxed kernels refuse
// io_uring_setup; BTPU_IOURING_NET=0 — or its legacy spelling
// BTPU_FORCE_NO_URING=1 — refuses it on purpose, =1 requires it, auto
// probes):
// UringDataPlane::create returns null and the TCP server falls back to the
// thread-per-connection loop. Both engines share RegionTable and the
// admission gate, so registration and overload behavior cannot diverge.
//
// Ownership model (docs/CORRECTNESS.md §8): every Conn is owned by exactly
// one loop thread and touched by no other, so per-connection state needs no
// locks at all. The only cross-thread edges are (a) the RegionTable mutex,
// (b) the AdmissionGate's internal mutex (try_enter/release), (c) the exec
// pool's task queue + per-loop completion queue (each a Mutex + eventfd
// wake), and (d) the stop flag. Blocking region callbacks (virtual-region
// reads/writes without a direct fd, fabric offer/pull) run on the exec
// pool, never on a loop thread.
#pragma once

#include <cstdint>
#include <functional>
#include <memory>
#include <unordered_map>

#include <string>

#include "btpu/common/admission.h"
#include "btpu/common/pool_span.h"
#include "btpu/common/stripe_counter.h"
#include "btpu/common/thread_annotations.h"
#include "btpu/net/net.h"
#include "btpu/transport/transport.h"

namespace btpu::transport {

// One registered region, shared verbatim between the uring engine and the
// thread-per-connection fallback. base != nullptr: flat memory (pool pages,
// served zero-copy). base == nullptr: callback-backed (virtual) region;
// direct_fd >= 0 additionally exposes the backing file for ring-submitted
// reads (region offset == file offset — the disk backends are flat files).
struct Region {
  uint8_t* base{nullptr};
  uint64_t len{0};
  uint64_t remote_base{0};
  RegionReadFn read_fn;
  RegionWriteFn write_fn;
  RegionOfferFn offer_fn;  // device-fabric hooks (attach_fabric); may be null
  RegionPullFn pull_fn;
  int direct_fd{-1};        // backing file for ring-unified reads; -1 = none
  bool direct_odirect{false};  // O_DIRECT file: 512-align ring reads
  std::string tag;  // pool id at registration — the poolsan shadow lookup key
};

// Region registry shared by whichever serve engine is running. The lock is
// per-lookup (a few map ops); resolved callback copies are used outside it.
struct RegionTable {
  Mutex mutex;
  std::unordered_map<uint64_t, Region> map BTPU_GUARDED_BY(mutex);

  // Resolves (addr, rkey, len, extent_gen) through poolspan::resolve — the
  // one sanctioned base+offset chokepoint. Returns OK with either `target`
  // pointing into a flat region (bounds- and shadow-proved) or `target` ==
  // nullptr and `region_out` carrying the callbacks (+ optional direct fd);
  // MEMORY_ACCESS_ERROR on a bounds/rkey/red-zone violation; STALE_EXTENT
  // on a poolsan conviction (stale generation, quarantined extent) — the
  // engine answers that status verbatim so the client learns WHY.
  BTPU_NODISCARD ErrorCode resolve(uint64_t addr, uint64_t rkey, uint64_t len,
                                   uint64_t extent_gen, poolspan::Access access,
                                   uint64_t trace_id, uint8_t*& target, Region& region_out,
                                   uint64_t& offset) {
    MutexLock lock(mutex);
    auto it = map.find(rkey);
    if (it == map.end()) return ErrorCode::MEMORY_ACCESS_ERROR;
    const Region& region = it->second;
    if (addr < region.remote_base || len > region.len ||
        addr - region.remote_base > region.len - len)
      return ErrorCode::MEMORY_ACCESS_ERROR;
    offset = addr - region.remote_base;
    if (region.base) {
      auto span = poolspan::resolve(region.base, region.len, offset, len, extent_gen,
                                    access, region.tag.c_str(), trace_id);
      if (!span.ok()) return span.error();
      target = span.value().data();
    } else {
      target = nullptr;
      region_out = region;
    }
    return ErrorCode::OK;
  }
};

// Staging-segment handling shared VERBATIM by both serve engines — the
// invariant is that a client cannot tell which engine answered, and shared
// code is how that stays true across edits.
//
// Maps the client-created shm segment named by a hello op, replacing (and
// unmapping) any previous mapping on success. Returns OK, or
// CONNECTION_FAILED when the segment cannot be opened/mapped (different
// host, stale name) — the client falls back to streaming on that status.
ErrorCode map_staging_segment(const char* name, uint8_t*& stg_base, uint64_t& stg_len);

// The staged-op bounds check applied before any byte of the segment is
// believed (also the rejection-override rule: a bad segment outranks
// shed/deadline statuses).
inline bool staging_bounds_ok(const uint8_t* stg_base, uint64_t stg_len, uint64_t shm_off,
                              uint64_t len) {
  return stg_base != nullptr && shm_off <= stg_len && len <= stg_len - shm_off;
}

// Server-side lane counters shared with the fallback server (defined in
// tcp_transport.cpp): ops/bytes served straight off registered pool pages
// with zero worker-side staging copies, plus SEND_ZC completion
// classification (a kernel that COPIED a "zero-copy" send reports it via
// REPORT_USAGE — sustained nonzero copied on a real NIC is a perf
// regression signal, see docs/OPERATIONS.md).
struct DataPlaneCounters {
  StripeCounter* pool_direct_ops{nullptr};
  StripeCounter* pool_direct_bytes{nullptr};
  StripeCounter* zerocopy_sent{nullptr};
  StripeCounter* zerocopy_copied{nullptr};
};

// The event-loop data plane. create() probes io_uring at runtime and
// returns null when it (or the env gate) says no — the caller then runs
// the thread-per-connection fallback on the same listener.
class UringDataPlane {
 public:
  struct Options {
    unsigned loops{0};        // 0 = auto: min(hw_concurrency, 4)
    unsigned sq_entries{512};  // per-loop SQ size (descending-retry on init)
    unsigned exec_threads{2};  // blocking-callback offload pool cap
    DataPlaneCounters counters{};
  };

  // Takes ownership of the listener ON SUCCESS ONLY — a null return (no
  // io_uring on this kernel, env-forced off, init failure) leaves it with
  // the caller so the thread-per-connection fallback can serve the same
  // port. `regions` and `gate` must outlive the engine (the owning
  // TcpTransportServer guarantees it).
  static std::unique_ptr<UringDataPlane> create(net::Socket& listener, RegionTable* regions,
                                                AdmissionGate* gate, const Options& opts);
  ~UringDataPlane();

  UringDataPlane(const UringDataPlane&) = delete;
  UringDataPlane& operator=(const UringDataPlane&) = delete;

  // Idempotent. Cancels in-flight ops, drains every completion, closes all
  // connection fds and the listener, joins loop + exec threads.
  void stop();

  // Live accepted connections across all loops (diagnostics: fan-in tests
  // assert thousands ride the engine without per-connection threads).
  size_t connection_count() const noexcept;

  struct Internals;

 private:
  UringDataPlane() = default;
  std::unique_ptr<Internals> impl_;
};

// True when this process is allowed AND able to run the uring data plane:
// BTPU_IOURING_NET (auto|0|1; legacy alias BTPU_FORCE_NO_URING=1 == 0)
// permits it and a probe io_uring_setup succeeds. Cheap enough to call per
// server start (one syscall + close on success).
bool uring_runtime_available();

// Live engine loops in this process (all UringDataPlane instances): the
// lane scoreboard's "is the event loop actually on?" signal.
size_t uring_active_loop_count() noexcept;

}  // namespace btpu::transport
