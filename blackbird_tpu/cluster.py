"""Embedded in-process cluster: keystone + N workers, for tests and benches."""

from __future__ import annotations

import ctypes
from typing import TYPE_CHECKING

from blackbird_tpu import native
from blackbird_tpu.native import StorageClass, TransportKind, lib

if TYPE_CHECKING:
    from blackbird_tpu.client import Client


class EmbeddedCluster:
    """Hermetic cluster (keystone + workers + coordination) in this process.

    Example:
        with EmbeddedCluster(workers=4, pool_bytes=64 << 20) as cluster:
            client = cluster.client()
            client.put("k", b"hello")
            assert client.get("k") == b"hello"
    """

    def __init__(
        self,
        workers: int = 2,
        pool_bytes: int = 64 << 20,
        storage_class: StorageClass = StorageClass.RAM_CPU,
        transport: TransportKind = TransportKind.LOCAL,
        tiered_device_bytes: int | None = None,
        data_dir: str | None = None,
        group_commit_us: int = -1,
    ) -> None:
        """data_dir arms coordinator persistence: a new cluster on the SAME
        dir recovers every acked durable object (inline tier — RAM pool
        bytes die with the process by design). group_commit_us tunes the
        WAL group-commit window (0 = fdatasync per record, <0 = env/500us
        default); see docs/OPERATIONS.md "Durability"."""
        self._handle: int | None
        if tiered_device_bytes is not None:
            if data_dir is not None:
                raise ValueError("data_dir is not supported with tiered clusters")
            self._handle = lib.btpu_cluster_create_tiered(
                workers, tiered_device_bytes, pool_bytes
            )
        elif data_dir is not None:
            # Manifest-backed capability probe (native.have, not hasattr):
            # btpu_cluster_create_ex is an OPTIONAL symbol a prebuilt older
            # library may lack, and asking for durability it cannot provide
            # must raise, not degrade.
            if not native.have("btpu_cluster_create_ex"):
                raise RuntimeError("this libbtpu build has no durable-cluster support")
            self._handle = lib.btpu_cluster_create_ex(
                workers, pool_bytes, int(storage_class), int(transport),
                str(data_dir).encode(), group_commit_us
            )
        else:
            self._handle = lib.btpu_cluster_create(
                workers, pool_bytes, int(storage_class), int(transport)
            )
        if not self._handle:
            raise RuntimeError("embedded cluster failed to start")

    def client(self, cache_bytes: int | None = None) -> Client:
        from blackbird_tpu.client import Client

        return Client._embedded(self, cache_bytes=cache_bytes)

    @property
    def worker_count(self) -> int:
        return lib.btpu_cluster_worker_count(self._handle)

    def kill_worker(self, index: int) -> None:
        """Abrupt worker death: drives keystone failure detection + repair."""
        lib.btpu_cluster_kill_worker(self._handle, index)

    def counters(self) -> dict[str, int]:
        out = (ctypes.c_uint64 * 6)()
        lib.btpu_cluster_counters(self._handle, out)
        return {
            "objects_repaired": int(out[0]),
            "objects_lost": int(out[1]),
            "evicted": int(out[2]),
            "gc_collected": int(out[3]),
            "workers_lost": int(out[4]),
            "objects_demoted": int(out[5]),
        }

    def close(self) -> None:
        if self._handle:
            lib.btpu_cluster_destroy(self._handle)
            self._handle = None

    def __enter__(self) -> EmbeddedCluster:
        return self

    def __exit__(self, *exc: object) -> None:
        self.close()

    def __del__(self) -> None:
        try:
            self.close()
        except Exception:
            pass
