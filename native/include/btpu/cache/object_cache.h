// Lease-coherent client object cache: a size-bounded, sharded segmented-LRU
// holding VERIFIED object bytes keyed by (key, version), so a hot repeated
// read is served at memory speed with zero worker involvement.
//
// Role parity: FaRM-style near-client caching and Mooncake-store's client
// buffer pool (PAPERS.md) — the reference blackbird has no data cache at all
// (every repeated get pays a full worker round trip).
//
// Coherence contract (the part that makes stale bytes structurally
// impossible rather than merely unlikely):
//   * Every entry records the keystone-stamped object version — the
//     (incarnation generation, epoch) pair the keystone returns with
//     placements. The keystone bumps the epoch on EVERY placement/content
//     mutation (put/overwrite/remove/evict/demote/repair-rewrite) and mints
//     a fresh generation per incarnation, so a (gen, epoch) pair never
//     renames different bytes.
//   * Embedded clients validate every hit directly against the in-process
//     keystone's current version (a shared-lock map read, ~100 ns): a hit is
//     linearizable with the metadata — no staleness window at all.
//   * Remote clients hold a TTL read lease per entry (granted with the
//     placements). Within the lease, invalidations fanned out over the
//     coordinator watch lane delete entries eagerly; at lease expiry — or
//     whenever the watch stream is severed — the entry degrades to
//     "must revalidate": one keystone control RTT compares the current
//     version and either renews the lease (bytes untouched, zero data-plane
//     work) or drops the entry. Staleness is therefore bounded by the lease
//     TTL even with the watch lane down, and near-zero with it up.
//
// Concurrency: N shards, each with its own mutex and its own two-segment
// (probation/protected) LRU. Entry bytes are immutable and shared_ptr-held:
// a reader resolves the hit under the shard lock, then copies out of the
// pinned buffer WITHOUT the lock — an invalidation racing the copy retires
// the entry from the map but can never tear or free the bytes mid-read.
#pragma once

#include <atomic>
#include <chrono>
#include <cstdint>
#include <list>
#include <memory>
#include <mutex>
#include <string>
#include <unordered_map>
#include <vector>

#include "btpu/common/thread_annotations.h"
#include "btpu/common/types.h"

namespace btpu::cache {

// Keystone-stamped object version: `gen` names the keystone incarnation
// (fresh per process / promotion, so epochs re-minted after a restart can
// never collide with cached ones), `epoch` the per-mutation revision.
struct ObjectVersion {
  uint64_t gen{0};
  uint64_t epoch{0};
  bool operator==(const ObjectVersion&) const = default;
  // 0/0 = "server did not stamp" (pre-cache keystone): never cacheable.
  bool valid() const noexcept { return gen != 0 || epoch != 0; }
};

struct CacheStats {
  uint64_t hits{0};
  uint64_t misses{0};
  uint64_t fills{0};
  uint64_t invalidations{0};   // entries dropped by watch/direct invalidation
  uint64_t stale_rejects{0};   // hits rejected because the version moved
  uint64_t lease_expiries{0};  // hits that had to revalidate (lease lapsed)
  uint64_t evictions{0};       // capacity evictions (segmented-LRU)
  uint64_t bytes{0};           // resident payload bytes
  uint64_t entries{0};         // resident entries
};

// Process-global cache counters (sum over every ObjectCache in the process):
// exported through capi for bench/tests and through /metrics for operators,
// exactly like the transport lane counters.
uint64_t cache_hit_count() noexcept;
uint64_t cache_miss_count() noexcept;
uint64_t cache_invalidation_count() noexcept;
uint64_t cache_stale_reject_count() noexcept;
// "cached" data lane: ops/bytes served out of the cache (0 wire bytes, one
// user-space copy per byte) — rides next to pvm/staged/stream in
// lane_counters() and copies_per_byte accounting. note_cached_serve is
// called by the CLIENT at the moment bytes are actually copied to the
// caller (a validated hit whose caller buffer turns out too small is a hit,
// but never a served byte — the lanes row must not inflate).
uint64_t cached_op_count() noexcept;
uint64_t cached_byte_count() noexcept;
void note_cached_serve(uint64_t served_bytes) noexcept;

class ObjectCache {
 public:
  using Clock = std::chrono::steady_clock;
  using Bytes = std::shared_ptr<const std::vector<uint8_t>>;

  // capacity_bytes bounds the sum of payload bytes (metadata overhead is
  // not charged; keys are tiny next to payloads). Objects larger than
  // max_object_bytes (or a shard's capacity) are never cached.
  explicit ObjectCache(uint64_t capacity_bytes, uint64_t max_object_bytes = 0,
                       uint32_t shard_count = 8);

  // Hit resolution. kExpired hands the caller the bytes WITHOUT counting a
  // hit: the caller must revalidate the version against the keystone and
  // then call renew() (serve) or invalidate() (drop).
  enum class Outcome { kMiss, kHit, kExpired };
  struct Hit {
    Outcome outcome{Outcome::kMiss};
    Bytes bytes;
    ObjectVersion version;
    uint32_t content_crc{0};
    // lookup_validated only: the hit is valid (version-checked) but its
    // lease period has lapsed — the embedded client uses this as a cheap
    // once-per-lease cue to touch the keystone's last_access so pressure
    // eviction doesn't judge the hottest cached objects coldest.
    bool lease_lapsed{false};
  };
  Hit lookup(const ObjectKey& key);

  // Validated hit for in-process (embedded) clients: `current` is the
  // keystone's version for the key RIGHT NOW (invalid() = object gone). A
  // mismatch drops the entry (stale_reject) and reports a miss.
  Hit lookup_validated(const ObjectKey& key, const ObjectVersion& current);

  // Counter-free, promotion-free inspection (size probes): kHit when the
  // entry's lease is live, kExpired when lapsed, kMiss when absent. Never
  // mutates state.
  Hit peek(const ObjectKey& key) const;

  // Counts a hit that bypassed lookup()'s accounting — the revalidate-
  // then-serve path, which already holds the pinned bytes from its
  // kExpired lookup.
  void count_revalidated_hit();

  // Inserts verified bytes. Refused (no-op) when the version is unstamped,
  // the object exceeds the size bounds, or an entry with a NEWER version is
  // already resident. lease_deadline is ABSOLUTE and must be anchored at
  // the time the version/lease grant was FETCHED (not at fill time): a slow
  // transfer between grant and fill must shorten the serve window, never
  // extend the staleness bound past grant + lease. (Ignored by
  // lookup_validated, which validates every hit anyway.)
  void fill(const ObjectKey& key, const ObjectVersion& version, uint32_t content_crc,
            Bytes bytes, Clock::time_point lease_deadline);

  // Revalidation verdict for a kExpired entry: renews the resident entry's
  // lease iff it still holds `version` (anchor the deadline at the
  // revalidating metadata fetch, like fill), and drops it (stale_reject)
  // when the resident version moved.
  void renew(const ObjectKey& key, const ObjectVersion& version,
             Clock::time_point lease_deadline);

  // Coherence: drop the entry (watch invalidation, version mismatch,
  // re-created key). Counted as an invalidation when an entry was resident.
  void invalidate(const ObjectKey& key);
  // Drops the entry ONLY while it still holds `version`: the safe form for
  // verdicts about a snapshot — a concurrent reader may have refilled the
  // key with newer (valid) bytes that must not be clobbered.
  void invalidate_if_version(const ObjectKey& key, const ObjectVersion& version);
  void invalidate_all();

  // Collapses every entry's lease deadline to "already expired": called when
  // the invalidation watch stream is severed, so entries filled under push
  // coherence immediately degrade to revalidate-on-hit instead of trusting
  // a lane that can no longer deliver.
  void expire_all_leases();

  CacheStats stats() const;
  uint64_t capacity_bytes() const noexcept { return capacity_; }

 private:
  struct Entry {
    ObjectKey key;
    ObjectVersion version;
    uint32_t content_crc{0};
    Bytes bytes;
    Clock::time_point lease_deadline;
    bool is_protected{false};
  };
  using EntryList = std::list<Entry>;
  struct Shard {
    mutable Mutex mutex;
    // Segmented LRU: first-time entries enter probation; a second hit
    // promotes to protected (capped at ~80% of the shard), which scan
    // traffic cannot flush. Eviction takes probation's tail first.
    EntryList probation BTPU_GUARDED_BY(mutex);   // front = most recent
    EntryList protected_ BTPU_GUARDED_BY(mutex);  // front = most recent
    std::unordered_map<ObjectKey, EntryList::iterator> index BTPU_GUARDED_BY(mutex);
    uint64_t bytes BTPU_GUARDED_BY(mutex){0};
    uint64_t protected_bytes BTPU_GUARDED_BY(mutex){0};
  };

  Shard& shard_for(const ObjectKey& key);
  // All three run under the shard lock.
  void promote_locked(Shard& s, EntryList::iterator it) BTPU_REQUIRES(s.mutex);
  void evict_for_space_locked(Shard& s, uint64_t need) BTPU_REQUIRES(s.mutex);
  void erase_locked(Shard& s, EntryList::iterator it) BTPU_REQUIRES(s.mutex);

  uint64_t capacity_;
  uint64_t max_object_;
  uint64_t shard_capacity_;
  std::vector<std::unique_ptr<Shard>> shards_;

  mutable std::atomic<uint64_t> hits_{0}, misses_{0}, fills_{0}, invalidations_{0},
      stale_rejects_{0}, lease_expiries_{0}, evictions_{0};
};

}  // namespace btpu::cache
