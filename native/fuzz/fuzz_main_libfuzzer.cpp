// libFuzzer entry point (clang boxes; scripts/fuzz.sh builds one binary per
// target with -DBTPU_FUZZ_TARGET=<name> and -fsanitize=fuzzer,address).
// Clang-less boxes run the deterministic sweep in fuzz_replay_main.cpp
// instead; both share the target functions in fuzz_targets.h.
#include "fuzz_targets.h"

#ifndef BTPU_FUZZ_TARGET
#error "build with -DBTPU_FUZZ_TARGET=rpc_frame|control_error|tcp_header|record|wal_record"
#endif

#define BTPU_FUZZ_CAT_(a, b) a##b
#define BTPU_FUZZ_CAT(a, b) BTPU_FUZZ_CAT_(a, b)

extern "C" int LLVMFuzzerTestOneInput(const uint8_t* data, size_t size) {
  return btpu_fuzz::BTPU_FUZZ_CAT(run_, BTPU_FUZZ_TARGET)(data, size);
}
