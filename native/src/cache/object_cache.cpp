#include "btpu/cache/object_cache.h"

#include <algorithm>
#include <functional>

#include "btpu/common/flight_recorder.h"

namespace btpu::cache {

namespace {
// Process-global counters, mirrored from every cache instance (capi +
// /metrics read these, like the transport lane counters).
std::atomic<uint64_t> g_hits{0}, g_misses{0}, g_invalidations{0}, g_stale_rejects{0};
std::atomic<uint64_t> g_cached_ops{0}, g_cached_bytes{0};

// One flight-recorder event per process-global miss: the op is about to
// pay a wire round trip — exactly what a flight dump wants to show.
void global_miss() noexcept {
  // ordering: relaxed — monotonic stat counter; no payload is published through it.
  g_misses.fetch_add(1, std::memory_order_relaxed);
  flight::record(flight::Ev::kCacheMiss);
}
}  // namespace

// ordering: relaxed — stat folds; a point-in-time scrape has no ordering needs.
uint64_t cache_hit_count() noexcept { return g_hits.load(std::memory_order_relaxed); }
uint64_t cache_miss_count() noexcept { return g_misses.load(std::memory_order_relaxed); }
uint64_t cache_invalidation_count() noexcept {
  return g_invalidations.load(std::memory_order_relaxed);
}
uint64_t cache_stale_reject_count() noexcept {
  return g_stale_rejects.load(std::memory_order_relaxed);
}
// ordering: relaxed — stat folds; a point-in-time scrape has no ordering needs.
uint64_t cached_op_count() noexcept { return g_cached_ops.load(std::memory_order_relaxed); }
uint64_t cached_byte_count() noexcept {
  return g_cached_bytes.load(std::memory_order_relaxed);
}
// No flight event here on purpose: this is the cached-get FAST path (the
// bench.py trace-overhead budget), and the serving site already records a
// light op_end event. Misses record kCacheMiss (global_miss above) — they
// are about to pay a wire round trip, where one event is invisible.
void note_cached_serve(uint64_t served_bytes) noexcept {
  // ordering: relaxed — monotonic stat counters; no payload is published through them.
  g_cached_ops.fetch_add(1, std::memory_order_relaxed);
  g_cached_bytes.fetch_add(served_bytes, std::memory_order_relaxed);
}

ObjectCache::ObjectCache(uint64_t capacity_bytes, uint64_t max_object_bytes,
                         uint32_t shard_count)
    : capacity_(capacity_bytes) {
  shard_count = std::max<uint32_t>(1, shard_count);
  // Tiny capacities collapse to one shard so the whole budget is usable
  // (8 shards of capacity/8 would reject any object > capacity/8).
  if (capacity_ / shard_count < (64u << 10)) shard_count = 1;
  shard_capacity_ = capacity_ / shard_count;
  // Per-object ceiling: explicit bound, else whatever fits a shard. The
  // shard bound always applies — fill() charges one shard only.
  max_object_ = max_object_bytes ? std::min(max_object_bytes, shard_capacity_)
                                 : shard_capacity_;
  shards_.reserve(shard_count);
  for (uint32_t i = 0; i < shard_count; ++i) shards_.push_back(std::make_unique<Shard>());
}

ObjectCache::Shard& ObjectCache::shard_for(const ObjectKey& key) {
  return *shards_[std::hash<ObjectKey>{}(key) % shards_.size()];
}

// Second hit promotes probation -> protected; protected overflow demotes its
// tail back to probation's MRU end (standard SLRU: a demoted entry was
// re-touched at some point, so it outranks never-re-touched scan entries —
// eviction still takes probation's LRU tail first).
void ObjectCache::promote_locked(Shard& s, EntryList::iterator it) {
  if (it->is_protected) {
    if (it != s.protected_.begin())
      s.protected_.splice(s.protected_.begin(), s.protected_, it);
    return;
  }
  it->is_protected = true;
  s.protected_bytes += it->bytes->size();
  s.protected_.splice(s.protected_.begin(), s.probation, it);
  const uint64_t protected_cap = shard_capacity_ - shard_capacity_ / 5;  // ~80%
  while (s.protected_bytes > protected_cap && !s.protected_.empty()) {
    auto tail = std::prev(s.protected_.end());
    if (tail == it) break;  // never demote the entry just promoted
    tail->is_protected = false;
    s.protected_bytes -= tail->bytes->size();
    s.probation.splice(s.probation.begin(), s.protected_, tail);
  }
}

void ObjectCache::erase_locked(Shard& s, EntryList::iterator it) {
  s.bytes -= it->bytes->size();
  if (it->is_protected) {
    s.protected_bytes -= it->bytes->size();
    s.index.erase(it->key);
    s.protected_.erase(it);
  } else {
    s.index.erase(it->key);
    s.probation.erase(it);
  }
}

void ObjectCache::evict_for_space_locked(Shard& s, uint64_t need) {
  while (s.bytes + need > shard_capacity_) {
    EntryList& victims = !s.probation.empty() ? s.probation : s.protected_;
    if (victims.empty()) return;
    erase_locked(s, std::prev(victims.end()));
    // ordering: relaxed — monotonic stat counter; entry payloads publish via the shard mutex.
    evictions_.fetch_add(1, std::memory_order_relaxed);
  }
}

ObjectCache::Hit ObjectCache::lookup(const ObjectKey& key) {
  Shard& s = shard_for(key);
  Hit hit;
  {
    MutexLock lock(s.mutex);
    auto idx = s.index.find(key);
    if (idx == s.index.end()) {
      // ordering: relaxed — monotonic stat counter; entry payloads publish via the shard mutex.
      misses_.fetch_add(1, std::memory_order_relaxed);
      global_miss();
      return hit;
    }
    auto it = idx->second;
    hit.bytes = it->bytes;  // pinned: safe to copy from after unlock
    hit.version = it->version;
    hit.content_crc = it->content_crc;
    if (Clock::now() >= it->lease_deadline) {
      // Lease lapsed: the caller must revalidate before serving. Not a miss
      // (the bytes may still be current) and not yet a hit.
      hit.outcome = Outcome::kExpired;
      // ordering: relaxed — monotonic stat counter; entry payloads publish via the shard mutex.
      lease_expiries_.fetch_add(1, std::memory_order_relaxed);
      return hit;
    }
    promote_locked(s, it);
  }
  hit.outcome = Outcome::kHit;
  // ordering: relaxed — monotonic stat counters; entry payloads publish via the shard mutex.
  hits_.fetch_add(1, std::memory_order_relaxed);
  g_hits.fetch_add(1, std::memory_order_relaxed);
  return hit;
}

ObjectCache::Hit ObjectCache::lookup_validated(const ObjectKey& key,
                                               const ObjectVersion& current) {
  Shard& s = shard_for(key);
  Hit hit;
  {
    MutexLock lock(s.mutex);
    auto idx = s.index.find(key);
    if (idx == s.index.end()) {
      // ordering: relaxed — monotonic stat counter; entry payloads publish via the shard mutex.
      misses_.fetch_add(1, std::memory_order_relaxed);
      global_miss();
      return hit;
    }
    auto it = idx->second;
    if (!current.valid() || !(it->version == current)) {
      // The key mutated (or vanished) under us: structurally impossible to
      // serve — drop the entry and report a miss.
      erase_locked(s, it);
      // ordering: relaxed — monotonic stat counters; entry payloads publish via the shard mutex.
      stale_rejects_.fetch_add(1, std::memory_order_relaxed);
      g_stale_rejects.fetch_add(1, std::memory_order_relaxed);
      misses_.fetch_add(1, std::memory_order_relaxed);
      global_miss();
      return hit;
    }
    hit.bytes = it->bytes;
    hit.version = it->version;
    hit.content_crc = it->content_crc;
    hit.lease_lapsed = Clock::now() >= it->lease_deadline;
    promote_locked(s, it);
  }
  hit.outcome = Outcome::kHit;
  // ordering: relaxed — monotonic stat counters; entry payloads publish via the shard mutex.
  hits_.fetch_add(1, std::memory_order_relaxed);
  g_hits.fetch_add(1, std::memory_order_relaxed);
  return hit;
}

ObjectCache::Hit ObjectCache::peek(const ObjectKey& key) const {
  auto& s = const_cast<ObjectCache*>(this)->shard_for(key);
  Hit hit;
  MutexLock lock(s.mutex);
  auto idx = s.index.find(key);
  if (idx == s.index.end()) return hit;
  const auto it = idx->second;
  hit.bytes = it->bytes;
  hit.version = it->version;
  hit.content_crc = it->content_crc;
  hit.outcome = Clock::now() < it->lease_deadline ? Outcome::kHit : Outcome::kExpired;
  return hit;
}

void ObjectCache::count_revalidated_hit() {
  // ordering: relaxed — monotonic stat counters; entry payloads publish via the shard mutex.
  hits_.fetch_add(1, std::memory_order_relaxed);
  g_hits.fetch_add(1, std::memory_order_relaxed);
}

void ObjectCache::fill(const ObjectKey& key, const ObjectVersion& version,
                       uint32_t content_crc, Bytes bytes, Clock::time_point lease_deadline) {
  if (!version.valid() || !bytes || bytes->empty() || bytes->size() > max_object_) return;
  Shard& s = shard_for(key);
  const auto deadline = lease_deadline;
  MutexLock lock(s.mutex);
  auto idx = s.index.find(key);
  if (idx != s.index.end()) {
    auto it = idx->second;
    // Same-gen epochs order fills racing an overwrite; a cross-gen fill
    // (keystone failover mid-race) has no order, so newest-write wins.
    if (it->version.gen == version.gen && it->version.epoch > version.epoch) return;
    erase_locked(s, it);
  }
  evict_for_space_locked(s, bytes->size());
  if (s.bytes + bytes->size() > shard_capacity_) return;  // larger than the shard
  s.bytes += bytes->size();
  s.probation.push_front(
      {key, version, content_crc, std::move(bytes), deadline, /*is_protected=*/false});
  s.index[key] = s.probation.begin();
  // ordering: relaxed — monotonic stat counter; entry payloads publish via the shard mutex.
  fills_.fetch_add(1, std::memory_order_relaxed);
}

void ObjectCache::renew(const ObjectKey& key, const ObjectVersion& version,
                        Clock::time_point lease_deadline) {
  Shard& s = shard_for(key);
  MutexLock lock(s.mutex);
  auto idx = s.index.find(key);
  if (idx == s.index.end()) return;
  auto it = idx->second;
  if (!(it->version == version)) {
    // Revalidation says the resident entry is someone else's bytes now.
    erase_locked(s, it);
    // ordering: relaxed — monotonic stat counters; entry payloads publish via the shard mutex.
    stale_rejects_.fetch_add(1, std::memory_order_relaxed);
    g_stale_rejects.fetch_add(1, std::memory_order_relaxed);
    return;
  }
  it->lease_deadline = lease_deadline;
}

void ObjectCache::invalidate(const ObjectKey& key) {
  Shard& s = shard_for(key);
  MutexLock lock(s.mutex);
  auto idx = s.index.find(key);
  if (idx == s.index.end()) return;
  erase_locked(s, idx->second);
  // ordering: relaxed — monotonic stat counters; entry payloads publish via the shard mutex.
  invalidations_.fetch_add(1, std::memory_order_relaxed);
  g_invalidations.fetch_add(1, std::memory_order_relaxed);
}

void ObjectCache::invalidate_if_version(const ObjectKey& key, const ObjectVersion& version) {
  Shard& s = shard_for(key);
  MutexLock lock(s.mutex);
  auto idx = s.index.find(key);
  if (idx == s.index.end() || !(idx->second->version == version)) return;
  erase_locked(s, idx->second);
  // ordering: relaxed — monotonic stat counters; entry payloads publish via the shard mutex.
  invalidations_.fetch_add(1, std::memory_order_relaxed);
  g_invalidations.fetch_add(1, std::memory_order_relaxed);
}

void ObjectCache::invalidate_all() {
  for (auto& sp : shards_) {
    MutexLock lock(sp->mutex);
    const uint64_t n = sp->index.size();
    sp->probation.clear();
    sp->protected_.clear();
    sp->index.clear();
    sp->bytes = sp->protected_bytes = 0;
    // ordering: relaxed — monotonic stat counters; entry payloads publish via the shard mutex.
    invalidations_.fetch_add(n, std::memory_order_relaxed);
    g_invalidations.fetch_add(n, std::memory_order_relaxed);
  }
}

void ObjectCache::expire_all_leases() {
  const auto past = Clock::now() - std::chrono::milliseconds(1);
  for (auto& sp : shards_) {
    MutexLock lock(sp->mutex);
    for (auto& e : sp->probation) e.lease_deadline = past;
    for (auto& e : sp->protected_) e.lease_deadline = past;
  }
}

CacheStats ObjectCache::stats() const {
  CacheStats out;
  // ordering: relaxed — stat folds into one snapshot; exactly as consistent as any scrape.
  out.hits = hits_.load(std::memory_order_relaxed);
  out.misses = misses_.load(std::memory_order_relaxed);
  out.fills = fills_.load(std::memory_order_relaxed);
  out.invalidations = invalidations_.load(std::memory_order_relaxed);
  out.stale_rejects = stale_rejects_.load(std::memory_order_relaxed);
  out.lease_expiries = lease_expiries_.load(std::memory_order_relaxed);
  out.evictions = evictions_.load(std::memory_order_relaxed);
  for (const auto& sp : shards_) {
    MutexLock lock(sp->mutex);
    out.bytes += sp->bytes;
    out.entries += sp->index.size();
  }
  return out;
}

}  // namespace btpu::cache
