#!/usr/bin/env bash
# One-command ThreadSanitizer leg: builds the native tree under
# -fsanitize=thread (separate build/tsan object tree) and runs the
# concurrency-heavy suites — the client object cache and the transports.
# Extra suites: TSAN_FILTERS="Cache Transport EndToEnd" scripts/tsan.sh
set -euo pipefail
cd "$(dirname "$0")/.."
exec make tsan ${TSAN_FILTERS:+TSAN_FILTERS="${TSAN_FILTERS}"}
