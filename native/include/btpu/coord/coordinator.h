// Coordination service interface: KV + TTL leases + prefix watches + service
// registry + leader election.
//
// Parity target: reference include/blackbird/etcd/etcd_service.h:30-246 /
// src/etcd/etcd_service.cpp:60-408 (EtcdService over etcd-cpp-apiv3). etcd is
// not available in this image, so the framework defines the interface and
// ships two implementations:
//   * MemCoordinator  — in-process store with real TTL expiry + watch events
//     (the hermetic fake SURVEY.md §4 calls for);
//   * RemoteCoordinator/CoordServer — the same store served over TCP for
//     multi-process clusters (bb-coord executable).
// Unlike the reference, leader election is implemented, not stubbed
// (reference etcd_service.cpp:379-385 is a stub).
#pragma once

#include <functional>
#include <string>
#include <vector>

#include "btpu/common/result.h"
#include "btpu/common/types.h"

namespace btpu::coord {

struct WatchEvent {
  enum class Type { kPut, kDelete };
  Type type;
  std::string key;
  std::string value;  // empty for deletes
};

using WatchCallback = std::function<void(const WatchEvent&)>;
using WatchId = int64_t;

struct KeyValue {
  std::string key;
  std::string value;
};

class Coordinator {
 public:
  virtual ~Coordinator() = default;

  // --- KV ---
  virtual Result<std::string> get(const std::string& key) = 0;
  virtual ErrorCode put(const std::string& key, const std::string& value) = 0;
  // Lease-per-call TTL put (reference etcd_service.cpp:130-157).
  virtual ErrorCode put_with_ttl(const std::string& key, const std::string& value,
                                 int64_t ttl_ms) = 0;
  virtual ErrorCode del(const std::string& key) = 0;
  virtual Result<std::vector<KeyValue>> get_with_prefix(const std::string& prefix) = 0;

  // --- Leases ---
  virtual Result<LeaseId> lease_grant(int64_t ttl_ms) = 0;
  virtual ErrorCode lease_keepalive(LeaseId lease) = 0;
  virtual ErrorCode lease_revoke(LeaseId lease) = 0;
  virtual ErrorCode put_with_lease(const std::string& key, const std::string& value,
                                   LeaseId lease) = 0;

  // --- Watches ---
  // Callback fires for every PUT/DELETE under prefix, including TTL expiry
  // (delivered as kDelete — the availability path, reference
  // keystone_service.cpp:728-751 relies on this).
  virtual Result<WatchId> watch_prefix(const std::string& prefix, WatchCallback cb) = 0;
  virtual ErrorCode unwatch(WatchId id) = 0;

  // --- Service registry (reference etcd_service.cpp:339-377) ---
  virtual ErrorCode register_service(const std::string& service_name, const std::string& id,
                                     const std::string& address, int64_t ttl_ms) = 0;
  virtual Result<std::vector<KeyValue>> discover_service(const std::string& service_name) = 0;
  virtual ErrorCode unregister_service(const std::string& service_name, const std::string& id) = 0;

  // --- Leader election (with fencing tokens) ---
  // First campaigner under `election` wins; on leader death/resign the next
  // campaigner is promoted and its callback fires with is_leader=true.
  // Every promotion MINTS a fencing epoch — monotonic across the store's
  // whole lifetime (durable, shared by all elections) — delivered to the
  // new leader in the callback. A deposed leader that resumes (GC pause,
  // SIGSTOP, partition heal) still holds its old epoch; the *_fenced
  // mutations below reject it, which is what makes split-brain windows
  // harmless (the raft-safety analog of the reference's etcd).
  using CampaignCallback = std::function<void(bool is_leader, uint64_t epoch)>;
  virtual ErrorCode campaign(const std::string& election, const std::string& candidate_id,
                             int64_t lease_ttl_ms, CampaignCallback cb) = 0;
  virtual ErrorCode resign(const std::string& election, const std::string& candidate_id) = 0;
  // Refreshes the candidate's election lease. A candidate (leader or
  // standby) that stops calling this within its lease TTL is treated as
  // dead and removed from the election — the liveness half of failover.
  virtual ErrorCode campaign_keepalive(const std::string& election,
                                       const std::string& candidate_id) = 0;
  virtual Result<std::string> current_leader(const std::string& election) = 0;
  // Current fencing epoch of the election (COORD_KEY_NOT_FOUND when it has
  // no leader).
  virtual Result<uint64_t> election_epoch(const std::string& election) = 0;

  // --- Fenced mutations ---
  // Execute iff `epoch` equals the election's current epoch; otherwise
  // FENCED and no state changes. A leader routes every durable write it
  // performs on behalf of its leadership through these.
  virtual ErrorCode put_fenced(const std::string& key, const std::string& value,
                               const std::string& election, uint64_t epoch) = 0;
  virtual ErrorCode del_fenced(const std::string& key, const std::string& election,
                               uint64_t epoch) = 0;

  virtual bool connected() const = 0;
};

// Well-known key scheme (reference keystone_service.cpp:590-604).
std::string workers_prefix(const std::string& cluster_id);
std::string worker_key(const std::string& cluster_id, const std::string& worker_id);
std::string pools_prefix(const std::string& cluster_id);
std::string pool_key(const std::string& cluster_id, const std::string& worker_id,
                     const std::string& pool_id);
std::string heartbeat_prefix(const std::string& cluster_id);
std::string heartbeat_key(const std::string& cluster_id, const std::string& worker_id);
std::string services_prefix(const std::string& service_name);
std::string objects_prefix(const std::string& cluster_id);
std::string object_record_key(const std::string& cluster_id, const std::string& object_key);
// Client object-cache invalidation topic: the keystone publishes
// "<new version>" (or "0" for removal) under the object's key here on every
// placement/content mutation; caching clients watch the prefix and drop the
// entry on any event. Values are TTL'd — the topic is a fan-out lane, not a
// registry, so it self-cleans.
std::string cache_inval_prefix(const std::string& cluster_id);
std::string cache_inval_key(const std::string& cluster_id, const std::string& object_key);

}  // namespace btpu::coord
