// Tiny self-contained unit-test framework (gtest is not available in this
// image and network fetch is disallowed, so we ship our own runner).
// Usage:   BTEST(Suite, Name) { BT_EXPECT_EQ(a, b); ... }
// Runner:  btpu_tests [--filter=substring] [--list]
#pragma once

#include <sys/stat.h>
#include <unistd.h>

#include <cstdio>
#include <functional>
#include <sstream>
#include <string>
#include <vector>

#include "btpu/common/env.h"
#include "btpu/common/result.h"

namespace btest {

// BT_EXPECT_OK accepts both conventions: a bare ErrorCode and a Result<T>
// (whose .error() is OK when it holds a value).
inline ::btpu::ErrorCode to_error_code(::btpu::ErrorCode ec) { return ec; }
template <typename T>
::btpu::ErrorCode to_error_code(const ::btpu::Result<T>& r) {
  return r.error();
}

// Locates a repo-relative file/dir from the test binary's location
// (build/ or build/{asan,tsan}/) or the repo-root cwd; `env_var` overrides.
// Shared by the golden-table and fuzz-corpus tests so their path-resolution
// behavior cannot drift.
inline std::string locate_repo_path(const char* env_var, const char* rel) {
  if (const char* env = ::btpu::env_str(env_var)) return env;
  std::vector<std::string> candidates = {rel};
  char exe[4096];
  const ssize_t n = ::readlink("/proc/self/exe", exe, sizeof(exe) - 1);
  if (n > 0) {
    exe[n] = '\0';
    std::string dir(exe);
    dir = dir.substr(0, dir.find_last_of('/'));
    candidates.push_back(dir + "/../" + rel);
    candidates.push_back(dir + "/../../" + rel);
  }
  for (const auto& c : candidates) {
    struct ::stat st {};
    if (::stat(c.c_str(), &st) == 0) return c;
  }
  return candidates.front();
}

struct TestCase {
  std::string name;
  std::function<void()> fn;
};

inline std::vector<TestCase>& registry() {
  static std::vector<TestCase> r;
  return r;
}

inline int& failure_count() {
  static int n = 0;
  return n;
}

inline bool& current_failed() {
  static bool f = false;
  return f;
}

struct Registrar {
  Registrar(std::string name, std::function<void()> fn) {
    registry().push_back({std::move(name), std::move(fn)});
  }
};

template <typename A, typename B>
std::string fmt_cmp(const char* op, const A& a, const B& b) {
  std::ostringstream ss;
  ss << "expected: " << a << " " << op << " " << b;
  return ss.str();
}

inline void report_failure(const char* file, int line, const std::string& msg) {
  std::fprintf(stderr, "  FAIL %s:%d: %s\n", file, line, msg.c_str());
  current_failed() = true;
}

inline int run_all(int argc, char** argv) {
  std::string filter;
  bool list = false;
  for (int i = 1; i < argc; ++i) {
    std::string a = argv[i];
    if (a.rfind("--filter=", 0) == 0) filter = a.substr(9);
    if (a == "--list") list = true;
  }
  int ran = 0, failed = 0;
  for (auto& tc : registry()) {
    if (!filter.empty() && tc.name.find(filter) == std::string::npos) continue;
    if (list) {
      std::printf("%s\n", tc.name.c_str());
      continue;
    }
    current_failed() = false;
    std::printf("[ RUN  ] %s\n", tc.name.c_str());
    std::fflush(stdout);
    try {
      tc.fn();
    } catch (const std::exception& e) {
      report_failure("<exception>", 0, std::string("uncaught exception: ") + e.what());
    } catch (...) {
      report_failure("<exception>", 0, "uncaught non-std exception");
    }
    ++ran;
    if (current_failed()) {
      ++failed;
      std::printf("[ FAIL ] %s\n", tc.name.c_str());
    } else {
      std::printf("[  OK  ] %s\n", tc.name.c_str());
    }
    std::fflush(stdout);
  }
  if (!list) {
    std::printf("%d tests ran, %d failed\n", ran, failed);
  }
  return failed == 0 ? 0 : 1;
}

}  // namespace btest

#define BTEST(Suite, Name)                                                   \
  static void btest_##Suite##_##Name();                                      \
  static ::btest::Registrar btest_reg_##Suite##_##Name(#Suite "." #Name,     \
                                                       btest_##Suite##_##Name); \
  static void btest_##Suite##_##Name()

#define BT_EXPECT(cond)                                                      \
  do {                                                                       \
    if (!(cond)) ::btest::report_failure(__FILE__, __LINE__, "expected: " #cond); \
  } while (0)

#define BT_EXPECT_EQ(a, b)                                                   \
  do {                                                                       \
    auto _va = (a);                                                          \
    auto _vb = (b);                                                          \
    if (!(_va == _vb))                                                       \
      ::btest::report_failure(__FILE__, __LINE__, ::btest::fmt_cmp("==", _va, _vb)); \
  } while (0)

#define BT_EXPECT_NE(a, b)                                                   \
  do {                                                                       \
    auto _va = (a);                                                          \
    auto _vb = (b);                                                          \
    if (_va == _vb)                                                          \
      ::btest::report_failure(__FILE__, __LINE__, ::btest::fmt_cmp("!=", _va, _vb)); \
  } while (0)

#define BT_ASSERT(cond)                                                      \
  do {                                                                       \
    if (!(cond)) {                                                           \
      ::btest::report_failure(__FILE__, __LINE__, "required: " #cond);       \
      return;                                                                \
    }                                                                        \
  } while (0)

// Non-fatal OK check for ErrorCode- or Result-returning calls. Variadic so
// call expressions containing top-level commas need no extra parens. Safe in
// fixtures and helpers (no `return` on failure, unlike BT_ASSERT_OK).
#define BT_EXPECT_OK(...)                                                    \
  do {                                                                       \
    const ::btpu::ErrorCode _btec = ::btest::to_error_code((__VA_ARGS__));   \
    if (_btec != ::btpu::ErrorCode::OK)                                      \
      ::btest::report_failure(__FILE__, __LINE__,                            \
                              std::string("expected OK, got ") +             \
                                  std::string(::btpu::to_string(_btec)));    \
  } while (0)

#define BT_ASSERT_OK(result_expr)                                            \
  do {                                                                       \
    if (!(result_expr).ok()) {                                               \
      ::btest::report_failure(__FILE__, __LINE__,                            \
                              std::string("required OK, got error ") +       \
                                  std::string(::btpu::to_string((result_expr).error()))); \
      return;                                                                \
    }                                                                        \
  } while (0)
