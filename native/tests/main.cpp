#include "btest.h"

int main(int argc, char** argv) { return btest::run_all(argc, argv); }
