// Batch engine: the shard-job machinery shared by the batched object
// I/O paths (put_many / get_many) and the pooled-slot put path — per-
// item jobs partitioned by data path (device vs wire), one pipelined
// wire batch with fused CRCs, one provider batch for device shards.
// Internal header (native/src/client); not part of the public SDK.
#pragma once

#include <cstring>
#include <map>
#include <vector>

#include "btpu/client/client.h"
#include "btpu/common/crc32c.h"
#include "btpu/ec/rs.h"
#include "btpu/transport/transport.h"

namespace btpu::client {

// Per-item shard jobs for a whole batch, partitioned by data path.
struct BatchJobs {
  std::vector<transport::ShardJob> device;   // all items' device shards
  std::vector<size_t> device_item;           // item index per device job
  std::vector<transport::ShardJob> wire;     // all items' wire shards
  std::vector<size_t> wire_item;
};

// Splits one copy of `size` bytes at `data` into jobs, appending to `jobs`.
// Returns INVALID_PARAMETERS when the shard lengths do not sum to size.
// `crcs_out` (when non-null) receives this copy's per-shard CRC32C stamps —
// computed here because the put path is the one place the shard boundaries
// and the bytes are both in hand.
inline ErrorCode append_copy_jobs(const CopyPlacement& copy, uint8_t* data, uint64_t size,
                           size_t item_index, BatchJobs& jobs,
                           CopyShardCrcs* crcs_out = nullptr) {
  if (crcs_out) {
    crcs_out->copy_index = copy.copy_index;
    crcs_out->crcs.clear();
    crcs_out->crcs.reserve(copy.shards.size());
  }
  uint64_t off = 0;
  for (const auto& shard : copy.shards) {
    if (off + shard.length > size) return ErrorCode::INVALID_PARAMETERS;
    transport::ShardJob job{&shard, 0, data + off, shard.length};
    if (std::holds_alternative<DeviceLocation>(shard.location)) {
      jobs.device.push_back(job);
      jobs.device_item.push_back(item_index);
    } else {
      jobs.wire.push_back(job);
      jobs.wire_item.push_back(item_index);
    }
    if (crcs_out) crcs_out->crcs.push_back(crc32c(data + off, shard.length));
    off += shard.length;
  }
  return off == size ? ErrorCode::OK : ErrorCode::INVALID_PARAMETERS;
}

// Coded-copy batch helpers. Arena owns padded-data and parity buffers until
// the wire batch executes (inner-vector buffers stay put when the arena
// grows). EC pools are wire-only by placement, so every job is a wire job.
inline ErrorCode append_ec_put_jobs(const CopyPlacement& copy, const uint8_t* data, uint64_t size,
                             size_t item_index, std::vector<std::vector<uint8_t>>& arena,
                             BatchJobs& jobs, CopyShardCrcs* crcs_out = nullptr) {
  const size_t k = copy.ec_data_shards, m = copy.ec_parity_shards;
  if (copy.shards.size() != k + m || size != copy.ec_object_size)
    return ErrorCode::INVALID_PARAMETERS;
  const uint64_t L = copy.shards.front().length;
  for (const auto& s : copy.shards) {
    if (s.length != L) return ErrorCode::INVALID_PARAMETERS;
  }
  std::vector<const uint8_t*> data_ptrs(k);
  for (size_t i = 0; i < k; ++i) {
    const uint64_t start = i * L;
    const uint64_t valid = start >= size ? 0 : std::min<uint64_t>(L, size - start);
    if (valid == L) {
      data_ptrs[i] = data + start;
    } else {
      arena.emplace_back(L, 0);
      if (valid > 0) std::memcpy(arena.back().data(), data + start, valid);
      data_ptrs[i] = arena.back().data();
    }
  }
  std::vector<uint8_t*> parity_ptrs(m);
  for (size_t j = 0; j < m; ++j) {
    arena.emplace_back(L);
    parity_ptrs[j] = arena.back().data();
  }
  if (!ec::rs_encode(data_ptrs.data(), k, parity_ptrs.data(), m, L))
    return ErrorCode::INVALID_PARAMETERS;
  if (crcs_out) {
    crcs_out->copy_index = copy.copy_index;
    crcs_out->crcs.clear();
    crcs_out->crcs.reserve(k + m);
  }
  for (size_t i = 0; i < k + m; ++i) {
    uint8_t* buf = i < k ? const_cast<uint8_t*>(data_ptrs[i]) : parity_ptrs[i - k];
    jobs.wire.push_back({&copy.shards[i], 0, buf, L});
    jobs.wire_item.push_back(item_index);
    // Shard CRCs cover the full L wire bytes (padding included) so readers
    // and scrubbers can verify a shard without knowing the object size.
    if (crcs_out) crcs_out->crcs.push_back(crc32c(buf, L));
  }
  return ErrorCode::OK;
}

// Post-batch copy of a padded shard's valid bytes into the user buffer.
struct EcReadFixup {
  size_t item;
  uint8_t* dst;
  const uint8_t* src;
  uint64_t n;
};

// Appends the k data-shard reads of one coded copy (the healthy fast path;
// a failed item falls back to the full reconstructing read).
inline void append_ec_get_jobs(const CopyPlacement& copy, uint8_t* buffer, uint64_t size,
                        size_t item_index, std::vector<std::vector<uint8_t>>& arena,
                        BatchJobs& jobs, std::vector<EcReadFixup>& fixups) {
  const size_t k = copy.ec_data_shards;
  const uint64_t L = copy.shards.front().length;
  for (size_t i = 0; i < k; ++i) {
    const uint64_t start = i * L;
    const uint64_t valid = start >= size ? 0 : std::min<uint64_t>(L, size - start);
    if (valid == 0) continue;  // pure padding: nothing to read
    uint8_t* buf;
    if (valid == L) {
      buf = buffer + start;
    } else {
      arena.emplace_back(L);
      buf = arena.back().data();
      fixups.push_back({item_index, buffer + start, buf, valid});
    }
    jobs.wire.push_back({&copy.shards[i], 0, buf, L});
    jobs.wire_item.push_back(item_index);
  }
}

// Range (offset, length) -> CRC32C map. Prefilled by the transport's fused
// write hashes; stamp_copy_crcs fills the gaps (device shards, failed ops).
using RangeCrcMap = std::map<std::pair<uint64_t, uint64_t>, uint32_t>;

// Per-copy shard CRC stamps for replicated/striped copies: replica copies
// cover the SAME bytes, so each distinct (offset, length) range is hashed
// once and reused. Wire shards arrive pre-hashed in `range_crc` (the
// transport fused the CRC into its copy/send of the bytes), so the typical
// put stamps every shard with ZERO standalone passes; only device shards
// and retried ranges fall back to hashing here.
inline std::vector<CopyShardCrcs> stamp_copy_crcs(const std::vector<CopyPlacement>& copies,
                                           const uint8_t* data, RangeCrcMap& range_crc) {
  std::vector<CopyShardCrcs> out;
  out.reserve(copies.size());
  for (const auto& copy : copies) {
    CopyShardCrcs crcs;
    crcs.copy_index = copy.copy_index;
    crcs.crcs.reserve(copy.shards.size());
    uint64_t off = 0;
    for (const auto& shard : copy.shards) {
      auto [it, fresh] = range_crc.try_emplace({off, shard.length}, 0);
      if (fresh) it->second = crc32c(data + off, shard.length);
      crcs.crcs.push_back(it->second);
      off += shard.length;
    }
    out.push_back(std::move(crcs));
  }
  return out;
}

// Whole-object CRC folded from one copy's shard stamps (shards tile the
// object contiguously in order — append_copy_jobs enforces exact cover).
// With fused wire hashes this makes the content stamp FREE: no pass over
// the bytes anywhere in the put path.
inline uint32_t fold_content_crc(const CopyShardCrcs& crcs, const CopyPlacement& copy) {
  uint32_t crc = 0;
  for (size_t i = 0; i < crcs.crcs.size(); ++i)
    crc = i == 0 ? crcs.crcs[0] : crc32c_combine(crc, crcs.crcs[i], copy.shards[i].length);
  return crc;
}

// Read-side mirror of stamp_copy_crcs: folds one copy's object CRC from the
// transport's fused read hashes, hashing only the gaps (device shards,
// skipped ops, the rare genuine-zero crc). The batched verified get then
// checks integrity with ~no second pass over wire bytes.
inline uint32_t fold_ranges_crc(const CopyPlacement& copy, const uint8_t* base, RangeCrcMap& ranges) {
  uint32_t crc = 0;
  uint64_t off = 0;
  for (size_t i = 0; i < copy.shards.size(); ++i) {
    const uint64_t len = copy.shards[i].length;
    auto [it, fresh] = ranges.try_emplace({off, len}, 0);
    if (fresh) it->second = crc32c(base + off, len);
    crc = i == 0 ? it->second : crc32c_combine(crc, it->second, len);
    off += len;
  }
  return crc;
}

// Collects one item's fused write hashes out of run_wire_jobs' output into
// the (object offset, length) -> crc form stamp_copy_crcs consumes. `item`
// filters a batch down to one object; 0-crc entries (skipped/failed ops, or
// the rare genuine zero) fall through to stamp_copy_crcs' own hashing.
inline void harvest_wire_ranges(const BatchJobs& jobs, const std::vector<uint32_t>& wire_crcs,
                         size_t item, const uint8_t* base, RangeCrcMap& ranges) {
  for (size_t j = 0; j < jobs.wire.size() && j < wire_crcs.size(); ++j) {
    if (jobs.wire_item[j] != item || wire_crcs[j] == 0) continue;
    ranges[{static_cast<uint64_t>(jobs.wire[j].buf - base), jobs.wire[j].len}] =
        wire_crcs[j];
  }
}

// Runs the wire jobs as ONE pipelined batch; per-op failures land on their
// item, jobs of items that already failed are skipped (their reservation is
// cancelled by the caller anyway). With `wire_crcs` (put path) ops ask the
// transport for a fused hash of the bytes they moved; (*wire_crcs)[j] gets
// job j's crc for ops that completed (entries stay 0 for skipped/failed
// jobs — stamp_copy_crcs treats a missing range as "hash it here").
// `crc_items` (parallel to the caller's items, may be null = all) limits
// the request to items whose hashes will actually be harvested — EC items
// stamp during encode, so hashing their padded/parity ranges is waste.
inline void run_wire_jobs(transport::TransportClient& client, const BatchJobs& jobs, bool is_write,
                   size_t max_concurrency, std::vector<ErrorCode>& item_errors,
                   std::vector<uint32_t>* wire_crcs = nullptr,
                   const std::vector<bool>* crc_items = nullptr) {
  if (jobs.wire.empty()) return;
  if (wire_crcs) wire_crcs->assign(jobs.wire.size(), 0);
  std::vector<transport::WireOp> ops;
  std::vector<size_t> op_item, op_job;
  ops.reserve(jobs.wire.size());
  for (size_t j = 0; j < jobs.wire.size(); ++j) {
    const size_t item = jobs.wire_item[j];
    if (item_errors[item] != ErrorCode::OK) continue;
    const auto& job = jobs.wire[j];
    transport::WireOp op;
    if (!transport::make_wire_op(*job.shard, job.in_off, job.buf, job.len, op)) {
      // FileLocation: worker-served, never a client target.
      item_errors[item] = ErrorCode::NOT_IMPLEMENTED;
      continue;
    }
    op.want_crc =
        wire_crcs != nullptr && (!crc_items || (item < crc_items->size() && (*crc_items)[item]));
    ops.push_back(op);
    op_item.push_back(item);
    op_job.push_back(j);
  }
  if (is_write) {
    (void)client.write_batch(ops.data(), ops.size(), max_concurrency);  // per-op status folded into item_errors below
  } else {
    (void)client.read_batch(ops.data(), ops.size(), max_concurrency);  // per-op status folded into item_errors below
  }
  for (size_t j = 0; j < ops.size(); ++j) {
    if (ops[j].status != ErrorCode::OK && item_errors[op_item[j]] == ErrorCode::OK)
      item_errors[op_item[j]] = ops[j].status;
    if (wire_crcs && ops[j].status == ErrorCode::OK) (*wire_crcs)[op_job[j]] = ops[j].crc;
  }
}

// Runs the device jobs as ONE provider batch; when the whole batch fails,
// retries per job so one poisoned item cannot sink the rest, recording
// errors into per-item slots.
inline void run_device_jobs(transport::TransportClient& client, const BatchJobs& jobs, bool is_write,
                     std::vector<ErrorCode>& item_errors) {
  if (jobs.device.empty()) return;
  if (transport::shard_io_batch(client, jobs.device.data(), jobs.device.size(), is_write) ==
      ErrorCode::OK)
    return;
  for (size_t j = 0; j < jobs.device.size(); ++j) {
    if (item_errors[jobs.device_item[j]] != ErrorCode::OK) continue;
    if (auto ec = transport::shard_io_batch(client, &jobs.device[j], 1, is_write);
        ec != ErrorCode::OK)
      item_errors[jobs.device_item[j]] = ec;
  }
}


}  // namespace btpu::client
