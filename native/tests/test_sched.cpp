// Schedule-exploration suite (docs/CORRECTNESS.md §10):
//
//   Sched.*        — high-value concurrency fixtures re-run across N seeded
//                    PCT schedules per run (BTPU_SCHED_SEEDS; BTPU_SCHED_SEED
//                    pins one for replay). These are the interleaving-
//                    sensitive fixtures that used to lean on real-time
//                    sleeps — under the scheduler, time is virtual and the
//                    schedule is the input.
//   SchedDfs.*     — bounded-EXHAUSTIVE model check of the four lock-free
//                    kernels (flight-recorder slot claim, histogram stripes,
//                    span-ring seqlock, AtomicAccessStamp): every
//                    interleaving of a 2-thread bounded fixture is
//                    enumerated and the linearizability/torn-read invariants
//                    checked; each test prints its explored-schedule count
//                    and FAILS on truncation.
//   SchedVictim.*  — fixtures the planted-mutant matrix drives in child
//                    processes. With no mutant armed they are plain passing
//                    tests in every build.
//   SchedMutants.* — the planted-mutant validation matrix: re-inject 4
//                    historical concurrency bugs (BTPU_SCHED_MUTANT) and
//                    require the hunter to find each within a fixed seed
//                    budget, then replay the printed seed 3/3.
//
// In builds without BTPU_SCHED the hooks compile to nothing: fixtures run
// once, free-scheduled, and the DFS/matrix tests print a notice and pass.
#include <sys/wait.h>
#include <unistd.h>

#include <algorithm>
#include <atomic>
#include <cstdio>
#include <cstring>
#include <filesystem>
#include <functional>
#include <string>
#include <thread>
#include <vector>

#include "btest.h"
#include "btpu/alloc/pool_allocator.h"
#include "btpu/cache/object_cache.h"
#include "btpu/client/client.h"
#include "btpu/client/embedded.h"
#include "btpu/common/admission.h"
#include "btpu/common/circuit_breaker.h"
#include "btpu/common/env.h"
#include "btpu/common/flight_recorder.h"
#include "btpu/common/histogram.h"
#include "btpu/common/sched.h"
#include "btpu/common/trace.h"
#include "btpu/keystone/keystone.h"
#include "btpu/coord/mem_coordinator.h"
#include "btpu/rpc/rpc_client.h"
#include "btpu/rpc/rpc_server.h"
#include "btpu/transport/transport.h"

using namespace btpu;
using namespace btpu::client;
using namespace btpu::coord;
using namespace btpu::cache;

namespace {

// Runs `fixture` under a seeded PCT schedule per seed in [1, N] (N =
// BTPU_SCHED_SEEDS, default `default_seeds`; BTPU_SCHED_SEED pins exactly
// one — the replay path). Stops at the first failing seed and prints the
// replay line. Without BTPU_SCHED the fixture runs once, free.
void run_seeds(const char* what, uint32_t default_seeds, uint32_t threads,
               uint32_t pct_steps, const std::function<void()>& fixture) {
  if (!sched::compiled_in()) {
    fixture();
    return;
  }
  const uint64_t pinned = env_u64("BTPU_SCHED_SEED", 0);
  // Clamp to >= 1: env_u64 parses garbage (and "0") as 0, and a campaign
  // that runs ZERO schedules yet prints [ OK ] is the pass-without-running
  // lie the sched-smoke leg's SKIP-never-PASS rule exists to prevent.
  const uint64_t n = std::max<uint64_t>(1, env_u64("BTPU_SCHED_SEEDS", default_seeds));
  const uint64_t first = pinned ? pinned : 1;
  const uint64_t last = pinned ? pinned : n;
  for (uint64_t seed = first; seed <= last; ++seed) {
    const bool failed_before = btest::current_failed();
    {
      sched::RunOptions ro;
      ro.seed = seed;
      ro.threads = threads;
      ro.pct_steps = pct_steps;
      sched::Run run(ro);
      fixture();
    }
    if (!failed_before && btest::current_failed()) {
      std::fprintf(stderr,
                   "  [sched] %s FAILED at seed %llu — BTPU_SCHED_SEED=%llu "
                   "./btpu_tests --filter=... replays it\n",
                   what, static_cast<unsigned long long>(seed),
                   static_cast<unsigned long long>(seed));
      return;
    }
  }
}

std::vector<uint8_t> pattern(uint64_t size, uint8_t seed) {
  std::vector<uint8_t> data(size);
  for (uint64_t i = 0; i < size; ++i) data[i] = static_cast<uint8_t>(i * 131 + seed);
  return data;
}

}  // namespace

// ===========================================================================
// Sched.* — seeded PCT campaigns over the interleaving-sensitive fixtures
// ===========================================================================

BTEST(Sched, AdmissionGateAdmitReleaseShedRaces) {
  // The AdmissionGate under every arrival/release order the scheduler can
  // produce: at most max_inflight in the gate at any instant, every verdict
  // accounted, nothing parked at the end. (This is also the semantic model
  // of the uring parking lot, which mirrors the gate's adaptive LIFO.)
  run_seeds("admission", 8, 3, 128, [] {
    AdmissionGate::Options opts;
    opts.max_inflight = 1;
    opts.max_queue = 1;
    AdmissionGate gate(opts);
    std::atomic<int> inside{0};
    std::atomic<int> admitted{0}, shed{0};
    auto body = [&](uint32_t id) {
      sched::Enroll enroll(id);
      const auto verdict = gate.admit(Deadline::infinite());
      if (verdict == AdmissionGate::Verdict::kAdmitted) {
        const int n = inside.fetch_add(1, std::memory_order_relaxed) + 1;
        BT_EXPECT(n <= 1);  // the gate's whole contract
        admitted.fetch_add(1, std::memory_order_relaxed);
        BTPU_SCHED_YIELD();
        inside.fetch_sub(1, std::memory_order_relaxed);
        gate.release();
      } else {
        BT_EXPECT(verdict == AdmissionGate::Verdict::kShed);
        shed.fetch_add(1, std::memory_order_relaxed);
      }
    };
    std::thread a(body, 0), b(body, 1), c(body, 2);
    a.join();
    b.join();
    c.join();
    BT_EXPECT_EQ(admitted.load() + shed.load(), 3);
    BT_EXPECT(admitted.load() >= 1);  // someone always gets through
    BT_EXPECT_EQ(gate.inflight(), 0u);
    BT_EXPECT_EQ(gate.queued(), 0ull);
  });
}

BTEST(Sched, AdmissionGateWaiterDeadlineRaces) {
  // A queued waiter with a deadline vs a slow holder: under the scheduler
  // the timeout is virtual (fires only when the schedule says so), so every
  // outcome — admitted before expiry, expired in queue — is enumerated
  // across seeds instead of being a wall-clock accident.
  run_seeds("admission-deadline", 8, 2, 128, [] {
    AdmissionGate::Options opts;
    opts.max_inflight = 1;
    opts.max_queue = 4;
    AdmissionGate gate(opts);
    std::atomic<int> holder_done{0};
    auto holder = [&] {
      sched::Enroll enroll(0);
      BT_EXPECT(gate.admit(Deadline::infinite()) == AdmissionGate::Verdict::kAdmitted);
      BTPU_SCHED_YIELD();
      gate.release();
      holder_done.store(1, std::memory_order_relaxed);
    };
    auto waiter = [&] {
      sched::Enroll enroll(1);
      const auto verdict = gate.admit(Deadline::after_ms(30));
      BT_EXPECT(verdict == AdmissionGate::Verdict::kAdmitted ||
                verdict == AdmissionGate::Verdict::kDeadline);
      if (verdict == AdmissionGate::Verdict::kAdmitted) gate.release();
    };
    std::thread a(holder), b(waiter);
    a.join();
    b.join();
    BT_EXPECT_EQ(gate.inflight(), 0u);
    BT_EXPECT_EQ(gate.queued(), 0ull);  // a dead waiter removed itself
  });
}

BTEST(Sched, CircuitBreakerHalfOpenProbeRaces) {
  // Port of Robust.CircuitBreakerTripHalfOpenRecover minus the sleeps:
  // open_ms=0 makes the cooldown purely schedule-driven, and the invariant
  // that HALF_OPEN admits exactly half_open_probes concurrent probes must
  // hold under EVERY interleaving of the racing allow() calls.
  run_seeds("breaker-halfopen", 8, 2, 128, [] {
    CircuitBreaker::Options opts;
    opts.failure_threshold = 1;
    opts.open_ms = 0;  // cooldown elapses immediately: schedule decides
    opts.half_open_probes = 1;
    CircuitBreaker breaker(opts);
    breaker.record_failure();  // trip
    std::atomic<int> probes{0};
    auto prober = [&](uint32_t id) {
      sched::Enroll enroll(id);
      if (breaker.allow()) probes.fetch_add(1, std::memory_order_relaxed);
    };
    std::thread a(prober, 0), b(prober, 1);
    a.join();
    b.join();
    // Exactly one concurrent caller wins the probe slot, never both.
    BT_EXPECT_EQ(probes.load(), 1);
    BT_EXPECT(breaker.state() == CircuitBreaker::State::kHalfOpen);
    // The probe's verdict closes or re-opens; no schedule may wedge it.
    breaker.record_success(100);
    BT_EXPECT(breaker.state() == CircuitBreaker::State::kClosed);
  });
}

BTEST(Sched, HedgeFirstWinsLoserDrains) {
  // Port of EndToEnd.HedgedReadFirstWinsUnderSlowReplica: no fault-injected
  // 300ms replica — the SCHEDULE decides whether the primary finishes
  // before the hedge trigger (a virtual timeout under sched) fires. Every
  // seed explores a different win/lose/drain interleaving; the invariants
  // (correct bytes, one latency sample per logical read, destructor drains
  // the loser safely) must hold in all of them.
  EmbeddedCluster cluster(EmbeddedClusterOptions::simple(2, 8 << 20));
  BT_ASSERT(cluster.start() == ErrorCode::OK);
  const auto data = pattern(32 * 1024, 7);
  {
    auto setup = cluster.make_client(ClientOptions());
    WorkerConfig cfg;
    cfg.replication_factor = 2;
    cfg.max_workers_per_copy = 1;
    BT_ASSERT(setup->put("sched/hedge", data.data(), data.size(), cfg) == ErrorCode::OK);
  }
  run_seeds("hedge", 8, 1, 256, [&] {
    std::thread t([&] {
      sched::Enroll enroll(0);
      ClientOptions copts;
      copts.hedge_reads = true;
      copts.hedge_delay_ms = 1;  // value irrelevant under sched: virtual time
      auto client = cluster.make_client(copts);
      const size_t samples_before = client->read_latency().samples();
      auto back = client->get("sched/hedge");
      BT_ASSERT_OK(back);
      BT_EXPECT(back.value() == data);
      // First-wins, counted once — whichever side won this schedule.
      BT_EXPECT_EQ(client->read_latency().samples(), samples_before + 1);
      client.reset();  // destructor drains any in-flight loser
    });
    t.join();
  });
}

BTEST(Sched, WalGroupCommitLeaderHandoff) {
  // Three writers over the group-commit WAL: leader election, ride-along
  // batching, and leader handoff are all decided by the schedule. Invariant:
  // every acked put is readable, and at least one covering fdatasync
  // happened (acked == durable all the way down).
  static std::atomic<int> invocation{0};
  run_seeds("wal-group-commit", 6, 3, 512, [] {
    const std::string dir = "/tmp/btpu-sched-wal-" + std::to_string(::getpid()) + "-" +
                            std::to_string(invocation.fetch_add(1));
    {
      DurabilityOptions opts{dir, /*fsync=*/true, 4096, /*group_commit_us=*/500};
      MemCoordinator coord(opts);
      auto writer = [&](uint32_t id) {
        sched::Enroll enroll(id);
        const std::string key = "k" + std::to_string(id);
        BT_EXPECT_OK(coord.put(key, "v" + std::to_string(id)));
      };
      std::thread a(writer, 0), b(writer, 1), c(writer, 2);
      a.join();
      b.join();
      c.join();
      for (int i = 0; i < 3; ++i) {
        auto got = coord.get("k" + std::to_string(i));
        BT_ASSERT_OK(got);
        BT_EXPECT_EQ(got.value(), "v" + std::to_string(i));
      }
      BT_EXPECT(coord.wal_sync_count() >= 1);
    }
    std::error_code ec;
    std::filesystem::remove_all(dir, ec);
  });
}

BTEST(Sched, KeystoneSlotCommitVsRemoveRaces) {
  // Keystone hammer, miniature: a put_start/put_complete pipeline racing a
  // remover on the same key across every schedule. Legal outcomes are
  // exactly {exists with full placements, removed}; anything torn —
  // half-spliced placements, counters that disagree — fails.
  run_seeds("keystone-slot", 6, 2, 1024, [] {
    keystone::KeystoneService ks(
        [] {
          KeystoneConfig c;
          c.gc_interval_sec = 3600;
          c.health_check_interval_sec = 3600;
          c.worker_heartbeat_ttl_sec = 3600;
          return c;
        }(),
        nullptr);
    BT_ASSERT(ks.initialize() == ErrorCode::OK);
    std::vector<uint8_t> memory(1 << 20);
    auto server = transport::make_transport_server(TransportKind::LOCAL);
    BT_EXPECT_OK(server->start("", 0));
    auto reg = server->register_region(memory.data(), memory.size(), "p0");
    BT_ASSERT(reg.ok());
    keystone::WorkerInfo w;
    w.worker_id = "w0";
    w.address = "local:w0";
    BT_EXPECT_OK(ks.register_worker(w));
    MemoryPool pool;
    pool.id = "p0";
    pool.node_id = "w0";
    pool.size = memory.size();
    pool.storage_class = StorageClass::RAM_CPU;
    pool.remote = reg.value();
    BT_EXPECT_OK(ks.register_memory_pool(pool));

    WorkerConfig cfg;
    cfg.replication_factor = 1;
    cfg.max_workers_per_copy = 1;
    auto putter = [&] {
      sched::Enroll enroll(0);
      auto placed = ks.put_start("contested", 4096, cfg);
      if (!placed.ok()) return;  // remover raced the start: legal
      BTPU_SCHED_YIELD();
      const ErrorCode done = ks.put_complete("contested");
      // The remover may have erased the pending object: both verdicts legal.
      BT_EXPECT(done == ErrorCode::OK || done == ErrorCode::OBJECT_NOT_FOUND);
    };
    auto remover = [&] {
      sched::Enroll enroll(1);
      const ErrorCode removed = ks.remove_object("contested");
      BT_EXPECT(removed == ErrorCode::OK || removed == ErrorCode::OBJECT_NOT_FOUND);
    };
    std::thread a(putter), b(remover);
    a.join();
    b.join();
    // Whatever interleaved, the end state is coherent: either the object
    // exists with its full 4096 bytes placed, or it is gone.
    auto exists = ks.object_exists("contested");
    BT_ASSERT_OK(exists);
    if (exists.value()) {
      auto copies = ks.get_workers("contested");
      BT_ASSERT_OK(copies);
      uint64_t total = 0;
      for (const auto& c : copies.value())
        for (const auto& s : c.shards) total += s.length;
      BT_EXPECT_EQ(total, 4096ull);
    }
  });
}

BTEST(Sched, CacheFillInvalidateCoherence) {
  // ObjectCache under racing fill/invalidate/lookup: a hit must always be
  // version-coherent (the bytes filled under that exact version), and a
  // newer resident version must never be clobbered by an older fill.
  run_seeds("cache", 8, 2, 256, [] {
    ObjectCache cache(1 << 20);
    const auto now = ObjectCache::Clock::now();
    const auto lease = now + std::chrono::hours(1);
    auto b1 = std::make_shared<const std::vector<uint8_t>>(pattern(512, 1));
    auto b2 = std::make_shared<const std::vector<uint8_t>>(pattern(512, 2));
    const ObjectVersion v1{1, 1}, v2{1, 2};
    auto filler = [&] {
      sched::Enroll enroll(0);
      cache.fill("k", v1, 0, b1, lease);
      auto hit = cache.lookup("k");
      if (hit.outcome == ObjectCache::Outcome::kHit) {
        // Version/bytes pairing is atomic: v1 serves b1, v2 serves b2.
        BT_EXPECT((hit.version == v1 && hit.bytes == b1) ||
                  (hit.version == v2 && hit.bytes == b2));
      }
    };
    auto mover = [&] {
      sched::Enroll enroll(1);
      cache.invalidate("k");
      cache.fill("k", v2, 0, b2, lease);
    };
    std::thread a(filler), b(mover);
    a.join();
    b.join();
    // v2 is the newest stamped version: the final resident entry is either
    // v2 (the usual case) or absent/v1 only if the v2 fill lost to an
    // invalidate that never happened — i.e. never: v2's fill is last in
    // both threads' orders only in SOME schedules, so allow v1 or v2 but
    // never a mixed pairing.
    auto peeked = cache.peek("k");
    if (peeked.outcome != ObjectCache::Outcome::kMiss) {
      BT_EXPECT((peeked.version == v1 && peeked.bytes == b1) ||
                (peeked.version == v2 && peeked.bytes == b2));
    }
  });
}

// ===========================================================================
// SchedDfs.* — exhaustive model check of the four lock-free kernels
// ===========================================================================

BTEST(Sched, PoolsanQuarantineChurn) {
  // The pool sanitizer's alloc/quarantine/drain state machine under every
  // interleaving the scheduler can produce: concurrent carve/free churn
  // against one tracked pool must never convict (no false positives), and
  // every generation stamp a thread reads for its OWN live extent must
  // validate. The annotated allocator + shadow mutexes are the preemption
  // points; a lost update between free's shadow-then-map two-step and
  // allocate's map-then-shadow stamp would surface as a conviction or a
  // failed carve-after-drain here.
  if (!poolsan::compiled_in() || !poolsan::armed()) {
    std::printf("  [sched] poolsan not compiled in/armed — fixture skipped\n");
    return;
  }
  run_seeds("poolsan-churn", 8, 3, 192, [] {
    MemoryPool pool;
    pool.id = "sched-poolsan";
    pool.node_id = "n";
    pool.size = 64 * 1024;
    pool.storage_class = StorageClass::RAM_CPU;
    pool.remote = {TransportKind::LOCAL, "local:sched-poolsan", 0x1000, "", "", "", 0};
    const auto before = poolsan::counters();
    ::setenv("BTPU_POOLSAN_QUARANTINE_BYTES", "4096", 1);  // cycle hard
    {
      alloc::PoolAllocator pa(pool, /*poolsan_track=*/true);
      auto body = [&](uint32_t id) {
        sched::Enroll enroll(id);
        for (int i = 0; i < 3; ++i) {
          auto r = pa.allocate(1024 + 512 * id);
          BTPU_SCHED_YIELD();
          if (!r) continue;  // transient pressure is legal; convictions are not
          const auto loc = pa.to_memory_location(*r);
          BT_EXPECT(loc.extent_gen != 0);  // own live extent always stamped
          pa.free(*r, "sched-churn");
        }
      };
      std::thread a(body, 0), b(body, 1), c(body, 2);
      a.join();
      b.join();
      c.join();
    }
    ::unsetenv("BTPU_POOLSAN_QUARANTINE_BYTES");
    const auto after = poolsan::counters();
    BT_EXPECT_EQ(after.convictions, before.convictions);  // zero false positives
  });
}

namespace {

// Every DFS test reports its explored-schedule count and hard-fails on
// truncation — a silently bounded "exhaustive" check is worse than none.
void report_dfs(const char* what, const sched::ExploreResult& result) {
  if (!sched::compiled_in()) {
    std::printf("  [sched] dfs %s: hooks not compiled in — fixture ran once, free\n", what);
    return;
  }
  std::printf("  [sched] dfs %s: %llu schedules explored (complete=%d, max_decisions=%llu)\n",
              what, static_cast<unsigned long long>(result.schedules),
              result.complete ? 1 : 0,
              static_cast<unsigned long long>(result.max_decisions));
  BT_EXPECT(result.complete);  // the bounded space was EXHAUSTED
  BT_EXPECT(result.schedules >= 2);
}

// Parses `"field":<u64>` out of a JSON-lines dump.
bool json_u64(const std::string& line, const char* field, uint64_t& out) {
  const std::string needle = std::string("\"") + field + "\":";
  const size_t at = line.find(needle);
  if (at == std::string::npos) return false;
  out = std::strtoull(line.c_str() + at + needle.size(), nullptr, 10);
  return true;
}

}  // namespace

BTEST(SchedDfs, FlightRecorderSeqlock) {
  // 2 threads, bounded ops: one writer records two generation-stamped
  // events (every payload field = g), one dumper snapshots concurrently.
  // Invariant: every event the dump PUBLISHES is single-generation — the
  // seqlock bracket must discard in-flight slots, never emit a mixed one.
  const auto result = sched::explore_dfs({.threads = 2}, [] {
    flight::Recorder rec(64, 1);
    auto writer = [&] {
      sched::Enroll enroll(0);
      for (uint64_t g = 1; g <= 2; ++g) rec.record(flight::Ev::kRetry, g, g, g, g * 1000);
    };
    auto dumper = [&] {
      sched::Enroll enroll(1);
      const std::string dump = rec.dump_json();
      size_t start = 0;
      while (start < dump.size()) {
        size_t end = dump.find('\n', start);
        if (end == std::string::npos) end = dump.size();
        const std::string line = dump.substr(start, end - start);
        start = end + 1;
        if (line.empty()) continue;
        uint64_t a0 = 0, a1 = 0;
        BT_EXPECT(json_u64(line, "a0", a0));
        BT_EXPECT(json_u64(line, "a1", a1));
        BT_EXPECT_EQ(a0, a1);  // mixed-generation payload = seqlock broken
        BT_EXPECT(a0 == 1 || a0 == 2);
        char want_trace[32];
        std::snprintf(want_trace, sizeof(want_trace), "\"trace\":\"%016llx\"",
                      static_cast<unsigned long long>(a0));
        BT_EXPECT(line.find(want_trace) != std::string::npos);
      }
    };
    std::thread w(writer), d(dumper);
    w.join();
    d.join();
    // Quiescent: both events are visible and consistent.
    BT_EXPECT_EQ(rec.recorded(), 2ull);
  });
  report_dfs("flight-recorder", result);
}

BTEST(SchedDfs, HistogramStripes) {
  // Writer records two 1us samples; reader snapshots twice mid-flight.
  // Invariants: snapshots are monotonic, count never exceeds the true
  // total, and sum lags count by at most the one in-flight sample (the
  // documented bucket-then-sum window).
  const auto result = sched::explore_dfs({.threads = 2}, [] {
    hist::Histogram h;
    auto writer = [&] {
      sched::Enroll enroll(0);
      h.record_us(1);
      h.record_us(1);
    };
    auto reader = [&] {
      sched::Enroll enroll(1);
      const auto s1 = h.snapshot();
      const auto s2 = h.snapshot();
      BT_EXPECT(s1.count <= s2.count);  // monotone
      BT_EXPECT(s1.sum_us <= s2.sum_us);
      for (const auto& s : {s1, s2}) {
        BT_EXPECT(s.count <= 2);
        // The window runs BOTH ways and the DFS proved it: sum lags count
        // by at most the one in-flight sample (bucket added, sum not yet),
        // and sum may LEAD count when a sample lands between the reader's
        // bucket fold and its later sum fold — the first draft asserted
        // "sum never leads" and the exhaustive enumeration refuted it.
        BT_EXPECT(s.sum_us <= 2);                 // never exceeds the true total
        BT_EXPECT(s.sum_us + 1 >= s.count);       // lags by <= 1 in-flight
      }
    };
    std::thread w(writer), r(reader);
    w.join();
    r.join();
    const auto fin = h.snapshot();
    BT_EXPECT_EQ(fin.count, 2ull);
    BT_EXPECT_EQ(fin.sum_us, 2ull);
  });
  report_dfs("histogram", result);
}

BTEST(SchedDfs, SpanRingSeqlock) {
  // Writer records two spans with generation-stamped fields; reader dumps
  // concurrently. Published lines must pair name/trace/start/dur from ONE
  // generation; in-flight slots are dropped, never torn.
  const auto result = sched::explore_dfs({.threads = 2}, [] {
#if defined(BTPU_SCHED)
    trace::span_ring_reset_for_test();
#endif
    auto writer = [&] {
      sched::Enroll enroll(0);
      trace::record_remote_span("sched.dfs.a", 0xA1, 0, 1000, 2000);   // dur 1us
      trace::record_remote_span("sched.dfs.b", 0xB2, 0, 3000, 7000);   // dur 4us
    };
    auto reader = [&] {
      sched::Enroll enroll(1);
      const std::string dump = trace::dump_spans_json();
      size_t start = 0;
      while (start < dump.size()) {
        size_t end = dump.find('\n', start);
        if (end == std::string::npos) end = dump.size();
        const std::string line = dump.substr(start, end - start);
        start = end + 1;
        if (line.empty()) continue;
        const bool is_a = line.find("\"sched.dfs.a\"") != std::string::npos;
        const bool is_b = line.find("\"sched.dfs.b\"") != std::string::npos;
        if (!is_a && !is_b) {
          // Hookless builds cannot reset the global ring, so earlier tests'
          // spans are legitimately present; under BTPU_SCHED the reset ran
          // and a foreign line would mean the reset (or the ring) is broken.
          BT_EXPECT(!sched::compiled_in());
          continue;
        }
        if (is_a) {
          BT_EXPECT(line.find("\"trace\":\"00000000000000a1\"") != std::string::npos);
          BT_EXPECT(line.find("\"start_us\":1.000") != std::string::npos);
          BT_EXPECT(line.find("\"dur_us\":1.000") != std::string::npos);
        } else if (is_b) {
          BT_EXPECT(line.find("\"trace\":\"00000000000000b2\"") != std::string::npos);
          BT_EXPECT(line.find("\"start_us\":3.000") != std::string::npos);
          BT_EXPECT(line.find("\"dur_us\":4.000") != std::string::npos);
        }
      }
    };
    std::thread w(writer), r(reader);
    w.join();
    r.join();
  });
  report_dfs("span-ring", result);
}

BTEST(SchedDfs, AtomicAccessStamp) {
  // Writer stores two stamps; reader loads twice. Invariants: every load is
  // one of the written values (no torn 64-bit reads), and the reader's two
  // loads respect the stamp's modification order (read-read coherence).
  using TimePoint = keystone::AtomicAccessStamp::TimePoint;
  const TimePoint t0{};  // default epoch
  const TimePoint t1{TimePoint::duration(100)};
  const TimePoint t2{TimePoint::duration(200)};
  const auto result = sched::explore_dfs({.threads = 2}, [&] {
    keystone::AtomicAccessStamp stamp;
    auto writer = [&] {
      sched::Enroll enroll(0);
      stamp.store(t1);
      stamp.store(t2);
    };
    auto reader = [&] {
      sched::Enroll enroll(1);
      const TimePoint first = stamp.load();
      const TimePoint second = stamp.load();
      for (const TimePoint& tp : {first, second})
        BT_EXPECT(tp == t0 || tp == t1 || tp == t2);
      BT_EXPECT(first <= second);  // modification order is monotone here
    };
    std::thread w(writer), r(reader);
    w.join();
    r.join();
    BT_EXPECT(stamp.load() == t2);
  });
  report_dfs("atomic-access-stamp", result);
}

// ===========================================================================
// SchedVictim.* — planted-mutant victims (plain passing tests, mutant off)
// ===========================================================================

BTEST(SchedVictim, HedgeNotifyAfterUnlock) {
  // Victim for mutant "hedge_notify_after_unlock" (the pre-PR-5 drain
  // race): hedged reads with the client destroyed while a loser attempt is
  // in flight. Mutant armed + the right schedule = the loser notifies a
  // destroyed hedge_cv_ (ASan heap-use-after-free).
  EmbeddedCluster cluster(EmbeddedClusterOptions::simple(2, 8 << 20));
  BT_ASSERT(cluster.start() == ErrorCode::OK);
  const auto data = pattern(16 * 1024, 3);
  {
    auto setup = cluster.make_client(ClientOptions());
    WorkerConfig cfg;
    cfg.replication_factor = 2;
    cfg.max_workers_per_copy = 1;
    BT_ASSERT(setup->put("victim/hedge", data.data(), data.size(), cfg) == ErrorCode::OK);
  }
  sched::RunOptions ro;
  ro.seed = env_u64("BTPU_SCHED_SEED", 1);
  ro.threads = 1;
  ro.pct_steps = 256;
  sched::Run run(ro);
  std::thread t([&] {
    sched::Enroll enroll(0);
    for (int i = 0; i < 3; ++i) {
      ClientOptions copts;
      copts.hedge_reads = true;
      copts.hedge_delay_ms = 1;
      auto client = cluster.make_client(copts);
      auto back = client->get("victim/hedge");
      BT_ASSERT_OK(back);
      BT_EXPECT(back.value() == data);
      client.reset();  // destroy while the loser may still be in flight
    }
  });
  t.join();
}

BTEST(SchedVictim, RpcSwapUnlocked) {
  // Victim for mutant "rpc_swap_unlocked" (the pre-PR-3 rotate_keystone
  // UAF): RPC calls through an unpinned raw client racing rotations that
  // destroy it. Mutant armed + the right schedule = ASan heap-use-after-free
  // inside the call.
  keystone::KeystoneService ks(
      [] {
        KeystoneConfig c;
        c.gc_interval_sec = 3600;
        c.health_check_interval_sec = 3600;
        return c;
      }(),
      nullptr);
  BT_ASSERT(ks.initialize() == ErrorCode::OK);
  rpc::KeystoneRpcServer server(ks, "127.0.0.1", 0);
  BT_ASSERT(server.start() == ErrorCode::OK);

  ClientOptions copts;
  copts.keystone_address = server.endpoint();
  copts.keystone_fallbacks = {server.endpoint()};  // rotation cycles, stays live
  client::ObjectClient client(copts);
  BT_ASSERT(client.connect() == ErrorCode::OK);

  sched::RunOptions ro;
  ro.seed = env_u64("BTPU_SCHED_SEED", 1);
  ro.threads = 2;
  ro.pct_steps = 512;
  sched::Run run(ro);
  std::thread caller([&] {
    sched::Enroll enroll(0);
    for (int i = 0; i < 4; ++i) BT_EXPECT_OK(client.object_exists("victim"));
  });
  std::thread rotator([&] {
    sched::Enroll enroll(1);
#if defined(BTPU_SCHED)
    for (int i = 0; i < 4; ++i) client.rotate_keystone_for_test();
#endif
  });
  caller.join();
  rotator.join();
}

BTEST(SchedVictim, AdmissionLostWakeup) {
  // Victim for mutant "admission_lost_wakeup": a released holder must wake
  // the queued waiter. Mutant armed + the waiter-queued schedule = the
  // waiter parks forever and the scheduler's watchdog convicts a deadlock
  // (seed printed, abort).
  AdmissionGate::Options opts;
  opts.max_inflight = 1;
  opts.max_queue = 4;
  AdmissionGate gate(opts);
  sched::RunOptions ro;
  ro.seed = env_u64("BTPU_SCHED_SEED", 1);
  ro.threads = 2;
  ro.pct_steps = 64;
  sched::Run run(ro);
  std::thread holder([&] {
    sched::Enroll enroll(0);
    BT_EXPECT(gate.admit(Deadline::infinite()) == AdmissionGate::Verdict::kAdmitted);
    BTPU_SCHED_YIELD();
    gate.release();
  });
  std::thread waiter([&] {
    sched::Enroll enroll(1);
    if (gate.admit(Deadline::infinite()) == AdmissionGate::Verdict::kAdmitted)
      gate.release();
  });
  holder.join();
  waiter.join();
  BT_EXPECT_EQ(gate.inflight(), 0u);
}

BTEST(SchedVictim, DemoteSkipEpochCheck) {
  // Victim for mutant "demote_skip_epoch_check" (the ABA/lost-update class
  // the placement epoch exists to kill): a tier-pressure demotion's
  // unlocked byte move racing a remove + re-put of the same key. Mutant
  // armed + the right schedule = the old object's staged placements are
  // spliced over the re-put and the read-back mismatches.
  KeystoneConfig cfg;
  cfg.gc_interval_sec = 3600;
  cfg.health_check_interval_sec = 3600;
  cfg.worker_heartbeat_ttl_sec = 3600;
  cfg.high_watermark = 0.5;
  cfg.eviction_ratio = 0.2;
  keystone::KeystoneService ks(cfg, nullptr);
  BT_ASSERT(ks.initialize() == ErrorCode::OK);

  std::vector<uint8_t> hot_mem(100 * 1024), cold_mem(1 << 20);
  auto hot_srv = transport::make_transport_server(TransportKind::LOCAL);
  auto cold_srv = transport::make_transport_server(TransportKind::LOCAL);
  BT_EXPECT_OK(hot_srv->start("", 0));
  BT_EXPECT_OK(cold_srv->start("", 0));
  auto hot_reg = hot_srv->register_region(hot_mem.data(), hot_mem.size(), "hot-pool");
  auto cold_reg = cold_srv->register_region(cold_mem.data(), cold_mem.size(), "cold-pool");
  BT_ASSERT(hot_reg.ok() && cold_reg.ok());
  for (const auto& [id, node, size, cls, reg] :
       {std::tuple{"hot-pool", "hot", hot_mem.size(), StorageClass::HBM_TPU, hot_reg.value()},
        std::tuple{"cold-pool", "cold", cold_mem.size(), StorageClass::SSD,
                   cold_reg.value()}}) {
    keystone::WorkerInfo w;
    w.worker_id = node;
    w.address = std::string("local:") + node;
    BT_EXPECT_OK(ks.register_worker(w));
    MemoryPool pool;
    pool.id = id;
    pool.node_id = node;
    pool.size = size;
    pool.storage_class = cls;
    pool.remote = reg;
    BT_EXPECT_OK(ks.register_memory_pool(pool));
  }

  WorkerConfig wc;
  wc.replication_factor = 1;
  wc.max_workers_per_copy = 1;
  wc.preferred_classes = {StorageClass::HBM_TPU};
  auto io = transport::make_transport_client();
  const auto old_payload = pattern(20 * 1024, 5);
  auto put_key = [&](const char* key, const std::vector<uint8_t>& payload) {
    auto placed = ks.put_start(key, payload.size(), wc);
    BT_ASSERT_OK(placed);
    uint64_t off = 0;
    for (const auto& shard : placed.value()[0].shards) {
      const auto& mem = std::get<MemoryLocation>(shard.location);
      BT_ASSERT(io->write(shard.remote, mem.remote_addr, mem.rkey, payload.data() + off,
                          shard.length) == ErrorCode::OK);
      off += shard.length;
    }
    BT_EXPECT_OK(ks.put_complete(key));
  };
  // 60% of the hot tier; "b" untouched => the LRU demotion victim.
  for (const char* key : {"a", "b", "c"}) put_key(key, old_payload);
  std::this_thread::sleep_for(std::chrono::milliseconds(5));
  (void)ks.get_workers("a");
  (void)ks.get_workers("c");

  const auto new_payload = pattern(24 * 1024, 9);
  {
    sched::RunOptions ro;
    ro.seed = env_u64("BTPU_SCHED_SEED", 1);
    ro.threads = 2;
    ro.pct_steps = 4096;
    ro.max_steps = 1u << 22;
    sched::Run run(ro);
    std::thread demoter([&] {
      sched::Enroll enroll(0);
      ks.run_health_check_once();  // demotes the over-watermark LRU ("b")
    });
    std::thread reputter([&] {
      sched::Enroll enroll(1);
      const ErrorCode removed = ks.remove_object("b");
      BT_EXPECT(removed == ErrorCode::OK || removed == ErrorCode::OBJECT_NOT_FOUND);
      put_key("b", new_payload);
    });
    demoter.join();
    reputter.join();
  }
  // The re-put is the last acked mutation: "b" must read back as
  // new_payload, whatever the demotion did.
  auto copies = ks.get_workers("b");
  BT_ASSERT_OK(copies);
  uint64_t total = 0;
  for (const auto& s : copies.value()[0].shards) total += s.length;
  BT_ASSERT(total == new_payload.size());
  std::vector<uint8_t> back(new_payload.size(), 0);
  uint64_t off = 0;
  for (const auto& shard : copies.value()[0].shards) {
    const auto& mem = std::get<MemoryLocation>(shard.location);
    BT_ASSERT(io->read(shard.remote, mem.remote_addr, mem.rkey, back.data() + off,
                       shard.length) == ErrorCode::OK);
    off += shard.length;
  }
  BT_EXPECT(back == new_payload);
}

// ===========================================================================
// SchedMutants.* — the planted-mutant validation matrix
// ===========================================================================

namespace {

// Runs one victim test in a child process with the mutant + seed armed.
// Returns the child's exit verdict: 0 = clean, nonzero = the hunter
// detected the bug (assertion failure, sanitizer abort, or the scheduler's
// deadlock watchdog).
int run_victim_child(const char* victim, const char* mutant, uint64_t seed) {
  char exe[4096];
  const ssize_t n = ::readlink("/proc/self/exe", exe, sizeof(exe) - 1);
  if (n <= 0) return -1;
  exe[n] = '\0';
  const pid_t pid = ::fork();
  if (pid < 0) return -1;
  if (pid == 0) {
    // Child: quiet stdout/stderr (the matrix prints the verdicts), arm the
    // mutant + seed, and keep the deadlock watchdog snappy.
    if (FILE* null = std::fopen("/dev/null", "w")) {
      ::dup2(::fileno(null), 1);
      ::dup2(::fileno(null), 2);
    }
    if (mutant != nullptr) ::setenv("BTPU_SCHED_MUTANT", mutant, 1);
    ::setenv("BTPU_SCHED_SEED", std::to_string(seed).c_str(), 1);
    ::setenv("BTPU_SCHED_HANG_MS", "400", 1);
    const std::string filter = std::string("--filter=SchedVictim.") + victim;
    ::execl(exe, exe, filter.c_str(), static_cast<char*>(nullptr));
    ::_exit(127);
  }
  int status = 0;
  if (::waitpid(pid, &status, 0) != pid) return -1;
  if (WIFEXITED(status)) return WEXITSTATUS(status);
  if (WIFSIGNALED(status)) return 128 + WTERMSIG(status);
  return -1;
}

struct PlantedMutant {
  const char* name;       // BTPU_SCHED_MUTANT value
  const char* victim;     // SchedVictim suffix
  bool needs_asan;        // detection manifests as a heap UAF
};

}  // namespace

BTEST(SchedMutants, MatrixDetectsPlantedRaces) {
  if (!sched::compiled_in()) {
    std::printf("  [sched] mutant matrix: hooks not compiled in — SKIP (run `make sched`)\n");
    return;
  }
  if (env_u64("BTPU_SCHED_MUTANTS", 1) == 0) {
    std::printf("  [sched] mutant matrix: disabled via BTPU_SCHED_MUTANTS=0 — SKIP\n");
    return;
  }
  const uint64_t budget = env_u64("BTPU_SCHED_MUTANT_BUDGET", 150);
  const PlantedMutant mutants[] = {
      {"hedge_notify_after_unlock", "HedgeNotifyAfterUnlock", /*needs_asan=*/true},
      {"rpc_swap_unlocked", "RpcSwapUnlocked", /*needs_asan=*/true},
      {"admission_lost_wakeup", "AdmissionLostWakeup", /*needs_asan=*/false},
      {"demote_skip_epoch_check", "DemoteSkipEpochCheck", /*needs_asan=*/false},
  };
  // gcc defines __SANITIZE_ADDRESS__; clang answers through __has_feature —
  // miss either and the two strongest (UAF-class) mutants silently SKIP on
  // a fully ASan-instrumented build.
#if defined(__SANITIZE_ADDRESS__)
  constexpr bool have_asan = true;
#elif defined(__has_feature)
#if __has_feature(address_sanitizer)
  constexpr bool have_asan = true;
#else
  constexpr bool have_asan = false;
#endif
#else
  constexpr bool have_asan = false;
#endif
  for (const auto& m : mutants) {
    // Sanity: the victim passes with the mutant OFF (seeded, scheduled).
    BT_EXPECT_EQ(run_victim_child(m.victim, nullptr, 1), 0);
    if (m.needs_asan && !have_asan) {
      std::printf("  [sched] mutant %-28s SKIP (UAF class: needs the asan tree)\n", m.name);
      continue;
    }
    uint64_t detected_seed = 0;
    for (uint64_t seed = 1; seed <= budget; ++seed) {
      if (run_victim_child(m.victim, m.name, seed) != 0) {
        detected_seed = seed;
        break;
      }
    }
    if (detected_seed == 0) {
      std::printf("  [sched] mutant %-28s NOT DETECTED within %llu seeds\n", m.name,
                  static_cast<unsigned long long>(budget));
      BT_EXPECT(detected_seed != 0);
      continue;
    }
    // Deterministic replay: the printed seed reproduces the failure 3/3.
    int replays = 0;
    for (int k = 0; k < 3; ++k)
      if (run_victim_child(m.victim, m.name, detected_seed) != 0) ++replays;
    std::printf("  [sched] mutant %-28s detected at seed %llu, replay %d/3\n", m.name,
                static_cast<unsigned long long>(detected_seed), replays);
    BT_EXPECT_EQ(replays, 3);
  }
}
