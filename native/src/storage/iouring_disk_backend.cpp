// io_uring disk tier (NVME/SSD): kernel-async reads/writes on one backing
// file, raw io_uring syscalls (liburing is not in this image).
//
// Parity target: reference src/worker/storage/iouring_disk_backend.cpp.
// Deliberate change: one pre-sized backing file with allocator offsets
// instead of the reference's file-per-shard scheme (iouring_disk_backend.cpp
// :326-343 synthesized fake remote addrs from path hashes and created files
// synchronously anyway) — a flat file keeps the same placement math as every
// other tier and avoids per-shard metadata ops on the hot path.
// O_DIRECT (default for NVME) bypasses page cache; unaligned edges go
// through a bounce buffer. Falls back to pread/pwrite when io_uring is
// unavailable (e.g. sandboxed kernels).
#include <fcntl.h>
#include <linux/io_uring.h>
#include <sys/mman.h>
#include <sys/syscall.h>
#include <unistd.h>

#include <atomic>
#include <cerrno>
#include <cstring>
#include <filesystem>
#include <mutex>

#include "backend_base.h"
#include "btpu/common/log.h"
#include "btpu/common/poolsan.h"

namespace btpu::storage {

namespace {

int io_uring_setup(unsigned entries, io_uring_params* p) {
  return static_cast<int>(::syscall(__NR_io_uring_setup, entries, p));
}
int io_uring_enter(int ring_fd, unsigned to_submit, unsigned min_complete, unsigned flags) {
  return static_cast<int>(
      ::syscall(__NR_io_uring_enter, ring_fd, to_submit, min_complete, flags, nullptr, 0));
}

// Minimal single-issuer ring: submit one SQE, wait for its CQE.
class MiniRing {
 public:
  ~MiniRing() { close_ring(); }

  bool init(unsigned entries = 32) {
    io_uring_params params{};
    ring_fd_ = io_uring_setup(entries, &params);
    if (ring_fd_ < 0) return false;

    sq_ring_sz_ = params.sq_off.array + params.sq_entries * sizeof(unsigned);
    cq_ring_sz_ = params.cq_off.cqes + params.cq_entries * sizeof(io_uring_cqe);
    sq_ring_ = ::mmap(nullptr, sq_ring_sz_, PROT_READ | PROT_WRITE, MAP_SHARED | MAP_POPULATE,
                      ring_fd_, IORING_OFF_SQ_RING);
    cq_ring_ = ::mmap(nullptr, cq_ring_sz_, PROT_READ | PROT_WRITE, MAP_SHARED | MAP_POPULATE,
                      ring_fd_, IORING_OFF_CQ_RING);
    sqes_sz_ = params.sq_entries * sizeof(io_uring_sqe);
    sqes_ = static_cast<io_uring_sqe*>(::mmap(nullptr, sqes_sz_, PROT_READ | PROT_WRITE,
                                              MAP_SHARED | MAP_POPULATE, ring_fd_,
                                              IORING_OFF_SQES));
    if (sq_ring_ == MAP_FAILED || cq_ring_ == MAP_FAILED || sqes_ == MAP_FAILED) {
      close_ring();
      return false;
    }
    auto* sq = static_cast<uint8_t*>(sq_ring_);
    sq_head_ = reinterpret_cast<std::atomic<unsigned>*>(sq + params.sq_off.head);
    sq_tail_ = reinterpret_cast<std::atomic<unsigned>*>(sq + params.sq_off.tail);
    sq_mask_ = *reinterpret_cast<unsigned*>(sq + params.sq_off.ring_mask);
    sq_array_ = reinterpret_cast<unsigned*>(sq + params.sq_off.array);
    auto* cq = static_cast<uint8_t*>(cq_ring_);
    cq_head_ = reinterpret_cast<std::atomic<unsigned>*>(cq + params.cq_off.head);
    cq_tail_ = reinterpret_cast<std::atomic<unsigned>*>(cq + params.cq_off.tail);
    cq_mask_ = *reinterpret_cast<unsigned*>(cq + params.cq_off.ring_mask);
    cqes_ = reinterpret_cast<io_uring_cqe*>(cq + params.cq_off.cqes);
    return true;
  }

  // Blocking single-op submit+wait. Returns op result (>=0) or -errno.
  int32_t run(uint8_t opcode, int fd, void* buf, uint32_t len, uint64_t file_offset) {
    MutexLock lock(mutex_);
    // ordering: relaxed — only this (mutex-serialized) submitter advances the tail; the kernel side synchronizes via the release store below.
    const unsigned tail = sq_tail_->load(std::memory_order_relaxed);
    const unsigned idx = tail & sq_mask_;
    io_uring_sqe& sqe = sqes_[idx];
    std::memset(&sqe, 0, sizeof(sqe));
    sqe.opcode = opcode;
    sqe.fd = fd;
    sqe.addr = reinterpret_cast<uint64_t>(buf);
    sqe.len = len;
    sqe.off = file_offset;
    sq_array_[idx] = idx;
    // ordering: release — publishes the fully-written SQE before the kernel observes the new tail.
    sq_tail_->store(tail + 1, std::memory_order_release);

    if (io_uring_enter(ring_fd_, 1, 1, IORING_ENTER_GETEVENTS) < 0) return -errno;

    // ordering: acquire (both) — pairs with the kernel's release publish of the CQE so res below reads the completed value.
    const unsigned head = cq_head_->load(std::memory_order_acquire);
    if (head == cq_tail_->load(std::memory_order_acquire)) return -EIO;
    const io_uring_cqe& cqe = cqes_[head & cq_mask_];
    const int32_t res = cqe.res;
    // ordering: release — returns the consumed CQE slot to the kernel after the read above.
    cq_head_->store(head + 1, std::memory_order_release);
    return res;
  }

  bool ok() const { return ring_fd_ >= 0; }

 private:
  void close_ring() {
    if (sq_ring_ && sq_ring_ != MAP_FAILED) ::munmap(sq_ring_, sq_ring_sz_);
    if (cq_ring_ && cq_ring_ != MAP_FAILED) ::munmap(cq_ring_, cq_ring_sz_);
    if (sqes_ && sqes_ != reinterpret_cast<io_uring_sqe*>(MAP_FAILED)) ::munmap(sqes_, sqes_sz_);
    if (ring_fd_ >= 0) ::close(ring_fd_);
    sq_ring_ = cq_ring_ = nullptr;
    sqes_ = nullptr;
    ring_fd_ = -1;
  }

  int ring_fd_{-1};
  void* sq_ring_{nullptr};
  void* cq_ring_{nullptr};
  io_uring_sqe* sqes_{nullptr};
  size_t sq_ring_sz_{0}, cq_ring_sz_{0}, sqes_sz_{0};
  std::atomic<unsigned>*sq_head_{}, *sq_tail_{}, *cq_head_{}, *cq_tail_{};
  unsigned sq_mask_{0}, cq_mask_{0};
  unsigned* sq_array_{nullptr};
  io_uring_cqe* cqes_{nullptr};
  Mutex mutex_;
};

constexpr uint64_t kAlign = 512;

}  // namespace

class IoUringDiskBackend : public OffsetBackendBase {
 public:
  explicit IoUringDiskBackend(BackendConfig config) : OffsetBackendBase(std::move(config)) {}
  ~IoUringDiskBackend() override { shutdown(); }

  ErrorCode initialize() override {
    if (fd_ >= 0) return ErrorCode::INVALID_STATE;
    if (config_.path.empty()) return ErrorCode::MISSING_REQUIRED_FIELD;
    std::error_code fs_ec;
    std::filesystem::create_directories(
        std::filesystem::path(config_.path).parent_path(), fs_ec);

    int flags = O_CREAT | O_RDWR | O_CLOEXEC;
    if (config_.use_odirect) flags |= O_DIRECT;
    fd_ = ::open(config_.path.c_str(), flags, 0644);
    if (fd_ < 0 && config_.use_odirect) {
      // Filesystem without O_DIRECT support (tmpfs): fall back to buffered.
      LOG_WARN << "iouring backend: O_DIRECT unsupported on " << config_.path
               << ", using buffered I/O";
      odirect_active_ = false;
      fd_ = ::open(config_.path.c_str(), O_CREAT | O_RDWR | O_CLOEXEC, 0644);
    } else {
      odirect_active_ = config_.use_odirect;
    }
    if (fd_ < 0) return ErrorCode::INITIALIZATION_FAILED;
    if (::ftruncate(fd_, static_cast<off_t>(config_.capacity)) != 0) {
      ::close(fd_);
      fd_ = -1;
      return ErrorCode::INSUFFICIENT_SPACE;
    }
    ring_ = std::make_unique<MiniRing>();
    if (!ring_->init()) {
      LOG_WARN << "io_uring unavailable (" << std::strerror(errno)
               << "), falling back to pread/pwrite";
      ring_.reset();
    }
    if (odirect_active_) {
      bounce_.resize(1 << 20);
      if (posix_memalign(&bounce_aligned_, kAlign, bounce_.size()) != 0)
        return ErrorCode::OUT_OF_MEMORY;
    }
    return init_allocator();
  }

  void shutdown() override {
    if (fd_ >= 0) {
      ::close(fd_);
      fd_ = -1;
    }
    ring_.reset();
    if (bounce_aligned_) {
      std::free(bounce_aligned_);
      bounce_aligned_ = nullptr;
    }
  }

  void* base_address() const override { return nullptr; }  // served via read/write_at
  bool persistent() const override { return true; }

  // Region offset == file offset (flat backing file): the TCP uring engine
  // reads shards straight off this fd on its own ring.
  int direct_io_fd(bool* odirect) const override {
    if (odirect) *odirect = odirect_active_;
    return fd_;
  }

  ErrorCode write_at(uint64_t offset, const void* src, uint64_t len) override {
    return io_at(offset, const_cast<void*>(src), len, /*is_write=*/true);
  }
  ErrorCode read_at(uint64_t offset, void* dst, uint64_t len) override {
    return io_at(offset, dst, len, /*is_write=*/false);
  }

 private:
  // Aligned direct I/O when possible; bounce buffer for unaligned O_DIRECT.
  ErrorCode io_at(uint64_t offset, void* buf, uint64_t len, bool is_write) {
    if (fd_ < 0) return ErrorCode::INVALID_STATE;
    if (len > config_.capacity || offset > config_.capacity - len)
      return ErrorCode::MEMORY_ACCESS_ERROR;
    if (len == 0) return ErrorCode::OK;
#if defined(BTPU_POOLSAN)
    // No host mapping to resolve a span against (file-backed tier) — the
    // shadow-state check runs by pool name instead, so stale/quarantined
    // extents are convicted on this tier too.
    if (poolsan::armed()) {
      const ErrorCode verdict = poolsan::check_access(
          nullptr, config_.pool_id.c_str(), config_.capacity, offset, len, 0,
          is_write ? poolsan::Access::kWrite : poolsan::Access::kRead);
      if (verdict != ErrorCode::OK) return verdict;
    }
#endif

    const bool aligned = !odirect_active_ ||
                         ((offset % kAlign) == 0 && (len % kAlign) == 0 &&
                          (reinterpret_cast<uintptr_t>(buf) % kAlign) == 0);
    if (aligned) return raw_io(offset, buf, len, is_write);

    // Unaligned O_DIRECT: widen to aligned window through the bounce buffer.
    MutexLock lock(bounce_mutex_);
    uint64_t pos = offset;
    auto* user = static_cast<uint8_t*>(buf);
    uint64_t remaining = len;
    while (remaining > 0) {
      const uint64_t win_start = pos & ~(kAlign - 1);
      const uint64_t max_win = bounce_.size();
      uint64_t win_len = std::min<uint64_t>(max_win, ((pos + remaining) - win_start + kAlign - 1) &
                                                         ~(kAlign - 1));
      win_len = std::min(win_len, ((config_.capacity - win_start) & ~(kAlign - 1)));
      if (win_len == 0) return ErrorCode::MEMORY_ACCESS_ERROR;
      BTPU_RETURN_IF_ERROR(raw_io(win_start, bounce_aligned_, win_len, /*is_write=*/false));
      const uint64_t in_win = std::min(remaining, win_len - (pos - win_start));
      auto* window = static_cast<uint8_t*>(bounce_aligned_);
      if (is_write) {
        std::memcpy(window + (pos - win_start), user, in_win);
        BTPU_RETURN_IF_ERROR(raw_io(win_start, bounce_aligned_, win_len, /*is_write=*/true));
      } else {
        std::memcpy(user, window + (pos - win_start), in_win);
      }
      pos += in_win;
      user += in_win;
      remaining -= in_win;
    }
    return ErrorCode::OK;
  }

  ErrorCode raw_io(uint64_t offset, void* buf, uint64_t len, bool is_write) {
    auto* p = static_cast<uint8_t*>(buf);
    uint64_t done = 0;
    while (done < len) {
      const uint32_t chunk = static_cast<uint32_t>(std::min<uint64_t>(len - done, 1u << 30));
      int32_t rc;
      if (ring_) {
        rc = ring_->run(is_write ? IORING_OP_WRITE : IORING_OP_READ, fd_, p + done, chunk,
                        offset + done);
      } else {
        rc = static_cast<int32_t>(is_write ? ::pwrite(fd_, p + done, chunk, offset + done)
                                           : ::pread(fd_, p + done, chunk, offset + done));
        if (rc < 0) rc = -errno;
      }
      if (rc < 0) {
        LOG_ERROR << "disk io failed at " << offset + done << ": " << std::strerror(-rc);
        return ErrorCode::MEMORY_ACCESS_ERROR;
      }
      if (rc == 0) {
        // Read past EOF inside capacity (sparse file): zero-fill.
        if (!is_write) {
          std::memset(p + done, 0, len - done);
          return ErrorCode::OK;
        }
        return ErrorCode::MEMORY_ACCESS_ERROR;
      }
      done += static_cast<uint64_t>(rc);
    }
    return ErrorCode::OK;
  }

  int fd_{-1};
  bool odirect_active_{false};
  std::unique_ptr<MiniRing> ring_;
  std::vector<uint8_t> bounce_;  // sizing only; aligned buffer is below
  void* bounce_aligned_{nullptr};
  Mutex bounce_mutex_;
};

std::unique_ptr<StorageBackend> make_iouring_disk_backend(const BackendConfig& config) {
  return std::make_unique<IoUringDiskBackend>(config);
}

// ---- factory (all storage classes wired; reference gap fixed) -------------

std::unique_ptr<StorageBackend> make_ram_backend(const BackendConfig& config);
std::unique_ptr<StorageBackend> make_cxl_backend(const BackendConfig& config);
std::unique_ptr<StorageBackend> make_hbm_backend(const BackendConfig& config);
std::unique_ptr<StorageBackend> make_mmap_disk_backend(const BackendConfig& config);

std::unique_ptr<StorageBackend> create_storage_backend(const BackendConfig& config) {
  BackendConfig cfg = config;
  switch (config.storage_class) {
    case StorageClass::RAM_CPU:
      return make_ram_backend(cfg);
    case StorageClass::CXL_MEMORY:
    case StorageClass::CXL_TYPE2_DEVICE:
      return make_cxl_backend(cfg);
    case StorageClass::HBM_TPU:
      return make_hbm_backend(cfg);
    case StorageClass::NVME:
      if (config.path.empty()) return nullptr;
      cfg.use_odirect = true;
      return make_iouring_disk_backend(cfg);
    case StorageClass::SSD:
      if (config.path.empty()) return nullptr;
      return make_iouring_disk_backend(cfg);
    case StorageClass::HDD:
      if (config.path.empty()) return nullptr;
      return make_mmap_disk_backend(cfg);
    default:
      LOG_ERROR << "no backend for storage class "
                << storage_class_name(config.storage_class);
      return nullptr;
  }
}

}  // namespace btpu::storage
