"""JAX-backed HBM provider: TPU device buffers as the top storage tier.

The native HbmBackend talks to a C ABI provider table (hbm_provider.h v3).
This module implements that table with JAX: a region is ONE device-resident
uint8 buffer shaped (n_pages, PAGE); reads/writes are host<->device
transfers.

Design (device links pay per-operation latency — one PJRT call each — so
the whole point is few, large ops):

* A scatter/gather batch (write_batch/read_batch) is decomposed host-side
  into fixed-size pages. Writes build ONE flat (total_pages, PAGE) host
  array covering every region's pages, move it with ONE device_put, then
  run one jitted `lax.scan` per touched region that merges each page into
  the region buffer on device (masked by the page's valid byte range, so
  arbitrary offsets/lengths work without read-modify-write on the host).
  The region buffer is donated, so updates are in place.
* Reads run one jitted scan per region gathering the touched pages into an
  (m, PAGE) array, issue all device->host copies asynchronously, then
  scatter bytes to the destination buffers on host.
* jit executables are bounded: page counts are padded to powers of two
  (padding entries have empty valid ranges, i.e. no-ops), so each region
  shape compiles at most log2(max_pages) variants per direction.
* Writes are asynchronous (dispatch only); flush() blocks until every
  accepted write is durably in device memory, which is what the native
  client calls before put_complete.

Replaces the round-1 design (per-1MiB-chunk copy-on-write lists, one ctypes
+ jit dispatch per chunk) that measured 0.01 GB/s on a real TPU: per-object
device ops were latency-bound. With batching, throughput is limited by the
host<->device link, not the framework.

Host-view mode: when a device's buffers are host-addressable (CPU backend;
any unified-memory platform), regions detect it at alloc with a
write-through probe and serve ALL their I/O by plain memcpy through a
stable zero-copy host view — no per-op device dispatch at all, which is
what makes the cross-process staged lane to a CPU-device worker run at
memory speed. Real TPU HBM is not host-addressable; those regions keep the
dispatch-thin jit paths (single-run ops compute their page indices on
device from a scalar start, skipping the index/meta transfers).
"""

from __future__ import annotations

import ctypes
import os
import threading
from functools import partial
from typing import Any, Callable, TypeAlias
import warnings

import numpy as np
import numpy.typing as npt

from blackbird_tpu import native
from blackbird_tpu.native import lib

# One I/O vector as the C ABI hands it over: (region_id, offset, buf
# pointer, length). A region's bookkeeping dict and the staging machinery
# stay Any-valued — they hold jax arrays, devices, locks, and executors,
# none of which have stable typed surfaces.
_Vec: TypeAlias = "tuple[int, int, int, int]"
_Region: TypeAlias = "dict[str, Any]"

_u64 = ctypes.c_uint64

_ALLOC_FN = ctypes.CFUNCTYPE(ctypes.c_int, ctypes.c_void_p, ctypes.c_char_p, _u64,
                             ctypes.POINTER(_u64))
_FREE_FN = ctypes.CFUNCTYPE(ctypes.c_int, ctypes.c_void_p, _u64)
_WRITE_FN = ctypes.CFUNCTYPE(ctypes.c_int, ctypes.c_void_p, _u64, _u64, ctypes.c_void_p, _u64)
_READ_FN = ctypes.CFUNCTYPE(ctypes.c_int, ctypes.c_void_p, _u64, _u64, ctypes.c_void_p, _u64)
_AVAIL_FN = ctypes.CFUNCTYPE(_u64, ctypes.c_void_p, ctypes.c_char_p)


class _IoVec(ctypes.Structure):
    _fields_ = [
        ("region_id", _u64),
        ("offset", _u64),
        ("buf", ctypes.c_void_p),
        ("len", _u64),
    ]


_BATCH_FN = ctypes.CFUNCTYPE(ctypes.c_int, ctypes.c_void_p, ctypes.POINTER(_IoVec), _u64)
_FLUSH_FN = ctypes.CFUNCTYPE(ctypes.c_int, ctypes.c_void_p)
_COPY_FN = ctypes.CFUNCTYPE(ctypes.c_int, ctypes.c_void_p, _u64, _u64, _u64, _u64, _u64)
# NOTE: the out-buffer is c_void_p, NOT c_char_p — ctypes converts c_char_p
# callback arguments to an immutable bytes COPY, so writes through it would
# never reach the caller's buffer.
_FABRIC_ADDR_FN = ctypes.CFUNCTYPE(ctypes.c_int, ctypes.c_void_p, ctypes.c_void_p, _u64)
_FABRIC_OFFER_FN = ctypes.CFUNCTYPE(ctypes.c_int, ctypes.c_void_p, _u64, _u64, _u64, _u64)
_FABRIC_PULL_FN = ctypes.CFUNCTYPE(ctypes.c_int, ctypes.c_void_p, ctypes.c_char_p, _u64,
                                   _u64, _u64, _u64)
_HOST_VIEW_FN = ctypes.CFUNCTYPE(ctypes.c_void_p, ctypes.c_void_p, _u64)


class _ProviderStruct(ctypes.Structure):
    # Must match BtpuHbmProviderV5 (hbm_provider.h) field for field: the V3
    # table, the device-fabric entries, then the host-view entry.
    _fields_ = [
        ("ctx", ctypes.c_void_p),
        ("alloc_region", _ALLOC_FN),
        ("free_region", _FREE_FN),
        ("write", _WRITE_FN),
        ("read", _READ_FN),
        ("available", _AVAIL_FN),
        ("write_batch", _BATCH_FN),
        ("read_batch", _BATCH_FN),
        ("flush", _FLUSH_FN),
        ("copy", _COPY_FN),
        ("fabric_address", _FABRIC_ADDR_FN),
        ("fabric_offer", _FABRIC_OFFER_FN),
        ("fabric_pull", _FABRIC_PULL_FN),
        ("host_view_base", _HOST_VIEW_FN),
    ]


def _pow2_at_least(n: int) -> int:
    return 1 << max(0, (n - 1).bit_length())


class JaxHbmProvider:
    """Page-batched device-buffer regions managed through JAX."""

    def __init__(self, page_bytes: int = 64 << 10, max_staging_bytes: int = 32 << 20,
                 host_view: str | bool = "auto") -> None:
        import jax

        # Donation is an optimization (in-place region updates); backends
        # that cannot honor it (CPU) fall back to a copy and warn on every
        # dispatch. Registered at construction (not import) and scoped to
        # jax's exact message so the application's warning config is
        # otherwise untouched.
        warnings.filterwarnings(
            "ignore", message="Some donated buffers were not usable")

        self._jax = jax
        # Host-view mode: when a device's buffers are host-addressable
        # (the CPU backend — and by extension any unified-memory platform
        # where np.asarray of a committed array is a zero-copy alias), every
        # region I/O is served by plain memcpy through a stable host view of
        # the region buffer, with ZERO per-op device dispatches. The region's
        # jax buffer is never donated in this mode, so the view stays valid
        # for the region's lifetime, and jit consumers (none in steady state)
        # still see view writes because the memory IS the device memory.
        # Real TPUs are not host-addressable: the probe fails and the jit
        # scatter/gather paths below serve as before. "auto" probes at alloc;
        # False forces the device path (used by tests to keep it covered);
        # BTPU_HBM_HOST_VIEW=0 force-disables process-wide.
        if os.environ.get("BTPU_HBM_HOST_VIEW") == "0":
            host_view = False
        self._host_view = host_view
        self.page_bytes = page_bytes
        # Upper bound on the flat host->device staging array per flush round;
        # larger batches are split so the device never needs more than this
        # much transient memory on top of the regions themselves — and so a
        # multi-round batch pipelines: fill of round N+1 overlaps the
        # transfer of round N (two staging slots per device).
        self.max_staging_bytes = max_staging_bytes
        self._lock = threading.Lock()            # region table
        self._regions: dict[int, _Region] = {}
        self._view_regions = 0                   # count with a host view
        self._next_id = 1
        self._struct: _ProviderStruct | None = None  # built in register()
        self._dirty: set[int] = set()            # regions with in-flight writes
        self.copy_calls = 0                      # device-to-device copies served
        # Reusable host staging buffers: re-faulting a fresh multi-MiB array
        # every batch cost ~20 ms/64 MiB. Keyed by device; entry =
        # {slots: [{buf, fences} x2], next, lock} — two slots so round N+1's
        # fill overlaps round N's transfer. _staging_lock guards only the dict; each
        # entry's lock is held across that device's fill+dispatch, so
        # concurrent writers to ONE device serialize (its link forces that
        # anyway) while different devices proceed in parallel. Lock order:
        # entry lock may take a region lock inside; nothing takes an entry
        # lock while holding a region lock (synchronize releases region
        # locks first).
        self._staging: dict[Any, dict[str, Any]] = {}
        self._staging_lock = threading.Lock()
        # Cross-process device fabric: the shared per-process transfer
        # endpoint (server + connections + offer GC) lives in TransferLink,
        # one lifecycle for this provider and fabric.py's FabricClient.
        from blackbird_tpu.transferlink import TransferLink

        self._link = TransferLink(jax)
        self.fabric_pulls = 0

        P = page_bytes
        jnp = jax.numpy

        # Fully vectorized page merge: ONE gather + masked select + ONE
        # scatter per batch (a lax.scan variant measured ~0.6 s/batch on a
        # v5e — sequential carry updates serialize on device). Padding rows
        # carry an out-of-bounds index and are dropped by the scatter, so
        # pow2-padded page counts keep the jit cache at log2(max_pages)
        # executables per region shape. Duplicate page indices within one
        # batch would scatter in undefined order — the host-side caller
        # routes those batches through the per-vec fallback.
        def write_pages(region: Any, pages: Any, meta: Any) -> Any:
            idx, v0, v1 = meta[0], meta[1], meta[2]
            cur = region.at[idx].get(mode="clip")
            io = jnp.arange(P, dtype=jnp.int32)
            mask = (io >= v0[:, None]) & (io < v1[:, None])
            merged = jnp.where(mask, pages, cur)
            return region.at[idx].set(merged, mode="drop")

        self._write_fn = jax.jit(write_pages, donate_argnums=0)
        self._read_fn = jax.jit(lambda region, idx: region.at[idx].get(mode="clip"))
        # Staging-reuse fence: a tiny slice of a freshly written region
        # buffer. It executes after the merge kernel, we hold its only
        # reference (so unlike the region buffer itself it can never be
        # donated away at another op's dispatch), and blocking on it proves
        # the merge — and therefore the staging read — completed.
        self._fence_fn = jax.jit(lambda r: r[:1, :1])
        # Single-run fast paths: the serving-shape op is ONE contiguous
        # whole-page run per region (a 1 MiB staged-lane read/write). Those
        # skip the index/meta arrays entirely — the page index is computed ON
        # DEVICE from a scalar start, saving one host->device transfer per
        # op (device links pay per-operation latency). Cached per padded run
        # length, so the jit cache stays log2-bounded like the idx paths.
        self._read_run_fns: dict[int, Any] = {}
        self._write_run_fns: dict[int, Any] = {}

    def _read_run_fn(self, m: int) -> Any:
        fn = self._read_run_fns.get(m)
        if fn is None:
            jnp = self._jax.numpy
            fn = self._read_run_fns[m] = self._jax.jit(
                lambda r, p0: r.at[p0 + jnp.arange(m, dtype=jnp.int32)].get(mode="clip"))
        return fn

    def _write_run_fn(self, m: int) -> Any:
        fn = self._write_run_fns.get(m)
        if fn is None:
            jnp = self._jax.numpy

            def set_run(r: Any, pages: Any, p0: Any, n_valid: Any) -> Any:
                k = jnp.arange(m, dtype=jnp.int32)
                # Padding rows get an out-of-bounds index -> dropped.
                idx = jnp.where(k < n_valid, p0 + k, r.shape[0])
                return r.at[idx].set(pages, mode="drop")

            fn = self._write_run_fns[m] = self._jax.jit(set_run, donate_argnums=0)
        return fn

    # -- device helpers ----------------------------------------------------

    def _device_for(self, device_id: str) -> Any:
        devices = self._jax.local_devices()
        if ":" in device_id:
            try:
                ordinal = int(device_id.split(":", 1)[1])
                if 0 <= ordinal < len(devices):
                    return devices[ordinal]
            except ValueError:
                pass
        return devices[0]

    # -- provider callbacks ------------------------------------------------

    def _alloc(self, _ctx: Any, device_id: bytes | None, size: int,
               out_id: Any) -> int:
        try:
            jnp = self._jax.numpy
            device = self._device_for(device_id.decode() if device_id else "tpu:0")
            n_pages = max(1, -(-size // self.page_bytes))
            with self._jax.default_device(device):
                buf = jnp.zeros((n_pages, self.page_bytes), dtype=jnp.uint8)
            # Commit to the device: an uncommitted array has UnspecifiedValue
            # sharding, which makes the first write_pages call compile a
            # second executable once the donated output comes back committed.
            buf = self._jax.device_put(buf, device)
            buf.block_until_ready()
            view = self._probe_host_view(buf, device, n_pages)
            with self._lock:
                region_id = self._next_id
                self._next_id += 1
                if view is not None:
                    self._view_regions += 1
                self._regions[region_id] = {
                    "buf": buf,
                    "size": size,
                    "n_pages": n_pages,
                    "device": device,
                    # Zero-copy writable alias of the device buffer, or None.
                    # When set, ALL I/O for this region is plain memcpy and
                    # the buffer is never donated (see __init__ notes).
                    "view": view,
                    # Serializes dispatches per region: the write path donates
                    # the buffer, so a concurrent reader must never pick up a
                    # reference that is about to be invalidated.
                    "lock": threading.Lock(),
                }
            out_id[0] = region_id
            return 0
        except Exception:  # noqa: BLE001 - must not raise through the C ABI
            return 1

    def _probe_host_view(self, buf: Any, device: Any,
                         n_pages: int) -> npt.NDArray[np.uint8] | None:
        """A writable zero-copy alias of `buf`'s memory, or None.

        Gated on the platform claiming host-addressable buffers, then PROVEN
        by a write-through probe: a byte written through the candidate view
        must be observed by a jit read of the buffer (np.asarray may return a
        cached COPY on some stacks, which would silently disconnect the view
        from device memory — only the round trip is trusted)."""
        if self._host_view is False or device.platform != "cpu":
            return None
        try:
            ro = np.asarray(buf)
            if not ro.flags["C_CONTIGUOUS"] or ro.size != n_pages * self.page_bytes:
                return None
            ptr = ro.__array_interface__["data"][0]
            view = np.ctypeslib.as_array(
                ctypes.cast(ptr, ctypes.POINTER(ctypes.c_uint8)),
                shape=(n_pages * self.page_bytes,))
            view[0] = 0xAA
            seen = int(np.asarray(self._fence_fn(buf)).reshape(())[()])
            view[0] = 0
            return view if seen == 0xAA else None
        except Exception:  # noqa: BLE001 - fall back to the device path
            return None

    def _free(self, _ctx: Any, region_id: int) -> int:
        with self._lock:
            self._dirty.discard(region_id)
            region = self._regions.pop(region_id, None)
            if region is not None and region["view"] is not None:
                self._view_regions -= 1
            return 0 if region is not None else 1

    # -- page decomposition (host-side, pure numpy) ------------------------

    def _decompose(
        self, vecs: list[_Vec],
    ) -> tuple[dict[int, _Region], dict[int, list[Any]]]:
        """Validates vecs and groups them by region.

        Returns {region_id: (region, spans)} where spans is a list of
        (page_idx, v0, v1, src) — src a numpy view of the host bytes for
        that page's valid range. Raises ValueError on any bad vec.
        """
        P = self.page_bytes
        with self._lock:
            regions = dict(self._regions)
        grouped: dict[int, list[Any]] = {}
        for region_id, offset, buf, length in vecs:
            region = regions.get(region_id)
            if region is None or offset + length > region["size"]:
                raise ValueError("bad region/range")
            if length == 0:
                continue
            host = np.ctypeslib.as_array(
                ctypes.cast(buf, ctypes.POINTER(ctypes.c_uint8)), shape=(length,))
            spans = grouped.setdefault(region_id, [])
            pos = 0
            while pos < length:
                page_idx = (offset + pos) // P
                v0 = (offset + pos) % P
                n = min(length - pos, P - v0)
                spans.append((page_idx, v0, v0 + n, host[pos : pos + n]))
                pos += n
        return regions, grouped

    @staticmethod
    def _join_pending(slot: dict[str, Any]) -> None:
        """Joins a slot's in-flight dispatch without consuming it (the
        result is cached, so a later join is free). Fences are appended by
        the dispatcher thread; a slot's fence list is only complete — and
        safe to drain destructively — after this returns. Exceptions were
        already raised to the write that owned the dispatch."""
        pending = slot.get("pending")
        if pending is not None:
            try:
                pending.result()
            except Exception:  # noqa: BLE001 - raised to its writer already
                pass

    @staticmethod
    def _await_fences(entry: dict[str, Any]) -> None:
        """Blocks until every fence for `entry`'s buffer has executed.

        Fences are never donated (this provider holds their only reference),
        so block_until_ready cannot see a deleted array; the guard stays for
        interpreter-shutdown robustness only. Caller holds entry["lock"] AND
        has joined the slot's pending dispatch (else the reassignment below
        could discard a fence being appended concurrently)."""
        for fence in entry["fences"]:
            try:
                fence.block_until_ready()
            except Exception:  # noqa: BLE001 - teardown only
                pass
        entry["fences"] = []

    def _staging_entry(self, dev: Any) -> dict[str, Any]:
        with self._staging_lock:
            entry = self._staging.get(dev)
            if entry is None:
                # TWO slots per device: round N+1 fills one buffer while
                # round N's transfer/merge still drains the other, so the
                # host staging pass overlaps the device link instead of
                # serializing with it (round size = max_staging_bytes).
                # The single-thread dispatcher is what makes the overlap
                # REAL on hardware backends: device_put there BLOCKS its
                # calling thread for the whole H2D (measured 22 ms / 32 MiB
                # on the tunneled TPU — async dispatch only covers compiled
                # computations, not host transfers), so transfers run on
                # this thread while the caller fills the next slot. One
                # thread per device also preserves round order (duplicate-
                # page chunks rely on rounds landing in sequence).
                from concurrent.futures import ThreadPoolExecutor  # noqa: PLC0415

                entry = self._staging[dev] = {
                    "slots": [{"buf": None, "fences": [], "pending": None}
                              for _ in range(2)],
                    "next": 0,
                    "lock": threading.Lock(),
                    "exec": ThreadPoolExecutor(
                        max_workers=1, thread_name_prefix="btpu-hbm-dispatch"),
                }
            return entry

    def _staging_for(self, entry: dict[str, Any], rows: int,
                     page_bytes: int) -> tuple[npt.NDArray[np.uint8], dict[str, Any]]:
        """A reusable (rows, page) host staging view for one device, plus
        the slot whose fences the caller must append its dispatches to.

        Before handing a slot's buffer out again we block on the fences of
        every computation that consumed it last time — not merely the
        device_put transfer: the CPU backend's device_put is ZERO-COPY (the
        device buffer aliases the staging memory), so the bytes are only
        safe to overwrite once the merge kernels that read them have
        finished. A slot's fences are appended by the dispatcher thread, so
        the slot's in-flight dispatch (`pending`) is joined FIRST — only
        then is the fence list complete. With two slots the wait only fires
        two rounds back — hidden under the intervening round's transfer.
        Caller holds entry["lock"]."""
        slot = entry["slots"][entry["next"]]
        entry["next"] = (entry["next"] + 1) % len(entry["slots"])
        self._join_pending(slot)
        slot["pending"] = None
        self._await_fences(slot)  # also covers an old buffer being replaced
        buf = slot["buf"]
        if buf is None or buf.shape[0] < rows or buf.shape[1] != page_bytes:
            buf = slot["buf"] = np.empty((rows, page_bytes), dtype=np.uint8)
        return buf[:rows], slot

    def _run_single_round(self, flat: Any, slot: dict[str, Any], region: _Region,
                          region_id: int, p0: int, n: int,
                          m_padded: int) -> None:
        """Dispatcher-thread body for the single-region single-run fast path
        (no meta array: the scatter index is p0 + arange on device)."""
        dev_flat = self._jax.device_put(flat, region["device"])
        with region["lock"]:
            region["buf"] = self._write_run_fn(m_padded)(
                region["buf"], dev_flat, np.int32(p0), np.int32(n))
            slot["fences"].append(self._fence_fn(region["buf"]))
        with self._lock:
            if region_id in self._regions:
                self._dirty.add(region_id)

    def _run_device_round(self, flat: Any, meta: Any, dev: Any,
                          layouts: list[Any], slot: dict[str, Any],
                          regions: dict[int, _Region]) -> None:
        """Dispatcher-thread body shared by the aligned and generic write
        paths: ONE H2D of the filled staging segment + metadata, then each
        region's donated merge over its slice, fence append, dirty mark."""
        jax = self._jax
        dev_flat = jax.device_put(flat, dev)
        dev_meta = jax.device_put(meta, dev)
        for region_id, start, m_padded, _spans in layouts:
            region = regions[region_id]
            if len(layouts) == 1:
                pages, pmeta = dev_flat, dev_meta  # no slicing dispatches
            else:
                pages = jax.lax.dynamic_slice_in_dim(dev_flat, start, m_padded,
                                                     axis=0)
                pmeta = jax.lax.dynamic_slice(dev_meta, (0, start), (3, m_padded))
            with region["lock"]:
                region["buf"] = self._write_fn(region["buf"], pages, pmeta)
                slot["fences"].append(self._fence_fn(region["buf"]))
            with self._lock:
                if region_id in self._regions:
                    self._dirty.add(region_id)

    def _dispatch(self, entry: dict[str, Any], slot: dict[str, Any],
                  fn: Callable[[], None], futures: list[Any]) -> None:
        """Queues `fn` (device_put + merge dispatches for one filled slot)
        on the device's dispatcher thread. The caller thread is then free to
        fill the next slot while this round's H2D occupies the link. Every
        write path JOINS its futures before returning (_join_dispatches):
        batch errors stay synchronous at the ABI, and a read issued after
        write_batch returns can never see a pre-merge region buffer."""
        fut = entry["exec"].submit(fn)
        slot["pending"] = fut
        futures.append(fut)

    @staticmethod
    def _join_dispatches(futures: list[Any]) -> None:
        err: Exception | None = None
        for fut in futures:  # settle ALL before raising: slots stay sane
            try:
                fut.result()
            except Exception as exc:  # noqa: BLE001
                err = err or exc
        if err is not None:
            raise err

    # -- aligned fast path -------------------------------------------------

    def _aligned_runs(
        self, vecs: list[_Vec], *, check_overlap: bool,
    ) -> tuple[dict[int, _Region], dict[int, list[Any]]] | None:
        """Groups whole-page-aligned vecs as (page0, n_pages, host_view) runs.

        Returns (regions, {region_id: [runs]}) when EVERY vec is page-aligned
        (allocator HBM placements are chunk-aligned, so real put/get batches
        always are) — or None to route through the generic span machinery.
        Writes also require non-overlapping runs per region (scatter order
        for duplicate pages is undefined)."""
        P = self.page_bytes
        with self._lock:
            regions = dict(self._regions)
        per_region: dict[int, list[Any]] = {}
        for region_id, offset, buf, length in vecs:
            if length == 0:
                continue
            if offset % P or length % P:
                return None
            region = regions.get(region_id)
            if region is None or offset + length > region["size"]:
                raise ValueError("bad region/range")
            host = np.ctypeslib.as_array(
                ctypes.cast(buf, ctypes.POINTER(ctypes.c_uint8)), shape=(length,))
            per_region.setdefault(region_id, []).append((offset // P, length // P, host))
        if check_overlap:
            for runs in per_region.values():
                ordered = sorted(r[:2] for r in runs)
                last_end = -1
                for p0, n in ordered:
                    if p0 < last_end:
                        return None
                    last_end = p0 + n
        return regions, per_region

    def _write_vecs_aligned(self, regions: dict[int, _Region],
                            per_region: dict[int, list[Any]]) -> None:
        """Whole-page batch write: one BULK staging copy per run (the
        generic span path below fills page by page in Python — on 64x1MiB
        batches that loop cost more than the copy itself), then the same
        one-device_put-one-scatter-per-region dispatch as the generic path.

        The staging buffer (not the caller's memory) feeds device_put: the
        write ABI promises sources may be reused the moment the call
        returns, and on the CPU backend device_put zero-copy ALIASES host
        memory until the merge kernel runs — aliasing caller buffers here
        would corrupt in-flight writes (see _staging_for).

        Rounds bound the staging footprint the same way the generic cap
        does."""
        P = self.page_bytes
        cap = max(1, self.max_staging_bytes // P)
        round_pr: dict[int, list[Any]] = {}
        count = 0
        futures: list[Any] = []

        def flush_round() -> None:
            nonlocal round_pr, count
            if round_pr:
                self._write_aligned_round(regions, round_pr, futures)
            round_pr, count = {}, 0

        try:
            for region_id, runs in per_region.items():
                for p0, n, host in runs:
                    pos = 0
                    while pos < n:
                        take = min(n - pos, cap - count)
                        if take == 0:
                            flush_round()
                            continue
                        round_pr.setdefault(region_id, []).append(
                            (p0 + pos, take, host[pos * P : (pos + take) * P]))
                        count += take
                        pos += take
            flush_round()
        finally:
            self._join_dispatches(futures)

    def _write_aligned_round(self, regions: dict[int, _Region],
                             per_region: dict[int, list[Any]],
                             futures: list[Any]) -> None:
        """Fills staging for one round on the CALLER thread, then queues the
        device work (H2D + merge dispatch) on the device's dispatcher thread
        — the caller immediately proceeds to fill the next round's slot, so
        on backends whose device_put blocks (real TPU) consecutive rounds
        pipeline fill(N+1) under transfer(N). _write_vecs_aligned joins the
        futures before returning."""
        jax = self._jax
        P = self.page_bytes
        if len(per_region) == 1:
            ((region_id, runs),) = per_region.items()
            if len(runs) == 1:
                # Single region, single contiguous run (the serving shape):
                # skip the meta array — the scatter index is p0 + arange
                # computed on device, bounded by n_valid so padding rows
                # drop. One staging fill, one device_put, one dispatch.
                p0, n, host = runs[0]
                region = regions[region_id]
                m_padded = _pow2_at_least(n)
                entry = self._staging_entry(region["device"])
                with entry["lock"]:
                    flat, slot = self._staging_for(entry, m_padded, P)
                    flat[:n] = host.reshape(n, P)
                    self._dispatch(
                        entry, slot,
                        partial(self._run_single_round, flat, slot, region,
                                region_id, p0, n, m_padded),
                        futures)
                return
        by_device: dict[Any, list[Any]] = {}
        for region_id, runs in per_region.items():
            by_device.setdefault(regions[region_id]["device"], []).append(
                (region_id, runs))
        for dev, entries in by_device.items():
            layouts: list[Any] = []  # (region_id, start_row, m_padded, runs)
            total_rows = 0
            for region_id, runs in entries:
                m_padded = _pow2_at_least(sum(n for _p0, n, _h in runs))
                layouts.append((region_id, total_rows, m_padded, runs))
                total_rows += m_padded
            entry = self._staging_entry(dev)
            with entry["lock"]:
                flat, slot = self._staging_for(entry, total_rows, P)
                meta = np.zeros((3, total_rows), dtype=np.int32)
                for region_id, start, m_padded, runs in layouts:
                    # Padding rows carry an out-of-bounds page index so the
                    # scatter drops them (mode='drop').
                    meta[0, start : start + m_padded] = regions[region_id]["n_pages"]
                    row = start
                    for p0, n, host in runs:
                        meta[0, row : row + n] = np.arange(p0, p0 + n, dtype=np.int32)
                        meta[2, row : row + n] = P  # full pages: v0=0, v1=P
                        flat[row : row + n] = host.reshape(n, P)  # ONE copy per run
                        row += n

                self._dispatch(
                    entry, slot,
                    partial(self._run_device_round, flat, meta, dev, layouts,
                            slot, regions),
                    futures)

    # -- host-view fast path -----------------------------------------------

    def _serve_view_vecs(self, vecs: list[_Vec], *, is_write: bool) -> list[_Vec]:
        """Serves vecs whose region has a host view; returns the remainder.

        Pure memcpy, no locks: writes are synchronous (nothing to flush) and
        concurrent overlapping ops are the client's contract, exactly as on
        the DRAM tier. Bounds are validated here because served vecs never
        reach the device-path validators. On platforms with no host-visible
        regions (real TPUs) this is a single counter check — the hot path
        pays no extra table copy or vec pass."""
        with self._lock:
            if self._view_regions == 0:
                return vecs
            regions = dict(self._regions)
        rest: list[_Vec] = []
        for vec in vecs:
            region_id, offset, buf, length = vec
            region = regions.get(region_id)
            if region is None or offset + length > region["size"]:
                raise ValueError("bad region/range")
            view = region["view"]
            if view is None:
                rest.append(vec)
                continue
            if length == 0:
                continue
            host = np.ctypeslib.as_array(
                ctypes.cast(buf, ctypes.POINTER(ctypes.c_uint8)), shape=(length,))
            if is_write:
                view[offset : offset + length] = host
            else:
                host[:] = view[offset : offset + length]
        return rest

    # -- batched write -----------------------------------------------------

    def _write_vecs(self, vecs: list[_Vec]) -> None:
        vecs = self._serve_view_vecs(vecs, is_write=True)
        if not vecs:
            return
        aligned = self._aligned_runs(vecs, check_overlap=True)
        if aligned is not None:
            self._write_vecs_aligned(*aligned)
            return
        jax = self._jax
        P = self.page_bytes
        regions, grouped = self._decompose(vecs)
        if not grouped:
            return
        # Scatter order is undefined for duplicate indices, so each dispatch
        # must touch every page at most once: split each region's span list
        # into ordered chunks with unique page indices (duplicates only occur
        # when one batch writes the same page twice — later chunks land in
        # later rounds, preserving write order).
        chunks: list[tuple[int, list[Any]]] = []
        for region_id, spans in grouped.items():
            seen: set[int] = set()
            cur: list[Any] = []
            for span in spans:
                if span[0] in seen:
                    chunks.append((region_id, cur))
                    cur, seen = [span], {span[0]}
                else:
                    cur.append(span)
                    seen.add(span[0])
            if cur:
                chunks.append((region_id, cur))
        # Pack chunks into rounds under the staging cap; a region appears at
        # most once per round (keeps its scatter indices unique). The cap is
        # accounted in POW2-PADDED rows — the staging array is padded per
        # region, so counting raw spans would let it grow to ~2x the cap.
        max_pages = max(1, self.max_staging_bytes // P)
        max_pages = 1 << (max_pages.bit_length() - 1)  # pow2 so splits fit
        rounds: list[dict[int, list[Any]]] = []
        current: dict[int, list[Any]] = {}
        count = 0
        for region_id, spans in chunks:
            if region_id in current or count + _pow2_at_least(len(spans)) > max_pages:
                if current:
                    rounds.append(current)
                current, count = {}, 0
            while len(spans) > max_pages:  # chunk alone exceeds the cap
                rounds.append({region_id: spans[:max_pages]})
                spans = spans[max_pages:]
            if spans:
                current[region_id] = spans
                count += _pow2_at_least(len(spans))
        if current:
            rounds.append(current)

        futures: list[Any] = []
        try:
            for round_spans in rounds:
                # Group regions by device; per device, build ONE flat (M, P)
                # host staging array covering every region's (padded) pages
                # and move it with ONE device_put on the device's dispatcher
                # thread (blocking H2D there overlaps the caller filling the
                # next round — same pipeline as the aligned path). Each
                # region then runs one donated scan over its segment of the
                # staging array.
                by_device: dict[Any, list[Any]] = {}
                for region_id, spans in round_spans.items():
                    dev = regions[region_id]["device"]
                    by_device.setdefault(dev, []).append((region_id, spans))
                for dev, entries in by_device.items():
                    layouts: list[Any] = []  # (region_id, start_row, m_padded, spans)
                    total = 0
                    for region_id, spans in entries:
                        m_padded = _pow2_at_least(len(spans))
                        layouts.append((region_id, total, m_padded, spans))
                        total += m_padded
                    entry = self._staging_entry(dev)
                    with entry["lock"]:
                        flat, slot = self._staging_for(entry, total, P)  # pad rows unused
                        meta = np.zeros((3, total), dtype=np.int32)  # idx / v0 / v1
                        for region_id, start, m_padded, spans in layouts:
                            # Padding rows carry an out-of-bounds page index
                            # so the scatter drops them (mode='drop').
                            meta[0, start : start + m_padded] = (
                                regions[region_id]["n_pages"])
                            for k, (page_idx, a, b, src) in enumerate(spans):
                                row = start + k
                                meta[0, row] = page_idx
                                meta[1, row] = a
                                meta[2, row] = b
                                flat[row, a:b] = src

                        self._dispatch(
                            entry, slot,
                            partial(self._run_device_round, flat, meta, dev,
                                    layouts, slot, regions),
                            futures)
        finally:
            self._join_dispatches(futures)

    # -- batched read ------------------------------------------------------

    def _read_vecs_aligned(self, regions: dict[int, _Region],
                           per_region: dict[int, list[Any]]) -> None:
        """Whole-page batch read: one gather dispatch per region, async D2H,
        then ONE vectorized copy per destination buffer (the generic span
        path below scatters page by page in Python)."""
        jax = self._jax
        P = self.page_bytes
        fetches: list[Any] = []  # (out device array, runs)
        for region_id, runs in per_region.items():
            region = regions[region_id]
            total = sum(n for _p0, n, _h in runs)
            m_padded = _pow2_at_least(total)
            if len(runs) == 1:
                # Single contiguous run (the serving shape): the page index
                # is p0 + arange computed on device — no idx transfer.
                # Padding rows clip to the last page and are discarded below.
                with region["lock"]:
                    out = self._read_run_fn(m_padded)(region["buf"], np.int32(runs[0][0]))
                fetches.append((out, runs))
                continue
            idx = np.zeros(m_padded, dtype=np.int32)
            row = 0
            for p0, n, _h in runs:
                idx[row : row + n] = np.arange(p0, p0 + n, dtype=np.int32)
                row += n
            with region["lock"]:
                out = self._read_fn(region["buf"], jax.device_put(idx, region["device"]))
            fetches.append((out, runs))
        for out, _runs in fetches:
            if hasattr(out, "copy_to_host_async"):
                out.copy_to_host_async()
        for out, runs in fetches:
            host = np.asarray(out)
            row = 0
            for _p0, n, dst in runs:
                dst[:] = host[row : row + n].reshape(-1)
                row += n

    def _read_vecs(self, vecs: list[_Vec]) -> None:
        vecs = self._serve_view_vecs(vecs, is_write=False)
        if not vecs:
            return
        aligned = self._aligned_runs(vecs, check_overlap=False)
        if aligned is not None:
            self._read_vecs_aligned(*aligned)
            return
        jax = self._jax
        regions, grouped = self._decompose(vecs)
        if not grouped:
            return
        fetches: list[Any] = []  # (out device array, spans)
        for region_id, spans in grouped.items():
            region = regions[region_id]
            m_padded = _pow2_at_least(len(spans))
            idx = np.zeros(m_padded, dtype=np.int32)
            for k, (page_idx, _a, _b, _dst) in enumerate(spans):
                idx[k] = page_idx
            with region["lock"]:
                out = self._read_fn(region["buf"], jax.device_put(idx, region["device"]))
            fetches.append((out, spans))
        # Overlap the device->host transfers, then scatter to destinations.
        # Measured on a tunneled v5e dev TPU: async-issuing N region fetches
        # before the first np.asarray reaches the same aggregate bandwidth
        # as one maximal D2H op and hides the per-op RTTs (the e2e get rate
        # exceeds the single-op link rate); the transfer IS shared with the
        # later np.asarray on this stack.
        for out, _spans in fetches:
            if hasattr(out, "copy_to_host_async"):
                out.copy_to_host_async()
        for out, spans in fetches:
            host = np.asarray(out)
            for k, (_page_idx, a, b, dst) in enumerate(spans):
                dst[:] = host[k, a:b]

    # -- C ABI entry points ------------------------------------------------

    def _write(self, _ctx: Any, region_id: int, offset: int, buf: int,
               length: int) -> int:
        try:
            self._write_vecs([(region_id, offset, buf, length)])
            return 0
        except Exception:  # noqa: BLE001
            return 1

    def _read(self, _ctx: Any, region_id: int, offset: int, buf: int,
              length: int) -> int:
        try:
            self._read_vecs([(region_id, offset, buf, length)])
            return 0
        except Exception:  # noqa: BLE001
            return 1

    def _write_batch(self, _ctx: Any, vecs_ptr: Any, n: int) -> int:
        try:
            vecs = [(vecs_ptr[i].region_id, vecs_ptr[i].offset, vecs_ptr[i].buf,
                     vecs_ptr[i].len) for i in range(n)]
            self._write_vecs(vecs)
            return 0
        except Exception:  # noqa: BLE001
            return 1

    def _read_batch(self, _ctx: Any, vecs_ptr: Any, n: int) -> int:
        try:
            vecs = [(vecs_ptr[i].region_id, vecs_ptr[i].offset, vecs_ptr[i].buf,
                     vecs_ptr[i].len) for i in range(n)]
            self._read_vecs(vecs)
            return 0
        except Exception:  # noqa: BLE001
            return 1

    # -- device-to-device copy (the ICI path) ------------------------------

    def _copy(self, _ctx: Any, src_region: int, src_off: int, dst_region: int,
              dst_off: int, length: int) -> int:
        """Region-to-region copy with no host staging.

        Pages are gathered on the source device, moved with ONE device_put —
        which XLA routes over ICI when the regions live on different chips —
        and merged into the destination region's buffer on its own device.
        Offsets must be congruent mod the page size (allocator HBM placements
        are chunk-aligned, so this holds in practice); other layouts return
        nonzero and the native side stages through host memory (hbm_copy)."""
        try:
            jax = self._jax
            P = self.page_bytes
            if (src_off - dst_off) % P != 0:
                return 1
            with self._lock:
                src = self._regions.get(src_region)
                dst = self._regions.get(dst_region)
            if src is None or dst is None:
                return 1
            if src_off + length > src["size"] or dst_off + length > dst["size"]:
                return 1
            if length == 0:
                return 0
            if src["view"] is not None and dst["view"] is not None:
                # Host-visible both sides: one memcpy (bytes() snapshot only
                # for a same-region overlapping move, where slice assignment
                # direction would matter).
                chunk = src["view"][src_off : src_off + length]
                if src_region == dst_region and abs(src_off - dst_off) < length:
                    chunk = bytes(chunk)
                dst["view"][dst_off : dst_off + length] = np.frombuffer(
                    chunk, dtype=np.uint8) if isinstance(chunk, bytes) else chunk
                with self._lock:
                    self.copy_calls += 1
                return 0
            if src["view"] is not None or dst["view"] is not None:
                # Mixed modes (should not occur within one process/platform):
                # let the native side stage through read/write, each of which
                # picks its own fast path.
                return 1
            spans: list[tuple[int, int, int, int]] = []  # (src_page, dst_page, v0, v1)
            pos = 0
            while pos < length:
                a = (src_off + pos) % P
                n = min(length - pos, P - a)
                spans.append(((src_off + pos) // P, (dst_off + pos) // P, a, a + n))
                pos += n
            max_pages = max(1, self.max_staging_bytes // P)
            max_pages = 1 << (max_pages.bit_length() - 1)  # pow2: pad stays in cap
            for start in range(0, len(spans), max_pages):
                chunk = spans[start : start + max_pages]
                m_padded = _pow2_at_least(len(chunk))
                gidx = np.zeros(m_padded, dtype=np.int32)
                meta = np.zeros((3, m_padded), dtype=np.int32)
                meta[0, :] = dst["n_pages"]  # padding rows dropped by scatter
                for k, (sp, dp, a, b) in enumerate(chunk):
                    gidx[k] = sp
                    meta[0, k] = dp
                    meta[1, k] = a
                    meta[2, k] = b
                # Sequential (never nested) region locks: lock order cannot
                # deadlock with a concurrent opposite-direction copy.
                with src["lock"]:
                    pages = self._read_fn(src["buf"], jax.device_put(gidx, src["device"]))
                moved = jax.device_put(pages, dst["device"])  # ICI when cross-chip
                dev_meta = jax.device_put(meta, dst["device"])
                with dst["lock"]:
                    dst["buf"] = self._write_fn(dst["buf"], moved, dev_meta)
            with self._lock:
                if dst_region in self._regions:
                    self._dirty.add(dst_region)
                self.copy_calls += 1
            return 0
        except Exception:  # noqa: BLE001
            return 1

    # -- cross-process device fabric (jax.experimental.transfer) -----------
    # Server/connection/offer-GC lifecycle is shared with fabric.py through
    # TransferLink; this provider adds only the region <-> array glue.

    @property
    def fabric_offers(self) -> int:
        return self._link.offers

    @property
    def fabric_discards(self) -> int:
        return self._link.discards

    @property
    def fabric_gc_dropped(self) -> int:
        return self._link.gc_dropped

    def _fabric_server(self) -> Any:
        return self._link.server()

    def _fabric_range_array(self, region: _Region, offset: int,
                            length: int) -> Any:
        """The region's [offset, offset+len) bytes as a 1-D device array —
        the unit the fabric transfers (both sides agree on uint8[len])."""
        if region["view"] is not None:
            return self._jax.device_put(
                np.asarray(region["view"][offset : offset + length]), region["device"])
        P = self.page_bytes
        p0, a = offset // P, offset % P
        m_padded = _pow2_at_least(-(-(a + length) // P))  # keep jit cache log2-bounded
        with region["lock"]:
            pages = self._read_run_fn(m_padded)(region["buf"], np.int32(p0))
        # Chunk-aligned placements make this a pure reshape in practice;
        # padded rows (clipped reads) fall off the slice.
        return pages.reshape(-1)[a : a + length]

    def _fabric_address(self, _ctx: Any, buf: int, cap: int) -> int:
        try:
            server = self._fabric_server()
            if server is None:
                return 1
            addr = server.address().encode()
            if len(addr) + 1 > cap:
                return 1
            ctypes.memmove(buf, addr, len(addr) + 1)
            return 0
        except Exception:  # noqa: BLE001
            return 1

    def _fabric_offer(self, _ctx: Any, region_id: int, offset: int, length: int,
                      transfer_id: int) -> int:
        try:
            with self._lock:
                region = self._regions.get(region_id)
            if (self._link.server() is None or region is None
                    or offset + length > region["size"]):
                return 1
            arr = self._fabric_range_array(region, offset, length)
            self._link.offer(int(transfer_id), arr, device=region["device"])
            return 0
        except Exception:  # noqa: BLE001
            return 1

    def _fabric_pull(self, _ctx: Any, remote_addr: bytes, transfer_id: int,
                     region_id: int, offset: int, length: int) -> int:
        try:
            jax = self._jax
            jnp = jax.numpy

            if self._link.server() is None:
                return 1
            with self._lock:
                region = self._regions.get(region_id)
            if region is None or offset + length > region["size"]:
                return 1
            out = self._link.pull(remote_addr.decode(), int(transfer_id), int(length),
                                  device=region["device"])
            if region["view"] is not None:
                region["view"][offset : offset + length] = np.asarray(out)
            else:
                # Pad to whole pow2 pages on device, then the masked scatter
                # the write path uses (phase bytes masked by v0/v1, pad rows
                # dropped via an out-of-range index) — pow2 keeps the jit
                # cache log2-bounded like every other dispatch here.
                P = self.page_bytes
                p0, a = offset // P, offset % P
                m = -(-(a + length) // P)
                m_padded = _pow2_at_least(m)
                pages = jnp.pad(out, (a, m_padded * P - a - length)).reshape(m_padded, P)
                meta = np.zeros((3, m_padded), dtype=np.int32)
                meta[0, :] = region["n_pages"]  # pad rows: dropped by scatter
                meta[0, :m] = np.arange(p0, p0 + m, dtype=np.int32)
                meta[1, 0] = a
                meta[2, :m] = P
                meta[2, m - 1] = (a + length - 1) % P + 1
                dev_meta = jax.device_put(meta, region["device"])
                with region["lock"]:
                    region["buf"] = self._write_fn(region["buf"], pages, dev_meta)
                    region["buf"].block_until_ready()  # pull blocks until durable
            self.fabric_pulls += 1
            return 0
        except Exception:  # noqa: BLE001
            return 1

    def _host_view_base(self, _ctx: Any, region_id: int) -> int | None:
        """v5: the region's stable CPU-addressable base, or None. Only
        host-view regions qualify — their buffer is never donated (all I/O
        is memcpy through the probed view), so the pointer stays valid for
        the region's whole life. Handing it to the native side removes the
        per-op ctypes dispatch from the staged data path entirely."""
        try:
            with self._lock:
                region = self._regions.get(region_id)
            if region is None or region["view"] is None:
                return None
            return int(region["view"].ctypes.data)
        except Exception:  # noqa: BLE001
            return None

    def _flush(self, _ctx: Any) -> int:
        try:
            self.synchronize()
            return 0
        except Exception:  # noqa: BLE001
            return 1

    def _available(self, _ctx: Any, _device_id: Any) -> int:
        return 0  # unknown

    # -- registration ------------------------------------------------------

    def close(self) -> None:
        """Releases the per-device staging machinery: joins in-flight
        dispatches, drains fences, and shuts the dispatcher threads down.
        Idempotent. Without this, repeated provider create/destroy cycles in
        one process leak one dispatcher thread per device per instance (the
        executors are otherwise only parked, never joined)."""
        with self._staging_lock:
            entries, self._staging = self._staging, {}
        for entry in entries.values():
            with entry["lock"]:
                for slot in entry["slots"]:
                    self._join_pending(slot)
                    self._await_fences(slot)
            entry["exec"].shutdown(wait=True)

    def register(self) -> JaxHbmProvider:
        """Installs this provider process-wide for all HBM_TPU backends."""
        self._struct = _ProviderStruct(
            ctx=None,
            alloc_region=_ALLOC_FN(self._alloc),
            free_region=_FREE_FN(self._free),
            write=_WRITE_FN(self._write),
            read=_READ_FN(self._read),
            available=_AVAIL_FN(self._available),
            write_batch=_BATCH_FN(self._write_batch),
            read_batch=_BATCH_FN(self._read_batch),
            flush=_FLUSH_FN(self._flush),
            copy=_COPY_FN(self._copy),
            fabric_address=_FABRIC_ADDR_FN(self._fabric_address),
            fabric_offer=_FABRIC_OFFER_FN(self._fabric_offer),
            fabric_pull=_FABRIC_PULL_FN(self._fabric_pull),
            host_view_base=_HOST_VIEW_FN(self._host_view_base),
        )
        ptr = ctypes.cast(ctypes.pointer(self._struct), ctypes.c_void_p)
        # Walk the provider-version chain through the manifest (native.have,
        # not hasattr): v4/v5 are OPTIONAL symbols a prebuilt older library
        # may lack; the v3 prefix of the struct matches exactly either way.
        if native.have("btpu_register_hbm_provider_v5"):
            lib.btpu_register_hbm_provider_v5(ptr)
        elif native.have("btpu_register_hbm_provider_v4"):
            lib.btpu_register_hbm_provider_v4(ptr)  # v4 prefix matches
        else:
            lib.btpu_register_hbm_provider_v3(ptr)
        JaxHbmProvider._registered = self
        return self

    _registered: JaxHbmProvider | None = None

    @staticmethod
    def unregister() -> None:
        """Restores the built-in host-memory emulation and tears down the
        registered provider's dispatcher threads (see close())."""
        if native.have("btpu_register_hbm_provider_v5"):
            lib.btpu_register_hbm_provider_v5(None)
        elif native.have("btpu_register_hbm_provider_v4"):
            lib.btpu_register_hbm_provider_v4(None)
        else:
            lib.btpu_register_hbm_provider_v3(None)
        registered, JaxHbmProvider._registered = JaxHbmProvider._registered, None
        if registered is not None:
            registered.close()

    def region_count(self) -> int:
        with self._lock:
            return len(self._regions)

    def synchronize(self) -> None:
        """Blocks until all in-flight device writes have completed.

        Write dispatches are asynchronous; the native client calls the
        provider's flush() (which lands here) before acknowledging
        put_complete, and benchmarks call it before stopping timers.

        The per-region lock is held across block_until_ready: a concurrent
        write would otherwise donate (delete) the snapshotted buffer mid-
        wait. Lock order is always region-lock -> table-lock, so the dirty
        ids are copied out of the table first."""
        with self._lock:
            dirty_ids = [(r, self._regions[r]) for r in self._dirty if r in self._regions]
        for region_id, region in dirty_ids:
            with region["lock"]:
                buf = region["buf"]
                if hasattr(buf, "block_until_ready"):
                    buf.block_until_ready()
            with self._lock:
                self._dirty.discard(region_id)
        # Drop completed fences so an idle device's list cannot grow stale
        # references between writes (fences are one element each, so this is
        # hygiene, not memory pressure).
        with self._staging_lock:
            entries = list(self._staging.values())
        for entry in entries:
            with entry["lock"]:
                for slot in entry["slots"]:
                    self._join_pending(slot)  # fence list complete after this
                    self._await_fences(slot)
