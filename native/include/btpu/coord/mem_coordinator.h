// In-process coordination store with real TTL expiry and watch delivery.
// See coordinator.h for the interface contract.
#pragma once

#include <atomic>
#include <condition_variable>
#include <map>
#include <thread>
#include <unordered_map>

#include "btpu/common/thread_annotations.h"
#include "btpu/coord/coordinator.h"

namespace btpu::coord {

// Durability for the coordination store (the etcd-cluster role the
// reference delegates to deployment — etcd_service.cpp wraps a durable,
// replicated etcd; bb-coord must survive restarts on its own). State is a
// write-ahead log + snapshot: every mutation appends a CRC-chained record
// (wal_format.h), and the log compacts into a snapshot once it grows. A
// mutation acks only after the record is covered by an fdatasync — by
// default via GROUP COMMIT: appends accumulate for a bounded window
// (group_commit_us) and one fdatasync covers the whole batch, so the sync
// cost amortizes across concurrent writers at UNCHANGED durability
// (acked == durable either way). On load, leases are re-armed to their
// full TTL so live owners get one refresh interval to resume heartbeats
// before expiry fires; elections and watches are session state and are
// re-established by reconnecting clients.
struct DurabilityOptions {
  std::string dir;             // empty = memory-only (no persistence)
  bool fsync{true};            // false = never sync (tests; crash may lose acks)
  size_t compact_every{4096};  // WAL records between snapshot compactions
  // Group-commit switch. 0 = sync-per-record (one inline fdatasync per
  // append, the pre-group-commit behavior); >0 = leader-based group commit
  // — acks release when a covering fdatasync lands, and the batching
  // window is the in-flight sync's own duration (appends landing during a
  // sync ride the next leader), so added ack delay is bounded by the
  // storage's sync latency, never by an imposed sleep. The magnitude is
  // advisory (kept in MICROSECONDS for forward compatibility with an
  // explicit accumulation timer); <0 = $BTPU_WAL_GROUP_COMMIT_US,
  // default 500.
  int64_t group_commit_us{-1};
};

class MemCoordinator : public Coordinator {
 public:
  explicit MemCoordinator(DurabilityOptions durability = {});
  ~MemCoordinator() override;

  Result<std::string> get(const std::string& key) override;
  ErrorCode put(const std::string& key, const std::string& value) override;
  ErrorCode put_with_ttl(const std::string& key, const std::string& value,
                         int64_t ttl_ms) override;
  ErrorCode del(const std::string& key) override;
  Result<std::vector<KeyValue>> get_with_prefix(const std::string& prefix) override;

  Result<LeaseId> lease_grant(int64_t ttl_ms) override;
  ErrorCode lease_keepalive(LeaseId lease) override;
  ErrorCode lease_revoke(LeaseId lease) override;
  ErrorCode put_with_lease(const std::string& key, const std::string& value,
                           LeaseId lease) override;

  Result<WatchId> watch_prefix(const std::string& prefix, WatchCallback cb) override;
  ErrorCode unwatch(WatchId id) override;

  ErrorCode register_service(const std::string& service_name, const std::string& id,
                             const std::string& address, int64_t ttl_ms) override;
  Result<std::vector<KeyValue>> discover_service(const std::string& service_name) override;
  ErrorCode unregister_service(const std::string& service_name, const std::string& id) override;

  ErrorCode campaign(const std::string& election, const std::string& candidate_id,
                     int64_t lease_ttl_ms, CampaignCallback cb) override;
  ErrorCode resign(const std::string& election, const std::string& candidate_id) override;
  ErrorCode campaign_keepalive(const std::string& election,
                               const std::string& candidate_id) override;
  Result<std::string> current_leader(const std::string& election) override;
  Result<uint64_t> election_epoch(const std::string& election) override;

  ErrorCode put_fenced(const std::string& key, const std::string& value,
                       const std::string& election, uint64_t epoch) override;
  ErrorCode del_fenced(const std::string& key, const std::string& election,
                       uint64_t epoch) override;

  bool connected() const override { return true; }

  // fdatasync calls issued for WAL durability so far. The group-commit
  // acceptance signal: syncs/mutation < 1 proves batching regardless of
  // scheduler noise (sync-per-record mode reads ~1).
  // ordering: relaxed — diagnostic gauge; durability is proven under sync_mutex_, not here.
  uint64_t wal_sync_count() const { return wal_syncs_.load(std::memory_order_relaxed); }

  // Recovery verdict, set once during construction (journal_load): OK;
  // DATA_CORRUPTION (mid-log / snapshot corruption — torn tails do NOT
  // trip this, they are truncated and healed); INVALID_STATE (journal or
  // snapshot written by a newer build); or COORD_ERROR (the journal cannot
  // open/initialize, so every mutation would fail-stop anyway). Non-OK
  // refuses every read and mutation with the same code: a store that
  // cannot prove its state serves nothing. bb-coord checks this at startup
  // and exits instead of serving.
  ErrorCode durability_status() const { return journal_status_; }

  // ---- replication (standby bb-coord mirroring; see coord_server.h) ----
  // The sink receives every mutation record (same encoding as the WAL) with
  // a monotonically increasing sequence. Called UNDER the store mutex: the
  // sink must only enqueue, never call back into the store.
  void set_replication_sink(std::function<void(uint64_t, const std::vector<uint8_t>&)> sink);
  // Consistent snapshot + the sequence of the last record it includes.
  std::pair<std::vector<uint8_t>, uint64_t> snapshot_with_seq();
  // Follower side: replaces state wholesale / applies one streamed record.
  ErrorCode load_replica_snapshot(const std::vector<uint8_t>& bytes);
  ErrorCode apply_replica_record(const std::vector<uint8_t>& record);
  // Followers never expire leases (only the primary owns liveness); promote()
  // re-arms every lease to its full TTL and resumes expiry — the same grace
  // journal recovery gives reconnecting owners.
  void set_follower(bool follower);
  void promote();
  bool is_follower() const;

 private:
  using Clock = std::chrono::steady_clock;

  struct Entry {
    std::string value;
    LeaseId lease{0};  // 0 = no lease
  };
  struct Lease {
    int64_t ttl_ms{0};
    Clock::time_point deadline;
    std::vector<std::string> keys;
  };
  struct Watch {
    WatchId id;
    std::string prefix;
    WatchCallback cb;
  };
  struct Candidate {
    std::string id;
    LeaseId lease;
    CampaignCallback cb;
  };
  struct Election {
    std::vector<Candidate> candidates;  // front() = leader
    uint64_t epoch{0};                  // fencing token of the current leader
  };

  void expiry_loop();
  // Collects matching callbacks under the lock, invokes them outside it.
  void notify(WatchEvent::Type type, const std::string& key, const std::string& value)
      BTPU_EXCLUDES(mutex_);
  // del_locked / promote_next_locked / apply_record_locked take the caller's
  // guard BY REFERENCE because they drop and re-take it around watch/leader
  // callbacks (callbacks must run unlocked). The REQUIRES contract holds at
  // both entry and exit; the interior dance is invisible to the analysis, so
  // their DEFINITIONS carry BTPU_NO_THREAD_SAFETY_ANALYSIS.
  ErrorCode del_locked(const std::string& key, MutexLock& lock) BTPU_REQUIRES(mutex_);
  void promote_next_locked(const std::string& election, MutexLock& lock)
      BTPU_REQUIRES(mutex_);
  // Mints the next fencing epoch for `election` (monotonic across restarts
  // and across all elections: journaled).
  uint64_t mint_epoch_locked(const std::string& election) BTPU_REQUIRES(mutex_);
  // OK iff `election` currently has a leader whose epoch == `epoch`.
  ErrorCode check_fence_locked(const std::string& election, uint64_t epoch) const
      BTPU_REQUIRES(mutex_);

  // ---- durability (no-ops when durability_.dir is empty) ----
  void journal_load();                       // ctor only, before threads
  // Recovery refused (corruption / future format): record why and clear
  // every partially-recovered structure so nothing unproven is served.
  void recovery_fail_locked(ErrorCode status) BTPU_REQUIRES(mutex_);
  void journal_append_locked(const std::vector<uint8_t>& record) BTPU_REQUIRES(mutex_);
  void journal_compact_locked() BTPU_REQUIRES(mutex_);  // snapshot + truncate WAL
  // Leader-based group commit: after appending (and releasing mutex_), a
  // mutator parks here until an fdatasync covers its record. The FIRST
  // unsatisfied waiter becomes the sync leader and issues one fdatasync for
  // everything appended so far; waiters that landed meanwhile are covered
  // by the next leader. No handoff to a helper thread — a lone writer pays
  // exactly one fdatasync (like sync-per-record, but without holding
  // mutex_ across it), and under concurrency the batch grows to everyone
  // who appended during the leader's sync. Returns FALSE when the covering
  // sync failed (journal broken, waiters released, the mutation must NOT
  // ack). Lock order: mutex_ -> sync_mutex_ (appends publish under both);
  // a failing leader takes mutex_ -> sync_mutex_ for journal_break_locked
  // while holding neither.
  BTPU_NODISCARD bool wait_durable(uint64_t seq) BTPU_EXCLUDES(mutex_);
  // Sequence a public mutator must wait on: the last record it appended.
  uint64_t appended_seq_locked() const BTPU_REQUIRES(mutex_) { return wal_appended_; }
  // Unrecoverable WAL write failure: stop journaling and release every
  // durability waiter (persistence is loudly degraded, not wedged). The fd
  // stays open until the destructor — the syncer may be mid-fdatasync on
  // it, and closing would let the number be reused under that call.
  void journal_break_locked() BTPU_REQUIRES(mutex_);
  bool journal_write_header_locked() BTPU_REQUIRES(mutex_);
  // Rejects values that can never fit one journal frame BEFORE any memory
  // mutation (durability-configured stores only; framing headroom included).
  ErrorCode check_journalable(size_t key_bytes, size_t value_bytes) const;
  std::string snapshot_path() const;
  std::string wal_path() const;
  // Journal + replication sink, every mutation goes through here.
  void log_locked(const std::vector<uint8_t>& record) BTPU_REQUIRES(mutex_);
  std::vector<uint8_t> snapshot_bytes_locked() const BTPU_REQUIRES(mutex_);
  BTPU_NODISCARD bool decode_snapshot_locked(const std::vector<uint8_t>& bytes)
      BTPU_REQUIRES(mutex_);
  // Applies one WAL-encoded record: shared by crash recovery (no journal fd
  // open yet, no watches registered) and live follower mirroring (journals
  // and notifies). Returns false on a malformed record.
  bool apply_record_locked(const uint8_t* data, size_t len, MutexLock& lock)
      BTPU_REQUIRES(mutex_);

  DurabilityOptions durability_;
  int64_t group_commit_us_{0};  // resolved window (ctor; immutable after)
  // Set once in journal_load (ctor, pre-thread), read-only afterwards.
  ErrorCode journal_status_{ErrorCode::OK};
  int wal_fd_ BTPU_GUARDED_BY(mutex_){-1};
  size_t wal_records_ BTPU_GUARDED_BY(mutex_){0};
  uint64_t wal_appended_ BTPU_GUARDED_BY(mutex_){0};  // records appended ever
  uint32_t wal_chain_ BTPU_GUARDED_BY(mutex_){0};     // running chain CRC
  bool wal_broken_ BTPU_GUARDED_BY(mutex_){false};
  // Sticky per-mutation journal verdict: public mutators clear it before
  // mutating and FAIL the op (COORD_ERROR) if any of their appends could
  // not reach the journal — a durability-configured store must never ack
  // what it cannot persist (memory-only stores never set it).
  bool journal_op_failed_ BTPU_GUARDED_BY(mutex_){false};
  // Group-commit rendezvous (leaf lock; see wait_durable above).
  bool group_commit_{false};  // resolved in ctor; immutable after
  mutable Mutex sync_mutex_ BTPU_ACQUIRED_AFTER(mutex_);
  CondVarAny sync_cv_;
  uint64_t sync_pending_ BTPU_GUARDED_BY(sync_mutex_){0};
  uint64_t sync_completed_ BTPU_GUARDED_BY(sync_mutex_){0};  // released waiters
  uint64_t sync_durable_ BTPU_GUARDED_BY(sync_mutex_){0};    // PROVEN synced
  // File offsets mirroring the seq trio: a failed covering sync ROLLS the
  // WAL back to sync_durable_end_ before breaking the journal, so a
  // mutation refused with COORD_ERROR cannot resurface after a restart
  // (its record would otherwise still scan as an intact chain).
  off_t wal_end_ BTPU_GUARDED_BY(mutex_){0};                // after last append
  off_t sync_pending_end_ BTPU_GUARDED_BY(sync_mutex_){0};  // offset of sync_pending_
  off_t sync_durable_end_ BTPU_GUARDED_BY(sync_mutex_){0};  // offset of sync_durable_
  int sync_fd_ BTPU_GUARDED_BY(sync_mutex_){-1};
  bool sync_in_flight_ BTPU_GUARDED_BY(sync_mutex_){false};
  std::atomic<uint64_t> wal_syncs_{0};
  std::function<void(uint64_t, const std::vector<uint8_t>&)> repl_sink_ BTPU_GUARDED_BY(mutex_);
  uint64_t repl_seq_ BTPU_GUARDED_BY(mutex_){0};
  bool follower_ BTPU_GUARDED_BY(mutex_){false};

  mutable Mutex mutex_;
  // Ordered: prefix scans are ranges.
  std::map<std::string, Entry> data_ BTPU_GUARDED_BY(mutex_);
  std::unordered_map<LeaseId, Lease> leases_ BTPU_GUARDED_BY(mutex_);
  std::vector<Watch> watches_ BTPU_GUARDED_BY(mutex_);
  std::map<std::string, Election> elections_ BTPU_GUARDED_BY(mutex_);
  // Fencing clock. max_epoch_ is the mint counter (global: tokens are
  // unique across elections); election_epochs_ remembers each election's
  // last minted epoch DURABLY, so the fence still judges correctly in the
  // window after a coordinator restart when elections_ (session state) is
  // empty but leaders still hold their tokens.
  uint64_t max_epoch_ BTPU_GUARDED_BY(mutex_){0};
  std::map<std::string, uint64_t> election_epochs_ BTPU_GUARDED_BY(mutex_);
  std::atomic<LeaseId> next_lease_{1};
  std::atomic<WatchId> next_watch_{1};

  std::thread expiry_thread_;
  // condition_variable_any: waits on the annotated MutexLock (BasicLockable).
  CondVarAny expiry_cv_;
  bool stopping_ BTPU_GUARDED_BY(mutex_){false};
};

}  // namespace btpu::coord
