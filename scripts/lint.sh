#!/usr/bin/env bash
# Static lint gate (make lint):
#   1. scripts/btpu_lint.py — the project-invariant linter (annotated-mutex
#      only, env via env.h, steady-clock deadlines, wire structs registered
#      in the golden table, nodiscard on error-returning declarations).
#      Pattern-based with an optional libclang refinement, so it runs — and
#      can FAIL — on every box, clang or not.
#   2. clang -fsyntax-only -Wthread-safety -Werror sweep over every native
#      source — the machine check behind the GUARDED_BY/REQUIRES annotations
#      in btpu/common/thread_annotations.h. Skipped WITH A NOTICE when clang
#      is not installed (gcc has no equivalent analysis; the annotations
#      compile to no-ops there).
#   3. python -m compileall over blackbird_tpu/ and tests/ so syntax rot in
#      the bindings fails the gate even on machines that never import them.
set -uo pipefail
cd "$(dirname "$0")/.."

fail=0

# ---- project-invariant linter ---------------------------------------------
PY="${PYTHON:-python3}"
if command -v "$PY" > /dev/null 2>&1; then
  echo "lint: ${PY} scripts/btpu_lint.py (project invariants)"
  if ! "$PY" scripts/btpu_lint.py; then
    echo "lint: FAIL — project-invariant violations (see above)" >&2
    fail=1
  fi
else
  echo "lint: FAIL — python3 required for the project-invariant linter" >&2
  fail=1
fi

# ---- clang thread-safety sweep --------------------------------------------
CLANG="${CLANG:-}"
if [ -z "${CLANG}" ]; then
  for cand in clang++ clang++-21 clang++-20 clang++-19 clang++-18 clang++-17 \
              clang++-16 clang++-15 clang++-14; do
    if command -v "$cand" > /dev/null 2>&1; then CLANG="$cand"; break; fi
  done
fi

if [ -z "${CLANG}" ]; then
  if [ "${BTPU_REQUIRE_CLANG:-0}" = "1" ]; then
    echo "lint: FAIL — BTPU_REQUIRE_CLANG=1 but clang not found" >&2
    fail=1
  else
    echo "lint: NOTICE — clang not found; skipping the -Wthread-safety sweep" >&2
    echo "lint:          (annotations still compile as no-ops under gcc;" >&2
    echo "lint:          install clang to machine-check the lock discipline)" >&2
  fi
else
  echo "lint: ${CLANG} -Wthread-safety sweep over native/"
  srcs=$(find native/src native/exe native/tests examples -name '*.cpp' | sort)
  for src in $srcs; do
    # -fsyntax-only: the analysis runs in the frontend; no objects are
    # written, so the sweep is fast and needs no link environment.
    if ! "${CLANG}" -std=c++20 -fsyntax-only -Inative/include -Inative/tests \
         -Wall -Wextra -Wno-unused-parameter \
         -Wthread-safety -Werror=thread-safety "$src"; then
      echo "lint: FAIL ${src}" >&2
      fail=1
    fi
  done
  [ "$fail" -eq 0 ] && echo "lint: thread-safety sweep clean"
fi

# ---- python bytecode lint --------------------------------------------------
PY="${PYTHON:-python3}"
if command -v "$PY" > /dev/null 2>&1; then
  echo "lint: ${PY} -m compileall blackbird_tpu/ tests/ bench.py"
  if ! "$PY" -m compileall -q blackbird_tpu tests bench.py; then
    echo "lint: FAIL — python sources do not byte-compile" >&2
    fail=1
  fi
else
  echo "lint: NOTICE — python3 not found; skipping compileall" >&2
fi

exit "$fail"
