#!/usr/bin/env bash
# Static lint gate (make lint):
#   1. scripts/btpu_lint.py — the project-invariant linter (annotated-mutex
#      only, env via env.h, steady-clock deadlines, wire structs registered
#      in the golden table, nodiscard on error-returning declarations).
#      Pattern-based with an optional libclang refinement, so it runs — and
#      can FAIL — on every box, clang or not.
#   2. scripts/capi_check.py — the FFI-boundary drift checker: every
#      extern "C" signature and mirrored enum must agree across the headers,
#      native/tests/capi_golden.txt, and blackbird_tpu/_capi.py (docs/
#      CORRECTNESS.md §11). Pattern pass always runs; libclang refinement
#      rides the same budget/require knobs as btpu_lint.
#   3. clang -fsyntax-only -Wthread-safety -Werror sweep over every native
#      source — the machine check behind the GUARDED_BY/REQUIRES annotations
#      in btpu/common/thread_annotations.h. SKIP with a notice when clang is
#      not installed (BTPU_REQUIRE_CLANG=1 turns the skip into a failure).
#   4. python -m compileall over blackbird_tpu/ and tests/ so syntax rot in
#      the bindings fails the gate even on machines that never import them.
#   5. mypy --strict over the Python plane (mypy.ini pins the config).
#      SKIP with a notice when mypy is not installed — never PASS —
#      and BTPU_REQUIRE_MYPY=1 (CI) turns that skip into a failure.
#   6. ruff check (pyflakes fallback) over the same files; ruff.toml pins
#      the rule set. SKIP-never-PASS when neither tool exists;
#      BTPU_REQUIRE_RUFF=1 (CI) turns the skip into a failure.
#
# Every leg runs even after an earlier one fails. The trailing
# `lint-scoreboard:` lines are machine-readable (check.sh turns them into
# summary rows); keep their format stable.
set -uo pipefail
cd "$(dirname "$0")/.."

fail=0
declare -A leg

# ---- project-invariant linter ---------------------------------------------
PY="${PYTHON:-python3}"
if command -v "$PY" > /dev/null 2>&1; then
  echo "lint: ${PY} scripts/btpu_lint.py (project invariants)"
  if "$PY" scripts/btpu_lint.py; then
    leg[invariants]=PASS
  else
    echo "lint: FAIL — project-invariant violations (see above)" >&2
    leg[invariants]=FAIL
    fail=1
  fi
else
  echo "lint: FAIL — python3 required for the project-invariant linter" >&2
  leg[invariants]=FAIL
  fail=1
fi

# ---- FFI-boundary drift check ----------------------------------------------
if command -v "$PY" > /dev/null 2>&1; then
  echo "lint: ${PY} scripts/capi_check.py (FFI boundary: headers vs golden vs ctypes manifest)"
  if "$PY" scripts/capi_check.py; then
    leg[capi-check]=PASS
  else
    echo "lint: FAIL — FFI boundary drift (see above; docs/CORRECTNESS.md §11)" >&2
    leg[capi-check]=FAIL
    fail=1
  fi
else
  leg[capi-check]=FAIL
  fail=1
fi

# ---- clang thread-safety sweep --------------------------------------------
CLANG="${CLANG:-}"
if [ -z "${CLANG}" ]; then
  for cand in clang++ clang++-21 clang++-20 clang++-19 clang++-18 clang++-17 \
              clang++-16 clang++-15 clang++-14; do
    if command -v "$cand" > /dev/null 2>&1; then CLANG="$cand"; break; fi
  done
fi

if [ -z "${CLANG}" ]; then
  if [ "${BTPU_REQUIRE_CLANG:-0}" = "1" ]; then
    echo "lint: FAIL — BTPU_REQUIRE_CLANG=1 but clang not found" >&2
    leg[tsa-sweep]=FAIL
    fail=1
  else
    echo "lint: NOTICE — clang not found; skipping the -Wthread-safety sweep" >&2
    echo "lint:          (annotations still compile as no-ops under gcc;" >&2
    echo "lint:          install clang to machine-check the lock discipline)" >&2
    leg[tsa-sweep]="SKIP (no clang — sweep did not run)"
  fi
else
  echo "lint: ${CLANG} -Wthread-safety sweep over native/"
  sweep_fail=0
  srcs=$(find native/src native/exe native/tests examples -name '*.cpp' | sort)
  for src in $srcs; do
    # -fsyntax-only: the analysis runs in the frontend; no objects are
    # written, so the sweep is fast and needs no link environment.
    if ! "${CLANG}" -std=c++20 -fsyntax-only -Inative/include -Inative/tests \
         -Wall -Wextra -Wno-unused-parameter \
         -Wthread-safety -Werror=thread-safety "$src"; then
      echo "lint: FAIL ${src}" >&2
      sweep_fail=1
      fail=1
    fi
  done
  if [ "$sweep_fail" -eq 0 ]; then
    echo "lint: thread-safety sweep clean"
    leg[tsa-sweep]=PASS
  else
    leg[tsa-sweep]=FAIL
  fi
fi

# ---- python bytecode lint --------------------------------------------------
if command -v "$PY" > /dev/null 2>&1; then
  echo "lint: ${PY} -m compileall blackbird_tpu/ tests/ bench.py"
  if "$PY" -m compileall -q blackbird_tpu tests bench.py; then
    leg[compileall]=PASS
  else
    echo "lint: FAIL — python sources do not byte-compile" >&2
    leg[compileall]=FAIL
    fail=1
  fi
else
  echo "lint: NOTICE — python3 not found; skipping compileall" >&2
  leg[compileall]="SKIP (no python3)"
fi

# ---- mypy strict type check ------------------------------------------------
# The Python plane is strictly typed (mypy.ini pins the mode and the module
# overrides; blackbird_tpu ships py.typed). Absent mypy, the leg SKIPs with
# a notice — never PASSes — because an unchecked plane is not a typed plane.
if command -v "$PY" > /dev/null 2>&1 && "$PY" -m mypy --version > /dev/null 2>&1; then
  echo "lint: ${PY} -m mypy (strict, mypy.ini)"
  if "$PY" -m mypy --config-file mypy.ini; then
    leg[mypy]=PASS
  else
    echo "lint: FAIL — mypy strict violations (see above)" >&2
    leg[mypy]=FAIL
    fail=1
  fi
elif [ "${BTPU_REQUIRE_MYPY:-0}" = "1" ]; then
  echo "lint: FAIL — BTPU_REQUIRE_MYPY=1 but mypy is not installed" >&2
  leg[mypy]=FAIL
  fail=1
else
  echo "lint: NOTICE — mypy not found; skipping the strict type check" >&2
  echo "lint:          (pip install mypy to machine-check the Python plane)" >&2
  leg[mypy]="SKIP (mypy not installed — plane not type-checked)"
fi

# ---- ruff (pyflakes fallback) ----------------------------------------------
PYFILES=(blackbird_tpu tests bench.py scripts/capi_check.py scripts/btpu_lint.py)
if command -v ruff > /dev/null 2>&1; then
  echo "lint: ruff check (ruff.toml)"
  if ruff check "${PYFILES[@]}"; then
    leg[ruff]=PASS
  else
    echo "lint: FAIL — ruff findings (see above)" >&2
    leg[ruff]=FAIL
    fail=1
  fi
elif command -v "$PY" > /dev/null 2>&1 && "$PY" -c 'import pyflakes' 2> /dev/null; then
  echo "lint: ${PY} -m pyflakes (ruff fallback)"
  if "$PY" -m pyflakes "${PYFILES[@]}"; then
    leg[ruff]="PASS (pyflakes fallback)"
  else
    echo "lint: FAIL — pyflakes findings (see above)" >&2
    leg[ruff]=FAIL
    fail=1
  fi
elif [ "${BTPU_REQUIRE_RUFF:-0}" = "1" ]; then
  echo "lint: FAIL — BTPU_REQUIRE_RUFF=1 but neither ruff nor pyflakes is installed" >&2
  leg[ruff]=FAIL
  fail=1
else
  echo "lint: NOTICE — ruff/pyflakes not found; skipping the pyflakes-class sweep" >&2
  leg[ruff]="SKIP (ruff/pyflakes not installed)"
fi

# ---- machine-readable scoreboard (parsed by check.sh) -----------------------
for name in invariants capi-check tsa-sweep compileall mypy ruff; do
  echo "lint-scoreboard: ${name}=${leg[$name]}"
done

exit "$fail"
