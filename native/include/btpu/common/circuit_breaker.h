// Per-endpoint circuit breaker (client-side degradation). Classic three
// states:
//   CLOSED    — healthy; every request allowed. Consecutive failures, or
//               consecutive successes slower than the latency trip line,
//               open the breaker.
//   OPEN      — failing; requests are refused locally (the caller routes to
//               another replica) until the cooldown elapses.
//   HALF_OPEN — cooldown elapsed; a limited number of probe requests are
//               let through. A probe success closes the breaker, a probe
//               failure re-opens it for another (jittered) cooldown.
// The latency trip exists because a worker that answers correctly but 50x
// slower than its peers is operationally DOWN for tail-latency purposes —
// error-rate-only breakers never notice it (The Tail at Scale).
#pragma once

#include <chrono>
#include <cstdint>
#include <memory>
#include <string>
#include <unordered_map>

#include "btpu/common/deadline.h"
#include "btpu/common/flight_recorder.h"
#include "btpu/common/thread_annotations.h"

namespace btpu {

// Namespace-scope (not nested) so it is complete before any default
// argument references it — gcc-10 rejects both nested-incomplete and
// brace-init default args for aggregates with member initializers (PR 88165).
struct BreakerOptions {
  uint32_t failure_threshold{3};   // consecutive failures to trip
  uint32_t slow_threshold{5};      // consecutive over-line successes to trip
  uint32_t open_ms{2000};          // cooldown before half-open probes
  uint32_t half_open_probes{1};    // probes allowed per half-open window
  // Latency trip line: a success slower than max(slow_floor_us,
  // slow_factor * rolling mean) counts as "slow". 0 floor + factor keeps
  // fast endpoints honest without tripping on cold-start noise.
  uint64_t slow_floor_us{2000};
  double slow_factor{8.0};
};

class CircuitBreaker {
 public:
  enum class State : uint8_t { kClosed = 0, kOpen = 1, kHalfOpen = 2 };

  using Options = BreakerOptions;

  explicit CircuitBreaker(Options options = Options()) : options_(options) {}

  // May this request proceed? OPEN returns false (caller skips the
  // endpoint); an elapsed cooldown transitions to HALF_OPEN and admits up
  // to half_open_probes callers as probes.
  bool allow() {
    MutexLock lock(mutex_);
    switch (state_) {
      case State::kClosed:
        return true;
      case State::kOpen:
        if (Clock::now() < open_until_) return false;
        state_ = State::kHalfOpen;
        probes_inflight_ = 0;
        [[fallthrough]];
      case State::kHalfOpen:
        if (probes_inflight_ >= options_.half_open_probes) return false;
        ++probes_inflight_;
        return true;
    }
    return true;
  }

  void record_success(uint64_t latency_us) {
    MutexLock lock(mutex_);
    consecutive_failures_ = 0;
    if (state_ == State::kHalfOpen) {
      // A probe that answers but is still over the line has NOT recovered —
      // closing on it (and folding its latency) would converge the EWMA
      // onto the slow endpoint's latency and permanently defeat the
      // latency trip via the recovery path. Re-open instead.
      const uint64_t probe_line = slow_line_locked();
      if (latency_us > 0 && probe_line > 0 && latency_us > probe_line) {
        trip_locked();
        return;
      }
      state_ = State::kClosed;
      consecutive_slow_ = 0;
      if (latency_us > 0) fold_mean_locked(latency_us);
      return;
    }
    // Judge against the PRE-update baseline, and keep slow outliers OUT of
    // the EWMA: folding them first drags the trip line up behind the very
    // slowness it is supposed to catch (a 50x-slow worker would raise its
    // own bar past tripping within three samples).
    const uint64_t line = slow_line_locked();
    if (latency_us > 0 && line > 0 && latency_us > line) {
      if (++consecutive_slow_ >= options_.slow_threshold) trip_locked();
      return;
    }
    consecutive_slow_ = 0;
    // Rolling mean (EWMA, alpha 1/8) over healthy successes only: failures
    // and over-line outliers carry no baseline information.
    if (latency_us > 0) fold_mean_locked(latency_us);
  }

  void record_failure() {
    MutexLock lock(mutex_);
    consecutive_slow_ = 0;
    if (state_ == State::kHalfOpen) {
      trip_locked();  // the probe failed: straight back to OPEN
      return;
    }
    if (state_ == State::kClosed && ++consecutive_failures_ >= options_.failure_threshold)
      trip_locked();
  }

  // Non-mutating ordering hint: is this endpoint currently refusing
  // requests? Unlike allow(), never consumes a half-open probe slot — use
  // for candidate ORDERING, and allow() only for attempts actually made
  // (an admitted probe that is never attempted would wedge HALF_OPEN).
  bool open_now() const {
    MutexLock lock(mutex_);
    return state_ == State::kOpen && Clock::now() < open_until_;
  }

  State state() const {
    MutexLock lock(mutex_);
    return state_;
  }
  uint64_t mean_latency_us() const {
    MutexLock lock(mutex_);
    return mean_us_;
  }

 private:
  using Clock = std::chrono::steady_clock;

  void fold_mean_locked(uint64_t latency_us) BTPU_REQUIRES(mutex_) {
    mean_us_ = mean_us_ == 0 ? latency_us : (mean_us_ * 7 + latency_us) / 8;
  }

  uint64_t slow_line_locked() const BTPU_REQUIRES(mutex_) {
    if (mean_us_ == 0) return 0;  // no baseline yet: never trip on latency
    const auto scaled = static_cast<uint64_t>(static_cast<double>(mean_us_) *
                                              options_.slow_factor);
    return scaled > options_.slow_floor_us ? scaled : options_.slow_floor_us;
  }

  void trip_locked() BTPU_REQUIRES(mutex_) {
    state_ = State::kOpen;
    consecutive_failures_ = 0;
    consecutive_slow_ = 0;
    // Jittered cooldown: replicas tripped by one event must not all probe
    // the sick endpoint in the same instant.
    RetryPolicy jitter{options_.open_ms, options_.open_ms, 1.0, 1};
    open_until_ = Clock::now() + std::chrono::milliseconds(jitter.backoff_ms(0));
    // ordering: relaxed — monotonic stat counter (breaker state itself is mutex-guarded).
    robust_counters().breaker_trips.fetch_add(1, std::memory_order_relaxed);
    flight::record(flight::Ev::kBreakerTrip);
  }

  const Options options_;
  mutable Mutex mutex_;
  State state_ BTPU_GUARDED_BY(mutex_){State::kClosed};
  uint32_t consecutive_failures_ BTPU_GUARDED_BY(mutex_){0};
  uint32_t consecutive_slow_ BTPU_GUARDED_BY(mutex_){0};
  uint32_t probes_inflight_ BTPU_GUARDED_BY(mutex_){0};
  uint64_t mean_us_ BTPU_GUARDED_BY(mutex_){0};
  Clock::time_point open_until_ BTPU_GUARDED_BY(mutex_){};
};

// Endpoint-keyed breaker registry (one per ObjectClient). Breakers are
// created on first sight and live for the registry's lifetime — endpoints
// are worker transport addresses, a small, stable set.
class BreakerRegistry {
 public:
  explicit BreakerRegistry(CircuitBreaker::Options options = CircuitBreaker::Options())
      : options_(options) {}

  std::shared_ptr<CircuitBreaker> for_endpoint(const std::string& endpoint) {
    MutexLock lock(mutex_);
    auto& slot = breakers_[endpoint];
    if (!slot) slot = std::make_shared<CircuitBreaker>(options_);
    return slot;
  }

  // Peek without creating (counter/test surface).
  std::shared_ptr<CircuitBreaker> peek(const std::string& endpoint) const {
    MutexLock lock(mutex_);
    auto it = breakers_.find(endpoint);
    return it == breakers_.end() ? nullptr : it->second;
  }

 private:
  const CircuitBreaker::Options options_;
  mutable Mutex mutex_;
  std::unordered_map<std::string, std::shared_ptr<CircuitBreaker>> breakers_
      BTPU_GUARDED_BY(mutex_);
};

}  // namespace btpu
