"""Checkpoint sharded JAX arrays through the object store.

Each device shard of a `jax.Array` is saved as its own object (so saves
parallelize over the striped native data path and, multi-host, every host
writes only the shards it owns), plus one small JSON metadata object with
the global shape, dtype, and each shard's index box.

Restore is sharding-polymorphic: `load_sharded` rebuilds the array under
ANY target sharding — same mesh, fewer/more devices, or a different layout
— via `jax.make_array_from_callback`: each target device slice reads only
the stored shards it overlaps, so a host never materializes more than it
needs plus a bounded cache of source shards.

Role: the device-tier half of SURVEY §5 checkpoint/resume. The native
keystone already persists object *metadata* durably; this persists device
*bytes* — e.g. model weights sharded over a v5e slice checkpointed into
the DRAM/NVMe tiers and restored after a preemption onto a different
topology.
"""

from __future__ import annotations

import json
from typing import TYPE_CHECKING, Any, Callable, Sequence

import numpy as np
import numpy.typing as npt

if TYPE_CHECKING:
    from blackbird_tpu.client import Client
    from blackbird_tpu.fabric import FabricClient

_META_SUFFIX = "/meta"
_SHARD_SUFFIX = "/shard/"


def _index_to_boxes(index: Sequence[slice]) -> list[list[int]]:
    """A shard index (tuple of slices) -> [[start, stop], ...] per dim."""
    boxes: list[list[int]] = []
    for sl in index:
        boxes.append([int(sl.start or 0), int(sl.stop) if sl.stop is not None else -1])
    return boxes


def _boxes_to_index(boxes: Sequence[Sequence[int]],
                    shape: Sequence[int]) -> tuple[slice, ...]:
    return tuple(
        slice(start, stop if stop >= 0 else dim)
        for (start, stop), dim in zip(boxes, shape)
    )


def _box_name(boxes: list[list[int]]) -> str:
    """Deterministic shard-key suffix derived from the index box."""
    return "x".join(f"{a}-{b}" for a, b in boxes) if boxes else "scalar"


def _overwrite(client: Client, key: str, do_put: Callable[[], None]) -> None:
    """Runs `do_put` with overwrite semantics: on OBJECT_ALREADY_EXISTS,
    remove + retry once.

    The store's put_start rejects existing keys (keystone.cpp put lifecycle);
    a checkpoint save must win over whatever a crashed/partial previous save
    left behind, including shards no longer listed in any readable meta.
    """
    try:
        do_put()
        return
    except Exception as exc:  # noqa: BLE001 - duck-typed client
        from blackbird_tpu.native import ErrorCode

        if getattr(exc, "code", None) != int(ErrorCode.OBJECT_ALREADY_EXISTS):
            raise
    try:
        client.remove(key)
    except Exception:  # noqa: BLE001 - lost race / already gone
        pass
    do_put()


def _put_fresh(client: Client, key: str, data: Any, **kwargs: Any) -> None:
    _overwrite(client, key, lambda: client.put(key, data, **kwargs))


def _is_device_class(preferred_class: Any) -> bool:
    name = (preferred_class.name.lower() if hasattr(preferred_class, "name")
            else str(preferred_class or "")).lower()
    return name == "hbm_tpu"


def _fabric_put_fresh(client: Client, fabric: FabricClient, key: str,
                      shard_data: Any, kwargs: dict[str, Any]) -> bool:
    """Fabric leg of the checkpoint writer: True when the shard landed over
    the fabric (with the same overwrite semantics as _put_fresh), False =
    use the staged byte path."""
    from blackbird_tpu.fabric import FabricUnavailable

    pc = kwargs.get("preferred_class")
    name = pc.name.lower() if hasattr(pc, "name") else (pc or "hbm_tpu")
    fabric_kwargs: dict[str, Any] = {"replicas": kwargs.get("replicas", 1),
                                     "preferred_class": name}
    try:
        _overwrite(client, key, lambda: fabric.put(key, shard_data, **fabric_kwargs))
        return True
    except FabricUnavailable:
        return False


def save_sharded(client: Client, prefix: str, array: Any, *, replicas: int = 1,
                 preferred_class: Any = None, ec: tuple[int, int] | None = None,
                 fabric: FabricClient | None = None) -> None:
    """Saves `array` (sharded or single-device) under `prefix`.

    With `fabric` (a `blackbird_tpu.FabricClient`), device-resident shard
    bytes move over the transfer fabric — this process offers each shard
    from its own runtime and the worker pulls it straight into device
    memory, no host staging (the production TPU checkpoint shape). Shards
    the fabric cannot take (no fabric endpoints, EC requested) fall back
    to the staged byte path transparently.

    Writes one object per *distinct* shard box (replicated shards are
    deduplicated) and a `<prefix>/meta` JSON object describing them. The
    layout is multi-host safe by construction: shard keys are derived from
    the shard's index box (not a per-process counter), and every object has
    exactly ONE writer — each shard box is written by the process owning
    the lowest device id replicating that box, and the meta object (plus
    stale-shard cleanup) by the process owning the lowest device id in the
    sharding. Other hosts skip those keys entirely, so no host ever trips
    on another's put.
    """
    import jax  # local: keep module import-light for non-JAX users

    if not isinstance(array, jax.Array):
        array = jax.numpy.asarray(array)
    kwargs: dict[str, Any] = {"replicas": replicas}
    if ec is not None:
        # Checkpoints are the natural erasure-coding consumer: large, cold,
        # durability-critical. ec=(k, m) stores each shard object as one
        # RS-coded copy — any m worker losses tolerated at (k+m)/k storage
        # (replicas is ignored by the store when ec is set). The tiny meta
        # object stays replicated: coding a few hundred bytes k-ways wastes
        # placement slots for no durability gain.
        kwargs["ec"] = ec
    if preferred_class is not None:
        kwargs["preferred_class"] = preferred_class
    my_process = jax.process_index()

    # Global layout from the sharding, identical on every host; the owner
    # of each box (lowest device id among its replicas) is its sole writer.
    index_map = array.sharding.devices_indices_map(array.shape)
    shards_meta: list[dict[str, Any]] = []
    box_owner: dict[str, Any] = {}
    for device, index in index_map.items():
        boxes = _index_to_boxes(index)
        name = _box_name(boxes)
        if name not in box_owner:
            shape = [
                (b if b >= 0 else dim) - a for (a, b), dim in zip(boxes, array.shape)
            ]
            shards_meta.append(
                {"key": f"{prefix}{_SHARD_SUFFIX}{name}", "boxes": boxes, "shape": shape}
            )
        if name not in box_owner or device.id < box_owner[name].id:
            box_owner[name] = device
    meta_owner = min(index_map, key=lambda d: d.id)

    # Stale shards from a previous save under this prefix must go, or a
    # re-save with fewer/different boxes would leak the rest forever.
    old_keys: set[str] = set()
    try:
        old_meta = json.loads(bytes(client.get(prefix + _META_SUFFIX)))
        old_keys = {s["key"] for s in old_meta.get("shards", [])}
    except Exception:  # noqa: BLE001 - no previous checkpoint
        pass

    for shard in array.addressable_shards:
        name = _box_name(_index_to_boxes(shard.index))
        if shard.device != box_owner[name]:
            continue  # another device/host owns this box
        key = f"{prefix}{_SHARD_SUFFIX}{name}"
        if key in old_keys:  # re-save over an existing object
            try:
                client.remove(key)
            except Exception:  # noqa: BLE001 - listed but never written/evicted
                pass
        # Fabric attempt only for device-tier targets: a host-tier
        # placement can never carry fabric endpoints, and probing it would
        # cost a reserve+cancel keystone round trip per shard.
        if fabric is not None and ec is None and _is_device_class(preferred_class):
            if _fabric_put_fresh(client, fabric, key, shard.data, kwargs):
                continue
        host = np.ascontiguousarray(np.asarray(shard.data))
        _put_fresh(client, key, host.reshape(-1).view(np.uint8), **kwargs)

    if meta_owner.process_index != my_process:
        return
    meta: dict[str, Any] = {
        "global_shape": list(array.shape),
        "dtype": np.dtype(array.dtype).str,
        "shards": shards_meta,
    }
    if old_keys:
        try:
            client.remove(prefix + _META_SUFFIX)
        except Exception:  # noqa: BLE001
            pass
    meta_kwargs = {k: v for k, v in kwargs.items() if k != "ec"}
    if ec is not None:
        # The meta must survive what the coded shards survive (m losses).
        # ec=(1, m) degenerates to m+1 single-shard copies (scalar multiples
        # of the data; any ONE reconstructs it) on distinct workers — unlike
        # `replicas`, not clamped by the keystone's max_replicas, so the
        # tolerance actually matches.
        meta_kwargs["ec"] = (1, ec[1])
    _put_fresh(client, prefix + _META_SUFFIX, json.dumps(meta).encode(), **meta_kwargs)
    # Drop old shard objects the new layout no longer references.
    for stale in old_keys - {s["key"] for s in shards_meta}:
        try:
            client.remove(stale)
        except Exception:  # noqa: BLE001
            pass


def load_sharded(client: Client, prefix: str, *, sharding: Any = None,
                 fabric: FabricClient | None = None) -> Any:
    """Restores an array saved by `save_sharded`.

    With `sharding` (any `jax.sharding.Sharding`), returns a `jax.Array`
    laid out accordingly — the target does not need to match the sharding
    the array was saved with. Without it, returns a host `numpy` array.

    With `fabric` (a `blackbird_tpu.FabricClient`), device-tier shards are
    pulled over the transfer fabric by THIS process's runtime instead of
    the worker's staged host lane; host-tier shards fall back to the
    staged path transparently.
    """
    meta = json.loads(bytes(client.get(prefix + _META_SUFFIX)))
    global_shape = tuple(meta["global_shape"])
    dtype = np.dtype(meta["dtype"])

    # Source shards fetched lazily, at most once each.
    cache: dict[str, npt.NDArray[Any]] = {}

    def fetch(shard_meta: dict[str, Any]) -> npt.NDArray[Any]:
        key = shard_meta["key"]
        if key not in cache:
            if fabric is not None:
                raw = np.frombuffer(fabric.get_bytes(key), dtype=np.uint8)
            else:
                raw = np.frombuffer(bytes(client.get(key)), dtype=np.uint8)
            cache[key] = raw.view(dtype).reshape(shard_meta["shape"])
        return cache[key]

    def read_slice(index: tuple[slice, ...]) -> npt.NDArray[Any]:
        """Assembles [index] of the global array from overlapping shards."""
        starts = [sl.start or 0 for sl in index]
        stops = [sl.stop if sl.stop is not None else dim
                 for sl, dim in zip(index, global_shape)]
        out = np.empty([b - a for a, b in zip(starts, stops)], dtype=dtype)
        filled = 0
        for shard_meta in meta["shards"]:
            src_index = _boxes_to_index(shard_meta["boxes"], global_shape)
            # Overlap box between the request and this stored shard.
            o_starts: list[int] = []
            o_stops: list[int] = []
            for (a, b), sl in zip(zip(starts, stops), src_index):
                o_starts.append(max(a, sl.start))
                o_stops.append(min(b, sl.stop))
            if any(a >= b for a, b in zip(o_starts, o_stops)):
                continue
            src = fetch(shard_meta)
            src_sel: tuple[slice, ...] = tuple(
                slice(a - sl.start, b - sl.start)
                for a, b, sl in zip(o_starts, o_stops, src_index)
            )
            dst_sel = tuple(
                slice(a - s, b - s) for a, b, s in zip(o_starts, o_stops, starts)
            )
            out[dst_sel] = src[src_sel]
            filled += int(np.prod([b - a for a, b in zip(o_starts, o_stops)]))
        if filled != out.size:
            raise ValueError(f"checkpoint {prefix!r} is missing data for {index}")
        return out

    if sharding is None:
        full = read_slice(tuple(slice(0, dim) for dim in global_shape))
        return full

    import jax

    return jax.make_array_from_callback(global_shape, sharding, read_slice)


def list_checkpoints(client: Client, root: str = "") -> list[str]:
    """Checkpoint prefixes under `root` (keys holding a readable meta).

    Discovery for resume-after-preemption: a restarting trainer lists
    `ckpt/` and picks its checkpoint without tracking keys externally
    (uses the store's prefix listing, which the reference lacks). To pick
    the LATEST step, parse the step number — lexicographic max() breaks
    across digit-count boundaries ("step999" > "step1000") unless step
    names are zero-padded."""
    suffix = _META_SUFFIX
    return [
        obj["key"][: -len(suffix)]
        for obj in client.list(root)
        if obj["key"].endswith(suffix)
    ]


def remove_checkpoint(client: Client, prefix: str) -> None:
    """Deletes the metadata and every shard object of a checkpoint.

    The meta goes FIRST: a removal interrupted halfway must not leave a
    discoverable-but-unloadable checkpoint for `list_checkpoints` resume.
    The shard sweep then unions the prefix listing (orphans from
    interrupted saves, never listed in any meta) with the meta's own shard
    list (shards stranded mid-put are PENDING and invisible to listing)."""
    shard_keys: set[str] = set()
    try:
        meta = json.loads(bytes(client.get(prefix + _META_SUFFIX)))
        shard_keys.update(s["key"] for s in meta.get("shards", []))
    except Exception:  # noqa: BLE001 - missing/unreadable meta (partial save)
        pass
    try:
        client.remove(prefix + _META_SUFFIX)
    except Exception:  # noqa: BLE001 - already gone
        pass
    shard_keys.update(obj["key"] for obj in client.list(prefix + _SHARD_SUFFIX))
    for key in shard_keys:
        try:
            client.remove(key)
        except Exception:  # noqa: BLE001 - lost race / already gone
            pass
