// Data-movement helpers shared by the keystone mover TUs (repair, drain,
// evict) and the persistence TU (allocator re-adoption mapping).
#include "btpu/keystone/keystone.h"

#include <algorithm>
#include <optional>
#include <unordered_set>

#include "btpu/common/log.h"
#include "btpu/common/trace.h"
#include "btpu/common/crc32c.h"
#include "btpu/common/wire.h"
#include "btpu/ec/rs.h"
#include "btpu/storage/hbm_provider.h"

#include "keystone_internal.h"

namespace btpu::keystone::detail {
// Reads or writes [obj_off, obj_off+len) of one copy through its shards
// (shared walk lives in transport::copy_range_io).
ErrorCode copy_io(transport::TransportClient& client, const CopyPlacement& copy,
                  uint64_t obj_off, uint8_t* buf, uint64_t len, bool is_write) {
  return transport::copy_range_io(client, copy, obj_off, buf, len, is_write);
}

// Shard CRCs are layout-bound: after a byte-identical move (repair top-up,
// demotion), the source's stamps remain valid for the destination only when
// it striped identically. A different layout stays unstamped rather than
// wrongly stamped.
void carry_shard_crcs(const CopyPlacement& src, CopyPlacement& dst) {
  if (src.shard_crcs.size() != src.shards.size()) return;
  if (dst.shards.size() != src.shards.size()) return;
  for (size_t i = 0; i < dst.shards.size(); ++i) {
    if (dst.shards[i].length != src.shards[i].length) return;
  }
  dst.shard_crcs = src.shard_crcs;
}

bool all_shards_on_device(const CopyPlacement& copy) {
  return !copy.shards.empty() &&
         std::all_of(copy.shards.begin(), copy.shards.end(), [](const ShardPlacement& s) {
           return std::holds_alternative<DeviceLocation>(s.location);
         });
}

// Device-resident copy-to-copy transfer: walks both shard lists and moves
// each overlapping segment region-to-region through the HBM provider — on a
// TPU mesh that is the ICI path (chip-to-chip, no host staging).
ErrorCode device_copy_object(const CopyPlacement& src, const CopyPlacement& dst,
                             uint64_t size) {
  size_t si = 0, di = 0;
  uint64_t s_off = 0, d_off = 0, pos = 0;
  while (pos < size) {
    if (si >= src.shards.size() || di >= dst.shards.size())
      return ErrorCode::INVALID_PARAMETERS;
    const ShardPlacement& ss = src.shards[si];
    const ShardPlacement& ds = dst.shards[di];
    const auto& sl = std::get<DeviceLocation>(ss.location);
    const auto& dl = std::get<DeviceLocation>(ds.location);
    const uint64_t n = std::min({ss.length - s_off, ds.length - d_off, size - pos});
    if (auto ec = storage::hbm_copy(sl.region_id, sl.offset + s_off, dl.region_id,
                                    dl.offset + d_off, n);
        ec != ErrorCode::OK)
      return ec;
    pos += n;
    s_off += n;
    d_off += n;
    if (s_off == ss.length) { ++si; s_off = 0; }
    if (d_off == ds.length) { ++di; d_off = 0; }
  }
  return ErrorCode::OK;
}

// Cross-process device fabric: when every overlapping (src, dst) segment
// sits on pools that BOTH advertise a fabric endpoint (hbm_provider v4),
// the keystone orchestrates offer+pull between the two worker processes and
// the bytes ride the device fabric (chip fabric on TPU) — never this
// keystone, never the staged host lane. Returns false on any miss; the
// caller falls back (a partially fabric-moved destination is re-streamed
// whole, which is correct if wasteful — failures here are rare).
bool fabric_copy_object(transport::TransportClient& client, const CopyPlacement& src,
                        const CopyPlacement& dst, uint64_t size, const alloc::PoolMap& pools) {
  static std::atomic<uint64_t> transfer_salt{0x66616272u};  // process-unique ids
  size_t si = 0, di = 0;
  uint64_t s_off = 0, d_off = 0, pos = 0;
  while (pos < size) {
    if (si >= src.shards.size() || di >= dst.shards.size()) return false;
    const ShardPlacement& ss = src.shards[si];
    const ShardPlacement& ds = dst.shards[di];
    const auto* sm = std::get_if<MemoryLocation>(&ss.location);
    const auto* dm = std::get_if<MemoryLocation>(&ds.location);
    if (!sm || !dm) return false;
    auto sp = pools.find(ss.pool_id);
    auto dp = pools.find(ds.pool_id);
    if (sp == pools.end() || dp == pools.end()) return false;
    const std::string& src_fabric = sp->second.fabric_addr;
    if (src_fabric.empty() || dp->second.fabric_addr.empty()) return false;
    // Same process (one fabric server serves all its pools): the host lane
    // is a local memcpy there and a self-pull buys nothing.
    if (src_fabric == dp->second.fabric_addr) return false;
    // Bounded segments: each offer pins a staged device array on the source
    // until pulled (or GC'd), so cap what a single failed round can strand.
    constexpr uint64_t kFabricSeg = 32ull << 20;
    const uint64_t n =
        std::min({ss.length - s_off, ds.length - d_off, size - pos, kFabricSeg});
    const uint64_t id =
        (static_cast<uint64_t>(std::chrono::steady_clock::now().time_since_epoch().count())
         << 16) ^
        transfer_salt.fetch_add(1);
    if (client.fabric_offer(ss.remote, sm->remote_addr + s_off, sm->rkey, n, id) !=
        ErrorCode::OK)
      return false;
    if (client.fabric_pull(ds.remote, dm->remote_addr + d_off, dm->rkey, n, id,
                           src_fabric) != ErrorCode::OK)
      return false;
    pos += n;
    s_off += n;
    d_off += n;
    if (s_off == ss.length) { ++si; s_off = 0; }
    if (d_off == ds.length) { ++di; d_off = 0; }
  }
  return true;
}

// Streams `size` bytes from `src` into every copy in `dsts` through a bounded
// chunk buffer, so keystone-side data movement (repair, demotion) never
// buffers a whole object in host memory. Fully device-resident src->dst
// pairs skip the host entirely (ICI path), and cross-process device pools
// with fabric endpoints move over the device fabric (when `pools` is
// given). The source's CRC (when stamped) is verified as the bytes stream:
// a mover must never propagate a bit-rotten copy — the caller fails over to
// the next source instead. Device->device and fabric moves skip that check
// (those bytes never touch the host); such destinations are reported
// through `used_unchecked` so the caller can queue the object for scrub
// revalidation — stamps are carried, so rot in the source would otherwise
// ride along unchecked until a client verify or ring-walk scrub.
ErrorCode copy_object_bytes(transport::TransportClient& client, const CopyPlacement& src,
                            const std::vector<CopyPlacement>& dsts, uint64_t size,
                            const alloc::PoolMap* pools,
                            std::atomic<uint64_t>* fabric_moves,
                            bool* used_unchecked) {
  std::vector<const CopyPlacement*> staged;
  if (all_shards_on_device(src)) {
    for (const auto& dst : dsts) {
      if (all_shards_on_device(dst) &&
          device_copy_object(src, dst, size) == ErrorCode::OK) {
        // Moved chip-to-chip, no host bytes — and no CRC gate either.
        if (used_unchecked) *used_unchecked = true;
        continue;
      }
      staged.push_back(&dst);
    }
  } else {
    for (const auto& dst : dsts) staged.push_back(&dst);
  }
  if (!staged.empty() && pools) {
    std::vector<const CopyPlacement*> rest;
    for (const CopyPlacement* dst : staged) {
      if (fabric_copy_object(client, src, *dst, size, *pools)) {
        if (fabric_moves) fabric_moves->fetch_add(1);
        if (used_unchecked) *used_unchecked = true;
      } else {
        rest.push_back(dst);
      }
    }
    staged.swap(rest);
  }
  if (staged.empty()) return ErrorCode::OK;

  constexpr uint64_t kChunk = 16ull << 20;
  std::vector<uint8_t> buf(static_cast<size_t>(std::min(size, kChunk)));
  uint32_t crc = 0;
  for (uint64_t off = 0; off < size; off += kChunk) {
    const uint64_t n = std::min(kChunk, size - off);
    if (auto ec = copy_io(client, src, off, buf.data(), n, /*is_write=*/false);
        ec != ErrorCode::OK)
      return ec;
    crc = crc32c(buf.data(), n, crc);
    for (const CopyPlacement* dst : staged) {
      if (auto ec = copy_io(client, *dst, off, buf.data(), n, /*is_write=*/true);
          ec != ErrorCode::OK)
        return ec;
    }
  }
  if (src.content_crc != 0 && crc != src.content_crc) {
    LOG_WARN << "mover source copy " << src.copy_index
             << " failed crc verification; trying another source";
    return ErrorCode::CHECKSUM_MISMATCH;
  }
  return ErrorCode::OK;
}

// Maps a shard placement back to (pool, offset-range) for allocator adoption.
std::optional<std::pair<MemoryPoolId, alloc::Range>> shard_to_range(
    const ShardPlacement& shard, const alloc::PoolMap& pools) {
  auto it = pools.find(shard.pool_id);
  if (it == pools.end()) return std::nullopt;
  if (const auto* mem = std::get_if<MemoryLocation>(&shard.location)) {
    if (mem->remote_addr < it->second.remote.remote_base) return std::nullopt;
    return std::make_pair(shard.pool_id,
                          alloc::Range{mem->remote_addr - it->second.remote.remote_base,
                                       shard.length});
  }
  if (const auto* dev = std::get_if<DeviceLocation>(&shard.location)) {
    return std::make_pair(shard.pool_id, alloc::Range{dev->offset, shard.length});
  }
  if (const auto* file = std::get_if<FileLocation>(&shard.location)) {
    return std::make_pair(shard.pool_id, alloc::Range{file->file_offset, shard.length});
  }
  return std::nullopt;
}

// All-or-nothing mapping of shards onto (pool, range) pairs.
bool append_copy_ranges(const CopyPlacement& copy, const alloc::PoolMap& pools,
                        std::vector<std::pair<MemoryPoolId, alloc::Range>>& out) {
  const size_t mark = out.size();
  for (const auto& shard : copy.shards) {
    auto mapped = shard_to_range(shard, pools);
    if (!mapped) {
      out.resize(mark);
      return false;
    }
    out.push_back(std::move(*mapped));
  }
  return true;
}

std::optional<std::vector<std::pair<MemoryPoolId, alloc::Range>>> map_copies_to_ranges(
    const std::vector<CopyPlacement>& copies, const alloc::PoolMap& pools) {
  std::vector<std::pair<MemoryPoolId, alloc::Range>> out;
  for (const auto& copy : copies) {
    if (!append_copy_ranges(copy, pools, out)) return std::nullopt;
  }
  return out;
}

}  // namespace btpu::keystone::detail
