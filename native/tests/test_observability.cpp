// Observability layer tests: real histogram bucket math + concurrent
// recording, flight-recorder wraparound/dump (tsan-exercised), trace-id
// propagation across the RPC plane and BOTH data-plane engines, span-ring
// dump format, slow-op surfacing, and the /metrics exposition-format
// self-check (parse every line; duplicate or undocumented families fail).
#include <unistd.h>

#include <atomic>
#include <cstdlib>
#include <cstring>
#include <fstream>
#include <map>
#include <set>
#include <sstream>
#include <string>
#include <thread>
#include <vector>

#include "btest.h"
#include "btpu/common/flight_recorder.h"
#include "btpu/common/histogram.h"
#include "btpu/common/trace.h"
#include "btpu/keystone/keystone.h"
#include "btpu/rpc/http_metrics.h"
#include "btpu/rpc/rpc_client.h"
#include "btpu/rpc/rpc_server.h"
#include "btpu/transport/transport.h"

using namespace btpu;

namespace {

struct ScopedEnv {
  ScopedEnv(const char* name, const std::string& value) : name_(name) {
    if (const char* old = std::getenv(name)) saved_ = old;
    ::setenv(name, value.c_str(), 1);
  }
  ~ScopedEnv() {
    if (saved_.empty())
      ::unsetenv(name_);
    else
      ::setenv(name_, saved_.c_str(), 1);
  }
  const char* name_;
  std::string saved_;
};

}  // namespace

// ---- histogram bucket math -------------------------------------------------

BTEST(Histogram, BucketBoundaries) {
  // le bounds are 2^i us: value v lands in the smallest bucket covering it.
  BT_EXPECT_EQ(hist::bucket_index(0), 0u);
  BT_EXPECT_EQ(hist::bucket_index(1), 0u);
  BT_EXPECT_EQ(hist::bucket_index(2), 1u);
  BT_EXPECT_EQ(hist::bucket_index(3), 2u);
  BT_EXPECT_EQ(hist::bucket_index(4), 2u);
  BT_EXPECT_EQ(hist::bucket_index(5), 3u);
  BT_EXPECT_EQ(hist::bucket_index(1 << 20), 20u);
  BT_EXPECT_EQ(hist::bucket_index((1 << 20) + 1), 21u);
  BT_EXPECT_EQ(hist::bucket_index(1ull << 26), 26u);
  BT_EXPECT_EQ(hist::bucket_index((1ull << 26) + 1), hist::kInfBucket);
  BT_EXPECT_EQ(hist::bucket_index(~0ull), hist::kInfBucket);

  hist::Histogram h;
  h.record_us(1);
  h.record_us(2);
  h.record_us(1000);
  h.record_us((1ull << 26) + 5);  // +Inf
  const auto s = h.snapshot();
  BT_EXPECT_EQ(s.count, 4ull);
  BT_EXPECT_EQ(s.sum_us, 1 + 2 + 1000 + ((1ull << 26) + 5));
  BT_EXPECT_EQ(s.buckets[0], 1ull);
  BT_EXPECT_EQ(s.buckets[1], 1ull);
  BT_EXPECT_EQ(s.buckets[10], 1ull);  // 1000 <= 1024 = 2^10
  BT_EXPECT_EQ(s.buckets[hist::kInfBucket], 1ull);
  // Quantiles stay inside the winning bucket's bounds.
  const double p50 = hist::Histogram::quantile_us(s, 0.50);
  BT_EXPECT(p50 >= 1.0 && p50 <= 2.0);
  const double p99 = hist::Histogram::quantile_us(s, 0.99);
  BT_EXPECT(p99 >= 1000.0);
}

BTEST(Histogram, ConcurrentRecordingIsExact) {
  // 8 threads x 20k records: totals must be exact (relaxed atomics, no
  // lost updates) and the stripes must fold into one snapshot. tsan runs
  // this suite — the recording path must be clean under it.
  hist::Histogram h;
  constexpr int kThreads = 8, kPer = 20'000;
  std::vector<std::thread> threads;
  for (int t = 0; t < kThreads; ++t) {
    threads.emplace_back([&h, t] {
      for (int i = 0; i < kPer; ++i)
        h.record_us(static_cast<uint64_t>((t * kPer + i) % 5000));
    });
  }
  for (auto& t : threads) t.join();
  const auto s = h.snapshot();
  BT_EXPECT_EQ(s.count, static_cast<uint64_t>(kThreads) * kPer);
  uint64_t bucket_sum = 0;
  for (size_t i = 0; i < hist::kBucketCount; ++i) bucket_sum += s.buckets[i];
  BT_EXPECT_EQ(bucket_sum, s.count);
}

BTEST(Histogram, RegistryRendersPrometheusShape) {
  hist::op("test_obs_op").record_us(7);
  const std::string text = hist::render_prometheus();
  BT_EXPECT(text.find("# TYPE btpu_op_duration_us histogram") != std::string::npos);
  BT_EXPECT(text.find("btpu_op_duration_us_bucket{op=\"test_obs_op\",le=\"8\"}") !=
            std::string::npos);
  BT_EXPECT(text.find("btpu_op_duration_us_bucket{op=\"test_obs_op\",le=\"+Inf\"}") !=
            std::string::npos);
  BT_EXPECT(text.find("btpu_op_duration_us_count{op=\"test_obs_op\"}") != std::string::npos);
  BT_EXPECT(text.find("btpu_op_duration_us_sum{op=\"test_obs_op\"}") != std::string::npos);
}

// ---- flight recorder -------------------------------------------------------

BTEST(Flight, WraparoundKeepsNewestEvents) {
  // A tiny single-stripe recorder overwritten 3x: the dump returns at most
  // capacity events, and they are the NEWEST ones, in timestamp order.
  flight::Recorder rec(64, 1);
  for (uint64_t i = 0; i < 200; ++i)
    rec.record(flight::Ev::kRetry, /*a0=*/i, 0, 0, /*t_ns=*/1000 + i);
  BT_EXPECT_EQ(rec.recorded(), 200ull);
  const std::string dump = rec.dump_json();
  size_t lines = 0;
  uint64_t first_a0 = ~0ull, last_a0 = 0;
  std::istringstream in(dump);
  std::string line;
  while (std::getline(in, line)) {
    ++lines;
    const auto at = line.find("\"a0\":");
    BT_ASSERT(at != std::string::npos);
    const uint64_t a0 = std::strtoull(line.c_str() + at + 5, nullptr, 10);
    first_a0 = std::min(first_a0, a0);
    last_a0 = std::max(last_a0, a0);
  }
  BT_EXPECT_EQ(lines, 64u);
  BT_EXPECT_EQ(last_a0, 199ull);
  BT_EXPECT_EQ(first_a0, 136ull);  // 200 - 64
}

BTEST(Flight, ConcurrentRecordAndDump) {
  // Writers hammering every stripe while a reader dumps: no torn events
  // surface (seqlock discipline), no crashes, tsan-clean. The dump may
  // drop in-flight slots — that is the design, not a failure.
  flight::Recorder rec(256, 4);
  std::atomic<bool> stop{false};
  std::vector<std::thread> writers;
  for (int t = 0; t < 4; ++t) {
    writers.emplace_back([&rec, &stop, t] {
      // A guaranteed floor of records, then spin until stopped: on a 1-CPU
      // box the dumping main thread can finish before a writer is ever
      // scheduled, and the post-join recorded() check needs real traffic.
      uint64_t i = 0;
      while (i < 1000 || !stop.load(std::memory_order_relaxed)) {
        ++i;
        rec.record(flight::Ev::kCacheHit, static_cast<uint64_t>(t), i, 0x1234, i);
      }
    });
  }
  for (int round = 0; round < 50; ++round) {
    const std::string dump = rec.dump_json();
    std::istringstream in(dump);
    std::string line;
    while (std::getline(in, line)) {
      BT_EXPECT(line.find("\"ev\":\"cache_hit\"") != std::string::npos);
      BT_EXPECT(line.find("\"trace\":\"0000000000001234\"") != std::string::npos);
    }
  }
  stop = true;
  for (auto& w : writers) w.join();
  BT_EXPECT(rec.recorded() > 0);
}

BTEST(Flight, GlobalRecorderAndEventNames) {
  const uint64_t before = flight::recorder().recorded();
  flight::record(flight::Ev::kWalSync, 42, 7);
  BT_EXPECT(flight::recorder().recorded() > before);
  BT_EXPECT_EQ(std::string(flight::ev_name(flight::Ev::kWalSync)), "wal_sync");
  BT_EXPECT_EQ(std::string(flight::ev_name(flight::Ev::kUringSubmit)), "uring_submit");
  BT_EXPECT_EQ(std::string(flight::ev_name(static_cast<flight::Ev>(0xFF))), "unknown");
}

// ---- trace context + span ring ---------------------------------------------

BTEST(Trace, OpScopeMintsAndRestores) {
  BT_EXPECT_EQ(trace::current().trace_id, 0ull);
  uint64_t inner_trace = 0;
  {
    trace::OpScope op("test_obs_root");
    inner_trace = trace::current().trace_id;
    BT_EXPECT(inner_trace != 0);
    BT_EXPECT_EQ(op.trace_id(), inner_trace);
    {
      // Nested public entry: inert, context unchanged.
      trace::OpScope nested("test_obs_nested");
      BT_EXPECT_EQ(trace::current().trace_id, inner_trace);
      BT_EXPECT_EQ(nested.trace_id(), 0ull);
    }
    {
      // A Span becomes the ambient parent while open.
      const uint64_t parent_before = trace::current().span_id;
      TRACE_SPAN("test_obs_child");
      BT_EXPECT(trace::current().span_id != parent_before);
    }
  }
  BT_EXPECT_EQ(trace::current().trace_id, 0ull);
  // The root span landed in the ring under its trace id.
  const std::string dump = trace::dump_spans_json(inner_trace);
  BT_EXPECT(dump.find("\"name\":\"test_obs_root\"") != std::string::npos);
  BT_EXPECT(dump.find("\"name\":\"test_obs_child\"") != std::string::npos);
  // And the filter excludes other traces' spans.
  BT_EXPECT(dump.find("\"name\":\"test_obs_nested\"") == std::string::npos);
}

BTEST(Trace, SlowOpSurfacing) {
  const uint64_t saved = trace::slow_threshold_us();
  trace::set_slow_threshold_us(1);  // everything is slow
  uint64_t id = 0;
  {
    trace::OpScope op("test_obs_slow");
    id = op.trace_id();
    ::usleep(2000);
  }
  trace::set_slow_threshold_us(saved);
  bool found = false;
  for (const auto& slow : trace::recent_slow_ops()) {
    if (slow.trace_id == id) {
      found = true;
      BT_EXPECT_EQ(std::string(slow.op), "test_obs_slow");
      BT_EXPECT(slow.dur_us >= 1000);
    }
  }
  BT_EXPECT(found);
}

BTEST(Trace, DisabledTracingIsInert) {
  trace::set_enabled(false);
  const uint64_t spans_before = trace::span_ring_recorded();
  const uint64_t events_before = flight::recorder().recorded();
  {
    trace::OpScope op("test_obs_off");
    BT_EXPECT_EQ(op.trace_id(), 0ull);
    BT_EXPECT_EQ(trace::current().trace_id, 0ull);
    TRACE_SPAN("test_obs_off_child");
    flight::record(flight::Ev::kRetry);
  }
  trace::set_enabled(true);
  BT_EXPECT_EQ(trace::span_ring_recorded(), spans_before);
  BT_EXPECT_EQ(flight::recorder().recorded(), events_before);
}

// ---- cross-process propagation (RPC plane) ---------------------------------

BTEST(Trace, RpcPropagationStitchesKeystoneSpan) {
  KeystoneConfig cfg;
  cfg.gc_interval_sec = 1;
  cfg.health_check_interval_sec = 1;
  keystone::KeystoneService ks(cfg, nullptr);
  BT_ASSERT(ks.initialize() == ErrorCode::OK);
  rpc::KeystoneRpcServer server(ks, "127.0.0.1", 0);
  BT_ASSERT(server.start() == ErrorCode::OK);
  rpc::KeystoneRpcClient client(server.endpoint());
  BT_ASSERT(client.connect() == ErrorCode::OK);

  uint64_t trace_id = 0;
  {
    trace::OpScope op("test_obs_rpc");
    trace_id = op.trace_id();
    auto r = client.object_exists("nope/key");
    BT_ASSERT_OK(r);
    BT_EXPECT(!r.value());
  }
  // The server handled the call on ITS thread but under OUR trace id: the
  // ring (shared in-process here; /debug/trace across processes) must hold
  // the dispatch span stitched by the propagated ids.
  const std::string dump = trace::dump_spans_json(trace_id);
  BT_EXPECT(dump.find("\"name\":\"keystone.rpc.object_exists\"") != std::string::npos);
  BT_EXPECT(dump.find("\"name\":\"client.rpc\"") != std::string::npos);
  server.stop();
}

// ---- cross-process propagation (data plane, BOTH engines) ------------------

namespace {

void data_plane_propagation_case(bool force_thread_fallback) {
  // Force real socket serving: the pvm/staged same-process shortcuts are
  // per-call dials since PR 9, so the read below actually crosses the TCP
  // data plane and the SERVER side must record the op span.
  ScopedEnv pvm("BTPU_PVM", "0");
  ScopedEnv staged("BTPU_STAGED_DATA", "0");
  ScopedEnv engine("BTPU_IOURING_NET", force_thread_fallback ? "0" : "auto");

  auto server = transport::make_transport_server(TransportKind::TCP);
  BT_ASSERT(server->start("127.0.0.1", 0) == ErrorCode::OK);
  std::vector<uint8_t> region(256 * 1024, 0xAB);
  auto reg = server->register_region(region.data(), region.size(), "obs-pool");
  BT_ASSERT_OK(reg);

  auto client = transport::make_transport_client();
  std::vector<uint8_t> out(4096);
  uint64_t trace_id = 0;
  {
    trace::OpScope op("test_obs_data");
    trace_id = op.trace_id();
    const uint64_t rkey = std::stoull(reg.value().rkey_hex, nullptr, 16);
    BT_ASSERT(client->read(reg.value(), reg.value().remote_base, rkey, out.data(),
                           out.size()) == ErrorCode::OK);
  }
  BT_EXPECT(out[0] == 0xAB && out[4095] == 0xAB);
  // The SERVER records its span after pushing the response's last byte —
  // nothing orders that before the client's read returns (the engine loop
  // may still be draining its completion), so poll briefly instead of
  // asserting an ordering the protocol never promised. Surfaced as a flake
  // on a loaded box by the PR 11 gate runs.
  std::string dump;
  for (int i = 0; i < 400; ++i) {
    dump = trace::dump_spans_json(trace_id);
    if (dump.find("\"name\":\"worker.data.read\"") != std::string::npos) break;
    std::this_thread::sleep_for(std::chrono::milliseconds(5));
  }
  BT_EXPECT(dump.find("\"name\":\"worker.data.read\"") != std::string::npos);
  server->stop();
}

}  // namespace

BTEST(Trace, DataPlanePropagationThreadServer) { data_plane_propagation_case(true); }

BTEST(Trace, DataPlanePropagationUringEngine) {
  if (!transport::uring_runtime_available()) {
    std::printf("  (io_uring unavailable; engine case covered by fallback)\n");
    return;
  }
  data_plane_propagation_case(false);
}

// ---- /metrics exposition self-check ----------------------------------------

namespace {

// Parses Prometheus text exposition: every sample line must belong to a
// family declared by exactly one HELP+TYPE pair; histogram families must
// have well-formed cumulative le-labeled buckets with +Inf == _count.
struct Exposition {
  std::map<std::string, std::string> family_type;  // name -> counter|gauge|histogram
  std::set<std::string> dup_families;
  std::vector<std::string> orphan_samples;
  // histogram series key -> (le -> cumulative count), _sum/_count seen
  std::map<std::string, std::map<double, uint64_t>> hist_buckets;
  std::map<std::string, uint64_t> hist_count;

  static std::string sample_family(const std::string& name) {
    for (const char* suffix : {"_bucket", "_sum", "_count"}) {
      const size_t n = std::strlen(suffix);
      if (name.size() > n && name.compare(name.size() - n, n, suffix) == 0)
        return name.substr(0, name.size() - n);
    }
    return name;
  }

  bool parse(const std::string& text) {
    std::istringstream in(text);
    std::string line;
    std::set<std::string> helped, typed;
    while (std::getline(in, line)) {
      if (line.empty()) continue;
      if (line.rfind("# HELP ", 0) == 0 || line.rfind("# TYPE ", 0) == 0) {
        const bool is_help = line[2] == 'H';
        const size_t start = 7;
        const size_t sp = line.find(' ', start);
        if (sp == std::string::npos) return false;
        const std::string name = line.substr(start, sp - start);
        auto& seen = is_help ? helped : typed;
        if (seen.count(name)) dup_families.insert(name);
        seen.insert(name);
        if (!is_help) family_type[name] = line.substr(sp + 1);
        continue;
      }
      if (line[0] == '#') continue;
      // Sample: name[{labels}] value
      const size_t brace = line.find('{');
      const size_t sp = line.find(' ');
      if (sp == std::string::npos) return false;
      const std::string name = line.substr(0, std::min(brace, sp));
      const std::string family = sample_family(name);
      auto it = family_type.find(family);
      const auto exact = family_type.find(name);
      if (exact != family_type.end() && exact->second != "histogram") {
        // counter/gauge sample: name matches its family exactly
      } else if (it != family_type.end() && it->second == "histogram" && name != family) {
        // histogram sample (_bucket/_sum/_count)
        const size_t vstart = line.rfind(' ');
        const uint64_t value = std::strtoull(line.c_str() + vstart + 1, nullptr, 10);
        if (name == family + "_bucket") {
          const auto le_at = line.find("le=\"");
          if (le_at == std::string::npos) return false;
          const std::string le = line.substr(le_at + 4, line.find('"', le_at + 4) - le_at - 4);
          const double le_v = le == "+Inf" ? 1e300 : std::strtod(le.c_str(), nullptr);
          const std::string series = line.substr(0, vstart);  // unique per labels
          // Key by everything except the le label: strip it.
          std::string key = series;
          const auto cut = key.find(",le=");
          const auto cut2 = key.find("{le=");
          if (cut != std::string::npos) key.erase(cut, key.find('"', cut + 5) - cut + 1);
          else if (cut2 != std::string::npos)
            key.erase(cut2 + 1, key.find('"', cut2 + 5) - cut2);
          hist_buckets[key][le_v] = value;
        } else if (name == family + "_count") {
          hist_count[line.substr(0, vstart)] = value;
        }
      } else {
        orphan_samples.push_back(name);
      }
    }
    return true;
  }
};

}  // namespace

BTEST(Metrics, ExpositionSelfCheck) {
  // Drive real traffic so histogram families exist, then parse EVERY line
  // of the real exposition.
  KeystoneConfig cfg;
  cfg.gc_interval_sec = 1;
  cfg.health_check_interval_sec = 1;
  keystone::KeystoneService ks(cfg, nullptr);
  BT_ASSERT(ks.initialize() == ErrorCode::OK);
  rpc::KeystoneRpcServer server(ks, "127.0.0.1", 0);
  BT_ASSERT(server.start() == ErrorCode::OK);
  rpc::KeystoneRpcClient client(server.endpoint());
  BT_ASSERT(client.connect() == ErrorCode::OK);
  (void)client.object_exists("k").ok();
  hist::wal_sync().record_us(100);
  hist::uring_send().record_us(10);
  hist::data_op("read").record_us(5);
  hist::op("get").record_us(3);

  rpc::MetricsHttpServer metrics(ks, "127.0.0.1", 0);
  const std::string text = metrics.render_metrics();
  server.stop();

  Exposition exp;
  BT_ASSERT(exp.parse(text));
  BT_EXPECT(exp.dup_families.empty());
  for (const auto& f : exp.dup_families)
    btest::report_failure(__FILE__, __LINE__, "duplicate family: " + f);
  for (const auto& o : exp.orphan_samples)
    btest::report_failure(__FILE__, __LINE__,
                          "sample without a declared family: " + o);
  BT_EXPECT(exp.family_type.count("btpu_op_duration_us"));
  BT_EXPECT(exp.family_type.count("btpu_rpc_duration_us"));
  BT_EXPECT(exp.family_type.count("btpu_wal_sync_duration_us"));

  // Histogram well-formedness: cumulative monotone, +Inf present and equal
  // to the series' _count.
  BT_EXPECT(!exp.hist_buckets.empty());
  for (const auto& [series, buckets] : exp.hist_buckets) {
    BT_ASSERT(!buckets.empty());
    uint64_t prev = 0;
    for (const auto& [le, cum] : buckets) {
      if (cum < prev)
        btest::report_failure(__FILE__, __LINE__,
                              "non-monotone cumulative buckets in " + series);
      prev = cum;
    }
    BT_EXPECT(buckets.count(1e300));  // +Inf
  }

  // Every exported family must be documented in docs/OPERATIONS.md — an
  // undocumented metric is a dashboard nobody can interpret.
  const std::string ops_path =
      btest::locate_repo_path("BTPU_OPERATIONS_MD", "docs/OPERATIONS.md");
  std::ifstream ops(ops_path);
  BT_ASSERT(ops.good());
  std::stringstream doc;
  doc << ops.rdbuf();
  const std::string doc_text = doc.str();
  for (const auto& [family, type] : exp.family_type) {
    if (doc_text.find(family) == std::string::npos)
      btest::report_failure(__FILE__, __LINE__,
                            "metrics family '" + family + "' (" + type +
                                ") is not documented in docs/OPERATIONS.md");
  }

  // The worker/coord shape: no keystone — process sections only, and the
  // exposition still parses cleanly.
  rpc::MetricsHttpServer obs(nullptr, "127.0.0.1", 0);
  const std::string worker_text = obs.render_metrics();
  Exposition wexp;
  BT_ASSERT(wexp.parse(worker_text));
  BT_EXPECT(wexp.orphan_samples.empty());
  BT_EXPECT(worker_text.find("btpu_put_starts_total") == std::string::npos);
  BT_EXPECT(worker_text.find("btpu_flight_events_total") != std::string::npos);
}
