#include "btpu/coord/coord_server.h"

#include <sys/socket.h>
#include <sys/time.h>

#include <unordered_map>

#include "btpu/common/log.h"
#include "btpu/common/wire.h"
#include "btpu/coord/coord_proto.h"

namespace btpu::coord {

using wire::Reader;
using wire::Writer;

CoordServer::CoordServer(std::string host, uint16_t port, DurabilityOptions durability)
    : host_(std::move(host)), port_(port), store_(std::move(durability)) {}

CoordServer::~CoordServer() { stop(); }

ErrorCode CoordServer::start() {
  uint16_t bound = 0;
  auto listener = net::tcp_listen(host_, port_, &bound);
  if (!listener.ok()) return listener.error();
  listener_ = std::move(listener).value();
  port_ = bound;
  running_ = true;
  // Every mutation streams into the replication buffer (the sink runs under
  // the store mutex: enqueue only). Registered even with no followers — the
  // buffer is bounded and cheap, and a follower can attach at any time.
  store_.set_replication_sink([this](uint64_t seq, const std::vector<uint8_t>& rec) {
    {
      MutexLock lock(repl_mutex_);
      // Only retained while a mirror is attached (followers always start
      // from a fresh snapshot, so an empty buffer loses nothing) — a non-HA
      // deployment must not pin the last N mutation payloads forever.
      if (mirror_count_ == 0) return;
      repl_buffer_.emplace_back(seq, rec);
      while (repl_buffer_.size() > kReplBufferMax) repl_buffer_.pop_front();
    }
    repl_cv_.notify_all();
  });
  accept_thread_ = std::thread([this] { accept_loop(); });
  LOG_INFO << "coord server listening on " << endpoint()
           << (follower_.load() ? " (follower)" : "");
  return ErrorCode::OK;
}

void CoordServer::set_follower(bool follower) {
  follower_ = follower;
  store_.set_follower(follower);
}

void CoordServer::promote() {
  if (!follower_.exchange(false)) return;
  store_.promote();
}

bool CoordServer::is_mutation(uint8_t opcode) noexcept {
  switch (static_cast<Op>(opcode)) {
    case Op::kPut:
    case Op::kPutTtl:
    case Op::kDel:
    case Op::kLeaseGrant:
    case Op::kLeaseKeepalive:
    case Op::kLeaseRevoke:
    case Op::kPutWithLease:
    case Op::kCampaign:
    case Op::kResign:
    case Op::kCampaignKeepalive:
    case Op::kPutFenced:
    case Op::kDelFenced:
      return true;
    default:
      return false;
  }
}

void CoordServer::stop() {
  if (!running_.exchange(false)) return;
  // Detach the sink first: the store's expiry thread outlives this call (it
  // is joined in ~MemCoordinator, after the repl members are destroyed) and
  // must not fire into a dead buffer/mutex.
  store_.set_replication_sink(nullptr);
  repl_cv_.notify_all();  // wake mirror streamers so they observe !running_
  // Join the accept loop (its poll wakes within 200ms) before touching the
  // listener: closing a socket under a concurrent poll is a data race.
  if (accept_thread_.joinable()) accept_thread_.join();
  listener_.close();
  std::vector<std::thread> threads;
  {
    MutexLock lock(conns_mutex_);
    threads.swap(conn_threads_);
    // Wake connection threads blocked in recv so they can exit.
    for (auto& s : conns_) s->shutdown();
    conns_.clear();
  }
  for (auto& t : threads) {
    if (t.joinable()) t.join();
  }
}

void CoordServer::accept_loop() {
  while (running_) {
    auto sock = net::tcp_accept(listener_, 200);
    if (!sock.ok()) {
      if (sock.error() == ErrorCode::OPERATION_TIMEOUT) continue;
      if (!running_) break;
      continue;
    }
    auto conn = std::make_shared<net::Socket>(std::move(sock).value());
    MutexLock lock(conns_mutex_);
    conns_.push_back(conn);
    conn_threads_.emplace_back([this, conn] { serve_connection(conn); });
  }
}

namespace {

// Serializes pushes on the event channel (watch callbacks fire from the
// expiry thread and from writer threads concurrently).
struct EventChannel {
  Mutex mutex;
  int fd;
  bool alive{true};

  void push(Op op, const std::vector<uint8_t>& payload) {
    MutexLock lock(mutex);
    if (!alive) return;
    if (net::send_frame(fd, static_cast<uint8_t>(op), payload.data(), payload.size()) !=
        ErrorCode::OK) {
      alive = false;
    }
  }
};

}  // namespace

void CoordServer::serve_connection(std::shared_ptr<net::Socket> sock) {
  const int fd = sock->fd();
  uint8_t opcode = 0;
  std::vector<uint8_t> payload;

  // First frame must be kHello declaring the channel kind.
  if (net::recv_frame(fd, opcode, payload) != ErrorCode::OK ||
      static_cast<Op>(opcode) != Op::kHello || payload.size() != 1) {
    return;
  }
  const bool is_event_channel = payload[0] == 1;
  const bool is_mirror_channel = payload[0] == 2;
  {
    Writer w;
    w.put(ErrorCode::OK);
    (void)net::send_frame(fd, opcode, w.buffer().data(), w.size());  // peer gone; serve loop exits on next recv
  }
  if (is_mirror_channel) {
    serve_mirror(sock);
    return;
  }

  auto channel = std::make_shared<EventChannel>();
  channel->fd = fd;
  // Per-connection registrations (cleaned up on disconnect).
  std::unordered_map<int64_t, WatchId> watches;                  // client id -> store id
  std::vector<std::pair<std::string, std::string>> campaigns;    // election, candidate

  while (running_) {
    if (net::recv_frame(fd, opcode, payload) != ErrorCode::OK) break;
    Reader r(payload);
    Writer w;

    if (follower_.load() && is_mutation(opcode)) {
      // Standby: reads are served, mutations belong to the primary. Clients
      // holding both endpoints rotate on NOT_LEADER.
      w.put(ErrorCode::NOT_LEADER);
      MutexLock lock(channel->mutex);
      if (!channel->alive ||
          net::send_frame(fd, opcode, w.buffer().data(), w.size()) != ErrorCode::OK)
        break;
      continue;
    }

    switch (static_cast<Op>(opcode)) {
      case Op::kPing: {
        w.put(ErrorCode::OK);
        break;
      }
      case Op::kGet: {
        std::string key;
        if (!wire::decode(r, key)) { w.put(ErrorCode::INVALID_PARAMETERS); break; }
        auto res = store_.get(key);
        w.put(res.error() == ErrorCode::OK && res.ok() ? ErrorCode::OK : res.error());
        if (res.ok()) wire::encode(w, res.value());
        break;
      }
      case Op::kPut: {
        std::string key, value;
        if (!wire::decode_fields(r, key, value)) { w.put(ErrorCode::INVALID_PARAMETERS); break; }
        w.put(store_.put(key, value));
        break;
      }
      case Op::kPutTtl: {
        std::string key, value;
        int64_t ttl_ms = 0;
        if (!wire::decode_fields(r, key, value, ttl_ms)) {
          w.put(ErrorCode::INVALID_PARAMETERS);
          break;
        }
        w.put(store_.put_with_ttl(key, value, ttl_ms));
        break;
      }
      case Op::kDel: {
        std::string key;
        if (!wire::decode(r, key)) { w.put(ErrorCode::INVALID_PARAMETERS); break; }
        w.put(store_.del(key));
        break;
      }
      case Op::kPutFenced: {
        std::string key, value, election;
        uint64_t epoch = 0;
        if (!wire::decode_fields(r, key, value, election, epoch)) {
          w.put(ErrorCode::INVALID_PARAMETERS);
          break;
        }
        w.put(store_.put_fenced(key, value, election, epoch));
        break;
      }
      case Op::kDelFenced: {
        std::string key, election;
        uint64_t epoch = 0;
        if (!wire::decode_fields(r, key, election, epoch)) {
          w.put(ErrorCode::INVALID_PARAMETERS);
          break;
        }
        w.put(store_.del_fenced(key, election, epoch));
        break;
      }
      case Op::kElectionEpoch: {
        std::string election;
        if (!wire::decode(r, election)) { w.put(ErrorCode::INVALID_PARAMETERS); break; }
        auto res = store_.election_epoch(election);
        w.put(res.ok() ? ErrorCode::OK : res.error());
        if (res.ok()) w.put<uint64_t>(res.value());
        break;
      }
      case Op::kGetPrefix: {
        std::string prefix;
        if (!wire::decode(r, prefix)) { w.put(ErrorCode::INVALID_PARAMETERS); break; }
        auto res = store_.get_with_prefix(prefix);
        w.put(res.ok() ? ErrorCode::OK : res.error());
        if (res.ok()) {
          w.put<uint32_t>(static_cast<uint32_t>(res.value().size()));
          for (const auto& kv : res.value()) {
            wire::encode(w, kv.key);
            wire::encode(w, kv.value);
          }
        }
        break;
      }
      case Op::kLeaseGrant: {
        int64_t ttl_ms = 0;
        if (!wire::decode(r, ttl_ms)) { w.put(ErrorCode::INVALID_PARAMETERS); break; }
        auto res = store_.lease_grant(ttl_ms);
        w.put(res.ok() ? ErrorCode::OK : res.error());
        if (res.ok()) w.put<int64_t>(res.value());
        break;
      }
      case Op::kLeaseKeepalive: {
        int64_t lease = 0;
        if (!wire::decode(r, lease)) { w.put(ErrorCode::INVALID_PARAMETERS); break; }
        w.put(store_.lease_keepalive(lease));
        break;
      }
      case Op::kLeaseRevoke: {
        int64_t lease = 0;
        if (!wire::decode(r, lease)) { w.put(ErrorCode::INVALID_PARAMETERS); break; }
        w.put(store_.lease_revoke(lease));
        break;
      }
      case Op::kPutWithLease: {
        std::string key, value;
        int64_t lease = 0;
        if (!wire::decode_fields(r, key, value, lease)) {
          w.put(ErrorCode::INVALID_PARAMETERS);
          break;
        }
        w.put(store_.put_with_lease(key, value, lease));
        break;
      }
      case Op::kCurrentLeader: {
        std::string election;
        if (!wire::decode(r, election)) { w.put(ErrorCode::INVALID_PARAMETERS); break; }
        auto res = store_.current_leader(election);
        w.put(res.ok() ? ErrorCode::OK : res.error());
        if (res.ok()) wire::encode(w, res.value());
        break;
      }
      case Op::kWatchPrefix: {
        if (!is_event_channel) { w.put(ErrorCode::INVALID_STATE); break; }
        int64_t client_watch_id = 0;
        std::string prefix;
        if (!wire::decode_fields(r, client_watch_id, prefix)) {
          w.put(ErrorCode::INVALID_PARAMETERS);
          break;
        }
        // Idempotent re-registration (reconnect replay + call retry can both
        // send the same id): drop the previous store watch first, or events
        // would be delivered twice.
        auto existing = watches.find(client_watch_id);
        if (existing != watches.end()) {
          warn_if_error(store_.unwatch(existing->second), "replaced-watch unwatch");
          watches.erase(existing);
        }
        auto res = store_.watch_prefix(prefix, [channel, client_watch_id](const WatchEvent& ev) {
          Writer pw;
          pw.put<int64_t>(client_watch_id);
          pw.put<uint8_t>(ev.type == WatchEvent::Type::kPut ? 0 : 1);
          wire::encode(pw, ev.key);
          wire::encode(pw, ev.value);
          channel->push(Op::kEvent, pw.buffer());
        });
        w.put(res.ok() ? ErrorCode::OK : res.error());
        if (res.ok()) watches[client_watch_id] = res.value();
        break;
      }
      case Op::kUnwatch: {
        int64_t client_watch_id = 0;
        if (!wire::decode(r, client_watch_id)) { w.put(ErrorCode::INVALID_PARAMETERS); break; }
        auto it = watches.find(client_watch_id);
        if (it == watches.end()) {
          w.put(ErrorCode::COORD_WATCH_ERROR);
        } else {
          w.put(store_.unwatch(it->second));
          watches.erase(it);
        }
        break;
      }
      case Op::kCampaign: {
        if (!is_event_channel) { w.put(ErrorCode::INVALID_STATE); break; }
        std::string election, candidate;
        int64_t ttl_ms = 0;
        if (!wire::decode_fields(r, election, candidate, ttl_ms)) {
          w.put(ErrorCode::INVALID_PARAMETERS);
          break;
        }
        auto ec = store_.campaign(election, candidate, ttl_ms,
                                  [channel, election, candidate](bool is_leader,
                                                                 uint64_t epoch) {
                                    Writer pw;
                                    wire::encode(pw, election);
                                    wire::encode(pw, candidate);
                                    wire::encode(pw, is_leader);
                                    pw.put<uint64_t>(epoch);
                                    channel->push(Op::kLeaderEvent, pw.buffer());
                                  });
        w.put(ec);
        if (ec == ErrorCode::OK) campaigns.emplace_back(election, candidate);
        break;
      }
      case Op::kResign: {
        std::string election, candidate;
        if (!wire::decode_fields(r, election, candidate)) {
          w.put(ErrorCode::INVALID_PARAMETERS);
          break;
        }
        w.put(store_.resign(election, candidate));
        std::erase(campaigns, std::make_pair(election, candidate));
        break;
      }
      case Op::kCampaignKeepalive: {
        std::string election, candidate;
        if (!wire::decode_fields(r, election, candidate)) {
          w.put(ErrorCode::INVALID_PARAMETERS);
          break;
        }
        w.put(store_.campaign_keepalive(election, candidate));
        break;
      }
      default:
        w.put(ErrorCode::NOT_IMPLEMENTED);
        break;
    }

    // Responses ride the same channel; on the event channel they interleave
    // with pushes, serialized through the channel mutex.
    MutexLock lock(channel->mutex);
    if (!channel->alive ||
        net::send_frame(fd, opcode, w.buffer().data(), w.size()) != ErrorCode::OK) {
      break;
    }
  }

  // Session teardown: drop this connection's watches and candidacies.
  {
    MutexLock lock(channel->mutex);
    channel->alive = false;
  }
  for (const auto& [cid, sid] : watches) warn_if_error(store_.unwatch(sid), "shutdown unwatch");
  for (const auto& [election, candidate] : campaigns) warn_if_error(store_.resign(election, candidate), "shutdown resign");
}

void CoordServer::serve_mirror(std::shared_ptr<net::Socket> sock) {
  const int fd = sock->fd();
  uint8_t opcode = 0;
  std::vector<uint8_t> payload;
  if (net::recv_frame(fd, opcode, payload) != ErrorCode::OK ||
      static_cast<Op>(opcode) != Op::kMirror)
    return;

  // Buffer retention starts BEFORE the snapshot so no record between the
  // two can be missed; the follower skips seqs the snapshot already covers.
  // Count and clear move together under repl_mutex_: a detach that raced a
  // fresh attach must never clear records the new follower still needs.
  {
    MutexLock lock(repl_mutex_);
    ++mirror_count_;
  }
  struct MirrorGuard {
    CoordServer* server;
    ~MirrorGuard() {
      MutexLock lock(server->repl_mutex_);
      if (--server->mirror_count_ == 0)
        server->repl_buffer_.clear();  // nobody is listening anymore
    }
  } guard{this};

  // Consistent handoff: the snapshot's sequence is taken under the store
  // mutex, and every record with a greater sequence is already (or will be)
  // in repl_buffer_ — the sink enqueues before the mutation's lock releases.
  auto [snapshot, snap_seq] = store_.snapshot_with_seq();
  {
    Writer w;
    w.put(ErrorCode::OK);
    w.put<uint64_t>(snap_seq);
    wire::encode(w, snapshot);
    if (net::send_frame(fd, opcode, w.buffer().data(), w.size()) != ErrorCode::OK) return;
  }
  LOG_INFO << "mirror follower attached at seq " << snap_seq;

  uint64_t last_sent = snap_seq;
  auto last_frame = std::chrono::steady_clock::now();
  while (running_) {
    std::vector<std::pair<uint64_t, std::vector<uint8_t>>> pending;
    {
      MutexLock lock(repl_mutex_);
      // Explicit wait loop (not the predicate overload): the analysis
      // checks this body with repl_mutex_ held, whereas a predicate lambda
      // is analyzed as its own unannotated function and would flag the
      // guarded repl_buffer_ reads. One bounded wait preserves the old
      // wait_for(…, 200ms, pred) timing.
      if (running_ && (repl_buffer_.empty() || repl_buffer_.back().first <= last_sent)) {
        repl_cv_.wait_for(lock, std::chrono::milliseconds(200));
      }
      if (!running_) break;
      if (!repl_buffer_.empty() && repl_buffer_.front().first > last_sent + 1) {
        // This follower lagged out of the window; it must re-sync.
        LOG_WARN << "mirror follower too slow (needs seq " << last_sent + 1
                 << ", window starts at " << repl_buffer_.front().first << "); dropping";
        return;
      }
      for (const auto& [seq, rec] : repl_buffer_) {
        if (seq > last_sent) pending.emplace_back(seq, rec);
      }
    }
    for (const auto& [seq, rec] : pending) {
      Writer w;
      w.put<uint64_t>(seq);
      wire::encode(w, rec);
      if (net::send_frame(fd, static_cast<uint8_t>(Op::kMirrorRecord), w.buffer().data(),
                          w.size()) != ErrorCode::OK)
        return;
      last_sent = seq;
      last_frame = std::chrono::steady_clock::now();
    }
    // Liveness: an idle stream still carries pings so the follower's recv
    // timeout distinguishes "quiet primary" from "hung/partitioned primary".
    if (std::chrono::steady_clock::now() - last_frame > std::chrono::milliseconds(500)) {
      if (net::send_frame(fd, static_cast<uint8_t>(Op::kPing), nullptr, 0) != ErrorCode::OK)
        return;
      last_frame = std::chrono::steady_clock::now();
    }
  }
}

// ---- CoordFollower --------------------------------------------------------

CoordFollower::CoordFollower(CoordServer& server, Options options)
    : server_(server), options_(std::move(options)) {}

CoordFollower::~CoordFollower() { stop(); }

ErrorCode CoordFollower::sync_once(net::Socket& sock) {
  auto hp = net::parse_host_port(options_.primary_endpoint);
  if (!hp) return ErrorCode::INVALID_ADDRESS;
  auto dialed = net::tcp_connect(hp->host, hp->port);
  if (!dialed.ok()) return dialed.error();
  sock = std::move(dialed).value();
  // A hung (SIGSTOP'd / partitioned) primary must look like a dead one:
  // the stream carries pings at least every ~500ms, so a 2s recv drought
  // means primary loss and starts the takeover clock.
  {
    struct timeval tv{2, 0};
    ::setsockopt(sock.fd(), SOL_SOCKET, SO_RCVTIMEO, &tv, sizeof(tv));
  }

  uint8_t hello = 2;  // mirror channel
  BTPU_RETURN_IF_ERROR(net::send_frame(sock.fd(), static_cast<uint8_t>(Op::kHello), &hello, 1));
  uint8_t opcode = 0;
  std::vector<uint8_t> payload;
  BTPU_RETURN_IF_ERROR(net::recv_frame(sock.fd(), opcode, payload));

  BTPU_RETURN_IF_ERROR(
      net::send_frame(sock.fd(), static_cast<uint8_t>(Op::kMirror), nullptr, 0));
  BTPU_RETURN_IF_ERROR(net::recv_frame(sock.fd(), opcode, payload));
  if (static_cast<Op>(opcode) != Op::kMirror) return ErrorCode::RPC_FAILED;
  Reader r(payload);
  ErrorCode ec{};
  uint64_t snap_seq = 0;
  std::vector<uint8_t> snapshot;
  if (!r.get(ec) || ec != ErrorCode::OK || !r.get(snap_seq) ||
      !wire::decode(r, snapshot))
    return ec != ErrorCode{} ? ec : ErrorCode::RPC_FAILED;
  return server_.store().load_replica_snapshot(snapshot);
}

ErrorCode CoordFollower::start() {
  net::Socket sock;
  if (auto ec = sync_once(sock); ec != ErrorCode::OK) {
    LOG_ERROR << "standby initial sync with " << options_.primary_endpoint
              << " failed: " << to_string(ec);
    return ec;
  }
  LOG_INFO << "standby synced from " << options_.primary_endpoint;
  thread_ = std::thread([this, s = std::move(sock)]() mutable { run(std::move(s)); });
  return ErrorCode::OK;
}

void CoordFollower::stop() {
  stopping_ = true;
  {
    MutexLock lock(sock_mutex_);
    if (live_sock_) live_sock_->shutdown();
  }
  if (thread_.joinable()) thread_.join();
}

void CoordFollower::run(net::Socket sock) {
  using Clock = std::chrono::steady_clock;
  while (!stopping_) {
    {
      MutexLock lock(sock_mutex_);
      live_sock_ = &sock;
    }
    // Stream records until the connection dies.
    uint8_t opcode = 0;
    std::vector<uint8_t> payload;
    while (!stopping_) {
      if (net::recv_frame(sock.fd(), opcode, payload) != ErrorCode::OK) break;
      if (static_cast<Op>(opcode) != Op::kMirrorRecord) continue;  // pings: liveness only
      Reader r(payload);
      uint64_t seq = 0;
      std::vector<uint8_t> rec;
      if (!r.get(seq) || !wire::decode(r, rec)) break;
      if (auto ec = server_.store().apply_replica_record(rec); ec != ErrorCode::OK) {
        LOG_ERROR << "mirror record " << seq << " failed to apply: " << to_string(ec);
      }
    }
    {
      MutexLock lock(sock_mutex_);
      live_sock_ = nullptr;
    }
    sock.close();
    if (stopping_) return;

    // Primary lost: retry within the grace window, then take over.
    const auto deadline = Clock::now() + std::chrono::milliseconds(options_.takeover_grace_ms);
    bool resynced = false;
    while (!stopping_ && Clock::now() < deadline) {
      std::this_thread::sleep_for(std::chrono::milliseconds(options_.redial_interval_ms));
      if (stopping_) return;
      if (sync_once(sock) == ErrorCode::OK) {
        LOG_INFO << "standby re-synced from " << options_.primary_endpoint;
        resynced = true;
        break;
      }
    }
    if (resynced) continue;
    if (stopping_) return;
    promoted_ = true;
    server_.promote();
    return;
  }
}

}  // namespace btpu::coord
