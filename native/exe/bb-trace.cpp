// bb-trace: collects span-ring dumps from every process of a cluster and
// stitches ONE distributed trace into Chrome/Perfetto trace_event JSON.
//
// Sources (mix freely):
//   --endpoint H:P   GET /debug/trace from a process's metrics/obs HTTP
//                    server (bb-keystone --metrics-port, bb-worker/bb-coord
//                    BTPU_OBS_PORT)
//   --file PATH      a spans-*.jsonl file (BTPU_TRACE_DUMP at-exit dumps,
//                    or a saved /debug/trace body)
//   --dir DIR        every spans-*.jsonl under DIR
//
// Selection:
//   --trace HEX      stitch exactly this 64-bit trace id (the id a slow-op
//                    log line / bb-client prints)
//   --list           print the collected trace ids (span count, root op,
//                    total duration) and exit
//   (default)        the trace with the LONGEST root span — "explain the
//                    slowest op I just ran"
//
// Output (--out, default trace.json): {"traceEvents":[...]} with complete
// ("X") events on the collecting processes' real pid/tid tracks and
// process_name metadata — drag into https://ui.perfetto.dev. Timestamps
// are CLOCK_MONOTONIC microseconds, comparable across processes on one
// host (cross-host spans still nest per process; absolute alignment needs
// synchronized clocks).
#include <dirent.h>
#include <unistd.h>

#include <algorithm>
#include <cstdio>
#include <cstring>
#include <fstream>
#include <map>
#include <sstream>
#include <string>
#include <vector>

#include "btpu/net/net.h"

using namespace btpu;

namespace {

struct SpanRec {
  std::string name;
  uint64_t trace{0}, span{0}, parent{0};
  double start_us{0}, dur_us{0};
  int pid{0};
  uint64_t tid{0};
  std::string proc;
};

// Minimal field extraction for OUR fixed span-line format (trace.cpp
// dump_spans_json) — not a general JSON parser on purpose: hostile input
// here is a malformed line, and the answer is skipping it.
bool find_string(const std::string& line, const char* key, std::string& out) {
  const std::string pat = std::string("\"") + key + "\":\"";
  const auto at = line.find(pat);
  if (at == std::string::npos) return false;
  const auto start = at + pat.size();
  const auto end = line.find('"', start);
  if (end == std::string::npos) return false;
  out = line.substr(start, end - start);
  return true;
}

bool find_number(const std::string& line, const char* key, double& out) {
  const std::string pat = std::string("\"") + key + "\":";
  const auto at = line.find(pat);
  if (at == std::string::npos) return false;
  out = std::strtod(line.c_str() + at + pat.size(), nullptr);
  return true;
}

bool parse_span_line(const std::string& line, SpanRec& rec) {
  std::string trace_hex, span_hex, parent_hex;
  double start = 0, dur = 0, pid = 0, tid = 0;
  if (!find_string(line, "name", rec.name)) return false;
  if (!find_string(line, "trace", trace_hex)) return false;
  if (!find_string(line, "span", span_hex)) return false;
  if (!find_string(line, "parent", parent_hex)) return false;
  if (!find_number(line, "start_us", start)) return false;
  if (!find_number(line, "dur_us", dur)) return false;
  (void)find_number(line, "pid", pid);
  (void)find_number(line, "tid", tid);
  (void)find_string(line, "proc", rec.proc);
  rec.trace = std::strtoull(trace_hex.c_str(), nullptr, 16);
  rec.span = std::strtoull(span_hex.c_str(), nullptr, 16);
  rec.parent = std::strtoull(parent_hex.c_str(), nullptr, 16);
  rec.start_us = start;
  rec.dur_us = dur;
  rec.pid = static_cast<int>(pid);
  rec.tid = static_cast<uint64_t>(tid);
  return rec.trace != 0;
}

void parse_body(const std::string& body, std::vector<SpanRec>& out) {
  std::istringstream in(body);
  std::string line;
  while (std::getline(in, line)) {
    SpanRec rec;
    if (parse_span_line(line, rec)) out.push_back(std::move(rec));
  }
}

// One-shot HTTP GET, returning the body (empty on any failure).
std::string http_get(const std::string& endpoint, const std::string& path) {
  auto hp = net::parse_host_port(endpoint);
  if (!hp) {
    std::fprintf(stderr, "bb-trace: bad endpoint '%s'\n", endpoint.c_str());
    return "";
  }
  auto sock = net::tcp_connect(hp->host, hp->port, 3000);
  if (!sock.ok()) {
    std::fprintf(stderr, "bb-trace: cannot reach %s\n", endpoint.c_str());
    return "";
  }
  const std::string req = "GET " + path + " HTTP/1.1\r\nHost: " + endpoint +
                          "\r\nConnection: close\r\n\r\n";
  if (net::write_all(sock.value().fd(), req.data(), req.size()) != ErrorCode::OK) return "";
  std::string resp;
  char buf[16384];
  while (true) {
    const ssize_t n = ::read(sock.value().fd(), buf, sizeof(buf));
    if (n <= 0) break;
    resp.append(buf, static_cast<size_t>(n));
    if (resp.size() > (256u << 20)) break;  // runaway peer
  }
  const auto at = resp.find("\r\n\r\n");
  return at == std::string::npos ? "" : resp.substr(at + 4);
}

std::string json_escape(const std::string& s) {
  std::string out;
  out.reserve(s.size());
  for (char ch : s) {
    if (ch == '"' || ch == '\\') {
      out.push_back('\\');
      out.push_back(ch);
    } else if (static_cast<unsigned char>(ch) >= 0x20) {
      out.push_back(ch);
    }
  }
  return out;
}

}  // namespace

int main(int argc, char** argv) {
  std::vector<std::string> endpoints, files;
  std::string out_path = "trace.json";
  uint64_t want_trace = 0;
  bool list_only = false;
  for (int i = 1; i < argc; ++i) {
    const auto need = [&](const char* flag) {
      if (i + 1 >= argc) {
        std::fprintf(stderr, "bb-trace: %s needs a value\n", flag);
        std::exit(2);
      }
      return std::string(argv[++i]);
    };
    if (!std::strcmp(argv[i], "--endpoint")) endpoints.push_back(need("--endpoint"));
    else if (!std::strcmp(argv[i], "--file")) files.push_back(need("--file"));
    else if (!std::strcmp(argv[i], "--dir")) {
      const std::string dir = need("--dir");
      if (DIR* d = ::opendir(dir.c_str())) {
        while (dirent* e = ::readdir(d)) {
          const std::string n = e->d_name;
          if (n.rfind("spans-", 0) == 0) files.push_back(dir + "/" + n);
        }
        ::closedir(d);
      } else {
        std::fprintf(stderr, "bb-trace: cannot read dir %s\n", dir.c_str());
      }
    } else if (!std::strcmp(argv[i], "--trace")) {
      want_trace = std::strtoull(need("--trace").c_str(), nullptr, 16);
    } else if (!std::strcmp(argv[i], "--out")) out_path = need("--out");
    else if (!std::strcmp(argv[i], "--list")) list_only = true;
    else {
      std::printf(
          "usage: bb-trace [--endpoint H:P]... [--file PATH]... [--dir DIR]\n"
          "                [--trace HEX] [--list] [--out trace.json]\n"
          "Collects /debug/trace span dumps from cluster processes (or\n"
          "BTPU_TRACE_DUMP files) and stitches one trace id into\n"
          "Chrome/Perfetto trace_event JSON (load at ui.perfetto.dev).\n");
      return std::strcmp(argv[i], "--help") == 0 ? 0 : 2;
    }
  }
  if (endpoints.empty() && files.empty()) {
    std::fprintf(stderr, "bb-trace: no sources (need --endpoint/--file/--dir; --help)\n");
    return 2;
  }

  std::vector<SpanRec> spans;
  for (const auto& ep : endpoints) parse_body(http_get(ep, "/debug/trace"), spans);
  for (const auto& f : files) {
    std::ifstream in(f);
    if (!in.good()) {
      std::fprintf(stderr, "bb-trace: cannot read %s\n", f.c_str());
      continue;
    }
    std::stringstream ss;
    ss << in.rdbuf();
    parse_body(ss.str(), spans);
  }
  if (spans.empty()) {
    std::fprintf(stderr, "bb-trace: no spans collected\n");
    return 1;
  }

  // Per-trace rollup: span count + the root span (parent == 0).
  struct TraceInfo {
    size_t count{0};
    double root_dur_us{0};
    std::string root_name;
  };
  std::map<uint64_t, TraceInfo> traces;
  for (const auto& s : spans) {
    auto& t = traces[s.trace];
    ++t.count;
    if (s.parent == 0 && s.dur_us >= t.root_dur_us) {
      t.root_dur_us = s.dur_us;
      t.root_name = s.name;
    }
  }
  if (list_only) {
    std::printf("%-18s %7s %12s  %s\n", "trace_id", "spans", "root_dur_us", "root_op");
    std::vector<std::pair<uint64_t, TraceInfo>> rows(traces.begin(), traces.end());
    std::sort(rows.begin(), rows.end(), [](const auto& a, const auto& b) {
      return a.second.root_dur_us > b.second.root_dur_us;
    });
    for (const auto& [id, t] : rows)
      std::printf("%016llx %7zu %12.1f  %s\n", static_cast<unsigned long long>(id),
                  t.count, t.root_dur_us, t.root_name.c_str());
    return 0;
  }
  if (want_trace == 0) {
    // Default: the trace whose ROOT span ran longest — the op to explain.
    double best = -1;
    for (const auto& [id, t] : traces) {
      if (t.root_dur_us > best) {
        best = t.root_dur_us;
        want_trace = id;
      }
    }
  }
  if (traces.find(want_trace) == traces.end()) {
    std::fprintf(stderr, "bb-trace: trace %016llx not found in the collected spans "
                 "(try --list)\n",
                 static_cast<unsigned long long>(want_trace));
    return 1;
  }

  std::ofstream out(out_path);
  if (!out.good()) {
    std::fprintf(stderr, "bb-trace: cannot write %s\n", out_path.c_str());
    return 1;
  }
  out << "{\"traceEvents\":[\n";
  bool first = true;
  std::map<int, std::string> proc_names;
  size_t emitted = 0;
  for (const auto& s : spans) {
    if (s.trace != want_trace) continue;
    if (!proc_names.count(s.pid)) proc_names[s.pid] = s.proc;
    char line[768];
    std::snprintf(line, sizeof(line),
                  "%s{\"ph\":\"X\",\"name\":\"%s\",\"cat\":\"btpu\",\"ts\":%.3f,"
                  "\"dur\":%.3f,\"pid\":%d,\"tid\":%llu,\"args\":{\"span\":\"%016llx\","
                  "\"parent\":\"%016llx\",\"trace\":\"%016llx\"}}",
                  first ? "" : ",\n", json_escape(s.name).c_str(), s.start_us,
                  s.dur_us > 0 ? s.dur_us : 0.001, s.pid,
                  static_cast<unsigned long long>(s.tid),
                  static_cast<unsigned long long>(s.span),
                  static_cast<unsigned long long>(s.parent),
                  static_cast<unsigned long long>(s.trace));
    out << line;
    first = false;
    ++emitted;
  }
  for (const auto& [pid, name] : proc_names) {
    char line[256];
    std::snprintf(line, sizeof(line),
                  "%s{\"ph\":\"M\",\"name\":\"process_name\",\"pid\":%d,"
                  "\"args\":{\"name\":\"%s\"}}",
                  first ? "" : ",\n", pid, json_escape(name).c_str());
    out << line;
    first = false;
  }
  out << "\n]}\n";
  std::printf("bb-trace: wrote %zu spans of trace %016llx (%zu process(es)) to %s\n",
              emitted, static_cast<unsigned long long>(want_trace), proc_names.size(),
              out_path.c_str());
  return 0;
}
