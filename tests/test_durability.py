"""Crash-proof durability from the Python surface: acked objects survive a
full cluster restart on the same persist dir.

The native crash harnesses (bb-crash's labeled crash-point matrix,
bb-soak --kill9) kill the process mid-operation; this tier-1 test covers the
clean half of the same contract end to end through the bindings — every put
the client saw acked must read back bit-exact from a NEW cluster booted on
the same coordinator WAL/snapshot dir, and acked removes must stay removed.
Inline-tier sized objects only: their bytes ride the durable metadata
records (RAM pool bytes die with the process by design)."""

import os

from blackbird_tpu import Client, EmbeddedCluster
from blackbird_tpu.native import BtpuError, ErrorCode
from pathlib import Path


def test_acked_objects_survive_cluster_restart(tmp_path: Path) -> None:
    data_dir = str(tmp_path / "persist")
    rng = os.urandom
    acked = {f"dur/obj{i}": rng(64 + 137 * i % 1900) for i in range(24)}

    with EmbeddedCluster(workers=2, pool_bytes=16 << 20, data_dir=data_dir) as cluster:
        client = cluster.client()
        for key, value in acked.items():
            # replicas=1 keeps the put inline-eligible; ttl 0 = never
            # expires, so recovery owes every single ack.
            client.put(key, value, replicas=1, ttl_ms=0)
        # Acked removes must stay removed after the restart too.
        for key in list(acked)[::5]:
            client.remove(key)
            del acked[key]

    with EmbeddedCluster(workers=2, pool_bytes=16 << 20, data_dir=data_dir) as revived:
        client = revived.client()
        for key, value in acked.items():
            assert client.get(key) == value, f"{key} lost or corrupt after restart"
        for i in range(0, 24, 5):
            try:
                client.get(f"dur/obj{i}")
                assert False, "acked remove resurrected after restart"
            except BtpuError as err:
                assert err.code == ErrorCode.OBJECT_NOT_FOUND
        # Accounting came back consistent: exactly the acked live set.
        assert client.stats()["objects"] == len(acked)
        # And the revived cluster still takes fresh durable writes.
        client.put("dur/fresh", b"post-restart", replicas=1, ttl_ms=0)
        assert client.get("dur/fresh") == b"post-restart"


def test_sync_per_record_mode_round_trips(tmp_path: Path) -> None:
    """group_commit_us=0 (fdatasync per record) is the compatibility mode —
    same acked==durable contract, no batching."""
    data_dir = str(tmp_path / "sync-each")
    with EmbeddedCluster(workers=1, pool_bytes=8 << 20, data_dir=data_dir,
                         group_commit_us=0) as cluster:
        client = cluster.client()
        client.put("dur/sync", b"x" * 512, replicas=1, ttl_ms=0)
    with EmbeddedCluster(workers=1, pool_bytes=8 << 20, data_dir=data_dir,
                         group_commit_us=0) as revived:
        assert revived.client().get("dur/sync") == b"x" * 512


def test_lane_counters_export_persist_backlog() -> None:
    counters = Client.lane_counters()
    assert "persist_retry_backlog" in counters
    assert counters["persist_retry_backlog"] == 0
