// Schedule-exploration harness ("the race hunter"): deterministic
// interleaving control over the annotated lock layer plus the hand-rolled
// lock-free kernels.
//
// ASan/TSan only ever observe the ONE schedule the OS happens to run; every
// real concurrency bug this repo has shipped (the rotate_keystone UAF, the
// hedge notify-after-unlock race, the bb-soak worker-swap race) was a
// SCHEDULE bug that survived many green sanitizer runs. This harness makes
// schedules a searchable input instead of an accident:
//
//   * Preemption points are injected at every annotated lock acquire /
//     release (btpu::Mutex / SharedMutex / the scoped guards), every
//     CondVarAny wait/notify, and at BTPU_ATOMIC_YIELD() markers threaded
//     through the lock-free kernels (flight recorder, histograms, span
//     ring, AtomicAccessStamp).
//   * While a sched::Run is armed, exactly ONE enrolled thread runs at a
//     time; at each preemption point a policy picks who runs next:
//       - PCT (Burckhardt et al., ASPLOS '10): seeded random thread
//         priorities with d-1 random priority-change points — probabilistic
//         bug-depth guarantees, one uint64 seed reproduces the schedule.
//       - DFS: bounded-exhaustive enumeration of EVERY interleaving of a
//         small fixture (sched::explore_dfs), for the lock-free kernels.
//   * Any assertion/sanitizer failure while armed prints the seed; running
//     with BTPU_SCHED_SEED=<n> (or the same Run options) replays the exact
//     interleaving, deterministically.
//
// Build shape: everything here compiles to zero-cost no-ops unless
// BTPU_SCHED is defined (the asan/tsan/`make sched` trees define it; the
// release/bench build does NOT — the bench.py cached-get guard proves the
// hot path is untouched). Unscheduled processes in a sched build pay one
// relaxed atomic load per hook.
//
// Threading model (docs/CORRECTNESS.md §10 for the full map):
//   * Threads participate only when ENROLLED (sched::Enroll RAII with an
//     explicit deterministic id, or the adopt protocol below for
//     library-spawned threads). Unenrolled threads run free; their
//     unlock/notify still wake enrolled waiters, so fixtures may lean on
//     unenrolled helpers (embedded servers) without wedging the scheduler.
//   * A blocked enrolled thread (mutex wait, cv wait) hands the token over;
//     if every enrolled thread blocks and nothing can wake them, the hang
//     watchdog prints the seed + per-thread wait states and aborts — the
//     hunter detects deadlocks and lost wakeups, not just races.
//   * Library code that spawns a thread an armed fixture must control
//     calls BTPU_SCHED_DECL_SPAWN() before std::thread{...} and
//     BTPU_SCHED_ADOPT_SPAWNED() first thing inside the body (see
//     client.cpp hedged_race). Both are no-ops unless a Run is armed.
#pragma once

#include <cstdint>

#if defined(BTPU_SCHED)
#include <atomic>
#include <functional>
#include <vector>
#endif

namespace btpu::sched {

// True in builds with the hooks compiled in (-DBTPU_SCHED). Tests print a
// notice and run their fixtures unscheduled when false.
#if defined(BTPU_SCHED)
inline constexpr bool kCompiledIn = true;
#else
inline constexpr bool kCompiledIn = false;
#endif
inline constexpr bool compiled_in() noexcept { return kCompiledIn; }

#if defined(BTPU_SCHED)

// Preemption-point vocabulary (reported in hang dumps; also the hook map).
enum class Point : uint8_t {
  kLock = 0,      // about to acquire a Mutex/SharedMutex (exclusive)
  kLockShared,    // about to acquire shared
  kUnlock,        // just released (exclusive or shared)
  kCvWait,        // CondVarAny wait about to park
  kCvNotify,      // CondVarAny notify_one/notify_all
  kAtomic,        // BTPU_ATOMIC_YIELD() inside a lock-free kernel
  kYield,         // explicit test yield (BTPU_SCHED_YIELD)
};

// ---- fast-path gates (one relaxed load when disarmed) ----------------------
extern std::atomic<bool> g_armed;
struct ThreadState;
ThreadState*& self_slot() noexcept;  // thread_local enrollment pointer

// ordering: relaxed — arming gate: enrollment (the other half of on()) happens-before any schedule decision via the scheduler mutex; unenrolled threads only ever see a cheap false.
inline bool armed() noexcept { return g_armed.load(std::memory_order_relaxed); }
// This thread is enrolled in an armed run: hooks must take the slow path.
inline bool on() noexcept { return armed() && self_slot() != nullptr; }

// ---- slow-path entry points (sched.cpp) ------------------------------------
// Decision point: hand the token to whoever the policy picks (possibly us).
void preempt(Point p, const void* addr) noexcept;
// Scheduled blocking-acquire protocol: deterministic try_lock/park loop.
// try_fn is invoked with the scheduler lock held, so it must be nonblocking
// (std try_lock is). Returns once the lock is held.
void acquire(Point p, const void* addr, bool (*try_fn)(void*), void* m) noexcept;
// Release notification: wakes enrolled threads parked on `addr`. Safe (and
// cheap) from ANY thread while a run is armed, enrolled or not.
void on_unlock(const void* addr) noexcept;
// CondVar protocol: register under the scheduler lock BEFORE releasing the
// user lock (no lost wakeups), park after, reacquire outside. park_wait
// returns true when woken by a notify, false when the scheduler fired the
// (virtual) timeout of a timed wait — time never passes for real.
struct CvWaitTicket {
  void* rep{nullptr};
};
CvWaitTicket cv_register(const void* cv_addr, bool timed) noexcept;
bool cv_park(CvWaitTicket t) noexcept;
void on_notify(const void* cv_addr, bool all) noexcept;

// ---- enrollment ------------------------------------------------------------
// RAII enrollment with an explicit deterministic id (0-based, unique per
// Run; fixtures assign ids in spawn order). Inert when no run is armed.
class Enroll {
 public:
  explicit Enroll(uint32_t id) noexcept;
  ~Enroll();
  Enroll(const Enroll&) = delete;
  Enroll& operator=(const Enroll&) = delete;

 private:
  bool active_{false};
};

// Adopt protocol for library-spawned threads (see header comment).
void decl_spawn() noexcept;
class AdoptScope {
 public:
  AdoptScope() noexcept;
  ~AdoptScope();
  AdoptScope(const AdoptScope&) = delete;
  AdoptScope& operator=(const AdoptScope&) = delete;

 private:
  bool active_{false};
};

// ---- run control -----------------------------------------------------------
enum class Mode : uint8_t { kPct = 0, kDfs = 1 };

struct RunOptions {
  uint64_t seed{1};
  Mode mode{Mode::kPct};
  // Enrollment barrier: no thread runs until this many have enrolled
  // (deterministic start). 0 = start immediately, schedule as they arrive.
  uint32_t threads{0};
  // PCT depth d: d-1 priority-change points (bug depth the run targets).
  uint32_t pct_depth{3};
  // Estimated step count the change points are sampled from.
  uint32_t pct_steps{64};
  // Step budget: exceeding it is a livelock verdict (seed printed, abort).
  uint64_t max_steps{1u << 20};
  // All-blocked / no-progress watchdog, ms (BTPU_SCHED_HANG_MS overrides).
  uint32_t hang_ms{5000};
};

// Arms schedule control for its scope. Construct BEFORE spawning enrolled
// threads and destroy AFTER joining them (the destructor waits for every
// enrolled thread — including adopted detached ones — to retire). One Run
// at a time per process.
class Run {
 public:
  explicit Run(const RunOptions& options);
  ~Run();
  Run(const Run&) = delete;
  Run& operator=(const Run&) = delete;
};

// Seed of the innermost armed run (0 = none) — failure banners print it.
uint64_t current_seed() noexcept;

// ---- bounded-exhaustive DFS ------------------------------------------------
struct ExploreResult {
  uint64_t schedules{0};   // schedules fully executed
  bool complete{false};    // the bounded space was exhausted (no truncation)
  uint64_t max_decisions{0};
};

struct ExploreOptions {
  uint32_t threads{0};          // enrollment barrier per schedule
  uint64_t max_schedules{0};    // 0 = BTPU_SCHED_DFS_MAX (default 200000)
  uint64_t max_steps{1u << 16};
};

// Runs `fixture` repeatedly, enumerating every scheduling decision of the
// enrolled threads depth-first. The fixture must be deterministic given the
// schedule (spawn the same threads with the same ids, bounded ops). Stops
// early (complete=false) only when max_schedules is hit — callers must
// treat that as a failure, never as coverage.
ExploreResult explore_dfs(const ExploreOptions& options,
                          const std::function<void()>& fixture);

// ---- planted mutants (test-only) -------------------------------------------
// True when BTPU_SCHED_MUTANT names `name`. Library code re-injects a
// historical concurrency bug behind this so the planted-mutant matrix can
// prove the hunter finds the exact bug class this repo actually ships.
// Never true outside BTPU_SCHED builds (the code is compiled out).
bool mutant_enabled(const char* name) noexcept;

#else  // !BTPU_SCHED — inert stand-ins so tests compile hook-free

enum class Mode : uint8_t { kPct = 0, kDfs = 1 };
struct RunOptions {
  uint64_t seed{1};
  Mode mode{Mode::kPct};
  uint32_t threads{0};
  uint32_t pct_depth{3};
  uint32_t pct_steps{64};
  uint64_t max_steps{1u << 20};
  uint32_t hang_ms{5000};
};
class Run {
 public:
  explicit Run(const RunOptions&) noexcept {}
};
class Enroll {
 public:
  explicit Enroll(uint32_t) noexcept {}
};
// Hook-free builds never arm a run: callers that branch on armed() (e.g. the
// client op core picking per-op adopted threads over persistent lanes) fold
// to the production path at compile time.
inline bool armed() noexcept { return false; }
inline bool on() noexcept { return false; }
inline uint64_t current_seed() noexcept { return 0; }
struct ExploreResult {
  uint64_t schedules{0};
  bool complete{false};
  uint64_t max_decisions{0};
};
struct ExploreOptions {
  uint32_t threads{0};
  uint64_t max_schedules{0};
  uint64_t max_steps{1u << 16};
};

// Hookless stub: runs the fixture once, free-scheduled. complete=false so
// callers can tell no exhaustive exploration happened.
template <typename Fn>
inline ExploreResult explore_dfs(const ExploreOptions&, Fn&& fixture) {
  fixture();
  return ExploreResult{1, false, 0};
}

#endif  // BTPU_SCHED

}  // namespace btpu::sched

// ---- hook macros ------------------------------------------------------------
// BTPU_ATOMIC_YIELD(): a preemption point inside a lock-free kernel. Place
// one between the atomic steps whose interleavings the DFS mode must
// enumerate (claim/publish/read-validate edges). Compiles to nothing
// outside BTPU_SCHED builds.
#if defined(BTPU_SCHED)
#define BTPU_ATOMIC_YIELD()                                            \
  do {                                                                 \
    if (::btpu::sched::on())                                           \
      ::btpu::sched::preempt(::btpu::sched::Point::kAtomic, nullptr);  \
  } while (0)
#define BTPU_SCHED_YIELD()                                             \
  do {                                                                 \
    if (::btpu::sched::on())                                           \
      ::btpu::sched::preempt(::btpu::sched::Point::kYield, nullptr);   \
  } while (0)
#define BTPU_SCHED_DECL_SPAWN()                                        \
  do {                                                                 \
    if (::btpu::sched::armed()) ::btpu::sched::decl_spawn();           \
  } while (0)
#define BTPU_SCHED_ADOPT_SPAWNED() ::btpu::sched::AdoptScope _btpu_sched_adopt
#else
#define BTPU_ATOMIC_YIELD() ((void)0)
#define BTPU_SCHED_YIELD() ((void)0)
#define BTPU_SCHED_DECL_SPAWN() ((void)0)
#define BTPU_SCHED_ADOPT_SPAWNED() ((void)0)
#endif
