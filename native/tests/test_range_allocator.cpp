// RangeAllocator + KeystoneAllocatorAdapter unit tests.
// Behavior parity with reference tests/allocation/test_range_allocator.cpp
// (striping shapes, replica spreading, capacity failures, class preference +
// spillover, endpoint/rkey integrity, invalid descriptors, fragmentation under
// concurrency, zero-size, node locality, duplicate keys, offset math,
// free-unknown-object) plus TPU additions (slice affinity, forget_pool).
#include <map>
#include <set>
#include <thread>

#include "btest.h"
#include "btpu/alloc/keystone_adapter.h"
#include "btpu/common/poolsan.h"
#include "btpu/alloc/range_allocator.h"

using namespace btpu;
using namespace btpu::alloc;

namespace {

MemoryPool make_pool(const std::string& id, const std::string& node, uint64_t size,
                     StorageClass cls = StorageClass::RAM_CPU, int32_t slice = 0,
                     int32_t host = 0) {
  MemoryPool p;
  p.id = id;
  p.node_id = node;
  p.size = size;
  p.storage_class = cls;
  p.remote = {TransportKind::TCP, node + ":7000", 0x100000000ull, "abcd", "", "", 0};
  p.topo = {slice, host, -1};
  return p;
}

PoolMap six_pools(uint64_t size_each = 1 << 20) {
  PoolMap pools;
  for (int i = 0; i < 6; ++i) {
    auto id = "pool-" + std::to_string(i);
    pools[id] = make_pool(id, "node-" + std::to_string(i), size_each);
  }
  return pools;
}

AllocationRequest make_request(const std::string& key, uint64_t size, size_t replicas = 1,
                               size_t max_workers = 4) {
  AllocationRequest req;
  req.object_key = key;
  req.data_size = size;
  req.replication_factor = replicas;
  req.max_workers_per_copy = max_workers;
  req.min_shard_size = 1024;
  return req;
}

uint64_t copy_total(const CopyPlacement& copy) {
  uint64_t total = 0;
  for (const auto& s : copy.shards) total += s.length;
  return total;
}

}  // namespace

BTEST(RangeAllocator, SingleCopySingleShard) {
  RangeAllocator ra;
  auto pools = six_pools();
  auto res = ra.allocate(make_request("obj", 64 * 1024, 1, 1), pools);
  BT_ASSERT_OK(res);
  BT_ASSERT(res.value().copies.size() == 1);
  BT_ASSERT(res.value().copies[0].shards.size() == 1);
  BT_EXPECT_EQ(copy_total(res.value().copies[0]), 64 * 1024ull);
}

BTEST(RangeAllocator, StripingSplitsAcrossWorkers) {
  RangeAllocator ra;
  auto pools = six_pools();
  auto res = ra.allocate(make_request("obj", 100 * 1024, 1, 4), pools);
  BT_ASSERT_OK(res);
  const auto& copy = res.value().copies[0];
  BT_EXPECT_EQ(copy.shards.size(), 4u);
  BT_EXPECT_EQ(copy_total(copy), 100 * 1024ull);
  // Shards hit distinct pools.
  std::set<MemoryPoolId> used;
  for (const auto& s : copy.shards) used.insert(s.pool_id);
  BT_EXPECT_EQ(used.size(), 4u);
}

BTEST(RangeAllocator, RemainderSpreadOneByte) {
  RangeAllocator ra;
  auto pools = six_pools();
  // 10001 over 4 workers: base 2500, remainder 1 -> shard sizes 2501,2500,2500,2500.
  auto req = make_request("obj", 10001, 1, 4);
  req.min_shard_size = 1;
  auto res = ra.allocate(req, pools);
  BT_ASSERT_OK(res);
  const auto& shards = res.value().copies[0].shards;
  BT_ASSERT(shards.size() == 4);
  BT_EXPECT_EQ(shards[0].length, 2501ull);
  BT_EXPECT_EQ(shards[1].length, 2500ull);
  BT_EXPECT_EQ(shards[2].length, 2500ull);
  BT_EXPECT_EQ(shards[3].length, 2500ull);
}

BTEST(RangeAllocator, ReplicasSpreadAcrossDisjointPools) {
  RangeAllocator ra;
  auto pools = six_pools();
  // 3 replicas, max 2 workers each, 6 pools -> each copy on its own pool pair.
  auto res = ra.allocate(make_request("obj", 32 * 1024, 3, 2), pools);
  BT_ASSERT_OK(res);
  BT_ASSERT(res.value().copies.size() == 3);
  std::set<MemoryPoolId> all_pools;
  size_t shard_count = 0;
  for (const auto& copy : res.value().copies) {
    BT_EXPECT_EQ(copy_total(copy), 32 * 1024ull);
    for (const auto& s : copy.shards) {
      all_pools.insert(s.pool_id);
      ++shard_count;
    }
  }
  BT_EXPECT_EQ(all_pools.size(), shard_count);  // no pool reused across copies
}

BTEST(RangeAllocator, ReplicasLandOnDisjointWorkersWithMultiPoolNodes) {
  // Multi-controller shape: each worker process owns several pools (one per
  // device). Copies must spread over disjoint WORKERS, not merely disjoint
  // pools — otherwise one process death takes every copy.
  RangeAllocator ra;
  PoolMap pools;
  for (int n = 0; n < 2; ++n) {
    for (int p = 0; p < 4; ++p) {
      auto id = "w" + std::to_string(n) + "-pool-" + std::to_string(p);
      pools[id] = make_pool(id, "worker-" + std::to_string(n), 1 << 20);
    }
  }
  // max_workers=2: the old pool-interleaved layout would put both copies on
  // worker-0's four pools.
  auto res = ra.allocate(make_request("obj", 64 * 1024, 2, 2), pools);
  BT_ASSERT_OK(res);
  BT_ASSERT(res.value().copies.size() == 2);
  std::set<std::string> copy_workers[2];
  for (int c = 0; c < 2; ++c) {
    for (const auto& s : res.value().copies[c].shards) {
      copy_workers[c].insert(s.worker_id);
    }
  }
  for (const auto& w : copy_workers[0]) {
    BT_EXPECT(!copy_workers[1].contains(w));
  }
}

BTEST(RangeAllocator, DisjointCopyStillStripesAcrossItsOwnWorkers) {
  // 3 workers x 2 pools, rf=2, max_workers=2: copy 0 is assigned two workers
  // and must stripe across BOTH (aggregate bandwidth), not collapse onto the
  // first worker's two pools.
  RangeAllocator ra;
  PoolMap pools;
  for (int n = 0; n < 3; ++n) {
    for (int p = 0; p < 2; ++p) {
      auto id = "s" + std::to_string(n) + "-pool-" + std::to_string(p);
      pools[id] = make_pool(id, "sworker-" + std::to_string(n), 1 << 20);
    }
  }
  auto res = ra.allocate(make_request("obj", 64 * 1024, 2, 2), pools);
  BT_ASSERT_OK(res);
  BT_ASSERT(res.value().copies.size() == 2);
  std::set<std::string> copy_workers[2];
  for (int c = 0; c < 2; ++c) {
    for (const auto& s : res.value().copies[c].shards) {
      copy_workers[c].insert(s.worker_id);
    }
  }
  for (const auto& w : copy_workers[0]) {
    BT_EXPECT(!copy_workers[1].contains(w));
  }
  // One copy got two workers; its two shards sit on distinct workers.
  const size_t widest = std::max(copy_workers[0].size(), copy_workers[1].size());
  BT_EXPECT_EQ(widest, 2u);
}

BTEST(RangeAllocator, ReplicasColocateWhenSingleWorkerRatherThanFail) {
  // Too few failure domains for disjoint copies: keep the old pool-level
  // spread instead of refusing the put.
  RangeAllocator ra;
  PoolMap pools;
  for (int p = 0; p < 4; ++p) {
    auto id = "only-pool-" + std::to_string(p);
    pools[id] = make_pool(id, "only-worker", 1 << 20);
  }
  auto res = ra.allocate(make_request("obj", 64 * 1024, 2, 2), pools);
  BT_ASSERT_OK(res);
  BT_ASSERT(res.value().copies.size() == 2);
  std::set<MemoryPoolId> all_pools;
  size_t shard_count = 0;
  for (const auto& copy : res.value().copies) {
    BT_EXPECT_EQ(copy_total(copy), 64 * 1024ull);
    for (const auto& s : copy.shards) {
      all_pools.insert(s.pool_id);
      ++shard_count;
    }
  }
  BT_EXPECT_EQ(all_pools.size(), shard_count);  // still pool-disjoint
}

BTEST(RangeAllocator, DisjointWorkerLayoutFallsBackOnUnevenSpace) {
  // Worker-1's pools are too small to hold a whole copy; the partitioned
  // layout cannot fit, so the allocator falls back to co-location on
  // worker-0 rather than failing the put.
  RangeAllocator ra;
  PoolMap pools;
  for (int p = 0; p < 4; ++p) {
    auto id = "big-pool-" + std::to_string(p);
    pools[id] = make_pool(id, "worker-big", 1 << 20);
  }
  pools["small-pool"] = make_pool("small-pool", "worker-small", 4 * 1024);
  auto res = ra.allocate(make_request("obj", 64 * 1024, 2, 2), pools);
  BT_ASSERT_OK(res);
  BT_ASSERT(res.value().copies.size() == 2);
  for (const auto& copy : res.value().copies) {
    BT_EXPECT_EQ(copy_total(copy), 64 * 1024ull);
  }
}

BTEST(RangeAllocator, CopyIndicesAreSequential) {
  RangeAllocator ra;
  auto pools = six_pools();
  auto res = ra.allocate(make_request("obj", 4096, 3, 1), pools);
  BT_ASSERT_OK(res);
  for (uint32_t i = 0; i < 3; ++i) BT_EXPECT_EQ(res.value().copies[i].copy_index, i);
}

BTEST(RangeAllocator, InsufficientCapacityFails) {
  RangeAllocator ra;
  PoolMap pools;
  pools["p0"] = make_pool("p0", "n0", 16 * 1024);
  auto res = ra.allocate(make_request("obj", 64 * 1024, 1, 1), pools);
  BT_EXPECT(!res.ok());
  BT_EXPECT(res.error() == ErrorCode::INSUFFICIENT_SPACE);
}

BTEST(RangeAllocator, ReplicationMultipliesDemand) {
  RangeAllocator ra;
  PoolMap pools;
  pools["p0"] = make_pool("p0", "n0", 100 * 1024);
  // one copy fits, three don't (single pool, 3x 40KB > 100KB)
  BT_ASSERT_OK(ra.allocate(make_request("one", 40 * 1024, 1, 1), pools));
  auto res = ra.allocate(make_request("three", 40 * 1024, 3, 1), pools);
  BT_EXPECT(!res.ok());
  BT_EXPECT(res.error() == ErrorCode::INSUFFICIENT_SPACE);
}

BTEST(RangeAllocator, ZeroSizeRejected) {
  RangeAllocator ra;
  auto pools = six_pools();
  auto res = ra.allocate(make_request("obj", 0, 1, 1), pools);
  BT_EXPECT(!res.ok());
  BT_EXPECT(res.error() == ErrorCode::INVALID_PARAMETERS);
}

BTEST(RangeAllocator, DuplicateKeyRejectedAndRolledBack) {
  // Byte-exact free-space/offset assertions: run untracked — red zones
  // and quarantine deliberately change this math (poolsan tests own the
  // tracked-math coverage).
  poolsan::ScopedDisarm poolsan_off;
  RangeAllocator ra;
  auto pools = six_pools();
  BT_ASSERT_OK(ra.allocate(make_request("dup", 4096, 1, 1), pools));
  const auto before = ra.get_stats(std::nullopt);
  auto res = ra.allocate(make_request("dup", 4096, 1, 1), pools);
  BT_EXPECT(!res.ok());
  BT_EXPECT(res.error() == ErrorCode::OBJECT_ALREADY_EXISTS);
  const auto after = ra.get_stats(std::nullopt);
  // The failed attempt must not leak ranges.
  BT_EXPECT_EQ(after.total_free_bytes, before.total_free_bytes);
  BT_EXPECT_EQ(after.total_objects, before.total_objects);
}

BTEST(RangeAllocator, FreeReturnsSpaceAndForgetsObject) {
  // Byte-exact free-space/offset assertions: run untracked — red zones
  // and quarantine deliberately change this math (poolsan tests own the
  // tracked-math coverage).
  poolsan::ScopedDisarm poolsan_off;
  RangeAllocator ra;
  auto pools = six_pools();
  BT_ASSERT_OK(ra.allocate(make_request("obj", 256 * 1024, 2, 2), pools));
  auto stats = ra.get_stats(std::nullopt);
  BT_EXPECT_EQ(stats.total_objects, 1ull);
  BT_EXPECT_EQ(stats.total_allocated_bytes, 512 * 1024ull);

  BT_EXPECT(ra.free("obj") == ErrorCode::OK);
  stats = ra.get_stats(std::nullopt);
  BT_EXPECT_EQ(stats.total_objects, 0ull);
  BT_EXPECT_EQ(stats.total_allocated_bytes, 0ull);
  BT_EXPECT_EQ(stats.total_free_bytes, 6ull << 20);
  // Double free / unknown key.
  BT_EXPECT(ra.free("obj") == ErrorCode::OBJECT_NOT_FOUND);
  BT_EXPECT(ra.free("never-existed") == ErrorCode::OBJECT_NOT_FOUND);
}

BTEST(RangeAllocator, PreferredClassWins) {
  RangeAllocator ra;
  PoolMap pools;
  pools["hbm"] = make_pool("hbm", "n0", 1 << 20, StorageClass::HBM_TPU);
  pools["dram"] = make_pool("dram", "n1", 1 << 20, StorageClass::RAM_CPU);
  auto req = make_request("obj", 4096, 1, 1);
  req.preferred_classes = {StorageClass::HBM_TPU};
  auto res = ra.allocate(req, pools);
  BT_ASSERT_OK(res);
  BT_EXPECT_EQ(res.value().copies[0].shards[0].pool_id, "hbm");
  BT_EXPECT(!res.value().stats.required_spillover);
}

BTEST(RangeAllocator, SpilloverToFallbackClassWhenPreferredFull) {
  RangeAllocator ra;
  PoolMap pools;
  pools["hbm"] = make_pool("hbm", "n0", 8 * 1024, StorageClass::HBM_TPU);
  pools["dram"] = make_pool("dram", "n1", 1 << 20, StorageClass::RAM_CPU);
  auto req = make_request("obj", 64 * 1024, 1, 1);
  req.preferred_classes = {StorageClass::HBM_TPU};
  auto res = ra.allocate(req, pools);
  BT_ASSERT_OK(res);
  BT_EXPECT_EQ(res.value().copies[0].shards[0].pool_id, "dram");
  BT_EXPECT(res.value().stats.required_spillover);
}

BTEST(RangeAllocator, RestrictToPreferredForbidsSpillover) {
  RangeAllocator ra;
  PoolMap pools;
  pools["hbm"] = make_pool("hbm", "n0", 8 * 1024, StorageClass::HBM_TPU);
  pools["dram"] = make_pool("dram", "n1", 1 << 20, StorageClass::RAM_CPU);
  auto req = make_request("obj", 64 * 1024, 1, 1);
  req.preferred_classes = {StorageClass::HBM_TPU};
  req.restrict_to_preferred = true;
  BT_EXPECT(ra.allocate(req, pools).error() == ErrorCode::INSUFFICIENT_SPACE);

  // Same request fits when restricted to the class that has room.
  req.preferred_classes = {StorageClass::RAM_CPU};
  auto res = ra.allocate(req, pools);
  BT_ASSERT_OK(res);
  BT_EXPECT_EQ(res.value().copies[0].shards[0].pool_id, "dram");
}

BTEST(RangeAllocator, ExcludedNodesNeverSelected) {
  RangeAllocator ra;
  PoolMap pools = six_pools();
  auto req = make_request("obj", 256 * 1024, 1, 6);
  req.excluded_nodes = {"node-0", "node-1"};
  auto res = ra.allocate(req, pools);
  BT_ASSERT_OK(res);
  for (const auto& copy : res.value().copies) {
    for (const auto& shard : copy.shards) {
      BT_EXPECT_NE(shard.worker_id, "node-0");
      BT_EXPECT_NE(shard.worker_id, "node-1");
    }
  }
  // Excluding every node leaves nothing.
  req.excluded_nodes = {"node-0", "node-1", "node-2", "node-3", "node-4", "node-5"};
  BT_EXPECT(ra.allocate(req, pools).error() == ErrorCode::INSUFFICIENT_SPACE);
}

BTEST(RangeAllocator, RenameMergeAndPoolRangeRemoval) {
  // Byte-exact free-space assertions: run untracked (see the disarmed
  // accounting tests above).
  poolsan::ScopedDisarm poolsan_off;
  RangeAllocator ra;
  PoolMap pools = six_pools();
  BT_ASSERT_OK(ra.allocate(make_request("a", 64 * 1024, 1, 1), pools));
  BT_ASSERT_OK(ra.allocate(make_request("b", 64 * 1024, 1, 1), pools));

  // Rename: "a" -> "c"; old key is gone, new key frees cleanly.
  BT_EXPECT(ra.rename_object("a", "c") == ErrorCode::OK);
  BT_EXPECT(ra.rename_object("a", "d") == ErrorCode::OBJECT_NOT_FOUND);
  BT_EXPECT(ra.rename_object("b", "c") == ErrorCode::OBJECT_ALREADY_EXISTS);
  BT_EXPECT(ra.free("a") == ErrorCode::OBJECT_NOT_FOUND);

  // Merge: "b" folds into "c"; freeing "c" returns all the space.
  const auto before = ra.get_stats(std::nullopt).total_free_bytes;
  BT_EXPECT(ra.merge_objects("b", "c") == ErrorCode::OK);
  BT_EXPECT(ra.merge_objects("b", "c") == ErrorCode::OBJECT_NOT_FOUND);
  BT_EXPECT(ra.free("c") == ErrorCode::OK);
  BT_EXPECT_EQ(ra.get_stats(std::nullopt).total_free_bytes, before + 2 * 64 * 1024);

  // remove_pool_ranges drops only the named pool's entries: the later free
  // must not return that pool's bytes (its pool left the cluster).
  auto striped = ra.allocate(make_request("s", 128 * 1024, 1, 2), pools);
  BT_ASSERT_OK(striped);
  BT_ASSERT(striped.value().copies[0].shards.size() == 2);
  const auto dead_pool = striped.value().copies[0].shards[0].pool_id;
  ra.remove_pool_ranges("s", dead_pool);
  ra.forget_pool(dead_pool);
  const auto mid = ra.get_stats(std::nullopt).total_free_bytes;
  BT_EXPECT(ra.free("s") == ErrorCode::OK);
  BT_EXPECT_EQ(ra.get_stats(std::nullopt).total_free_bytes, mid + 64 * 1024);
}

BTEST(RangeAllocator, NodeLocalityPinsAllocation) {
  RangeAllocator ra;
  auto pools = six_pools();
  auto req = make_request("obj", 4096, 1, 4);
  req.preferred_node = "node-3";
  auto res = ra.allocate(req, pools);
  BT_ASSERT_OK(res);
  for (const auto& s : res.value().copies[0].shards) BT_EXPECT_EQ(s.worker_id, "node-3");
  // Locality to a nonexistent node fails rather than spilling.
  auto req2 = make_request("obj2", 4096, 1, 1);
  req2.preferred_node = "node-99";
  BT_EXPECT(!ra.allocate(req2, pools).ok());
}

BTEST(RangeAllocator, SliceAffinityRanksIciPoolsFirst) {
  RangeAllocator ra;
  PoolMap pools;
  pools["far"] = make_pool("far", "nf", 2 << 20, StorageClass::RAM_CPU, /*slice=*/1);
  pools["near"] = make_pool("near", "nn", 1 << 20, StorageClass::RAM_CPU, /*slice=*/0);
  auto req = make_request("obj", 4096, 1, 1);
  req.preferred_slice = 0;
  auto res = ra.allocate(req, pools);
  BT_ASSERT_OK(res);
  // "far" has more free space, but "near" is on the preferred slice.
  BT_EXPECT_EQ(res.value().copies[0].shards[0].pool_id, "near");
}

BTEST(RangeAllocator, HostAffinityRanksHostLocalAboveSameSlice) {
  RangeAllocator ra;
  PoolMap pools;
  // Same slice, two hosts; a cross-slice pool with the most space.
  pools["h0"] = make_pool("h0", "n0", 2 << 20, StorageClass::RAM_CPU, /*slice=*/0, /*host=*/0);
  pools["h1"] = make_pool("h1", "n1", 1 << 20, StorageClass::RAM_CPU, /*slice=*/0, /*host=*/1);
  pools["far"] = make_pool("far", "nf", 4 << 20, StorageClass::RAM_CPU, /*slice=*/1, /*host=*/1);
  auto req = make_request("obj", 4096, 1, 1);
  req.preferred_slice = 0;
  req.preferred_host = 1;
  auto res = ra.allocate(req, pools);
  BT_ASSERT_OK(res);
  // "far" has the most space and matches host_id=1, but on the wrong slice;
  // "h0" is same-slice with more space; "h1" is the (slice, host) match and
  // must win anyway.
  BT_EXPECT_EQ(res.value().copies[0].shards[0].pool_id, "h1");

  // Host full: spills to same-slice first (h0), not cross-slice (far).
  auto big = make_request("obj2", (1 << 20) + 4096, 1, 1);
  big.preferred_slice = 0;
  big.preferred_host = 1;
  auto res2 = ra.allocate(big, pools);
  BT_ASSERT_OK(res2);
  BT_EXPECT_EQ(res2.value().copies[0].shards[0].pool_id, "h0");

  // Without preferred_slice the host hint is inert (per-slice coordinate):
  // ranking falls back to free space, which "far" wins.
  auto bare = make_request("obj3", 4096, 1, 1);
  bare.preferred_host = 1;
  auto res3 = ra.allocate(bare, pools);
  BT_ASSERT_OK(res3);
  BT_EXPECT_EQ(res3.value().copies[0].shards[0].pool_id, "far");
}

BTEST(RangeAllocator, PlacementCarriesEndpointRkeyAndAbsoluteAddr) {
  // Byte-exact free-space/offset assertions: run untracked — red zones
  // and quarantine deliberately change this math (poolsan tests own the
  // tracked-math coverage).
  poolsan::ScopedDisarm poolsan_off;
  RangeAllocator ra;
  PoolMap pools;
  auto pool = make_pool("p0", "n0", 1 << 20);
  pool.remote.remote_base = 0x7000000000ull;
  pool.remote.rkey_hex = "dead";
  pools["p0"] = pool;
  auto first = ra.allocate(make_request("a", 4096, 1, 1), pools);
  auto second = ra.allocate(make_request("b", 4096, 1, 1), pools);
  BT_ASSERT_OK(first);
  BT_ASSERT_OK(second);
  const auto& s1 = first.value().copies[0].shards[0];
  const auto& s2 = second.value().copies[0].shards[0];
  BT_EXPECT(s1.remote.transport == TransportKind::TCP);
  BT_EXPECT_EQ(s1.remote.endpoint, "n0:7000");
  const auto& m1 = std::get<MemoryLocation>(s1.location);
  const auto& m2 = std::get<MemoryLocation>(s2.location);
  BT_EXPECT_EQ(m1.remote_addr, 0x7000000000ull);       // base + offset 0
  BT_EXPECT_EQ(m2.remote_addr, 0x7000000000ull + 4096); // next carve
  BT_EXPECT_EQ(m1.rkey, 0xdeadull);
  BT_EXPECT_EQ(m1.size, 4096ull);
}

BTEST(RangeAllocator, InvalidPoolDescriptorFailsAllocation) {
  RangeAllocator ra;
  PoolMap pools;
  auto bad = make_pool("bad", "n0", 1 << 20);
  bad.remote.rkey_hex = "not-hex!";
  pools["bad"] = bad;
  auto res = ra.allocate(make_request("obj", 4096, 1, 1), pools);
  BT_EXPECT(!res.ok());
  BT_EXPECT(res.error() == ErrorCode::INVALID_PARAMETERS);
}

BTEST(RangeAllocator, MinShardSizeNarrowsStripe) {
  RangeAllocator ra;
  auto pools = six_pools();
  // 10KB over max 4 workers with 4KB min shards -> clamp to 2 workers of 5KB.
  auto req = make_request("obj", 10 * 1024, 1, 4);
  req.min_shard_size = 4096;
  auto res = ra.allocate(req, pools);
  BT_ASSERT_OK(res);
  const auto& shards = res.value().copies[0].shards;
  BT_EXPECT_EQ(shards.size(), 2u);
  for (const auto& s : shards) BT_EXPECT(s.length >= 4096);
}

BTEST(RangeAllocator, TinyObjectGetsSingleShard) {
  RangeAllocator ra;
  auto pools = six_pools();
  auto req = make_request("obj", 100, 1, 4);  // below min_shard_size entirely
  auto res = ra.allocate(req, pools);
  BT_ASSERT_OK(res);
  BT_EXPECT_EQ(res.value().copies[0].shards.size(), 1u);
  BT_EXPECT_EQ(res.value().copies[0].shards[0].length, 100ull);
}

BTEST(RangeAllocator, LargeObjectAcrossManyPools) {
  RangeAllocator ra;
  auto pools = six_pools(1 << 20);
  // 5MB across 6 pools of 1MB: needs all 6 (w-search must find w=6).
  auto req = make_request("big", 5 << 20, 1, 8);
  auto res = ra.allocate(req, pools);
  BT_ASSERT_OK(res);
  BT_EXPECT_EQ(res.value().copies[0].shards.size(), 6u);
  BT_EXPECT_EQ(copy_total(res.value().copies[0]), uint64_t{5 << 20});
}

BTEST(RangeAllocator, CanAllocateHonorsClassFilter) {
  RangeAllocator ra;
  PoolMap pools;
  pools["hbm"] = make_pool("hbm", "n0", 64 * 1024, StorageClass::HBM_TPU);
  pools["dram"] = make_pool("dram", "n1", 1 << 20, StorageClass::RAM_CPU);
  auto req = make_request("obj", 256 * 1024, 1, 1);
  req.preferred_classes = {StorageClass::HBM_TPU};
  // Only 64KB of HBM exists -> not feasible within the preferred class.
  // (The reference would wrongly report false for all non-RAM_CPU prefs and
  // true based on *all* pools for RAM_CPU — we filter properly.)
  BT_EXPECT(!ra.can_allocate(req, pools));
  req.preferred_classes = {StorageClass::RAM_CPU};
  BT_EXPECT(ra.can_allocate(req, pools));
  req.preferred_classes.clear();
  BT_EXPECT(ra.can_allocate(req, pools));
}

BTEST(RangeAllocator, GetFreeSpacePerClass) {
  // Byte-exact free-space/offset assertions: run untracked — red zones
  // and quarantine deliberately change this math (poolsan tests own the
  // tracked-math coverage).
  poolsan::ScopedDisarm poolsan_off;
  RangeAllocator ra;
  PoolMap pools;
  pools["hbm"] = make_pool("hbm", "n0", 1 << 20, StorageClass::HBM_TPU);
  pools["dram"] = make_pool("dram", "n1", 2 << 20, StorageClass::RAM_CPU);
  BT_ASSERT_OK(ra.allocate(make_request("obj", 4096, 1, 1), pools));  // lands somewhere
  const auto hbm_free = ra.get_free_space(StorageClass::HBM_TPU);
  const auto dram_free = ra.get_free_space(StorageClass::RAM_CPU);
  BT_EXPECT_EQ(hbm_free + dram_free, (3ull << 20) - 4096);
  BT_EXPECT_EQ(ra.get_free_space(StorageClass::NVME), 0ull);
}

BTEST(RangeAllocator, ForgetPoolDropsItsFreeSpace) {
  RangeAllocator ra;
  auto pools = six_pools();
  BT_ASSERT_OK(ra.allocate(make_request("obj", 4096, 1, 1), pools));
  const auto before = ra.get_stats(std::nullopt).total_free_bytes;
  ra.forget_pool("pool-0");
  const auto after = ra.get_stats(std::nullopt).total_free_bytes;
  BT_EXPECT(after < before);
}

BTEST(RangeAllocator, ConcurrentAllocationsStayConsistent) {
  // Byte-exact free-space/offset assertions: run untracked — red zones
  // and quarantine deliberately change this math (poolsan tests own the
  // tracked-math coverage).
  poolsan::ScopedDisarm poolsan_off;
  RangeAllocator ra;
  auto pools = six_pools(8 << 20);
  constexpr int kThreads = 6;
  constexpr int kPerThread = 40;
  std::vector<std::thread> threads;
  std::atomic<int> ok_count{0};
  for (int t = 0; t < kThreads; ++t) {
    threads.emplace_back([&, t] {
      for (int i = 0; i < kPerThread; ++i) {
        auto key = "obj-" + std::to_string(t) + "-" + std::to_string(i);
        auto res = ra.allocate(make_request(key, 16 * 1024, 1, 2), pools);
        if (res.ok()) ++ok_count;
      }
    });
  }
  for (auto& th : threads) th.join();
  BT_EXPECT_EQ(ok_count.load(), kThreads * kPerThread);
  auto stats = ra.get_stats(std::nullopt);
  BT_EXPECT_EQ(stats.total_objects, uint64_t(kThreads * kPerThread));
  BT_EXPECT_EQ(stats.total_allocated_bytes, uint64_t(kThreads * kPerThread) * 16 * 1024);
  // Free everything from multiple threads; space must be fully reclaimed.
  threads.clear();
  for (int t = 0; t < kThreads; ++t) {
    threads.emplace_back([&, t] {
      for (int i = 0; i < kPerThread; ++i) {
        (void)ra.free("obj-" + std::to_string(t) + "-" + std::to_string(i));  // hammer thread; reclamation asserted via stats below
      }
    });
  }
  for (auto& th : threads) th.join();
  stats = ra.get_stats(std::nullopt);
  BT_EXPECT_EQ(stats.total_objects, 0ull);
  BT_EXPECT_EQ(stats.total_free_bytes, 6ull * (8 << 20));
  BT_EXPECT_EQ(stats.fragmentation_ratio, 0.0);
}

BTEST(KeystoneAdapter, MapsWorkerConfigToRequest) {
  WorkerConfig cfg;
  cfg.replication_factor = 2;
  cfg.max_workers_per_copy = 3;
  cfg.preferred_node = "node-1";
  cfg.preferred_classes = {StorageClass::HBM_TPU};
  cfg.min_shard_size = 2048;
  cfg.preferred_slice = 1;
  auto req = KeystoneAllocatorAdapter::to_allocation_request("key", 4096, cfg);
  BT_EXPECT_EQ(req.object_key, "key");
  BT_EXPECT_EQ(req.data_size, 4096ull);
  BT_EXPECT_EQ(req.replication_factor, 2u);
  BT_EXPECT_EQ(req.max_workers_per_copy, 3u);
  BT_EXPECT(req.enable_striping);  // iff max_workers_per_copy > 1
  BT_EXPECT_EQ(req.preferred_slice, 1);
  cfg.max_workers_per_copy = 1;
  auto req2 = KeystoneAllocatorAdapter::to_allocation_request("key", 4096, cfg);
  BT_EXPECT(!req2.enable_striping);
}

BTEST(KeystoneAdapter, AllocateFreeRoundtrip) {
  KeystoneAllocatorAdapter adapter(AllocatorFactory::create_range_based());
  auto pools = six_pools();
  WorkerConfig cfg;
  cfg.replication_factor = 2;
  cfg.max_workers_per_copy = 2;
  auto res = adapter.allocate_data_copies("obj", 64 * 1024, cfg, pools);
  BT_ASSERT_OK(res);
  BT_EXPECT_EQ(res.value().size(), 2u);
  BT_EXPECT(adapter.free_object("obj") == ErrorCode::OK);
  BT_EXPECT(adapter.free_object("obj") == ErrorCode::OBJECT_NOT_FOUND);
}

BTEST(RangeAllocator, EcSpreadsOverDistinctWorkersNotPools) {
  // Two pools per worker on 3 workers: a 4+2 code must round-robin shards
  // over WORKERS (2 each), never stack shards on one worker while another
  // goes unused ("any m worker losses" is the contract, not pool losses).
  RangeAllocator alloc;
  PoolMap pools;
  for (int w = 0; w < 3; ++w) {
    for (int p = 0; p < 2; ++p) {
      auto id = "n" + std::to_string(w) + "-p" + std::to_string(p);
      pools[id] = make_pool(id, "node-" + std::to_string(w), 1 << 20);
    }
  }
  auto req = make_request("ec-obj", 240 * 1024);
  req.ec_data_shards = 4;
  req.ec_parity_shards = 2;
  auto result = alloc.allocate(req, pools);
  BT_ASSERT_OK(result);
  const auto& copy = result.value().copies[0];
  BT_ASSERT(copy.shards.size() == 6);
  BT_EXPECT_EQ(copy.ec_data_shards, 4u);
  std::map<std::string, int> per_worker;
  for (const auto& s : copy.shards) {
    BT_EXPECT_EQ(s.length, 60 * 1024ull);  // equal shards, ceil(240k/4)
    per_worker[s.worker_id]++;
  }
  BT_ASSERT(per_worker.size() == 3);
  for (const auto& [node, n] : per_worker) BT_EXPECT_EQ(n, 2);  // balanced

  // Device-tier pools are never EC candidates (no coded client path).
  PoolMap dev_pools;
  auto hbm = make_pool("hbm0", "node-9", 1 << 20, StorageClass::HBM_TPU);
  hbm.remote.transport = TransportKind::HBM;
  dev_pools["hbm0"] = hbm;
  auto dev_req = make_request("ec-dev", 64 * 1024);
  dev_req.ec_data_shards = 2;
  dev_req.ec_parity_shards = 1;
  BT_EXPECT(alloc.allocate(dev_req, dev_pools).error() == ErrorCode::INSUFFICIENT_SPACE);

  // Geometry limits are enforced.
  auto bad = make_request("ec-bad", 1024);
  bad.ec_data_shards = 0;
  bad.ec_parity_shards = 2;
  BT_EXPECT(alloc.allocate(bad, pools).error() == ErrorCode::INVALID_PARAMETERS);
}

BTEST(RangeAllocator, EcCapacityCheckCountsWholeShards) {
  // 2 pools, 3+1 code, shard 100 KiB: each pool takes ceil(4/2)=2 whole
  // shards = 200 KiB. Pools with 150 KiB free must be rejected up front
  // (the even-split estimate ceil(400k/2) would wrongly admit them).
  RangeAllocator alloc;
  PoolMap pools;
  pools["a"] = make_pool("a", "na", 150 * 1024);
  pools["b"] = make_pool("b", "nb", 150 * 1024);
  auto req = make_request("ec-tight", 300 * 1024);
  req.ec_data_shards = 3;
  req.ec_parity_shards = 1;
  BT_EXPECT(alloc.allocate(req, pools).error() == ErrorCode::INSUFFICIENT_SPACE);

  // With 200 KiB+ free per pool the same request fits.
  PoolMap roomy;
  roomy["a"] = make_pool("a", "na", 220 * 1024);
  roomy["b"] = make_pool("b", "nb", 220 * 1024);
  RangeAllocator fresh;
  auto req2 = make_request("ec-tight2", 300 * 1024);
  req2.ec_data_shards = 3;
  req2.ec_parity_shards = 1;
  auto ok = fresh.allocate(req2, roomy);
  BT_ASSERT_OK(ok);
  BT_EXPECT_EQ(ok.value().copies[0].shards.size(), size_t{4});
}
