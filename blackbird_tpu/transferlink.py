"""Shared per-process endpoint for the device fabric (jax.experimental.transfer).

One lifecycle, two users: the worker-side HBM provider (hbm.py) serving
keystone-commanded offers/pulls, and the client-side FabricClient
(fabric.py) moving bytes with its own runtime. Both need exactly the same
hard-won plumbing, which therefore lives here once:

  * lazy server start bound to this process's device client, with the
    BTPU_HBM_FABRIC=0 gate and a graceful "no fabric on this stack" probe
    (None, never an exception, on the serving paths);
  * a connection cache keyed by remote address;
  * offer bookkeeping with stale-offer GC: the transfer server pins every
    await_pull'd array until SOMETHING pulls it and the API has no cancel,
    so stale offers are drained by self-pulls — on ONE long-lived daemon
    thread fed by a bounded queue, so a wedged pull isolates instead of
    stalling the serving path, two pulls never race on the shared cached
    connection, and a stuck drainer surfaces as `gc_dropped` instead of an
    unbounded queue.

On TPU the transfer rides the chip fabric; on CPU it is a bulk socket
between the two processes' runtimes — either way the bytes never pass
through the keystone or the worker's staged host lane.
"""

from __future__ import annotations

import os
import threading
import time
from typing import TYPE_CHECKING, Any

if TYPE_CHECKING:
    import queue

__all__ = ["TransferLink"]


class TransferLink:
    # The jax module, devices, transfer server/connections are all Any on
    # purpose: jax.experimental.transfer has no stable typed surface and the
    # module is injected (tests substitute fakes). The typed boundary is
    # this class's own API.
    def __init__(self, jax_module: Any, device: Any = None) -> None:
        self._jax = jax_module
        self._device = device  # default: first local device, resolved lazily
        self._server: Any = None  # None = unprobed, False = unavailable/disabled
        self._probe_failed_server: Any = None  # keep half-dead servers alive
        self.unavailable_reason: str | None = None  # set when probe fails
        self._lock = threading.Lock()
        self._conns: dict[str, object] = {}
        # jax.experimental.transfer documents no thread-safety contract, and
        # callers (FabricClient batch APIs, concurrent worker-side command
        # handlers) may reach this link from several threads: serialize
        # await_pull on the shared server and pull per shared connection.
        # Cross-process parallelism (the kind that matters on a mesh) is
        # untouched — each process has its own link.
        self._offer_lock = threading.Lock()
        self._conn_locks: dict[str, threading.Lock] = {}
        self._offered: dict[int, tuple[object, float]] = {}
        self._gc_queue: queue.Queue[tuple[int, object]] | None = None
        self.offers = 0
        self.discards = 0  # stale offers drained by the GC self-pull
        self.gc_dropped = 0  # stale offers dropped: drainer is stuck

    # -- server / connections ----------------------------------------------

    def device(self) -> Any:
        if self._device is None:
            self._device = self._jax.local_devices()[0]
        return self._device

    def server(self) -> Any:
        """The lazily started per-process transfer server, or None
        (disabled via BTPU_HBM_FABRIC=0, or unavailable on this stack).

        Availability is probed END TO END, not just by server start: on the
        tunneled axon TPU stack `start_transfer_server` succeeds but every
        pull dies in the PJRT plugin (`PJRT_Client_CreateBuffersForAsync-
        HostToDevice is not implemented`, and the serving direction lacks
        `PJRT_Buffer_CopyRawToHost`), so a tiny self offer/pull is the only
        honest test. A stack that fails the probe reports None here — the
        worker then advertises no fabric endpoints and every caller takes
        the staged lane — with the first error preserved in
        `unavailable_reason` so benches/operators see the real cause."""
        with self._lock:
            if self._server is not None:
                return self._server or None
            if os.environ.get("BTPU_HBM_FABRIC") == "0":
                self._server = False
                self.unavailable_reason = "disabled (BTPU_HBM_FABRIC=0)"
                return None
            try:
                from jax.experimental import transfer  # noqa: PLC0415

                server = transfer.start_transfer_server(
                    self.device().client, "127.0.0.1:0", ["127.0.0.1:0"])
            except Exception as exc:  # noqa: BLE001 - no fabric on this stack
                self._server = False
                self.unavailable_reason = f"server start failed: {exc}"
                return None
            # Self-probe on a DEADLINED daemon thread: the same flapping
            # stack can also WEDGE a pull rather than error it (observed:
            # jax.devices() itself hangs when the tunnel is sick), and this
            # runs under self._lock — an unbounded hang here would freeze
            # every server()/address()/connect() caller in the process. The
            # thread touches only locals, so an abandoned probe can't corrupt
            # link state; its offered 16 bytes stay pinned in a process whose
            # fabric is now off.
            import secrets  # noqa: PLC0415
            import numpy as np  # noqa: PLC0415

            result: dict[str, Any] = {}

            def _probe() -> None:
                try:
                    tid = secrets.randbits(63)
                    arr = self._jax.device_put(
                        np.zeros(16, dtype=np.uint8), self.device())
                    arr.block_until_ready()
                    server.await_pull(tid, [arr])
                    conn = server.connect(server.address())
                    out = conn.pull(
                        tid, [self._spec((16,), np.uint8, self.device())])[0]
                    np.asarray(out)  # force materialization: axon fails HERE
                    result["ok"] = True
                except Exception as exc:  # noqa: BLE001 - can't move bytes
                    result["error"] = exc

            timeout_s = float(os.environ.get("BTPU_FABRIC_PROBE_TIMEOUT_S", "30"))
            t = threading.Thread(target=_probe, daemon=True,
                                 name="btpu-fabric-probe")
            t.start()
            t.join(timeout_s)
            if not result.get("ok"):
                self._server = False
                # Keep the half-dead server referenced: its teardown path is
                # unproven on the failing stack and a leaked listener is safer
                # than a destructor crash in a serving process.
                self._probe_failed_server = server
                self.unavailable_reason = (
                    f"probe pull failed: {result['error']}" if "error" in result
                    else f"probe pull wedged (> {timeout_s:.0f}s)")
                return None
            self._server = server
            return self._server

    def address(self) -> str | None:
        server = self.server()
        return str(server.address()) if server is not None else None

    def connect(self, addr: str) -> Any:
        server = self.server()  # before the lock: it takes the same lock
        with self._lock:
            conn = self._conns.get(addr)
            if conn is None:
                conn = self._conns[addr] = server.connect(addr)
            return conn

    def _conn_lock(self, addr: str) -> threading.Lock:
        with self._lock:
            return self._conn_locks.setdefault(addr, threading.Lock())

    def _spec(self, shape: Any, dtype: Any, device: Any) -> Any:
        from jax.sharding import SingleDeviceSharding  # noqa: PLC0415

        return self._jax.ShapeDtypeStruct(
            shape, dtype, sharding=SingleDeviceSharding(device))

    # -- offers --------------------------------------------------------------

    def offer(self, transfer_id: int, arr: Any, device: Any = None) -> None:
        """Registers `arr` for a remote pull under `transfer_id` and tracks
        it for GC. Raises when the server is unavailable."""
        server = self.server()
        if server is None:
            raise RuntimeError("device fabric unavailable")
        self.gc_offers()
        with self._offer_lock:
            server.await_pull(int(transfer_id), [arr])
        spec = self._spec(arr.shape, arr.dtype, device or self.device())
        with self._lock:
            self._offered[int(transfer_id)] = (spec, time.monotonic())
        self.offers += 1

    def pull(self, addr: str, transfer_id: int, length: int,
             device: Any = None) -> Any:
        """Pulls uint8[length] offered under `transfer_id` at `addr` into
        this process's runtime; returns the device array."""
        import numpy as np  # noqa: PLC0415

        spec = self._spec((int(length),), np.uint8, device or self.device())
        conn = self.connect(addr)
        with self._conn_lock(addr):
            return conn.pull(int(transfer_id), [spec])[0]

    def gc_offers(self, max_age_s: float = 60.0) -> None:
        """Discards offers whose pull never came (the peer fell back): the
        source never learns of a successful remote pull either, so consumed
        ids are self-pulled once too — measured to complete quickly, but
        that is observed, not documented, behavior, hence the isolated
        single drainer thread (see module docstring)."""
        now = time.monotonic()
        with self._lock:
            stale = [(tid, spec) for tid, (spec, at) in self._offered.items()
                     if now - at > max_age_s]
            for tid, _spec in stale:
                del self._offered[tid]
            if not stale:
                return
            if self._gc_queue is None:
                import queue  # noqa: PLC0415

                gc_queue = self._gc_queue = queue.Queue(maxsize=256)

                def _drain() -> None:
                    while True:
                        tid, spec = gc_queue.get()
                        try:
                            gc_addr = self.server().address()
                            conn = self.connect(gc_addr)
                            with self._conn_lock(gc_addr):
                                conn.pull(tid, [spec])
                            self.discards += 1
                        except Exception:  # noqa: BLE001 - best effort
                            pass

                threading.Thread(
                    target=_drain, daemon=True, name="btpu-fabric-gc").start()
        for entry in stale:
            try:
                self._gc_queue.put_nowait(entry)
            except Exception:  # noqa: BLE001 - queue full: drainer is stuck
                self.gc_dropped += 1
