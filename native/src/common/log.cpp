#include "btpu/common/log.h"

#include "btpu/common/thread_annotations.h"

#include <cstdio>
#include <ctime>

namespace btpu::log {

namespace {
const char* level_tag(Level l) {
  switch (l) {
    case Level::kError: return "E";
    case Level::kWarn: return "W";
    case Level::kInfo: return "I";
    case Level::kDebug: return "D";
    case Level::kTrace: return "T";
  }
  return "?";
}

const char* basename_of(const char* path) {
  const char* slash = std::strrchr(path, '/');
  return slash ? slash + 1 : path;
}
}  // namespace

void emit(Level l, const char* file, int line, const std::string& msg) {
  using namespace std::chrono;
  static Mutex mu;
  const auto now = system_clock::now();
  const auto t = system_clock::to_time_t(now);
  const auto us = duration_cast<microseconds>(now.time_since_epoch()).count() % 1000000;
  std::tm tm{};
  localtime_r(&t, &tm);
  MutexLock lock(mu);
  std::fprintf(stderr, "%s%02d%02d %02d:%02d:%02d.%06ld %s:%d] %s\n", level_tag(l),
               tm.tm_mon + 1, tm.tm_mday, tm.tm_hour, tm.tm_min, tm.tm_sec,
               static_cast<long>(us), basename_of(file), line, msg.c_str());
}

}  // namespace btpu::log
