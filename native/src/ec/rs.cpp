// Reed-Solomon over GF(2^8) with the 0x11d primitive polynomial.
//
// Shard i (data) is row i of the identity; parity row j is the Cauchy row
// C(j,i) = 1 / (x_j ^ y_i) with x_j = k + j, y_i = i. Any k rows of
// [I; C] form an invertible matrix (Cauchy property), so any k surviving
// shards determine the data. Reconstruction builds that k x k matrix from
// the surviving rows, inverts it with Gauss-Jordan over GF(256), and
// multiplies only the rows needed for the missing data shards.
#include "btpu/ec/rs.h"

#include <array>
#include <cstring>
#include <vector>

#if defined(__x86_64__)
#include <immintrin.h>
#endif

namespace btpu::ec {

namespace {

// ---- GF(256) tables --------------------------------------------------------

struct GfTables {
  std::array<uint8_t, 256> log{};
  std::array<uint8_t, 512> exp{};  // doubled so mul skips a mod

  GfTables() {
    uint16_t x = 1;
    for (int i = 0; i < 255; ++i) {
      exp[i] = static_cast<uint8_t>(x);
      log[x] = static_cast<uint8_t>(i);
      x <<= 1;
      if (x & 0x100) x ^= 0x11d;
    }
    for (int i = 255; i < 512; ++i) exp[i] = exp[i - 255];
  }
};

const GfTables& gf() {
  static const GfTables tables;
  return tables;
}

inline uint8_t gf_mul(uint8_t a, uint8_t b) {
  if (a == 0 || b == 0) return 0;
  const auto& t = gf();
  return t.exp[t.log[a] + t.log[b]];
}

inline uint8_t gf_inv(uint8_t a) {
  const auto& t = gf();
  return t.exp[255 - t.log[a]];
}

// dst[0..len) ^= c * src[0..len) — the encode/reconstruct hot loop.
//
// Vector path (x86 SSSE3/AVX2): the nibble-split trick — c*x =
// c*(hi(x)<<4) ^ c*lo(x), so two 16-entry product tables (one per nibble)
// turn the GF multiply into two byte-shuffle lookups. PSHUFB shuffles 16/32
// lanes at once, ~20x the byte-wise table walk. Scalar fallback otherwise.
#if defined(__x86_64__)
__attribute__((target("avx2"))) void gf_mul_add_avx2(uint8_t* dst, const uint8_t* src,
                                                     const uint8_t* lo_tbl,
                                                     const uint8_t* hi_tbl, size_t len) {
  const __m256i lo = _mm256_broadcastsi128_si256(_mm_loadu_si128((const __m128i*)lo_tbl));
  const __m256i hi = _mm256_broadcastsi128_si256(_mm_loadu_si128((const __m128i*)hi_tbl));
  const __m256i mask = _mm256_set1_epi8(0x0f);
  size_t i = 0;
  for (; i + 32 <= len; i += 32) {
    const __m256i x = _mm256_loadu_si256((const __m256i*)(src + i));
    const __m256i d = _mm256_loadu_si256((const __m256i*)(dst + i));
    const __m256i pl = _mm256_shuffle_epi8(lo, _mm256_and_si256(x, mask));
    const __m256i ph = _mm256_shuffle_epi8(
        hi, _mm256_and_si256(_mm256_srli_epi64(x, 4), mask));
    _mm256_storeu_si256((__m256i*)(dst + i),
                        _mm256_xor_si256(d, _mm256_xor_si256(pl, ph)));
  }
  // Tail: nibble tables directly.
  for (; i < len; ++i) dst[i] ^= lo_tbl[src[i] & 0x0f] ^ hi_tbl[src[i] >> 4];
}

bool have_avx2() {
  static const bool yes = __builtin_cpu_supports("avx2");
  return yes;
}
#endif

void gf_mul_add(uint8_t* dst, const uint8_t* src, uint8_t c, size_t len) {
  if (c == 0) return;
  if (c == 1) {
    for (size_t i = 0; i < len; ++i) dst[i] ^= src[i];
    return;
  }
  const auto& t = gf();
  const uint8_t lc = t.log[c];
#if defined(__x86_64__)
  if (have_avx2()) {
    alignas(16) uint8_t lo_tbl[16], hi_tbl[16];
    lo_tbl[0] = hi_tbl[0] = 0;
    for (int v = 1; v < 16; ++v) {
      lo_tbl[v] = t.exp[lc + t.log[v]];         // c * v
      hi_tbl[v] = t.exp[lc + t.log[v << 4]];    // c * (v << 4)
    }
    gf_mul_add_avx2(dst, src, lo_tbl, hi_tbl, len);
    return;
  }
#endif
  uint8_t row[256];
  row[0] = 0;
  for (int v = 1; v < 256; ++v) row[v] = t.exp[lc + t.log[v]];
  for (size_t i = 0; i < len; ++i) dst[i] ^= row[src[i]];
}

// Cauchy coefficient for parity row j, data column i.
inline uint8_t cauchy(size_t j, size_t k, size_t i) {
  return gf_inv(static_cast<uint8_t>((k + j) ^ i));
}

// Gauss-Jordan inversion of an n x n matrix over GF(256). Returns false on
// a singular matrix (cannot happen for rows of [I; Cauchy], kept anyway).
bool gf_invert(std::vector<uint8_t>& a, size_t n) {
  std::vector<uint8_t> inv(n * n, 0);
  for (size_t i = 0; i < n; ++i) inv[i * n + i] = 1;
  for (size_t col = 0; col < n; ++col) {
    size_t pivot = col;
    while (pivot < n && a[pivot * n + col] == 0) ++pivot;
    if (pivot == n) return false;
    if (pivot != col) {
      for (size_t x = 0; x < n; ++x) {
        std::swap(a[pivot * n + x], a[col * n + x]);
        std::swap(inv[pivot * n + x], inv[col * n + x]);
      }
    }
    const uint8_t scale = gf_inv(a[col * n + col]);
    for (size_t x = 0; x < n; ++x) {
      a[col * n + x] = gf_mul(a[col * n + x], scale);
      inv[col * n + x] = gf_mul(inv[col * n + x], scale);
    }
    for (size_t row = 0; row < n; ++row) {
      if (row == col) continue;
      const uint8_t c = a[row * n + col];
      if (c == 0) continue;
      for (size_t x = 0; x < n; ++x) {
        a[row * n + x] ^= gf_mul(c, a[col * n + x]);
        inv[row * n + x] ^= gf_mul(c, inv[col * n + x]);
      }
    }
  }
  a.swap(inv);
  return true;
}

}  // namespace

bool rs_encode(const uint8_t* const* data, size_t k, uint8_t* const* parity, size_t m,
               size_t len) {
  // Same geometry limits as rs_reconstruct: past them the uint8_t Cauchy
  // coordinates collide and the parity would be silently unrecoverable.
  if (k == 0 || m == 0 || k + m > kMaxTotalShards) return false;
  for (size_t j = 0; j < m; ++j) {
    std::memset(parity[j], 0, len);
    for (size_t i = 0; i < k; ++i) gf_mul_add(parity[j], data[i], cauchy(j, k, i), len);
  }
  return true;
}

bool rs_reconstruct(const uint8_t* const* present, size_t k, size_t m, size_t len,
                    uint8_t* const* out) {
  if (k == 0 || m == 0 || k + m > kMaxTotalShards) return false;

  // Fast path: every data shard survives — nothing to solve (parity-only
  // losses are re-encoded by the caller, not reconstructed here).
  bool data_missing = false;
  for (size_t i = 0; i < k && !data_missing; ++i) data_missing = present[i] == nullptr;
  if (!data_missing) return true;

  // Pick the first k present shards as the solving basis.
  std::vector<size_t> basis;
  basis.reserve(k);
  for (size_t i = 0; i < k + m && basis.size() < k; ++i) {
    if (present[i]) basis.push_back(i);
  }
  if (basis.size() < k) return false;

  // Rows of [I; C] for the basis shards: basis_matrix * data = basis_bytes.
  std::vector<uint8_t> matrix(k * k, 0);
  for (size_t r = 0; r < k; ++r) {
    const size_t shard = basis[r];
    if (shard < k) {
      matrix[r * k + shard] = 1;
    } else {
      for (size_t i = 0; i < k; ++i) matrix[r * k + i] = cauchy(shard - k, k, i);
    }
  }
  if (!gf_invert(matrix, k)) return false;

  // data[i] = sum_r inv[i][r] * basis_bytes[r]; only missing rows are built.
  for (size_t i = 0; i < k; ++i) {
    if (present[i]) continue;
    std::memset(out[i], 0, len);
    for (size_t r = 0; r < k; ++r)
      gf_mul_add(out[i], present[basis[r]], matrix[i * k + r], len);
  }
  return true;
}

}  // namespace btpu::ec
