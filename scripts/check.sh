#!/usr/bin/env bash
# The one-command correctness gate (make check):
#
#   1. make native      — normal build (includes the compile-time wire lint)
#   2. make lint        — project invariants + FFI-boundary capi check +
#                         clang TSA sweep + compileall + mypy strict + ruff
#                         (one scoreboard row per sub-leg)
#   3. capi self-test   — planted FFI drift must CONVICT (capi_check.py)
#   4. native suite     — all 25 suites incl. the wire golden-table diff
#   5. tier-1 pytest    — the Python/JAX layer (skips cleanly without jax)
#   6. make asan        — address + undefined + leak, full native suite
#   7. make tsan        — thread sanitizer, full native suite
#
# Every leg runs even after an earlier one fails (you want the whole
# scoreboard, not the first stumble); the exit code is the OR of all legs.
# See docs/CORRECTNESS.md for how to read failures.
set -uo pipefail
cd "$(dirname "$0")/.."

declare -A results
overall=0

run_leg() {
  local name="$1"
  shift
  echo
  echo "===================================================================="
  echo "== check: ${name}"
  echo "===================================================================="
  if "$@"; then
    results[$name]=PASS
  else
    results[$name]=FAIL
    overall=1
  fi
}

jobs="$(nproc 2> /dev/null || echo 1)"

run_leg "build" make -j"$jobs" native

# Lint is special-cased: its sub-legs (project invariants, FFI-boundary
# capi check, clang TSA sweep, compileall, mypy strict, ruff) each get their
# own scoreboard row, parsed from lint.sh's machine-readable
# `lint-scoreboard:` lines. Tool-absent legs show SKIP — never PASS — and
# the BTPU_REQUIRE_{CLANG,MYPY,RUFF}=1 knobs (CI) turn those skips into
# failures inside lint.sh itself.
echo
echo "===================================================================="
echo "== check: lint"
echo "===================================================================="
lint_out="$(scripts/lint.sh 2>&1)"
lint_rc=$?
printf '%s\n' "$lint_out"
if [ "$lint_rc" -ne 0 ]; then
  overall=1
fi
for row in invariants capi-check tsa-sweep compileall mypy ruff; do
  status="$(printf '%s\n' "$lint_out" \
            | sed -n "s/^lint-scoreboard: ${row}=//p" | tail -n 1)"
  if [ -z "$status" ]; then
    # A missing row means lint.sh crashed or the format drifted — that must
    # fail the GATE, not just render a FAIL row in a green run.
    results[lint-$row]="FAIL (no scoreboard line — lint.sh crashed?)"
    overall=1
  else
    results[lint-$row]="$status"
  fi
done

# The FFI checker must be able to CONVICT, not just agree: the planted-drift
# self-test mutates one signature and one enum value in a temp header copy
# and asserts conviction. Its libclang half SKIPs with a notice on boxes
# without libclang (never PASS); BTPU_REQUIRE_CLANG=1 makes that skip fatal.
run_leg "capi-selftest" python3 scripts/capi_check.py --self-test
run_leg "native-suite" ./build/btpu_tests
# The io_uring engine is the default TCP data plane wherever the kernel
# allows it, which means the whole suite above exercised it (and asan/tsan
# below re-run it sanitized). These legs pin the OTHER engine: the
# thread-per-connection fallback must stay wire-identical and reap its
# serving threads, because sandboxed kernels and BTPU_IOURING_NET=0 boxes
# run it for real. The RemoteLane suite is the cross-host-shaped byte path
# (pvm/shm lanes force-disabled), run here under BOTH engines.
run_leg "iouring-net-0-uring" env BTPU_IOURING_NET=0 ./build/btpu_tests --filter=Uring
run_leg "iouring-net-0-transport" env BTPU_IOURING_NET=0 ./build/btpu_tests --filter=Transport
run_leg "iouring-net-0-remote-lane" env BTPU_IOURING_NET=0 ./build/btpu_tests --filter=RemoteLane
# The async client op core (ClientCore suite: completion queue, cancel/
# deadline machines, many-op hammer, async batches, optimistic reads) moves
# bytes through whichever socket engine the box resolved, so it gets the
# same both-engines treatment as the remote lane.
run_leg "iouring-net-0-client-core" env BTPU_IOURING_NET=0 ./build/btpu_tests --filter=ClientCore
# The engine-required legs key on a capability probe: a kernel that cannot
# run io_uring scores SKIP — never PASS — because the engine genuinely did
# not run there (BTPU_IOURING_NET=1 still serves via the fallback rather
# than refusing, so a green run without the probe would prove nothing).
if ./build/bb-wire --probe > /dev/null 2>&1; then
  run_leg "iouring-net-1-uring" env BTPU_IOURING_NET=1 ./build/btpu_tests --filter=Uring
  run_leg "iouring-net-1-remote-lane" env BTPU_IOURING_NET=1 ./build/btpu_tests --filter=RemoteLane
  run_leg "iouring-net-1-client-core" env BTPU_IOURING_NET=1 ./build/btpu_tests --filter=ClientCore
else
  results[iouring-net-1-uring]="SKIP (kernel cannot run io_uring — probe failed)"
  results[iouring-net-1-remote-lane]="SKIP (kernel cannot run io_uring — probe failed)"
  results[iouring-net-1-client-core]="SKIP (kernel cannot run io_uring — probe failed)"
fi
# tests/conftest.py hard-imports jax, so probe BOTH: a box with pytest but
# no jax would otherwise fail at conftest load (exit 4), not skip cleanly.
if command -v python3 > /dev/null 2>&1 && python3 -c 'import pytest, jax' 2> /dev/null; then
  run_leg "tier1-pytest" env JAX_PLATFORMS=cpu python3 -m pytest tests/ -q -m 'not slow' \
    --continue-on-collection-errors -p no:cacheprovider -p no:xdist -p no:randomly
else
  echo "check: NOTICE — pytest and/or jax unavailable; skipping the tier-1 leg"
fi
# Sharded checkpoint/placement leg (ISSUE 17): the mesh-aware placement
# plane, the versioned checkpoint commit protocol (mid-save kill, crc-gated
# resume, concurrent savers), and the REAL 2-process jax.distributed pod
# drill with its zero-cross-host lane proof. A subset of the tier-1 files,
# pinned as its own scoreboard row so a checkpoint regression is named at a
# glance; SKIP — never PASS — when pytest/jax are unavailable, because the
# checkpoint plane genuinely did not run there.
if command -v python3 > /dev/null 2>&1 && python3 -c 'import pytest, jax' 2> /dev/null; then
  run_leg "checkpoint" env JAX_PLATFORMS=cpu python3 -m pytest \
    tests/test_checkpoint.py tests/test_placement.py tests/test_jaxdist_pod.py \
    -q -p no:cacheprovider -p no:xdist -p no:randomly
else
  results[checkpoint]="SKIP (pytest/jax unavailable — checkpoint plane not exercised)"
fi
# The planted-mutant matrix (SchedMutants, ~60-90 forked child processes
# per pass) is owned by the sched-smoke leg below / `make sched` / nightly —
# running it at full budget inside BOTH sanitizer full-suite legs too would
# triple the fork-exec bill on every check for zero extra coverage.
run_leg "asan" env BTPU_SCHED_MUTANTS=0 make -j"$jobs" asan
run_leg "tsan" env BTPU_SCHED_MUTANTS=0 make -j"$jobs" tsan
# Bounded hostile-input sweep: the full-budget run is `make fuzz` (1M
# execs/target); the check gate replays the corpus plus a smaller
# deterministic sweep so a decoder regression fails here too. Deliberately
# keyed on BTPU_CHECK_FUZZ_* (not BTPU_FUZZ_*): a CI job that exports the
# full-budget knobs for its dedicated fuzz leg must not silently double
# this smoke leg's cost too.
run_leg "fuzz-smoke" env BTPU_FUZZ_EXECS="${BTPU_CHECK_FUZZ_EXECS:-100000}" \
  BTPU_FUZZ_TIME="${BTPU_CHECK_FUZZ_TIME:-15}" scripts/fuzz.sh
# Bounded crash-matrix smoke: every labeled durability crash point
# (crashpoint.h kAll) fires under live traffic in BOTH WAL sync modes, and
# each recovery passes the invariant checker (zero acked-object loss, no
# fabricated state). Keyed on BTPU_CHECK_CRASH_* (same reasoning as the
# fuzz knobs); the FULL matrix + bb-soak --kill9 run in the nightly CI job.
run_leg "crash-smoke" ./build/bb-crash --dir /tmp/bb-crash-check \
  --iters "${BTPU_CHECK_CRASH_ITERS:-1}" --ops "${BTPU_CHECK_CRASH_OPS:-120}" \
  --windows "${BTPU_CHECK_CRASH_WINDOWS:-400,0}"
# Bounded schedule-exploration smoke: the seeded PCT sweep, the exhaustive
# DFS model check of the lock-free kernels, and the planted-mutant matrix,
# on the asan tree (built by the asan leg above — the sched hooks ride every
# sanitizer build). Keyed BTPU_CHECK_SCHED_* like the fuzz/crash smokes; the
# full-budget campaign is `make sched` / the nightly CI job. Disabling the
# leg scores SKIP, never PASS — an unexplored schedule space is not a green
# schedule space.
if [ "${BTPU_CHECK_SCHED:-1}" = "0" ]; then
  results[sched-smoke]="SKIP (disabled via BTPU_CHECK_SCHED=0 — no schedules explored)"
elif [ ! -x build/asan/btpu_tests ]; then
  results[sched-smoke]=FAIL
  overall=1
  echo "check: sched-smoke FAIL — build/asan/btpu_tests missing (asan leg did not build)" >&2
else
  run_leg "sched-smoke" env BTPU_SCHED_SEEDS="${BTPU_CHECK_SCHED_SEEDS:-12}" \
    BTPU_SCHED_MUTANT_BUDGET="${BTPU_CHECK_SCHED_MUTANT_BUDGET:-80}" \
    ./build/asan/btpu_tests --filter=Sched
fi

# Pool-sanitizer smoke: the full native suite on the asan tree with
# BTPU_POOLSAN=1 FORCED (red zones + quarantine + generation checks armed on
# every pool in every test) — the asan leg above already arms it by default,
# this leg pins the explicit dial and catches an accidentally-disarmed tree.
# SKIP never PASS when the asan binary is missing; BTPU_CHECK_POOLSAN_FILTERS
# narrows for bounded CI smokes (nightly runs the full suite + bb-soak armed).
if [ "${BTPU_CHECK_POOLSAN:-1}" = "0" ]; then
  results[poolsan-smoke]="SKIP (disabled via BTPU_CHECK_POOLSAN=0 — pools ran unshadowed)"
elif [ ! -x build/asan/btpu_tests ]; then
  results[poolsan-smoke]=FAIL
  overall=1
  echo "check: poolsan-smoke FAIL — build/asan/btpu_tests missing (asan leg did not build)" >&2
else
  if [ -n "${BTPU_CHECK_POOLSAN_FILTERS:-}" ]; then
    run_leg "poolsan-smoke" env BTPU_POOLSAN=1 BTPU_SCHED_MUTANTS=0 \
      ./build/asan/btpu_tests --filter="${BTPU_CHECK_POOLSAN_FILTERS}"
  else
    run_leg "poolsan-smoke" env BTPU_POOLSAN=1 BTPU_SCHED_MUTANTS=0 ./build/asan/btpu_tests
  fi
fi

echo
echo "===================================================================="
echo "== check: summary"
echo "===================================================================="
for leg in build lint-invariants lint-capi-check lint-tsa-sweep \
           lint-compileall lint-mypy lint-ruff capi-selftest native-suite \
           iouring-net-0-uring iouring-net-0-transport \
           iouring-net-0-remote-lane iouring-net-0-client-core \
           iouring-net-1-uring iouring-net-1-remote-lane \
           iouring-net-1-client-core \
           tier1-pytest checkpoint asan tsan fuzz-smoke crash-smoke sched-smoke \
           poolsan-smoke; do
  [ -n "${results[$leg]:-}" ] && printf '  %-26s %s\n' "$leg" "${results[$leg]}"
done
exit "$overall"
