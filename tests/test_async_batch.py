"""Async batch API (client op core): submit-now/complete-later batches
through the typed Python plane, end to end against an EmbeddedCluster.

The native side is covered shard-by-shard in native/tests/
test_client_core.cpp; these tests pin the PYTHON contract: result() raises
per item like the sync batch calls, handles survive close/cancel in any
order, and the op-core counters surface through lane_counters().
"""

from __future__ import annotations

import pytest

from blackbird_tpu import Client, EmbeddedCluster
from blackbird_tpu.native import BtpuError, ErrorCode


def test_async_put_then_get_roundtrip() -> None:
    with EmbeddedCluster(workers=2, pool_bytes=16 << 20) as cluster:
        client = cluster.client()
        payloads = {f"async/k{i}": bytes([i % 256]) * (1024 + i) for i in range(32)}
        put_batch = client.put_many_async(payloads)
        assert put_batch.result() is None  # waits; raises on any failed item
        assert put_batch.done()

        get_batch = client.get_many_async(list(payloads))
        data = get_batch.result()
        assert data is not None
        assert {k: d for k, d in zip(payloads, data)} == payloads
        put_batch.close()
        get_batch.close()


def test_async_batches_overlap_from_one_thread() -> None:
    """One submitter thread keeps many batches in flight simultaneously —
    the completion-core property the sync API cannot express."""
    with EmbeddedCluster(workers=2, pool_bytes=32 << 20) as cluster:
        client = cluster.client()
        before = Client.lane_counters()
        batches = [
            client.put_many_async({f"overlap/{b}/{i}": b"x" * 512 for i in range(8)})
            for b in range(16)
        ]
        for batch in batches:  # all 16 were in flight before the first wait
            assert batch.result() is None
        after = Client.lane_counters()
        assert after["client_ops_submitted"] - before["client_ops_submitted"] >= 16
        assert after["client_ops_completed"] - before["client_ops_completed"] >= 16
        assert after["client_inflight_ops"] == 0
        assert after["client_peak_inflight_ops"] >= 2
        got = client.get_many_async([f"overlap/3/{i}" for i in range(8)]).result()
        assert got == [b"x" * 512] * 8


def test_async_get_missing_key_raises_per_item() -> None:
    with EmbeddedCluster(workers=1, pool_bytes=4 << 20) as cluster:
        client = cluster.client()
        client.put("async/present", b"hello")
        # The size probe runs at submit, so a missing key fails fast there —
        # same first-failed-item contract as the sync get_many.
        with pytest.raises(BtpuError) as excinfo:
            client.get_many_async(["async/present", "async/missing"])
        assert excinfo.value.code == ErrorCode.OBJECT_NOT_FOUND


def test_async_put_duplicate_key_raises_from_result() -> None:
    with EmbeddedCluster(workers=1, pool_bytes=4 << 20) as cluster:
        client = cluster.client()
        client.put("async/dup", b"first")
        batch = client.put_many_async({"async/dup": b"second", "async/ok": b"x"})
        with pytest.raises(BtpuError) as excinfo:
            batch.result()
        assert excinfo.value.code == ErrorCode.OBJECT_ALREADY_EXISTS
        # The non-conflicting sibling item still landed.
        assert client.get("async/ok") == b"x"


def test_async_close_is_idempotent_and_blocks_use() -> None:
    with EmbeddedCluster(workers=1, pool_bytes=4 << 20) as cluster:
        client = cluster.client()
        batch = client.put_many_async({"async/closed": b"x"})
        assert batch.wait(timeout_ms=10_000)
        batch.close()
        batch.close()  # idempotent
        with pytest.raises(RuntimeError):
            batch.done()


def test_async_cancel_then_close_is_safe() -> None:
    """cancel() then close() must never deadlock or touch freed buffers —
    close() waits out whatever stage is still running."""
    with EmbeddedCluster(workers=2, pool_bytes=16 << 20) as cluster:
        client = cluster.client()
        batch = client.put_many_async({f"async/c{i}": b"y" * 4096 for i in range(16)})
        batch.cancel()
        batch.close()
        # The cluster is still fully serviceable afterwards.
        client.put("async/after-cancel", b"alive")
        assert client.get("async/after-cancel") == b"alive"
