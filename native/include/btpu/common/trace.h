// Distributed op tracing: 64-bit trace ids minted at every client entry,
// per-hop span ids, an in-memory span ring every process can dump, and
// first-class span timing (the Dapper shape — PAPERS.md).
//
// Three layers, cheapest first:
//   * Aggregates (record/summary): per-name duration stats, always on.
//   * Span ring: every Span that closes under a live trace context lands in
//     a bounded lock-free ring of structured records {trace_id, span_id,
//     parent, name, start_ns, dur_ns, tid}. `bb-trace` collects each
//     process's ring (over /debug/trace or BTPU_TRACE_DUMP files) and
//     stitches one trace id's records from every process into a
//     Chrome/Perfetto trace_event JSON.
//   * Slow-op / sampled surfacing: OpScope (opened at each ObjectClient
//     public entry) mints the trace id, owns the op histogram sample, and
//     on close logs the trace id of any op slower than BTPU_TRACE_SLOW_US
//     (or every 1/BTPU_TRACE_SAMPLE'th op) so an operator knows WHICH id to
//     stitch.
//
// Propagation: the ids ride the wire exactly like the PR-5 deadline — an
// append-only tagged trailer on the RPC protocol (rpc.h) and appended
// fields on the packed TCP data headers (data_wire.h). Zero = untraced
// (legacy peers). Servers adopt the ids with RemoteScope / record spans
// directly with record_remote_span (event-loop code with no thread
// identity).
//
// Span names must be STRING LITERALS (static storage duration): the ring
// stores the pointer, not a copy — enforced by scripts/btpu_lint.py
// (trace-span-literal) so a dangling name cannot compile in. This also
// fixes the historic footgun where Span held a std::string_view over a
// caller temporary.
//
// Usage:  { TRACE_SPAN("client.put.transfer"); ...hot path... }
#pragma once

#include <chrono>
#include <cstdint>
#include <string>
#include <string_view>
#include <vector>

namespace btpu::trace {

// ---- master switch ---------------------------------------------------------
// BTPU_TRACING=0 turns id minting, span recording, and flight/op events off
// (a single relaxed load per check). Default on: the bench.py trace-overhead
// guard proves the hot cached get pays <= 5% for it.
bool enabled() noexcept;
void set_enabled(bool on) noexcept;

// ---- ambient trace context -------------------------------------------------
struct TraceContext {
  uint64_t trace_id{0};  // 0 = untraced
  uint64_t span_id{0};   // the CURRENT span (parent for anything opened now)
};

TraceContext current() noexcept;
// Non-zero 64-bit id (thread-local xorshift128+; never returns 0).
uint64_t mint_id() noexcept;

// Process identity stamped on every dumped span (bb-trace shows it as the
// Perfetto process name). Defaults to "proc". `name` must be a literal.
void set_process_name(const char* name) noexcept;
const char* process_name() noexcept;

// ---- span records ----------------------------------------------------------
// Steady-clock ns (CLOCK_MONOTONIC): comparable across processes on one
// host, which is what makes single-host stitching line up. Cross-host
// traces still nest correctly per process; absolute alignment needs a
// synchronized clock and is out of scope.
uint64_t now_ns() noexcept;

// Records one completed span into the ring. `name` must be a string
// literal. Used directly by event-loop servers (uring engine) whose ops
// interleave on one thread; everything else goes through Span/OpScope.
// Mints and returns the record's own span id.
uint64_t record_remote_span(const char* name, uint64_t trace_id, uint64_t parent_span,
                            uint64_t start_ns, uint64_t end_ns) noexcept;

// JSON-lines dump of the span ring, oldest first, optionally filtered to
// one trace id (0 = all). One object per line:
//   {"name":...,"trace":"<hex>","span":"<hex>","parent":"<hex>",
//    "start_us":...,"dur_us":...,"pid":...,"tid":...,"proc":...}
// This is the exact body /debug/trace serves and bb-trace consumes.
std::string dump_spans_json(uint64_t trace_id = 0);

// Spans recorded into the ring since process start (diagnostics/tests).
uint64_t span_ring_recorded() noexcept;

#if defined(BTPU_SCHED)
// Test-only (schedule exploration): empties the span ring so the DFS model
// check starts every enumerated schedule from the identical ring state —
// stale live slots would both skew the yield-point tree between replays and
// unbound the dump's preemption count.
void span_ring_reset_for_test() noexcept;
#endif

// ---- slow-op surfacing -----------------------------------------------------
// BTPU_TRACE_SLOW_US (0 = off): OpScope logs any op that closes slower,
// with its trace id, and remembers the most recent ones here so tools can
// pick a trace id without scraping logs.
struct SlowOp {
  const char* op{nullptr};
  uint64_t trace_id{0};
  uint64_t dur_us{0};
};
std::vector<SlowOp> recent_slow_ops();
// Env-latched threshold, overridable at runtime (tests, live tuning).
uint64_t slow_threshold_us() noexcept;
void set_slow_threshold_us(uint64_t us) noexcept;

// ---- per-op scope (client public entries) ----------------------------------
// Mints a fresh trace context when none is active; nested entries (put()
// calling put_many()) are fully INERT — the outer scope owns the histogram
// sample and root span, so btpu_op_duration_us{op=...} stays the
// distribution of the entry the caller invoked. On close: records the
// duration into the op histogram, emits op start/end flight-recorder
// events, writes the root span into the ring, and applies the
// slow/sampled surfacing rules. `op` must be a string literal; relabel()
// lets an entry refine the op family once the serving tier is known
// (put -> put_inline/put_slot). The cached-get fast path deliberately
// does NOT open one (client.cpp cached_probe_*: sampled light
// instrumentation — a ~2us local serve cannot absorb this scope's cost
// inside the bench.py 5% overhead budget).
class OpScope {
 public:
  explicit OpScope(const char* op) noexcept;
  ~OpScope();
  OpScope(const OpScope&) = delete;
  OpScope& operator=(const OpScope&) = delete;

  void relabel(const char* op) noexcept { op_ = op; }
  // 0 when tracing is disabled or this scope joined an outer op.
  uint64_t trace_id() const noexcept { return root_ ? ctx_.trace_id : 0; }

 private:
  const char* op_;
  TraceContext ctx_{};     // context this scope installed (root_ only)
  TraceContext saved_{};   // restored on close
  uint64_t start_ns_{0};
  bool root_{false};
  bool active_{false};
};

// ---- server-side adoption --------------------------------------------------
// Installs wire-received ids as this thread's ambient context for the
// handler's duration (keystone RPC dispatch, thread-per-connection data
// server). trace_id 0 = untraced request: installs nothing.
class RemoteScope {
 public:
  RemoteScope(uint64_t trace_id, uint64_t span_id) noexcept;
  ~RemoteScope();
  RemoteScope(const RemoteScope&) = delete;
  RemoteScope& operator=(const RemoteScope&) = delete;

 private:
  TraceContext saved_{};
  bool active_{false};
};

// ---- aggregate span timing (pre-existing layer) ----------------------------
struct SpanStats {
  std::string name;
  uint64_t count{0};
  double total_us{0};
  double p50_us{0};
  double p99_us{0};
  double max_us{0};
};

// Records one duration sample for `name` (reservoir aggregates + optional
// BTPU_TRACE jsonl). Copies the name — any lifetime is fine HERE; the ring
// layer is what requires literals.
void record(std::string_view name, double duration_us);

// Aggregated percentiles per span name (reservoir of recent samples).
std::vector<SpanStats> summary();
void reset();

// RAII span. `name` MUST be a string literal (static storage duration):
// the span ring stores the pointer (scripts/btpu_lint.py trace-span-literal
// enforces call sites). Under a live trace context the span also becomes
// the ambient parent for anything opened within it.
class Span {
 public:
  explicit Span(const char* name) noexcept;
  ~Span();
  Span(const Span&) = delete;
  Span& operator=(const Span&) = delete;

 private:
  const char* name_;
  uint64_t start_ns_;
  uint64_t own_span_{0};     // minted when traced; restored to parent on close
  uint64_t parent_span_{0};
};

}  // namespace btpu::trace

#define BTPU_TRACE_CONCAT_INNER(a, b) a##b
#define BTPU_TRACE_CONCAT(a, b) BTPU_TRACE_CONCAT_INNER(a, b)
#define TRACE_SPAN(name) ::btpu::trace::Span BTPU_TRACE_CONCAT(_btpu_span_, __LINE__)(name)
