// Batched object I/O: get_workers_many / put_many / get_many — one
// keystone round trip and one coalesced transfer per batch, riding
// the shared batch engine (batch_engine.h). Split out of the
// monolithic client.cpp; see docs/BYTE_PATHS.md (client core).
#include "btpu/client/client.h"

#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <map>
#include <random>

#include "btpu/common/crc32c.h"
#include "btpu/common/env.h"
#include "btpu/common/flight_recorder.h"
#include "btpu/common/histogram.h"
#include "btpu/common/wire.h"
#include "btpu/common/log.h"
#include "btpu/common/poolsan.h"
#include "btpu/common/trace.h"
#include "btpu/coord/remote_coordinator.h"
#include "btpu/ec/rs.h"
#include "btpu/rpc/rpc.h"
#include "btpu/storage/hbm_provider.h"

#include "batch_engine.h"

namespace btpu::client {

std::vector<Result<std::vector<CopyPlacement>>> ObjectClient::get_workers_many(
    const std::vector<ObjectKey>& keys) {
  if (embedded_) return embedded_->batch_get_workers(keys);
  auto r = rpc_failover(/*idempotent=*/true, [&](rpc::KeystoneRpcClient& c) {
    return c.batch_get_workers(keys);
  });
  if (!r.ok())
    return std::vector<Result<std::vector<CopyPlacement>>>(keys.size(), r.error());
  return std::move(r.value());
}

std::vector<ErrorCode> ObjectClient::put_many(const std::vector<PutItem>& items) {
  return put_many(items, options_.default_config);
}

std::vector<ErrorCode> ObjectClient::put_many(const std::vector<PutItem>& items,
                                              const WorkerConfig& config) {
  trace::OpScope op_trace("put_many");  // inert when put() already opened one
  TRACE_SPAN("client.put_many");
  // Nested scopes tighten: when put() already opened the op deadline this
  // is a no-op, and a direct put_many call gets its own budget.
  OpDeadlineScope op_scope(static_cast<int64_t>(options_.op_deadline_ms));
  std::vector<ErrorCode> results(items.size(), ErrorCode::OK);
  if (items.empty()) return results;

  std::vector<BatchPutStartItem> starts;
  starts.reserve(items.size());
  for (const auto& item : items) {
    // A put of a removed-then-recreated key must not let this client's own
    // cached placement serve the PREVIOUS object's bytes afterwards.
    invalidate_placements(item.key);
    // content_crc rides in batch_put_complete instead (folded from the
    // transport's fused shard hashes) — hashing the bytes here would cost a
    // full standalone pass before the transfer even starts.
    starts.push_back({item.key, item.size, config, 0});
  }
  std::vector<Result<std::vector<CopyPlacement>>> placed;
  if (embedded_) {
    placed = embedded_->batch_put_start(starts);
  } else {
    auto r = rpc_failover(/*idempotent=*/false, [&](rpc::KeystoneRpcClient& c) {
      // Deferred content stamps require a keystone that applies them at
      // put_complete. Against an older server, stamp at put_start like the
      // pre-fusion path — otherwise every object written during a rolling
      // upgrade would complete unstamped and verified reads would silently
      // skip the CRC gate. One ping learns the version (and a v1 server
      // that cannot answer it stays at 0 = conservative up-front hashing).
      if (c.server_proto_version() == 0) (void)c.ping();  // best-effort probe; 0 keeps conservative stamping
      if (c.server_proto_version() < rpc::kProtoContentCrcAtComplete) {
        for (size_t i = 0; i < starts.size(); ++i) {
          if (starts[i].content_crc == 0)
            starts[i].content_crc = crc32c(items[i].data, items[i].size);
        }
      }
      return c.batch_put_start(starts);
    });
    if (!r.ok()) return std::vector<ErrorCode>(items.size(), r.error());
    placed = std::move(r.value());
  }

  BatchJobs jobs;
  std::vector<std::vector<uint8_t>> ec_arena;
  std::vector<std::vector<CopyShardCrcs>> item_crcs(items.size());
  std::vector<bool> fuse_crc(items.size(), true);  // EC items stamp at encode
  for (size_t i = 0; i < items.size(); ++i) {
    if (!placed[i].ok()) {
      results[i] = placed[i].error();
      continue;
    }
    auto* data = const_cast<uint8_t*>(static_cast<const uint8_t*>(items[i].data));
    if (!placed[i].value().empty() && placed[i].value().front().ec_data_shards > 0) {
      // Erasure-coded item: encode now, ship with the shared wire batch.
      fuse_crc[i] = false;
      CopyShardCrcs crcs;
      results[i] = append_ec_put_jobs(placed[i].value().front(), data, items[i].size, i,
                                      ec_arena, jobs, &crcs);
      if (results[i] == ErrorCode::OK) item_crcs[i].push_back(std::move(crcs));
      continue;
    }
    for (const auto& copy : placed[i].value()) {
      // Shard CRCs are computed AFTER the device dispatch below, riding
      // under the in-flight transfer instead of serializing before it.
      if (auto ec = append_copy_jobs(copy, data, items[i].size, i, jobs, nullptr);
          ec != ErrorCode::OK) {
        results[i] = ec;
        break;
      }
    }
  }

  std::vector<uint32_t> wire_crcs;
  {
    TRACE_SPAN("client.put.transfer");
    run_device_jobs(*data_, jobs, /*is_write=*/true, results);
    run_wire_jobs(*data_, jobs, /*is_write=*/true, options_.io_parallelism, results,
                  &wire_crcs, &fuse_crc);
  }
  // Replicated/striped shard CRC stamps: harvested from the transport's
  // FUSED write hashes (computed while the bytes moved), so the typical put
  // sweeps the source bytes zero extra times; device shards and retried
  // ranges are hashed in stamp_copy_crcs, overlapped with any still-
  // draining device DMA (the flush below is the only wait). EC items
  // computed theirs during encode (parity shards have no plain-data
  // source; their wire bufs live in the arena, so they are excluded from
  // the offset harvest).
  std::vector<uint32_t> item_content_crcs(items.size(), 0);
  for (size_t i = 0; i < items.size(); ++i) {
    if (!placed[i].ok() || results[i] != ErrorCode::OK) continue;
    if (!placed[i].value().empty() && placed[i].value().front().ec_data_shards > 0) {
      // Coded object: shard stamps cover padded/parity wire bytes, so the
      // whole-object stamp still needs its own pass here.
      item_content_crcs[i] = crc32c(items[i].data, items[i].size);
      continue;
    }
    const auto* base = static_cast<const uint8_t*>(items[i].data);
    RangeCrcMap ranges;
    harvest_wire_ranges(jobs, wire_crcs, i, base, ranges);
    item_crcs[i] = stamp_copy_crcs(placed[i].value(), base, ranges);
    if (!item_crcs[i].empty() && !placed[i].value().empty())
      item_content_crcs[i] = fold_content_crc(item_crcs[i][0], placed[i].value()[0]);
  }
  // Device writes may be asynchronous; put_complete must not be sent until
  // the bytes are durably in the tier.
  if (!jobs.device.empty()) {
    if (auto ec = storage::hbm_flush(); ec != ErrorCode::OK) {
      for (size_t j = 0; j < jobs.device.size(); ++j) {
        if (results[jobs.device_item[j]] == ErrorCode::OK) results[jobs.device_item[j]] = ec;
      }
    }
  }

  std::vector<ObjectKey> completes, cancels;
  std::vector<std::vector<CopyShardCrcs>> complete_crcs;
  std::vector<uint32_t> complete_content_crcs;
  std::vector<size_t> complete_idx;
  for (size_t i = 0; i < items.size(); ++i) {
    if (!placed[i].ok()) continue;  // never reserved
    if (results[i] == ErrorCode::OK) {
      completes.push_back(items[i].key);
      complete_crcs.push_back(std::move(item_crcs[i]));
      complete_content_crcs.push_back(item_content_crcs[i]);
      complete_idx.push_back(i);
    } else {
      LOG_WARN << "put " << items[i].key << " transfer failed ("
               << to_string(results[i]) << "), cancelling";
      cancels.push_back(items[i].key);
    }
  }
  if (!completes.empty()) {
    std::vector<ErrorCode> ecs;
    if (embedded_) {
      ecs = embedded_->batch_put_complete(completes, complete_crcs, complete_content_crcs);
    } else {
      auto r = rpc_failover(/*idempotent=*/false, [&](rpc::KeystoneRpcClient& c) {
        return c.batch_put_complete(completes, complete_crcs, complete_content_crcs);
      });
      ecs = r.ok() ? std::move(r.value())
                   : std::vector<ErrorCode>(completes.size(), r.error());
    }
    for (size_t j = 0; j < complete_idx.size() && j < ecs.size(); ++j)
      results[complete_idx[j]] = ecs[j];
  }
  if (!cancels.empty()) {
    if (embedded_) {
      embedded_->batch_put_cancel(cancels);
    } else {
      (void)rpc_failover(/*idempotent=*/false,
                   [&](rpc::KeystoneRpcClient& c) { return c.batch_put_cancel(cancels); });  // best-effort cancel; slot TTL reclaims
    }
  }
  return results;
}

std::vector<Result<uint64_t>> ObjectClient::get_many(const std::vector<GetItem>& items,
                                                     std::optional<bool> verify) {
  trace::OpScope op_trace("get_many");
  OpDeadlineScope op_scope(static_cast<int64_t>(options_.op_deadline_ms));
  if (!cache_ || items.empty()) return get_many_uncached(items, verify);
  // Cache pass first: hits (e.g. a checkpoint's hot shards re-read by
  // load_sharded) are served locally; only the misses ride the batch.
  std::vector<Result<uint64_t>> results(items.size(), ErrorCode::NO_COMPLETE_WORKER);
  std::vector<GetItem> missing;
  std::vector<size_t> missing_idx;
  const bool direct = embedded_ && !options_.cache_force_lease_mode;
  using Outcome = cache::ObjectCache::Outcome;
  // Lease-mode entries whose lease lapsed: revalidated as ONE batched
  // metadata round below, never one control RTT per key (an idle-then-
  // reloaded checkpoint would otherwise serialize N round trips).
  struct ExpiredItem {
    size_t idx;
    cache::ObjectCache::Hit hit;
  };
  std::vector<ExpiredItem> expired;
  for (size_t i = 0; i < items.size(); ++i) {
    if (!items[i].buffer) {
      missing.push_back(items[i]);
      missing_idx.push_back(i);
      continue;
    }
    if (direct) {
      uint64_t got = 0;
      if (cache_serve(items[i].key, items[i].buffer, items[i].buffer_size, got)) {
        results[i] = got;
      } else {
        missing.push_back(items[i]);
        missing_idx.push_back(i);
      }
      continue;
    }
    auto hit = cache_->lookup(items[i].key);
    if (hit.outcome == Outcome::kHit && hit.bytes->size() <= items[i].buffer_size) {
      std::memcpy(items[i].buffer, hit.bytes->data(), hit.bytes->size());
      results[i] = hit.bytes->size();
      cache::note_cached_serve(hit.bytes->size());
    } else if (hit.outcome == Outcome::kExpired &&
               hit.bytes->size() <= items[i].buffer_size) {
      expired.push_back({i, std::move(hit)});
    } else {
      missing.push_back(items[i]);
      missing_idx.push_back(i);
    }
  }
  if (!expired.empty()) {
    std::vector<ObjectKey> keys;
    keys.reserve(expired.size());
    for (const auto& e : expired) keys.push_back(items[e.idx].key);
    auto metas = get_workers_many(keys);
    const auto meta_at = std::chrono::steady_clock::now();  // lease anchor
    for (size_t j = 0; j < expired.size(); ++j) {
      auto& e = expired[j];
      const Result<std::vector<CopyPlacement>> meta =
          j < metas.size() ? std::move(metas[j])
                           : Result<std::vector<CopyPlacement>>(ErrorCode::OBJECT_NOT_FOUND);
      if (cache_revalidate(items[e.idx].key, e.hit, meta, meta_at)) {
        std::memcpy(items[e.idx].buffer, e.hit.bytes->data(), e.hit.bytes->size());
        results[e.idx] = e.hit.bytes->size();
        cache::note_cached_serve(e.hit.bytes->size());
      } else {
        missing.push_back(items[e.idx]);
        missing_idx.push_back(e.idx);
      }
    }
  }
  if (missing.empty()) return results;
  auto sub = get_many_uncached(missing, verify);
  for (size_t j = 0; j < missing_idx.size() && j < sub.size(); ++j)
    results[missing_idx[j]] = sub[j];
  return results;
}

std::vector<Result<uint64_t>> ObjectClient::get_many_uncached(
    const std::vector<GetItem>& items, std::optional<bool> verify) {
  TRACE_SPAN("client.get_many");
  const bool v = verify.value_or(verify_reads());
  std::vector<Result<uint64_t>> results(items.size(), ErrorCode::NO_COMPLETE_WORKER);
  if (items.empty()) return results;

  std::vector<ObjectKey> keys;
  keys.reserve(items.size());
  for (const auto& item : items) keys.push_back(item.key);
  std::vector<Result<std::vector<CopyPlacement>>> placements;
  if (embedded_) {
    placements = embedded_->batch_get_workers(keys);
  } else {
    auto r = rpc_failover(/*idempotent=*/true, [&](rpc::KeystoneRpcClient& c) {
      return c.batch_get_workers(keys);
    });
    if (!r.ok()) return std::vector<Result<uint64_t>>(items.size(), r.error());
    placements = std::move(r.value());
  }
  const auto meta_at = std::chrono::steady_clock::now();  // cache lease anchor

  // First pass: batched transfer of every item's first replica.
  BatchJobs jobs;
  std::vector<std::vector<uint8_t>> ec_arena;
  std::vector<EcReadFixup> ec_fixups;
  std::vector<ErrorCode> errors(items.size(), ErrorCode::OK);
  std::vector<uint64_t> sizes(items.size(), 0);
  // Items whose integrity gate can fold the transport's fused read hashes
  // instead of re-hashing the whole buffer: plain striped/replicated copies
  // with a content stamp. EC reads cover padded arena buffers (their ranges
  // don't map onto the object) and inline items carry no wire ops.
  std::vector<bool> fuse_crc(items.size(), false);
  for (size_t i = 0; i < items.size(); ++i) {
    if (!placements[i].ok()) {
      errors[i] = placements[i].error();
      continue;
    }
    if (placements[i].value().empty()) {
      errors[i] = ErrorCode::NO_COMPLETE_WORKER;
      continue;
    }
    const auto& copy = placements[i].value().front();
    const uint64_t copy_size = copy_logical_size(copy);
    sizes[i] = copy_size;
    if (copy_size > items[i].buffer_size) {
      errors[i] = ErrorCode::BUFFER_OVERFLOW;
      continue;
    }
    if (!copy.inline_data.empty()) {
      // Inline item: the metadata reply already carried the bytes (the CRC
      // gate below judges them like any other first-pass read).
      std::memcpy(items[i].buffer, copy.inline_data.data(), copy.inline_data.size());
      continue;
    }
    if (copy.ec_data_shards > 0) {
      // Erasure-coded item: data-shard reads ride the shared batch; a
      // failed item retries below through the reconstructing path.
      append_ec_get_jobs(copy, static_cast<uint8_t*>(items[i].buffer), copy_size, i,
                         ec_arena, jobs, ec_fixups);
      continue;
    }
    if (auto ec = append_copy_jobs(copy, static_cast<uint8_t*>(items[i].buffer), copy_size, i,
                                   jobs);
        ec != ErrorCode::OK)
      errors[i] = ec;
    else
      fuse_crc[i] = v && copy.content_crc != 0;
  }
  run_device_jobs(*data_, jobs, /*is_write=*/false, errors);
  std::vector<uint32_t> wire_crcs;
  run_wire_jobs(*data_, jobs, /*is_write=*/false, options_.io_parallelism, errors,
                v ? &wire_crcs : nullptr, v ? &fuse_crc : nullptr);
  for (const auto& fix : ec_fixups) {
    if (errors[fix.item] == ErrorCode::OK) std::memcpy(fix.dst, fix.src, fix.n);
  }
  // Integrity gate: a clean-looking first-pass read with a CRC mismatch is
  // demoted to a failure so the per-item retry below heals it (replica
  // failover, or the coded path's corruption hunt). Wire shards were hashed
  // WHILE they moved (fuse_crc items): their fold replaces the old whole-
  // buffer post-pass, which cost ~11% of verified get throughput at 1 MiB.
  // One pass over the batch's jobs distributes the fused hashes to their
  // items (a per-item harvest would rescan the whole job list K times).
  std::vector<RangeCrcMap> item_ranges(v ? items.size() : 0);
  if (v) {
    for (size_t j = 0; j < jobs.wire.size() && j < wire_crcs.size(); ++j) {
      const size_t item = jobs.wire_item[j];
      if (wire_crcs[j] == 0 || !fuse_crc[item]) continue;
      const auto* base = static_cast<const uint8_t*>(items[item].buffer);
      item_ranges[item][{static_cast<uint64_t>(jobs.wire[j].buf - base),
                         jobs.wire[j].len}] = wire_crcs[j];
    }
  }
  for (size_t i = 0; i < items.size(); ++i) {
    if (errors[i] != ErrorCode::OK || !placements[i].ok() || placements[i].value().empty())
      continue;
    const auto& copy = placements[i].value().front();
    const uint32_t expect = copy.content_crc;
    if (!v || expect == 0) continue;
    const uint32_t got =
        fuse_crc[i] ? fold_ranges_crc(copy, static_cast<const uint8_t*>(items[i].buffer),
                                      item_ranges[i])
                    : crc32c(items[i].buffer, sizes[i]);
    if (got != expect) {
      LOG_WARN << "get_many: content crc mismatch on " << items[i].key << "; retrying";
      errors[i] = ErrorCode::CHECKSUM_MISMATCH;
    }
  }

  for (size_t i = 0; i < items.size(); ++i) {
    if (!placements[i].ok() || placements[i].value().empty() ||
        errors[i] == ErrorCode::BUFFER_OVERFLOW) {
      results[i] = errors[i];
      continue;
    }
    if (errors[i] == ErrorCode::OK) {
      results[i] = sizes[i];
      if (v)
        cache_fill(items[i].key, placements[i].value().front(),
                   static_cast<const uint8_t*>(items[i].buffer), sizes[i], meta_at);
      continue;
    }
    // Replica failover, one item at a time (first copy already failed).
    ErrorCode last = errors[i];
    bool done = false;
    const auto& copies = placements[i].value();
    if (copies.front().ec_data_shards > 0) {
      // Coded object: the retry IS the degraded read (fetch survivors +
      // parity, reconstruct).
      if (transfer_copy_ec(copies.front(), static_cast<uint8_t*>(items[i].buffer), sizes[i],
                           /*is_write=*/false, v) == ErrorCode::OK) {
        results[i] = sizes[i];
        if (v)
          cache_fill(items[i].key, copies.front(),
                     static_cast<const uint8_t*>(items[i].buffer), sizes[i], meta_at);
      } else {
        results[i] = last;
      }
      continue;
    }
    for (size_t c = 1; c < copies.size() && !done; ++c) {
      const uint64_t copy_size = copy_logical_size(copies[c]);
      if (copy_size > items[i].buffer_size) {
        last = ErrorCode::BUFFER_OVERFLOW;
        continue;
      }
      if (auto ec = transfer_copy_get(copies[c], static_cast<uint8_t*>(items[i].buffer),
                                      copy_size, v);
          ec == ErrorCode::OK) {
        results[i] = copy_size;
        if (v)
          cache_fill(items[i].key, copies[c],
                     static_cast<const uint8_t*>(items[i].buffer), copy_size, meta_at);
        done = true;
      } else {
        last = ec;
      }
    }
    if (!done) results[i] = last;
  }
  return results;
}

}  // namespace btpu::client
