"""Multi-host pod deployment, end to end.

Two entry points:

1. `python examples/multihost_pod.py serve` — what EVERY pod host runs.
   Joins jax.distributed when configured, derives this host's worker from
   the runtime (one hbm_tpu pool per local chip, host_id = process index),
   registers with the shared control plane, and serves until SIGTERM
   (preemption), when it drains itself through the keystone first.

2. `python examples/multihost_pod.py drill` — a local drill of the same
   shape: coordinator + keystone + two device-owning worker processes
   (virtual CPU devices), a put striped across both processes with copies
   on disjoint failure domains, a process kill, and the cross-process
   repair that follows. Run it anywhere; no TPU needed.

Role parity: the reference's multi-host story is a hand-run
worker_service per host over etcd (examples/worker_example.cpp) with no
failure drill at all.
"""

import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))


def serve() -> int:
    import blackbird_tpu.distributed as btd

    coord = sys.argv[2] if len(sys.argv) > 2 else "127.0.0.1:9290"
    keystone = sys.argv[3] if len(sys.argv) > 3 else "127.0.0.1:9090"
    btd.init()  # no-op single-host; joins jax.distributed on a pod
    return btd.serve(coord, pool_bytes_per_device=1 << 30,
                     dram_pool_bytes=4 << 30, keystone_endpoints=keystone)


def drill() -> int:
    from blackbird_tpu import StorageClass
    from blackbird_tpu.procluster import ProcessCluster

    print("bringing up coordinator + keystone + 2 device-owning worker "
          "processes (4 virtual devices each)...")
    with ProcessCluster(workers=2, devices_per_worker=4, pool_mb=8) as pc:
        client = pc.wait_ready()
        payload = bytes(bytearray(range(256)) * 4096)  # 1 MiB
        client.put("pod/demo", payload, replicas=2, max_workers=4,
                   preferred_class=StorageClass.HBM_TPU)
        copies = client.placements("pod/demo")
        for c in copies:
            workers = sorted({s["worker"] for s in c["shards"]})
            print(f"  copy {c['copy_index']}: {len(c['shards'])} device shards "
                  f"on {workers}")
        print("killing worker process 0 (host crash)...")
        pc.kill_worker(0)
        while pc.client().stats()["workers"] != 1:
            time.sleep(0.2)
        assert client.get("pod/demo") == payload
        print("  degraded read OK (surviving copy)")
        while pc.objects_repaired() < 1:
            time.sleep(0.2)
        assert client.get("pod/demo") == payload
        print("  repaired across the process boundary; read OK")
    print("drill complete")
    return 0


if __name__ == "__main__":
    mode = sys.argv[1] if len(sys.argv) > 1 else "drill"
    sys.exit(serve() if mode == "serve" else drill())
